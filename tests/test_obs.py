"""The fleet's sensory layer (ISSUE 12): unified MetricsRegistry +
promtool-style exposition lint, the stdlib telemetry HTTP server
(/metrics /healthz /statusz /tracez), tail-sampled per-request trace
export, and declarative SLO burn-rate monitors.

Acceptance pins: merged exposition pages are collision-checked and
conform (HELP/TYPE ordering, cumulative buckets, +Inf == count — the
per-block invariants from test_serving.py extended to the MERGED page);
tail sampling keeps every timed-out/rejected request and the slowest
decile under a bounded ring; SLO alerts fire deterministically under
injected latency and stay silent on the clean run; a live engine serves
all four endpoints concurrently with decode at zero post-warmup jit
misses.
"""
import json
import threading
import urllib.error
from urllib.request import urlopen

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (Request, ServingConfig, ServingEngine,
                                  ServingMetrics)
from paddle_tpu.jit.api import compile_cache_misses
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.obs import (ExpositionError, MetricsCollisionError,
                            MetricsRegistry, SLOMonitor, TraceBuffer,
                            evaluate_slo, lint_exposition, parse_slo)
from paddle_tpu.profiler import StepMonitor
from paddle_tpu.profiler._metrics import parse_exposition


def _done_request(rid, e2e, *, status="done", ttft=None, n_out=4):
    """A terminal Request with a synthetic trace, for metrics feeding."""
    r = Request(id=rid, prompt=np.arange(1, 5), max_new_tokens=4,
                status=status, n_out=n_out if status == "done" else 0)
    t = r.trace
    t.trace_id = f"t-{rid}"
    t.t_enqueue = 0.0
    t.t_admit = 0.01
    if status == "done":
        t.t_prefill_done = 0.02
        t.t_first_token = ttft if ttft is not None else e2e * 0.5
        t.t_finish = e2e
    else:
        t.t_finish = e2e
        if status == "rejected":
            r.reason = "queue_full"
        elif status == "timeout":
            r.reason = "queue_deadline"
    return r


def _fed_metrics(latencies, **kw):
    met = ServingMetrics(**kw)
    for i, e2e in enumerate(latencies):
        met.record_request(_done_request(i, float(e2e)))
    return met


# ------------------------------------------------- exposition conformance

GOOD = """# HELP demo_requests_total requests
# TYPE demo_requests_total counter
demo_requests_total 5
# HELP demo_lat_seconds latency
# TYPE demo_lat_seconds histogram
demo_lat_seconds_bucket{le="0.1"} 2
demo_lat_seconds_bucket{le="1"} 4
demo_lat_seconds_bucket{le="+Inf"} 5
demo_lat_seconds_sum 3.5
demo_lat_seconds_count 5
"""


class TestExpositionLint:
    def test_good_page_parses_and_lints(self):
        fams = lint_exposition(GOOD)
        assert fams["demo_requests_total"]["type"] == "counter"
        assert fams["demo_lat_seconds"]["type"] == "histogram"

    def test_sample_without_type_rejected(self):
        with pytest.raises(ExpositionError, match="no preceding"):
            parse_exposition("demo_x 1\n")

    def test_type_before_help_rejected(self):
        with pytest.raises(ExpositionError, match="before its HELP"):
            parse_exposition("# TYPE demo_x gauge\ndemo_x 1\n")

    def test_interleaved_families_rejected(self):
        text = ("# HELP a_total a\n# TYPE a_total counter\na_total 1\n"
                "# HELP b b\n# TYPE b gauge\nb 2\n"
                "a_total 3\n")
        with pytest.raises(ExpositionError, match="contiguous|duplicate"):
            parse_exposition(text)

    def test_duplicate_sample_rejected(self):
        text = "# HELP b b\n# TYPE b gauge\nb 2\nb 3\n"
        with pytest.raises(ExpositionError, match="duplicate sample"):
            parse_exposition(text)

    def test_malformed_sample_rejected(self):
        with pytest.raises(ExpositionError, match="malformed"):
            parse_exposition("# HELP b b\n# TYPE b gauge\nb = oops\n")

    def test_counter_must_end_in_total(self):
        text = "# HELP c c\n# TYPE c counter\nc 1\n"
        with pytest.raises(ExpositionError, match="_total"):
            lint_exposition(text)

    def test_noncumulative_buckets_rejected(self):
        bad = GOOD.replace('demo_lat_seconds_bucket{le="1"} 4',
                           'demo_lat_seconds_bucket{le="1"} 1')
        with pytest.raises(ExpositionError, match="cumulative"):
            lint_exposition(bad)

    def test_inf_bucket_must_equal_count(self):
        bad = GOOD.replace("demo_lat_seconds_count 5",
                           "demo_lat_seconds_count 7")
        with pytest.raises(ExpositionError, match="_count"):
            lint_exposition(bad)

    def test_descending_le_rejected(self):
        bad = GOOD.replace('le="0.1"', 'le="2"')
        with pytest.raises(ExpositionError, match="ascend"):
            lint_exposition(bad)


class TestMetricsRegistry:
    def test_merged_engine_blocks_are_conformant(self):
        """The satellite pin: ServingMetrics + StepMonitor + SLO blocks
        composed through ONE registry parse as one conformant page —
        extending test_serving's per-block invariants to the merge."""
        met = _fed_metrics(np.linspace(0.01, 0.4, 30))
        mon = StepMonitor(items_per_step=4, track_memory=False)
        with mon.step():
            pass
        slo = SLOMonitor("e2e_p99=1s", met, long_s=10, short_s=1)
        slo.poll(1.0)
        reg = MetricsRegistry()
        reg.register("serving",
                     lambda: met.metrics_text(prefix="paddle_tpu_serving"))
        reg.register("batch",
                     lambda: mon.metrics_text(
                         prefix="paddle_tpu_serving_batch"))
        reg.register("slo", slo.metrics_text)
        fams = lint_exposition(reg.render())
        assert "paddle_tpu_serving_e2e_seconds" in fams
        assert "paddle_tpu_serving_batch_steps_total" in fams
        assert "paddle_tpu_slo_burn_rate" in fams

    def test_goodput_block_composes(self):
        from paddle_tpu.profiler.goodput import GoodputReport
        from paddle_tpu.profiler.timeline import SpanRecorder
        rec = SpanRecorder()
        rec.record("step", 0.0, 1.0, step=1)
        rec.record("compile", 1.0, 1.5)
        reg = MetricsRegistry()
        reg.register("goodput",
                     lambda: GoodputReport(rec).metrics_text())
        fams = lint_exposition(reg.render())
        assert fams["paddle_tpu_badput_seconds"]["type"] == "gauge"
        # the labeled family carries every taxonomy category incl. zeros
        cats = [s for s in fams["paddle_tpu_badput_seconds"]["samples"]]
        assert len(cats) >= 8

    def test_family_collision_names_both_producers(self):
        met = _fed_metrics([0.1])
        reg = MetricsRegistry()
        reg.register("a", lambda: met.metrics_text(prefix="p"))
        reg.register("b", lambda: met.metrics_text(prefix="p"))
        with pytest.raises(MetricsCollisionError, match="'a' and 'b'"):
            reg.render()

    def test_unregister_clears_collision(self):
        met = _fed_metrics([0.1])
        reg = MetricsRegistry()
        reg.register("a", lambda: met.metrics_text(prefix="p"))
        reg.register("b", lambda: met.metrics_text(prefix="p"))
        assert reg.unregister("b") and not reg.unregister("b")
        lint_exposition(reg.render())

    def test_duplicate_producer_name_rejected(self):
        reg = MetricsRegistry()
        reg.register("a", lambda: "")
        with pytest.raises(ValueError, match="already registered"):
            reg.register("a", lambda: "")

    def test_render_order_is_registration_order(self):
        reg = MetricsRegistry()
        reg.register("z", lambda: "# HELP z z\n# TYPE z gauge\nz 1\n")
        reg.register("a", lambda: "# HELP a a\n# TYPE a gauge\na 1\n")
        page = reg.render()
        assert page.index("z 1") < page.index("a 1")

    def test_empty_producer_skipped(self):
        reg = MetricsRegistry()
        reg.register("empty", lambda: "")
        assert reg.render() == ""

    def test_broken_block_fails_render(self):
        reg = MetricsRegistry()
        reg.register("bad", lambda: "no_type_sample 1\n")
        with pytest.raises(ExpositionError):
            reg.render()


# ------------------------------------------------------- trace buffering

class TestTraceBuffer:
    def test_capacity_is_a_hard_bound(self):
        buf = TraceBuffer(8)
        for i in range(100):
            buf.add({"status": "done", "e2e_s": 0.1, "trace_id": str(i)})
        s = buf.summary()
        assert s["retained"] == 8 and s["seen"] == 100
        assert s["evicted"] == 92

    def test_failures_always_survive_fast_successes(self):
        """Every timed-out/rejected request stays while fast successes
        churn through — the acceptance pin."""
        buf = TraceBuffer(16)
        fail_ids = []
        for i in range(200):
            if i % 40 == 7:
                st = "timeout" if i % 80 == 7 else "rejected"
                buf.add({"status": st, "trace_id": f"f{i}"})
                fail_ids.append(f"f{i}")
            buf.add({"status": "done", "e2e_s": 0.01,
                     "trace_id": f"d{i}"})
        kept = {t["trace_id"] for t in buf.snapshot(limit=None)}
        assert set(fail_ids) <= kept
        assert len(kept) <= 16
        by_status = buf.summary()["by_status"]
        assert by_status["timeout"] + by_status["rejected"] == 5

    def test_slowest_decile_retained(self):
        """100 requests, capacity 20: every member of the slowest decile
        is still in the ring at the end."""
        rng = np.random.RandomState(3)
        lats = list(rng.uniform(0.01, 0.1, 90)) + \
            list(rng.uniform(5.0, 9.0, 10))
        rng.shuffle(lats)
        buf = TraceBuffer(20, slow_quantile=0.9)
        for i, e2e in enumerate(lats):
            buf.add({"status": "done", "e2e_s": float(e2e),
                     "trace_id": f"r{i}"})
        kept = buf.snapshot(order="slowest", limit=None)
        kept_ids = {t["trace_id"] for t in kept}
        slow_ids = {f"r{i}" for i, e2e in enumerate(lats) if e2e >= 5.0}
        assert slow_ids <= kept_ids
        # and the slowest-first view leads with them
        assert {t["trace_id"] for t in kept[:10]} == slow_ids

    def test_snapshot_filters_and_orders(self):
        buf = TraceBuffer(8)
        buf.add({"status": "done", "e2e_s": 0.5, "trace_id": "a"})
        buf.add({"status": "timeout", "trace_id": "b"})
        buf.add({"status": "done", "e2e_s": 0.1, "trace_id": "c"})
        assert [t["trace_id"] for t in buf.snapshot()] == ["c", "b", "a"]
        assert [t["trace_id"] for t in
                buf.snapshot(status="timeout")] == ["b"]
        assert [t["trace_id"] for t in
                buf.snapshot(order="slowest", limit=1)] == ["a"]
        with pytest.raises(ValueError, match="order"):
            buf.snapshot(order="oldest")

    def test_all_failures_still_bounded(self):
        buf = TraceBuffer(4)
        for i in range(10):
            buf.add({"status": "rejected", "trace_id": str(i)})
        ids = [t["trace_id"] for t in buf.snapshot()]
        assert ids == ["9", "8", "7", "6"]     # oldest failures rotate out


# ------------------------------------------------------------ SLO monitor

class TestSLOParsing:
    def test_grammar(self):
        ts = parse_slo("ttft_p99=500ms, e2e_p95=2s,goodput=0.9,"
                       "tpot_p50=0.05")
        by = {t.name: t for t in ts}
        assert by["ttft_p99"].threshold_s == 0.5
        assert by["ttft_p99"].objective == 0.99
        assert abs(by["ttft_p99"].budget - 0.01) < 1e-12
        assert by["e2e_p95"].threshold_s == 2.0
        assert by["goodput"].hist is None
        assert by["goodput"].objective == 0.9
        assert by["tpot_p50"].threshold_s == 0.05

    def test_bad_specs_raise(self):
        for bad in ("nope_p99=1", "ttft_p99", "goodput=1.5", "",
                    "ttft_p0=1"):
            with pytest.raises(ValueError):
                parse_slo(bad)


class TestSLOEvaluate:
    def test_whole_run_burn_and_attainment(self):
        # 90 fast + 10 slow: p95 target on e2e -> bad_frac 0.1, budget
        # 0.05 -> burn 2.0 = breach; p50 target -> burn 0.2 = ok
        met = _fed_metrics([0.01] * 90 + [10.0] * 10)
        rows = evaluate_slo(parse_slo("e2e_p95=1s"), met)
        assert rows[0]["bad"] == 10 and rows[0]["total"] == 100
        assert abs(rows[0]["burn"] - 2.0) < 1e-6 and not rows[0]["ok"]
        rows = evaluate_slo(parse_slo("e2e_p50=1s"), met)
        assert abs(rows[0]["burn"] - 0.2) < 1e-6 and rows[0]["ok"]

    def test_threshold_inside_a_populated_bucket_counts_good(self):
        """Review-regression pin: requests BELOW the target whose bucket
        straddles the threshold must burn ZERO budget — the containing
        bucket's upper bound is the effective threshold. (The first cut
        excluded that bucket: 100 requests at 450ms against a 500ms
        target reported bad_fraction 1.0 — a guaranteed false page.)"""
        met = _fed_metrics([0.45] * 100)      # all meet a 500ms target
        rows = evaluate_slo(parse_slo("e2e_p99=500ms"), met)
        assert rows[0]["bad"] == 0 and rows[0]["burn"] == 0.0
        assert rows[0]["ok"]
        # and a nominal bucket-bound threshold keeps working despite the
        # bound being stored as 1.0000000000000002
        rows = evaluate_slo(parse_slo("e2e_p99=1s"),
                            _fed_metrics([0.9] * 50))
        assert rows[0]["bad"] == 0 and rows[0]["ok"]
        # observations past the threshold's bucket still count bad
        rows = evaluate_slo(parse_slo("e2e_p99=500ms"),
                            _fed_metrics([0.45] * 99 + [3.0]))
        assert rows[0]["bad"] == 1

    def test_goodput_floor_counts_non_completed(self):
        met = _fed_metrics([0.01] * 8)
        met.record_request(_done_request(90, 1.0, status="rejected"))
        met.record_request(_done_request(91, 1.0, status="timeout"))
        rows = evaluate_slo(parse_slo("goodput=0.5"), met)
        assert rows[0]["bad"] == 2 and rows[0]["total"] == 10
        assert rows[0]["ok"]                       # 80% >= 50% floor
        rows = evaluate_slo(parse_slo("goodput=0.9"), met)
        assert not rows[0]["ok"]                   # 80% < 90% floor


class TestSLOMonitorWindows:
    def _monitor(self, met, **kw):
        base = dict(long_s=60.0, short_s=10.0, burn_threshold=2.0)
        base.update(kw)
        return SLOMonitor(parse_slo("e2e_p90=1s"), met, **base)

    def test_clean_run_stays_silent(self):
        met = ServingMetrics()
        mon = self._monitor(met)
        rid = [0]

        def feed(n, e2e):
            for _ in range(n):
                met.record_request(_done_request(rid[0], e2e))
                rid[0] += 1
        for t in range(0, 120, 5):
            feed(10, 0.01)
            mon.poll(float(t))
        assert mon.alerts_total == 0 and not mon.breaching
        assert mon.alerts == []

    def test_alert_fires_on_sustained_injected_latency(self):
        """Injected latency past the target on every request: both
        windows burn at 10x budget -> exactly ONE structured alert
        (transition), visible through the metrics emission path."""
        seen = []
        met = ServingMetrics(on_record=seen.append)
        mon = self._monitor(met)
        rid = [0]

        def feed(n, e2e):
            for _ in range(n):
                met.record_request(_done_request(rid[0], e2e))
                rid[0] += 1
        for t in range(0, 30, 5):      # healthy warm-up
            feed(10, 0.01)
            mon.poll(float(t))
        for t in range(30, 100, 5):    # injected: every request 5s e2e
            feed(10, 5.0)
            mon.poll(float(t))
        assert mon.breaching and mon.alerts_total == 1
        alert_rows = [r for r in seen if "slo_alert" in r]
        assert len(alert_rows) == 1
        a = alert_rows[0]["slo_alert"]
        assert a["target"] == "e2e_p90" and a["breaching"]
        assert a["burn_long"] >= 2.0 and a["burn_short"] >= 2.0

    def test_short_window_recovery_clears(self):
        """After the injected stretch ends, the SHORT window recovers
        first and the breach clears (one slo_clear event) even while the
        long window still remembers the bad stretch — the multi-window
        point: no paging after recovery."""
        seen = []
        met = ServingMetrics(on_record=seen.append)
        mon = self._monitor(met)
        rid = [0]

        def feed(n, e2e):
            for _ in range(n):
                met.record_request(_done_request(rid[0], e2e))
                rid[0] += 1
        for t in range(0, 30, 5):
            feed(10, 5.0)              # bad stretch
            mon.poll(float(t))
        assert mon.breaching
        for t in range(30, 55, 5):
            feed(10, 0.01)             # recovered
            mon.poll(float(t))
        assert not mon.breaching
        kinds = [("alert" if "slo_alert" in r else "clear")
                 for r in seen if "slo_alert" in r or "slo_clear" in r]
        assert kinds == ["alert", "clear"]
        # the long window alone still carries the bad stretch
        last = mon.summary()["last_eval"][0]
        assert last["burn_long"] > 2.0 and last["burn_short"] < 2.0

    def test_metrics_text_is_conformant(self):
        met = _fed_metrics([0.01] * 10)
        mon = self._monitor(met)
        mon.poll(0.0)
        mon.poll(5.0)
        fams = lint_exposition(mon.metrics_text())
        assert fams["paddle_tpu_slo_alerts_total"]["type"] == "counter"

    def test_poll_time_must_be_monotonic(self):
        mon = self._monitor(ServingMetrics())
        mon.poll(5.0)
        with pytest.raises(ValueError, match="backwards"):
            mon.poll(1.0)


# ---------------------------------------------------- live engine + server

CAP, NEW, BATCH = 8, 6, 2


@pytest.fixture(scope="module")
def served_model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m, cfg


def _prompts(cfg, lens, seed=1):
    rng = np.random.RandomState(seed)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    return [ids[r, :ln] for r, ln in enumerate(lens)]


def _get_json(url):
    return json.loads(urlopen(url, timeout=10).read())


class TestTelemetryServer:
    def test_live_engine_all_endpoints_concurrent_zero_misses(
            self, served_model):
        """The acceptance pin: a live engine under traffic serves all
        four endpoints WHILE decoding — every payload validates, the
        steady loop adds zero jit cache misses with the server attached,
        and /tracez explains the requests it retained."""
        m, cfg = served_model
        eng = ServingEngine(m, ServingConfig(
            max_batch=BATCH, prompt_cap=CAP, max_new_tokens=NEW,
            decode_chunk=3))
        prompts = _prompts(cfg, [CAP, 5, 7, 3, 6, CAP])
        srv = eng.serve_telemetry()
        try:
            for p in prompts[:2]:
                eng.submit(p)
            eng.drain()                          # warmup compiles
            miss0 = compile_cache_misses()

            results, errors = {"passes": 0}, []

            def scrape():
                try:
                    while not stop.is_set():
                        lint_exposition(
                            urlopen(srv.url("/metrics"),
                                    timeout=10).read().decode())
                        h = _get_json(srv.url("/healthz"))
                        assert h["status"] == "ok"
                        s = _get_json(srv.url("/statusz"))
                        assert s["engine"]["paged"] is False
                        _get_json(srv.url("/tracez"))
                        results["passes"] += 1
                except Exception as e:           # noqa: BLE001
                    errors.append(e)

            stop = threading.Event()
            th = threading.Thread(target=scrape, daemon=True)
            th.start()
            try:
                for _ in range(3):
                    for p in prompts:
                        eng.submit(p)
                    eng.drain()
            finally:
                stop.set()
                th.join(timeout=10)
            assert not errors, errors
            assert results["passes"] >= 1
            assert compile_cache_misses() - miss0 == 0
            assert eng.monitor.recompiles == 0

            tz = _get_json(srv.url("/tracez?order=slowest&limit=100"))
            assert tz["summary"]["retained"] == 20   # 2 warmup + 18
            for tr in tz["traces"]:
                assert tr["trace_id"].startswith(eng._run_id)
                names = [e[0] for e in tr["events"]]
                assert names[0] == "prefill" and "decode" in names
        finally:
            srv.close()

    def test_healthz_drain_flip_and_unknown_route(self, served_model):
        m, cfg = served_model
        eng = ServingEngine(m, ServingConfig(
            max_batch=BATCH, prompt_cap=CAP, max_new_tokens=NEW,
            decode_chunk=3, queue_high_watermark=4))
        srv = eng.serve_telemetry()
        try:
            h = _get_json(srv.url("/healthz"))
            assert h["status"] == "ok" and h["queue_high_watermark"] == 4
            eng.begin_drain()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urlopen(srv.url("/healthz"), timeout=10)
            assert ei.value.code == 503
            assert json.loads(ei.value.read())["status"] == "draining"
            eng.resume_admission()
            assert _get_json(srv.url("/healthz"))["status"] == "ok"
            with pytest.raises(urllib.error.HTTPError) as ei:
                urlopen(srv.url("/nope"), timeout=10)
            assert ei.value.code == 404
        finally:
            srv.close()

    def test_broken_producer_500s_the_scrape_not_the_server(
            self, served_model):
        m, cfg = served_model
        eng = ServingEngine(m, ServingConfig(
            max_batch=BATCH, prompt_cap=CAP, max_new_tokens=NEW,
            decode_chunk=3))
        srv = eng.serve_telemetry()
        try:
            srv.registry.register(
                "broken", lambda: (_ for _ in ()).throw(
                    RuntimeError("boom")))
            with pytest.raises(urllib.error.HTTPError) as ei:
                urlopen(srv.url("/metrics"), timeout=10)
            assert ei.value.code == 500
            assert "boom" in json.loads(ei.value.read())["error"]
            srv.registry.unregister("broken")
            # the server survives: next scrape is clean
            lint_exposition(urlopen(srv.url("/metrics"),
                                    timeout=10).read().decode())
        finally:
            srv.close()

    def test_tracez_keeps_rejects_and_timeouts(self, served_model):
        m, cfg = served_model
        fake = {"t": 0.0}
        eng = ServingEngine(m, ServingConfig(
            max_batch=BATCH, prompt_cap=CAP, max_new_tokens=NEW,
            decode_chunk=3, deadline_s=0.5),
            metrics=ServingMetrics(trace_buffer=TraceBuffer(64)),
            clock=lambda: fake["t"])
        prompts = _prompts(cfg, [4, 4])
        eng.submit(prompts[0])                    # will expire
        eng.submit(np.arange(1, CAP + 3))         # rejected: prompt_shape
        fake["t"] = 1.0
        eng.submit(prompts[1])
        eng.drain()
        buf = eng.metrics.trace_buffer
        by = {t["status"]: t for t in buf.snapshot()}
        assert set(by) == {"done", "timeout", "rejected"}
        assert by["rejected"]["reason"] == "prompt_shape"
        assert by["timeout"]["reason"] == "queue_deadline"

    def test_request_span_tree_shape(self, served_model):
        m, cfg = served_model
        eng = ServingEngine(m, ServingConfig(
            max_batch=BATCH, prompt_cap=CAP, max_new_tokens=NEW,
            decode_chunk=3))
        done = []
        eng.submit(_prompts(cfg, [5])[0])
        done += eng.drain()
        r = done[0]
        tree = r.trace.span_tree()
        assert tree["trace_id"] == r.trace.trace_id
        assert tree["t0"] == r.trace.t_enqueue
        assert tree["t1"] == r.trace.t_finish
        names = [s["name"] for s in tree["spans"]]
        assert names[0] == "queue" and names[1] == "prefill"
        assert names.count("decode") == len(
            [e for e in r.trace.events if e[0] == "decode"])
        for s in tree["spans"]:
            assert tree["t0"] <= s["t0"] <= s["t1"] <= tree["t1"]
        # chunk-granular charging: a request's decode windows are the
        # chunks it was LIVE for, and the JSONL record carries them
        rec = r.record()
        assert rec["trace_id"] == tree["trace_id"]
        assert [e[0] for e in rec["events"]] == names[1:]


class TestPagedTraceEvents:
    def test_suffix_prefill_and_decode_windows(self, served_model):
        """Paged + prefix-cache engine: the repeated prompt's trace shows
        the cache doing its job — a suffix_prefill (or NO prefill at
        all on the zero-prefill hit) instead of a full one."""
        m, cfg = served_model
        eng = ServingEngine(m, ServingConfig(
            max_batch=2, prompt_cap=8, max_new_tokens=4, decode_chunk=2,
            paged=True, kv_block=4, prefix_cache=True))
        rng = np.random.RandomState(5)
        p = rng.randint(1, cfg.vocab_size, (8,)).astype(np.int64)
        eng.submit(p)
        first = eng.drain()
        assert [e[0] for e in first[0].trace.events][0] == "prefill"
        # identical prompt: block-aligned full hit -> zero-prefill (no
        # prefill window in the trace; TTFT = one decode step)
        eng.submit(p.copy())
        second = eng.drain()
        names = [e[0] for e in second[0].trace.events]
        assert "prefill" not in names and "suffix_prefill" not in names
        assert names and all(n == "decode" for n in names)
        # divergent tail -> suffix prefill window
        d = p.copy()
        d[4:] = rng.randint(1, cfg.vocab_size, (4,))
        eng.submit(d)
        third = eng.drain()
        names = [e[0] for e in third[0].trace.events]
        assert names[0] == "suffix_prefill"
        st = eng.statusz()
        assert st["kv"]["blocks_total"] == eng._pool.num_blocks
        assert st["prefix_cache"]["cached_blocks"] > 0


class TestHapiTelemetry:
    def test_profiler_callback_registers_and_unregisters(self):
        from paddle_tpu.hapi.callbacks import ProfilerCallback
        from paddle_tpu.obs import TelemetryServer
        from paddle_tpu.profiler.timeline import SpanRecorder
        mon = StepMonitor(items_per_step=4, track_memory=False)
        with mon.step():
            pass
        rec = SpanRecorder()
        rec.record("step", 0.0, 0.5, step=1)
        srv = TelemetryServer()                   # bound, not started
        try:
            cb = ProfilerCallback(monitor=mon, summary=False,
                                  timeline=rec, telemetry=srv)
            cb.on_train_begin()
            try:
                assert set(srv.registry.producers) == {"train_monitor",
                                                       "train_goodput"}
                fams = lint_exposition(srv.registry.render())
                assert "paddle_tpu_steps_total" in fams
                assert "paddle_tpu_goodput_ratio" in fams
            finally:
                cb.on_train_end()
            assert srv.registry.producers == []
        finally:
            srv.close()

    def test_young_timeline_renders_empty_not_broken(self):
        from paddle_tpu.hapi.callbacks import ProfilerCallback
        from paddle_tpu.obs import TelemetryServer
        from paddle_tpu.profiler.timeline import SpanRecorder
        srv = TelemetryServer()
        try:
            cb = ProfilerCallback(summary=False, timeline=SpanRecorder(),
                                  telemetry=srv)
            cb.on_train_begin()
            try:
                assert srv.registry.render() == ""
            finally:
                cb.on_train_end()
        finally:
            srv.close()
