"""Autograd engine tests — analytic grads vs jax.grad ground truth and
numeric finite differences (the reference's check_grad pattern,
eager_op_test.py:2084 with numeric_grad_delta)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


def t(a, sg=False):
    return paddle.to_tensor(a, stop_gradient=sg)


def numeric_grad(f, x, eps=1e-3):
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp, xm = x.copy(), x.copy()
        xp[i] += eps
        xm[i] -= eps
        g[i] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


class TestBackward:
    def test_chain(self):
        a = np.random.rand(3, 4).astype("float32") + 0.5
        x = t(a)
        y = (x * x + paddle.exp(x)).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), 2 * a + np.exp(a), rtol=1e-4)

    def test_broadcast_grad(self):
        a = np.random.randn(3, 4).astype("float32")
        b = np.random.randn(4).astype("float32")
        x, y = t(a), t(b)
        (x + y).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones_like(a))
        np.testing.assert_allclose(y.grad.numpy(), np.full_like(b, 3))

    def test_diamond_reuse(self):
        a = np.random.randn(3).astype("float32")
        x = t(a)
        y = x * 2
        z = (y + y * y).sum()
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), 2 + 8 * a, rtol=1e-5)

    def test_accumulation_over_backwards(self):
        x = t(np.array([1.0, 2.0], "float32"))
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])
        x.clear_grad()
        assert x.grad is None

    def test_stop_gradient_blocks(self):
        x = t(np.ones(3, "float32"))
        y = t(np.ones(3, "float32"), sg=True)
        (x * y).sum().backward()
        assert y.grad is None
        np.testing.assert_allclose(x.grad.numpy(), np.ones(3))

    def test_detach(self):
        x = t(np.ones(3, "float32"))
        d = (x * 2).detach()
        assert d.stop_gradient
        (d * x).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2, 2, 2])

    def test_retain_graph(self):
        x = t(np.ones(2, "float32"))
        y = (x * x).sum()
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4, 4])
        with pytest.raises(RuntimeError):
            y.backward()  # freed now

    def test_non_scalar_backward_seeds_ones(self):
        # paddle contract: implicit ones cotangent for any output shape
        x = t(np.ones((2, 2), "float32"))
        y = x * 2
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), np.full((2, 2), 2.0))
        x.clear_grad()
        y2 = x * 2
        y2.backward(paddle.full([2, 2], 3.0))
        np.testing.assert_allclose(x.grad.numpy(), np.full((2, 2), 6.0))

    def test_stop_gradient_on_intermediate_blocks_flow(self):
        x = t(np.ones(2, "float32"))
        y = x * 2
        y.stop_gradient = True  # user-detached branch
        z = (y * 3).sum()
        z.backward()
        assert x.grad is None

    def test_no_grad_context(self):
        x = t(np.ones(2, "float32"))
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient and y._node is None

    def test_matches_numeric(self):
        a = np.random.rand(4, 4).astype("float32") + 0.1

        def paddle_f(arr):
            x = t(arr)
            loss = paddle.tanh(x @ x).mean()
            loss.backward()
            return x.grad.numpy()

        def np_f(arr):
            return float(np.tanh(arr @ arr).mean())

        np.testing.assert_allclose(paddle_f(a), numeric_grad(np_f, a.astype("float64")),
                                   rtol=1e-2, atol=1e-3)

    def test_softmax_ce_grad_vs_jax(self):
        logits = np.random.randn(4, 10).astype("float32")
        labels = np.random.randint(0, 10, (4,))
        x = t(logits)
        loss = paddle.nn.functional.cross_entropy(x, paddle.to_tensor(labels))
        loss.backward()

        def jf(l):
            lp = jax.nn.log_softmax(l, axis=-1)
            return -lp[jnp.arange(4), jnp.asarray(labels)].mean()
        g = jax.grad(jf)(jnp.asarray(logits))
        np.testing.assert_allclose(x.grad.numpy(), np.asarray(g), rtol=1e-4, atol=1e-5)

    def test_hooks(self):
        x = t(np.ones(2, "float32"))
        seen = []
        h = x.register_hook(lambda g: seen.append(g.numpy().copy()))
        (x * 3).sum().backward()
        assert len(seen) == 1
        np.testing.assert_allclose(seen[0], [3, 3])
        h.remove()

    def test_multi_output_op(self):
        a = np.random.randn(6).astype("float32")
        x = t(a)
        parts = paddle.split(x, 3)
        (parts[0].sum() * 2 + parts[2].sum()).backward()
        expected = np.concatenate([np.full(2, 2.0), np.zeros(2), np.ones(2)])
        np.testing.assert_allclose(x.grad.numpy(), expected)


class TestGradAPI:
    def test_paddle_grad(self):
        a = np.random.randn(3).astype("float32")
        x = t(a)
        y = (x ** 2).sum()
        (gx,) = paddle.grad([y], [x])
        np.testing.assert_allclose(gx.numpy(), 2 * a, rtol=1e-5)
        assert x.grad is None  # .grad not touched

    def test_grad_unused(self):
        x = t(np.ones(2, "float32"))
        z = t(np.ones(2, "float32"))
        y = (x * 2).sum()
        with pytest.raises(RuntimeError):
            paddle.grad([y], [z], retain_graph=True)
        g = paddle.grad([y], [z], allow_unused=True)
        assert g[0] is None


class TestPyLayer:
    def test_custom_forward_backward(self):
        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return dy * 3 * x * x

        a = np.random.randn(4).astype("float32")
        x = t(a)
        y = Cube.apply(x)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), 3 * a * a, rtol=1e-5)

    def test_pylayer_multi_io(self):
        class AddMul(PyLayer):
            @staticmethod
            def forward(ctx, x, y):
                ctx.save_for_backward(x, y)
                return x + y, x * y

            @staticmethod
            def backward(ctx, da, dm):
                x, y = ctx.saved_tensor()
                return da + dm * y, da + dm * x

        a, b = np.ones(2, "float32") * 2, np.ones(2, "float32") * 3
        x, y = t(a), t(b)
        s, m = AddMul.apply(x, y)
        (s.sum() + m.sum()).backward()
        np.testing.assert_allclose(x.grad.numpy(), 1 + b)
        np.testing.assert_allclose(y.grad.numpy(), 1 + a)


def test_incubate_functional_autodiff():
    """jvp/vjp/Jacobian/Hessian + higher-order grad (reference:
    paddle.incubate.autograd functional API over the prim machinery)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate import autograd as iag

    def f(x):
        return (x ** 3).sum()

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    out, tangent = iag.jvp(f, x)
    np.testing.assert_allclose(float(tangent), 3 * 1 + 3 * 4, rtol=1e-5)

    out, (gx,) = iag.vjp(f, x)
    np.testing.assert_allclose(gx.numpy(), [3.0, 12.0], rtol=1e-5)

    def g(x):
        return paddle.stack([x[0] * x[1], x[0] + x[1]])

    J = iag.Jacobian(g, x)
    np.testing.assert_allclose(J.numpy(), [[2.0, 1.0], [1.0, 1.0]], rtol=1e-5)

    H = iag.Hessian(f, x)
    np.testing.assert_allclose(H.numpy(), [[6.0, 0.0], [0.0, 12.0]], rtol=1e-5)

    # third-order derivative of sum(x^3) wrt scalar-summed input: 6
    g3 = iag.grad(lambda x: (x ** 3).sum(), paddle.to_tensor(np.float32(2.0)),
                  order=3)
    np.testing.assert_allclose(float(g3), 6.0, rtol=1e-5)


def test_train_step_grad_accumulation_matches_full_batch():
    """grad_accum_steps=A over a batch == one full-batch step (reference
    gradient_merge semantics: same update, 1/A activation memory)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.jit.train_step import TrainStep

    def make():
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 1))
        o = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        return m, o

    np.random.seed(0)
    X = np.random.randn(8, 6).astype("float32")
    Y = np.random.randn(8, 1).astype("float32")

    m1, o1 = make()
    s1 = TrainStep(m1, o1, lambda x, y: nn.MSELoss()(m1(x), y))
    l1 = float(s1(paddle.to_tensor(X), paddle.to_tensor(Y)))

    m2, o2 = make()
    s2 = TrainStep(m2, o2, lambda x, y: nn.MSELoss()(m2(x), y),
                   grad_accum_steps=4)
    l2 = float(s2(paddle.to_tensor(X), paddle.to_tensor(Y)))

    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-5,
                                   atol=1e-6)
