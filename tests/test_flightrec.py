"""Flight recorder (ISSUE 17) — anomaly-triggered profiling invariants.

The contract under test:

  1. RING — capacity is a hard bound under churn; eviction never removes
     a trigger-pinned capture while a periodic one remains (only an
     all-pinned ring evicts its oldest pinned entry); evicted captures
     drop their trace file from disk.
  2. DEDUP — a trigger while a capture is pending/active COALESCES into
     it (and pins it); a trigger within the cooldown window of the last
     trigger-started capture is SUPPRESSED — an alert storm yields ONE
     capture. The cooldown clock is injected, so the window is exact.
  3. EVIDENCE — every finished capture appends one structured
     {"capture"} JSONL row linking trigger kind -> trace path -> the
     trigger's own row verbatim; a failing backend counts
     capture_errors and the recorder re-arms.
  4. BUS — attach() chains onto existing on_report/on_alert/on_record
     hooks without dropping them, detach() restores; the tap fires on
     slo_alert/straggler/recompile/numerics-with-events rows and
     nothing else.
  5. /profilez — list + KernelView/DeviceView/DistributedView tables
     byte-identical to trace_analysis on the same file + raw download,
     direct and over HTTP (bad input -> 400); fleet-merged like tracez.
  6. SATELLITES — /tracez?fmt=chrome trace-event export, the goodput
     timeline's install->first-span init anchor, kernel_diff /
     diff_regressions attribution, and the live-engine run: /profilez
     concurrent with closed-loop decode at zero post-warmup jit misses.
"""
import gzip
import json
import os
import time
import urllib.error
from urllib.request import urlopen

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.obs import (FixtureBackend, FlightRecorder, MetricsRegistry,
                            Raw, TelemetryServer, chrome_trace)
from paddle_tpu.obs.flightrec import TRIGGER_KEYS
from paddle_tpu.profiler.monitor import StepMonitor
from paddle_tpu.profiler.trace_analysis import (analyze, diff_regressions,
                                                format_kernel_diff,
                                                kernel_diff)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "mini_step.trace.json.gz")


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t


def _rec(tmp_path, **kw):
    kw.setdefault("backend", FixtureBackend(FIXTURE))
    kw.setdefault("cooldown_s", 0.0)
    return FlightRecorder(str(tmp_path / "captures"), **kw)


def _steps(rec, n):
    for _ in range(n):
        rec.begin_step()
        rec.end_step()


# ------------------------------------------------------------------ ring

class TestRing:
    def test_capacity_is_a_hard_bound_under_periodic_churn(self, tmp_path):
        rec = _rec(tmp_path, ring=3, every=1, capture_steps=1)
        _steps(rec, 10)
        s = rec.summary()
        assert s["captures_total"] == 10
        assert s["retained"] == 3
        assert s["evicted_periodic"] == 7
        assert s["evicted_pinned"] == 0
        # the ring keeps the newest captures
        assert [c["id"] for c in rec.captures] == ["c0008", "c0009",
                                                   "c0010"]

    def test_pinned_survives_periodic_eviction(self, tmp_path):
        rec = _rec(tmp_path, ring=3, every=1, capture_steps=1,
                   trigger_steps=1)
        _steps(rec, 2)                      # two periodic captures
        cid = rec.trigger("slo_alert", {"slo_alert": {"burn": 9.9}})
        _steps(rec, 8)                      # churn well past capacity
        ids = [c["id"] for c in rec.captures]
        assert cid in ids                   # the pinned one never evicted
        pinned = [c["pinned"] for c in rec.captures]
        assert sum(pinned) == 1
        assert rec.evicted_pinned == 0
        assert rec.evicted_periodic > 0

    def test_all_pinned_ring_still_bounded(self, tmp_path):
        rec = _rec(tmp_path, ring=2, trigger_steps=1)
        for i in range(3):
            rec.trigger("straggler", {"straggler": {"i": i}})
            _steps(rec, 1)
        s = rec.summary()
        assert s["retained"] == 2
        assert s["retained_pinned"] == 2
        assert s["evicted_pinned"] == 1     # oldest pinned gave way
        assert [c["id"] for c in rec.captures] == ["c0002", "c0003"]

    def test_eviction_removes_trace_file(self, tmp_path):
        rec = _rec(tmp_path, ring=1, every=1, capture_steps=1)
        _steps(rec, 2)
        gone = str(tmp_path / "captures" / "c0001.trace.json.gz")
        kept = str(tmp_path / "captures" / "c0002.trace.json.gz")
        assert not os.path.exists(gone)
        assert os.path.exists(kept)

    def test_periodic_cadence_and_validation(self, tmp_path):
        rec = _rec(tmp_path, ring=8, every=4, capture_steps=2)
        _steps(rec, 8)
        # first periodic starts at step 1; next one `every` steps later
        firsts = [c["step_first"] for c in rec.captures]
        assert firsts == [1, 5]
        with pytest.raises(ValueError):
            _rec(tmp_path, ring=0)
        with pytest.raises(ValueError):
            _rec(tmp_path, every=-1)


# ----------------------------------------------------------------- dedup

class TestTriggerDedup:
    def test_cooldown_suppresses_then_reopens(self, tmp_path):
        clk = FakeClock()
        rec = _rec(tmp_path, cooldown_s=30.0, trigger_steps=1, clock=clk)
        assert rec.trigger("slo_alert", {}) == "c0001"
        _steps(rec, 1)                      # capture finishes
        clk.t = 10.0                        # inside the window
        assert rec.trigger("slo_alert", {}) is None
        assert rec.triggers_suppressed == 1
        clk.t = 31.0                        # window expired
        assert rec.trigger("slo_alert", {}) == "c0002"
        assert rec.summary()["captures_total"] == 1

    def test_storm_coalesces_into_one_capture(self, tmp_path):
        rec = _rec(tmp_path, cooldown_s=600.0, trigger_steps=2)
        first = rec.trigger("slo_alert", {"slo_alert": {"t": "e2e"}})
        # the storm: more alerts before AND during the capture
        assert rec.trigger("slo_alert", {"slo_alert": {"t": "ttft"}}) \
            == first
        rec.begin_step()
        assert rec.trigger("straggler", {"straggler": {}}) == first
        rec.end_step()
        _steps(rec, 2)
        s = rec.summary()
        assert s["captures_total"] == 1
        assert s["triggers_total"] == 3
        assert s["triggers_coalesced"] == 2
        cap = rec.captures[0]
        assert cap["pinned"]
        assert [t["kind"] for t in cap["triggers"]] \
            == ["slo_alert", "slo_alert", "straggler"]

    def test_trigger_pins_and_extends_active_periodic(self, tmp_path):
        rec = _rec(tmp_path, every=100, capture_steps=1, trigger_steps=3)
        rec.begin_step()                    # periodic capture is active
        assert rec.captures_total == 0
        cid = rec.trigger("recompile", {"recompile": {"kind": "train"}})
        assert cid == "c0001"               # coalesced into the periodic
        rec.end_step()                      # 1 of 3 steps — extended
        assert rec.summary()["active"] == cid
        _steps(rec, 2)
        cap = rec.captures[0]
        assert cap["kind"] == "periodic" and cap["pinned"]
        assert cap["step_last"] - cap["step_first"] + 1 == 3

    def test_tap_key_probe(self, tmp_path):
        rec = _rec(tmp_path, trigger_steps=1, cooldown_s=0.0)
        for key in TRIGGER_KEYS:
            rec.tap({key: {}, "ts": 1.0})
            _steps(rec, 1)
        rec.tap({"numerics": {"events": [{"kind": "nan"}]}})
        _steps(rec, 1)
        n_keys = len(TRIGGER_KEYS) + 1       # + numerics-with-events
        assert rec.triggers_total == n_keys
        # inert rows: clears, event-free numerics, plain steps, non-dicts
        rec.tap({"slo_clear": {}})
        rec.tap({"straggler_clear": {}})
        rec.tap({"mem_pressure_clear": {}})
        rec.tap({"headroom_low_clear": {}})
        rec.tap({"numerics": {"events": []}})
        rec.tap({"step": 7, "wall_s": 0.1})
        rec.tap("not a dict")
        assert rec.triggers_total == n_keys


# -------------------------------------------------------------- evidence

class TestEvidence:
    def test_capture_row_links_triggers_own_row(self, tmp_path):
        path = str(tmp_path / "rows.jsonl")
        rec = _rec(tmp_path, trigger_steps=1, jsonl_path=path)
        alert_row = {"slo_alert": {"target": "e2e_p99", "burn_long": 9.0},
                     "ts": 123.0}
        rec.trigger("slo_alert", alert_row)
        _steps(rec, 1)
        rows = [json.loads(line) for line in open(path)]
        caps = [r for r in rows if "capture" in r]
        assert len(caps) == 1
        meta = caps[0]["capture"]
        assert meta["pinned"] and meta["kind"] == "trigger"
        assert os.path.exists(meta["trace_path"])
        assert meta["triggers"][0]["kind"] == "slo_alert"
        assert meta["triggers"][0]["row"] == alert_row   # verbatim link
        assert meta["steps"] == 1

    def test_on_capture_hook_and_meta_shape(self, tmp_path):
        seen = []
        rec = _rec(tmp_path, every=1, capture_steps=1)
        rec.on_capture = seen.append
        _steps(rec, 2)
        assert [m["id"] for m in seen] == ["c0001", "c0002"]
        assert all(m["wall_s"] >= 0 for m in seen)

    def test_failing_backend_counts_and_rearms(self, tmp_path):
        class Boom:
            def start(self):
                pass

            def stop(self, dest):
                raise RuntimeError("tracer exploded")

        rec = _rec(tmp_path, trigger_steps=1, backend=Boom())
        rec.trigger("slo_alert", {})
        _steps(rec, 1)
        assert rec.capture_errors == 1
        assert rec.captures[0]["error"].startswith("RuntimeError")
        # the recorder re-arms: a later trigger captures again
        rec.backend = FixtureBackend(FIXTURE)
        rec.trigger("slo_alert", {})
        _steps(rec, 1)
        assert rec.captures[-1]["error"] is None
        assert rec.captures[-1]["trace_path"]

    def test_failing_start_counts_and_clears_active(self, tmp_path):
        class BoomStart:
            def start(self):
                raise RuntimeError("no tracer")

            def stop(self, dest):  # pragma: no cover
                return None

        rec = _rec(tmp_path, trigger_steps=1, backend=BoomStart())
        rec.trigger("slo_alert", {})
        _steps(rec, 1)
        assert rec.capture_errors == 1
        assert rec.summary()["active"] is None
        assert rec.captures_total == 0

    def test_metrics_text_exposes_counters(self, tmp_path):
        from paddle_tpu.profiler._metrics import parse_exposition
        rec = _rec(tmp_path, trigger_steps=1, every=1, capture_steps=1)
        rec.trigger("slo_alert", {})
        _steps(rec, 3)
        fams = parse_exposition(rec.metrics_text())
        pre = "paddle_tpu_flightrec_"

        def val(name):
            return float(fams[pre + name]["samples"][0][2])

        assert val("captures_total") == 3
        assert val("captures_pinned_total") == 1
        assert val("triggers_total") == 1
        assert val("ring_retained") == 3


# ------------------------------------------------------------ trigger bus

class TestAttach:
    def test_chain_preserves_previous_hooks(self, tmp_path):
        prev_rows = []
        mon = StepMonitor(track_memory=False,
                          on_report=prev_rows.append)
        rec = _rec(tmp_path, trigger_steps=1)
        rec.attach(monitor=mon)
        assert mon.flightrec is rec
        row = {"straggler": {"ratio": 3.0}, "ts": 1.0}
        mon.on_report(row)                  # the chained hook
        assert prev_rows == [row]           # previous hook still ran
        assert rec.triggers_total == 1
        rec.detach()
        assert mon.flightrec is None
        mon.on_report({"straggler": {}})
        assert rec.triggers_total == 1      # tap unhooked
        assert len(prev_rows) == 2          # original hook restored

    def test_second_recorder_rejected(self, tmp_path):
        mon = StepMonitor(track_memory=False)
        a = _rec(tmp_path, trigger_steps=1)
        b = FlightRecorder(str(tmp_path / "b"),
                           backend=FixtureBackend(FIXTURE))
        a.attach(monitor=mon)
        with pytest.raises(ValueError):
            b.attach(monitor=mon)
        a.attach(monitor=mon)               # re-attach of self is fine
        a.detach()

    def test_monitor_steps_drive_recorder(self, tmp_path):
        mon = StepMonitor(track_memory=False)
        rec = _rec(tmp_path, trigger_steps=2).attach(monitor=mon)
        rec.trigger("slo_alert", {})
        for _ in range(3):
            mon.begin_step()
            mon.end_step(items=4)
        assert rec.captures_total == 1
        assert rec.captures[0]["step_last"] - \
            rec.captures[0]["step_first"] + 1 == 2
        rec.detach()

    def test_externally_timed_steps_drive_recorder(self, tmp_path):
        # TrainStep's path: end_step(wall_s=...) with NO begin_step —
        # each external end IS a step boundary and must advance captures
        mon = StepMonitor(track_memory=False)
        rec = _rec(tmp_path, trigger_steps=2).attach(monitor=mon)
        rec.trigger("slo_alert", {})
        for _ in range(4):
            mon.end_step(items=4, wall_s=0.01)
        assert rec.captures_total == 1
        rec.detach()

    def test_recompile_rows_reach_the_bus(self, tmp_path):
        mon = StepMonitor(track_memory=False, log_recompiles=False)
        rec = _rec(tmp_path, trigger_steps=1).attach(monitor=mon)
        mon.record_compile("train", ((4, 8),))       # first compile
        assert rec.triggers_total == 0               # not a recompile
        mon.record_compile("train", ((8, 8),), prev_sig=((4, 8),))
        assert rec.triggers_total == 1
        cap_trig = (rec.summary()["pending"] or
                    rec.summary()["active"])
        assert cap_trig is not None
        rec.detach()

    def test_slo_alert_via_metrics_hook(self, tmp_path):
        # serve_telemetry taps metrics.on_record — SLO alerts flow
        # through metrics._emit, so the bus sees them without touching
        # slo.on_alert (no double-tap)
        from paddle_tpu.inference import ServingMetrics
        met = ServingMetrics()
        rec = _rec(tmp_path, trigger_steps=1).attach(metrics=met)
        met._emit({"slo_alert": {"target": "e2e_p99"}, "ts": 1.0})
        met._emit({"slo_clear": {"target": "e2e_p99"}, "ts": 2.0})
        assert rec.triggers_total == 1
        rec.detach()


# -------------------------------------------------------------- /profilez

class TestProfilez:
    def _captured(self, tmp_path):
        rec = _rec(tmp_path, trigger_steps=2)
        rec.trigger("slo_alert", {"slo_alert": {"burn": 5.0}})
        _steps(rec, 2)
        return rec

    def test_list_and_views_match_trace_analysis(self, tmp_path):
        rec = self._captured(tmp_path)
        listing = rec.profilez({})
        assert listing["summary"]["captures_total"] == 1
        cap = listing["captures"][0]
        assert cap["pinned"] and cap["steps"] == 2
        an = analyze(cap["trace_path"], steps=2)
        for view, table in (("kernel", an.kernel_view()),
                            ("device", an.device_view()),
                            ("distributed", an.distributed_view())):
            p = rec.profilez({"id": cap["id"], "view": view})
            assert p["table"] == table      # byte-identical render
            assert p["rows"]
            assert p["total_device_us"] == an.total_device_us()

    def test_raw_download_and_errors(self, tmp_path):
        rec = self._captured(tmp_path)
        cap = rec.profilez({})["captures"][0]
        raw = rec.profilez({"id": cap["id"], "fmt": "raw"})
        assert isinstance(raw, Raw)
        with open(cap["trace_path"], "rb") as f:
            assert raw.body == f.read()
        with pytest.raises(ValueError):
            rec.profilez({"id": "c9999"})
        with pytest.raises(ValueError):
            rec.profilez({"id": cap["id"], "view": "bogus"})
        os.remove(cap["trace_path"])
        with pytest.raises(ValueError):
            rec.profilez({"id": cap["id"], "view": "kernel"})

    def test_over_http(self, tmp_path):
        rec = self._captured(tmp_path)
        srv = TelemetryServer(MetricsRegistry(),
                              routes={"/profilez": rec.profilez}).start()
        try:
            listing = json.loads(urlopen(srv.url("/profilez"),
                                         timeout=5).read())
            cap = listing["captures"][0]
            p = json.loads(urlopen(
                srv.url(f"/profilez?id={cap['id']}&view=kernel"),
                timeout=5).read())
            assert p["table"] == analyze(cap["trace_path"],
                                         steps=2).kernel_view()
            resp = urlopen(srv.url(f"/profilez?id={cap['id']}&fmt=raw"),
                           timeout=5)
            assert resp.headers["Content-Type"] == "application/gzip"
            assert "attachment" in resp.headers["Content-Disposition"]
            with open(cap["trace_path"], "rb") as f:
                assert resp.read() == f.read()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urlopen(srv.url("/profilez?id=c9999"), timeout=5)
            assert ei.value.code == 400
        finally:
            srv.close()


class TestFleetProfilez:
    def _member(self, tmp_path, name):
        rec = _rec(tmp_path / name, trigger_steps=1)
        rec.trigger("slo_alert", {"slo_alert": {"replica": name}})
        _steps(rec, 1)
        srv = TelemetryServer(MetricsRegistry(),
                              routes={"/profilez": rec.profilez}).start()
        return rec, srv

    def test_fleet_merge_and_detail_proxy(self, tmp_path):
        from paddle_tpu.obs import FleetAggregator
        ra, sa = self._member(tmp_path, "r0")
        rb, sb = self._member(tmp_path, "r1")
        bare = TelemetryServer(MetricsRegistry()).start()  # no recorder
        try:
            fleet = FleetAggregator({"r0": sa, "r1": sb, "r2": bare},
                                    timeout=2.0, cache_ttl=0.0)
            merged = fleet.fleet_profilez({})
            assert merged["summary"]["with_recorder"] == 2
            assert {c["replica"] for c in merged["captures"]} \
                == {"r0", "r1"}
            # detail mode proxies the member's own handler verbatim
            cap = next(c for c in merged["captures"]
                       if c["replica"] == "r0")
            detail = fleet.fleet_profilez({"replica": "r0",
                                           "id": cap["id"],
                                           "view": "kernel"})
            assert detail["replica"] == "r0"
            assert detail["table"] == analyze(
                cap["trace_path"], steps=1).kernel_view()
            raw = fleet.fleet_profilez({"replica": "r0",
                                        "id": cap["id"], "fmt": "raw"})
            assert isinstance(raw, Raw)
            with pytest.raises(ValueError):
                fleet.fleet_profilez({"replica": "r0", "id": "c9999"})
        finally:
            sa.close(), sb.close(), bare.close()


# ------------------------------------------------------- chrome export

class TestChromeTrace:
    REC = {"trace_id": "t-1", "status": "done", "reason": None,
           "queue_s": 0.01, "ttft_s": 0.5, "tpot_s": 0.05, "e2e_s": 1.0,
           "spans": {"t_enqueue": 100.0, "t_admit": 100.01,
                     "t_first_token": 100.5, "t_finish": 101.0},
           "events": [["prefill", 100.01, 100.4],
                      ["decode", 100.4, 101.0]]}

    def test_event_structure(self):
        doc = chrome_trace([self.REC])
        evs = doc["traceEvents"]
        names = [(e["ph"], e.get("name")) for e in evs]
        assert ("M", "process_name") in names
        req = next(e for e in evs if e["ph"] == "X"
                   and e["name"] == "request")
        assert req["ts"] == 0.0             # relative to min enqueue
        assert req["dur"] == pytest.approx(1e6)
        assert req["args"]["e2e_s"] == 1.0
        queue = next(e for e in evs if e["name"] == "queue")
        assert queue["dur"] == pytest.approx(1e4)
        assert any(e["ph"] == "I" and e["name"] == "first_token"
                   for e in evs)
        lanes = {e["name"]: e["tid"] for e in evs if e["ph"] == "X"}
        assert lanes["request"] == 0 and lanes["prefill"] == 1
        assert doc["displayTimeUnit"] == "ms"

    def test_shared_timebase_across_requests(self):
        rec2 = dict(self.REC, trace_id="t-2",
                    spans=dict(self.REC["spans"], t_enqueue=99.0,
                               t_finish=100.2))
        evs = chrome_trace([self.REC, rec2])["traceEvents"]
        reqs = {e["pid"]: e for e in evs
                if e["ph"] == "X" and e["name"] == "request"}
        assert reqs[2]["ts"] == 0.0         # earliest enqueue is t=0
        assert reqs[1]["ts"] == pytest.approx(1e6)

    def test_tracez_fmt_chrome_over_http(self):
        from paddle_tpu.obs import TraceBuffer
        buf = TraceBuffer(capacity=8)
        buf.add(self.REC)
        srv = TelemetryServer(MetricsRegistry(), tracez=buf).start()
        try:
            doc = json.loads(urlopen(srv.url("/tracez?fmt=chrome"),
                                     timeout=5).read())
            assert any(e.get("name") == "request"
                       for e in doc["traceEvents"])
        finally:
            srv.close()


# ----------------------------------------------------- timeline anchor

class TestInitAnchor:
    def test_slow_install_materializes_init_span(self, tmp_path):
        from paddle_tpu.profiler import timeline as tl
        from paddle_tpu.profiler.goodput import report_from
        p = str(tmp_path / "seg.timeline.jsonl")
        rec = tl.SpanRecorder(p)
        rec.init_gap_min_s = 0.01
        tl.install(rec)
        try:
            time.sleep(0.03)                # "model build" time
            t1 = rec.now()
            rec.record("step", t1 - 0.005, t1, step=1)
        finally:
            tl.install(None)
        rec.close()
        spans = list(rec._spans)
        assert spans[0].cat == "other" and spans[0].meta.get("init")
        assert spans[0].t1 == spans[1].t0   # seam, no gap, no overlap
        rep = report_from(p)
        rep.check_conservation()            # init time inside the ledger
        assert rep.category_s["other"] >= 0.01

    def test_fast_install_adds_nothing(self, tmp_path):
        from paddle_tpu.profiler import timeline as tl
        rec = tl.SpanRecorder()
        tl.install(rec)
        try:
            t1 = rec.now()
            rec.record("step", max(0.0, t1 - 0.001), t1, step=1)
        finally:
            tl.install(None)
        assert len(rec._spans) == 1         # below the init threshold

    def test_seasoned_recorder_reinstall_is_noop(self, tmp_path):
        from paddle_tpu.profiler import timeline as tl
        rec = tl.SpanRecorder()
        rec.init_gap_min_s = 0.0
        t = rec.now()
        rec.record("step", t, t + 0.001)
        rec.anchor_init()                   # re-install after spans
        time.sleep(0.02)
        rec.record("step", t + 0.001, t + 0.002)
        assert len(rec._spans) == 2         # no fabricated init span


# ------------------------------------------------------- kernel diffing

class TestKernelDiff:
    def _doctor(self, tmp_path, mutate):
        with gzip.open(FIXTURE, "rt") as f:
            data = json.load(f)
        mutate(data["traceEvents"])
        p = str(tmp_path / "doctored.trace.json.gz")
        with gzip.open(p, "wt") as f:
            json.dump(data, f)
        return p

    def test_self_diff_is_all_zero(self):
        an = analyze(FIXTURE, steps=1)
        diff = kernel_diff(an, an)
        assert diff["total"]["delta_us"] == 0
        assert all(r["status"] == "common" and r["delta_us"] == 0
                   for r in diff["kernels"])
        assert diff_regressions(diff, regress_pct=0.0) == []
        assert "KernelDiff" in format_kernel_diff(diff)

    def test_slowdown_attributed_to_the_kernel(self, tmp_path):
        def slow(evs):
            for e in evs:
                if e.get("ph") == "X" and e.get("name") == "fusion.1":
                    e["dur"] *= 2

        b = analyze(self._doctor(tmp_path, slow), steps=1)
        a = analyze(FIXTURE, steps=1)
        diff = kernel_diff(a, b)
        top = diff["kernels"][0]               # sorted by |delta|
        assert top["name"] == "fusion.1"
        assert top["delta_pct"] == pytest.approx(100.0)
        regs = diff_regressions(diff, regress_pct=50.0)
        assert [r["name"] for r in regs] == ["fusion.1"]
        # the gate is strict: exactly-at-threshold does not fire
        assert diff_regressions(diff, regress_pct=100.0) == []

    def test_new_and_vanished_kernels(self, tmp_path):
        def rename(evs):
            for e in evs:
                if e.get("ph") == "X" and e.get("name") == "copy.4":
                    e["name"] = "copy.5"

        b = analyze(self._doctor(tmp_path, rename), steps=1)
        diff = kernel_diff(analyze(FIXTURE, steps=1), b)
        status = {r["name"]: r["status"] for r in diff["kernels"]}
        assert status["copy.4"] == "vanished"
        assert status["copy.5"] == "new"
        regs = diff_regressions(diff, regress_pct=5.0)
        assert any(r["name"] == "copy.5" and r["reason"] == "new kernel"
                   for r in regs)

    def test_min_us_noise_floor(self, tmp_path):
        def nudge(evs):
            for e in evs:
                if e.get("ph") == "X" and e.get("name") == "copy.4":
                    e["dur"] += 20          # +20us = +20%, tiny in us
        b = analyze(self._doctor(tmp_path, nudge), steps=1)
        diff = kernel_diff(analyze(FIXTURE, steps=1), b)
        assert diff_regressions(diff, regress_pct=5.0, min_us=50.0) == []
        assert [r["name"] for r in
                diff_regressions(diff, regress_pct=5.0, min_us=10.0)] \
            == ["copy.4"]


# -------------------------------------------------- live-engine closure

class TestEngineIntegration:
    def test_profilez_concurrent_with_decode_zero_misses(self, tmp_path):
        from paddle_tpu.inference import ServingConfig, ServingEngine
        from paddle_tpu.jit.api import compile_cache_misses
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_position_embeddings=32,
                        intermediate_size=64)
        model = GPTForCausalLM(cfg)
        model.eval()
        engine = ServingEngine(model, ServingConfig(
            max_batch=2, prompt_cap=8, max_new_tokens=4, decode_chunk=2))
        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, 64, (5,)).astype(np.int64)
                   for _ in range(4)]
        for p in prompts[:2]:
            engine.submit(p)
        engine.drain()

        rec = _rec(tmp_path, trigger_steps=2, cooldown_s=600.0)
        miss0 = compile_cache_misses()
        srv = engine.serve_telemetry(flightrec=rec)
        try:
            rec.trigger("slo_alert", {"slo_alert": {"injected": True}})
            for p in prompts:
                engine.submit(p)
            listing = json.loads(urlopen(srv.url("/profilez"),
                                         timeout=5).read())
            assert "captures" in listing    # live during decode
            engine.drain()
            assert compile_cache_misses() == miss0
            assert rec.captures_total == 1
            assert rec.captures[0]["pinned"]
            page = urlopen(srv.url("/metrics"), timeout=5).read().decode()
            assert "paddle_tpu_flightrec_captures_total 1" in page
        finally:
            srv.close()
