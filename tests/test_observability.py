"""Observability layer (SURVEY §5.1 parity): trace_analysis on a
checked-in miniature device capture, StepMonitor metrics + the
recompilation detector, annotate_layers path naming, scheduler edge cases,
and the device memory telemetry the monitor reads."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import device, profiler
from paddle_tpu.profiler import (ProfilerState, StepMonitor, SummaryView,
                                 make_scheduler, trace_analysis)
import paddle_tpu.nn as nn

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")

# The fixture capture (fixtures/mini_step.trace.json.gz) holds 2 identical
# steps on a /device:TPU:0 lane — per step: fusion.1 300us, convolution.2
# 200us, all-reduce.3 100us (50us of it under convolution.2), copy.4 50us —
# plus an "XLA Modules" envelope lane and a host lane that the parser must
# exclude (both would double-count).


class TestSchedulerStateMachine:
    def test_single_record_slot_returns_immediately(self):
        s = make_scheduler(closed=0, ready=0, record=1, repeat=2)
        S = ProfilerState
        assert [s(i) for i in range(3)] == \
            [S.RECORD_AND_RETURN, S.RECORD_AND_RETURN, S.CLOSED]

    def test_infinite_repeat_cycles(self):
        s = make_scheduler(closed=1, ready=1, record=2, repeat=0)
        S = ProfilerState
        period = [S.CLOSED, S.READY, S.RECORD, S.RECORD_AND_RETURN]
        assert [s(i) for i in range(8)] == period * 2
        assert s(4000 + 2) == S.RECORD  # still cycling far out

    def test_skip_first_shifts_whole_schedule(self):
        s = make_scheduler(closed=1, ready=0, record=1, repeat=1,
                           skip_first=3)
        S = ProfilerState
        assert [s(i) for i in range(6)] == \
            [S.CLOSED, S.CLOSED, S.CLOSED, S.CLOSED, S.RECORD_AND_RETURN,
             S.CLOSED]

    def test_exhausted_repeat_stays_closed(self):
        s = make_scheduler(closed=0, ready=1, record=1, repeat=2)
        assert s(4) == ProfilerState.CLOSED
        assert s(100) == ProfilerState.CLOSED


class TestTraceAnalysis:
    def _an(self, **kw):
        return trace_analysis.analyze(FIXTURES, **kw)

    def test_find_trace_file(self):
        f = trace_analysis.find_trace_file(FIXTURES)
        assert f is not None and f.endswith(".trace.json.gz")

    def test_op_totals_and_exclusions(self):
        an = self._an(steps=2)
        rows = {r["name"]: r for r in an.op_totals()}
        assert rows["fusion.1"]["dur_us"] == 600 and \
            rows["fusion.1"]["calls"] == 2
        assert rows["convolution.2"]["dur_us"] == 400
        assert rows["all-reduce.3"]["dur_us"] == 200
        # module-envelope lane and host lane must NOT be counted
        assert "jit_train_step" not in rows and "dispatch" not in rows
        assert an.total_device_us() == 1300

    def test_categories(self):
        an = self._an()
        cats = {r["name"]: r["category"] for r in an.op_totals()}
        assert cats == {"fusion.1": "fusion", "convolution.2": "compute",
                        "all-reduce.3": "collective", "copy.4": "copy"}

    def test_overlap_ratio(self):
        ov = self._an().overlap()
        # all-reduce [450,550) overlaps convolution [300,500) by 50us/step
        assert ov["collective_us"] == 200
        assert ov["overlapped_us"] == 100
        assert abs(ov["ratio"] - 0.5) < 1e-9

    def test_steady_window_trims_edges(self):
        # first 40% of the 0..1650us span keeps only step-0's four ops
        an = self._an(window=(0.0, 0.4))
        assert all(r["calls"] == 1 for r in an.op_totals())

    def test_views_render(self):
        an = self._an(steps=2)
        kv = an.kernel_view()
        assert "fusion.1" in kv and "ms/step" in kv
        dv = an.device_view()
        assert "/device:TPU:0" in dv and "category split" in dv
        xv = an.distributed_view()
        assert "all-reduce.3" in xv and "overlap ratio 0.50" in xv

    def test_profiler_summary_views_from_capture(self):
        # acceptance surface: summary(views=[KernelView]) renders the
        # per-op device-time table parsed from a real capture
        p = profiler.Profiler(trace_dir=FIXTURES, timer_only=True)
        out = p.summary(views=[SummaryView.KernelView,
                               SummaryView.DistributedView], steps=2)
        assert "fusion.1" in out and "0.300" in out
        assert "overlap ratio" in out

    def test_missing_capture_reports_not_crashes(self, tmp_path):
        p = profiler.Profiler(trace_dir=str(tmp_path), timer_only=True)
        out = p.summary(views=[SummaryView.KernelView])
        assert "no device trace" in out


class TestStepMonitor:
    def test_mfu_and_throughput_math(self):
        mon = StepMonitor(flops_per_step=2e9, peak_flops=1e12,
                          items_per_step=8, track_memory=False)
        for _ in range(3):
            mon.end_step(wall_s=0.004)
        r = mon.report()
        assert r["steps"] == 3
        assert abs(r["step_ms"] - 4.0) < 1e-6
        assert abs(r["mfu"] - 0.5) < 1e-6          # 2e9 / 0.004 / 1e12
        assert abs(r["items_per_s"] - 2000.0) < 1e-6

    def test_recompile_detector_shape_delta(self):
        mon = StepMonitor(track_memory=False)
        sig_a = (((4, 8), "float32"),)
        sig_b = (((6, 8), "float32"),)
        mon.record_compile("train_step", sig_a)
        mon.end_step(wall_s=0.01)
        mon.record_compile("train_step", sig_b, prev_sig=sig_a)
        mon.end_step(wall_s=0.01)
        assert mon.compiles == 2 and mon.recompiles == 1
        ev = mon.recompile_events[0]
        assert "(4, 8)" in ev["delta"] and "(6, 8)" in ev["delta"]

    def test_compile_steps_excluded_from_steady_median(self):
        mon = StepMonitor(track_memory=False)
        mon.record_compile("train_step", ("sig",))
        mon.end_step(wall_s=5.0)          # compile step: huge wall
        for _ in range(3):
            mon.end_step(wall_s=0.01)
        assert abs(mon.report()["step_ms"] - 10.0) < 1e-6

    def test_train_step_integration(self, tmp_path):
        from paddle_tpu.jit.train_step import TrainStep
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 4))
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        ce = nn.CrossEntropyLoss()
        jsonl = str(tmp_path / "mon.jsonl")
        mon = StepMonitor(items_per_step=4, jsonl_path=jsonl)
        step = TrainStep(m, opt, lambda x, y: ce(m(x), y), monitor=mon)
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        y = paddle.to_tensor(np.random.randint(0, 4, (4,)).astype("int64"))
        step(x, y)
        step(x, y)
        # batch 4 -> 6: the detector must flag a recompile with the delta
        x2 = paddle.to_tensor(np.random.randn(6, 8).astype(np.float32))
        y2 = paddle.to_tensor(np.random.randint(0, 4, (6,)).astype("int64"))
        step(x2, y2)
        r = mon.report()
        assert r["steps"] == 3
        assert r["compiles"] == 2 and r["recompiles"] == 1
        assert "(4, 8)" in mon.recompile_events[0]["delta"]
        assert r["hbm_peak_bytes"] and r["hbm_peak_bytes"] > 0
        rows = [json.loads(l) for l in open(jsonl)]
        assert len(rows) == 3 and rows[2]["compiled"] is True

    def test_run_steps_records_step_count(self):
        from paddle_tpu.jit.train_step import TrainStep
        paddle.seed(0)
        m = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        mon = StepMonitor(track_memory=False)
        step = TrainStep(m, opt, lambda x, y: ((m(x) - y) ** 2).mean(),
                         monitor=mon)
        xs = paddle.to_tensor(np.random.randn(3, 2, 4).astype(np.float32))
        step.run_steps(3, xs, xs)
        assert mon.report()["steps"] == 3
        assert mon.records[0]["steps"] == 3

    def test_on_report_hook_and_metrics_text(self):
        seen = []
        mon = StepMonitor(items_per_step=2, unit="tokens/s",
                          on_report=seen.append, track_memory=False)
        with mon.step():
            pass
        assert len(seen) == 1 and seen[0]["step"] == 1
        text = mon.metrics_text()
        assert "paddle_tpu_steps_total 1" in text
        assert "# TYPE paddle_tpu_throughput gauge" in text

    def test_profiler_callback_drives_monitor(self):
        from paddle_tpu.hapi.callbacks import ProfilerCallback
        mon = StepMonitor(track_memory=False)
        cb = ProfilerCallback(monitor=mon, summary=False)
        cb.on_train_begin()
        for i in range(2):
            cb.on_train_batch_begin(i)
            cb.on_train_batch_end(i)
        cb.on_train_end()
        assert mon.report()["steps"] == 2


class TestAnnotateLayers:
    class _Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.trunk = nn.Sequential(nn.Linear(8, 8), nn.Tanh())
            self.head = nn.Linear(8, 2)

        def forward(self, x):
            return self.head(self.trunk(x))

    def test_qualified_paths_and_parity(self):
        paddle.seed(0)
        m = self._Net()
        x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
        want = m(x).numpy()
        h = profiler.annotate_layers(m)
        assert set(h.paths) == {"_Net", "_Net/trunk", "_Net/trunk/0",
                                "_Net/trunk/1", "_Net/head"}
        np.testing.assert_allclose(m(x).numpy(), want)  # behavior unchanged
        h.remove()
        np.testing.assert_allclose(m(x).numpy(), want)
        assert "forward" not in m.__dict__  # original forward restored

    def test_root_override_and_idempotence(self):
        m = self._Net()
        h1 = profiler.annotate_layers(m, root="gpt")
        assert "gpt/head" in h1.paths
        h2 = profiler.annotate_layers(m, root="gpt")
        assert h2.paths == []           # already annotated: no double wrap
        h1.remove()


class TestDeviceMemoryStats:
    def test_stats_shape_and_peak_monotonic(self):
        s = device.memory_stats()
        assert s["bytes_in_use"] >= 0
        assert device.max_memory_allocated() >= s["bytes_in_use"]

    def test_live_allocation_visible(self):
        before = device.memory_allocated()
        t = paddle.to_tensor(np.zeros((512, 512), np.float32))  # 1 MiB
        after = device.memory_allocated()
        assert after - before >= 512 * 512 * 4
        assert device.max_memory_allocated() >= after
        del t

    def test_chip_peak_flops_known_kinds(self):
        class _D:
            device_kind = "TPU v5e"
        assert device.chip_peak_flops(_D()) == 197e12
        _D.device_kind = "weird accelerator"
        assert device.chip_peak_flops(_D()) == 275e12
