"""Window-batched fused-MHA-with-bias kernel parity (interpret mode, CPU).

Covers the masked-attention capability of the reference's fused attention
(fused_attention_op.cu + fused_softmax_mask.cu): additive per-head bias with
batch periodicity, forward/backward parity vs the XLA reference including
d(bias) (the learned rel-pos-bias gradient path), and the Swin window
grouping equivalence (block-diag bias == per-window attention).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.fused_mha import mha_reference_packed
from paddle_tpu.ops.pallas.fused_mha_bias import fused_mha_bias


def _ref_bias(qkv, nh, bias):
    """XLA reference: softmax(q·kᵀ·scale + bias[p % R]) · v, packed."""
    b, s, f3 = qkv.shape
    hd = f3 // 3 // nh
    a = qkv.reshape(b, s, 3, nh, hd)
    q, k, v = a[:, :, 0], a[:, :, 1], a[:, :, 2]
    scale = hd ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    r_n = bias.shape[0]
    reps = b // r_n
    full = jnp.tile(bias, (reps, 1, 1, 1))
    logits = logits + full
    p = jax.nn.softmax(logits, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o.reshape(b, s, nh * hd)


def _rand(b, s, nh, hd, r_n, seed=0):
    rng = np.random.RandomState(seed)
    qkv = jnp.asarray(rng.randn(b, s, 3 * nh * hd).astype(np.float32)) * 0.3
    bias = jnp.asarray(rng.randn(r_n, nh, s, s).astype(np.float32)) * 0.5
    return qkv, bias


@pytest.mark.parametrize("nh,hd,r_n", [(4, 32, 2), (3, 32, 1), (2, 64, 4)])
def test_fwd_matches_reference(nh, hd, r_n):
    qkv, bias = _rand(4, 96, nh, hd, r_n)
    g = nh if (nh * hd) % 128 else None
    out = fused_mha_bias(qkv, nh, bias, heads_per_program=g, interpret=True)
    want = _ref_bias(qkv, nh, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("nh,hd,r_n", [(4, 32, 2), (3, 32, 1)])
def test_grads_match_reference(nh, hd, r_n):
    qkv, bias = _rand(4, 64, nh, hd, r_n, seed=1)
    g = nh if (nh * hd) % 128 else None

    def f_kernel(a, bb):
        return jnp.sum(fused_mha_bias(a, nh, bb, heads_per_program=g,
                                      interpret=True) ** 2)

    def f_ref(a, bb):
        return jnp.sum(_ref_bias(a, nh, bb) ** 2)

    gk = jax.grad(f_kernel, argnums=(0, 1))(qkv, bias)
    gr = jax.grad(f_ref, argnums=(0, 1))(qkv, bias)
    np.testing.assert_allclose(np.asarray(gk[0]), np.asarray(gr[0]),
                               rtol=3e-4, atol=3e-4)
    np.testing.assert_allclose(np.asarray(gk[1]), np.asarray(gr[1]),
                               rtol=3e-4, atol=3e-4)


def test_block_diag_equals_per_window():
    """Grouping W_g windows with a block-diagonal -inf bias must equal
    running each window separately (the Swin routing invariant)."""
    nh, hd, n, wg = 2, 64, 49, 4
    rng = np.random.RandomState(3)
    qkv_w = jnp.asarray(rng.randn(8, n, 3 * nh * hd).astype(np.float32)) * 0.3
    # per-window reference (no bias)
    want = mha_reference_packed(qkv_w, nh)
    # grouped: [8, 49, F3] -> [2, 196, F3] with block-diag zero-bias
    s = wg * n
    static = np.full((1, 1, s, s), -1e9, np.float32)
    for w in range(wg):
        static[0, 0, w * n:(w + 1) * n, w * n:(w + 1) * n] = 0.0
    bias = jnp.asarray(np.broadcast_to(static, (1, nh, s, s)).copy())
    grouped = qkv_w.reshape(2, s, 3 * nh * hd)
    out = fused_mha_bias(grouped, nh, bias, interpret=True)
    np.testing.assert_allclose(np.asarray(out.reshape(8, n, nh * hd)),
                               np.asarray(want), rtol=2e-4, atol=2e-4)


def test_swin_block_routed_parity_whole_map_window():
    """nW == 1 branch: the window covers the whole map, so the fused path
    groups IMAGES into one sequence — cross-image attention must stay
    blocked by the block-diagonal bias."""
    import os
    from paddle_tpu.vision.models.swin import SwinBlock
    import paddle_tpu as paddle

    paddle.seed(0)
    # 4x4 map with ws=4 -> nW=1 (stage-4 shape class); batch of 4 groups
    blk = SwinBlock(dim=32, input_resolution=(4, 4), num_heads=2,
                    window_size=4)
    x = paddle.to_tensor(np.random.RandomState(9)
                         .randn(4, 16, 32).astype(np.float32))
    os.environ["PADDLE_TPU_FUSED_MHA_BIAS"] = "0"
    try:
        want = blk(x)
    finally:
        del os.environ["PADDLE_TPU_FUSED_MHA_BIAS"]
    from paddle_tpu.ops.pallas import fused_mha_bias as mod
    orig_gate, orig_fn = mod.use_fused_mha_bias, mod.fused_mha_bias
    mod.use_fused_mha_bias = lambda *a, **k: True
    mod.fused_mha_bias = lambda *a, **k: orig_fn(*a, **{**k,
                                                        "interpret": True})
    try:
        blk.attn._bias_static_cache = None   # replan under forced gate
        got = blk(x)
    finally:
        mod.use_fused_mha_bias = orig_gate
        mod.fused_mha_bias = orig_fn
    np.testing.assert_allclose(got.numpy(), want.numpy(),
                               rtol=3e-4, atol=3e-4)


def test_swin_block_routed_parity():
    """SwinBlock forward+grad parity: fused-bias path vs XLA path."""
    import os
    from paddle_tpu.vision.models.swin import SwinBlock
    import paddle_tpu as paddle

    paddle.seed(0)
    blk = SwinBlock(dim=32, input_resolution=(8, 8), num_heads=2,
                    window_size=4, shift_size=2)
    x = paddle.to_tensor(np.random.RandomState(5)
                         .randn(4, 64, 32).astype(np.float32))
    os.environ["PADDLE_TPU_FUSED_MHA_BIAS"] = "0"
    try:
        want = blk(x)
        want.sum().backward()
        g_want = {n: np.array(p.grad.numpy())
                  for n, p in blk.named_parameters() if p.grad is not None}
        blk.clear_gradients()
    finally:
        del os.environ["PADDLE_TPU_FUSED_MHA_BIAS"]

    # force-enable and run the kernel in interpret mode via monkeypatch
    from paddle_tpu.ops.pallas import fused_mha_bias as mod
    orig_gate, orig_fn = mod.use_fused_mha_bias, mod.fused_mha_bias
    mod.use_fused_mha_bias = lambda *a, **k: True
    mod.fused_mha_bias = lambda *a, **k: orig_fn(*a, **{**k,
                                                        "interpret": True})
    try:
        got = blk(x)
        got.sum().backward()
        g_got = {n: np.array(p.grad.numpy())
                 for n, p in blk.named_parameters() if p.grad is not None}
    finally:
        mod.use_fused_mha_bias = orig_gate
        mod.fused_mha_bias = orig_fn
    np.testing.assert_allclose(got.numpy(), want.numpy(),
                               rtol=3e-4, atol=3e-4)
    assert set(g_got) == set(g_want)
    for name in g_want:
        np.testing.assert_allclose(g_got[name], g_want[name],
                                   rtol=3e-3, atol=3e-3,
                                   err_msg=name)
