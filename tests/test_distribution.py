"""paddle.distribution tests: moments, log_prob vs scipy-free closed forms,
sampling statistics, KL registry, transforms."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def _np(t):
    return np.asarray(t._data)


class TestNormal:
    def test_moments_logprob(self):
        d = D.Normal(1.0, 2.0)
        assert float(_np(d.mean)) == pytest.approx(1.0)
        assert float(_np(d.variance)) == pytest.approx(4.0)
        # N(1,2) logpdf at 1.0 = -log(2*sqrt(2pi))
        assert float(_np(d.log_prob(1.0))) == pytest.approx(
            -np.log(2 * np.sqrt(2 * np.pi)), rel=1e-5)
        assert float(_np(d.entropy())) == pytest.approx(
            0.5 + 0.5 * np.log(2 * np.pi) + np.log(2.0), rel=1e-5)

    def test_sample_stats(self):
        paddle.seed(0)
        d = D.Normal(3.0, 0.5)
        s = _np(d.sample((20000,)))
        assert s.mean() == pytest.approx(3.0, abs=0.02)
        assert s.std() == pytest.approx(0.5, abs=0.02)

    def test_cdf_icdf_roundtrip(self):
        d = D.Normal(0.0, 1.0)
        x = np.linspace(-2, 2, 9, dtype=np.float32)
        p = _np(d.cdf(paddle.to_tensor(x)))
        back = _np(d.icdf(paddle.to_tensor(p)))
        np.testing.assert_allclose(back, x, atol=1e-4)

    def test_kl_closed_form(self):
        p = D.Normal(0.0, 1.0)
        q = D.Normal(1.0, 2.0)
        kl = float(_np(D.kl_divergence(p, q)))
        expect = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
        assert kl == pytest.approx(expect, rel=1e-5)


class TestUniform:
    def test_basic(self):
        d = D.Uniform(2.0, 4.0)
        assert float(_np(d.mean)) == pytest.approx(3.0)
        assert float(_np(d.log_prob(3.0))) == pytest.approx(-np.log(2.0))
        assert float(_np(d.log_prob(5.0))) == -np.inf
        assert float(_np(d.entropy())) == pytest.approx(np.log(2.0))
        paddle.seed(1)
        s = _np(d.sample((5000,)))
        assert s.min() >= 2.0 and s.max() < 4.0


class TestCategorical:
    def test_logits_probs(self):
        d = D.Categorical(probs=[0.2, 0.3, 0.5])
        np.testing.assert_allclose(_np(d.probs), [0.2, 0.3, 0.5], atol=1e-6)
        assert float(_np(d.log_prob(2))) == pytest.approx(np.log(0.5), rel=1e-5)
        ent = -sum(p * np.log(p) for p in [0.2, 0.3, 0.5])
        assert float(_np(d.entropy())) == pytest.approx(ent, rel=1e-5)

    def test_sample_distribution(self):
        paddle.seed(0)
        d = D.Categorical(probs=[0.1, 0.9])
        s = _np(d.sample((5000,)))
        assert (s == 1).mean() == pytest.approx(0.9, abs=0.03)

    def test_kl(self):
        p = D.Categorical(probs=[0.5, 0.5])
        q = D.Categorical(probs=[0.9, 0.1])
        kl = float(_np(D.kl_divergence(p, q)))
        expect = 0.5 * np.log(0.5 / 0.9) + 0.5 * np.log(0.5 / 0.1)
        assert kl == pytest.approx(expect, rel=1e-4)


class TestBernoulli:
    def test_basic(self):
        d = D.Bernoulli(probs=0.7)
        assert float(_np(d.mean)) == pytest.approx(0.7, rel=1e-5)
        assert float(_np(d.variance)) == pytest.approx(0.21, rel=1e-4)
        assert float(_np(d.log_prob(1.0))) == pytest.approx(np.log(0.7), rel=1e-4)


class TestBetaGammaDirichlet:
    def test_beta(self):
        d = D.Beta(2.0, 3.0)
        assert float(_np(d.mean)) == pytest.approx(0.4, rel=1e-5)
        # Beta(2,3) pdf at 0.5: x(1-x)^2/B(2,3), B(2,3)=1/12
        expect = np.log(0.5 * 0.25 * 12)
        assert float(_np(d.log_prob(0.5))) == pytest.approx(expect, rel=1e-4)
        paddle.seed(0)
        s = _np(d.sample((8000,)))
        assert s.mean() == pytest.approx(0.4, abs=0.02)

    def test_gamma(self):
        d = D.Gamma(3.0, 2.0)
        assert float(_np(d.mean)) == pytest.approx(1.5)
        paddle.seed(0)
        s = _np(d.sample((8000,)))
        assert s.mean() == pytest.approx(1.5, abs=0.05)

    def test_dirichlet(self):
        d = D.Dirichlet(paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32)))
        np.testing.assert_allclose(_np(d.mean), [1 / 6, 2 / 6, 3 / 6], atol=1e-6)
        paddle.seed(0)
        s = _np(d.sample((4000,)))
        np.testing.assert_allclose(s.sum(-1), 1.0, atol=1e-5)
        np.testing.assert_allclose(s.mean(0), [1 / 6, 2 / 6, 3 / 6], atol=0.02)

    def test_multinomial(self):
        d = D.Multinomial(10, paddle.to_tensor(np.array([0.3, 0.7], np.float32)))
        paddle.seed(0)
        s = _np(d.sample((500,)))
        assert s.shape == (500, 2)
        np.testing.assert_allclose(s.sum(-1), 10.0)
        assert s[:, 1].mean() == pytest.approx(7.0, abs=0.3)


class TestExpFamilies:
    def test_exponential(self):
        d = D.Exponential(2.0)
        assert float(_np(d.mean)) == pytest.approx(0.5)
        assert float(_np(d.log_prob(1.0))) == pytest.approx(np.log(2) - 2, rel=1e-5)

    def test_laplace(self):
        d = D.Laplace(0.0, 1.0)
        assert float(_np(d.log_prob(0.0))) == pytest.approx(-np.log(2), rel=1e-5)
        x = np.linspace(-2, 2, 7, dtype=np.float32)
        p = _np(d.cdf(paddle.to_tensor(x)))
        back = _np(d.icdf(paddle.to_tensor(p)))
        np.testing.assert_allclose(back, x, atol=1e-4)

    def test_gumbel(self):
        d = D.Gumbel(0.0, 1.0)
        assert float(_np(d.mean)) == pytest.approx(0.5772, abs=1e-3)
        paddle.seed(0)
        s = _np(d.sample((20000,)))
        assert s.mean() == pytest.approx(0.5772, abs=0.03)

    def test_kl_exponential(self):
        p, q = D.Exponential(1.0), D.Exponential(2.0)
        kl = float(_np(D.kl_divergence(p, q)))
        assert kl == pytest.approx(np.log(1 / 2) + 2 / 1 - 1, rel=1e-5)


class TestGradients:
    """Distribution math must be differentiable w.r.t. parameters —
    the VAE / policy-gradient contract the reference provides by building
    on paddle ops."""

    def test_kl_grad_wrt_loc(self):
        mu = paddle.to_tensor(np.array([0.5, -0.5], np.float32))
        mu.stop_gradient = False
        p = D.Normal(mu, 1.0)
        q = D.Normal(0.0, 1.0)
        kl = D.kl_divergence(p, q).sum()
        kl.backward()
        # d/dmu [mu^2/2] = mu
        np.testing.assert_allclose(_np(mu.grad), [0.5, -0.5], atol=1e-5)

    def test_rsample_reparameterized(self):
        paddle.seed(0)
        mu = paddle.to_tensor(np.zeros(3, np.float32))
        mu.stop_gradient = False
        d = D.Normal(mu, 1.0)
        s = d.rsample((5,)).sum()
        s.backward()
        # dsum/dmu = 5 per element (broadcast over sample dim)
        np.testing.assert_allclose(_np(mu.grad), 5.0, atol=1e-5)

    def test_log_prob_grad_categorical(self):
        logits = paddle.to_tensor(np.zeros(3, np.float32))
        logits.stop_gradient = False
        d = D.Categorical(logits=logits)
        lp = d.log_prob(1)
        lp.backward()
        g = _np(logits.grad)
        # grad of log softmax at uniform: onehot - 1/3
        np.testing.assert_allclose(g, [-1 / 3, 2 / 3, -1 / 3], atol=1e-5)

    def test_entropy_grad_flows(self):
        scale = paddle.to_tensor(np.array(2.0, np.float32))
        scale.stop_gradient = False
        d = D.Normal(0.0, scale)
        e = d.entropy()
        e.backward()
        np.testing.assert_allclose(_np(scale.grad), 0.5, atol=1e-6)


class TestTransforms:
    def test_affine_roundtrip(self):
        t = D.AffineTransform(1.0, 3.0)
        x = paddle.to_tensor(np.array([0.5, -2.0], np.float32))
        y = t.forward(x)
        np.testing.assert_allclose(_np(t.inverse(y)), _np(x), atol=1e-6)
        np.testing.assert_allclose(_np(t.forward_log_det_jacobian(x)),
                                   np.log(3.0), atol=1e-6)

    def test_exp_sigmoid_tanh(self):
        for t in [D.ExpTransform(), D.SigmoidTransform(), D.TanhTransform()]:
            x = paddle.to_tensor(np.array([0.1, -0.3, 0.7], np.float32))
            y = t.forward(x)
            np.testing.assert_allclose(_np(t.inverse(y)), _np(x), atol=1e-5)

    def test_stickbreaking_simplex(self):
        t = D.StickBreakingTransform()
        x = paddle.to_tensor(np.array([0.2, -0.5, 1.0], np.float32))
        y = _np(t.forward(x))
        assert y.shape == (4,)
        assert y.sum() == pytest.approx(1.0, rel=1e-5)
        np.testing.assert_allclose(_np(t.inverse(paddle.to_tensor(y))), _np(x),
                                   atol=1e-4)

    def test_stickbreaking_log_det_numeric(self):
        import jax
        t = D.StickBreakingTransform()
        x = np.array([0.2, -0.5, 1.0], np.float32)
        # numeric log|det J| of the K-1 x K-1 square part via jacfwd on the
        # first K-1 outputs
        jac = jax.jacfwd(lambda v: t._forward(v)[:-1])(x)
        expect = np.linalg.slogdet(np.asarray(jac))[1]
        got = float(_np(t.forward_log_det_jacobian(paddle.to_tensor(x))))
        assert got == pytest.approx(float(expect), rel=1e-4)

    def test_chain_transform_param_grads(self):
        loc = paddle.to_tensor(np.array(1.0, np.float32))
        scale = paddle.to_tensor(np.array(2.0, np.float32))
        loc.stop_gradient = False
        scale.stop_gradient = False
        td = D.TransformedDistribution(
            D.Normal(0.0, 1.0), [D.AffineTransform(loc, scale)])
        lp = td.log_prob(paddle.to_tensor(np.array(1.5, np.float32)))
        lp.backward()
        assert loc.grad is not None and scale.grad is not None
        # d/dloc log p(y) = (y-loc)/scale^2 = 0.5/4
        np.testing.assert_allclose(_np(loc.grad), 0.125, atol=1e-5)

    def test_bernoulli_large_logits_finite(self):
        logits = paddle.to_tensor(np.array([25.0, -25.0], np.float32))
        logits.stop_gradient = False
        d = D.Bernoulli(logits=logits)
        lp = d.log_prob(paddle.to_tensor(np.array([0.0, 1.0], np.float32)))
        vals = _np(lp)
        assert np.isfinite(vals).all()
        lp.sum().backward()
        assert np.isfinite(_np(logits.grad)).all()

    def test_transformed_lognormal_matches(self):
        base = D.Normal(0.0, 1.0)
        td = D.TransformedDistribution(base, [D.ExpTransform()])
        ln = D.LogNormal(0.0, 1.0)
        for v in [0.5, 1.0, 2.5]:
            assert float(_np(td.log_prob(v))) == pytest.approx(
                float(_np(ln.log_prob(v))), rel=1e-4)
