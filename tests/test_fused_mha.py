"""Fused short-sequence MHA kernel parity (interpret mode on CPU).

Covers the fused_attention_op.cu capability class (QKV-packed attention +
softmax + probability dropout in one kernel): forward/backward parity vs the
XLA reference path, the ragged-length padding mask, head grouping, and the
in-kernel PRNG dropout (determinism + finite-difference gradient consistency,
since the Mosaic bitstream is not reproducible outside the kernel).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.fused_mha import (fused_mha, mha_reference_packed,
                                             _pick_group)


def _rand_qkv(b, s, nh, hd, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(b, s, 3 * nh * hd).astype(np.float32)) * 0.3


@pytest.mark.parametrize("s", [256, 197, 64])
def test_fused_mha_forward_matches_reference(s):
    qkv = _rand_qkv(2, s, 4, 64)
    out = fused_mha(qkv, 4, interpret=True)
    want = mha_reference_packed(qkv, 4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_fused_mha_causal_matches_reference():
    qkv = _rand_qkv(1, 128, 2, 64, seed=3)
    out = fused_mha(qkv, 2, causal=True, interpret=True)
    want = mha_reference_packed(qkv, 2, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_fused_mha_kv_len_matches_masked_reference():
    # explicit kv_len tighter than the shape: identical to a padding mask
    qkv = _rand_qkv(1, 256, 2, 64, seed=4)
    out = fused_mha(qkv, 2, kv_len=200, interpret=True)
    want = mha_reference_packed(qkv, 2, kv_len=200)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("s", [256, 197])
def test_fused_mha_grads_match_reference(s):
    qkv = _rand_qkv(1, s, 4, 64, seed=1)

    def f_kernel(a):
        return jnp.sum(fused_mha(a, 4, interpret=True) ** 2)

    def f_ref(a):
        return jnp.sum(mha_reference_packed(a, 4) ** 2)

    gk = jax.grad(f_kernel)(qkv)
    gr = jax.grad(f_ref)(qkv)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                               rtol=2e-3, atol=2e-3)


def test_fused_mha_head_grouping_invariant():
    qkv = _rand_qkv(1, 128, 4, 64, seed=2)
    full = fused_mha(qkv, 4, heads_per_program=4, interpret=True)
    split = fused_mha(qkv, 4, heads_per_program=2, interpret=True)
    np.testing.assert_allclose(np.asarray(full), np.asarray(split),
                               rtol=1e-5, atol=1e-5)

    def g(a, G):
        return jnp.sum(fused_mha(a, 4, heads_per_program=G,
                                 interpret=True) ** 2)

    gf = jax.grad(lambda a: g(a, 4))(qkv)
    gs = jax.grad(lambda a: g(a, 2))(qkv)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gs),
                               rtol=1e-5, atol=1e-5)


def test_pick_group_respects_budget_and_divisibility():
    # bert-base bwd shape: must split below 12 heads under 7 streams
    g_fwd = _pick_group(12, 64, 512, 2, n_bufs=4)
    g_bwd = _pick_group(12, 64, 512, 2, n_bufs=7)
    assert 12 % g_fwd == 0 and 12 % g_bwd == 0
    assert g_bwd <= g_fwd
    # tiny case always admits all heads
    assert _pick_group(4, 64, 128, 2, n_bufs=7) == 4


class TestDropout:
    """In-kernel PRNG dropout.

    The Mosaic PRNG has no CPU emulation (pltpu.InterpretParams stubs
    prng_random_bits to zeros), so the numeric dropout checks —
    per-seed determinism, inverted-dropout mean preservation, and
    finite-difference gradient consistency of the regenerated backward
    mask — live in tools/validate_fused_mha_tpu.py and run on hardware;
    their measured results are recorded in README's kernel section."""

    def test_zero_p_is_exact_noop(self):
        qkv = _rand_qkv(1, 128, 2, 64, seed=5)
        base = fused_mha(qkv, 2, interpret=True)
        zero = fused_mha(qkv, 2, dropout_p=0.0, interpret=True)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(zero))

    def test_dropout_requires_seed(self):
        qkv = _rand_qkv(1, 128, 2, 64)
        with pytest.raises(ValueError):
            fused_mha(qkv, 2, dropout_p=0.1)


def test_score_f32_env_override(monkeypatch):
    """PADDLE_TPU_SCORE_F32=1 reverts bf16 score storage to exact f32
    everywhere (advisor r3: give users a no-code-change convergence
    check for the models that hard-wire score_dtype=model dtype)."""
    from paddle_tpu.ops.attention import attention_reference
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(1, 16, 2, 8).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    exact = attention_reference(q, k, v)                     # f32 scores
    half = attention_reference(q, k, v, score_dtype=jnp.bfloat16)
    assert np.abs(np.asarray(exact) - np.asarray(half)).max() > 0
    monkeypatch.setenv("PADDLE_TPU_SCORE_F32", "1")
    forced = attention_reference(q, k, v, score_dtype=jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(forced), np.asarray(exact))


def test_fused_mha_per_row_lengths():
    """kv_len as a [B] array: per-row padding masks (right-padded batches)
    match the masked reference row by row, fwd and grads."""
    qkv = _rand_qkv(3, 64, 2, 64, seed=11)
    lens = jnp.asarray([64, 40, 17], jnp.int32)
    out = fused_mha(qkv, 2, kv_len=lens, interpret=True)
    for i, ln in enumerate([64, 40, 17]):
        want = mha_reference_packed(qkv[i:i + 1], 2, kv_len=ln)
        np.testing.assert_allclose(np.asarray(out[i:i + 1, :ln]),
                                   np.asarray(want[:, :ln]),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"row {i} len {ln}")

    def f(a):
        o = fused_mha(a, 2, kv_len=lens, interpret=True)
        # only valid query rows contribute (padded-row outputs are garbage
        # by contract — the model discards them)
        m = (jnp.arange(64)[None, :, None] < lens[:, None, None])
        return jnp.sum(jnp.where(m, o, 0.0) ** 2)

    def f_ref(a):
        tot = 0.0
        for i, ln in enumerate([64, 40, 17]):
            o = mha_reference_packed(a[i:i + 1], 2, kv_len=ln)
            tot = tot + jnp.sum(o[:, :ln] ** 2)
        return tot

    gk = jax.grad(f)(qkv)
    gr = jax.grad(f_ref)(qkv)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                               rtol=2e-3, atol=2e-3)
