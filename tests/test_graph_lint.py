"""Graph lint (ISSUE 6): the static-analysis suite that proves the
zero-recompile / zero-sync / donation invariants BEFORE the job runs.

Covers: each pass detects its planted violation (and names itself),
the transfer guard catches implicit host transfers under lax.scan and
grad-accum naming the LAYER, the recompile differ explains signature
deltas, the serving preflight/engine/TrainStep wiring, the structured
config-validation finding, the source lint, and — the acceptance pin —
the framework's own core executables (GPT prefill/decode static+paged,
TrainStep(gpt), a vision forward) are lint-clean modulo the documented
allowlist."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.analysis import (
    Allowlist, ConfigValidationError, Finding, Findings, GraphLint,
    GraphLintError, HostTransferError, abstract_signature,
    diff_signatures, explain_recompile, lint_capture, transfer_guard)

SDS = jax.ShapeDtypeStruct


# ------------------------------------------------------------ the passes

def test_dtype_promotion_detected_and_named():
    def up(x):
        return x.astype(jnp.float32) * 2.0

    fs = GraphLint().check(up, SDS((128, 256), jnp.bfloat16), name="up")
    hits = [f for f in fs if f.pass_name == "dtype_promotion"]
    assert hits, "planted bf16->f32 upcast not detected"
    assert hits[0].code == "bfloat16_to_float32"
    assert not hits[0].allowed
    assert "float32" in hits[0].message


def test_dtype_promotion_threshold_spares_small_tensors():
    def up(x):
        return x.astype(jnp.float32)

    fs = GraphLint(upcast_bytes=1 << 16).check(
        up, SDS((4, 4), jnp.bfloat16))
    assert not fs.for_pass("dtype_promotion")


def test_baked_const_detected():
    big = jnp.ones((512, 600), jnp.float32)   # 1.2 MB

    def f(x):
        return x + big

    fs = GraphLint().check(f, SDS((512, 600), jnp.float32), name="baked")
    hits = fs.for_pass("baked_const")
    assert hits and hits[0].code == "large_const"
    assert hits[0].data["bytes"] == 512 * 600 * 4


def test_donation_miss_detected():
    def f(a, b):
        return jnp.sum(a) + b     # donated `a` matches no output

    fs = GraphLint().check(f, SDS((512, 600), jnp.float32),
                           SDS((), jnp.float32), donate_argnums=(0,),
                           name="dm")
    hits = fs.for_pass("donation")
    assert hits and hits[0].code == "donated_unaliased"


def test_donation_honored_plus_candidate_advice():
    def f(a, b):
        return a + b

    fs = GraphLint().check(f, SDS((512, 600), jnp.float32),
                           SDS((512, 600), jnp.float32),
                           donate_argnums=(0,), name="ok")
    assert not [f_ for f_ in fs if f_.code == "donated_unaliased"]
    # b is large, not donated, and an output matches it exactly -> advice
    cand = [f_ for f_ in fs if f_.code == "donatable"]
    assert cand and cand[0].severity == "info"


def test_donation_alias_parse_survives_sharding_attrs():
    """mhlo.sharding attr values contain nested braces and sort BEFORE
    tf.aliasing_output in the lowered signature — the alias parse must
    not truncate there (else every sharded donation reads as a silent
    copy)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.analysis import parse_io_aliases
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("dp",))
    sh = NamedSharding(mesh, P())

    def f(a, b):
        return a + b

    jf = jax.jit(f, donate_argnums=(0,), in_shardings=(sh, sh))
    txt = jf.lower(SDS((8, 8), jnp.float32),
                   SDS((8, 8), jnp.float32)).as_text()
    assert "mhlo.sharding" in txt      # the hazard is actually present
    n, aliases = parse_io_aliases(txt)
    assert n == 2 and aliases == {0: 0}
    fs = GraphLint(donate_bytes=1).check(
        jf, SDS((8, 8), jnp.float32), SDS((8, 8), jnp.float32),
        name="sharded")
    assert not [f_ for f_ in fs if f_.code == "donated_unaliased"]


def test_host_transfer_callback_detected_inside_scan():
    def f(x):
        def body(c, _):
            y = jax.pure_callback(
                lambda v: np.asarray(v),
                SDS((), jnp.float32), c)
            return c + y, None
        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    fs = GraphLint().check(f, jnp.float32(1.0), name="cb")
    hits = fs.for_pass("host_transfer")
    assert hits and hits[0].code == "pure_callback"
    assert hits[0].severity == "error"


# ------------------------------------------------- transfer guard / hook

class _BadInner(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 4)

    def forward(self, x):
        y = self.fc(x)
        y.item()          # planted implicit host transfer
        return y


class _BadNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.inner = _BadInner()

    def forward(self, x):
        return self.inner(x)


def test_transfer_guard_names_layer_path():
    net = _BadNet()

    def fwd(x):
        return net(Tensor(x))._data

    with transfer_guard() as g:
        with pytest.raises(HostTransferError) as ei:
            jax.make_jaxpr(fwd)(SDS((2, 4), jnp.float32))
    assert "_BadNet/inner" in str(ei.value)
    assert "item" in str(ei.value)
    assert g.findings and g.findings[0].pass_name == "host_transfer"
    assert g.findings[0].code == "tracer_item"


def test_transfer_guard_inactive_on_concrete_tensors():
    t = paddle.to_tensor([3.5])
    with transfer_guard():
        assert t.item() == pytest.approx(3.5)     # eager reads stay legal
        assert float(t) == pytest.approx(3.5)


def test_graphlint_reports_planted_item_as_finding():
    net = _BadNet()

    def fwd(x):
        return net(Tensor(x))._data

    fs = GraphLint().check(fwd, SDS((2, 4), jnp.float32), name="bad")
    hits = fs.for_pass("host_transfer")
    assert hits and hits[0].code == "tracer_item"
    assert "_BadNet/inner" in hits[0].where


def test_transfer_guard_under_lax_scan_body():
    """r8's zero-sync claim is hardest to see inside scan bodies — the
    guard must catch a planted .item() there and still name the layer."""
    net = _BadNet()

    def scanned(x):
        def body(c, _):
            out = net(Tensor(c))._data
            return out, None
        y, _ = jax.lax.scan(body, x, None, length=2)
        return y

    fs = GraphLint().check(scanned, SDS((2, 4), jnp.float32),
                           name="scanned")
    hits = fs.for_pass("host_transfer")
    assert hits and "_BadNet/inner" in hits[0].where


# ---------------------------------------------------- recompile differ

def test_signature_diff_explains_each_delta():
    a = abstract_signature(np.zeros((4, 64), np.int64),
                           np.zeros((4,), np.int32))
    assert explain_recompile(a, a) == ""

    b = abstract_signature(np.zeros((4, 80), np.int64),
                           np.zeros((4,), np.int32))
    fs = diff_signatures(a, b, names=("ids", "lens"))
    assert len(fs) == 1 and fs[0].code == "shape"
    assert "ids" in fs[0].message and "[4, 80]" in fs[0].message

    c = abstract_signature(np.zeros((4, 64), np.float32),
                           np.zeros((4,), np.int32))
    assert diff_signatures(a, c)[0].code == "dtype"

    d = abstract_signature(np.zeros((4, 64), np.int64), "different")
    assert diff_signatures(a, d)[0].code == "structure"


def test_signature_weak_type_delta():
    strong = abstract_signature(SDS((), jnp.float32))
    weak = abstract_signature(SDS((), jnp.float32, weak_type=True))
    fs = diff_signatures(strong, weak)
    # same shape+dtype; only weak_type differs
    assert [f.code for f in fs] == ["weak_type"]


# ------------------------------------------------------- GraphLint modes

def test_guard_mode_raises_with_findings():
    def up(x):
        return x.astype(jnp.float32)

    with pytest.raises(GraphLintError) as ei:
        GraphLint(mode="error").check(up, SDS((128, 256), jnp.bfloat16),
                                      name="up")
    assert ei.value.findings
    assert "dtype_promotion" in str(ei.value)


def test_allowlist_marks_but_keeps_findings():
    def up(x):
        return x.astype(jnp.float32)

    lint = GraphLint(mode="error", allow=[
        {"pass": "dtype_promotion", "code": "*", "where": "",
         "reason": "test: deliberate accumulation"}])
    fs = lint.check(up, SDS((128, 256), jnp.bfloat16), name="up")
    assert len(fs) == 1 and fs[0].allowed
    assert fs[0].allow_reason == "test: deliberate accumulation"
    assert not fs.active("warn")


def test_findings_grouped_collapses_repeats():
    f1 = Finding("p", "c", "warn", "m", where="w", executable="e")
    f2 = Finding("p", "c", "warn", "m", where="w", executable="e")
    f3 = Finding("p", "other", "warn", "m2", where="w", executable="e")
    g = Findings([f1, f2, f3]).grouped()
    assert len(g) == 2
    assert g[0].data["count"] == 2 and g[0].message.startswith("[x2]")


# ------------------------------------------------------ model fixtures

def _tiny_gpt(dtype="bfloat16"):
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=64, param_dtype=dtype)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m, cfg


# low thresholds: the toy model must still exercise every pass — the
# deliberate sites arrive allowlisted with their documented reasons
_LINT = dict(upcast_bytes=256, const_bytes=2048, donate_bytes=2048)


# ------------------------------------- acceptance pin: core executables

def test_gpt_static_engine_lint_clean():
    """The padded engine's {prefill_static, decode_static} executables
    pass every pass (non-allowlisted findings = 0), audited through the
    engine's own lint= wiring on the warmup batch."""
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model, _ = _tiny_gpt()
    eng = ServingEngine(model, ServingConfig(
        max_batch=2, prompt_cap=8, max_new_tokens=4, decode_chunk=2,
        lint=GraphLint(**_LINT)))
    eng.submit(np.arange(1, 6))
    eng.submit(np.arange(2, 9))
    done = eng.drain()
    assert all(r.status == "done" for r in done)
    fs = eng.lint_findings
    assert fs is not None, "engine never audited its executables"
    active = fs.active("warn")
    assert not active, f"padded executables not lint-clean: " \
                       f"{[str(f) for f in active]}"
    # the audit must have SEEN the graphs: the documented bf16 exceptions
    # (attention softmax, layernorm moments, sampling head) show up
    # allowed — an empty report would mean the capture missed the calls
    assert any(f.allowed for f in fs)
    assert {f.pass_name for f in fs} >= {"dtype_promotion"}


def test_gpt_paged_engine_lint_clean_and_donation_aliased():
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model, _ = _tiny_gpt()
    eng = ServingEngine(model, ServingConfig(
        max_batch=2, prompt_cap=8, max_new_tokens=4, decode_chunk=2,
        paged=True, kv_block=4, lint=GraphLint(**_LINT)))
    eng.submit(np.arange(1, 6))
    eng.submit(np.arange(2, 9))
    done = eng.drain()
    assert all(r.status == "done" for r in done)
    fs = eng.lint_findings
    assert fs is not None
    active = fs.active("warn")
    assert not active, f"paged executables not lint-clean: " \
                       f"{[str(f) for f in active]}"
    # r10's donated pools must be ALIASED, not silently copied: the
    # donation pass ran over the paged pair and reported no misses
    assert not [f for f in fs if f.code == "donated_unaliased"]


def test_train_step_gpt_lint_clean():
    from paddle_tpu import optimizer as opt
    from paddle_tpu.jit.train_step import TrainStep
    model, cfg = _tiny_gpt()
    model.train()
    o = opt.AdamW(parameters=model.parameters(), learning_rate=1e-4)
    ts = TrainStep(model, o, lambda ids, lab: model.loss(ids, lab))
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8)))
    fs = ts.lint(ids, ids, lint=GraphLint(**_LINT))
    active = fs.active("warn")
    assert not active, f"TrainStep(gpt) not lint-clean: " \
                       f"{[str(f) for f in active]}"
    assert ts.lint_findings is fs


def test_vision_forward_lint_clean():
    from paddle_tpu.core import autograd
    from paddle_tpu.jit.api import _swap_params, _trace_guard
    from paddle_tpu.vision.models.small import LeNet
    paddle.seed(0)
    model = LeNet()
    model.eval()
    params = [p for _, p in model.named_parameters()]

    def fwd(pa, x):
        with _trace_guard(), _swap_params(params, list(pa)), \
                autograd.no_grad():
            return model(Tensor(x))._data

    fs = GraphLint(**_LINT).check(
        fwd, tuple(SDS(tuple(p._data.shape), p._data.dtype)
                   for p in params),
        SDS((2, 1, 28, 28), jnp.float32), name="lenet_forward")
    active = fs.active("warn")
    assert not active, f"vision forward not lint-clean: " \
                       f"{[str(f) for f in active]}"


# ----------------------------------------------- TrainStep lint wiring

class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


class _SyncMLP(_MLP):
    def forward(self, x):
        y = super().forward(x)
        y.numpy()        # planted per-step host sync
        return y


def _mk_step(model, **kw):
    from paddle_tpu import optimizer as opt
    from paddle_tpu.jit.train_step import TrainStep
    o = opt.AdamW(parameters=model.parameters(), learning_rate=1e-3)

    def loss_fn(x, y):
        return nn.functional.mse_loss(model(x), y)

    return TrainStep(model, o, loss_fn, **kw)


def test_train_step_lint_option_runs_before_first_compile():
    paddle.seed(0)
    ts = _mk_step(_MLP(), lint=True)
    x = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
    y = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
    assert ts.lint_findings is None
    ts(x, y)
    assert ts.lint_findings is not None
    assert not ts.lint_findings.active("warn")


def test_train_step_guard_mode_catches_planted_sync_pre_compile():
    paddle.seed(0)
    ts = _mk_step(_SyncMLP(), lint="error")
    x = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
    y = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
    with pytest.raises(GraphLintError) as ei:
        ts(x, y)
    assert "_SyncMLP" in str(ei.value)      # names the layer path
    assert "tracer_numpy" in str(ei.value)  # and the transfer kind


def test_transfer_guard_under_grad_accum_path():
    """The grad-accum microbatch scan is the other place the zero-sync
    claim is hard to eyeball: a planted sync inside the scanned
    fwd+bwd body is still caught, still naming the layer."""
    paddle.seed(0)
    ts = _mk_step(_SyncMLP(), grad_accum_steps=2)
    x = SDS((4, 8), jnp.float32)
    y = SDS((4, 4), jnp.float32)
    fs = ts.lint(x, y, lint=GraphLint(**_LINT))
    hits = fs.for_pass("host_transfer")
    assert hits and hits[0].code == "tracer_numpy"
    assert "_SyncMLP" in hits[0].where


def test_train_step_lint_is_abstract_no_param_updates():
    paddle.seed(0)
    model = _MLP()
    ts = _mk_step(model)
    before = model.fc1.weight.numpy().copy()
    ts.lint(SDS((4, 8), jnp.float32), SDS((4, 4), jnp.float32))
    np.testing.assert_array_equal(before, model.fc1.weight.numpy())


# ------------------------------------------------- serving integration

def test_serving_preflight_findings_and_reject_reason():
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model, _ = _tiny_gpt("float32")
    eng = ServingEngine(model, ServingConfig(
        max_batch=2, prompt_cap=8, max_new_tokens=4))
    # admissible -> empty findings
    assert not eng.preflight(np.arange(1, 5))
    # over-cap prompt -> recompile_hazard naming the shape delta
    pf = eng.preflight(np.arange(1, 20))
    assert len(pf) == 1 and pf[0].code == "prompt_shape"
    assert pf[0].pass_name == "recompile_hazard"
    assert "[2, 19]" in pf[0].message
    # the submit path carries the finding code as the refusal reason
    r = eng.submit(np.arange(1, 20))
    assert r.status == "rejected" and r.reason == "prompt_shape"
    r2 = eng.submit(np.arange(1, 5), max_new_tokens=0)
    assert r2.status == "rejected" and r2.reason == "max_new_tokens"


def test_serving_guard_mode_lint_raises_on_planted_hazard():
    """A guard-mode engine lint actually trips: plant a hazard by
    shrinking the upcast threshold to zero tolerance for the sampling
    head with an EMPTY allowlist. The findings are stored BEFORE the
    raise so a caller catching the error can still read them."""
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model, _ = _tiny_gpt()
    lint = GraphLint(mode="error", upcast_bytes=64,
                     allowlist=Allowlist([]))
    eng = ServingEngine(model, ServingConfig(
        max_batch=2, prompt_cap=8, max_new_tokens=3, lint=lint))
    eng.submit(np.arange(1, 5))
    with pytest.raises(GraphLintError):
        eng.drain()
    assert eng.lint_findings is not None and eng.lint_findings.active("warn")


def test_serving_lint_audits_late_built_executables():
    """Traffic that finishes at prefill (budget-1) must not latch the
    audit shut: a decode executable built on a LATER step still gets
    audited the first step it appears."""
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model, _ = _tiny_gpt()
    eng = ServingEngine(model, ServingConfig(
        max_batch=2, prompt_cap=8, max_new_tokens=4, decode_chunk=2,
        paged=True, kv_block=4, lint=GraphLint(**_LINT)))
    # budget-1 request: finishes inside _admit_paged, decode never runs
    eng.submit(np.arange(1, 5), max_new_tokens=1)
    eng.drain()
    audited = {k for _, k in eng._lint_seen}
    assert any(k.startswith("paged_prefill") for k in audited)
    assert not any(k.startswith("paged_decode") for k in audited)
    # a real request later: the decode executable compiles NOW and is
    # audited now
    eng.submit(np.arange(1, 6), max_new_tokens=4)
    eng.drain()
    audited = {k for _, k in eng._lint_seen}
    assert any(k.startswith("paged_decode") for k in audited)
    assert not eng.lint_findings.active("warn")


def test_paged_cache_dtype_config_finding():
    """ISSUE 6 satellite, updated by ISSUE 10: int8+paged now SERVES
    (the paged int8 pool landed); a cache dtype the paged engine still
    cannot hold keeps the structured config-validation finding (same
    schema as the lint), still a ValueError for existing callers, and
    says WHY + what to do."""
    from paddle_tpu.inference import ServingConfig
    cfg = ServingConfig(paged=True, cache_dtype="int8")
    assert cfg.cache_dtype == "int8"       # the ISSUE-10 mode
    with pytest.raises(ConfigValidationError) as ei:
        ServingConfig(paged=True, cache_dtype="float16")
    assert isinstance(ei.value, ValueError)
    f = ei.value.finding
    assert f.pass_name == "config"
    assert f.code == "paged_cache_dtype"
    assert "model dtype" in f.message.lower()
    assert "paged=False" in f.message      # the actionable way out
    assert f.data == {"cache_dtype": "float16", "paged": True}


def test_lint_capture_records_serving_executables():
    model, _ = _tiny_gpt("float32")
    with lint_capture() as calls:
        st = model.prefill_static(np.ones((1, 4), np.int64), max_len=8)
        model.decode_static(st, 2)
    kinds = [k[0] for k, _, _ in calls]
    assert "prefill" in kinds and "decode" in kinds
    fs = GraphLint(**_LINT).check_calls(calls)
    assert not fs.active("warn")


# ------------------------------------------------------- source lint

def test_source_lint_repo_clean():
    import tools.lint_source as ls
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert ls.run(root) == []


def test_source_lint_detects_and_allows(tmp_path):
    import tools.lint_source as ls
    bad = tmp_path / "paddle_tpu"
    bad.mkdir()
    (bad / "models").mkdir()
    src = (
        "import numpy as np\n"
        "def f(t, ok):\n"
        "    a = t.item()\n"
        "    b = float(t.sum())\n"
        "    c = np.asarray(t)\n"
        "    d = ok.item()  # lint: allow(tracer-item)\n"
        "    return a, b, c, d\n")
    (bad / "models" / "gpt.py").write_text(src)
    found = ls.lint_file("paddle_tpu/models/gpt.py", str(tmp_path))
    codes = sorted(f["code"] for f in found)
    assert codes == ["tracer-asarray", "tracer-float", "tracer-item"]
    assert all(f["pass"] == "source_lint" for f in found)


def test_check_tiers_lint_budget_line():
    import tools.check_tiers as ct
    recs = [{"nodeid": "a::b", "duration": 1.0, "markers": [],
             "outcome": "passed"}]
    ok = ct.check(recs, budget=780, slow_threshold=60,
                  lint_seconds=3.0, lint_budget=15.0)
    assert ok["ok"] and not ok["lint_over_budget"]
    bad = ct.check(recs, budget=780, slow_threshold=60,
                   lint_seconds=30.0, lint_budget=15.0)
    assert not bad["ok"] and bad["lint_over_budget"]
