"""PS sparse-embedding + RPC tests (SURVEY §2.2 parity)."""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.ps import (SparseTable, DistributedEmbedding,
                                       GeoSGDEmbedding, GraphTable)
from paddle_tpu.distributed import rpc


# ------------------------------------------------------------------ tables
def test_sparse_table_insert_on_pull():
    t = SparseTable(dim=4, optimizer="sgd", lr=0.1, init_scale=0.0)
    rows = t.pull(np.array([5, 9, 5]))
    assert rows.shape == (3, 4) and len(t) == 2
    np.testing.assert_allclose(rows, 0.0)  # init_scale 0 -> zero rows


def test_sparse_table_push_accumulates_duplicates():
    t = SparseTable(dim=2, optimizer="sgd", lr=1.0, init_scale=0.0)
    t.pull(np.array([1, 2]))
    t.push(np.array([1, 1, 2]), np.array([[1., 0.], [1., 0.], [0., 2.]]))
    rows = t.pull(np.array([1, 2]))
    np.testing.assert_allclose(rows, [[-2., 0.], [0., -2.]])


def test_sparse_table_adagrad_and_save_load(tmp_path):
    t = SparseTable(dim=3, optimizer="adagrad", lr=0.1)
    t.pull(np.array([7]))
    t.push(np.array([7]), np.ones((1, 3), np.float32))
    want = t.pull(np.array([7]))
    t.save(str(tmp_path / "shard0"))
    t2 = SparseTable(dim=3)
    t2.load(str(tmp_path / "shard0.npz"))
    np.testing.assert_allclose(t2.pull(np.array([7])), want)


def test_distributed_embedding_trains():
    paddle.seed(0)
    emb = DistributedEmbedding(dim=8, num_shards=4, optimizer="sgd", lr=0.5)
    dense = nn.Linear(8, 1)
    ids = paddle.to_tensor(np.array([[1, 2], [3, 4]], np.int64))
    tgt = paddle.to_tensor(np.array([[1.0], [-1.0]], np.float32))

    losses = []
    for _ in range(30):
        vec = emb(ids)                     # [2, 2, 8]
        pooled = vec.sum(axis=1)           # [2, 8]
        loss = ((dense(pooled) - tgt) ** 2).mean()
        loss.backward()
        # dense params train on-device; sparse rows updated by the push
        for p in dense.parameters():
            if p.grad is not None:
                p.set_value(p.numpy() - 0.1 * p.grad.numpy())
                p.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])
    assert emb.state_size() == 4  # ids 1..4 materialized


def test_distributed_embedding_save_load(tmp_path):
    emb = DistributedEmbedding(dim=4, num_shards=2)
    ids = paddle.to_tensor(np.array([10, 11], np.int64))
    want = emb(ids).numpy()
    emb.save(str(tmp_path / "emb"))
    emb2 = DistributedEmbedding(dim=4, num_shards=2, seed=999)
    emb2.load(str(tmp_path / "emb"))
    np.testing.assert_allclose(emb2(ids).numpy(), want)


# -------------------------------------------------------------------- rpc
def _add(a, b):
    return a + b


def _rpc_worker(rank, port, results):
    name = f"worker{rank}"
    rpc.init_rpc(name, rank=rank, world_size=2,
                 master_endpoint=f"127.0.0.1:{port}")
    if rank == 0:
        results["sync"] = rpc.rpc_sync("worker1", _add, args=(2, 3))
        fut = rpc.rpc_async("worker1", _add, args=(10, 20))
        results["async"] = fut.wait()
        infos = rpc.get_all_worker_infos()
        results["names"] = [w.name for w in infos]
    rpc.shutdown()


def test_geo_sgd_defers_global_updates_until_sync():
    """GeoSGD contract: local rows move every step, the GLOBAL table only
    moves at geo_step boundaries — and then by the accumulated delta."""
    paddle.seed(0)
    emb = GeoSGDEmbedding(dim=4, geo_step=3, lr=0.1)
    ids = np.array([5, 9], np.int64)
    base = emb._pull(ids).copy()          # creates rows, caches local=base
    global_before = emb.tables[0].pull(ids).copy()
    np.testing.assert_array_equal(base, global_before)

    g = np.ones((2, 4), np.float32)
    emb._push(ids, g)                     # step 1: local only
    emb._push(ids, g)                     # step 2: local only
    np.testing.assert_array_equal(emb.tables[0].pull(ids), global_before)
    local_mid = emb._pull(ids)
    assert np.allclose(local_mid, base - 0.2), "local SGD must advance"

    emb._push(ids, g)                     # step 3: triggers sync
    global_after = emb.tables[0].pull(ids)
    np.testing.assert_allclose(global_after, base - 0.3, atol=1e-6)
    # local re-based on fresh global
    np.testing.assert_allclose(emb._pull(ids), global_after, atol=1e-6)


def test_geo_sgd_merges_deltas_from_two_trainers():
    paddle.seed(0)
    shared = DistributedEmbedding(dim=2, optimizer="sgd", lr=1.0)
    t1 = GeoSGDEmbedding(dim=2, geo_step=100, lr=1.0)
    t2 = GeoSGDEmbedding(dim=2, geo_step=100, lr=1.0)
    t1.tables = t2.tables = shared.tables  # same global table
    ids = np.array([7], np.int64)
    t1._pull(ids), t2._pull(ids)
    base = shared.tables[0].pull(ids).copy()
    t1._push(ids, np.full((1, 2), 1.0, np.float32))
    t2._push(ids, np.full((1, 2), 2.0, np.float32))
    t1.sync()
    t2.sync()
    # both deltas land additively: base - 1 - 2
    np.testing.assert_allclose(shared.tables[0].pull(ids), base - 3.0,
                               atol=1e-6)


def test_geo_sgd_push_without_pull_and_save_load(tmp_path):
    emb = GeoSGDEmbedding(dim=2, geo_step=100, lr=1.0)
    ids = np.array([3], np.int64)
    emb._push(ids, np.ones((1, 2), np.float32))  # no prior pull: must work
    local = emb._pull(ids)
    # save must flush the unsynced local delta into the global table
    prefix = str(tmp_path / "geo")
    emb.save(prefix)
    np.testing.assert_allclose(emb.tables[0].pull(ids), local, atol=1e-6)
    # load must drop the stale cache
    emb2 = GeoSGDEmbedding(dim=2, geo_step=100, lr=1.0)
    emb2._pull(ids)  # populate a cache that load() must invalidate
    emb2.load(prefix)
    np.testing.assert_allclose(emb2._pull(ids), local, atol=1e-6)


class TestGraphTable:
    def _line_graph(self):
        g = GraphTable(seed=0)
        g.add_edges([0, 1, 2], [1, 2, 3])
        return g

    def test_sample_neighbors_and_degree(self):
        g = GraphTable(seed=0)
        g.add_edges([0, 0, 0, 1], [10, 11, 12, 20])
        assert list(g.degree([0, 1, 99])) == [3, 1, 0]
        n = g.sample_neighbors([0, 1, 99], sample_size=2)
        assert len(n[0]) == 2 and set(n[0]) <= {10, 11, 12}
        assert list(n[1]) == [20]
        assert len(n[2]) == 0

    def test_weighted_sampling_prefers_heavy_edges(self):
        g = GraphTable(seed=0)
        g.add_edges([0] * 3, [1, 2, 3], weight=[100.0, 1e-6, 1e-6])
        hits = 0
        for _ in range(50):
            (nb,) = g.sample_neighbors([0], sample_size=1)
            hits += int(nb[0] == 1)
        assert hits >= 45

    def test_zero_weight_edges_fall_back_to_uniform(self):
        g = GraphTable(seed=0)
        g.add_edges([0] * 5, [1, 2, 3, 4, 5], weight=[1.0, 0, 0, 0, 0])
        (nb,) = g.sample_neighbors([0], sample_size=3)
        assert len(nb) == 3 and len(set(nb)) == 3
        g2 = GraphTable(seed=0)
        g2.add_edges([0] * 4, [1, 2, 3, 4], weight=[0.0] * 4)
        (nb2,) = g2.sample_neighbors([0], sample_size=2)
        assert len(nb2) == 2

    def test_random_walk_follows_edges_and_stops_at_sink(self):
        g = self._line_graph()
        walks = g.random_walk([0], walk_len=5)
        assert walks.shape == (1, 6)
        np.testing.assert_array_equal(walks[0, :4], [0, 1, 2, 3])
        np.testing.assert_array_equal(walks[0, 4:], [3, 3])  # sink repeats

    def test_node_features_roundtrip(self):
        g = self._line_graph()
        g.set_node_feat([1, 2], np.array([[1, 2], [3, 4]], np.float32))
        out = g.get_node_feat([2, 1, 5])
        np.testing.assert_array_equal(out, [[3, 4], [1, 2], [0, 0]])


def test_rpc_sync_async_threads():
    import socket as sk
    with sk.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    results = {}
    # rank 0 hosts the master store; run both "processes" as threads (the
    # transport is identical; subprocess spin-up is covered by launch tests)
    t1 = threading.Thread(target=_rpc_worker, args=(0, port, results))
    t2 = threading.Thread(target=_rpc_worker, args=(1, port, results))
    t1.start(); t2.start()
    t1.join(60); t2.join(60)
    assert results["sync"] == 5
    assert results["async"] == 30
    assert results["names"] == ["worker0", "worker1"]


class TestPsRuntime:
    def test_remote_embedding_trains_against_ps_server(self):
        """PsServer in-process; DistributedEmbedding(endpoints=) pulls,
        pushes grads, and the REMOTE table's rows move."""
        from paddle_tpu.distributed.fleet.ps_runtime import PsServer
        srv = PsServer()
        srv.serve_in_thread()
        try:
            emb = DistributedEmbedding(dim=4, endpoints=[f"127.0.0.1:{srv.port}"],
                                       lr=0.5)
            ids = paddle.to_tensor(np.array([3, 9], np.int64))
            before = emb.tables[0].pull(np.array([3, 9]))
            out = emb(ids)
            loss = (out * out).sum()
            loss.backward()
            after = emb.tables[0].pull(np.array([3, 9]))
            assert not np.allclose(before, after)
            assert len(emb.tables[0]) == 2
        finally:
            srv.stop()

    def test_geo_sgd_over_remote_tables(self):
        from paddle_tpu.distributed.fleet.ps_runtime import (PsServer,
                                                             RemoteShard)
        srv = PsServer()
        srv.serve_in_thread()
        try:
            emb = GeoSGDEmbedding(dim=2, geo_step=2, lr=1.0)
            emb.tables = [RemoteShard(f"127.0.0.1:{srv.port}", "geo", 2,
                                      optimizer="sgd", lr=1.0)]
            emb.num_shards = 1
            ids = np.array([5], np.int64)
            emb._pull(ids)
            base = srv.tables["geo"].pull(np.array([5])).copy()
            emb._push(ids, np.ones((1, 2), np.float32))
            emb._push(ids, np.ones((1, 2), np.float32))  # triggers sync
            np.testing.assert_allclose(srv.tables["geo"].pull(np.array([5])),
                                       base - 2.0, atol=1e-6)
        finally:
            srv.stop()

    def test_launch_ps_mode_end_to_end(self, tmp_path):
        """Full job through the launch CLI ps controller: 2 servers + 2
        trainers; trainers train a remote embedding and worker 0 stops the
        servers (the reference ps-mode lifecycle)."""
        import os
        import subprocess, sys, textwrap
        script = tmp_path / "ps_job.py"
        script.write_text(textwrap.dedent("""
            import os
            import numpy as np
            import jax; jax.config.update("jax_platforms", "cpu")
            import paddle_tpu as paddle
            from paddle_tpu.distributed import fleet

            if fleet.is_server():
                fleet.init_server()
                fleet.run_server()
            else:
                fleet.init_worker()
                from paddle_tpu.distributed.ps import DistributedEmbedding
                emb = DistributedEmbedding(dim=4,
                    endpoints=fleet.server_endpoints(), lr=0.1)
                wid = int(os.environ["PADDLE_TRAINER_ID"])
                ids = paddle.to_tensor(np.arange(4, dtype=np.int64) + wid * 4)
                for _ in range(3):
                    out = emb(ids)
                    (out * out).sum().backward()
                sizes = [len(t) for t in emb.tables]
                # servers hold rows from BOTH trainers (4 own ids, up to 8
                # total depending on the peer's progress)
                assert 4 <= sum(sizes) <= 8, sizes
                fleet.barrier_worker()
                fleet.stop_worker()
                print("TRAINER", wid, "OK", sizes)
        """))
        env = dict(os.environ)
        env["PYTHONPATH"] = "/root/repo" + (
            ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--run_mode", "ps", "--server_num", "2", "--trainer_num", "2",
             "--start_port", "7301", "--log_dir", str(tmp_path / "logs"),
             str(script)],
            capture_output=True, text=True, timeout=240,
            cwd="/root/repo", env=env)
        logs = "\n".join((tmp_path / "logs" / f).read_text()
                         for f in os.listdir(tmp_path / "logs"))
        assert r.returncode == 0, (r.stdout, r.stderr, logs)
        assert logs.count("OK") == 2, logs
