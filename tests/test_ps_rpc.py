"""PS sparse-embedding + RPC tests (SURVEY §2.2 parity)."""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.ps import SparseTable, DistributedEmbedding
from paddle_tpu.distributed import rpc


# ------------------------------------------------------------------ tables
def test_sparse_table_insert_on_pull():
    t = SparseTable(dim=4, optimizer="sgd", lr=0.1, init_scale=0.0)
    rows = t.pull(np.array([5, 9, 5]))
    assert rows.shape == (3, 4) and len(t) == 2
    np.testing.assert_allclose(rows, 0.0)  # init_scale 0 -> zero rows


def test_sparse_table_push_accumulates_duplicates():
    t = SparseTable(dim=2, optimizer="sgd", lr=1.0, init_scale=0.0)
    t.pull(np.array([1, 2]))
    t.push(np.array([1, 1, 2]), np.array([[1., 0.], [1., 0.], [0., 2.]]))
    rows = t.pull(np.array([1, 2]))
    np.testing.assert_allclose(rows, [[-2., 0.], [0., -2.]])


def test_sparse_table_adagrad_and_save_load(tmp_path):
    t = SparseTable(dim=3, optimizer="adagrad", lr=0.1)
    t.pull(np.array([7]))
    t.push(np.array([7]), np.ones((1, 3), np.float32))
    want = t.pull(np.array([7]))
    t.save(str(tmp_path / "shard0"))
    t2 = SparseTable(dim=3)
    t2.load(str(tmp_path / "shard0.npz"))
    np.testing.assert_allclose(t2.pull(np.array([7])), want)


def test_distributed_embedding_trains():
    paddle.seed(0)
    emb = DistributedEmbedding(dim=8, num_shards=4, optimizer="sgd", lr=0.5)
    dense = nn.Linear(8, 1)
    ids = paddle.to_tensor(np.array([[1, 2], [3, 4]], np.int64))
    tgt = paddle.to_tensor(np.array([[1.0], [-1.0]], np.float32))

    losses = []
    for _ in range(30):
        vec = emb(ids)                     # [2, 2, 8]
        pooled = vec.sum(axis=1)           # [2, 8]
        loss = ((dense(pooled) - tgt) ** 2).mean()
        loss.backward()
        # dense params train on-device; sparse rows updated by the push
        for p in dense.parameters():
            if p.grad is not None:
                p.set_value(p.numpy() - 0.1 * p.grad.numpy())
                p.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])
    assert emb.state_size() == 4  # ids 1..4 materialized


def test_distributed_embedding_save_load(tmp_path):
    emb = DistributedEmbedding(dim=4, num_shards=2)
    ids = paddle.to_tensor(np.array([10, 11], np.int64))
    want = emb(ids).numpy()
    emb.save(str(tmp_path / "emb"))
    emb2 = DistributedEmbedding(dim=4, num_shards=2, seed=999)
    emb2.load(str(tmp_path / "emb"))
    np.testing.assert_allclose(emb2(ids).numpy(), want)


# -------------------------------------------------------------------- rpc
def _add(a, b):
    return a + b


def _rpc_worker(rank, port, results):
    name = f"worker{rank}"
    rpc.init_rpc(name, rank=rank, world_size=2,
                 master_endpoint=f"127.0.0.1:{port}")
    if rank == 0:
        results["sync"] = rpc.rpc_sync("worker1", _add, args=(2, 3))
        fut = rpc.rpc_async("worker1", _add, args=(10, 20))
        results["async"] = fut.wait()
        infos = rpc.get_all_worker_infos()
        results["names"] = [w.name for w in infos]
    rpc.shutdown()


def test_rpc_sync_async_threads():
    import socket as sk
    with sk.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    results = {}
    # rank 0 hosts the master store; run both "processes" as threads (the
    # transport is identical; subprocess spin-up is covered by launch tests)
    t1 = threading.Thread(target=_rpc_worker, args=(0, port, results))
    t2 = threading.Thread(target=_rpc_worker, args=(1, port, results))
    t1.start(); t2.start()
    t1.join(60); t2.join(60)
    assert results["sync"] == 5
    assert results["async"] == 30
    assert results["names"] == ["worker0", "worker1"]
