"""Op-parity sweep — table-driven OpTest over the public op surface.

Reference (SURVEY §4): the reference's main correctness net is the OpTest
harness run over its ~600-kernel surface (unittests/op_test.py:327
check_output, eager_op_test.py:2084 check_grad vs finite differences).
This file is the analog for the TPU build: every public op in
`paddle_tpu.core.ops` and `paddle_tpu.nn.functional` is either

  * SWEPT — a table entry below runs dual-executor output checks against a
    numpy (or torch-CPU oracle) reference, plus numeric-vs-analytic grad
    checks for differentiable ops, or
  * WAIVED — listed in `WAIVERS` with the reason (stochastic op, alias,
    python-side utility, or covered by a dedicated deeper test).

`test_every_op_accounted` enforces the partition, so a newly added op that
is neither swept nor waived fails the suite.

Shapes are deliberately tiny (<= 24 elements) to keep wall-time sane on the
1-core CI host; numeric grads cost 2*numel eager evals per input.
"""
from __future__ import annotations

import inspect

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core import ops as _ops
from op_test import OpTest


def _r(seed):
    return np.random.RandomState(seed)


def _f32(*shape, seed=0, lo=None, hi=None, positive=False, unit=False):
    a = _r(seed).randn(*shape).astype(np.float32)
    if positive:
        a = np.abs(a) + 0.5
    if unit:  # open interval (-1, 1)
        a = np.tanh(a) * 0.99
    if lo is not None:
        a = np.clip(a, lo, hi)
    return a


def _i64(*shape, seed=0, lo=0, hi=8):
    return _r(seed).randint(lo, hi, size=shape).astype(np.int64)


def case(name, op, inputs, ref, attrs=None, grad=(), rtol=1e-5, atol=1e-6,
         static=True, grad_rtol=1e-2, grad_atol=1e-3):
    return dict(name=name, op=op, inputs=inputs, ref=ref, attrs=attrs or {},
                grad=list(grad), rtol=rtol, atol=atol, static=static,
                grad_rtol=grad_rtol, grad_atol=grad_atol)


def _torch():
    import torch
    return torch


def _t(x):
    import torch
    return torch.from_numpy(np.asarray(x))


# ---------------------------------------------------------------------------
# unary elementwise (op(x)); entries: (name, ref, input kwargs, has_grad)
_X = dict(seed=0)
_UNARY = [
    ("abs", np.abs, dict(), True),
    ("acos", np.arccos, dict(unit=True), True),
    ("acosh", np.arccosh, dict(positive=True, lo=1.5, hi=4.0), True),
    ("asin", np.arcsin, dict(unit=True), True),
    ("asinh", np.arcsinh, dict(), True),
    ("atan", np.arctan, dict(), True),
    ("atanh", np.arctanh, dict(unit=True), True),
    ("ceil", np.ceil, dict(), False),
    ("cos", np.cos, dict(), True),
    ("cosh", np.cosh, dict(), True),
    ("digamma", lambda x: _torch().digamma(_t(x)).numpy(), dict(positive=True), True),
    ("erf", lambda x: _torch().erf(_t(x)).numpy(), dict(), True),
    ("erfinv", lambda x: _torch().erfinv(_t(x)).numpy(), dict(unit=True), True),
    ("exp", np.exp, dict(), True),
    ("expm1", np.expm1, dict(), True),
    ("floor", np.floor, dict(), False),
    ("frac", lambda x: x - np.trunc(x), dict(), True),
    ("lgamma", lambda x: _torch().lgamma(_t(x)).numpy(), dict(positive=True), True),
    ("log", np.log, dict(positive=True), True),
    ("log10", np.log10, dict(positive=True), True),
    ("log1p", np.log1p, dict(positive=True), True),
    ("log2", np.log2, dict(positive=True), True),
    ("logsigmoid", lambda x: -np.log1p(np.exp(-x)), dict(), True),
    ("neg", np.negative, dict(), True),
    ("reciprocal", np.reciprocal, dict(positive=True), True),
    ("relu", lambda x: np.maximum(x, 0), dict(), True),
    ("round", np.round, dict(), False),
    ("rsqrt", lambda x: 1 / np.sqrt(x), dict(positive=True), True),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x)), dict(), True),
    ("sign", np.sign, dict(), False),
    ("sgn", np.sign, dict(), False),
    ("sin", np.sin, dict(), True),
    ("sinh", np.sinh, dict(), True),
    ("sqrt", np.sqrt, dict(positive=True), True),
    ("square", np.square, dict(), True),
    ("tan", np.tan, dict(unit=True), True),
    ("tanh", np.tanh, dict(), True),
    ("trunc", np.trunc, dict(), False),
    ("isnan", np.isnan, dict(), False),
    ("isinf", np.isinf, dict(), False),
    ("isfinite", np.isfinite, dict(), False),
]

# binary elementwise (op(x, y)); (name, ref, x kwargs, y kwargs, has_grad)
_BINARY = [
    ("add", np.add, dict(seed=1), dict(seed=2), True),
    ("subtract", np.subtract, dict(seed=1), dict(seed=2), True),
    ("multiply", np.multiply, dict(seed=1), dict(seed=2), True),
    ("divide", np.divide, dict(seed=1), dict(seed=2, positive=True), True),
    ("pow", lambda x, y: np.power(x, y), dict(seed=1, positive=True), dict(seed=2), True),
    ("maximum", np.maximum, dict(seed=1), dict(seed=2), True),
    ("minimum", np.minimum, dict(seed=1), dict(seed=2), True),
    ("fmax", np.fmax, dict(seed=1), dict(seed=2), True),
    ("fmin", np.fmin, dict(seed=1), dict(seed=2), True),
    ("atan2", np.arctan2, dict(seed=1), dict(seed=2, positive=True), True),
    ("copysign", np.copysign, dict(seed=1), dict(seed=2), False),
    ("hypot", np.hypot, dict(seed=1), dict(seed=2), True),
    ("logaddexp", np.logaddexp, dict(seed=1), dict(seed=2), True),
    ("nextafter", np.nextafter, dict(seed=1), dict(seed=2), False),
    ("heaviside", np.heaviside, dict(seed=1), dict(seed=2, positive=True), False),
]

_COMPARE = ["equal", "not_equal", "greater_than", "greater_equal",
            "less_than", "less_equal"]
_CMP_REF = {"equal": np.equal, "not_equal": np.not_equal,
            "greater_than": np.greater, "greater_equal": np.greater_equal,
            "less_than": np.less, "less_equal": np.less_equal}

_LOGICAL = [("logical_and", np.logical_and), ("logical_or", np.logical_or),
            ("logical_xor", np.logical_xor)]

_BITWISE = [("bitwise_and", np.bitwise_and), ("bitwise_or", np.bitwise_or),
            ("bitwise_xor", np.bitwise_xor),
            ("bitwise_left_shift", np.left_shift),
            ("bitwise_right_shift", np.right_shift)]


def _build_cases():
    C = []
    for name, ref, kw, has_grad in _UNARY:
        C.append(case(name, getattr(paddle, name), {"x": _f32(3, 4, **kw)},
                      ref, grad=["x"] if has_grad else [], rtol=2e-5, atol=2e-5))
    for name, ref, kx, ky, has_grad in _BINARY:
        C.append(case(name, getattr(paddle, name),
                      {"x": _f32(3, 4, **kx), "y": _f32(3, 4, **ky)},
                      ref, grad=["x", "y"] if has_grad else [], rtol=2e-5, atol=2e-5))
    for name in _COMPARE:
        C.append(case(name, getattr(paddle, name),
                      {"x": _i64(3, 4, seed=3).astype(np.float32),
                       "y": _i64(3, 4, seed=4).astype(np.float32)},
                      _CMP_REF[name]))
    for name, ref in _LOGICAL:
        C.append(case(name, getattr(paddle, name),
                      {"x": _i64(3, 4, seed=5, hi=2).astype(bool),
                       "y": _i64(3, 4, seed=6, hi=2).astype(bool)}, ref))
    C.append(case("logical_not", paddle.logical_not,
                  {"x": _i64(3, 4, seed=5, hi=2).astype(bool)}, np.logical_not))
    for name, ref in _BITWISE:
        C.append(case(name, getattr(paddle, name),
                      {"x": _i64(3, 4, seed=7, hi=16), "y": _i64(3, 4, seed=8, hi=4)},
                      ref))
    C.append(case("bitwise_not", paddle.bitwise_not, {"x": _i64(3, 4, hi=16)},
                  np.invert))
    C += [
        case("floor_divide", paddle.floor_divide,
             {"x": _i64(3, 4, seed=9, lo=-8), "y": _i64(3, 4, seed=10, lo=1)},
             np.floor_divide),
        case("mod", paddle.mod, {"x": _i64(3, 4, seed=9, lo=-8),
                                 "y": _i64(3, 4, seed=10, lo=1)}, np.mod),
        case("remainder", paddle.remainder,
             {"x": _f32(3, 4, seed=1), "y": _f32(3, 4, seed=2, positive=True)},
             np.mod, rtol=1e-4, atol=1e-4),
        case("floor_mod", paddle.floor_mod,
             {"x": _i64(3, 4, seed=9, lo=-8), "y": _i64(3, 4, seed=10, lo=1)},
             np.mod),
        case("scale", paddle.scale, {"x": _f32(3, 4)},
             lambda x, scale, bias: x * scale + bias,
             attrs={"scale": 2.5, "bias": 0.5}, grad=["x"]),
        case("clip", paddle.clip, {"x": _f32(3, 4)},
             lambda x, min, max: np.clip(x, min, max),
             attrs={"min": -0.5, "max": 0.5}, grad=["x"]),
        case("lerp", paddle.lerp,
             {"x": _f32(3, 4, seed=1), "y": _f32(3, 4, seed=2)},
             lambda x, y, weight: x + 0.3 * (y - x), attrs={"weight": 0.3},
             grad=["x", "y"]),
        case("nan_to_num", paddle.nan_to_num,
             {"x": np.array([[np.nan, 1.0, np.inf, -np.inf]], np.float32)},
             lambda x: np.nan_to_num(x)),
        case("logit", paddle.logit, {"x": _f32(3, 4, seed=2, lo=0.1, hi=0.9)},
             lambda x: np.log(x / (1 - x)), grad=["x"], rtol=1e-4, atol=1e-4),
        case("stanh", paddle.stanh, {"x": _f32(3, 4)},
             lambda x, scale_a, scale_b: scale_b * np.tanh(scale_a * x),
             attrs={"scale_a": 0.67, "scale_b": 1.7159}, grad=["x"]),
        case("angle", paddle.angle, {"x": _f32(3, 4)}, np.angle),
        case("conj", paddle.conj, {"x": _f32(3, 4)}, np.conj, grad=["x"]),
        case("real", paddle.real, {"x": _f32(3, 4)}, np.real),
        case("imag", paddle.imag, {"x": _f32(3, 4)}, np.imag),
        case("deg2rad", paddle.deg2rad, {"x": _f32(3, 4)}, np.deg2rad, grad=["x"]),
        case("rad2deg", paddle.rad2deg, {"x": _f32(3, 4)}, np.rad2deg, grad=["x"]),
        case("gcd", paddle.gcd, {"x": _i64(3, 4, seed=1, lo=1, hi=30),
                                 "y": _i64(3, 4, seed=2, lo=1, hi=30)}, np.gcd),
        case("lcm", paddle.lcm, {"x": _i64(3, 4, seed=1, lo=1, hi=12),
                                 "y": _i64(3, 4, seed=2, lo=1, hi=12)}, np.lcm),
        case("increment", paddle.increment, {"x": np.array([1.5], np.float32)},
             lambda x, value: x + value, attrs={"value": 2.0}),
    ]
    # reductions
    C += [
        case("sum", paddle.sum, {"x": _f32(3, 4)},
             lambda x, axis: x.sum(axis), attrs={"axis": 1}, grad=["x"]),
        case("mean", paddle.mean, {"x": _f32(3, 4)},
             lambda x, axis: x.mean(axis), attrs={"axis": 0}, grad=["x"]),
        case("max", paddle.max, {"x": _f32(3, 4)},
             lambda x, axis: x.max(axis), attrs={"axis": 1}, grad=["x"]),
        case("min", paddle.min, {"x": _f32(3, 4)},
             lambda x, axis: x.min(axis), attrs={"axis": 1}, grad=["x"]),
        case("amax", paddle.amax, {"x": _f32(3, 4)},
             lambda x, axis: x.max(axis), attrs={"axis": 1}),
        case("amin", paddle.amin, {"x": _f32(3, 4)},
             lambda x, axis: x.min(axis), attrs={"axis": 1}),
        case("prod", paddle.prod, {"x": _f32(2, 3)},
             lambda x, axis: x.prod(axis), attrs={"axis": 1}, grad=["x"],
             rtol=1e-4, atol=1e-4),
        case("std", paddle.std, {"x": _f32(3, 4)},
             lambda x, axis: x.std(axis, ddof=1), attrs={"axis": 1},
             grad=["x"], rtol=1e-4, atol=1e-4),
        case("var", paddle.var, {"x": _f32(3, 4)},
             lambda x, axis: x.var(axis, ddof=1), attrs={"axis": 1}, grad=["x"]),
        case("median", paddle.median, {"x": _f32(1, 5)},
             lambda x, axis: np.median(x, axis), attrs={"axis": 1}),
        case("nanmedian", paddle.nanmedian,
             {"x": np.array([[1.0, np.nan, 3.0, 2.0, 5.0]], np.float32)},
             lambda x, axis: np.nanmedian(x, axis), attrs={"axis": 1}),
        case("nanmean", paddle.nanmean,
             {"x": np.array([[1.0, np.nan, 3.0]], np.float32)},
             lambda x, axis: np.nanmean(x, axis), attrs={"axis": 1}),
        case("nansum", paddle.nansum,
             {"x": np.array([[1.0, np.nan, 3.0]], np.float32)},
             lambda x, axis: np.nansum(x, axis), attrs={"axis": 1}),
        case("logsumexp", paddle.logsumexp, {"x": _f32(3, 4)},
             lambda x, axis: np.log(np.exp(x).sum(axis)), attrs={"axis": 1},
             grad=["x"], rtol=1e-4, atol=1e-4),
        case("all", paddle.all, {"x": _i64(3, 4, hi=2).astype(bool)},
             lambda x, axis: x.all(axis), attrs={"axis": 1}),
        case("any", paddle.any, {"x": _i64(3, 4, hi=2).astype(bool)},
             lambda x, axis: x.any(axis), attrs={"axis": 1}),
        case("count_nonzero", paddle.count_nonzero,
             {"x": (_f32(3, 4) > 0).astype(np.float32)},
             lambda x, axis: np.count_nonzero(x, axis), attrs={"axis": 1}),
        case("quantile", paddle.quantile, {"x": _f32(1, 8)},
             lambda x, q, axis: np.quantile(x, q, axis=axis),
             attrs={"q": 0.3, "axis": 1}, rtol=1e-4, atol=1e-4),
        case("nanquantile", paddle.nanquantile,
             {"x": np.array([[1.0, np.nan, 3.0, 2.0]], np.float32)},
             lambda x, q, axis: np.nanquantile(x, q, axis=axis),
             attrs={"q": 0.5, "axis": 1}, rtol=1e-4, atol=1e-4),
        case("cumsum", paddle.cumsum, {"x": _f32(3, 4)},
             lambda x, axis: np.cumsum(x, axis), attrs={"axis": 1}, grad=["x"]),
        case("cumprod", paddle.cumprod, {"x": _f32(2, 3, positive=True)},
             lambda x, dim: np.cumprod(x, dim), attrs={"dim": 1}, grad=["x"],
             rtol=1e-4, atol=1e-4),
        case("logcumsumexp", paddle.logcumsumexp, {"x": _f32(2, 4)},
             lambda x, axis: np.log(np.cumsum(np.exp(x), axis)),
             attrs={"axis": 1}, grad=["x"], rtol=1e-4, atol=1e-4),
        case("cummax", paddle.cummax, {"x": _f32(2, 4)},
             lambda x, axis: (np.maximum.accumulate(x, axis),
                              _cummax_idx(x, axis)), attrs={"axis": 1}),
        case("cummin", paddle.cummin, {"x": _f32(2, 4)},
             lambda x, axis: (np.minimum.accumulate(x, axis),
                              _cummin_idx(x, axis)), attrs={"axis": 1}),
        case("argmax", paddle.argmax, {"x": _f32(3, 4)},
             lambda x, axis: x.argmax(axis), attrs={"axis": 1}),
        case("argmin", paddle.argmin, {"x": _f32(3, 4)},
             lambda x, axis: x.argmin(axis), attrs={"axis": 1}),
    ]
    # manipulation / indexing
    idx = np.array([2, 0, 1], np.int64)
    C += [
        case("reshape", paddle.reshape, {"x": _f32(3, 4)},
             lambda x, shape: x.reshape(shape), attrs={"shape": [4, 3]},
             grad=["x"]),
        case("flatten", paddle.flatten, {"x": _f32(2, 3, 4)},
             lambda x, start_axis, stop_axis: x.reshape(2, 12),
             attrs={"start_axis": 1, "stop_axis": 2}, grad=["x"]),
        case("transpose", paddle.transpose, {"x": _f32(2, 3, 4)},
             lambda x, perm: x.transpose(perm), attrs={"perm": [2, 0, 1]},
             grad=["x"]),
        case("t", paddle.t, {"x": _f32(3, 4)}, lambda x: x.T, grad=["x"]),
        case("moveaxis", paddle.moveaxis, {"x": _f32(2, 3, 4)},
             lambda x, source, destination: np.moveaxis(x, source, destination),
             attrs={"source": 0, "destination": 2}, grad=["x"]),
        case("swapaxes", paddle.swapaxes, {"x": _f32(2, 3, 4)},
             lambda x, axis1, axis2: np.swapaxes(x, axis1, axis2),
             attrs={"axis1": 0, "axis2": 2}, grad=["x"]),
        case("squeeze", paddle.squeeze, {"x": _f32(3, 1, 4)},
             lambda x, axis: np.squeeze(x, axis), attrs={"axis": 1}, grad=["x"]),
        case("unsqueeze", paddle.unsqueeze, {"x": _f32(3, 4)},
             lambda x, axis: np.expand_dims(x, axis), attrs={"axis": 1},
             grad=["x"]),
        case("concat", lambda x, y, axis: paddle.concat([x, y], axis=axis),
             {"x": _f32(2, 3, seed=1), "y": _f32(2, 3, seed=2)},
             lambda x, y, axis: np.concatenate([x, y], axis), attrs={"axis": 0},
             grad=["x", "y"]),
        case("stack", lambda x, y, axis: paddle.stack([x, y], axis=axis),
             {"x": _f32(2, 3, seed=1), "y": _f32(2, 3, seed=2)},
             lambda x, y, axis: np.stack([x, y], axis), attrs={"axis": 1},
             grad=["x", "y"]),
        case("unstack", paddle.unstack, {"x": _f32(2, 3)},
             lambda x, axis: [x[0], x[1]], attrs={"axis": 0}),
        case("split", paddle.split, {"x": _f32(4, 3)},
             lambda x, num_or_sections, axis: np.split(x, 2, axis),
             attrs={"num_or_sections": 2, "axis": 0}),
        case("chunk", paddle.chunk, {"x": _f32(4, 3)},
             lambda x, chunks, axis: np.split(x, 2, axis),
             attrs={"chunks": 2, "axis": 0}),
        case("vsplit", paddle.vsplit, {"x": _f32(4, 3)},
             lambda x, num_or_sections: np.split(x, 2, 0),
             attrs={"num_or_sections": 2}),
        case("tile", paddle.tile, {"x": _f32(2, 3)},
             lambda x, repeat_times: np.tile(x, repeat_times),
             attrs={"repeat_times": [2, 1]}, grad=["x"]),
        case("expand", paddle.expand, {"x": _f32(1, 3)},
             lambda x, shape: np.broadcast_to(x, shape),
             attrs={"shape": [4, 3]}, grad=["x"]),
        case("broadcast_to", paddle.broadcast_to, {"x": _f32(1, 3)},
             lambda x, shape: np.broadcast_to(x, shape), attrs={"shape": [4, 3]}),
        case("expand_as", paddle.expand_as,
             {"x": _f32(1, 3), "y": _f32(4, 3, seed=9)},
             lambda x, y: np.broadcast_to(x, y.shape)),
        case("flip", paddle.flip, {"x": _f32(3, 4)},
             lambda x, axis: np.flip(x, axis), attrs={"axis": [1]}, grad=["x"]),
        case("roll", paddle.roll, {"x": _f32(3, 4)},
             lambda x, shifts, axis: np.roll(x, shifts, axis),
             attrs={"shifts": 2, "axis": 1}, grad=["x"]),
        case("rot90", paddle.rot90, {"x": _f32(3, 4)},
             lambda x, k, axes: np.rot90(x, k, axes), attrs={"k": 1, "axes": [0, 1]}),
        case("pad2", paddle.pad, {"x": _f32(3, 4)},
             lambda x, pad: np.pad(x, [(1, 2), (0, 1)]),
             attrs={"pad": [1, 2, 0, 1]}, grad=["x"]),
        case("gather", paddle.gather, {"x": _f32(4, 3), "index": idx},
             lambda x, index: x[index], grad=["x"]),
        case("gather_nd", paddle.gather_nd,
             {"x": _f32(3, 4), "index": np.array([[0, 1], [2, 3]], np.int64)},
             lambda x, index: x[index[:, 0], index[:, 1]], grad=["x"]),
        case("take_along_axis", paddle.take_along_axis,
             {"arr": _f32(3, 4), "indices": _i64(3, 2, hi=4)},
             lambda arr, indices, axis: np.take_along_axis(arr, indices, 1),
             attrs={"axis": 1}, grad=["arr"]),
        case("put_along_axis", paddle.put_along_axis,
             {"arr": _f32(3, 4), "indices": np.array([[0], [1], [2]], np.int64),
              "values": _f32(3, 1, seed=5)},
             lambda arr, indices, values, axis: _pa_ref(arr, indices, values, 1),
             attrs={"axis": 1}, grad=["arr", "values"]),
        case("scatter", paddle.scatter,
             {"x": _f32(4, 3), "index": np.array([1, 3], np.int64),
              "updates": _f32(2, 3, seed=5)},
             lambda x, index, updates: _scatter_ref(x, index, updates),
             grad=["x", "updates"]),
        case("scatter_nd_add", paddle.scatter_nd_add,
             {"x": _f32(4, 3), "index": np.array([[1], [3]], np.int64),
              "updates": _f32(2, 3, seed=5)},
             lambda x, index, updates: _scatter_nd_add_ref(x, index, updates),
             grad=["x", "updates"]),
        case("scatter_nd", paddle.scatter_nd,
             {"index": np.array([[1], [3]], np.int64),
              "updates": _f32(2, 3, seed=5)},
             lambda index, updates, shape: _scatter_nd_add_ref(
                 np.zeros((4, 3), np.float32), index, updates),
             attrs={"shape": [4, 3]}),
        case("index_select", paddle.index_select,
             {"x": _f32(4, 3), "index": idx},
             lambda x, index, axis: x[index], attrs={"axis": 0}, grad=["x"]),
        case("index_sample", paddle.index_sample,
             {"x": _f32(3, 4), "index": _i64(3, 2, hi=4)},
             lambda x, index: np.take_along_axis(x, index, 1)),
        case("index_add",
             lambda x, index, value, axis: paddle.index_add(x, index, axis, value),
             {"x": _f32(4, 3), "index": np.array([0, 2], np.int64),
              "value": _f32(2, 3, seed=5)},
             lambda x, index, value, axis: _index_add_ref(x, index, value),
             attrs={"axis": 0}, grad=["x", "value"]),
        case("masked_fill", paddle.masked_fill,
             {"x": _f32(3, 4), "mask": (_f32(3, 4, seed=7) > 0)},
             lambda x, mask, value: np.where(mask, np.float32(2.0), x),
             attrs={"value": 2.0}, grad=["x"]),
        case("where", paddle.where,
             {"condition": (_f32(3, 4, seed=7) > 0), "x": _f32(3, 4, seed=1),
              "y": _f32(3, 4, seed=2)},
             lambda condition, x, y: np.where(condition, x, y), grad=["x", "y"]),
        case("masked_select", paddle.masked_select,
             {"x": _f32(3, 4), "mask": (_f32(3, 4, seed=7) > 0)},
             lambda x, mask: x[mask], static=False),
        case("nonzero", paddle.nonzero, {"x": np.array([[1.0, 0.0], [0.0, 2.0]], np.float32)},
             lambda x: np.stack(np.nonzero(x), axis=1), static=False),
        case("diff", paddle.diff, {"x": _f32(3, 5)},
             lambda x, axis: np.diff(x, axis=axis), attrs={"axis": 1}, grad=["x"]),
        case("repeat_interleave", paddle.repeat_interleave, {"x": _f32(2, 3)},
             lambda x, repeats, axis: np.repeat(x, repeats, axis),
             attrs={"repeats": 2, "axis": 1}, grad=["x"]),
        case("argsort", paddle.argsort, {"x": _f32(3, 4)},
             lambda x, axis: np.argsort(x, axis, kind="stable"), attrs={"axis": 1}),
        case("sort", paddle.sort, {"x": _f32(3, 4)},
             lambda x, axis: np.sort(x, axis), attrs={"axis": 1}, grad=["x"]),
        case("topk", paddle.topk, {"x": _f32(1, 6)},
             lambda x, k: (np.sort(x, 1)[:, ::-1][:, :2],
                           np.argsort(-x, 1, kind="stable")[:, :2]),
             attrs={"k": 2}),
        case("kthvalue", paddle.kthvalue, {"x": _f32(1, 6)},
             lambda x, k: (np.sort(x, 1)[:, 1],
                           np.argsort(x, 1, kind="stable")[:, 1]),
             attrs={"k": 2}),
        case("mode", paddle.mode, {"x": np.array([[1.0, 2.0, 2.0, 3.0]], np.float32)},
             lambda x: (np.array([2.0], np.float32), np.array([2], np.int64))),
        case("searchsorted", paddle.searchsorted,
             {"sorted_sequence": np.array([1.0, 3.0, 5.0, 7.0], np.float32),
              "values": np.array([2.0, 5.0], np.float32)},
             lambda sorted_sequence, values: np.searchsorted(sorted_sequence, values)),
        case("bucketize", paddle.bucketize,
             {"x": np.array([2.0, 5.0], np.float32),
              "sorted_sequence": np.array([1.0, 3.0, 5.0, 7.0], np.float32)},
             lambda x, sorted_sequence: np.searchsorted(sorted_sequence, x)),
        case("bincount", paddle.bincount, {"x": np.array([0, 1, 1, 3], np.int64)},
             lambda x: np.bincount(x), static=False),
        case("histogram", paddle.histogram, {"x": _f32(10)},
             lambda x, bins, min, max: np.histogram(x, bins, (min, max))[0],
             attrs={"bins": 4, "min": -2.0, "max": 2.0}),
        case("tril", paddle.tril, {"x": _f32(3, 4)}, np.tril, grad=["x"]),
        case("triu", paddle.triu, {"x": _f32(3, 4)}, np.triu, grad=["x"]),
        case("diag", paddle.diag, {"x": _f32(3)}, np.diag),
        case("diagflat", paddle.diagflat, {"x": _f32(3)}, np.diagflat),
        case("diagonal", paddle.diagonal, {"x": _f32(3, 4)},
             lambda x: np.diagonal(x), grad=["x"]),
        case("trace", paddle.trace, {"x": _f32(3, 3)}, np.trace, grad=["x"]),
        case("unbind", paddle.unbind, {"x": _f32(2, 3)},
             lambda x, axis: [x[0], x[1]], attrs={"axis": 0}),
        case("unfold_t", paddle.unfold, {"x": _f32(1, 8)},
             lambda x, axis, size, step: np.stack([x[:, 0:4], x[:, 2:6], x[:, 4:8]], 1),
             attrs={"axis": 1, "size": 4, "step": 2}),
        case("as_strided", paddle.as_strided, {"x": _f32(6)},
             lambda x, shape, stride: np.lib.stride_tricks.as_strided(
                 x, (3, 2), (x.itemsize * 2, x.itemsize)),
             attrs={"shape": [3, 2], "stride": [2, 1]}),
        case("slice_op", paddle.slice, {"x": _f32(3, 4)},
             lambda x, axes, starts, ends: x[:, 1:3],
             attrs={"axes": [1], "starts": [1], "ends": [3]}, grad=["x"]),
        case("strided_slice", paddle.strided_slice, {"x": _f32(3, 8)},
             lambda x, axes, starts, ends, strides: x[:, 1:7:2],
             attrs={"axes": [1], "starts": [1], "ends": [7], "strides": [2]}),
        case("crop", paddle.crop, {"x": _f32(3, 4)},
             lambda x, shape, offsets: x[1:3, 1:4],
             attrs={"shape": [2, 3], "offsets": [1, 1]}),
        case("reverse", paddle.reverse, {"x": _f32(3, 4)},
             lambda x, axis: np.flip(x, axis), attrs={"axis": [0]}),
        case("take", paddle.take, {"x": _f32(3, 4),
                                   "index": np.array([0, 5, 11], np.int64)},
             lambda x, index: x.reshape(-1)[index]),
        case("index_put", paddle.index_put,
             {"x": _f32(3, 4),
              "indices": np.array([0, 2], np.int64),
              "value": _f32(2, 4, seed=11)},
             lambda x, indices, value: _index_put_ref(x, indices, value),
             static=False),
        case("multiplex", lambda a, b, index: paddle.multiplex([a, b], index),
             {"a": _f32(3, 4, seed=1), "b": _f32(3, 4, seed=2),
              "index": np.array([[0], [1], [0]], np.int64)},
             lambda a, b, index: np.where(index == 0, a, b)),
        case("shard_index", paddle.shard_index,
             {"input": np.array([[1], [6], [3]], np.int64)},
             lambda input, index_num, nshards, shard_id: _shard_index_ref(
                 input, 8, 2, 0), attrs={"index_num": 8, "nshards": 2,
                                         "shard_id": 0}),
        case("broadcast_tensors",
             lambda x, y: paddle.broadcast_tensors([x, y]),
             {"x": _f32(1, 3), "y": _f32(2, 1, seed=4)},
             lambda x, y: [np.broadcast_to(x, (2, 3)), np.broadcast_to(y, (2, 3))]),
    ]
    # linalg-ish
    C += [
        case("matmul", paddle.matmul, {"x": _f32(3, 4), "y": _f32(4, 2, seed=2)},
             np.matmul, grad=["x", "y"], rtol=1e-4, atol=1e-5),
        case("mm", paddle.mm, {"input": _f32(3, 4), "mat2": _f32(4, 2, seed=2)},
             np.matmul, rtol=1e-4, atol=1e-5),
        case("bmm", paddle.bmm, {"x": _f32(2, 3, 4), "y": _f32(2, 4, 2, seed=2)},
             np.matmul, grad=["x", "y"], rtol=1e-4, atol=1e-5),
        case("mv", paddle.mv, {"x": _f32(3, 4), "vec": _f32(4, seed=2)},
             np.matmul, grad=["x", "vec"], rtol=1e-4, atol=1e-5),
        case("addmm", paddle.addmm,
             {"input": _f32(3, 2), "x": _f32(3, 4, seed=1), "y": _f32(4, 2, seed=2)},
             lambda input, x, y, beta, alpha: beta * input + alpha * (x @ y),
             attrs={"beta": 0.5, "alpha": 2.0}, grad=["input", "x", "y"],
             rtol=1e-4, atol=1e-5),
        case("outer", paddle.outer, {"x": _f32(3), "y": _f32(4, seed=2)},
             np.outer, grad=["x", "y"]),
        case("inner", paddle.inner, {"x": _f32(2, 4), "y": _f32(3, 4, seed=2)},
             np.inner, grad=["x", "y"], rtol=1e-4, atol=1e-5),
        case("dot", paddle.dot, {"x": _f32(4), "y": _f32(4, seed=2)},
             np.dot, grad=["x", "y"]),
        case("cross", paddle.cross, {"x": _f32(2, 3), "y": _f32(2, 3, seed=2)},
             lambda x, y: np.cross(x, y), grad=["x", "y"]),
        case("kron", paddle.kron, {"x": _f32(2, 2), "y": _f32(2, 3, seed=2)},
             np.kron, grad=["x", "y"]),
        case("matrix_power", paddle.matrix_power, {"x": _f32(3, 3)},
             lambda x, n: np.linalg.matrix_power(x, n), attrs={"n": 3},
             rtol=1e-3, atol=1e-4),
        case("norm_fro", paddle.norm, {"x": _f32(3, 4)},
             lambda x: np.linalg.norm(x), rtol=1e-4, atol=1e-5),
        case("dist", paddle.dist, {"x": _f32(3, 4), "y": _f32(3, 4, seed=2)},
             lambda x, y, p: np.linalg.norm((x - y).ravel(), ord=2),
             attrs={"p": 2}, rtol=1e-4, atol=1e-5),
        case("renorm", paddle.renorm, {"x": _f32(3, 4)},
             lambda x, p, axis, max_norm: _renorm_ref(x, 2.0, 0, 1.0),
             attrs={"p": 2.0, "axis": 0, "max_norm": 1.0}, rtol=1e-4, atol=1e-4),
        case("tensordot", paddle.tensordot,
             {"x": _f32(2, 3, 4), "y": _f32(3, 4, 5, seed=2)},
             lambda x, y, axes: np.tensordot(x, y, axes=2), attrs={"axes": 2},
             rtol=1e-3, atol=1e-4),
        case("einsum", lambda x, y: paddle.einsum("ij,jk->ik", x, y),
             {"x": _f32(3, 4), "y": _f32(4, 2, seed=2)},
             lambda x, y: np.einsum("ij,jk->ik", x, y), rtol=1e-4, atol=1e-5),
        case("add_n", lambda x, y: paddle.add_n([x, y]),
             {"x": _f32(3, 4, seed=1), "y": _f32(3, 4, seed=2)},
             lambda x, y: x + y),
        case("frexp", paddle.frexp, {"x": np.array([1.5, -4.0, 0.25], np.float32)},
             lambda x: tuple(np.frexp(x))),
        case("complex_op", paddle.complex, {"real": _f32(3), "imag": _f32(3, seed=2)},
             lambda real, imag: real + 1j * imag),
        case("as_complex", paddle.as_complex, {"x": _f32(3, 2)},
             lambda x: x[..., 0] + 1j * x[..., 1]),
        case("as_real", paddle.as_real,
             {"x": (_f32(3) + 1j * _f32(3, seed=2)).astype(np.complex64)},
             lambda x: np.stack([x.real, x.imag], -1)),
        case("cast", paddle.cast, {"x": _f32(3, 4)},
             lambda x, dtype: x.astype(np.float64), attrs={"dtype": "float64"}),
        case("allclose_op", paddle.allclose,
             {"x": _f32(3), "y": _f32(3)}, lambda x, y: np.allclose(x, y)),
        case("isclose", paddle.isclose, {"x": _f32(3), "y": _f32(3)},
             lambda x, y: np.isclose(x, y)),
        case("equal_all", paddle.equal_all, {"x": _f32(3), "y": _f32(3)},
             lambda x, y: np.array_equal(x, y)),
    ]
    # nn.functional — activations
    ACT = [
        ("relu6", lambda x: np.clip(x, 0, 6), True),
        ("silu", lambda x: x / (1 + np.exp(-x)), True),
        ("swish", lambda x: x / (1 + np.exp(-x)), True),
        ("elu", lambda x: np.where(x > 0, x, np.exp(x) - 1), True),
        ("selu", lambda x: 1.0507009873554805 * np.where(
            x > 0, x, 1.6732632423543772 * (np.exp(x) - 1)), True),
        ("celu", lambda x: np.maximum(x, 0) + np.minimum(0, np.exp(x) - 1), True),
        ("leaky_relu", lambda x: np.where(x > 0, x, 0.01 * x), True),
        ("hardshrink", lambda x: np.where(np.abs(x) > 0.5, x, 0), False),
        ("softshrink", lambda x: np.where(x > 0.5, x - 0.5,
                                          np.where(x < -0.5, x + 0.5, 0)), True),
        ("tanhshrink", lambda x: x - np.tanh(x), True),
        ("hardtanh", lambda x: np.clip(x, -1, 1), True),
        ("hardsigmoid", lambda x: np.clip(x / 6 + 0.5, 0, 1), True),
        ("hardswish", lambda x: x * np.clip(x + 3, 0, 6) / 6, True),
        ("mish", lambda x: x * np.tanh(np.log1p(np.exp(x))), True),
        ("softplus", lambda x: np.log1p(np.exp(x)), True),
        ("softsign", lambda x: x / (1 + np.abs(x)), True),
        ("log_sigmoid", lambda x: -np.log1p(np.exp(-x)), True),
        ("thresholded_relu", lambda x: np.where(x > 1.0, x, 0), False),
    ]
    for name, ref, has_grad in ACT:
        C.append(case("F." + name, getattr(F, name), {"x": _f32(3, 4)}, ref,
                      grad=["x"] if has_grad else [], rtol=1e-4, atol=1e-5))
    C += [
        case("F.gelu", F.gelu, {"x": _f32(3, 4)},
             lambda x: _torch().nn.functional.gelu(_t(x)).numpy(),
             grad=["x"], rtol=1e-4, atol=1e-5),
        case("F.glu", F.glu, {"x": _f32(3, 4)},
             lambda x, axis: x[:, :2] * (1 / (1 + np.exp(-x[:, 2:]))),
             attrs={"axis": 1}, grad=["x"], rtol=1e-4, atol=1e-5),
        case("F.prelu", F.prelu, {"x": _f32(3, 4), "weight": np.array([0.25], np.float32)},
             lambda x, weight: np.where(x > 0, x, 0.25 * x), grad=["x"]),
        case("F.maxout", F.maxout, {"x": _f32(1, 4, 2, 2)},
             lambda x, groups: x.reshape(1, 2, 2, 2, 2).max(2),
             attrs={"groups": 2}),
        case("F.softmax", F.softmax, {"x": _f32(3, 4)},
             lambda x, axis: _softmax_ref(x, axis), attrs={"axis": 1},
             grad=["x"], rtol=1e-4, atol=1e-5),
        case("F.log_softmax", F.log_softmax, {"x": _f32(3, 4)},
             lambda x, axis: np.log(_softmax_ref(x, axis)), attrs={"axis": 1},
             grad=["x"], rtol=1e-4, atol=1e-5),
        case("F.one_hot", F.one_hot, {"x": np.array([0, 2, 1], np.int64)},
             lambda x, num_classes: np.eye(3, dtype=np.float32)[x],
             attrs={"num_classes": 3}),
        case("F.linear", F.linear,
             {"x": _f32(3, 4), "weight": _f32(4, 2, seed=2), "bias": _f32(2, seed=3)},
             lambda x, weight, bias: x @ weight + bias,
             grad=["x", "weight", "bias"], rtol=1e-4, atol=1e-5),
        case("F.embedding", F.embedding,
             {"x": np.array([0, 2], np.int64), "weight": _f32(4, 3)},
             lambda x, weight: weight[x], grad=["weight"]),
        case("F.label_smooth", F.label_smooth,
             {"label": np.eye(3, dtype=np.float32)},
             lambda label, epsilon: label * (1 - 0.1) + 0.1 / 3,
             attrs={"epsilon": 0.1}),
        case("F.normalize", F.normalize, {"x": _f32(3, 4)},
             lambda x, axis: x / np.linalg.norm(x, axis=1, keepdims=True).clip(1e-12),
             attrs={"axis": 1}, grad=["x"], rtol=1e-4, atol=1e-5),
        case("F.cosine_similarity", F.cosine_similarity,
             {"x1": _f32(3, 4), "x2": _f32(3, 4, seed=2)},
             lambda x1, x2, axis: (x1 * x2).sum(1) /
             (np.linalg.norm(x1, axis=1) * np.linalg.norm(x2, axis=1)).clip(1e-8),
             attrs={"axis": 1}, grad=["x1", "x2"], rtol=1e-4, atol=1e-4),
        case("F.pairwise_distance", F.pairwise_distance,
             {"x": _f32(3, 4), "y": _f32(3, 4, seed=2)},
             lambda x, y: np.linalg.norm(x - y + 1e-6, axis=1),
             rtol=1e-3, atol=1e-4),
        case("F.pad", F.pad, {"x": _f32(1, 2, 3, 4)},
             lambda x, pad: np.pad(x, [(0, 0), (0, 0), (1, 1), (2, 2)]),
             attrs={"pad": [2, 2, 1, 1]}, grad=["x"]),
        case("F.zeropad2d", F.zeropad2d, {"x": _f32(1, 2, 3, 4)},
             lambda x, padding: np.pad(x, [(0, 0), (0, 0), (1, 1), (2, 2)]),
             attrs={"padding": [2, 2, 1, 1]}),
        case("F.diag_embed", F.diag_embed, {"input": _f32(2, 3)},
             lambda input: np.stack([np.diag(r) for r in input])),
        case("F.bilinear", F.bilinear,
             {"x1": _f32(3, 2), "x2": _f32(3, 4, seed=2),
              "weight": _f32(5, 2, 4, seed=3)},
             lambda x1, x2, weight: np.einsum("bi,oij,bj->bo", x1, weight, x2),
             rtol=1e-4, atol=1e-4),
        case("F.sequence_mask", F.sequence_mask,
             {"x": np.array([1, 3, 2], np.int64)},
             lambda x, maxlen: (np.arange(4)[None, :] < x[:, None]),
             attrs={"maxlen": 4}),
        case("F.gather_tree", F.gather_tree,
             {"ids": np.array([[[2], [5]], [[3], [6]]], np.int64),
              "parents": np.array([[[0], [0]], [[0], [0]]], np.int64)},
             # beam=1 backtrace returns the ids unchanged
             lambda ids, parents: ids),
    ]
    # norms
    C += [
        case("F.layer_norm", F.layer_norm,
             {"x": _f32(3, 4), "normalized_shape_": np.zeros(0, np.float32)},
             None, static=False),  # replaced below with closure-style case
    ]
    C.pop()  # layer_norm needs kw style; use explicit lambdas instead
    C += [
        case("F.layer_norm",
             lambda x, weight, bias: F.layer_norm(x, [4], weight=weight, bias=bias),
             {"x": _f32(3, 4), "weight": _f32(4, seed=2, positive=True),
              "bias": _f32(4, seed=3)},
             lambda x, weight, bias: _ln_ref(x, weight, bias),
             grad=["x", "weight", "bias"], rtol=1e-4, atol=1e-4),
        case("F.rms_norm",
             lambda x, weight: F.rms_norm(x, weight=weight),
             {"x": _f32(3, 4), "weight": _f32(4, seed=2, positive=True)},
             lambda x, weight: x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * weight,
             grad=["x", "weight"], rtol=1e-4, atol=1e-4),
        case("F.batch_norm",
             lambda x, rm, rv, w, b: F.batch_norm(x, rm, rv, weight=w, bias=b,
                                                  training=False),
             {"x": _f32(3, 4), "rm": _f32(4, seed=1), "rv": _f32(4, seed=2, positive=True),
              "w": _f32(4, seed=3, positive=True), "b": _f32(4, seed=4)},
             lambda x, rm, rv, w, b: (x - rm) / np.sqrt(rv + 1e-5) * w + b,
             rtol=1e-4, atol=1e-4),
        case("F.group_norm",
             lambda x, w, b: F.group_norm(x, 2, weight=w, bias=b),
             {"x": _f32(2, 4, 3), "w": _f32(4, seed=2, positive=True),
              "b": _f32(4, seed=3)},
             lambda x, w, b: _gn_ref(x, 2, w, b), rtol=1e-4, atol=1e-4,
             grad=["x"]),
        case("F.instance_norm", lambda x: F.instance_norm(x),
             {"x": _f32(2, 3, 4)},
             lambda x: (x - x.mean(-1, keepdims=True)) /
             np.sqrt(x.var(-1, keepdims=True) + 1e-5),
             rtol=1e-4, atol=1e-4),
        case("F.local_response_norm", F.local_response_norm,
             {"x": _f32(1, 4, 3, 3)},
             lambda x, size: _lrn_ref(x, 5), attrs={"size": 5},
             rtol=1e-4, atol=1e-4),
    ]
    # losses
    C += [
        case("F.mse_loss", F.mse_loss,
             {"input": _f32(3, 4), "label": _f32(3, 4, seed=2)},
             lambda input, label: ((input - label) ** 2).mean(),
             grad=["input"], rtol=1e-4, atol=1e-5),
        case("F.l1_loss", F.l1_loss,
             {"input": _f32(3, 4), "label": _f32(3, 4, seed=2)},
             lambda input, label: np.abs(input - label).mean(),
             grad=["input"], rtol=1e-4, atol=1e-5),
        case("F.smooth_l1_loss", F.smooth_l1_loss,
             {"input": _f32(3, 4), "label": _f32(3, 4, seed=2)},
             lambda input, label: _smooth_l1_ref(input, label, 1.0),
             rtol=1e-4, atol=1e-4, grad=["input"]),
        case("F.kl_div", F.kl_div,
             {"input": np.log(_softmax_ref(_f32(3, 4), 1)),
              "label": _softmax_ref(_f32(3, 4, seed=2), 1)},
             lambda input, label: (label * (np.log(label) - input)).mean(),
             rtol=1e-4, atol=1e-4),
        case("F.nll_loss", F.nll_loss,
             {"input": np.log(_softmax_ref(_f32(3, 4), 1)),
              "label": np.array([0, 3, 1], np.int64)},
             lambda input, label: -input[np.arange(3), label].mean(),
             grad=["input"], rtol=1e-4, atol=1e-5),
        case("F.cross_entropy", F.cross_entropy,
             {"input": _f32(3, 4), "label": np.array([0, 3, 1], np.int64)},
             lambda input, label: -np.log(_softmax_ref(input, 1))[np.arange(3), label].mean(),
             grad=["input"], rtol=1e-4, atol=1e-5),
        case("F.softmax_with_cross_entropy", F.softmax_with_cross_entropy,
             {"logits": _f32(3, 4), "label": np.array([[0], [3], [1]], np.int64)},
             lambda logits, label: -np.log(_softmax_ref(logits, 1))[
                 np.arange(3), label[:, 0]][:, None],
             rtol=1e-4, atol=1e-5),
        case("F.binary_cross_entropy", F.binary_cross_entropy,
             {"input": _f32(3, 4, lo=0.1, hi=0.9, positive=True) % 0.8 + 0.1,
              "label": (_f32(3, 4, seed=2) > 0).astype(np.float32)},
             lambda input, label: -(label * np.log(input) +
                                    (1 - label) * np.log(1 - input)).mean(),
             rtol=1e-4, atol=1e-4, grad=["input"]),
        case("F.binary_cross_entropy_with_logits",
             F.binary_cross_entropy_with_logits,
             {"logit": _f32(3, 4), "label": (_f32(3, 4, seed=2) > 0).astype(np.float32)},
             lambda logit, label: (np.maximum(logit, 0) - logit * label +
                                   np.log1p(np.exp(-np.abs(logit)))).mean(),
             rtol=1e-4, atol=1e-4, grad=["logit"]),
        case("F.margin_ranking_loss", F.margin_ranking_loss,
             {"input": _f32(4), "other": _f32(4, seed=2),
              "label": np.sign(_f32(4, seed=3)).astype(np.float32)},
             lambda input, other, label: np.maximum(
                 0, -label * (input - other) + 0.0).mean()),
        case("F.square_error_cost", F.square_error_cost,
             {"input": _f32(3, 4), "label": _f32(3, 4, seed=2)},
             lambda input, label: (input - label) ** 2),
        case("F.log_loss", F.log_loss,
             {"input": _f32(3, 1, lo=0.1, hi=0.9, positive=True) % 0.8 + 0.1,
              "label": (_f32(3, 1, seed=2) > 0).astype(np.float32)},
             lambda input, label: -label * np.log(input + 1e-4) -
             (1 - label) * np.log(1 - input + 1e-4), rtol=1e-4, atol=1e-4),
        case("F.soft_margin_loss", F.soft_margin_loss,
             {"input": _f32(3, 4), "label": np.sign(_f32(3, 4, seed=2)).astype(np.float32)},
             lambda input, label: np.log1p(np.exp(-label * input)).mean(),
             rtol=1e-4, atol=1e-4),
        case("F.hinge_embedding_loss", F.hinge_embedding_loss,
             {"input": _f32(3, 4, positive=True),
              "label": np.sign(_f32(3, 4, seed=2)).astype(np.float32)},
             lambda input, label: np.where(
                 label == 1, input, np.maximum(0, 1.0 - input)).mean()),
        case("F.cosine_embedding_loss", F.cosine_embedding_loss,
             {"input1": _f32(3, 4), "input2": _f32(3, 4, seed=2),
              "label": np.array([1, -1, 1], np.float32)},
             lambda input1, input2, label: _cos_emb_ref(input1, input2, label),
             rtol=1e-4, atol=1e-4),
        case("F.triplet_margin_loss", F.triplet_margin_loss,
             {"input": _f32(3, 4), "positive": _f32(3, 4, seed=2),
              "negative": _f32(3, 4, seed=3)},
             lambda input, positive, negative: _triplet_ref(
                 input, positive, negative, 1.0), rtol=1e-3, atol=1e-4),
        case("F.multi_label_soft_margin_loss", F.multi_label_soft_margin_loss,
             {"input": _f32(3, 4),
              "label": (_f32(3, 4, seed=2) > 0).astype(np.float32)},
             lambda input, label: (-(label * np.log(1 / (1 + np.exp(-input))) +
                                     (1 - label) * np.log(1 - 1 / (1 + np.exp(-input))))
                                   ).mean(1).mean(), rtol=1e-4, atol=1e-4),
        case("F.dice_loss", F.dice_loss,
             {"input": _softmax_ref(_f32(3, 2), 1),
              "label": _i64(3, 1, hi=2)},
             lambda input, label: _dice_ref(input, label), rtol=1e-4, atol=1e-4),
        case("F.sigmoid_focal_loss", F.sigmoid_focal_loss,
             {"logit": _f32(3, 4), "label": (_f32(3, 4, seed=2) > 0).astype(np.float32)},
             lambda logit, label: _focal_ref(logit, label), rtol=1e-3, atol=1e-4),
        case("F.npair_loss", F.npair_loss,
             {"anchor": _f32(3, 4), "positive": _f32(3, 4, seed=2),
              "labels": np.array([0, 1, 2], np.int64)},
             lambda anchor, positive, labels: _npair_ref(anchor, positive, labels),
             rtol=1e-3, atol=1e-4),
    ]
    # conv / pool (torch oracle)
    C += [
        case("F.conv2d", F.conv2d,
             {"x": _f32(1, 2, 5, 5), "weight": _f32(3, 2, 3, 3, seed=2)},
             lambda x, weight: _torch().nn.functional.conv2d(
                 _t(x), _t(weight)).numpy(),
             grad=["x", "weight"], rtol=1e-3, atol=1e-4),
        case("F.conv1d", F.conv1d,
             {"x": _f32(1, 2, 6), "weight": _f32(3, 2, 3, seed=2)},
             lambda x, weight: _torch().nn.functional.conv1d(
                 _t(x), _t(weight)).numpy(), rtol=1e-3, atol=1e-4),
        case("F.conv3d", F.conv3d,
             {"x": _f32(1, 2, 4, 4, 4), "weight": _f32(3, 2, 2, 2, 2, seed=2)},
             lambda x, weight: _torch().nn.functional.conv3d(
                 _t(x), _t(weight)).numpy(), rtol=1e-3, atol=1e-4),
        case("F.conv2d_transpose", F.conv2d_transpose,
             {"x": _f32(1, 2, 4, 4), "weight": _f32(2, 3, 3, 3, seed=2)},
             lambda x, weight: _torch().nn.functional.conv_transpose2d(
                 _t(x), _t(weight)).numpy(), rtol=1e-3, atol=1e-4),
        case("F.conv1d_transpose", F.conv1d_transpose,
             {"x": _f32(1, 2, 4), "weight": _f32(2, 3, 3, seed=2)},
             lambda x, weight: _torch().nn.functional.conv_transpose1d(
                 _t(x), _t(weight)).numpy(), rtol=1e-3, atol=1e-4),
        case("F.conv3d_transpose", F.conv3d_transpose,
             {"x": _f32(1, 2, 3, 3, 3), "weight": _f32(2, 2, 2, 2, 2, seed=2)},
             lambda x, weight: _torch().nn.functional.conv_transpose3d(
                 _t(x), _t(weight)).numpy(), rtol=1e-3, atol=1e-4),
        case("F.max_pool2d", F.max_pool2d, {"x": _f32(1, 2, 4, 4)},
             lambda x, kernel_size: _torch().nn.functional.max_pool2d(
                 _t(x), 2).numpy(), attrs={"kernel_size": 2}, grad=["x"]),
        case("F.max_pool1d", F.max_pool1d, {"x": _f32(1, 2, 6)},
             lambda x, kernel_size: _torch().nn.functional.max_pool1d(
                 _t(x), 2).numpy(), attrs={"kernel_size": 2}),
        case("F.max_pool3d", F.max_pool3d, {"x": _f32(1, 2, 4, 4, 4)},
             lambda x, kernel_size: _torch().nn.functional.max_pool3d(
                 _t(x), 2).numpy(), attrs={"kernel_size": 2}),
        case("F.avg_pool2d", F.avg_pool2d, {"x": _f32(1, 2, 4, 4)},
             lambda x, kernel_size: _torch().nn.functional.avg_pool2d(
                 _t(x), 2).numpy(), attrs={"kernel_size": 2}, grad=["x"]),
        case("F.avg_pool1d", F.avg_pool1d, {"x": _f32(1, 2, 6)},
             lambda x, kernel_size: _torch().nn.functional.avg_pool1d(
                 _t(x), 2).numpy(), attrs={"kernel_size": 2}),
        case("F.avg_pool3d", F.avg_pool3d, {"x": _f32(1, 2, 4, 4, 4)},
             lambda x, kernel_size: _torch().nn.functional.avg_pool3d(
                 _t(x), 2).numpy(), attrs={"kernel_size": 2}),
        case("F.adaptive_avg_pool2d", F.adaptive_avg_pool2d, {"x": _f32(1, 2, 4, 4)},
             lambda x, output_size: _torch().nn.functional.adaptive_avg_pool2d(
                 _t(x), 2).numpy(), attrs={"output_size": 2}),
        case("F.adaptive_avg_pool1d", F.adaptive_avg_pool1d, {"x": _f32(1, 2, 6)},
             lambda x, output_size: _torch().nn.functional.adaptive_avg_pool1d(
                 _t(x), 2).numpy(), attrs={"output_size": 2}),
        case("F.adaptive_avg_pool3d", F.adaptive_avg_pool3d, {"x": _f32(1, 2, 4, 4, 4)},
             lambda x, output_size: _torch().nn.functional.adaptive_avg_pool3d(
                 _t(x), 2).numpy(), attrs={"output_size": 2}),
        case("F.adaptive_max_pool2d", F.adaptive_max_pool2d, {"x": _f32(1, 2, 4, 4)},
             lambda x, output_size: _torch().nn.functional.adaptive_max_pool2d(
                 _t(x), 2).numpy(), attrs={"output_size": 2}),
        case("F.adaptive_max_pool1d", F.adaptive_max_pool1d, {"x": _f32(1, 2, 6)},
             lambda x, output_size: _torch().nn.functional.adaptive_max_pool1d(
                 _t(x), 2).numpy(), attrs={"output_size": 2}),
        case("F.adaptive_max_pool3d", F.adaptive_max_pool3d, {"x": _f32(1, 2, 4, 4, 4)},
             lambda x, output_size: _torch().nn.functional.adaptive_max_pool3d(
                 _t(x), 2).numpy(), attrs={"output_size": 2}),
        case("F.interpolate", F.interpolate, {"x": _f32(1, 2, 4, 4)},
             lambda x, scale_factor, mode: _torch().nn.functional.interpolate(
                 _t(x), scale_factor=2, mode="nearest").numpy(),
             attrs={"scale_factor": 2, "mode": "nearest"}),
        case("F.upsample", F.upsample, {"x": _f32(1, 2, 4, 4)},
             lambda x, scale_factor, mode: _torch().nn.functional.interpolate(
                 _t(x), scale_factor=2, mode="nearest").numpy(),
             attrs={"scale_factor": 2, "mode": "nearest"}),
        case("F.pixel_shuffle", F.pixel_shuffle, {"x": _f32(1, 4, 2, 2)},
             lambda x, upscale_factor: _torch().nn.functional.pixel_shuffle(
                 _t(x), 2).numpy(), attrs={"upscale_factor": 2}),
        case("F.pixel_unshuffle", F.pixel_unshuffle, {"x": _f32(1, 1, 4, 4)},
             lambda x, downscale_factor: _torch().nn.functional.pixel_unshuffle(
                 _t(x), 2).numpy(), attrs={"downscale_factor": 2}),
        case("F.channel_shuffle", F.channel_shuffle, {"x": _f32(1, 4, 2, 2)},
             lambda x, groups: _torch().nn.functional.channel_shuffle(
                 _t(x), 2).numpy(), attrs={"groups": 2}),
        case("F.unfold", F.unfold, {"x": _f32(1, 2, 4, 4)},
             lambda x, kernel_sizes: _torch().nn.functional.unfold(
                 _t(x), 2).numpy(), attrs={"kernel_sizes": 2}),
        case("F.fold", F.fold, {"x": _f32(1, 8, 4)},
             lambda x, output_sizes, kernel_sizes: _torch().nn.functional.fold(
                 _t(x), (3, 3), 2).numpy(),
             attrs={"output_sizes": [3, 3], "kernel_sizes": 2}),
        case("F.max_unpool2d",
             lambda x, indices: F.max_unpool2d(x, indices, kernel_size=2),
             {"x": _f32(1, 1, 2, 2, positive=True),
              "indices": np.array([[[[0, 3], [8, 11]]]], np.int64)},
             lambda x, indices: _torch().nn.functional.max_unpool2d(
                 _t(x), _t(indices), 2).numpy()),
        case("F.grid_sample", F.grid_sample,
             {"x": _f32(1, 1, 3, 3), "grid": np.clip(_f32(1, 2, 2, 2, seed=2), -1, 1)},
             lambda x, grid: _torch().nn.functional.grid_sample(
                 _t(x), _t(grid), align_corners=True).numpy(),
             rtol=1e-3, atol=1e-4),
        case("F.affine_grid", F.affine_grid,
             {"theta": _f32(1, 2, 3)},
             lambda theta, out_shape: _torch().nn.functional.affine_grid(
                 _t(theta), [1, 1, 3, 3], align_corners=True).numpy(),
             attrs={"out_shape": [1, 1, 3, 3]}, rtol=1e-4, atol=1e-5),
        case("F.temporal_shift", F.temporal_shift, {"x": _f32(4, 4, 2, 2)},
             lambda x, seg_num, shift_ratio: _temporal_shift_ref(x, 2, 0.25),
             attrs={"seg_num": 2, "shift_ratio": 0.25}),
    ]
    # attention
    C += [
        case("F.scaled_dot_product_attention",
             F.scaled_dot_product_attention,
             {"query": _f32(1, 3, 2, 4), "key": _f32(1, 3, 2, 4, seed=2),
              "value": _f32(1, 3, 2, 4, seed=3)},
             lambda query, key, value: _sdpa_ref(query, key, value),
             rtol=1e-3, atol=1e-4),
    ]
    return C


# ---------------------------------------------------------------------------
# numpy reference helpers
def _softmax_ref(x, axis):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _cummax_idx(x, axis):
    idx = np.zeros(x.shape, np.int64)
    run = np.zeros(x.shape[0], np.int64)
    best = x[:, 0].copy()
    for j in range(x.shape[1]):
        upd = x[:, j] >= best
        run = np.where(x[:, j] > best, j, run)
        best = np.maximum(best, x[:, j])
        idx[:, j] = run
    return idx


def _cummin_idx(x, axis):
    return _cummax_idx(-x, axis)


def _pa_ref(arr, indices, values, axis):
    out = arr.copy()
    np.put_along_axis(out, indices, values, axis)
    return out


def _scatter_ref(x, index, updates):
    out = x.copy()
    out[index] = updates
    return out


def _scatter_nd_add_ref(x, index, updates):
    out = x.copy()
    for i, ix in enumerate(index[:, 0]):
        out[ix] += updates[i]
    return out


def _index_add_ref(x, index, value):
    out = x.copy()
    for i, ix in enumerate(index):
        out[ix] += value[i]
    return out


def _index_put_ref(x, indices, value):
    out = x.copy()
    out[indices] = value[:, None] if value.ndim == 1 and out[indices].ndim == 2 \
        else value
    return out


def _shard_index_ref(input, index_num, nshards, shard_id):
    size = index_num // nshards
    lo, hi = shard_id * size, (shard_id + 1) * size
    out = np.where((input >= lo) & (input < hi), input - lo, -1)
    return out


def _renorm_ref(x, p, axis, max_norm):
    norms = np.linalg.norm(x.reshape(x.shape[0], -1), ord=p, axis=1)
    factor = np.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor[:, None]


def _ln_ref(x, w, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * w + b


def _gn_ref(x, groups, w, b, eps=1e-5):
    n, c = x.shape[:2]
    g = x.reshape(n, groups, -1)
    mu = g.mean(-1, keepdims=True)
    var = g.var(-1, keepdims=True)
    out = ((g - mu) / np.sqrt(var + eps)).reshape(x.shape)
    return out * w[None, :, None] + b[None, :, None]


def _lrn_ref(x, size, alpha=1e-4, beta=0.75, k=1.0):
    alpha = alpha / size  # paddle/torch divide alpha by n
    n, c, h, w = x.shape
    sq = x ** 2
    acc = np.zeros_like(x)
    half = size // 2
    for i in range(c):
        lo, hi = max(0, i - half), min(c, i + half + 1)
        acc[:, i] = sq[:, lo:hi].sum(1)
    return x / (k + alpha * acc) ** beta


def _smooth_l1_ref(x, y, delta):
    d = np.abs(x - y)
    return np.where(d < delta, 0.5 * d ** 2, delta * (d - 0.5 * delta)).mean()


def _cos_emb_ref(x1, x2, label, margin=0.0):
    cos = (x1 * x2).sum(1) / (np.linalg.norm(x1, axis=1) *
                              np.linalg.norm(x2, axis=1)).clip(1e-8)
    pos = 1 - cos
    neg = np.maximum(0, cos - margin)
    return np.where(label == 1, pos, neg).mean()


def _triplet_ref(a, p, n, margin):
    dp = np.linalg.norm(a - p + 1e-6, axis=1)
    dn = np.linalg.norm(a - n + 1e-6, axis=1)
    return np.maximum(0, dp - dn + margin).mean()


def _dice_ref(input, label):
    oh = np.eye(input.shape[-1], dtype=np.float32)[label[:, 0]]
    inter = (input * oh).sum()
    return 1 - (2 * inter + 0.0) / (input.sum() + oh.sum() + 1e-5)


def _focal_ref(logit, label, alpha=0.25, gamma=2.0):
    p = 1 / (1 + np.exp(-logit))
    ce = np.maximum(logit, 0) - logit * label + np.log1p(np.exp(-np.abs(logit)))
    pt = p * label + (1 - p) * (1 - label)
    a = alpha * label + (1 - alpha) * (1 - label)
    return (a * (1 - pt) ** gamma * ce).sum()


def _npair_ref(anchor, positive, labels, l2_reg=0.002):
    sim = anchor @ positive.T
    n = anchor.shape[0]
    ce = -np.log(_softmax_ref(sim, 1))[np.arange(n), np.arange(n)].mean()
    reg = l2_reg * ((anchor ** 2).sum(1).mean() +
                    (positive ** 2).sum(1).mean()) * 0.25
    return ce + reg


def _temporal_shift_ref(x, seg_num, shift_ratio):
    nt, c, h, w = x.shape
    n = nt // seg_num
    x5 = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    out = np.zeros_like(x5)
    out[:, :-1, :fold] = x5[:, 1:, :fold]
    out[:, 1:, fold:2 * fold] = x5[:, :-1, fold:2 * fold]
    out[:, :, 2 * fold:] = x5[:, :, 2 * fold:]
    return out.reshape(nt, c, h, w)


def _sdpa_ref(q, k, v):
    # inputs are (B, S, H, D) paddle layout
    qt, kt, vt = (np.transpose(a, (0, 2, 1, 3)) for a in (q, k, v))
    s = qt @ np.transpose(kt, (0, 1, 3, 2)) / np.sqrt(q.shape[-1])
    p = _softmax_ref(s, -1)
    o = p @ vt
    return np.transpose(o, (0, 2, 1, 3))


# ---------------------------------------------------------------------------
CASES = _build_cases()
_SLICE = __import__("os").environ.get("PTPU_SWEEP_SLICE")
if _SLICE:  # debugging aid: run a contiguous chunk, e.g. PTPU_SWEEP_SLICE=0:100
    _a, _b = map(int, _SLICE.split(":"))
    CASES = CASES[_a:_b]
_IDS = [c["name"] for c in CASES]
assert len(set(_IDS)) == len(_IDS), "duplicate case names"


def _make(c):
    class _C(OpTest):
        def config(self):
            self.op = c["op"]
            self.inputs = c["inputs"]
            self.attrs = c["attrs"]
            self.ref = c["ref"]
            self.rtol = c["rtol"]
            self.atol = c["atol"]
            self.check_static = c["static"]
            self.grad_rtol = c["grad_rtol"]
            self.grad_atol = c["grad_atol"]
    return _C()


@pytest.mark.parametrize("c", CASES, ids=_IDS)
def test_op_sweep(c):
    t = _make(c)
    t.check_output()


_GRAD_CASES = [c for c in CASES if c["grad"]]


@pytest.mark.heavy
@pytest.mark.parametrize("c", _GRAD_CASES,
                         ids=[c["name"] for c in _GRAD_CASES])
def test_op_sweep_grads(c):
    """Numeric-vs-analytic gradient tier (heavy: finite differences cost
    ~2 extra forwards per input element chunk)."""
    t = _make(c)
    t.check_grad(c["grad"])


# Under-jit waivers: cases whose EAGER path is fine but which cannot run
# inside an outer jax.jit, each with the reason. Cases with static=False
# are ALREADY excluded by the filter below (they declare a host fallback /
# concrete-value dependency — bincount's value-dependent output length,
# eig's CPU-only lowering, etc. live there); add entries here only for a
# static=True case that still cannot trace.
JIT_WAIVERS: dict = {}

_JIT_CASES = [c for c in CASES
              if c["static"] and c["name"].split("[")[0] not in JIT_WAIVERS]


@pytest.mark.heavy
@pytest.mark.parametrize("c", _JIT_CASES,
                         ids=[c["name"] for c in _JIT_CASES])
def test_op_sweep_under_jit(c):
    """Trace-safety tier (VERDICT r2 #5): every op runs inside an OUTER
    jax.jit — host fallbacks that materialize values fail here instead of
    inside a user's to_static/TrainStep program."""
    t = _make(c)
    t.check_jit()


# ---------------------------------------------------------------------------
# Coverage accounting: every public op is swept here or waived with a reason.
WAIVERS = {
    # --- stochastic ops: exact-output checks impossible; moments/dtype/shape
    #     covered in test_tensor_ops.py::test_rand_shapes and
    #     test_distribution.py
    "bernoulli": "stochastic", "multinomial": "stochastic",
    "poisson": "stochastic", "rand": "stochastic", "randn": "stochastic",
    "randint": "stochastic", "randperm": "stochastic", "uniform": "stochastic",
    "normal": "stochastic", "standard_normal": "stochastic",
    "rand_like": "stochastic", "randn_like": "stochastic",
    "randint_like": "stochastic", "exponential_": "stochastic in-place",
    "uniform_": "stochastic in-place", "normal_": "stochastic in-place",
    # --- in-place aliases of swept ops (same lowering; in-place semantics
    #     tested in test_tensor_ops.py)
    "reshape_": "in-place alias of reshape", "squeeze_": "alias of squeeze",
    "unsqueeze_": "alias of unsqueeze", "tanh_": "alias of tanh",
    "scatter_": "alias of scatter", "zero_": "alias of zeros_like",
    "fill_": "alias of full_like",
    # --- creation ops: no inputs to diff; output parity covered by
    #     test_tensor_ops.py::test_zeros_ones_full/test_arange_linspace_eye
    "zeros": "creation; test_tensor_ops", "ones": "creation; test_tensor_ops",
    "full": "creation; test_tensor_ops", "empty": "creation (= zeros)",
    "zeros_like": "creation; test_tensor_ops", "ones_like": "creation",
    "full_like": "creation", "empty_like": "creation",
    "arange": "creation; test_tensor_ops", "linspace": "creation",
    "logspace": "creation", "eye": "creation", "meshgrid": "creation",
    "tril_indices": "creation", "triu_indices": "creation",
    # --- python-side utilities / predicates (no kernel)
    "apply_op": "internal dispatch helper", "assign": "copy; trivially clone",
    "astype": "alias of cast", "clone": "identity copy",
    "convert_dtype": "dtype utility", "get_default_dtype": "dtype utility",
    "to_tensor": "constructor; test_tensor_ops", "tolist": "host transfer",
    "is_tensor": "predicate", "is_floating_point": "predicate",
    "is_integer": "predicate", "is_complex": "predicate",
    "is_empty": "predicate", "rank": "metadata", "shape": "metadata",
    "numel": "metadata", "broadcast_shape": "shape utility",
    # --- covered by dedicated deeper tests
    "norm": "swept as norm_fro; p-variants in test_tensor_ops::test_norm_trace",
    "unique": "dynamic shape; test_tensor_ops::test_sort_topk_unique",
    "unique_consecutive": "dynamic shape; test_tensor_ops",
    "pad": "swept as pad2 (core) and F.pad (functional)",
    "slice": "swept as slice_op",
    "softmax": "swept as F.softmax (same lowering)",
    "log_softmax": "swept as F.log_softmax (same lowering)",
}

F_WAIVERS = {
    "fused_conv_bn_act": "fused composite (r6 channels-last path); "
                         "conv/BN parity incl. fold covered in "
                         "test_channels_last",
    "clear_channels_last_weight_cache": "cache-management helper, not an "
                                        "op; exercised implicitly by "
                                        "test_channels_last",
    "dropout": "stochastic; p=0/eval identity in test_nn_extras",
    "dropout2d": "stochastic", "dropout3d": "stochastic",
    "alpha_dropout": "stochastic", "rrelu": "stochastic; test_nn_extras",
    "gumbel_softmax": "stochastic",
    "relu_": "in-place alias", "elu_": "in-place alias",
    "softmax_": "in-place alias", "tanh_": "in-place alias",
    "relu": "swept at core level", "softmax": "swept as F.softmax",
    "log_softmax": "swept as F.log_softmax",
    "ctc_loss": "dedicated test in test_sparse_quant_text_audio (Viterbi/CTC)",
    "rnnt_loss": "gated (explicit NotImplementedError; no TPU lowering yet)",
    "sparse_attention": "dedicated test in test_flash_attention",
    "margin_cross_entropy": "distributed op; test_distributed mpu coverage",
    "class_center_sample": "distributed sampling op; test_distributed",
    "hsigmoid_loss": "hierarchical softmax; dedicated test",
    "max_unpool1d": "same kernel as max_unpool2d (swept); shape variant",
    "max_unpool3d": "same kernel as max_unpool2d (swept); shape variant",
    "one_hot": "swept as F.one_hot",
    "sequence_mask": "swept as F.sequence_mask",
    "gather_tree": "swept as F.gather_tree",
    "apply_op": "internal dispatch helper (re-exported)",
    "convert_dtype": "dtype utility (re-exported)",
    "sigmoid": "swept at core level (same lowering)",
    "tanh": "swept at core level (same lowering)",
    "multi_margin_loss": "covered by test_nn_extras losses family",
    "triplet_margin_with_distance_loss":
        "covered by test_nn_extras::test_losses_and_misc",
}


def _core_surface():
    names = set()
    for n in dir(_ops):
        f = getattr(_ops, n)
        if not n.startswith("_") and inspect.isfunction(f):
            names.add(n)
    return names


def _functional_surface():
    import paddle_tpu.nn.functional as Fm
    names = set()
    for n in dir(Fm):
        f = getattr(Fm, n)
        if not n.startswith("_") and inspect.isfunction(f) \
                and f.__module__.startswith("paddle_tpu"):
            names.add(n)
    return names


def test_every_op_accounted():
    swept = set()
    for c in CASES:
        nm = c["name"]
        if nm.startswith("F."):
            swept.add(("F", nm[2:]))
        else:
            swept.add(("core", nm))
    core_swept = {n for k, n in swept if k == "core"}
    f_swept = {n for k, n in swept if k == "F"}
    # map sweep aliases back to op names
    alias = {"pad2": "pad", "slice_op": "slice", "norm_fro": "norm",
             "complex_op": "complex", "allclose_op": "allclose",
             "unfold_t": "unfold", "einsum": "einsum",
             "add_n": "add_n", "cast": "cast"}
    core_swept = {alias.get(n, n) for n in core_swept}

    missing_core = _core_surface() - core_swept - set(WAIVERS)
    missing_f = _functional_surface() - f_swept - set(F_WAIVERS)
    assert not missing_core, f"unswept, unwaived core ops: {sorted(missing_core)}"
    assert not missing_f, f"unswept, unwaived functional ops: {sorted(missing_f)}"
