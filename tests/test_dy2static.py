"""dy2static AST-transform tests (reference test model:
dygraph_to_static/ suite — run control-flow functions through @to_static and
compare against eager; SURVEY §4 "API/layer level").

The decisive property: ONE compiled signature serves BOTH branches / a
data-dependent trip count — trace-time unrolling would bake in the branch
taken by the first call.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.dy2static import (ast_transform, convert_ifelse,
                                      convert_while_loop)


def _eager_and_static(fn, *argsets):
    sf = paddle.jit.to_static(fn)
    for args in argsets:
        want = fn(*[paddle.to_tensor(a) for a in args])
        got = sf(*[paddle.to_tensor(a) for a in args])
        np.testing.assert_allclose(np.asarray(got._data), np.asarray(want._data),
                                   rtol=1e-5, atol=1e-6)
    return sf


class TestIfElse:
    def test_tensor_if_both_branches_one_compile(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y

        pos = np.ones((2, 3), np.float32)
        neg = -np.ones((2, 3), np.float32)
        sf = _eager_and_static(f, (pos,), (neg,))
        assert len(sf._cache) == 1  # same signature: lax.cond, not unroll

    def test_if_return_style(self):
        def f(x):
            if x.mean() > 0.5:
                return x * 10.0
            else:
                return x * 0.1

        hi = np.full((4,), 0.9, np.float32)
        lo = np.full((4,), 0.1, np.float32)
        _eager_and_static(f, (hi,), (lo,))

    def test_if_var_defined_single_branch(self):
        def f(x):
            y = x
            if x.sum() > 0:
                z = x * 3.0
                y = z
            return y + 0.0

        _eager_and_static(f, (np.ones(3, np.float32),),
                          (-np.ones(3, np.float32),))

    def test_concrete_predicate_untouched(self):
        def f(x, flag=True):
            if flag:
                return x + 1.0
            else:
                return x - 1.0

        sf = paddle.jit.to_static(f)
        out = sf(paddle.to_tensor(np.zeros(2, np.float32)))
        np.testing.assert_allclose(np.asarray(out._data), 1.0)

    def test_ternary(self):
        def f(x):
            y = x * 2.0 if x.sum() > 0 else x * -2.0
            return y

        _eager_and_static(f, (np.ones(3, np.float32),),
                          (-np.ones(3, np.float32),))

    def test_nested_tensor_if(self):
        def f(x):
            if x.sum() > 0:
                if x.max() > 1.0:
                    y = x * 2.0
                else:
                    y = x * 3.0
            else:
                y = -x
            return y

        _eager_and_static(f, (np.full(3, 2.0, np.float32),),
                          (np.full(3, 0.1, np.float32),),
                          (-np.ones(3, np.float32),))

    def test_int_promotes_to_float_in_while(self):
        def f(x):
            while x.sum() > 1.0:
                x = x / 2.0
            return x

        # int32 input: eager promotes to float via /, static must match
        got = paddle.jit.to_static(f)(paddle.to_tensor(np.array([8], np.int32)))
        want = f(paddle.to_tensor(np.array([8], np.int32)))
        np.testing.assert_allclose(np.asarray(got._data),
                                   np.asarray(want._data))

    def test_augassign_in_branch(self):
        def f(x):
            acc = x * 0.0
            if x.sum() > 0:
                acc += x
            else:
                acc -= x
            return acc

        _eager_and_static(f, (np.ones(3, np.float32),),
                          (-np.ones(3, np.float32),))


class TestGradThroughBranch:
    def test_untaken_branch_cannot_poison_grads(self):
        """Backward must differentiate only the taken branch: the untaken
        sqrt(negative) would contribute NaN if branches were traced outside
        lax.cond."""
        import paddle_tpu.nn as nn

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(3, 3)

            def forward(self, x):
                h = self.lin(x)
                if h.sum() > 0:
                    y = paddle.sqrt(h)
                else:
                    y = h * 2.0
                return y.sum()

        paddle.seed(3)
        m = M()
        # force h strictly negative: positive weights, zero bias, x < 0
        m.lin.weight.set_value(np.abs(m.lin.weight.numpy()) + 0.1)
        m.lin.bias.set_value(np.zeros(3, np.float32))
        x = paddle.to_tensor(-np.ones((1, 3), np.float32) * 5.0)
        h = m.lin(x)
        assert float(h.sum()) <= 0  # make sure we're on the *2 branch
        want = m(x)
        want.backward()
        ref = m.lin.weight.grad.numpy().copy()
        assert np.isfinite(ref).all()
        m.clear_gradients()

        sm = paddle.jit.to_static(M())
        sm.set_state_dict(m.state_dict())
        out = sm(x)
        out.backward()
        got = sm.lin.weight.grad.numpy()
        assert np.isfinite(got).all(), "NaN leaked from the untaken branch"
        np.testing.assert_allclose(got, ref, rtol=1e-5)


def _late_helper(x):
    return x * 0.0  # overwritten below — ast_transform must see live globals


@paddle.jit.to_static
def _uses_late_global(x):
    if x.sum() > 0:
        return _late_helper(x)
    else:
        return x


def _late_helper(x):  # noqa: F811 — the live binding
    return x + 10.0


class TestLiveGlobals:
    def test_transformed_fn_sees_rebound_global(self):
        out = _uses_late_global(paddle.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(out._data), 11.0)


class TestWhile:
    def test_data_dependent_trip_count(self):
        def f(x):
            while x.sum() > 1.0:
                x = x / 2.0
            return x

        _eager_and_static(f, (np.full((4,), 8.0, np.float32),),
                          (np.full((4,), 0.1, np.float32),))

    def test_counter_loop(self):
        def f(x, n):
            i = paddle.to_tensor(np.int32(0))
            while i < n:
                x = x + 1.0
                i = i + 1
            return x

        _eager_and_static(f, (np.zeros(2, np.float32), np.int32(5)),
                          (np.zeros(2, np.float32), np.int32(0)))


class TestLogical:
    def test_and_or_not(self):
        def f(x):
            if (x.sum() > 0) and (x.max() < 10.0):
                return x + 1.0
            else:
                return x - 1.0

        _eager_and_static(f, (np.ones(3, np.float32),),
                          (np.full(3, 20.0, np.float32),),
                          (-np.ones(3, np.float32),))

    def test_short_circuit_python(self):
        # concrete lhs False must NOT evaluate rhs (python semantics)
        calls = []

        def rhs():
            calls.append(1)
            return True

        from paddle_tpu.jit.dy2static import convert_logical_and
        out = convert_logical_and(lambda: False, rhs)
        assert out is False and not calls


class TestRuntimeDirect:
    def test_convert_ifelse_concrete(self):
        assert convert_ifelse(True, lambda: 1, lambda: 2) == 1
        assert convert_ifelse(False, lambda: 1, lambda: 2) == 2

    def test_convert_while_concrete(self):
        out = convert_while_loop(lambda i: i < 3, lambda i: (i + 1,), (0,))
        assert out == (3,)

    def test_transform_preserves_plain_functions(self):
        def g(a, b):
            return a + b

        tg = ast_transform(g)
        assert tg(1, 2) == 3


class TestLayerControlFlow:
    def test_layer_with_tensor_branch(self):
        import paddle_tpu.nn as nn

        class Gate(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)

            def forward(self, x):
                h = self.lin(x)
                if h.sum() > 0:
                    out = h * 2.0
                else:
                    out = -h
                return out

        paddle.seed(0)
        m = Gate()
        m.eval()
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        want = m(x)
        m2 = Gate()
        m2.set_state_dict(m.state_dict())
        m2.eval()
        sm2 = paddle.jit.to_static(m2)
        got = sm2(x)
        np.testing.assert_allclose(np.asarray(got._data),
                                   np.asarray(want._data), rtol=1e-5)


class TestForRange:
    def test_static_range_matches_eager(self):
        def f(x):
            acc = x * 0.0
            for i in range(3):
                acc = acc + x * float(i + 1)
            return acc

        _eager_and_static(f, (np.ones(2, np.float32),))

    def test_tensor_trip_count(self):
        """range(tensor) would raise under plain tracing; the For rewrite
        lowers it to lax.while_loop — one compile serves both counts."""
        def f(x, n):
            acc = x * 0.0
            for _i in range(n):
                acc = acc + x
            return acc

        sf = paddle.jit.to_static(f)
        x = np.ones(2, np.float32)
        o3 = sf(paddle.to_tensor(x), paddle.to_tensor(np.int32(3)))
        o5 = sf(paddle.to_tensor(x), paddle.to_tensor(np.int32(5)))
        np.testing.assert_allclose(np.asarray(o3._data), 3.0)
        np.testing.assert_allclose(np.asarray(o5._data), 5.0)
        assert len(sf._cache) == 1

    def test_range_start_step(self):
        def f(x):
            acc = x * 0.0
            for i in range(2, 8, 2):
                acc = acc + float(i)
            return acc

        _eager_and_static(f, (np.zeros(2, np.float32),))

    def test_nonrange_for_untouched(self):
        def f(x):
            acc = x * 0.0
            for v in [1.0, 2.0]:
                acc = acc + v
            return acc

        _eager_and_static(f, (np.zeros(2, np.float32),))


class TestForSemantics:
    def test_loop_var_final_value_matches_python(self):
        def f(x):
            i = -1.0
            for i in range(3):
                x = x + 1.0
            return x * float(i)

        _eager_and_static(f, (np.ones(2, np.float32),))

    def test_zero_iteration_keeps_prior_binding(self):
        def f(x):
            i = 7
            for i in range(0):
                x = x + 100.0
            return x + float(i)

        _eager_and_static(f, (np.zeros(2, np.float32),))

    def test_negative_step(self):
        def f(x):
            acc = x * 0.0
            for i in range(5, 0, -2):
                acc = acc + float(i)
            return acc

        _eager_and_static(f, (np.zeros(2, np.float32),))
