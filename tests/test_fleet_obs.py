"""Fleet-scope observability (ISSUE 13): exposition merge math against a
pooled-numpy oracle, FleetAggregator staleness/degrade semantics over live
servers, the server-owned SLO poll timer, the per-collective ledger from
the checked-in trace fixture, and the shard-wall straggler state machine —
including a real 2-process CPU-mesh run with an injected slow shard."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.obs import (CollectiveLedger, FleetAggregator,
                            FleetMergeError, MetricsRegistry,
                            TelemetryServer, TraceBuffer, bucket_percentile,
                            feed_shard_walls, lint_exposition,
                            load_shard_walls, merge_exposition)
from paddle_tpu.obs.fleet import _grid_consistent
from paddle_tpu.profiler._metrics import (LogHistogram, counter_lines,
                                          gauge_lines, histogram_lines)
from paddle_tpu.profiler.monitor import StepMonitor

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _hist_page(name, hist, prefix="t", extra_lines=()):
    lines = list(extra_lines) + histogram_lines(prefix, name, hist,
                                                f"{name} help")
    return "\n".join(lines) + "\n"


def _parse_hist(families, full_name):
    fam = families[full_name]
    buckets, count = [], 0.0
    for base, labels, val in fam["samples"]:
        if base.endswith("_bucket"):
            le = labels[1:-1].split("=", 1)[1].strip('"')
            buckets.append((float("inf") if le == "+Inf" else float(le),
                            float(val)))
        elif base.endswith("_count"):
            count = float(val)
    return sorted(buckets), count


class TestExpositionMerge:
    """merge_exposition: counters sum, gauges label, histograms pool."""

    def test_counters_summed_across_replicas(self):
        pages = {f"r{i}": "\n".join(counter_lines(
            "t", "requests_total", 10 * (i + 1), "reqs")) + "\n"
            for i in range(3)}
        fams = lint_exposition(merge_exposition(pages))
        assert fams["t_requests_total"]["samples"] == [
            ("t_requests_total", "", "60")]

    def test_gauges_labeled_not_summed(self):
        pages = {f"r{i}": "\n".join(gauge_lines(
            "t", "queue_depth", i + 1, "depth")) + "\n" for i in range(2)}
        fams = lint_exposition(merge_exposition(pages))
        samples = fams["t_queue_depth"]["samples"]
        assert {(s[1], s[2]) for s in samples} == {
            ('{replica="r0"}', "1"), ('{replica="r1"}', "2")}

    def test_labeled_gauge_keeps_its_labels(self):
        page = ('# HELP t_burn burn\n# TYPE t_burn gauge\n'
                't_burn{target="ttft",window="long"} 0.5\n')
        fams = lint_exposition(merge_exposition({"rA": page}))
        assert fams["t_burn"]["samples"][0][1] == \
            '{replica="rA",target="ttft",window="long"}'

    def test_merged_histogram_percentiles_match_pooled_numpy_oracle(self):
        rng = np.random.RandomState(7)
        streams = [rng.lognormal(-2.0, 0.7, 400),
                   rng.lognormal(-1.2, 0.4, 250),
                   rng.lognormal(-2.5, 1.0, 150)]
        pages, pooled_hist = {}, LogHistogram(per_decade=10)
        for i, s in enumerate(streams):
            h = LogHistogram(per_decade=10)
            for v in s:
                h.observe(v)
                pooled_hist.observe(v)
            pages[f"r{i}"] = _hist_page("e2e_seconds", h)
        merged = merge_exposition(pages)
        fams = lint_exposition(merged)
        buckets, count = _parse_hist(fams, "t_e2e_seconds")
        pooled = np.concatenate(streams)
        assert count == pooled.size
        ratio = 10 ** (1 / 10)          # one bucket of relative error
        for q in (0.5, 0.9, 0.99):
            got = bucket_percentile(buckets, count, q)
            # exact vs the pooled histogram's own bucket estimate (same
            # buckets, same counts — only min/max clamping can differ)
            want_hist = pooled_hist.percentile(q)
            assert got == pytest.approx(want_hist, rel=0.27)
            # and within bucket resolution of the raw numpy stream
            want_np = float(np.percentile(pooled, q * 100))
            assert want_np / ratio ** 2 <= got <= want_np * ratio ** 2

    def test_histogram_sum_and_count_added(self):
        h1, h2 = LogHistogram(per_decade=10), LogHistogram(per_decade=10)
        for v in (0.1, 0.2):
            h1.observe(v)
        h2.observe(0.4)
        fams = lint_exposition(merge_exposition(
            {"a": _hist_page("e2e_seconds", h1),
             "b": _hist_page("e2e_seconds", h2)}))
        fam = fams["t_e2e_seconds"]
        total = [v for b, _, v in fam["samples"]
                 if b == "t_e2e_seconds_sum"][0]
        assert float(total) == pytest.approx(0.7)
        _, count = _parse_hist(fams, "t_e2e_seconds")
        assert count == 3

    def test_empty_and_blank_pages_contribute_nothing(self):
        h = LogHistogram(per_decade=10)
        h.observe(0.1)
        pages = {"live": _hist_page("e2e_seconds", h), "young": "",
                 "blank": "   \n"}
        fams = lint_exposition(merge_exposition(pages))
        _, count = _parse_hist(fams, "t_e2e_seconds")
        assert count == 1

    def test_partial_replica_missing_family_is_fine(self):
        h = LogHistogram(per_decade=10)
        h.observe(0.1)
        pages = {"a": _hist_page("e2e_seconds", h),
                 "b": "\n".join(counter_lines("t", "requests_total", 5,
                                              "reqs")) + "\n"}
        fams = lint_exposition(merge_exposition(pages))
        _, count = _parse_hist(fams, "t_e2e_seconds")
        assert count == 1
        assert fams["t_requests_total"]["samples"][0][2] == "5"

    def test_mismatched_bucket_layouts_rejected_structured(self):
        good = LogHistogram(lo=1e-4, per_decade=10)
        bad = LogHistogram(lo=1.5e-4, per_decade=10)   # shifted grid
        for v in (0.003, 0.02, 0.4):
            good.observe(v)
            bad.observe(v * 1.1)
        with pytest.raises(FleetMergeError) as ei:
            merge_exposition({"a": _hist_page("e2e_seconds", good),
                              "b": _hist_page("e2e_seconds", bad)})
        err = ei.value
        assert err.family == "t_e2e_seconds"
        assert err.replicas == ["a", "b"]
        assert "layout" in err.detail
        assert err.to_dict()["error"] == "fleet_merge"

    def test_type_disagreement_rejected(self):
        pages = {"a": "# HELP t_x x\n# TYPE t_x gauge\nt_x 1\n",
                 "b": "# HELP t_x x\n# TYPE t_x counter\nt_x 2\n"}
        with pytest.raises(FleetMergeError):
            merge_exposition(pages)

    def test_non_linting_member_page_named(self):
        with pytest.raises(FleetMergeError) as ei:
            merge_exposition({"broken": "t_x 1\n"})   # sample, no TYPE
        assert ei.value.replicas == ["broken"]

    def test_grid_consistency_rules(self):
        g10 = [1e-4 * 10 ** (k / 10) for k in range(0, 40, 3)]
        g20 = [1e-4 * 10 ** (k / 20) for k in range(1, 50, 7)]
        assert _grid_consistent(g10)
        assert _grid_consistent(sorted(set(g10 + g20)))  # nested refines
        assert _grid_consistent([0.5, 1.5, 3.5, 7.5])    # arithmetic
        shifted = sorted(set(
            g10[:5] + [1.5e-4 * 10 ** (k / 10) for k in range(2, 20, 5)]))
        assert not _grid_consistent(shifted)
        mixed = sorted(set([1e-2 * 10 ** (k / 10) for k in range(0, 12, 2)]
                           + [0.5, 1.5, 2.5]))
        assert not _grid_consistent(mixed)

    def test_bucket_percentile_empty(self):
        assert bucket_percentile([], 0, 0.99) is None


def _page_producer(i):
    def produce():
        return "\n".join(
            counter_lines("s", "requests_total", 10 * (i + 1), "reqs")
            + gauge_lines("s", "queue_depth", i, "depth")) + "\n"
    return produce


def _mk_server(i, health=None, tracez=None, broken=False):
    reg = MetricsRegistry()
    if broken:
        def produce():
            raise RuntimeError("boom")
        reg.register("m", produce)
    else:
        reg.register("m", _page_producer(i))
    return TelemetryServer(reg, health=health, status=lambda: {"i": i},
                           tracez=tracez).start()


class TestFleetAggregator:
    def test_merge_staleness_and_rejoin(self):
        def health(n, draining=False):
            return lambda: {"status": "draining" if draining else "ok",
                            "draining": draining, "queue_depth": n,
                            "queue_capacity": 8, "inflight": 1,
                            "overloaded_total": 2 * n,
                            "rejected_total": 0}
        srvs = [_mk_server(i, health=health(i)) for i in range(3)]
        try:
            # cache_ttl=0: this test asserts scrape-to-scrape staleness
            # transitions; the TTL cache would serve pre-kill snapshots
            fleet = FleetAggregator(
                {f"r{i}": s for i, s in enumerate(srvs)}, timeout=1.0,
                cache_ttl=0.0)
            page = fleet.merged_metrics()
            lint_exposition(page)
            assert "s_requests_total 60" in page
            assert 'paddle_tpu_fleet_replicas{state="stale"} 0' in page
            h = fleet.fleet_healthz()
            assert (h["status"], h["serving"], h["queue_depth"],
                    h["overloaded_total"]) == ("ok", 3, 3, 6)
            # kill r1: stale + degraded around, never an exception
            srvs[1].close()
            page = fleet.merged_metrics()
            lint_exposition(page)
            assert "s_requests_total 40" in page
            assert 'paddle_tpu_fleet_up{replica="r1"} 0' in page
            h = fleet.fleet_healthz()
            assert h["serving"] == 2 and h["stale"] == 1
            assert h["per_replica"]["r1"]["state"] == "stale"
            assert h["per_replica"]["r1"]["consecutive_failures"] >= 1
            # a replacement replica rejoins under a fresh name
            assert fleet.remove_replica("r1")
            srv_new = _mk_server(1, health=health(1))
            srvs.append(srv_new)
            fleet.add_replica("r1b", srv_new)
            page = fleet.merged_metrics()
            assert "s_requests_total 60" in page
            assert fleet.fleet_healthz()["serving"] == 3
        finally:
            for s in srvs:
                try:
                    s.close()
                except Exception:
                    pass

    def test_draining_member_counted_not_stale(self):
        # a draining replica answers /healthz with 503 + the JSON body;
        # the rollup must read the body, not mark the member dead
        srv = _mk_server(0, health=lambda: {
            "status": "draining", "draining": True, "queue_depth": 4,
            "queue_capacity": 8, "inflight": 2, "overloaded_total": 1,
            "rejected_total": 3})
        try:
            fleet = FleetAggregator({"d": srv}, timeout=1.0)
            h = fleet.fleet_healthz()
            assert h["draining"] == 1 and h["stale"] == 0
            assert h["status"] == "unserviceable"   # zero members serving
            assert h["queue_depth"] == 4
        finally:
            srv.close()

    def test_broken_member_metrics_degrades_not_500(self):
        srvs = [_mk_server(0), _mk_server(1, broken=True)]
        try:
            fleet = FleetAggregator(
                {"ok": srvs[0], "broken": srvs[1]}, timeout=1.0)
            page = fleet.merged_metrics()
            lint_exposition(page)
            assert "s_requests_total 10" in page
            assert 'paddle_tpu_fleet_up{replica="broken"} 0' in page
        finally:
            for s in srvs:
                s.close()

    def test_fleet_server_routes_and_tracez_merge(self):
        from urllib.request import urlopen
        bufs = [TraceBuffer(capacity=8) for _ in range(2)]
        recs = [
            {"id": 1, "status": "done", "trace_id": "aaa-1", "e2e_s": 0.5},
            {"id": 2, "status": "done", "trace_id": "aaa-2", "e2e_s": 0.1},
            {"id": 1, "status": "timeout", "trace_id": "bbb-1",
             "e2e_s": None},
            # a trace_id seen by BOTH members must merge to one row
            {"id": 2, "status": "done", "trace_id": "aaa-2",
             "e2e_s": 0.1},
        ]
        bufs[0].add(recs[0]).add(recs[1])
        bufs[1].add(recs[2]).add(recs[3])
        srvs = [_mk_server(i, tracez=bufs[i]) for i in range(2)]
        fsrv = None
        try:
            fleet = FleetAggregator(
                {f"r{i}": s for i, s in enumerate(srvs)}, timeout=1.0)
            tz = fleet.fleet_tracez({"order": "slowest"})
            ids = [t["trace_id"] for t in tz["traces"]]
            assert ids[0] == "aaa-1"          # slowest first
            assert ids.count("aaa-2") == 1    # deduped on trace_id
            assert {t["replica"] for t in tz["traces"]} == {"r0", "r1"}
            assert tz["summary"]["answered"] == 2
            # and over HTTP through the fleet server's extra routes
            fsrv = fleet.serve()
            body = json.loads(urlopen(
                fsrv.url("/fleet/tracez?order=slowest&limit=2"),
                timeout=5).read())
            assert len(body["traces"]) == 2
            assert body["traces"][0]["trace_id"] == "aaa-1"
            h = json.loads(urlopen(fsrv.url("/fleet/healthz"),
                                   timeout=5).read())
            assert h["replicas"] == 2
            mx = urlopen(fsrv.url("/metrics"), timeout=5).read().decode()
            lint_exposition(mx)
            assert "s_requests_total 30" in mx
            # malformed client input on an extra route is a 400, not the
            # 500 a monitor would page on as an aggregator failure
            from urllib.error import HTTPError
            with pytest.raises(HTTPError) as ei:
                urlopen(fsrv.url("/fleet/tracez?limit=abc"), timeout=5)
            assert ei.value.code == 400
        finally:
            if fsrv is not None:
                fsrv.close()
            for s in srvs:
                s.close()

    def test_fleet_healthz_503_when_no_member_serves(self):
        from urllib.error import HTTPError
        from urllib.request import urlopen
        srv = _mk_server(0)     # no health fn -> scrape of /healthz is
        fsrv = None             # the default {"status": "ok"} ... so use
        try:                    # a dead member instead
            fleet = FleetAggregator({"r0": srv}, timeout=0.5)
            srv.close()
            fsrv = fleet.serve()
            with pytest.raises(HTTPError) as ei:
                urlopen(fsrv.url("/healthz"), timeout=5)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["status"] == "unserviceable"
            assert body["stale"] == 1
        finally:
            if fsrv is not None:
                fsrv.close()
            try:
                srv.close()
            except Exception:
                pass


class TestScrapeTTLCache:
    """ISSUE 14 satellite: the scrape-storm guard — member scrapes are
    cached per route for cache_ttl seconds, so N fleet-page clients cost
    the members ONE scrape per window; 0 disables; membership changes
    invalidate; staleness bookkeeping untouched by cached responses."""

    def _counting_server(self):
        calls = [0]
        reg = MetricsRegistry()

        def produce():
            calls[0] += 1
            return "\n".join(
                counter_lines("s", "requests_total", 10, "reqs")) + "\n"

        reg.register("m", produce)
        return TelemetryServer(reg).start(), calls

    def test_ttl_collapses_scrape_storm(self):
        srv, calls = self._counting_server()
        try:
            fleet = FleetAggregator({"r0": srv}, timeout=1.0,
                                    cache_ttl=30.0)
            pages = [fleet.merged_metrics() for _ in range(5)]
            assert calls[0] == 1            # 5 clients, ONE member scrape
            assert fleet.scrape_cache_hits_total == 4
            assert all("s_requests_total 10" in p for p in pages)
            assert "scrape_cache_hits_total 4" in pages[-1]
            # a different route is a different cache entry
            fleet.fleet_healthz()
            assert fleet.scrape_cache_hits_total == 4
            fleet.fleet_healthz()
            assert fleet.scrape_cache_hits_total == 5
            assert fleet.fleet_statusz()["scrape_cache_hits_total"] == 5
        finally:
            srv.close()

    def test_back_to_back_fleet_scrapes_hit_each_member_once(self):
        """ISSUE 17 satellite pin: with the default ~1s TTL, TWO
        back-to-back fleet scrapes cost every member exactly ONE
        /metrics request — the second page is served from cache."""
        sa, calls_a = self._counting_server()
        sb, calls_b = self._counting_server()
        try:
            fleet = FleetAggregator({"r0": sa, "r1": sb}, timeout=1.0)
            assert fleet.cache_ttl == 1.0       # the default guard
            page1 = fleet.merged_metrics()
            page2 = fleet.merged_metrics()
            assert (calls_a[0], calls_b[0]) == (1, 1)
            assert fleet.scrape_cache_hits_total == 1
            assert "s_requests_total 20" in page1
            assert "s_requests_total 20" in page2
        finally:
            sa.close()
            sb.close()

    def test_ttl_zero_disables(self):
        srv, calls = self._counting_server()
        try:
            fleet = FleetAggregator({"r0": srv}, timeout=1.0,
                                    cache_ttl=0.0)
            fleet.merged_metrics()
            fleet.merged_metrics()
            assert calls[0] == 2
            assert fleet.scrape_cache_hits_total == 0
        finally:
            srv.close()

    def test_ttl_expires(self):
        srv, calls = self._counting_server()
        try:
            fleet = FleetAggregator({"r0": srv}, timeout=1.0,
                                    cache_ttl=0.05)
            fleet.merged_metrics()
            time.sleep(0.06)
            fleet.merged_metrics()
            assert calls[0] == 2
        finally:
            srv.close()

    def test_membership_change_invalidates(self):
        srv, calls = self._counting_server()
        srv2, calls2 = self._counting_server()
        try:
            fleet = FleetAggregator({"r0": srv}, timeout=1.0,
                                    cache_ttl=30.0)
            assert "s_requests_total 10" in fleet.merged_metrics()
            fleet.add_replica("r1", srv2)
            # the fresh member shows up on the VERY next scrape — the
            # cache was invalidated, both members scraped once more
            assert "s_requests_total 20" in fleet.merged_metrics()
            assert (calls[0], calls2[0]) == (2, 1)
            fleet.remove_replica("r1")
            assert "s_requests_total 10" in fleet.merged_metrics()
        finally:
            srv.close()
            srv2.close()

    def test_cached_scrape_never_touches_staleness(self):
        """A member dying inside the TTL window stays 'live' until the
        cache expires — cached responses must not mark_ok a corpse, and
        the first REAL scrape after expiry degrades it."""
        srv, _ = self._counting_server()
        fleet = FleetAggregator({"r0": srv}, timeout=0.5,
                                cache_ttl=0.2)
        try:
            fleet.merged_metrics()
            srv.close()
            fleet.merged_metrics()                     # cached: still ok
            assert not fleet.replica_states()["r0"]["stale"]
            time.sleep(0.25)
            fleet.merged_metrics()                     # real: degrades
            assert fleet.replica_states()["r0"]["stale"]
        finally:
            srv.close()
            fleet.close()


class TestServerPoller:
    def test_poller_runs_and_stops_with_server(self):
        calls = []
        srv = TelemetryServer(MetricsRegistry())
        srv.add_poller(lambda: calls.append(time.monotonic()), 0.02,
                       name="tick")
        srv.start()
        deadline = time.time() + 5.0
        while len(calls) < 3 and time.time() < deadline:
            time.sleep(0.01)
        assert len(calls) >= 3
        srv.close()
        n = len(calls)
        time.sleep(0.08)
        assert len(calls) == n          # thread died with the server
        assert srv.pollers[0]["polls"] >= 3

    def test_poller_survives_exceptions(self):
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] == 1:
                raise RuntimeError("transient")
        srv = TelemetryServer(MetricsRegistry())
        srv.add_poller(flaky, 0.02, name="flaky")
        srv.start()
        deadline = time.time() + 5.0
        while state["n"] < 3 and time.time() < deadline:
            time.sleep(0.01)
        srv.close()
        assert state["n"] >= 3
        rec = srv.pollers[0]
        assert rec["errors"] == 1 and rec["polls"] >= 2

    def test_bad_interval_rejected(self):
        srv = TelemetryServer(MetricsRegistry())
        with pytest.raises(ValueError):
            srv.add_poller(lambda: None, 0)
        srv.close()


@pytest.fixture(scope="module")
def toy_engine():
    import paddle_tpu as paddle
    from paddle_tpu.inference import ServingConfig, ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=32,
                    intermediate_size=64)
    model = GPTForCausalLM(cfg)
    model.eval()
    eng = ServingEngine(model, ServingConfig(
        max_batch=2, prompt_cap=8, max_new_tokens=4))
    rng = np.random.RandomState(0)
    for _ in range(2):
        eng.submit(rng.randint(1, 64, (5,)).astype(np.int64))
    eng.drain()
    return eng


class TestSLOServerTimer:
    """The r15 NOTE follow-up: serve_telemetry owns the poll cadence."""

    def test_server_side_poll_timer_drives_burn_eval(self, toy_engine):
        srv = toy_engine.serve_telemetry(
            slo="e2e_p99=60s,goodput=0.5", poll_interval=0.03)
        try:
            assert srv.slo is not None
            deadline = time.time() + 5.0
            while not srv.slo._snaps and time.time() < deadline:
                time.sleep(0.01)
            # burn evaluation happened with NO external poll() driver
            assert srv.slo._snaps
            assert srv.pollers[0]["name"] == "slo"
            assert srv.pollers[0]["polls"] >= 1
            # the slo block rides the scrape page
            from urllib.request import urlopen
            text = urlopen(srv.url("/metrics"), timeout=5).read().decode()
            lint_exposition(text)
            assert "paddle_tpu_slo_alerts_total" in text
        finally:
            srv.close()
        n_snaps = len(srv.slo._snaps)
        time.sleep(0.1)
        assert len(srv.slo._snaps) == n_snaps   # timer stopped with server

    def test_poll_interval_without_slo_rejected(self, toy_engine):
        with pytest.raises(ValueError):
            toy_engine.serve_telemetry(poll_interval=1.0)


class TestCollectiveLedger:
    def test_rows_from_checked_in_fixture(self):
        ledger = CollectiveLedger.from_trace(FIXTURES, steps=2)
        assert len(ledger.rows) == 1
        r = ledger.rows[0]
        assert r["name"] == "all-reduce.3" and r["calls"] == 2
        assert r["dur_us"] == 200 and r["busy_us"] == 200
        # per step: all-reduce [450,550) overlaps convolution [300,500)
        # by 50us -> half the collective time is EXPOSED
        assert r["overlapped_us"] == 100 and r["exposed_us"] == 100
        assert r["exposed_frac"] == pytest.approx(0.5)
        # 2 x 1 MiB at 100us busy each -> ~10.5 GB/s bus bandwidth
        assert r["bytes"] == 2 * 1048576
        assert r["bus_gbps"] == pytest.approx(10.48576)
        # the ledger IS the decomposition of the overlap gauge
        assert ledger.overlap["ratio"] == pytest.approx(0.5)
        t = ledger.totals()
        assert t["exposed_frac"] == pytest.approx(0.5)

    def test_table_and_exposition_render(self):
        ledger = CollectiveLedger.from_trace(FIXTURES, steps=2)
        table = ledger.table()
        assert "all-reduce.3" in table and "GB/s" in table
        assert "exposed" in table
        text = ledger.metrics_text()
        fams = lint_exposition(text)
        assert 'paddle_tpu_comm_collective_exposed_seconds' in fams
        sample = [s for s in fams[
            "paddle_tpu_comm_collective_bus_gbps"]["samples"]][0]
        assert sample[1] == '{op="all-reduce.3"}'

    def test_registry_composes_ledger_with_monitor(self):
        # the collision case the docstring promises away: a monitor that
        # ADOPTED the same rows and a standalone ledger on one page
        mon = StepMonitor(track_memory=False)
        ledger = CollectiveLedger.from_trace(FIXTURES)
        mon.record_collectives(ledger.rows)
        reg = MetricsRegistry()
        reg.register("monitor", mon.metrics_text)
        reg.register("collectives", ledger.metrics_text)
        fams = lint_exposition(reg.render())
        assert "paddle_tpu_collective_seconds" in fams          # monitor
        assert "paddle_tpu_comm_collective_seconds" in fams     # ledger

    def test_bytes_absent_renders_unknown(self):
        events = [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "X", "pid": 1, "tid": 0, "name": "all-gather.9",
             "ts": 0, "dur": 100},
            {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1",
             "ts": 0, "dur": 50}]
        ledger = CollectiveLedger.from_trace(events)
        r = ledger.rows[0]
        assert r["bytes"] is None and r["bus_gbps"] is None
        assert r["overlapped_us"] == 50 and r["exposed_us"] == 50
        assert "-" in ledger.table()
        lint_exposition(ledger.metrics_text())

    def test_monitor_adopts_ledger_rows(self):
        mon = StepMonitor(track_memory=False)
        ledger = CollectiveLedger.from_trace(FIXTURES, steps=2)
        mon.record_collectives(ledger.rows)
        mon.record_overlap(ledger.overlap)
        rep = mon.report()
        assert rep["overlap_ratio"] == pytest.approx(0.5)
        assert rep["collectives"][0]["name"] == "all-reduce.3"
        assert rep["collectives"][0]["exposed_ms"] == pytest.approx(0.1)
        text = mon.metrics_text()
        lint_exposition(text)
        assert 'paddle_tpu_collective_seconds{op="all-reduce.3"}' in text

    def test_distributed_view_renders_ledger_columns(self):
        from paddle_tpu.profiler.trace_analysis import analyze
        view = analyze(FIXTURES, steps=2).distributed_view()
        assert "exposed" in view and "GB/s" in view
        assert "overlap ratio 0.50" in view


class TestStragglerStateMachine:
    def test_single_event_per_sustained_straggler(self):
        rows = []
        mon = StepMonitor(track_memory=False, on_report=rows.append,
                          straggler_threshold=1.5)
        for step in range(8):
            slow = 0.03 if step >= 3 else 0.01
            mon.record_shard_steps({"0": 0.01, "1": 0.01, "2": slow},
                                   step=step)
        events = [r for r in rows if "straggler" in r]
        assert len(events) == 1                  # transition, not per-step
        ev = events[0]["straggler"]
        assert ev["slowest_shard"] == "2"
        assert ev["skew_ratio"] == pytest.approx(3.0)
        assert mon.stragglers_total == 1 and mon.straggling

    def test_clear_event_on_recovery(self):
        rows = []
        mon = StepMonitor(track_memory=False, on_report=rows.append)
        mon.record_shard_steps({"0": 0.01, "1": 0.05}, step=0)
        mon.record_shard_steps({"0": 0.01, "1": 0.011}, step=1)
        kinds = [next(iter(r)) for r in rows]
        assert kinds == ["straggler", "straggler_clear"]
        assert not mon.straggling and mon.stragglers_total == 1

    def test_two_shard_skew_uses_other_shard_baseline(self):
        mon = StepMonitor(track_memory=False)
        skew = mon.record_shard_steps({"0": 0.01, "1": 0.025})
        assert skew["skew_ratio"] == pytest.approx(2.5)
        assert skew["slowest_shard"] == "1"

    def test_even_rest_uses_true_median(self):
        # 3 shards -> 2-element baseline: the TRUE median (mean of the
        # middle pair), not the upper element — review regression pin
        # (upper-middle read 2.0/1.4 = 1.43 and never fired at 1.5)
        rows = []
        mon = StepMonitor(track_memory=False, on_report=rows.append,
                          straggler_threshold=1.5)
        skew = mon.record_shard_steps({"0": 1.0, "1": 1.4, "2": 2.0},
                                      step=0)
        assert skew["skew_ratio"] == pytest.approx(2.0 / 1.2)
        assert mon.straggling and len(rows) == 1

    def test_single_shard_never_straggles(self):
        mon = StepMonitor(track_memory=False)
        mon.record_shard_steps({"0": 5.0}, step=0)
        assert not mon.straggling and mon.stragglers_total == 0

    def test_gauges_in_exposition(self):
        mon = StepMonitor(track_memory=False)
        mon.record_shard_steps({"0": 0.01, "1": 0.04}, step=0)
        text = mon.metrics_text()
        lint_exposition(text)
        assert 'paddle_tpu_shard_step_seconds{shard="1"} 0.04' in text
        assert "paddle_tpu_shard_skew_ratio 4" in text
        assert "paddle_tpu_slowest_shard 1" in text
        assert "paddle_tpu_straggling 1" in text

    def test_counter_survives_state_dict_roundtrip(self):
        mon = StepMonitor(track_memory=False)
        mon.record_shard_steps({"0": 0.01, "1": 0.05}, step=0)
        fresh = StepMonitor(track_memory=False)
        fresh.set_state_dict(mon.state_dict())
        assert fresh.stragglers_total == 1

    def test_stitch_and_feed_from_jsonl(self, tmp_path):
        for shard in range(2):
            mon = StepMonitor(track_memory=False, jsonl_path=str(
                tmp_path / f"shard_{shard}.jsonl"))
            for step in range(5):
                wall = 0.04 if shard == 1 and step >= 2 else 0.01
                mon.end_step(wall_s=wall)
        # shard 0 ran one extra (incomplete) step: must be skipped
        mon0 = StepMonitor(track_memory=False, jsonl_path=str(
            tmp_path / "shard_0.jsonl"))
        mon0._steps = 5
        mon0.end_step(wall_s=0.01)
        walls = load_shard_walls(str(tmp_path))
        assert set(walls) == {1, 2, 3, 4, 5, 6}
        assert walls[1] == {"0": 0.01, "1": 0.01}
        rows = []
        agg = StepMonitor(track_memory=False, on_report=rows.append)
        fed = feed_shard_walls(agg, walls)
        assert len(fed) == 5                    # step 6 incomplete
        events = [r for r in rows if "straggler" in r]
        assert len(events) == 1
        assert events[0]["straggler"]["slowest_shard"] == "1"


_WORKER = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
sys.path.insert(0, {repo!r})
import jax
import jax.numpy as jnp
from paddle_tpu.distributed import build_mesh, shard_identity
from paddle_tpu.profiler import StepMonitor

shard, world = shard_identity()
assert world == 2, world
mesh = build_mesh({{"dp": 2}})          # each process runs the same
#                                         2-shard CPU-mesh program —
#                                         single-controller SPMD's shape
from jax.sharding import NamedSharding, PartitionSpec as P
x = jax.device_put(jnp.ones((4, 64)), NamedSharding(mesh, P("dp", None)))
step = jax.jit(lambda a: (a @ a.T).sum())
step(x).block_until_ready()             # warm up outside the timing
mon = StepMonitor(track_memory=False,
                  jsonl_path=os.path.join({out!r}, f"shard_{{shard}}.jsonl"))
for i in range(6):
    mon.begin_step()
    step(x).block_until_ready()
    time.sleep(0.01)                    # floor the step wall so scheduler
    #                                     jitter stays well under threshold
    if shard == 1 and i >= 2:
        time.sleep(0.08)                # the injected slow shard
    mon.end_step()
print("worker", shard, "done")
"""


@pytest.mark.parametrize("nshards", [2])
def test_multiprocess_mesh_straggler_event(tmp_path, nshards):
    """ISSUE 13 acceptance: a 2-process (2-shard CPU mesh) run with an
    injected slow shard produces skew gauges + exactly ONE structured
    straggler event after stitching the shards' JSONL streams."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _WORKER.format(repo=repo, out=str(tmp_path))
    procs = []
    for shard in range(nshards):
        env = dict(os.environ,
                   PADDLE_TPU_PROCESS_ID=str(shard),
                   PADDLE_TPU_NUM_PROCESSES=str(nshards))
        env.pop("PADDLE_TPU_TIER_DURATIONS", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
    walls = load_shard_walls(str(tmp_path))
    assert len(walls) == 6
    assert all(set(w) == {"0", "1"} for w in walls.values())
    rows = []
    mon = StepMonitor(track_memory=False, on_report=rows.append,
                      straggler_threshold=2.0)
    feed_shard_walls(mon, walls)
    events = [r for r in rows if "straggler" in r]
    assert len(events) == 1, events
    ev = events[0]["straggler"]
    assert ev["slowest_shard"] == "1"
    assert ev["skew_ratio"] >= 2.0
    assert mon.straggling and mon.stragglers_total == 1
    text = mon.metrics_text()
    lint_exposition(text)
    assert 'paddle_tpu_shard_step_seconds{shard="1"}' in text


class TestBenchHistory:
    def _load_tool(self):
        import importlib.util
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_history", os.path.join(repo, "tools",
                                          "bench_history.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _write(self, tmp_path, rev, tail):
        p = tmp_path / f"BENCH_{rev}.json"
        p.write_text(json.dumps({"n": 1, "cmd": "x", "rc": 0,
                                 "tail": tail}))
        return str(p)

    def test_trend_and_regression_gate(self, tmp_path):
        bh = self._load_tool()
        row = {"metric": "tok/s (gpt)", "value": 100.0, "unit": "tokens/s",
               "extra": {"row": "gpt", "step_ms": 10.0, "recompiles": 0}}
        f1 = self._write(tmp_path, "r01", json.dumps(row) + "\n")
        row2 = dict(row, value=80.0)
        f2 = self._write(tmp_path, "r02", json.dumps(row2) + "\n")
        hist = bh.load_history([f1, f2])
        assert set(hist) == {"gpt"}
        assert hist["gpt"]["r01"]["value"] == 100.0
        table = bh.trend_table(hist, ["r01", "r02"])
        assert "gpt" in table and "100.0" in table and "80.0" in table
        v = bh.check_regressions(hist, ["r01", "r02"], regress_pct=10.0)
        assert len(v) == 1 and v[0]["drop_pct"] == pytest.approx(20.0)
        assert not bh.check_regressions(hist, ["r01", "r02"],
                                        regress_pct=25.0)
        assert bh.main([f1, f2, "--regress-pct", "10"]) == 1
        assert bh.main([f1, f2, "--regress-pct", "25"]) == 0

    def test_truncated_array_tail_parses(self, tmp_path):
        bh = self._load_tool()
        # the r05 shape: head-truncated JSON array fragment
        tail = ('"row": "lost", "metric": "m", "value": 1.0}, '
                '{"row": "kept", "metric": "tok/s", "value": 5.0, '
                '"step_ms": 2.0}]')
        f = self._write(tmp_path, "r05", tail)
        hist = bh.load_history([f])
        assert "kept" in hist
        assert hist["kept"]["r05"]["value"] == 5.0

    def test_real_bench_files_parse(self):
        bh = self._load_tool()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        import glob as g
        files = sorted(g.glob(os.path.join(repo, "BENCH_r*.json")))
        hist = bh.load_history(files)
        assert "gpt-cpu-smoke" in hist          # r06 row
        assert "resnet50" in hist               # r05 row
        # and the repo's own gate passes at head (no row regressed
        # against its previous recorded revision)
        assert bh.check_regressions(
            hist, sorted({r for v in hist.values() for r in v}),
            regress_pct=50.0) == []
