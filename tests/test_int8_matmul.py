"""Weight-only int8 matmul kernel parity (interpret mode, CPU).

Covers the weight-only int8 GEMM of the reference's serving transformer
(fused_multi_transformer_op.cu): both weight layouts, the exactness of
post-accumulation per-channel scaling, and the XLA fallback equivalence.
"""
import numpy as np
import pytest
import jax.numpy as jnp

from paddle_tpu.ops.pallas.int8_matmul import int8_matmul, int8_linear_nd


def _quant(w, axis):
    s = np.max(np.abs(w), axis=axis, keepdims=True) / 127.0
    s = np.maximum(s, 1e-12)
    q = np.clip(np.round(w / s), -127, 127).astype(np.int8)
    return q, s.astype(np.float32)


@pytest.mark.parametrize("m,k,n", [(8, 256, 512), (32, 128, 384)])
def test_int8_matmul_kn_matches_dequant(m, k, n, monkeypatch):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(m, k).astype(np.float32)) * 0.3
    w = rng.randn(k, n).astype(np.float32) * 0.1
    q, s = _quant(w, axis=0)                       # per-output-column
    monkeypatch.setenv("PADDLE_TPU_INT8_MATMUL", "1")
    got = int8_matmul(x, jnp.asarray(q), jnp.asarray(s.reshape(-1)),
                      w_layout="kn", interpret=True)
    want = x @ jnp.asarray(q.astype(np.float32) * s)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_int8_matmul_nk_matches_dequant(monkeypatch):
    rng = np.random.RandomState(1)
    m, k, n = 8, 128, 640
    x = jnp.asarray(rng.randn(m, k).astype(np.float32)) * 0.3
    w = rng.randn(n, k).astype(np.float32) * 0.1   # [N, K] (wte layout)
    q, s = _quant(w, axis=1)                       # per-row
    monkeypatch.setenv("PADDLE_TPU_INT8_MATMUL", "1")
    got = int8_matmul(x, jnp.asarray(q), jnp.asarray(s.reshape(-1)),
                      w_layout="nk", interpret=True)
    want = x @ jnp.asarray((q.astype(np.float32) * s).T)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_fallback_matches_kernel(monkeypatch):
    """Gate-off path (XLA dequant matmul) == kernel numerics."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(16, 128).astype(np.float32)) * 0.3
    w = rng.randn(128, 256).astype(np.float32) * 0.1
    q, s = _quant(w, axis=0)
    qj, sj = jnp.asarray(q), jnp.asarray(s.reshape(-1))
    monkeypatch.setenv("PADDLE_TPU_INT8_MATMUL", "0")
    fb = int8_matmul(x, qj, sj, w_layout="kn")
    monkeypatch.setenv("PADDLE_TPU_INT8_MATMUL", "1")
    kr = int8_matmul(x, qj, sj, w_layout="kn", interpret=True)
    np.testing.assert_allclose(np.asarray(fb), np.asarray(kr),
                               rtol=1e-4, atol=1e-4)


def test_nd_wrapper_and_bias(monkeypatch):
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(2, 4, 128).astype(np.float32)) * 0.3
    w = rng.randn(128, 256).astype(np.float32) * 0.1
    b = jnp.asarray(rng.randn(256).astype(np.float32))
    q, s = _quant(w, axis=0)
    monkeypatch.setenv("PADDLE_TPU_INT8_MATMUL", "1")
    got = int8_linear_nd(x, jnp.asarray(q), jnp.asarray(s.reshape(-1)), b,
                         interpret=True)
    want = x @ jnp.asarray(q.astype(np.float32) * s) + b
    assert got.shape == (2, 4, 256)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
