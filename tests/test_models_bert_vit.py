"""BERT + ViT model family tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.models import (BertConfig, BertForMaskedLM,
                               BertForPretraining,
                               BertForSequenceClassification, BertModel,
                               ViTConfig, VisionTransformer, bert_config,
                               ernie_config)


def tiny_bert(**kw):
    cfg = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
               max_position_embeddings=32, intermediate_size=64,
               hidden_dropout=0.0)
    cfg.update(kw)
    return BertConfig(**cfg)


def tiny_vit(**kw):
    cfg = dict(image_size=16, patch_size=4, hidden_size=32, num_layers=2,
               num_heads=4, intermediate_size=64, num_classes=5)
    cfg.update(kw)
    return ViTConfig(**cfg)


class TestBert:
    def test_backbone_shapes(self):
        paddle.seed(0)
        m = BertModel(tiny_bert())
        m.eval()
        ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype("int64"))
        seq, pooled = m(ids)
        assert tuple(seq.shape) == (2, 16, 32)
        assert tuple(pooled.shape) == (2, 32)

    def test_mlm_logits_and_tied_grads(self):
        paddle.seed(0)
        m = BertForMaskedLM(tiny_bert())
        m.train()
        ids = paddle.to_tensor(np.random.randint(0, 128, (2, 8)).astype("int64"))
        logits = m(ids)
        assert tuple(logits.shape) == (2, 8, 128)
        loss = nn.CrossEntropyLoss()(
            paddle.reshape(logits, [-1, 128]),
            paddle.reshape(ids, [-1]))
        loss.backward()
        wte = m.bert.embeddings.word_embeddings.weight
        assert wte.grad is not None  # tied head must flow into embeddings

    def test_cls_learns_toy_task(self):
        paddle.seed(0)
        cfg = tiny_bert(num_labels=2)
        m = BertForSequenceClassification(cfg)
        m.train()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m.parameters())
        # class = whether first token id > 64
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 128, (32, 8)).astype("int64")
        labels = (ids[:, 0] > 64).astype("int64")
        lossf = nn.CrossEntropyLoss()
        losses = []
        for _ in range(60):
            loss = lossf(m(paddle.to_tensor(ids)), paddle.to_tensor(labels))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < 0.3 * losses[0]

    def test_padding_mask_changes_output(self):
        paddle.seed(0)
        m = BertModel(tiny_bert())
        m.eval()
        ids = paddle.to_tensor(np.random.randint(1, 128, (1, 8)).astype("int64"))
        mask = paddle.to_tensor(np.array([[1, 1, 1, 1, 0, 0, 0, 0]], np.int32))
        seq_full, _ = m(ids)
        seq_masked, _ = m(ids, attention_mask=mask)
        # masking the tail must change the first token's representation
        assert not np.allclose(np.asarray(seq_full._data[0, 0]),
                               np.asarray(seq_masked._data[0, 0]), atol=1e-5)

    def test_additive_float_mask(self):
        """0/-1e4 additive float masks must behave like the 0/1 keep mask."""
        paddle.seed(0)
        m = BertModel(tiny_bert())
        m.eval()
        ids = paddle.to_tensor(np.random.randint(1, 128, (1, 8)).astype("int64"))
        keep = np.array([[1, 1, 1, 1, 0, 0, 0, 0]], np.int32)
        additive = np.where(keep > 0, 0.0, -1e9).astype(np.float32)
        a, _ = m(ids, attention_mask=paddle.to_tensor(keep))
        b, _ = m(ids, attention_mask=paddle.to_tensor(additive))
        np.testing.assert_allclose(np.asarray(a._data), np.asarray(b._data),
                                   atol=1e-5)

    def test_attention_dropout_active_in_train(self):
        paddle.seed(0)
        m = BertModel(tiny_bert(attention_dropout=0.5))
        m.train()
        ids = paddle.to_tensor(np.random.randint(1, 128, (1, 8)).astype("int64"))
        a, _ = m(ids)
        b, _ = m(ids)
        assert not np.allclose(np.asarray(a._data), np.asarray(b._data))
        m.eval()
        c, _ = m(ids)
        d, _ = m(ids)
        np.testing.assert_allclose(np.asarray(c._data), np.asarray(d._data))

    def test_embedding_init_scale(self):
        m = BertModel(tiny_bert())
        for w in (m.embeddings.position_embeddings.weight,
                  m.embeddings.token_type_embeddings.weight):
            assert np.asarray(w._data).std() < 0.05  # initializer_range=0.02

    def test_pretraining_heads(self):
        paddle.seed(0)
        m = BertForPretraining(tiny_bert())
        m.eval()
        ids = paddle.to_tensor(np.random.randint(0, 128, (2, 8)).astype("int64"))
        mlm_logits, nsp_logits = m(ids)
        assert tuple(mlm_logits.shape) == (2, 8, 128)
        assert tuple(nsp_logits.shape) == (2, 2)

    def test_token_type_embeddings_used(self):
        paddle.seed(0)
        m = BertModel(tiny_bert())
        m.eval()
        ids = paddle.to_tensor(np.random.randint(0, 128, (1, 8)).astype("int64"))
        tt = paddle.to_tensor(np.ones((1, 8), np.int64))
        a, _ = m(ids)
        b, _ = m(ids, token_type_ids=tt)
        assert not np.allclose(np.asarray(a._data), np.asarray(b._data))


class TestViT:
    def test_forward_shape(self):
        paddle.seed(0)
        m = VisionTransformer(tiny_vit())
        m.eval()
        x = paddle.randn([2, 3, 16, 16])
        y = m(x)
        assert tuple(y.shape) == (2, 5)
        assert np.isfinite(np.asarray(y._data)).all()

    def test_feature_mode(self):
        m = VisionTransformer(tiny_vit(num_classes=0))
        m.eval()
        y = m(paddle.randn([1, 3, 16, 16]))
        assert tuple(y.shape) == (1, 17, 32)  # 16 patches + cls

    def test_learns_toy_task(self):
        paddle.seed(0)
        m = VisionTransformer(tiny_vit(num_classes=2))
        m.train()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m.parameters())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 3, 16, 16)).astype(np.float32)
        labels = (np.arange(16) % 2).astype("int64")
        x[labels == 1] += 2.0
        lossf = nn.CrossEntropyLoss()
        losses = []
        for _ in range(25):
            loss = lossf(m(paddle.to_tensor(x)), paddle.to_tensor(labels))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < 0.3 * losses[0]


def test_ernie_classification_and_mlm():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import (ernie_config, ErnieForSequenceClassification,
                                   ErnieForMaskedLM)
    paddle.seed(0)
    cfg = ernie_config("ernie-tiny", vocab_size=128,
                       max_position_embeddings=32, num_layers=2)
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype("int64"))
    task = paddle.to_tensor(np.ones((2, 16), np.int64))

    clf = ErnieForSequenceClassification(cfg, num_classes=3)
    logits = clf(ids, task_type_ids=task)
    assert list(logits.shape) == [2, 3]
    # trains one step
    loss = paddle.nn.functional.cross_entropy(
        logits, paddle.to_tensor(np.array([0, 2], np.int64)))
    loss.backward()
    assert clf.classifier.weight.grad is not None
    # task embedding changes the output (vs task 0)
    logits0 = clf(ids)
    assert not np.allclose(logits.numpy(), logits0.numpy())

    mlm = ErnieForMaskedLM(cfg)
    out = mlm(ids)
    assert list(out.shape) == [2, 16, 128]


def test_bert_fused_mlm_loss_matches_unfused():
    """BertForMaskedLM.loss == CE over forward() logits at masked positions
    (the -100 ignore-index contract)."""
    import paddle_tpu.nn as nn
    paddle.seed(0)
    cfg = bert_config("bert-base", vocab_size=128, hidden_size=64,
                      num_layers=2, num_heads=4, max_position_embeddings=32,
                      intermediate_size=128)
    m = BertForMaskedLM(cfg)
    m.eval()  # identical forwards need identical dropout (= none)
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype("int64"))
    labels_np = np.random.randint(0, 128, (2, 16)).astype("int64")
    labels_np[:, ::2] = -100          # only odd positions scored
    labels = paddle.to_tensor(labels_np)

    logits = m(ids)
    ce = nn.CrossEntropyLoss(ignore_index=-100)
    want = ce(logits.reshape([-1, 128]), labels.reshape([-1]))
    got = m.loss(ids, labels, chunk_size=8)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    got.backward()
    assert np.isfinite(
        m.bert.embeddings.word_embeddings.weight.grad.numpy()).all()


def test_ernie_fused_mlm_loss_finite_and_trains():
    from paddle_tpu.models import ErnieForMaskedLM
    paddle.seed(0)
    cfg = ernie_config("ernie-tiny", vocab_size=128, hidden_size=64,
                       num_layers=2, num_heads=4,
                       max_position_embeddings=32, intermediate_size=128)
    m = ErnieForMaskedLM(cfg)
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype("int64"))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, opt,
                                lambda a, b: m.loss(a, b, chunk_size=8))
    l0 = float(step(ids, ids))
    for _ in range(4):
        l = float(step(ids, ids))
    assert l < l0


def test_bert_seq_lens_matches_mask_path():
    """seq_lens (per-row lengths) must equal the equivalent bool padding
    mask on the reference path — CPU uses the fallback conversion, TPU
    routes into the fused kernel's SMEM table."""
    import numpy as np
    paddle.seed(6)
    cfg = bert_config("bert-base", hidden_size=64, num_layers=2, num_heads=4,
                      vocab_size=128, intermediate_size=128,
                      hidden_dropout=0.0, attention_dropout=0.0)
    m = BertModel(cfg)
    m.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(1, 128, (3, 24)).astype("int32"))
    lens = [24, 15, 7]
    mask = np.zeros((3, 24), np.int32)
    for i, ln in enumerate(lens):
        mask[i, :ln] = 1
    seq_a, _ = m(ids, attention_mask=paddle.to_tensor(mask))
    seq_b, _ = m(ids, seq_lens=paddle.to_tensor(
        np.asarray(lens, np.int32)))
    for i, ln in enumerate(lens):
        np.testing.assert_allclose(seq_a.numpy()[i, :ln],
                                   seq_b.numpy()[i, :ln],
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"row {i}")
