"""Pallas fused LayerNorm parity (interpret mode; capability analog of the
reference's phi/kernels/gpu/layer_norm_kernel — single-HBM-pass fwd+bwd)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.layer_norm import fused_layer_norm


def _ref(x, gamma, beta, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    o = (x - mu) * jax.lax.rsqrt(var + eps)
    if gamma is not None:
        o = o * gamma
    if beta is not None:
        o = o + beta
    return o


@pytest.mark.parametrize("affine", ["gb", "g", "b", "none"])
def test_fused_ln_forward(affine):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(3, 16, 256).astype(np.float32))
    g = jnp.asarray(rng.randn(256).astype(np.float32)) if "g" in affine else None
    b = jnp.asarray(rng.randn(256).astype(np.float32)) if "b" in affine else None
    out = fused_layer_norm(x, g, b, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, g, b)),
                               rtol=2e-5, atol=2e-5)


def test_fused_ln_grads():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 32, 128).astype(np.float32))
    g = jnp.asarray(rng.randn(128).astype(np.float32))
    b = jnp.asarray(rng.randn(128).astype(np.float32))

    def lf(x, g, b):
        return jnp.sum(fused_layer_norm(x, g, b, interpret=True) ** 2)

    def lr(x, g, b):
        return jnp.sum(_ref(x, g, b) ** 2)

    gf = jax.grad(lf, argnums=(0, 1, 2))(x, g, b)
    gr = jax.grad(lr, argnums=(0, 1, 2))(x, g, b)
    for a, c, nm in zip(gf, gr, ["dx", "dgamma", "dbeta"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=2e-4, atol=2e-4, err_msg=nm)


def test_functional_layer_norm_routes_fused(monkeypatch):
    """PADDLE_TPU_FUSED_LN=1 routes F.layer_norm through the kernel."""
    monkeypatch.setenv("PADDLE_TPU_FUSED_LN", "1")
    import paddle_tpu as paddle
    import paddle_tpu.ops.pallas.layer_norm as LN
    # the platform gate correctly refuses CPU — fake a TPU device so the
    # REAL predicate (env + shape checks included) drives the routing
    from types import SimpleNamespace
    monkeypatch.setattr(LN.jax, "devices",
                        lambda: [SimpleNamespace(platform="tpu")])
    calls = []
    orig = LN.fused_layer_norm

    def spy(x, g=None, b=None, eps=1e-5, interpret=False):
        calls.append(x.shape)
        return orig(x, g, b, eps=eps, interpret=True)

    monkeypatch.setattr(LN, "fused_layer_norm", spy)
    import paddle_tpu.nn as nn
    ln = nn.LayerNorm(128)
    x = paddle.to_tensor(np.random.randn(8, 128).astype("float32"))
    out = ln(x)
    assert calls, "fused LN was not routed to"
    want = _ref(jnp.asarray(x.numpy()), jnp.asarray(ln.weight.numpy()),
                jnp.asarray(ln.bias.numpy()))
    np.testing.assert_allclose(out.numpy(), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # grads flow through the tape
    loss = (ln(x) ** 2).sum()
    loss.backward()
    assert ln.weight.grad is not None
