"""Quantized gradient all-reduce (ISSUE 20): the int8 factored-scale
sync and its gates.

Covers: the quantize/dequantize round-trip oracle (per-element error
bounded by half a chunk scale; stochastic rounding unbiased), the
overflow-free int8_psum against the f32 pmean oracle on the 8-device
host mesh, the f32-fallback classifier and the comm-group bucketing
math, the dtype-qualified CommPlan specs (comm_extra / comm_bytes /
comm_missing, dict-spec validation), TrainStep(grad_comm=...) precondition
errors, convergence parity of the int8 step against its f32 twin with
the f32 twin bit-identical to the implicit-psum baseline, the static
sync-bytes ratio + train_comm_plan default-deny, and the collective
ledger's wire-dtype surface (from_static / by_dtype / host-lane trace
fallback)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.analysis import (CommPlan, CommPlanError, rows_by_kind,
                                 train_comm_plan)
from paddle_tpu.distributed.quant_collectives import (
    build_comm_groups, comm_group_stats, default_f32_fallback,
    dequantize_chunked, int8_psum, quantize_chunked)

SDS = jax.ShapeDtypeStruct

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device host mesh")


# ------------------------------------------------- quantization oracle

def test_quantize_roundtrip_error_bound():
    """Deterministic round-trip: every element lands within half its
    chunk's scale (round-to-nearest, no clipping inside the amax)."""
    rng = np.random.RandomState(0)
    x = (rng.randn(512) * rng.uniform(0.01, 10.0, 512)).astype(np.float32)
    codes, scales = quantize_chunked(jnp.asarray(x), chunk=128)
    assert codes.shape == (4, 128) and codes.dtype == jnp.int8
    assert scales.shape == (4,)
    y = np.asarray(dequantize_chunked(codes, scales, x.size,
                                      shape=x.shape))
    bound = np.repeat(np.asarray(scales), 128) / 2 + 1e-7
    assert np.all(np.abs(y - x) <= bound)
    # zeros quantize exactly; padding never leaks into the round-trip
    z = jnp.zeros((37,), jnp.float32)
    zc, zs = quantize_chunked(z, chunk=16)
    assert np.all(np.asarray(dequantize_chunked(zc, zs, 37)) == 0.0)


def test_quantize_stochastic_rounding_unbiased():
    """E[stochastic round-trip] = x: averaging many independent keys
    converges on the input well below the deterministic step size."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.uniform(-1.0, 1.0, 64).astype(np.float32))
    keys = jax.random.split(jax.random.PRNGKey(0), 512)

    def rt(k):
        c, s = quantize_chunked(x, chunk=64, stochastic=True, key=k)
        return dequantize_chunked(c, s, x.size)

    ys = np.asarray(jax.vmap(rt)(keys))
    scale = float(jnp.max(jnp.abs(x))) / 127
    # each draw is within one step of x (floor(q+u) vs q) ...
    assert np.max(np.abs(ys - np.asarray(x))) <= scale + 1e-7
    # ... and the mean is unbiased: sem = scale/sqrt(12*512), take 6x
    assert np.max(np.abs(ys.mean(0) - np.asarray(x))) <= 6 * scale / 78


def test_default_f32_fallback_is_ndim_le_1():
    assert default_f32_fallback("gpt.h.0.ln_1.weight", (64,))
    assert default_f32_fallback("gpt.h.0.ln_1.bias", (64,))
    assert default_f32_fallback("scalar", ())
    # matrices — embeddings included — quantize by default: falling
    # embeddings back to f32 sinks the wire-bytes ratio below the gate
    assert not default_f32_fallback("gpt.wte.weight", (128, 64))
    assert not default_f32_fallback("gpt.h.0.mlp.up.weight", (64, 256))


def test_build_comm_groups_and_wire_stats():
    names = ["wte.weight", "h.0.ln.weight", "h.0.mlp.w"]
    shapes = [(128, 64), (64,), (64, 64)]
    groups = [("emb", [0]), ("h.0", [1, 2])]
    plan = build_comm_groups(names, shapes, groups)
    assert plan == [("emb", (0,), ()), ("h.0", (2,), (1,))]
    st = comm_group_stats(plan, shapes)
    n_q, n_f = 128 * 64 + 64 * 64, 64
    assert st["quant_elems"] == n_q and st["f32_elems"] == n_f
    # ring terms: f32 twin 2*4B/elem; int8 2*1B/elem + per-chunk scales
    assert st["f32_twin_bytes"] == 2 * 4 * (n_q + n_f)
    chunks = -(-128 * 64 // 256) + -(-64 * 64 // 256)
    assert st["int8_bytes"] == 2 * n_q + 2 * 4 * chunks + 2 * 4 * n_f
    assert st["ratio"] == pytest.approx(
        st["f32_twin_bytes"] / st["int8_bytes"])


@needs_mesh
def test_int8_psum_close_to_f32_mean():
    """The overflow-free recipe on a real 8-way mesh: shared pmax'd
    scales, codes bounded by 127//8, dequantized mean within half a
    scale of the exact f32 pmean."""
    from jax import shard_map
    mesh = dist.build_mesh({"dp": 8})
    x = np.random.RandomState(2).randn(8, 37).astype(np.float32)

    def f(xs):
        xs = xs[0]
        return (int8_psum(xs, "dp", 8, chunk=16)[None],
                jax.lax.pmean(xs, "dp")[None])

    q, m = shard_map(f, mesh=mesh, axis_names={"dp"},
                     in_specs=(P("dp", None),), out_specs=(P(), P()),
                     check_vma=False)(x)
    scale = np.abs(x).max() / (127 // 8)
    assert np.max(np.abs(np.asarray(q) - np.asarray(m))) <= scale / 2 + 1e-6


# ------------------------------------------- dtype-qualified CommPlan

def _static_rows(f32_bytes=64, with_s8=True, extra_kind=None):
    rows = []
    if with_s8:
        rows.append({"name": "all-reduce.1", "kind": "all-reduce",
                     "dtype": "s8", "bytes": 1000, "calls": 1})
    rows.append({"name": "all-reduce.2", "kind": "all-reduce",
                 "dtype": "f32", "bytes": f32_bytes, "calls": 1})
    if extra_kind:
        rows.append({"name": f"{extra_kind}.3", "kind": extra_kind,
                     "dtype": "f32", "bytes": 64, "calls": 1})
    return rows


def test_rows_by_kind_dtype_split():
    got = rows_by_kind(_static_rows(), by_dtype=True)
    assert set(got) == {"all-reduce:s8", "all-reduce:f32"}
    assert got["all-reduce:s8"]["kind"] == "all-reduce"
    assert got["all-reduce:s8"]["dtype"] == "s8"
    # rows without a dtype column fall back to the bare kind
    got = rows_by_kind([{"name": "all-reduce.9"}], by_dtype=True)
    assert set(got) == {"all-reduce"}


def test_int8_plan_compliant_and_default_deny():
    plan = train_comm_plan(4, dtype="int8", max_f32_bytes=128)
    assert not plan.check(_static_rows())
    # an f32 all-reduce above the side-channel cap = the gradient sync
    # sneaking back in f32 — fails as comm_bytes
    fs = plan.check(_static_rows(f32_bytes=4096))
    assert [f.code for f in fs] == ["comm_bytes"]
    # no s8 sync at all: the quantized path never lowered
    fs = plan.check(_static_rows(with_s8=False))
    assert "comm_missing" in [f.code for f in fs]
    # any other kind stays default-denied
    fs = plan.check(_static_rows(extra_kind="all-gather"))
    assert "comm_extra" in [f.code for f in fs]
    with pytest.raises(CommPlanError):
        plan.verify(_static_rows(f32_bytes=4096), executable="ts")


def test_qualified_only_plan_rejects_other_dtype():
    plan = CommPlan({"all-reduce:s8": "+"})
    fs = plan.check(_static_rows())
    assert [f.code for f in fs] == ["comm_extra"]
    assert fs[0].data["dtype"] == "f32"


def test_plan_spec_validation():
    with pytest.raises(ValueError):
        CommPlan({"all-reduce": {"calls": "+", "max_bytes": -1}})
    with pytest.raises(ValueError):
        CommPlan({"all-reduce": {"calls": "+", "surprise": 1}})
    with pytest.raises(ValueError):
        train_comm_plan(4, dtype="int4")
    # the f32 plan stays the classic bare default-deny
    assert set(train_comm_plan(dtype="f32").expect) == {"all-reduce"}


# ------------------------------------------------- TrainStep wiring

def _tiny_gpt(mesh, grad_comm, **kw):
    from paddle_tpu import optimizer as opt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128,
                    intermediate_size=128, param_dtype="float32")
    model = GPTForCausalLM(cfg)
    model.train()
    o = opt.AdamW(parameters=model.parameters(), learning_rate=1e-3)
    return TrainStep(model, o, lambda ids, lab: model.loss(ids, lab),
                     mesh=mesh, grad_comm=grad_comm, **kw)


@needs_mesh
def test_grad_comm_precondition_errors():
    with pytest.raises(ValueError, match="mesh"):
        _tiny_gpt(None, "int8")
    with pytest.raises(ValueError, match="grad_comm"):
        _tiny_gpt(dist.build_mesh({"dp": 8}), "int4")
    # partial-manual shard_map is off the table on this backend: the
    # quantized sync requires a PURE data-parallel mesh
    with pytest.raises(ValueError, match="pure"):
        _tiny_gpt(dist.build_mesh({"dp": 4, "mp": 2}), "int8")
    with pytest.raises(ValueError, match="grad_accum_steps"):
        _tiny_gpt(dist.build_mesh({"dp": 8}), "int8", grad_accum_steps=2)


@needs_mesh
def test_int8_static_bytes_ratio_and_plan():
    """The static acceptance gate at pytest level: the int8 step's
    gradient-sync all-reduce bytes sit >= 3.5x under the f32 twin, the
    executable satisfies train_comm_plan, and the same plan REJECTS the
    f32 twin (the default-deny cuts both ways)."""
    mesh = dist.build_mesh({"dp": 8})
    dist.set_mesh(mesh)
    try:
        ids = SDS((8, 16), "int64")

        def ar_bytes(audit):
            return sum(r.get("bytes") or 0 for r in audit.rows
                       if r.get("kind") == "all-reduce")

        twin = _tiny_gpt(mesh, "f32")
        twin_audit = twin.sharding_audit(ids, ids)
        ts = _tiny_gpt(mesh, "int8")
        plan = train_comm_plan(
            len(ts._comm_groups), dtype="int8",
            max_f32_bytes=max(ar_bytes(twin_audit) // 8, 1))
        audit = ts.sharding_audit(ids, ids, plan=plan)
        assert not audit.findings.for_pass("comm_plan"), \
            [str(f) for f in audit.findings.for_pass("comm_plan")]
        ratio = ar_bytes(twin_audit) / ar_bytes(audit)
        assert ratio >= 3.5, f"sync-bytes ratio {ratio:.2f} < 3.5"
        # the twin's f32 gradient sync violates the int8 plan
        assert plan.check(twin_audit.rows)
    finally:
        dist.set_mesh(None)


@needs_mesh
def test_int8_convergence_parity():
    """Numerics sentinel: 4 fixed-data steps — the explicit-f32 path is
    BIT-identical to the implicit partitioner psum, and the int8 path
    tracks it within the sentinel bound (quantization noise only)."""
    mesh = dist.build_mesh({"dp": 8})
    dist.set_mesh(mesh)
    try:
        ids = paddle.to_tensor(np.random.RandomState(0).randint(
            1, 128, (8, 16)).astype("int64"))

        def losses(mode):
            paddle.seed(7)
            ts = _tiny_gpt(mesh, mode)
            return [float(ts(ids, ids)) for _ in range(4)]

        base = losses(None)
        f32 = losses("f32")
        i8 = losses("int8")
        assert f32 == base, "explicit f32 per-group sync changed numerics"
        assert max(abs(a - b) for a, b in zip(i8, f32)) < 0.05
        assert i8[-1] < i8[0], "int8 step is not descending"
    finally:
        dist.set_mesh(None)


# ------------------------------------------------- ledger dtype surface

def _ledger_rows():
    return [{"name": "all-reduce.1", "kind": "all-reduce", "dtype": "s8",
             "calls": 15, "bytes": 160000, "dur_us": None,
             "busy_us": None, "overlapped_us": None, "exposed_us": None,
             "exposed_frac": None, "bus_gbps": None},
            {"name": "all-reduce.2", "kind": "all-reduce", "dtype": "f32",
             "calls": 17, "bytes": 15000, "dur_us": None,
             "busy_us": None, "overlapped_us": None, "exposed_us": None,
             "exposed_frac": None, "bus_gbps": None}]


def test_ledger_from_static_dtype_surface():
    from paddle_tpu.obs.collectives import CollectiveLedger
    led = CollectiveLedger.from_static(_ledger_rows())
    # totals must survive clock-less static rows (None, not 0)
    t = led.totals()
    assert t["collectives"] == 2 and t["busy_us"] == 0
    assert t["bytes"] == 175000
    by = led.by_dtype()
    assert by["s8"] == {"calls": 15, "bytes": 160000}
    assert by["f32"] == {"calls": 17, "bytes": 15000}
    table = led.table()
    assert "dtype" in table and "s8" in table
    text = led.metrics_text()
    assert 'collective_bytes_by_dtype{dtype="s8"} 160000' in text


def test_trace_host_lane_fallback_overlap():
    """A CPU capture has no device pid; the analyzer falls back to the
    XLA CPU client's execution threads and still measures real
    overlap/exposed — runtime envelopes are dropped so they can't count
    everything as overlapped."""
    from paddle_tpu.profiler.trace_analysis import TraceAnalysis
    events = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 10,
         "args": {"name": "tf_XLATfrtCpuClient/1"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 11,
         "args": {"name": "tf_XLATfrtCpuClient/2"}},
        {"ph": "X", "pid": 1, "tid": 10, "name": "all-reduce.1",
         "ts": 0.0, "dur": 100.0},
        {"ph": "X", "pid": 1, "tid": 11, "name": "dot.1",
         "ts": 50.0, "dur": 100.0},
        {"ph": "X", "pid": 1, "tid": 10, "name": "ThunkExecutor::run",
         "ts": 0.0, "dur": 1000.0},
    ]
    ta = TraceAnalysis(events)
    assert ta.host_lanes
    assert len(ta.device_events) == 2       # envelope dropped
    ov = ta.overlap()
    assert ov["collective_us"] == 100.0 and ov["overlapped_us"] == 50.0
    assert ov["ratio"] == pytest.approx(0.5)
    rows = ta.collective_rows()
    assert rows[0]["name"].startswith("all-reduce")
    assert rows[0]["exposed_us"] == 50.0
    assert rows[0]["dtype"] is None          # runtime rows carry no dtype
    # a real device lane present -> no fallback
    ta2 = TraceAnalysis(events + [
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 2, "tid": 1, "name": "fusion.1",
         "ts": 0.0, "dur": 10.0}])
    assert not ta2.host_lanes
    assert len(ta2.device_events) == 1
