"""DGC top-k sparsified gradient exchange (reference: dgc_op.h +
meta_optimizers/dgc_optimizer.py) — the COMMUNICATION-compressed path
(VERDICT r2: optimizer-side emulation alone is name-parity)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def _mesh2():
    return dist.build_mesh({"dp": 2}, devices=jax.devices()[:2])


def _loss_fn(params, batch):
    w, = params
    x, y = batch
    pred = x @ w
    return jnp.mean((pred - y) ** 2)


def _data(rng, n=8, d=4):
    x = rng.randn(n, d).astype(np.float32)
    w_true = rng.randn(d, 1).astype(np.float32)
    y = x @ w_true
    return jnp.asarray(x), jnp.asarray(y), w_true


class TestDGC:
    def test_sparsity_zero_matches_dense_mean_grad(self):
        rng = np.random.RandomState(0)
        x, y, _ = _data(rng)
        w = jnp.zeros((4, 1), jnp.float32)
        mesh = _mesh2()
        with dist.mesh_scope(mesh):
            loss, grads, res = dist.dgc_value_and_grad(
                _loss_fn, [w], (x, y), sparsity=0.0, mesh=mesh)
        dense_l, dense_g = jax.value_and_grad(
            lambda p, b: _loss_fn(p, b))([w], (x, y))
        # shard-mean of per-half grads == full-batch grad for MSE over
        # equal halves
        np.testing.assert_allclose(float(loss), float(dense_l), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(grads[0]),
                                   np.asarray(dense_g[0]),
                                   rtol=1e-5, atol=1e-6)
        assert float(jnp.abs(res[0]).sum()) < 1e-6  # k=n: nothing kept back

    def test_mass_conservation_with_error_feedback(self):
        """sent + kept == contributed: no gradient mass is lost, it is only
        delayed (the DGC error-feedback invariant)."""
        rng = np.random.RandomState(1)
        x, y, _ = _data(rng)
        w = jnp.zeros((4, 1), jnp.float32)
        mesh = _mesh2()
        D = 2
        with dist.mesh_scope(mesh):
            loss, grads, res = dist.dgc_value_and_grad(
                _loss_fn, [w], (x, y), sparsity=0.5, mesh=mesh)
            # contributed mass: per-shard g/D (recompute densely per shard)
            g0 = jax.grad(_loss_fn)([w], (x[:4], y[:4]))[0] / D
            g1 = jax.grad(_loss_fn)([w], (x[4:], y[4:]))[0] / D
        total_in = np.asarray(g0 + g1)
        total_out = np.asarray(grads[0]) + np.asarray(res[0][0]) \
            + np.asarray(res[0][1])
        np.testing.assert_allclose(total_out, total_in, rtol=1e-5, atol=1e-7)

    def test_wire_bytes_compressed(self):
        """The exchange's all_gather operands are k-element (values,
        indices), NOT the n-element dense tensor — verified on the traced
        jaxpr (the point of DGC)."""
        from jax import shard_map
        from jax.sharding import PartitionSpec as P
        n, sparsity = 1024, 0.999
        k = max(1, int(n * (1 - sparsity)))
        mesh = _mesh2()

        def body(g):
            with dist.mesh_scope(mesh):
                s, r = dist.sparse_allreduce(g, "dp", sparsity)
            return s

        with dist.mesh_scope(mesh):
            f = shard_map(body, mesh=mesh, axis_names={"dp"},
                          in_specs=P(), out_specs=P(), check_vma=False)
            jaxpr = jax.make_jaxpr(lambda g: jax.jit(f)(g))(
                jnp.zeros((n,), jnp.float32))

        gathered = []

        def walk(jx):
            for eqn in jx.eqns:
                if eqn.primitive.name == "all_gather":
                    gathered.extend(int(np.prod(v.aval.shape))
                                    for v in eqn.invars)
                for v in eqn.params.values():
                    if hasattr(v, "eqns"):
                        walk(v)
                    elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                        walk(v.jaxpr)

        walk(jaxpr.jaxpr)
        assert gathered, "no all_gather found in the DGC exchange jaxpr"
        assert max(gathered) == k, (gathered, k)   # k elements, never n
        assert len(gathered) == 2                  # values + indices

    def test_training_converges_with_dgc(self):
        rng = np.random.RandomState(2)
        x, y, w_true = _data(rng, n=64)
        w = jnp.zeros((4, 1), jnp.float32)
        vel = jnp.zeros_like(w)
        res = None
        mesh = _mesh2()
        losses = []
        with dist.mesh_scope(mesh):
            for i in range(120):
                loss, grads, res = dist.dgc_value_and_grad(
                    _loss_fn, [w], (x, y), sparsity=0.5,
                    residuals=res, mesh=mesh)
                vel = 0.8 * vel + grads[0]
                w = w - 0.02 * vel
                losses.append(float(loss))
        assert losses[-1] < 0.05 * losses[0], (losses[0], losses[-1])
