"""Goodput accounting (ISSUE 8) — the wall-clock attribution timeline.

The contract under test:

  1. CONSERVATION — categorized spans + idle ≡ wall within ε, on a real
     fit loop, a checkpointed loop, and a kill-and-restart run; spans
     from the instrumented seams never double-count (overlap ≈ 0).
  2. RESTART ATTRIBUTION — an injected kill shows up in the stitched
     report as nonzero `restart_downtime` + `replay`, with the
     replayed-step count matching the resume step delta.
  3. OVERHEAD — a record() costs <1% of the CPU toy's median step wall
     at the seams' spans-per-step rate, measured and asserted.
  4. INPUT STALLS — the prefetch-thread loader counts empty-buffer waits
     as `input_wait` (producer split), keeps warm-buffer waits ≈ 0,
     honors `timeout=` with a named error, and the resumable cursor is
     unaffected by the instrumentation.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.io import DataLoader, DataLoaderTimeoutError
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.jit.train_step import TrainStep
from paddle_tpu.profiler import timeline as tl_mod
from paddle_tpu.profiler.goodput import (BADPUT_CATEGORIES,
                                         ConservationError, GoodputReport,
                                         report_from)
from paddle_tpu.profiler.monitor import StepMonitor
from paddle_tpu.profiler.timeline import (CATEGORIES, SpanRecorder,
                                          load_segments)
from paddle_tpu.resilience import CheckpointManager

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "mini_step.trace.json.gz")


# ------------------------------------------------------------- helpers

class _Net(nn.Layer):
    def __init__(self, d_in=8, d_h=16, d_out=4):
        super().__init__()
        self.fc1 = nn.Linear(d_in, d_h)
        self.fc2 = nn.Linear(d_h, d_out)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _mk_step(seed=0, d_in=8, d_h=16, d_out=4, **kw):
    paddle.seed(seed)
    net = _Net(d_in, d_h, d_out)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    lossf = nn.CrossEntropyLoss()
    return TrainStep(net, opt, lambda x, y: lossf(net(x), y), **kw)


def _batch(seed=0, b=16, d_in=8, d_out=4):
    rng = np.random.RandomState(seed)
    return (rng.randn(b, d_in).astype(np.float32),
            rng.randint(0, d_out, (b,)).astype(np.int64))


def _write_seg(path, wall0, rows, exit_row=None, seg_id="s"):
    """Hand-author a segment file: rows = (cat, t0, t1[, step[, steps]])."""
    with open(path, "w") as f:
        f.write(json.dumps({"segment": {"id": seg_id, "pid": 1,
                                        "wall0": wall0, "meta": {}}}) + "\n")
        for r in rows:
            row = {"cat": r[0], "t0": r[1], "t1": r[2]}
            if len(r) > 3 and r[3] is not None:
                row["step"] = r[3]
            if len(r) > 4:
                row["steps"] = r[4]
            f.write(json.dumps(row) + "\n")
        if exit_row is not None:
            f.write(json.dumps({"exit": exit_row}) + "\n")


# ======================================================== SpanRecorder

class TestSpanRecorder:
    def test_taxonomy_is_closed(self):
        rec = SpanRecorder()
        with pytest.raises(ValueError, match="unknown timeline category"):
            rec.record("cofee_break", 0.0, 1.0)
        for cat in CATEGORIES:
            rec.record(cat, 0.0, 0.1)

    def test_ring_caps_memory_but_file_keeps_all(self, tmp_path):
        p = str(tmp_path / "seg.timeline.jsonl")
        rec = SpanRecorder(p, capacity=4)
        for i in range(10):
            rec.record("step", i, i + 0.5, step=i + 1)
        assert len(rec.spans()) == 4
        assert rec.dropped == 6
        rec.close()
        segs = load_segments(str(tmp_path))
        assert len(segs) == 1 and len(segs[0].spans) == 10

    def test_jsonl_round_trip_with_exit_stamp(self, tmp_path):
        p = str(tmp_path / "seg.timeline.jsonl")
        rec = SpanRecorder(p, meta={"job": "t"})
        rec.record("compile", 0.0, 1.5, step=1)
        rec.record("step", 1.6, 1.7, step=2, note="x")
        rec.mark_exit("preemption", step=2, signum=15)
        rec.mark_exit("second-call-ignored")       # first stamp wins
        rec.close()
        (seg,) = load_segments(p)
        assert [s.cat for s in seg.spans] == ["compile", "step"]
        assert seg.spans[1].meta == {"note": "x"}
        assert seg.spans[0].abs0 == pytest.approx(seg.wall0 + 0.0)
        assert seg.exit_row["reason"] == "preemption"
        assert seg.exit_row["step"] == 2
        assert seg.max_step == 2
        # end = exit stamp (later than the last span)
        assert seg.end == pytest.approx(seg.wall0 + seg.exit_row["t"])

    def test_install_current_and_context(self):
        assert tl_mod.current() is None
        rec = SpanRecorder()
        with tl_mod.installed(rec):
            assert tl_mod.current() is rec
            with rec.span("other", tag="ctx"):
                pass
        assert tl_mod.current() is None
        (sp,) = rec.spans()
        assert sp.cat == "other" and sp.meta == {"tag": "ctx"}
        assert sp.t1 >= sp.t0

    def test_thread_safety(self, tmp_path):
        rec = SpanRecorder(str(tmp_path / "t.timeline.jsonl"))

        def work(k):
            for i in range(200):
                t = rec.now()
                rec.record("step", t, t, step=k * 1000 + i)

        ts = [threading.Thread(target=work, args=(k,)) for k in range(4)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        rec.close()
        (seg,) = load_segments(str(tmp_path))
        assert len(seg.spans) == 800


# ===================================================== report stitching

class TestGoodputReportSynthetic:
    def test_conservation_exact_with_gap(self, tmp_path):
        p = str(tmp_path / "a.timeline.jsonl")
        _write_seg(p, 1000.0, [("compile", 0.0, 1.0, 1),
                               ("step", 2.0, 3.0, 2)])
        rep = report_from(p)
        assert rep.wall_s == pytest.approx(3.0)
        assert rep.categorized_s == pytest.approx(2.0)
        assert rep.idle_s == pytest.approx(1.0)
        assert rep.goodput_s == pytest.approx(1.0)
        assert rep.goodput_ratio == pytest.approx(1 / 3)
        detail = rep.check_conservation()
        assert abs(detail["residual_s"]) < 1e-9

    def test_overlapping_spans_violate_conservation(self, tmp_path):
        p = str(tmp_path / "a.timeline.jsonl")
        _write_seg(p, 0.0, [("step", 0.0, 2.0, 1),
                            ("eval", 1.0, 2.0)])       # nested: 1s double
        rep = report_from(p)
        assert rep.overlap_s == pytest.approx(1.0)
        with pytest.raises(ConservationError, match="double-count"):
            rep.check_conservation()

    def test_replay_and_derived_restart_downtime(self, tmp_path):
        # segment 1: steps 1..5, dies (exit stamp) at t=6
        _write_seg(str(tmp_path / "s0.timeline.jsonl"), 1000.0,
                   [("step", float(i), i + 0.5, i) for i in range(1, 6)],
                   exit_row={"t": 6.0, "reason": "kill", "step": 5})
        # segment 2 (restart): resumes from ckpt step 3 → re-runs 4..5
        # (4 under a fresh compile), then fresh steps 6..8
        rows = [("compile", 0.0, 1.0, 4)]
        rows += [("step", float(i - 3), i - 2.5, i) for i in range(5, 9)]
        _write_seg(str(tmp_path / "s1.timeline.jsonl"), 1010.0, rows)
        rep = report_from(str(tmp_path))
        assert rep.restarts == 1
        # downtime: seg1 died at abs 1006, seg2 starts at abs 1010
        assert rep.category_s["restart_downtime"] == pytest.approx(4.0)
        assert rep.derived_downtime_s == pytest.approx(4.0)
        # replayed steps = {4 (compile re-run), 5}; only step-5's span
        # time moves to `replay` (compile time stays compile)
        assert rep.replayed_steps == {4, 5}
        assert rep.category_s["replay"] == pytest.approx(0.5)
        assert rep.category_s["compile"] == pytest.approx(1.0)
        # goodput = steps 1..5 pre-kill (2.5s) + fresh 6..8 (1.5s);
        # the re-run of step 5 sits in `replay`, not here
        assert rep.goodput_s == pytest.approx(5 * 0.5 + 3 * 0.5)
        rep.check_conservation()
        assert "replayed steps: 2" in rep.table()

    def test_explicit_supervisor_downtime_not_double_counted(self,
                                                             tmp_path):
        _write_seg(str(tmp_path / "s0.timeline.jsonl"), 1000.0,
                   [("step", 1.0, 2.0, 1)],
                   exit_row={"t": 6.0, "reason": "kill"})
        # supervisor segment explicitly covers [1006, 1009] of the gap
        _write_seg(str(tmp_path / "sup.timeline.jsonl"), 1000.0,
                   [("restart_downtime", 6.0, 9.0)], seg_id="sup")
        _write_seg(str(tmp_path / "s1.timeline.jsonl"), 1010.0,
                   [("step", 0.0, 1.0, 2)])
        rep = report_from(str(tmp_path))
        # 3s explicit + 1s derived remainder — never 3 + 4
        assert rep.category_s["restart_downtime"] == pytest.approx(4.0)
        assert rep.derived_downtime_s == pytest.approx(1.0)
        # the supervisor's downtime-only segment is not a process
        # incarnation: one worker restart, not two
        assert rep.restarts == 1
        rep.check_conservation()

    def test_segments_from_different_runs_are_refused(self, tmp_path):
        """Regression (review): stitching unrelated runs (a chaos
        --sweep's per-seed dirs) would recategorize every later run as
        replay of the earlier one — refuse instead."""
        for i, run in enumerate(["seed0", "seed1"]):
            p = str(tmp_path / f"{run}.timeline.jsonl")
            with open(p, "w") as f:
                f.write(json.dumps({"segment": {
                    "id": run, "pid": 1, "wall0": 100.0 + 50 * i,
                    "meta": {"run": run}}}) + "\n")
                f.write(json.dumps({"cat": "step", "t0": 0.0, "t1": 1.0,
                                    "step": 1}) + "\n")
        with pytest.raises(ValueError, match="different runs"):
            report_from(str(tmp_path))
        import tools.goodput_report as gr
        assert gr.main([str(tmp_path)]) == 2
        # each run on its own is fine
        assert gr.main([str(tmp_path / "seed0.timeline.jsonl")]) == 0

    def test_metrics_text_exposes_all_categories(self, tmp_path):
        p = str(tmp_path / "a.timeline.jsonl")
        _write_seg(p, 0.0, [("step", 0.0, 1.0, 1), ("compile", 1.0, 3.0, 0)])
        text = report_from(p).metrics_text()
        assert "# TYPE paddle_tpu_goodput_ratio gauge" in text
        assert "paddle_tpu_goodput_seconds 1" in text
        for c in BADPUT_CATEGORIES:
            assert f'paddle_tpu_badput_seconds{{category="{c}"}}' in text
        assert 'paddle_tpu_badput_seconds{category="compile"} 2' in text


# ================================================= instrumented seams

class TestTrainStepSpans:
    def test_compile_then_step_spans_and_conservation(self, tmp_path):
        rec = SpanRecorder(str(tmp_path / "s.timeline.jsonl"))
        step = _mk_step(timeline=rec)   # explicit handle, no install
        x, y = _batch()
        for _ in range(4):
            step(x, y)
        spans = rec.spans()
        assert [s.cat for s in spans] == ["compile", "step", "step", "step"]
        assert [s.step for s in spans] == [1, 2, 3, 4]
        rep = GoodputReport(rec)
        rep.check_conservation()
        assert rep.goodput_s > 0
        assert rep.category_s["compile"] > rep.goodput_s  # CPU toy truth

    def test_run_steps_records_multi_step_span(self, tmp_path):
        rec = SpanRecorder()
        step = _mk_step(timeline=rec)
        x, y = _batch(b=8)
        stacked = (np.stack([x, x]), np.stack([y, y]))
        step.run_steps(2, *stacked)
        step.run_steps(2, *stacked)
        spans = rec.spans()
        assert [s.cat for s in spans] == ["compile", "step"]
        assert spans[0].steps == 2 and spans[0].step == 2
        assert spans[1].steps == 2 and spans[1].step == 4

    def test_installed_recorder_is_picked_up(self):
        rec = SpanRecorder()
        step = _mk_step()
        x, y = _batch()
        with tl_mod.installed(rec):
            step(x, y)
        step(x, y)      # not installed: no span
        assert len(rec.spans()) == 1


class TestCheckpointSpans:
    def test_sync_save_is_ckpt_blocking(self, tmp_path):
        rec = SpanRecorder()
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.timeline = rec
        mgr.save(1, {"w": np.zeros((4, 4), np.float32)})
        cats = [s.cat for s in rec.spans()]
        assert "ckpt_blocking" in cats
        (blk,) = [s for s in rec.spans() if s.cat == "ckpt_blocking"]
        assert blk.meta["mode"] == "sync" and blk.step == 1

    def test_async_save_snapshot_plus_drain(self, tmp_path):
        rec = SpanRecorder()
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.timeline = rec
        h = mgr.save(2, {"w": np.zeros((64, 64), np.float32)},
                     async_save=True)
        mgr.wait()
        assert h.done()
        cats = [s.cat for s in rec.spans()]
        assert cats.count("ckpt_blocking") == 1
        snap = next(s for s in rec.spans() if s.cat == "ckpt_blocking")
        assert snap.meta["mode"] == "async_snapshot"
        assert "ckpt_drain" in cats

    def test_checkpointed_loop_conservation(self, tmp_path):
        """Acceptance: conservation on a checkpointed train loop, with
        the checkpoint categories present in the breakdown."""
        rec = SpanRecorder(str(tmp_path / "s.timeline.jsonl"))
        mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=2)
        with tl_mod.installed(rec):
            step = _mk_step()
            x, y = _batch()
            for i in range(6):
                step(x, y)
                if (i + 1) % 2 == 0:
                    mgr.save(i + 1, step.state_dict(), async_save=True)
            mgr.wait()
        rec.close()
        rep = report_from(str(tmp_path / "s.timeline.jsonl"))
        rep.check_conservation()
        assert rep.goodput_s > 0
        assert rep.category_s["ckpt_blocking"] > 0
        assert rep.category_s["ckpt_drain"] > 0


class TestKillAndRestartTimeline:
    def test_restart_downtime_and_replay_attributed(self, tmp_path):
        """Acceptance: a kill-and-restart run shows nonzero
        restart_downtime and replay, the replayed-step count matches the
        resume step delta, and conservation holds across the stitched
        segments."""
        tdir = str(tmp_path)
        mgr = CheckpointManager(os.path.join(tdir, "ck"))
        x, y = _batch()
        kill_at, save_at, total = 4, 2, 7

        rec1 = SpanRecorder(os.path.join(tdir, "seg0.timeline.jsonl"))
        with tl_mod.installed(rec1):
            step = _mk_step(seed=3)
            for i in range(kill_at):
                step(x, y)
                if step._step_i == save_at:
                    mgr.save(save_at, step.state_dict())
            rec1.mark_exit("kill", step=kill_at)
        rec1.close()

        time.sleep(0.08)                       # the outage
        rec2 = SpanRecorder(os.path.join(tdir, "seg1.timeline.jsonl"))
        with tl_mod.installed(rec2):
            step = _mk_step(seed=3)            # fresh "process"
            resumed_at, sd = mgr.restore_latest()
            step.set_state_dict(sd)
            assert resumed_at == save_at
            while step._step_i < total:
                step(x, y)
        rec2.close()

        rep = report_from(tdir)
        rep.check_conservation()
        s = rep.summary()
        assert s["restarts"] == 1
        assert s["badput_s"]["restart_downtime"] >= 0.08
        assert s["replayed_steps"] == kill_at - save_at
        assert rep.replayed_steps == set(range(save_at + 1, kill_at + 1))
        # the first re-run rides a fresh compile; later re-runs are
        # replay TIME (both are replayed STEPS)
        assert s["badput_s"]["replay"] > 0
        assert rep.goodput_s > 0

    def test_elastic_supervisor_records_explicit_downtime(self):
        from paddle_tpu.distributed.fleet.elastic import run_with_restarts
        rec = SpanRecorder()
        codes = iter([42, 1, 0])
        report = run_with_restarts(lambda: next(codes),
                                   backoff_s=0.01, sleep=time.sleep,
                                   timeline=rec)
        assert report.final_code == 0
        downs = [s for s in rec.spans() if s.cat == "restart_downtime"]
        assert [d.meta["kind"] for d in downs] == ["resume", "crash"]
        assert downs[1].dur >= 0.01            # includes the backoff


class TestHapiFitTimeline:
    def test_callback_survives_aborted_fit(self):
        """Regression (review): a fit that dies mid-epoch (Preempted)
        never runs on_train_end — the next cycle's on_train_begin must
        not adopt the stale self-install as 'previous', or on_train_end
        would re-install a dead recorder instead of clearing the slot."""
        from paddle_tpu.hapi.callbacks import ProfilerCallback
        rec = SpanRecorder()
        cb = ProfilerCallback(timeline=rec, summary=False)
        cb.on_train_begin()            # cycle 1 ... dies, no on_train_end
        assert tl_mod.current() is rec
        cb.on_train_begin()            # restart cycle, same callback
        cb.on_train_end()
        assert tl_mod.current() is None

    def test_fit_loop_conservation_with_eval(self, tmp_path, capsys):
        """Acceptance: conservation on a real Model.fit loop (fused
        path), with eval passes attributed to the `eval` category."""
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.callbacks import ProfilerCallback
        from paddle_tpu.io.dataset import TensorDataset

        paddle.seed(0)
        rng = np.random.RandomState(0)
        xs = rng.randn(32, 8).astype(np.float32)
        ys = rng.randint(0, 4, (32, 1)).astype(np.int64)
        ds = TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])
        net = _Net()
        model = Model(net)
        model.prepare(optimizer=paddle.optimizer.Adam(
            learning_rate=1e-3, parameters=net.parameters()),
            loss=nn.CrossEntropyLoss())
        rec = SpanRecorder(str(tmp_path / "fit.timeline.jsonl"))
        cb = ProfilerCallback(timeline=rec, summary=False)
        model.fit(ds, eval_data=ds, batch_size=8, epochs=2, verbose=0,
                  callbacks=[cb])
        rec.close()
        assert tl_mod.current() is None        # restored after fit
        rep = report_from(str(tmp_path / "fit.timeline.jsonl"))
        rep.check_conservation()
        cats = {s.cat for _, s in rep.spans}
        assert "step" in cats and "compile" in cats and "eval" in cats
        assert "input_wait" in cats            # sync-loader fetches
        assert rep.goodput_s > 0
        assert rep.category_s["eval"] > 0


# =================================================== overhead contract

class TestRecorderOverhead:
    def test_record_cost_under_1pct_of_step_wall(self, tmp_path):
        """Acceptance: recorder overhead <1% of the CPU toy's median
        step wall. Direct measurement (not paired wall deltas — this
        shared box swings ±5% run to run): per-record cost at the
        seams' span rate (step + input fetch + ckpt ≈ 3 spans/step)
        against the median steady step wall."""
        # compute-dominated toy (the chaos --overhead leg's discipline:
        # the claim is only visible when a step costs more than the
        # bookkeeping under test)
        step = _mk_step(d_in=256, d_h=1024, d_out=16)
        rng = np.random.RandomState(0)
        x = rng.randn(512, 256).astype(np.float32)
        y = rng.randint(0, 16, (512,)).astype(np.int64)
        rec = SpanRecorder(str(tmp_path / "o.timeline.jsonl"))
        step.timeline = rec
        walls = []
        for _ in range(12):
            t0 = time.perf_counter()
            loss = step(x, y)
            np.asarray(loss._data)             # step complete on host
            walls.append(time.perf_counter() - t0)
        med_step = sorted(walls[1:])[len(walls[1:]) // 2]  # drop compile

        n = 3000
        t0 = time.perf_counter()
        for i in range(n):
            t = rec.now()
            rec.record("step", t, t + 1e-4, step=i)
        per_record = (time.perf_counter() - t0) / n
        rec.close()
        overhead = 3 * per_record
        assert overhead < 0.01 * med_step, (
            f"recorder overhead {overhead*1e6:.1f}µs/step (3 spans × "
            f"{per_record*1e6:.1f}µs) is ≥1% of the {med_step*1e3:.2f}ms "
            f"median step wall")


# ==================================================== dataloader stalls

class _SlowDS(Dataset):
    """Module-level (picklable) slow dataset — used where the pool path
    must NOT be forced off; tests that need the prefetch-THREAD path use
    locally-defined (unpicklable) datasets instead."""

    def __init__(self, n=32, delay=0.0):
        self.n, self.delay = n, delay

    def __getitem__(self, i):
        if self.delay:
            time.sleep(self.delay)
        return np.full((4,), i, np.float32)

    def __len__(self):
        return self.n


def _thread_loader(n=16, delay=0.0, per_item_delay=None, **kw):
    """A num_workers>0 loader pinned to the prefetch-thread path: the
    dataset is a local class, so it doesn't pickle and the process-pool
    path falls back to the thread."""

    class LocalDS(Dataset):           # noqa: local on purpose (no pickle)
        def __getitem__(self, i):
            d = per_item_delay(i) if per_item_delay else delay
            if d:
                time.sleep(d)
            return np.full((4,), i, np.float32)

        def __len__(self):
            return n

    return DataLoader(LocalDS(), batch_size=4, num_workers=1, **kw)


class TestDataLoaderStalls:
    def test_empty_buffer_wait_is_counted_and_spanned(self):
        loader = _thread_loader(n=16, delay=0.02)
        rec = SpanRecorder()
        loader.timeline = rec
        batches = list(loader)
        assert len(batches) == 4
        st = loader.stall_stats()
        assert st["consumer_wait_s"] > 0
        assert st["stalled_batches"] >= 1
        waits = [s for s in rec.spans() if s.cat == "input_wait"]
        assert waits and all(s.meta["split"] == "producer" for s in waits)
        assert sum(s.dur for s in waits) == pytest.approx(
            st["consumer_wait_s"], rel=0.2, abs=0.05)

    def test_warm_buffer_wait_is_near_zero(self):
        loader = _thread_loader(n=32, delay=0.0)
        t0 = time.monotonic()
        for _ in loader:
            time.sleep(0.01)           # slow consumer: producer runs ahead
        wall = time.monotonic() - t0
        st = loader.stall_stats()
        # the first batch may stall while the producer warms the buffer;
        # steady state must not
        assert st["consumer_wait_s"] < 0.5 * wall
        assert st["stalled_batches"] <= 2
        # input ran ahead: the producer blocked on the FULL buffer
        assert st["producer_wait_s"] > 0

    def test_timeout_enforced_with_named_error(self):
        loader = _thread_loader(
            n=16, per_item_delay=lambda i: 10.0 if i >= 4 else 0.0,
            timeout=0.3)
        rec = SpanRecorder()
        loader.timeline = rec
        with pytest.raises(DataLoaderTimeoutError,
                           match="prefetch-thread") as ei:
            list(loader)
        assert ei.value.worker == "prefetch-thread"
        assert ei.value.waited_s >= 0.3
        spans = [s for s in rec.spans() if s.cat == "input_wait"]
        assert any(s.meta.get("timed_out") for s in spans)

    def test_cursor_resume_unaffected_by_prefetch_instrumentation(self):
        def harvest(loader, upto=None):
            out = []
            for b in loader:
                out.append(np.asarray(b._data))
                if upto and len(out) >= upto:
                    break
            return out

        ref = _thread_loader(n=32, shuffle=True, seed=7)
        want = harvest(ref)                       # full epoch, in order

        fwd = _thread_loader(n=32, shuffle=True, seed=7)
        head = harvest(fwd, upto=3)
        cursor = fwd.state_dict()
        assert cursor["batch_idx"] == 3
        resumed = _thread_loader(n=32, shuffle=True, seed=7)
        resumed.set_state_dict(cursor)
        tail = harvest(resumed)
        got = head + tail
        assert len(got) == len(want)
        for a, b in zip(got, want):
            assert a.tobytes() == b.tobytes()

    def test_end_of_epoch_sentinel_is_not_a_stall(self):
        """Regression (review): blocking on the end-of-epoch _END
        sentinel is not an input stall — an empty dataset's whole epoch
        is one sentinel wait and must record zero stalled batches."""
        loader = _thread_loader(n=0)
        rec = SpanRecorder()
        loader.timeline = rec
        assert list(loader) == []
        st = loader.stall_stats()
        assert st["stalled_batches"] == 0
        assert st["consumer_wait_s"] == 0
        assert not [s for s in rec.spans() if s.cat == "input_wait"]

    def test_abandoned_consumer_stops_producer_fetches(self):
        """Regression (review): once the consumer abandons iteration,
        the producer must stop fetching — the put fast path checks the
        stop flag before filling freed queue slots."""
        fetched = []

        class CountingDS(Dataset):     # local: pins the thread path
            def __getitem__(self, i):
                fetched.append(i)
                time.sleep(0.01)
                return np.float32(i)

            def __len__(self):
                return 64

        loader = DataLoader(CountingDS(), batch_size=4, num_workers=1)
        for _ in loader:
            break                      # abandon after one batch
        n_at_break = len(fetched)
        time.sleep(0.3)                # producer drains its last put
        assert len(fetched) - n_at_break <= 2 * loader.prefetch_factor * 4
        assert len(fetched) < 64

    def test_sync_path_records_fetch_as_input_wait(self):
        ds = _SlowDS(n=12)
        loader = DataLoader(ds, batch_size=4, num_workers=0)
        rec = SpanRecorder()
        with tl_mod.installed(rec):
            n = len(list(loader))
        waits = [s for s in rec.spans() if s.cat == "input_wait"]
        assert len(waits) == n == 3
        assert all(s.meta["split"] == "sync" for s in waits)


# ================================================== overlap_ratio gauge

class TestOverlapGauge:
    def test_record_overlap_surfaces_ratio(self):
        from paddle_tpu.profiler.trace_analysis import analyze
        ov = analyze(FIXTURE).overlap()
        assert ov["ratio"] == pytest.approx(0.5)   # the r7 fixture truth
        mon = StepMonitor(track_memory=False)
        mon.record_overlap(ov)
        assert mon.report()["overlap_ratio"] == pytest.approx(0.5)
        text = mon.metrics_text()
        assert "# TYPE paddle_tpu_overlap_ratio gauge" in text
        assert "paddle_tpu_overlap_ratio 0.5" in text

    def test_unset_overlap_is_absent_not_zero(self):
        mon = StepMonitor(track_memory=False)
        assert mon.report()["overlap_ratio"] is None
        assert "overlap_ratio" not in mon.metrics_text()

    def test_bare_ratio_accepted(self):
        mon = StepMonitor(track_memory=False)
        mon.record_overlap(0.25)
        assert mon.report()["overlap_ratio"] == pytest.approx(0.25)


# ============================================================ CLI + CI

class TestGoodputCLI:
    def _mk_run(self, tmp_path):
        _write_seg(str(tmp_path / "s0.timeline.jsonl"), 100.0,
                   [("compile", 0.0, 1.0, 1), ("step", 1.0, 3.0, 2)],
                   exit_row={"t": 3.0, "reason": "kill"})
        _write_seg(str(tmp_path / "s1.timeline.jsonl"), 104.0,
                   [("step", 0.0, 1.0, 2), ("step", 1.0, 2.0, 3)])
        return str(tmp_path)

    def test_cli_table_and_gates(self, tmp_path, capsys):
        import tools.goodput_report as gr
        run = self._mk_run(tmp_path)
        assert gr.main([run]) == 0
        out = capsys.readouterr().out
        assert "Goodput attribution" in out and "restart" in out
        assert gr.main([run, "--min-goodput", "0.2"]) == 0
        assert gr.main([run, "--min-goodput", "0.99"]) == 1
        assert gr.main([str(tmp_path / "nope")]) == 2

    def test_cli_json_has_attribution(self, tmp_path, capsys):
        import tools.goodput_report as gr
        run = self._mk_run(tmp_path)
        assert gr.main([run, "--json"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["conservation_ok"] is True
        assert out["restarts"] == 1
        assert out["replayed_steps"] == 1
        assert out["badput_s"]["restart_downtime"] == pytest.approx(1.0)

    def test_check_tiers_goodput_budget(self):
        import tools.check_tiers as ct
        recs = [{"nodeid": "t::a", "duration": 1.0, "markers": []}]
        ok = ct.check(recs, budget=100, slow_threshold=60,
                      goodput_seconds=5.0, goodput_budget=30.0)
        assert ok["ok"] and not ok["goodput_over_budget"]
        bad = ct.check(recs, budget=100, slow_threshold=60,
                       goodput_seconds=45.0, goodput_budget=30.0)
        assert not bad["ok"] and bad["goodput_over_budget"]
