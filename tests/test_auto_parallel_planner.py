"""Auto-parallel cluster/cost-model/planner (VERDICT §2.2 partial row:
the reference's cluster.py + cost/ + planner_v2.py capability)."""
import json
import os

import numpy as np
import pytest

from paddle_tpu.distributed.auto_parallel import (
    Cluster, ModelDesc, Planner, estimate_plan, ring_all_reduce_time)


GPT13 = ModelDesc(hidden=2048, layers=24, heads=16, vocab=50304)
GPT67 = ModelDesc(hidden=4096, layers=32, heads=32, vocab=50304)


def test_cluster_presets_and_json(tmp_path):
    c = Cluster.preset("v5e", 8)
    assert c.num_chips == 8 and c.peak_flops == 197e12
    p = str(tmp_path / "cluster.json")
    c.to_json(p)
    c2 = Cluster.from_json(p)
    assert c2.__dict__ == c.__dict__


def test_comm_cost_shapes():
    assert ring_all_reduce_time(1e9, 1, 45e9) == 0.0
    t2 = ring_all_reduce_time(1e9, 2, 45e9)
    t8 = ring_all_reduce_time(1e9, 8, 45e9)
    assert 0 < t2 < t8 < 2 * 1e9 / 45e9  # bounded by 2x buffer/bw


def test_estimate_more_chips_faster():
    c8 = Cluster.preset("v5e", 8)
    one = estimate_plan(GPT13, c8, {"dp": 1}, batch=8, seq=1024)
    eight = estimate_plan(GPT13, c8, {"dp": 8}, batch=8, seq=1024)
    assert eight.step_time < one.step_time


def test_memory_pruning_and_remat_rescue():
    c = Cluster.preset("v5e", 8)
    # 6.7B pure-dp on a 16G chip cannot fit (params+moments ~ 53G)
    solo = estimate_plan(GPT67, c, {"dp": 8}, batch=8, seq=1024)
    assert not solo.fits
    plans = Planner(c).tune(GPT67, batch=8, seq=1024)
    assert plans, "planner found no feasible 6.7B plan on 8 chips"
    assert all(p.fits for p in plans)
    assert all(p.mesh["mp"] * p.mesh["pp"] > 1 for p in plans), \
        "6.7B needs model/pipeline sharding on 16G chips"


def test_planner_ranks_sanely_for_13b_class():
    c = Cluster.preset("v5e", 8)
    plans = Planner(c).tune(GPT13, batch=8, seq=1024)
    assert plans and plans[0].fits
    assert plans[0].step_time <= plans[-1].step_time
    best = plans[0].mesh
    assert best["dp"] * best["mp"] * best["pp"] == 8
    # 1.3B fits per-chip with bf16 moments: pure-ish dp should win or tie
    assert best["mp"] <= 2 and best["pp"] <= 2, plans


def test_tp_beyond_heads_excluded():
    c = Cluster.preset("v5e", 64)
    plans = Planner(c).tune(GPT13, batch=64, seq=1024, max_mp=16)
    assert all(p.mesh["mp"] <= 16 for p in plans)
