"""Flash-attention kernel parity: fwd + blockwise bwd vs XLA reference
(interpret mode on CPU; the driver exercises compiled mode on TPU)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.ops.attention import attention_reference


def _rand(b, s, h, d, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)) * 0.3,
            jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)) * 0.3,
            jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)) * 0.3)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("d", [128, 64])
def test_flash_forward_matches_reference(causal, d):
    q, k, v = _rand(2, 256, 2, d)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    want = attention_reference(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("d", [128, 64])
def test_flash_backward_matches_reference(causal, d):
    q, k, v = _rand(1, 256, 2, d, seed=1)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=128,
                                       block_k=128, interpret=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, is_causal=causal) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg=f"d{name} mismatch")


def test_flash_cross_attention_lengths():
    q, _, _ = _rand(1, 128, 2, 64, seed=2)
    _, k, v = _rand(1, 512, 2, 64, seed=3)
    out = flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_flash_autotune_measured_selection(tmp_path, monkeypatch):
    """PHI-autotune analog (SURVEY §2.1 autotune row): measured tile
    selection, persistent cache hit on the second call."""
    import paddle_tpu as paddle
    from paddle_tpu.ops.pallas import autotune as at
    monkeypatch.setattr(at, "_CACHE_PATH", str(tmp_path / "at.json"))
    at._CACHE = None
    calls = {"n": 0}

    def bench_fn(cand):
        calls["n"] += 1
        import jax.numpy as jnp
        # pretend (512, 512) is fastest, (256,...) infeasible
        if cand[0] == 256:
            raise RuntimeError("vmem oom")
        import time as _t
        # large contrast so the selection is robust on a loaded CI core
        delay = 0.0 if cand == (512, 512) else 0.05

        def run():
            _t.sleep(delay)
            return jnp.zeros(())
        return run

    best = at.tune("k", (8, 512), [(1024, 512), (512, 512), (256, 512)],
                   bench_fn, iters=1)
    assert best == (512, 512)
    n_first = calls["n"]
    assert n_first >= 2                   # measured multiple candidates
    best2 = at.tune("k", (8, 512), [(1024, 512), (512, 512)], bench_fn)
    assert best2 == (512, 512)
    assert calls["n"] == n_first          # cache hit: no re-measure
    # cache file persisted
    at._CACHE = None
    assert at.tune("k", (8, 512), [], bench_fn) == (512, 512)


def test_flash_autotune_flag_wiring():
    """FLAGS_flash_autotune routes flash_attention through the tuner."""
    import paddle_tpu as paddle
    from paddle_tpu.ops.pallas import flash_attention as fa, autotune as at
    seen = {}

    orig = at.tune_flash_blocks
    at.tune_flash_blocks = \
        lambda *a: (seen.setdefault("a", a), (512, 512))[1]
    try:
        paddle.set_flags({"FLAGS_flash_autotune": True})
        q = jnp.zeros((1, 512, 2, 64), jnp.float32)
        fa.flash_attention(q, q, q, causal=True, interpret=True)  # interpret: no tune
        assert "a" not in seen
        try:
            fa.flash_attention(q, q, q, causal=True)
        except Exception:
            pass  # compiled pallas can't run on the CPU test backend;
            #      the tuner consult happens before lowering
        assert seen["a"][1] == 512        # s_q reached the tuner
    finally:
        at.tune_flash_blocks = orig
        paddle.set_flags({"FLAGS_flash_autotune": False})


def test_tune_in_step_measures_full_step_and_caches(tmp_path, monkeypatch):
    """In-context autotune (VERDICT r2 #8): candidates are timed through a
    caller-supplied FULL step under override_blocks, the winner is the
    end-to-end-fastest (not the isolated-kernel-fastest), and it persists
    in the same cache tune() uses."""
    import time
    from paddle_tpu.ops.pallas import autotune as at

    monkeypatch.setattr(at, "_CACHE_PATH", str(tmp_path / "cache.json"))
    at._CACHE = None

    seen = []

    def build_step():
        cand = at._OVERRIDE
        seen.append(cand)

        def run():
            # candidate (512, 512) is fastest END-TO-END; (1024, 1024)
            # would win an isolated benchmark (simulated inversion)
            time.sleep({(1024, 1024): 0.03, (512, 512): 0.005,
                        (256, 256): 0.02}[cand])
            import jax.numpy as jnp
            return jnp.zeros(())

        return run

    got = at.tune_in_step("flash_step_test", (1, 2, 3),
                          [(1024, 1024), (512, 512), (256, 256)], build_step)
    assert got == (512, 512), got
    assert set(seen) == {(1024, 1024), (512, 512), (256, 256)}
    # cached: a second call must NOT rebuild anything
    seen.clear()
    got2 = at.tune_in_step("flash_step_test", (1, 2, 3),
                           [(1024, 1024)], build_step)
    assert got2 == (512, 512) and not seen


def test_override_blocks_reaches_flash(monkeypatch):
    """flash_attention honors the tuner's override at trace time."""
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import autotune as at
    from paddle_tpu.ops.pallas import flash_attention as fa

    q = jnp.zeros((1, 64, 2, 8), jnp.float32)
    with at.override_blocks(4, 4):
        out = fa.flash_attention(q, q, q, causal=True)
        assert out.shape == q.shape   # reference fallback ran (tiles < 8)


@pytest.mark.parametrize("kv_len", [197, 130, 256])
def test_flash_kv_len_padding_mask(kv_len):
    """kv_len masks zero-padded key rows: fwd AND grads must match the
    reference computed on the UNPADDED arrays (the ViT-197 path)."""
    s_pad = 256
    q, k, v = _rand(2, s_pad, 2, 64, seed=3)

    def f_flash(q, k, v):
        out = flash_attention(q, k, v, block_q=128, block_k=128,
                              interpret=True, kv_len=kv_len)
        return jnp.sum(out[:, :kv_len] ** 2)

    def f_ref(q, k, v):
        out = attention_reference(q[:, :kv_len], k[:, :kv_len], v[:, :kv_len],
                                  scale=1.0 / np.sqrt(64))
        return jnp.sum(out ** 2)

    np.testing.assert_allclose(float(f_flash(q, k, v)), float(f_ref(q, k, v)),
                               rtol=2e-4)
    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        # valid rows match; padded rows of dk/dv are exactly zero
        np.testing.assert_allclose(np.asarray(gf[:, :kv_len]),
                                   np.asarray(gr[:, :kv_len]),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg=f"d{name} valid-row mismatch")
        if name in "kv" and kv_len < s_pad:
            assert float(jnp.abs(gf[:, kv_len:]).max()) == 0.0, \
                f"d{name} padded rows must be zero"


def test_functional_attention_padded_flash_route(monkeypatch):
    """functional_attention at an odd S >= 512 routes through the padded
    flash kernel and matches the reference (interpret-mode check). Shorter
    odd sequences (e.g. ViT's 197) stay on the XLA path — measured faster
    at that scale."""
    import paddle_tpu.ops.attention as A
    q, k, v = _rand(1, 520, 1, 64, seed=4)
    want = attention_reference(q, k, v)
    # force the pallas predicate on, interpret via monkeypatched flash
    monkeypatch.setenv("PADDLE_TPU_FLASH", "1")
    import paddle_tpu.ops.pallas.flash_attention as FA
    orig = FA.flash_attention
    calls = []

    def interp_flash(*a, **kw):
        calls.append(kw.get("kv_len"))
        kw["interpret"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(FA, "flash_attention", interp_flash)
    got = A.functional_attention(q, k, v)
    assert calls == [520], f"padded flash route not taken: {calls}"
    assert got.shape == q.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


class TestPackedFlash:
    """flash_attention_packed: [B, S, nh*128] layout, in-kernel head loop."""

    def _qkv(self, B=2, S=256, NH=2, HD=128, seed=7):
        rng = np.random.RandomState(seed)
        H = NH * HD
        mk = lambda: jnp.asarray(rng.randn(B, S, H).astype(np.float32) * 0.3)
        return mk(), mk(), mk(), NH, HD

    def _ref(self, q, k, v, nh, hd, causal, kv_len=None):
        B, S, H = q.shape
        q4 = q.reshape(B, S, nh, hd)
        k4 = k.reshape(B, S, nh, hd)
        v4 = v.reshape(B, S, nh, hd)
        if kv_len is not None:
            k4, v4 = k4[:, :kv_len], v4[:, :kv_len]
        return attention_reference(q4, k4, v4, is_causal=causal,
                                   scale=1.0 / np.sqrt(hd)).reshape(B, S, H)

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_and_grads(self, causal):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention_packed
        q, k, v, NH, HD = self._qkv()

        def lf(q, k, v):
            return jnp.sum(flash_attention_packed(
                q, k, v, NH, causal=causal, block_q=128, block_k=128,
                interpret=True) ** 2)

        def lr(q, k, v):
            return jnp.sum(self._ref(q, k, v, NH, HD, causal) ** 2)

        np.testing.assert_allclose(float(lf(q, k, v)), float(lr(q, k, v)),
                                   rtol=2e-4)
        gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, c, nm in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       rtol=2e-3, atol=2e-3,
                                       err_msg=f"d{nm} causal={causal}")

    def test_kv_len(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention_packed
        q, k, v, NH, HD = self._qkv()
        out = flash_attention_packed(q, k, v, NH, block_q=128, block_k=128,
                                     interpret=True, kv_len=200)
        want = self._ref(q, k, v, NH, HD, False, kv_len=200)
        np.testing.assert_allclose(np.asarray(out[:, :200]),
                                   np.asarray(want[:, :200]),
                                   rtol=2e-4, atol=2e-4)

    def test_head_dim_fallback(self):
        # hd != 128 falls back to the 4-D kernel path (reference fallback
        # on CPU since tiles degrade) — shape contract holds
        from paddle_tpu.ops.pallas.flash_attention import flash_attention_packed
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(1, 64, 2 * 64).astype(np.float32))
        out = flash_attention_packed(q, q, q, 2, interpret=True)
        assert out.shape == q.shape

    def test_gpt_routes_through_packed(self, monkeypatch):
        """PADDLE_TPU_FLASH_PACKED=1 routes GPT training attention through
        the packed kernel (interpret-mode, tiny config)."""
        monkeypatch.setenv("PADDLE_TPU_FLASH_PACKED", "1")
        # the platform gate correctly refuses CPU — stub it for the
        # interpret-mode routing check
        import paddle_tpu.models.gpt as G
        monkeypatch.setattr(G, "_use_packed_flash", lambda: True)
        import paddle_tpu.ops.pallas.flash_attention as FA
        calls = []
        orig = FA.flash_attention_packed

        def spy(*a, **kw):
            calls.append(a[3] if len(a) > 3 else kw.get("num_heads"))
            kw["interpret"] = True
            return orig(*a, **kw)

        monkeypatch.setattr(FA, "flash_attention_packed", spy)
        import numpy as np_
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.models import GPTForCausalLM, gpt_config
        paddle.seed(0)
        cfg = gpt_config("gpt3-125m", hidden_size=256, num_layers=1,
                         num_heads=2, vocab_size=128,
                         max_position_embeddings=128)
        assert cfg.head_dim == 128
        m = GPTForCausalLM(cfg)
        ids = paddle.to_tensor(np_.random.randint(0, 128, (1, 128)).astype("int32"))
        lbl = paddle.to_tensor(np_.random.randint(0, 128, (1, 128)).astype("int64"))
        loss = m.loss(ids, lbl)
        loss.backward()
        assert calls, "packed kernel was not routed to"
        assert float(loss.numpy()) > 0 and np_.isfinite(float(loss.numpy()))

def test_flash_save_transposed_grad_parity():
    """PADDLE_TPU_FLASH_SAVE_T residual path (head-major residuals reused in
    bwd) must produce the same gradients as the default recompute-transpose
    path (advisor r3 finding: this opt-in had no coverage)."""
    q, k, v = _rand(2, 256, 2, 64, seed=7)

    def loss(st):
        def f(q, k, v):
            out = flash_attention(q, k, v, causal=True, block_q=128,
                                  block_k=128, interpret=True,
                                  save_transposed=st)
            return jnp.sum(out ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_def = loss(False)
    g_st = loss(True)
    for gd, gs, name in zip(g_def, g_st, "qkv"):
        np.testing.assert_allclose(np.asarray(gd), np.asarray(gs),
                                   rtol=1e-6, atol=1e-6,
                                   err_msg=f"d{name} save_transposed mismatch")


def test_flash_kv_len_nonpositive_rejected():
    """kv_len <= 0 would mask every key column and silently return a uniform
    average of V (advisor r3 finding) — must raise instead."""
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_packed
    q, k, v = _rand(1, 128, 2, 64)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, kv_len=0, interpret=True)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, kv_len=-3, interpret=True)
    qp = jnp.reshape(q, (1, 128, 128))
    with pytest.raises(ValueError):
        flash_attention_packed(qp, qp, qp, num_heads=1, kv_len=0,
                               interpret=True)
