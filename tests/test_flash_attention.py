"""Flash-attention kernel parity: fwd + blockwise bwd vs XLA reference
(interpret mode on CPU; the driver exercises compiled mode on TPU)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.ops.attention import attention_reference


def _rand(b, s, h, d, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)) * 0.3,
            jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)) * 0.3,
            jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)) * 0.3)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("d", [128, 64])
def test_flash_forward_matches_reference(causal, d):
    q, k, v = _rand(2, 256, 2, d)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    want = attention_reference(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("d", [128, 64])
def test_flash_backward_matches_reference(causal, d):
    q, k, v = _rand(1, 256, 2, d, seed=1)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=128,
                                       block_k=128, interpret=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, is_causal=causal) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg=f"d{name} mismatch")


def test_flash_cross_attention_lengths():
    q, _, _ = _rand(1, 128, 2, 64, seed=2)
    _, k, v = _rand(1, 512, 2, 64, seed=3)
    out = flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
