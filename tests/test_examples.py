"""Every runnable example executes end-to-end (slow tier; subprocess per
script, CPU mode — the examples' own default)."""
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(_REPO, "examples"))
    if f.endswith(".py"))


@pytest.mark.slow
@pytest.mark.parametrize("script", _EXAMPLES)
def test_example_runs(script):
    env = dict(os.environ)
    env.pop("PADDLE_TPU_EXAMPLE_TPU", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", script)],
        capture_output=True, text=True, timeout=600, env=env, cwd=_REPO)
    assert r.returncode == 0, (
        f"{script} failed\nstdout:\n{r.stdout[-2000:]}\n"
        f"stderr:\n{r.stderr[-2000:]}")
