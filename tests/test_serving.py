"""Serving observability: ServingEngine over the static decode stack,
request metrics (histograms/gauges/counters + JSONL records), the shared
Prometheus renderer, resumable decode_static, and the wired
inference.Config.enable_profile().

Engine acceptance (ISSUE 4): greedy outputs bit-identical to
generate_static_ragged on the same prompts; ZERO jit cache misses across a
steady-state serving loop after warmup; metrics_text() a valid Prometheus
exposition carrying TTFT/TPOT/e2e histograms + queue/batch/KV gauges.
"""
import json
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (Request, ServingConfig, ServingEngine,
                                  ServingMetrics, synthetic_traffic)
from paddle_tpu.jit.api import compile_cache_misses
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.profiler import LogHistogram, StepMonitor


# ---------------------------------------------------------- LogHistogram

class TestLogHistogram:
    def test_percentiles_match_numpy_on_known_samples(self):
        rng = np.random.RandomState(0)
        xs = np.exp(rng.randn(2000) * 0.8 - 2.5)       # lognormal latencies
        h = LogHistogram(lo=1e-4, hi=10.0, per_decade=20)
        for x in xs:
            h.observe(float(x))
        for q in (0.5, 0.9, 0.99):
            got = h.percentile(q)
            want = float(np.percentile(xs, q * 100))
            # derived-from-buckets error bound: one bucket's relative width
            assert abs(got - want) / want < 10 ** (1 / 20) - 1, (q, got, want)

    def test_edges_clamp_to_observed_extremes(self):
        h = LogHistogram(lo=0.01, hi=10, per_decade=4)
        for v in (0.5, 1.5, 3.0):
            h.observe(v)
        assert h.percentile(0.0) == 0.5
        assert h.percentile(1.0) == 3.0
        assert h.count == 3 and abs(h.sum - 5.0) < 1e-12
        assert abs(h.mean - 5.0 / 3) < 1e-12

    def test_overflow_and_underflow_buckets(self):
        h = LogHistogram(lo=0.1, hi=1.0, per_decade=2)
        h.observe(1e-5)                # below lo -> first bucket
        h.observe(50.0)                # beyond hi -> +Inf bucket
        assert h.counts[0] == 1 and h.counts[-1] == 1
        assert h.percentile(1.0) == 50.0

    def test_empty_histogram(self):
        h = LogHistogram()
        assert h.percentile(0.5) is None and h.mean is None
        assert h.summary()["count"] == 0

    def test_rejects_nan_and_bad_q(self):
        h = LogHistogram()
        with pytest.raises(ValueError):
            h.observe(float("nan"))
        h.observe(1.0)
        with pytest.raises(ValueError):
            h.percentile(1.5)


# ------------------------------------------- Prometheus exposition format

_SAMPLE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{le="[^"]+"\})? '
    r'(-?\d+(\.\d+)?([eE][-+]?\d+)?|\+Inf|NaN)$')


def _check_exposition(text):
    """Validate Prometheus text format 0.0.4 invariants; returns
    {metric_name: type}."""
    types, helped = {}, set()
    for line in text.strip().split("\n"):
        if line.startswith("# HELP "):
            helped.add(line.split()[2])
        elif line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            types[name] = kind
        else:
            m = _SAMPLE.match(line)
            assert m, f"malformed sample line: {line!r}"
            base = m.group(1)
            root = re.sub(r"_(bucket|sum|count)$", "", base)
            assert base in types or root in types, f"no TYPE for {line!r}"
    assert set(types) == helped, "HELP/TYPE mismatch"
    return types


def _histogram_invariants(text, name):
    """Bucket lines cumulative + ascending le; +Inf equals _count."""
    bucket_re = re.compile(
        rf'^{re.escape(name)}_bucket{{le="([^"]+)"}} (\d+)$', re.M)
    rows = [(le, int(c)) for le, c in bucket_re.findall(text)]
    assert rows and rows[-1][0] == "+Inf"
    counts = [c for _, c in rows]
    assert counts == sorted(counts), "buckets must be cumulative"
    les = [float(le) for le, _ in rows[:-1]]
    assert les == sorted(les), "le bounds must ascend"
    count = int(re.search(rf"^{re.escape(name)}_count (\d+)$", text,
                          re.M).group(1))
    assert rows[-1][1] == count, "+Inf bucket must equal _count"


class TestExpositionFormat:
    def test_serving_metrics_text_is_valid(self):
        met = ServingMetrics()
        rng = np.random.RandomState(1)
        for _ in range(50):
            r = Request(id=0, prompt=np.arange(4), max_new_tokens=4,
                        status="done", n_out=4)
            t = float(rng.uniform(0.001, 2.0))
            r.trace.t_enqueue, r.trace.t_admit = 0.0, 0.1 * t
            r.trace.t_first_token, r.trace.t_finish = 0.5 * t, t
            met.record_request(r)
        met.record_batch(n_real=3, capacity=4, kv_tokens=30, kv_slots=48,
                         kv_capacity=64, queue_depth=2)
        text = met.metrics_text()
        types = _check_exposition(text)
        for h in ("ttft_seconds", "tpot_seconds", "e2e_seconds",
                  "queue_seconds"):
            assert types[f"paddle_tpu_serving_{h}"] == "histogram"
            _histogram_invariants(text, f"paddle_tpu_serving_{h}")
        for g in ("queue_depth", "batch_fill_ratio", "kv_occupancy",
                  "kv_slots_occupancy"):
            assert types[f"paddle_tpu_serving_{g}"] == "gauge"
        for c in ("requests_total", "rejected_total", "timeout_total",
                  "tokens_in_total", "tokens_out_total"):
            assert types[f"paddle_tpu_serving_{c}"] == "counter"
        assert "paddle_tpu_serving_requests_total 50" in text

    def test_step_monitor_shares_the_renderer(self):
        mon = StepMonitor(items_per_step=4, track_memory=False)
        with mon.step():
            pass
        types = _check_exposition(mon.metrics_text())
        assert types["paddle_tpu_steps_total"] == "gauge"

    def test_summary_percentile_triplets(self):
        met = ServingMetrics()
        met.observe_call(0.25, items=8)
        s = met.summary()
        assert s["completed_total"] == 1 and s["items_total"] == 8
        assert s["tokens_out_total"] == 0          # rows are not tokens
        assert abs(s["e2e_seconds"]["p50"] - 0.25) < 0.05


# --------------------------------------------------- engine test fixtures

CAP, NEW, BATCH = 8, 6, 2


@pytest.fixture(scope="module")
def served_model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
                    max_position_embeddings=64, intermediate_size=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m, cfg


def _engine(m, **kw):
    base = dict(max_batch=BATCH, prompt_cap=CAP, max_new_tokens=NEW,
                decode_chunk=3)
    base.update(kw)
    return ServingEngine(m, ServingConfig(**base))


def _prompts(cfg, lens, seed=1):
    rng = np.random.RandomState(seed)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    return ids


# ------------------------------------------------------- resumable decode

def test_decode_static_resume_greedy_parity(served_model):
    """Chunked decode over return_state must replay the one-shot argmax
    chain bit-for-bit (ragged positions offset by `generated`)."""
    m, cfg = served_model
    lens = [CAP, 5]
    ids = _prompts(cfg, lens)
    t = paddle.to_tensor(ids)
    ref = m.generate_static_ragged(t, lens, max_new_tokens=NEW).numpy()[:, CAP:]
    st = m.prefill_static(t, max_len=CAP + NEW, prompt_lens=np.int32(lens))
    t1, st = m.decode_static(st, 1, return_state=True)
    t2, st = m.decode_static(st, 2, return_state=True)
    t3, st = m.decode_static(st, 3, return_state=True)
    got = np.concatenate([t1.numpy(), t2.numpy(), t3.numpy()], axis=1)
    np.testing.assert_array_equal(got, ref)
    assert st["generated"] == NEW
    with pytest.raises(ValueError, match="cache rows"):
        m.decode_static(st, 100)       # resumed capacity accounting


def test_decode_static_resume_carries_eos_mask(served_model):
    m, cfg = served_model
    lens = [CAP, 5]
    ids = _prompts(cfg, lens)
    t = paddle.to_tensor(ids)
    ref = m.generate_static_ragged(t, lens, max_new_tokens=NEW).numpy()
    eos = int(ref[0, CAP])             # row 0 "emits EOS" on token 1
    refe = m.generate_static_ragged(t, lens, max_new_tokens=NEW,
                                    eos_token_id=eos).numpy()[:, CAP:]
    st = m.prefill_static(t, max_len=CAP + NEW, prompt_lens=np.int32(lens))
    a, st = m.decode_static(st, 1, eos_token_id=eos, return_state=True)
    b, st = m.decode_static(st, NEW - 1, eos_token_id=eos,
                            return_state=True)
    got = np.concatenate([a.numpy(), b.numpy()], axis=1)
    np.testing.assert_array_equal(got, refe)
    assert (got[0] == eos).all()       # done row kept emitting EOS


# ------------------------------------------------------------ the engine

def test_engine_greedy_parity_with_ragged(served_model):
    """Acceptance: ServingEngine output == generate_static_ragged
    bit-for-bit on identical prompts."""
    m, cfg = served_model
    lens = [CAP, 5]
    ids = _prompts(cfg, lens)
    eng = _engine(m)
    for i in range(len(lens)):
        eng.submit(ids[i, :lens[i]])
    done = eng.drain()
    assert [r.status for r in done] == ["done", "done"]
    ref = m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                   max_new_tokens=NEW).numpy()[:, CAP:]
    np.testing.assert_array_equal(np.stack([r.tokens for r in done]), ref)
    # spans are complete and ordered for served requests
    for r in done:
        tr = r.trace
        assert tr.t_enqueue <= tr.t_admit <= tr.t_prefill_done \
            <= tr.t_first_token <= tr.t_finish
        assert tr.ttft_s >= 0 and tr.e2e_s >= tr.ttft_s


def test_engine_zero_recompiles_after_warmup(served_model):
    """Acceptance: a steady-state serving loop adds ZERO jit cache misses
    after the warmup batch — including partial batches (padded rows keep
    every shape pinned)."""
    m, cfg = served_model
    eng = _engine(m)
    ids = _prompts(cfg, [CAP, 5])
    eng.submit(ids[0, :CAP])
    eng.submit(ids[1, :5])
    eng.drain()                        # warmup: compiles prefill + chunks
    miss0 = compile_cache_misses()
    for i in range(3):
        eng.submit(ids[0, :CAP])
        if i != 1:
            eng.submit(ids[1, :5])     # batch 2 is partial: dummy-padded
        eng.drain()
    assert compile_cache_misses() - miss0 == 0
    assert eng.monitor.recompiles == 0
    assert all(r.get("jit_cache_misses", 0) == 0
               for r in eng.monitor.records[1:])


def test_engine_batch_gauges_and_counters(served_model):
    m, cfg = served_model
    eng = _engine(m)
    ids = _prompts(cfg, [4])
    eng.submit(ids[0, :4])             # 1 of 2 slots used
    eng.drain()
    s = eng.summary()
    assert s["batch_fill_ratio"] == 0.5
    assert 0 < s["kv_occupancy"] <= 1.0
    # padded engine: each admitted row pins a full max_len slab
    assert s["kv_slots_occupancy"] == 1 * eng.config.max_len / \
        (BATCH * eng.config.max_len)
    assert s["tokens_in_total"] == 4 and s["tokens_out_total"] == NEW
    assert s["batches_total"] == 1 and s["completed_total"] == 1
    assert s["batch_step"]["steps"] == 1


def test_engine_rejects_overlong_prompt_with_shape_delta(served_model):
    """A prompt beyond the cap would force a new prefill executable: the
    engine refuses and logs the would-be shape delta through
    StepMonitor.record_compile."""
    m, cfg = served_model
    eng = _engine(m)
    req = eng.submit(np.arange(1, CAP + 3))
    assert req.status == "rejected" and req.reason == "prompt_shape"
    assert eng.summary()["rejected_total"] == 1
    ev = eng.monitor.recompile_events[0]
    assert ev["kind"] == "serving_reject"
    assert str(CAP) in ev["delta"] and str(CAP + 2) in ev["delta"]
    # the warning must NOT feed the numeric churn counters: nothing was
    # built (the request was refused precisely so nothing would be)
    assert eng.monitor.recompiles == 0 and eng.monitor.compiles == 0
    assert eng.queue_depth == 0        # never admitted
    # repeat offenders count as rejections but warn only once per shape
    assert eng.submit(np.arange(1, CAP + 3)).status == "rejected"
    assert eng.summary()["rejected_total"] == 2
    assert len(eng.monitor.recompile_events) == 1


def test_engine_queue_full_rejection(served_model):
    m, cfg = served_model
    eng = _engine(m, queue_capacity=2)
    ids = _prompts(cfg, [3, 3, 3])
    assert eng.submit(ids[0, :3]).status == "queued"
    assert eng.submit(ids[1, :3]).status == "queued"
    r = eng.submit(ids[2, :3])
    assert r.status == "rejected" and r.reason == "queue_full"
    assert eng.summary()["rejected_total"] == 1
    assert eng.queue_depth == 2


def test_engine_deadline_timeout(served_model):
    """Requests whose queue wait blows their deadline expire at admission
    (deterministic via the injectable clock)."""
    m, cfg = served_model
    fake = {"t": 0.0}
    eng = ServingEngine(m, ServingConfig(max_batch=BATCH, prompt_cap=CAP,
                                         max_new_tokens=NEW, decode_chunk=3,
                                         deadline_s=0.5),
                        clock=lambda: fake["t"])
    ids = _prompts(cfg, [3, 3])
    eng.submit(ids[0, :3])                        # will expire
    eng.submit(ids[1, :3], deadline_s=10.0)       # per-request override
    fake["t"] = 1.0
    done = eng.drain()
    # expired traffic is a terminal RESULT, not silently dropped
    assert sorted(r.status for r in done) == ["done", "timeout"]
    timed = next(r for r in done if r.status == "timeout")
    assert timed.reason == "queue_deadline" and timed.tokens is None
    s = eng.summary()
    assert s["timeout_total"] == 1 and s["completed_total"] == 1
    # its queue wait (1.0s on the fake clock) lands in the histogram —
    # the longest waits must not vanish from the distribution at expiry
    assert abs(s["queue_seconds"]["p99"] - 1.0) < 0.2


def test_engine_eos_early_exit_and_token_counts(served_model):
    """With a forced-EOS vocabulary walk, finished rows report n_out up to
    and including EOS, and the chunk loop stops once every row is done."""
    m, cfg = served_model
    lens = [CAP, 5]
    ids = _prompts(cfg, lens)
    ref = m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                   max_new_tokens=NEW).numpy()
    eos = int(ref[0, CAP])
    eng = _engine(m, eos_token_id=eos)
    eng.submit(ids[0, :CAP])
    eng.submit(ids[1, :5])
    done = eng.drain()
    by_id = {r.id: r for r in done}
    assert by_id[0].n_out == 1                     # EOS was its 1st token
    assert by_id[0].tokens[0] == eos
    assert by_id[1].n_out >= 1
    s = eng.summary()
    assert s["tokens_out_total"] == sum(r.n_out for r in done)
    # per-row finish is chunk-granular: the EOS-on-token-1 row is stamped
    # at its own chunk, not charged for the batch's remaining chunks
    if by_id[1].n_out > 1:
        assert by_id[0].trace.t_finish < by_id[1].trace.t_finish
        assert by_id[1].trace.tpot_s(by_id[1].n_out) > 0


def test_warmup_depth_extension_is_not_a_recompile(served_model):
    """An EOS early-exit can truncate the warmup batch before the deeper
    chunk executables ever compiled; their eventual first compile is NOT
    shape churn and must not trip the steady-state recompile guard."""
    m, cfg = served_model
    lens = [CAP, 5]
    ids = _prompts(cfg, lens)
    ref = m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                   max_new_tokens=NEW).numpy()
    eos = int(ref[0, CAP])             # row 0 greedily emits EOS first
    eng = _engine(m, eos_token_id=eos)
    eng.submit(ids[0, :CAP])
    eng.drain()                        # warmup stops after chunk 1
    assert eng._max_depth == 2         # prefill + first-token chunk only
    eng.submit(ids[1, :5])             # decodes deeper than warmup did
    eng.drain()
    assert eng._max_depth > 2
    assert eng.monitor.recompiles == 0


def test_engine_respects_per_request_budget(served_model):
    m, cfg = served_model
    eng = _engine(m)
    ids = _prompts(cfg, [4, 4])
    eng.submit(ids[0, :4], max_new_tokens=2)
    eng.submit(ids[1, :4], max_new_tokens=100)     # clamped to engine max
    done = eng.drain()
    by_id = {r.id: r for r in done}
    assert by_id[0].tokens.shape[0] == 2
    assert by_id[1].tokens.shape[0] == NEW
    # a zero budget is unservable, not "serve 1 anyway"
    r = eng.submit(ids[0, :4], max_new_tokens=0)
    assert r.status == "rejected" and r.reason == "max_new_tokens"


def test_engine_exception_records_inflight_requests(served_model):
    """A batch dying mid-flight must not lose the admitted requests from
    the accounting: they land as status='error' before the raise."""
    m, cfg = served_model
    eng = _engine(m)
    eng.submit(_prompts(cfg, [4])[0, :4])
    real_prefill = m.prefill_static

    def boom(*a, **kw):
        raise RuntimeError("injected device failure")

    m.prefill_static = boom
    try:
        with pytest.raises(RuntimeError, match="injected"):
            eng.step()
    finally:
        m.prefill_static = real_prefill
    s = eng.summary()
    assert s["errors_total"] == 1 and s["inflight"] == 0
    assert eng.queue_depth == 0


def test_request_jsonl_schema(served_model, tmp_path):
    """One JSONL row per terminal request: nested "request" payload +
    "ts", spans and derived latencies present for served requests."""
    m, cfg = served_model
    jsonl = str(tmp_path / "requests.jsonl")
    eng = ServingEngine(m, ServingConfig(max_batch=BATCH, prompt_cap=CAP,
                                         max_new_tokens=NEW,
                                         decode_chunk=3),
                        metrics=ServingMetrics(jsonl_path=jsonl))
    ids = _prompts(cfg, [CAP, 5])
    eng.submit(ids[0, :CAP])
    eng.submit(ids[1, :5])
    eng.submit(np.arange(1, CAP + 5))              # rejected -> also a row
    eng.drain()
    rows = [json.loads(l) for l in open(jsonl)]
    assert len(rows) == 3
    for row in rows:
        assert set(row) == {"request", "ts"}
        r = row["request"]
        assert {"id", "status", "prompt_tokens", "output_tokens",
                "spans"} <= set(r)
    served = [r["request"] for r in rows if r["request"]["status"] == "done"]
    assert len(served) == 2
    for r in served:
        assert {"queue_s", "ttft_s", "tpot_s", "e2e_s"} <= set(r)
        assert {"t_enqueue", "t_admit", "t_prefill_done", "t_first_token",
                "t_finish", "batch_id"} <= set(r["spans"])
    rej = next(r["request"] for r in rows
               if r["request"]["status"] == "rejected")
    assert rej["reason"] == "prompt_shape" and rej["output_tokens"] == 0


def test_on_record_hook(served_model):
    m, cfg = served_model
    seen = []
    eng = ServingEngine(m, ServingConfig(max_batch=BATCH, prompt_cap=CAP,
                                         max_new_tokens=NEW,
                                         decode_chunk=3),
                        metrics=ServingMetrics(on_record=seen.append))
    eng.submit(_prompts(cfg, [4])[0, :4])
    eng.drain()
    assert len(seen) == 1 and seen[0]["request"]["status"] == "done"


def test_engine_metrics_text_is_valid_exposition(served_model):
    m, cfg = served_model
    eng = _engine(m)
    ids = _prompts(cfg, [CAP, 5])
    eng.submit(ids[0, :CAP])
    eng.submit(ids[1, :5])
    eng.drain()
    text = eng.metrics_text()
    types = _check_exposition(text)
    # request metrics and the batch StepMonitor block share one page
    assert "paddle_tpu_serving_ttft_seconds" in types
    assert "paddle_tpu_serving_batch_steps_total" in types
    _histogram_invariants(text, "paddle_tpu_serving_ttft_seconds")
    # the same page through the unified registry path (ISSUE 12): the
    # promtool-style lint covers everything _check_exposition pins plus
    # family contiguity/collisions — obs tests extend this to merged
    # multi-producer pages
    from paddle_tpu.obs import lint_exposition
    fams = lint_exposition(eng.metrics_registry().render())
    assert set(types) <= set(fams)


def test_synthetic_traffic_shape():
    tr = synthetic_traffic(16, prompt_cap=8, vocab_size=64, rate=100.0,
                           seed=0)
    assert len(tr) == 16
    ats = [t["at"] for t in tr]
    assert ats == sorted(ats) and ats[0] == 0.0
    assert all(1 <= t["prompt"].shape[0] <= 8 for t in tr)
    assert all(t["prompt"].min() >= 1 and t["prompt"].max() < 64
               for t in tr)


@pytest.mark.slow
def test_engine_under_load_open_loop(served_model):
    """Load generation: open-loop replay of 24 requests; everything
    completes, latency histograms fill, and the steady loop never
    recompiles (the serve_bench path minus the CLI)."""
    m, cfg = served_model
    eng = _engine(m)
    traffic = synthetic_traffic(24, prompt_cap=CAP,
                                vocab_size=cfg.vocab_size, rate=500.0,
                                seed=7)
    eng.submit(traffic[0]["prompt"])
    eng.drain()                        # warmup
    miss0 = compile_cache_misses()
    t0 = eng.clock()
    finished = []
    for item in traffic:
        eng.submit(item["prompt"], enqueue_at=t0 + item["at"])
        while eng.queue_depth >= BATCH:
            finished.extend(eng.step())
    finished.extend(eng.drain())
    assert sum(1 for r in finished if r.status == "done") == 24
    assert compile_cache_misses() - miss0 == 0
    s = eng.summary()
    assert s["ttft_seconds"]["count"] == 25        # incl. warmup request
    assert s["e2e_seconds"]["p99"] > 0


# -------------------------------------- inference.Config.enable_profile()

class TestPredictorProfile:
    def _export(self, tmp_path):
        from paddle_tpu import static
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                x = static.data("x", [-1, 8], "float32")
                y = static.nn.fc(x, 4)
            exe = static.Executor()
            prefix = str(tmp_path / "model")
            static.save_inference_model(prefix, [x], [y], exe, program=main)
            return prefix
        finally:
            paddle.disable_static()

    def test_run_latency_lands_in_metrics(self, tmp_path):
        from paddle_tpu import inference
        prefix = self._export(tmp_path)
        config = inference.Config(prefix)
        config.enable_profile()
        assert "profile" in config.summary()
        p = inference.create_predictor(config)
        for _ in range(3):
            p.run([np.random.randn(2, 8).astype(np.float32)])
        s = p.profile_summary()
        assert s["requests_total"] == 3 and s["completed_total"] == 3
        assert s["items_total"] == 6               # batch rows, not tokens
        assert s["e2e_seconds"]["p50"] > 0
        text = p.metrics_text()
        _check_exposition(text)
        assert "paddle_tpu_infer_requests_total 3" in text
        _histogram_invariants(text, "paddle_tpu_infer_e2e_seconds")

    def test_profile_off_by_default(self, tmp_path):
        from paddle_tpu import inference
        prefix = self._export(tmp_path)
        p = inference.create_predictor(inference.Config(prefix))
        p.run([np.random.randn(2, 8).astype(np.float32)])
        assert p.profile_summary() is None and p.metrics_text() == ""

    def test_clone_gets_fresh_metrics(self, tmp_path):
        from paddle_tpu import inference
        prefix = self._export(tmp_path)
        config = inference.Config(prefix)
        config.enable_profile()
        p = inference.create_predictor(config)
        p.run([np.random.randn(2, 8).astype(np.float32)])
        c = p.clone()
        assert c.profile_summary()["requests_total"] == 0
        c.run([np.random.randn(2, 8).astype(np.float32)])
        assert c.profile_summary()["requests_total"] == 1
        assert p.profile_summary()["requests_total"] == 1
