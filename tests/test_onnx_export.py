"""Literal ONNX interchange (VERDICT r2 missing #6; reference:
python/paddle/onnx/export.py → paddle2onnx).

The test decodes the produced .onnx with protobuf and EXECUTES it with an
independent numpy evaluator of the standard ONNX op semantics, comparing
against the framework's eager forward — format and math validated without
the onnx package (not in this image).
"""
import subprocess

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _decode(path):
    from paddle_tpu.onnx._export_onnx import _proto
    PB = _proto()
    m = PB.ModelProto()
    m.ParseFromString(open(path, "rb").read())
    return m


def _initializers(g):
    out = {}
    for t in g.initializer:
        if t.data_type == 7:  # INT64 (Reshape shapes, Slice bounds)
            out[t.name] = np.frombuffer(t.raw_data, np.int64).reshape(
                tuple(t.dims))
            continue
        assert t.data_type == 1  # FLOAT
        out[t.name] = np.frombuffer(t.raw_data, np.float32).reshape(
            tuple(t.dims))
    return out


def _run_onnx(model, x):
    """Minimal numpy evaluator of the exported op set — standard ONNX
    semantics, written against the spec (NOT against our exporter)."""
    g = model.graph
    env = dict(_initializers(g))
    env[g.input[0].name] = x

    def conv2d(X, W, B, strides, pads, dilations, group):
        n, cin, h, w = X.shape
        cout, cing, kh, kw = W.shape
        ph, pw = pads[0], pads[1]
        Xp = np.pad(X, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        oh = (h + 2 * ph - dilations[0] * (kh - 1) - 1) // strides[0] + 1
        ow = (w + 2 * pw - dilations[1] * (kw - 1) - 1) // strides[1] + 1
        out = np.zeros((n, cout, oh, ow), np.float32)
        cpg = cin // group
        opg = cout // group
        for gi in range(group):
            for oc in range(gi * opg, (gi + 1) * opg):
                for i in range(oh):
                    for j in range(ow):
                        ys = i * strides[0]
                        xs = j * strides[1]
                        patch = Xp[:, gi * cpg:(gi + 1) * cpg,
                                   ys:ys + dilations[0] * kh:dilations[0],
                                   xs:xs + dilations[1] * kw:dilations[1]]
                        out[:, oc, i, j] = (patch * W[oc]).sum(axis=(1, 2, 3))
        if B is not None:
            out += B.reshape(1, -1, 1, 1)
        return out

    def pool2d(X, k, s, pads, mode):
        n, c, h, w = X.shape
        ph, pw = pads[0], pads[1]
        fill = -np.inf if mode == "max" else 0.0
        Xp = np.pad(X, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                    constant_values=fill)
        oh = (h + 2 * ph - k[0]) // s[0] + 1
        ow = (w + 2 * pw - k[1]) // s[1] + 1
        out = np.zeros((n, c, oh, ow), np.float32)
        for i in range(oh):
            for j in range(ow):
                win = Xp[:, :, i * s[0]:i * s[0] + k[0],
                         j * s[1]:j * s[1] + k[1]]
                out[:, :, i, j] = (win.max((2, 3)) if mode == "max"
                                   else win.mean((2, 3)))
        return out

    for nd in g.node:
        a = {at.name: at for at in nd.attribute}

        def ints(name, default=None):
            return list(a[name].ints) if name in a else default

        ins = [env[i] for i in nd.input]
        if nd.op_type == "Gemm":
            y = ins[0] @ ins[1]
            if len(ins) > 2:
                y = y + ins[2]
        elif nd.op_type == "Conv":
            y = conv2d(ins[0], ins[1], ins[2] if len(ins) > 2 else None,
                       ints("strides", [1, 1]), ints("pads", [0, 0, 0, 0]),
                       ints("dilations", [1, 1]),
                       a["group"].i if "group" in a else 1)
        elif nd.op_type == "BatchNormalization":
            X, scale, B, mean, var = ins
            eps = a["epsilon"].f if "epsilon" in a else 1e-5
            sh = (1, -1) + (1,) * (X.ndim - 2)
            y = (X - mean.reshape(sh)) / np.sqrt(var.reshape(sh) + eps) \
                * scale.reshape(sh) + B.reshape(sh)
        elif nd.op_type == "Relu":
            y = np.maximum(ins[0], 0)
        elif nd.op_type == "Tanh":
            y = np.tanh(ins[0])
        elif nd.op_type == "Sigmoid":
            y = 1 / (1 + np.exp(-ins[0]))
        elif nd.op_type == "Softmax":
            ax = a["axis"].i if "axis" in a else -1
            e = np.exp(ins[0] - ins[0].max(axis=ax, keepdims=True))
            y = e / e.sum(axis=ax, keepdims=True)
        elif nd.op_type == "Flatten":
            ax = a["axis"].i if "axis" in a else 1
            y = ins[0].reshape(int(np.prod(ins[0].shape[:ax])), -1)
        elif nd.op_type == "MaxPool":
            y = pool2d(ins[0], ints("kernel_shape"), ints("strides"),
                       ints("pads", [0, 0, 0, 0]), "max")
        elif nd.op_type == "AveragePool":
            y = pool2d(ins[0], ints("kernel_shape"), ints("strides"),
                       ints("pads", [0, 0, 0, 0]), "avg")
        elif nd.op_type == "MatMul":
            y = np.matmul(ins[0], ins[1])
        elif nd.op_type == "Add":
            y = ins[0] + ins[1]
        elif nd.op_type == "Sub":
            y = ins[0] - ins[1]
        elif nd.op_type == "Mul":
            y = ins[0] * ins[1]
        elif nd.op_type == "Div":
            y = ins[0] / ins[1]
        elif nd.op_type == "Pow":
            y = ins[0] ** ins[1]
        elif nd.op_type == "Sqrt":
            y = np.sqrt(ins[0])
        elif nd.op_type == "Erf":
            import math
            y = np.vectorize(math.erf)(ins[0]).astype(np.float32)
        elif nd.op_type == "ReduceMean":
            axes = tuple(ints("axes"))
            keep = bool(a["keepdims"].i) if "keepdims" in a else True
            y = ins[0].mean(axis=axes, keepdims=keep)
        elif nd.op_type == "Transpose":
            y = ins[0].transpose(tuple(ints("perm")))
        elif nd.op_type == "Reshape":
            shp = [int(v) for v in ins[1]]
            shp = [ins[0].shape[i] if v == 0 else v
                   for i, v in enumerate(shp)]   # ONNX 0 = copy input dim
            y = ins[0].reshape(shp)
        elif nd.op_type == "Slice":
            starts, ends, axes = (np.asarray(ins[1]), np.asarray(ins[2]),
                                  np.asarray(ins[3]))
            sl = [slice(None)] * ins[0].ndim
            for st, en, ax in zip(starts, ends, axes):
                sl[int(ax)] = slice(int(st), int(en))
            y = ins[0][tuple(sl)]
        else:
            raise AssertionError(f"evaluator: unexpected op {nd.op_type}")
        if y.dtype != np.int64:
            y = y.astype(np.float32)
        env[nd.output[0]] = y
    return env[g.output[0].name]


class TestOnnxExport:
    def test_mlp_roundtrip(self, tmp_path):
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                          nn.Softmax())
        path = str(tmp_path / "mlp.onnx")
        paddle.onnx.export(m, path, input_spec=[
            paddle.jit.InputSpec([None, 8], "float32")])
        model = _decode(path)
        assert model.opset_import[0].version == 13
        assert [n.op_type for n in model.graph.node] == \
            ["Gemm", "Relu", "Gemm", "Softmax"]
        x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
        got = _run_onnx(model, x)
        want = m(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_convnet_roundtrip(self, tmp_path):
        paddle.seed(1)
        m = nn.Sequential(
            nn.Conv2D(3, 6, 3, stride=1, padding=1), nn.BatchNorm2D(6),
            nn.ReLU(), nn.MaxPool2D(2, 2), nn.Conv2D(6, 8, 3),
            nn.ReLU(), nn.AvgPool2D(2, 2), nn.Flatten(),
            nn.Linear(8 * 3 * 3, 5))
        # fold some nontrivial BN stats
        m[1]._mean.set_value(np.random.RandomState(2).rand(6).astype("float32"))
        m[1]._variance.set_value(
            (np.random.RandomState(3).rand(6) + 0.5).astype("float32"))
        m.eval()
        path = str(tmp_path / "conv.onnx")
        paddle.onnx.export(m, path, input_spec=[
            paddle.jit.InputSpec([None, 3, 16, 16], "float32")])
        model = _decode(path)
        x = np.random.RandomState(4).randn(2, 3, 16, 16).astype(np.float32)
        got = _run_onnx(model, x)
        want = m(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    @pytest.mark.skipif(__import__("shutil").which("protoc") is None,
                        reason="protoc binary not installed (the export "
                               "itself runs on the runtime-descriptor "
                               "fallback)")
    def test_wire_format_is_protobuf(self, tmp_path):
        """Schema-free decode (protoc --decode_raw) sees the ModelProto
        top-level fields: 1 (ir_version), 7 (graph), 8 (opset_import) —
        the normative ONNX wire layout, independent of our bindings."""
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(4, 2))
        path = str(tmp_path / "tiny.onnx")
        paddle.onnx.export(m, path, input_spec=[
            paddle.jit.InputSpec([1, 4], "float32")])
        r = subprocess.run(["protoc", "--decode_raw"],
                           stdin=open(path, "rb"), capture_output=True,
                           text=True)
        assert r.returncode == 0, r.stderr
        top = {line.split(":")[0].split(" ")[0].strip()
               for line in r.stdout.splitlines() if line and
               not line.startswith(" ")}
        assert {"1", "7", "8"} <= top, top

    def test_unsupported_layer_says_so(self, tmp_path):
        m = nn.Sequential(nn.LSTM(4, 8))
        with pytest.raises(NotImplementedError):
            paddle.onnx.export(m, str(tmp_path / "x.onnx"), input_spec=[
                paddle.jit.InputSpec([1, 4, 4], "float32")])

    def test_non_onnx_path_still_stablehlo(self, tmp_path):
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(4, 2))
        p = paddle.onnx.export(m, str(tmp_path / "m"), input_spec=[
            paddle.jit.InputSpec([2, 4], "float32")])
        import os
        assert os.path.exists(p + ".pdmodel")


class TestOnnxTransformerExport:
    def test_bert_base_encoder_parity(self, tmp_path):
        """A literal bert-base ENCODER exports to opset-13 .onnx (MatMul/
        Softmax/decomposed-LayerNorm/tanh-Gelu/Reshape/Transpose/Slice) and
        the independent numpy evaluation matches the framework forward
        (VERDICT r3 #9; reference: python/paddle/onnx/export.py:22 via
        paddle2onnx's full transformer converter)."""
        from paddle_tpu.models import BertModel, bert_config
        from paddle_tpu import onnx as ponnx

        cfg = bert_config("bert-base")          # real 768x12x12 encoder
        paddle.seed(0)
        model = BertModel(cfg)
        model.eval()
        S = 32
        path = str(tmp_path / "bert_encoder.onnx")
        ponnx.export(model.encoder, path,
                     input_spec=[[None, S, cfg.hidden_size]])

        m = _decode(path)
        ops = {nd.op_type for nd in m.graph.node}
        assert {"MatMul", "Softmax", "Transpose", "Reshape", "Slice",
                "Tanh", "ReduceMean"} <= ops, ops

        rng = np.random.RandomState(0)
        x = rng.randn(2, S, cfg.hidden_size).astype(np.float32) * 0.3
        got = _run_onnx(m, x)

        t = paddle.to_tensor(x)
        for layer in model.encoder:
            t = layer(t)
        want = t.numpy()
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_gpt_decoder_block_parity_and_causality(self, tmp_path):
        """GPT DECODER blocks export to real opset-13 .onnx with a causal
        teacher-forcing mask (VERDICT r4 #9): numeric parity vs the
        framework forward, AND causality holds — perturbing position t
        leaves outputs at positions < t unchanged. Reference:
        python/paddle/onnx/export.py:22 (paddle2onnx decoder path)."""
        from paddle_tpu.models import GPTForCausalLM, gpt_config
        from paddle_tpu import onnx as ponnx

        cfg = gpt_config("gpt3-125m")
        cfg.num_layers = 2
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        model.eval()
        S = 16
        path = str(tmp_path / "gpt_blocks.onnx")
        ponnx.export(model.gpt.h, path,
                     input_spec=[[None, S, cfg.hidden_size]])

        m = _decode(path)
        rng = np.random.RandomState(0)
        x = rng.randn(2, S, cfg.hidden_size).astype(np.float32) * 0.3
        got = _run_onnx(m, x)

        t = paddle.to_tensor(x)
        for blk in model.gpt.h:
            t = blk(t)
        want = t.numpy()
        np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)

        # causality: perturb position 9; outputs[:, :9] must be unchanged
        x2 = x.copy()
        x2[:, 9] += 1.0
        got2 = _run_onnx(m, x2)
        np.testing.assert_allclose(got2[:, :9], got[:, :9],
                                   rtol=1e-6, atol=1e-6)
        assert np.abs(got2[:, 9:] - got[:, 9:]).max() > 1e-3

    def test_layer_norm_and_gelu_standalone(self, tmp_path):
        from paddle_tpu import onnx as ponnx
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(16, 32), nn.LayerNorm(32), nn.GELU())
        m.eval()
        path = str(tmp_path / "ln.onnx")
        ponnx.export(m, path, input_spec=[[None, 8, 16]])
        dec = _decode(path)
        rng = np.random.RandomState(1)
        x = rng.randn(2, 8, 16).astype(np.float32)
        got = _run_onnx(dec, x)
        np.testing.assert_allclose(got, m(paddle.to_tensor(x)).numpy(),
                                   rtol=1e-4, atol=1e-4)
