"""Op-level numeric tests vs numpy — the OpTest analog (SURVEY §4:
op_test.py:327 check_output pattern: framework result vs numpy reference)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def t(a, sg=True):
    return paddle.to_tensor(a, stop_gradient=sg)


class TestCreation:
    def test_zeros_ones_full(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2, 3]).numpy().sum() == 6
        assert (paddle.full([2, 2], 7).numpy() == 7).all()
        assert paddle.zeros([2]).dtype == np.dtype("float32")

    def test_arange_linspace_eye(self):
        np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5))
        np.testing.assert_array_equal(paddle.eye(3).numpy(), np.eye(3, dtype="float32"))

    def test_to_tensor_dtypes(self):
        assert paddle.to_tensor([1, 2]).dtype == np.dtype("int64") or \
               paddle.to_tensor([1, 2]).dtype == np.dtype("int32")
        assert paddle.to_tensor([1.0, 2.0]).dtype == np.dtype("float32")
        assert paddle.to_tensor(np.float64([1.0])).dtype == np.dtype("float32")
        assert paddle.to_tensor([1], dtype="float16").dtype == np.dtype("float16")

    def test_rand_shapes(self):
        assert paddle.rand([3, 4]).shape == [3, 4]
        assert paddle.randn([2]).shape == [2]
        r = paddle.randint(0, 10, [100])
        assert (r.numpy() >= 0).all() and (r.numpy() < 10).all()


class TestMath:
    def test_elementwise_binary(self):
        a = np.random.randn(3, 4).astype("float32")
        b = np.random.randn(3, 4).astype("float32")
        for name, ref in [("add", np.add), ("subtract", np.subtract),
                          ("multiply", np.multiply), ("divide", np.divide),
                          ("maximum", np.maximum), ("minimum", np.minimum)]:
            out = getattr(paddle, name)(t(a), t(b)).numpy()
            np.testing.assert_allclose(out, ref(a, b), rtol=1e-6)

    def test_operators(self):
        a, b = np.random.randn(4).astype("float32"), np.random.randn(4).astype("float32")
        x, y = t(a), t(b)
        np.testing.assert_allclose((x + y).numpy(), a + b, rtol=1e-6)
        np.testing.assert_allclose((x - 2).numpy(), a - 2, rtol=1e-6)
        np.testing.assert_allclose((3 * x).numpy(), 3 * a, rtol=1e-6)
        np.testing.assert_allclose((x / y).numpy(), a / b, rtol=1e-5)
        np.testing.assert_allclose((-x).numpy(), -a)
        np.testing.assert_allclose(abs(x).numpy(), np.abs(a))

    def test_unary(self):
        a = np.random.rand(3, 4).astype("float32") + 0.1
        for name, ref in [("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt),
                          ("sin", np.sin), ("cos", np.cos), ("tanh", np.tanh),
                          ("floor", np.floor), ("ceil", np.ceil),
                          ("square", np.square), ("sign", np.sign)]:
            np.testing.assert_allclose(getattr(paddle, name)(t(a)).numpy(), ref(a),
                                       rtol=1e-5, atol=1e-6)

    def test_reductions(self):
        a = np.random.randn(3, 4, 5).astype("float32")
        np.testing.assert_allclose(paddle.sum(t(a)).numpy(), a.sum(), rtol=1e-5)
        np.testing.assert_allclose(paddle.mean(t(a), axis=1).numpy(), a.mean(1), rtol=1e-5)
        np.testing.assert_allclose(paddle.max(t(a), axis=[0, 2]).numpy(), a.max((0, 2)))
        np.testing.assert_allclose(paddle.sum(t(a), axis=1, keepdim=True).numpy(),
                                   a.sum(1, keepdims=True), rtol=1e-5)
        np.testing.assert_allclose(paddle.std(t(a)).numpy(), a.std(ddof=1), rtol=1e-4)
        np.testing.assert_allclose(paddle.logsumexp(t(a), axis=-1).numpy(),
                                   np.log(np.exp(a).sum(-1)), rtol=1e-5)

    def test_argmax_cumsum(self):
        a = np.random.randn(3, 4).astype("float32")
        np.testing.assert_array_equal(paddle.argmax(t(a), axis=1).numpy(), a.argmax(1))
        np.testing.assert_allclose(paddle.cumsum(t(a), axis=0).numpy(), a.cumsum(0), rtol=1e-6)

    def test_matmul(self):
        a = np.random.randn(2, 3, 4).astype("float32")
        b = np.random.randn(2, 4, 5).astype("float32")
        np.testing.assert_allclose(paddle.matmul(t(a), t(b)).numpy(), a @ b, rtol=1e-5)
        np.testing.assert_allclose(
            paddle.matmul(t(a), t(b.transpose(0, 2, 1)), transpose_y=True).numpy(),
            a @ b, rtol=1e-5)

    def test_einsum(self):
        a = np.random.randn(3, 4).astype("float32")
        b = np.random.randn(4, 5).astype("float32")
        np.testing.assert_allclose(paddle.einsum("ij,jk->ik", t(a), t(b)).numpy(),
                                   a @ b, rtol=1e-5)

    def test_clip_where(self):
        a = np.random.randn(10).astype("float32")
        np.testing.assert_allclose(paddle.clip(t(a), -0.5, 0.5).numpy(),
                                   np.clip(a, -0.5, 0.5))
        cond = a > 0
        np.testing.assert_allclose(
            paddle.where(t(cond), t(a), t(-a)).numpy(), np.abs(a))


class TestManipulation:
    def test_reshape_transpose(self):
        a = np.arange(24).reshape(2, 3, 4).astype("float32")
        assert paddle.reshape(t(a), [4, 6]).shape == [4, 6]
        np.testing.assert_array_equal(paddle.transpose(t(a), [2, 0, 1]).numpy(),
                                      a.transpose(2, 0, 1))
        assert t(a).flatten().shape == [24]
        assert t(a).flatten(1, 2).shape == [2, 12]

    def test_concat_split_stack(self):
        a = np.random.randn(2, 3).astype("float32")
        b = np.random.randn(2, 3).astype("float32")
        np.testing.assert_array_equal(paddle.concat([t(a), t(b)], axis=0).numpy(),
                                      np.concatenate([a, b], 0))
        parts = paddle.split(t(a), 3, axis=1)
        assert len(parts) == 3 and parts[0].shape == [2, 1]
        parts = paddle.split(t(np.random.randn(6, 2).astype("f4")), [1, 2, -1], axis=0)
        assert [p.shape[0] for p in parts] == [1, 2, 3]
        np.testing.assert_array_equal(paddle.stack([t(a), t(b)]).numpy(), np.stack([a, b]))

    def test_squeeze_expand(self):
        a = np.random.randn(1, 3, 1).astype("float32")
        assert paddle.squeeze(t(a)).shape == [3]
        assert paddle.squeeze(t(a), axis=0).shape == [3, 1]
        assert paddle.unsqueeze(t(a), [0]).shape == [1, 1, 3, 1]
        assert paddle.expand(t(np.zeros((1, 3), "f4")), [4, 3]).shape == [4, 3]
        assert paddle.tile(t(a), [2, 1, 1]).shape == [2, 3, 1]

    def test_gather_scatter(self):
        a = np.random.randn(5, 3).astype("float32")
        idx = np.array([0, 2, 4])
        np.testing.assert_array_equal(paddle.gather(t(a), t(idx)).numpy(), a[idx])
        upd = np.ones((3, 3), "float32")
        out = paddle.scatter(t(a), t(idx), t(upd)).numpy()
        ref = a.copy(); ref[idx] = 1
        np.testing.assert_array_equal(out, ref)

    def test_indexing(self):
        a = np.random.randn(4, 5).astype("float32")
        x = t(a)
        np.testing.assert_array_equal(x[1].numpy(), a[1])
        np.testing.assert_array_equal(x[1:3, ::2].numpy(), a[1:3, ::2])
        np.testing.assert_array_equal(x[:, -1].numpy(), a[:, -1])
        x[0] = 0.0
        assert (x.numpy()[0] == 0).all()

    def test_pad_flip_roll(self):
        a = np.random.randn(2, 3).astype("float32")
        # len(pad)==2*ndim: pads first dim -> last dim (paddle semantics)
        out = paddle.pad(t(a), [1, 1, 2, 2]).numpy()
        assert out.shape == (4, 7)
        # 4-element pad on 4-D NCHW input: (left,right,top,bottom) on W,H
        img = np.zeros((1, 1, 2, 3), "float32")
        assert paddle.pad(t(img), [1, 1, 2, 2]).numpy().shape == (1, 1, 6, 5)
        np.testing.assert_array_equal(paddle.flip(t(a), axis=0).numpy(), a[::-1])
        np.testing.assert_array_equal(paddle.roll(t(a), 1, axis=1).numpy(), np.roll(a, 1, 1))

    def test_sort_topk_unique(self):
        a = np.random.randn(3, 6).astype("float32")
        np.testing.assert_allclose(paddle.sort(t(a), axis=1).numpy(), np.sort(a, 1))
        vals, idx = paddle.topk(t(a), 2, axis=1)
        np.testing.assert_allclose(vals.numpy(), np.sort(a, 1)[:, -1:-3:-1], rtol=1e-6)
        u = paddle.unique(t(np.array([3, 1, 2, 1, 3])))
        np.testing.assert_array_equal(u.numpy(), [1, 2, 3])


class TestLogic:
    def test_comparisons(self):
        a = np.array([1.0, 2.0, 3.0], "float32")
        b = np.array([2.0, 2.0, 2.0], "float32")
        np.testing.assert_array_equal((t(a) < t(b)).numpy(), a < b)
        np.testing.assert_array_equal((t(a) == t(b)).numpy(), a == b)
        assert bool(paddle.allclose(t(a), t(a)))
        assert not bool(paddle.equal_all(t(a), t(b)))

    def test_isnan_isinf(self):
        a = np.array([1.0, np.nan, np.inf], "float32")
        np.testing.assert_array_equal(paddle.isnan(t(a)).numpy(), np.isnan(a))
        np.testing.assert_array_equal(paddle.isinf(t(a)).numpy(), np.isinf(a))


class TestLinalg:
    def test_solve_inv_det(self):
        a = np.random.randn(3, 3).astype("float32") + 3 * np.eye(3, dtype="float32")
        b = np.random.randn(3, 2).astype("float32")
        np.testing.assert_allclose(paddle.linalg.solve(t(a), t(b)).numpy(),
                                   np.linalg.solve(a, b), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(paddle.linalg.inv(t(a)).numpy(), np.linalg.inv(a),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(paddle.linalg.det(t(a)).numpy(), np.linalg.det(a),
                                   rtol=1e-4)

    def test_svd_qr_cholesky(self):
        a = np.random.randn(4, 3).astype("float32")
        u, s, vh_t = paddle.linalg.svd(t(a))
        np.testing.assert_allclose(s.numpy(), np.linalg.svd(a, compute_uv=False),
                                   rtol=1e-4, atol=1e-5)
        q, r = paddle.linalg.qr(t(a))
        np.testing.assert_allclose((q.numpy() @ r.numpy()), a, rtol=1e-4, atol=1e-5)
        spd = a.T @ a + np.eye(3, dtype="float32")
        c = paddle.linalg.cholesky(t(spd)).numpy()
        np.testing.assert_allclose(c @ c.T, spd, rtol=1e-4, atol=1e-4)

    def test_norm_trace(self):
        a = np.random.randn(3, 4).astype("float32")
        np.testing.assert_allclose(paddle.norm(t(a)).numpy(),
                                   np.linalg.norm(a), rtol=1e-5)
        np.testing.assert_allclose(paddle.trace(t(a)).numpy(), np.trace(a), rtol=1e-5)


class TestDtype:
    def test_cast(self):
        a = np.random.randn(3).astype("float32")
        assert paddle.cast(t(a), "float16").dtype == np.dtype("float16")
        assert t(a).astype("int32").dtype == np.dtype("int32")
        assert t(a).astype(paddle.bfloat16).dtype == paddle.bfloat16

    def test_bf16_roundtrip(self):
        a = np.random.randn(4, 4).astype("float32")
        x = t(a).astype("bfloat16")
        y = (x @ x).astype("float32")
        assert np.isfinite(y.numpy()).all()


def test_lars_and_dgc_optimizers_train():
    """LarsMomentum / DGCMomentum converge on a linear problem (reference:
    LarsMomentumOptimizer, DGCMomentumOptimizer meta strategies)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    def train(opt_cls, **kw):
        paddle.seed(0)
        np.random.seed(0)
        X = np.random.randn(128, 4).astype("float32")
        Y = X @ np.array([[1.], [-2.], [0.5], [3.]], np.float32)
        m = nn.Linear(4, 1)
        opt = opt_cls(parameters=m.parameters(), **kw)
        losses = []
        for _ in range(80):
            loss = ((m(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        return losses

    l = train(paddle.optimizer.LarsMomentum, learning_rate=0.5, lars_coeff=0.1)
    assert l[-1] < l[0] * 0.1
    l = train(paddle.optimizer.DGCMomentum, learning_rate=0.05, sparsity=0.5)
    assert l[-1] < l[0] * 0.2


class TestInt64Contract:
    """Integer-dtype contract (MIGRATION.md "Integer dtypes"): paddle's
    default int dtype is int64 and it must be REAL 64-bit — x64 is enabled
    at package import; no silent truncation (VERDICT r1 weak #5)."""

    def test_creation_defaults_int64(self):
        assert str(paddle.to_tensor([1, 2, 3]).dtype) == "int64"
        assert str(paddle.arange(5).dtype) == "int64"
        assert str(paddle.randint(0, 10, [4]).dtype) == "int64"
        assert str(paddle.randperm(5).dtype) == "int64"
        assert str(paddle.tril_indices(3, 3).dtype) == "int64"

    def test_int64_values_roundtrip(self):
        big = 2 ** 40 + 7
        t = paddle.to_tensor([big])
        assert int(t) == big
        assert int((t + 1).numpy()[0]) == big + 1
        # argmax/argmin indices are int64
        assert str(paddle.argmax(paddle.to_tensor([[1.0, 2.0]]), axis=1).dtype) == "int64"

    def test_float_defaults_unchanged(self):
        assert str(paddle.zeros([2]).dtype) == "float32"
        assert str(paddle.full([2], 1.5).dtype) == "float32"
        assert str(paddle.to_tensor([1.5]).dtype) == "float32"
        # python-scalar arithmetic keeps float32 (weak typing)
        x = paddle.ones([2])
        assert str((x * 2.0).dtype) == "float32"
        assert str((x + 1).dtype) == "float32"

    def test_no_implicit_float64(self):
        a = paddle.arange(5)
        assert str((a / 2).dtype) == "float32"
        assert str(paddle.mean(a).dtype) == "float32"
        assert str(paddle.sin(a).dtype) == "float32"
        # opt-in paths still produce real float64
        assert str(paddle.cast(a, "float64").dtype) == "float64"
        x64 = paddle.to_tensor(np.array([1.5]), dtype="float64")
        assert str((x64 * 2).dtype) == "float64"

    def test_int64_indexing_semantics(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        idx = paddle.to_tensor(np.array([2, 0], dtype=np.int64))
        out = paddle.index_select(x, idx, axis=0)
        np.testing.assert_allclose(out.numpy(), x.numpy()[[2, 0]])
        g = paddle.gather(x, idx)
        np.testing.assert_allclose(g.numpy(), x.numpy()[[2, 0]])
