"""fluid DistributeTranspiler: PS-mode training of a verbatim fluid-1.x
script (reference: fluid/transpiler/distribute_transpiler.py:264 +
test_dist_transpiler strategy — trainer grads applied server-side, fresh
params pulled, parity against a local run)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


def _build_program():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(pred, y))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    return main, startup, loss


def _data(step):
    rng = np.random.RandomState(100 + step)
    x = rng.randn(8, 4).astype(np.float32)
    y = (x.sum(1, keepdims=True) * 0.5).astype(np.float32)
    return {"x": x, "y": y}


@pytest.mark.slow
def test_transpiled_training_matches_local():
    """Single trainer + one in-process pserver: the transpiled program's
    losses match an untranspiled local run step for step (server-side SGD
    == local SGD)."""
    # local reference run
    paddle.seed(7)
    main_l, startup_l, loss_l = _build_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_l)
    local = [float(exe.run(main_l, feed=_data(s),
                           fetch_list=[loss_l])[0]) for s in range(4)]

    # transpiled run against a live PsServer
    paddle.seed(7)
    main_t, startup_t, loss_t = _build_program()
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main_t,
                pservers="127.0.0.1:0", trainers=1)
    pserver_prog = t.get_pserver_program("127.0.0.1:0")
    srv, _th = pserver_prog._ps_serve_in_thread()
    try:
        # rebind the bridge to the ephemeral port the server actually got
        trainer_prog = t.get_trainer_program()
        trainer_prog._ps_dist.endpoints = [f"127.0.0.1:{srv.port}"]
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup_t)
        dist = [float(exe2.run(trainer_prog, feed=_data(s),
                               fetch_list=[loss_t])[0]) for s in range(4)]
    finally:
        trainer_prog._ps_dist.close()
        srv.stop()
    np.testing.assert_allclose(dist, local, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_transpiler_api_surface_and_guards():
    main, startup, loss = _build_program()
    t = fluid.DistributeTranspiler(fluid.DistributeTranspilerConfig())
    with pytest.raises(ValueError):
        t.transpile(0, program=main, pservers="", trainers=1)
    t.transpile(0, program=main, pservers="127.0.0.1:7164,127.0.0.1:7165",
                trainers=2)
    prog, start = t.get_pserver_programs("127.0.0.1:7164")
    assert hasattr(prog, "_ps_serve")
    exe = fluid.Executor(fluid.CPUPlace())
    assert exe.run(start) == []          # startup no-op contract

    # non-SGD optimizers are rejected (server-side application scope)
    main2 = fluid.Program()
    with fluid.program_guard(main2, fluid.Program()):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        loss2 = fluid.layers.reduce_mean(fluid.layers.square_error_cost(
            fluid.layers.fc(x, size=1), y))
        fluid.optimizer.Adam(learning_rate=0.1).minimize(loss2)
    t2 = fluid.DistributeTranspiler()
    t2.transpile(0, program=main2, pservers="127.0.0.1:7166", trainers=1)
    with pytest.raises(NotImplementedError):
        t2.get_trainer_program()
