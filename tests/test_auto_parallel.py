"""Auto-parallel tests over the 8-device virtual CPU mesh (SURVEY §4's
fake-cluster strategy: auto_parallel tests run on topology JSON without
devices; here the virtual mesh is real enough to execute)."""
import numpy as np
import pytest
import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import auto_parallel as ap


def test_process_mesh_shapes():
    pm = ap.ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    assert pm.shape == [2, 4]
    assert pm.get_dim_size("mp") == 4
    jm = pm.jax_mesh()
    assert jm.axis_names == ("dp", "mp")
    assert jm.devices.shape == (2, 4)


def test_shard_tensor_places_array():
    pm = ap.ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    x = paddle.to_tensor(np.random.randn(8, 16).astype("float32"))
    ap.shard_tensor(x, pm, ["dp", "mp"])
    assert x.pspec == P("dp", "mp")
    shardings = {d.id for d in x._data.sharding.device_set}
    assert len(shardings) == 8
    # each shard holds 1/8 of the rows*cols
    shard = next(iter(x._data.addressable_shards))
    assert shard.data.shape == (4, 4)


def test_reshard_changes_layout():
    pm = ap.ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    x = paddle.to_tensor(np.random.randn(8, 16).astype("float32"))
    ap.shard_tensor(x, pm, ["dp", None])
    before = next(iter(x._data.addressable_shards)).data.shape
    ap.reshard(x, pm, [None, "mp"])
    after = next(iter(x._data.addressable_shards)).data.shape
    assert before == (4, 16) and after == (8, 4)


def test_engine_fit_decreases_loss():
    np.random.seed(0)
    paddle.seed(0)
    X = np.random.randn(64, 8).astype("float32")
    W = np.random.randn(8, 1).astype("float32")
    Y = X @ W

    model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    pm = ap.ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    # annotate the first linear's weight as TP-sharded over mp
    w0 = model[0].weight
    ap.shard_tensor(w0, pm, [None, "mp"])

    engine = ap.Engine(model=model, loss=nn.MSELoss(),
                       optimizer=paddle.optimizer.Adam(
                           learning_rate=1e-2, parameters=model.parameters()))
    engine.prepare(mode="train")
    hist = engine.fit((X, Y), batch_size=16, epochs=30)
    assert hist["loss"][-1] < hist["loss"][0] * 0.1, hist["loss"][::40]

    ev = engine.evaluate((X, Y), batch_size=32)
    assert ev["loss"] < hist["loss"][0]

    preds = engine.predict((X,), batch_size=32)
    assert len(preds) == 2 and list(preds[0].shape) == [32, 1]


def test_engine_save_load(tmp_path):
    model = nn.Linear(4, 2)
    engine = ap.Engine(model=model, loss=nn.MSELoss(),
                       optimizer=paddle.optimizer.SGD(
                           learning_rate=0.1, parameters=model.parameters()))
    w_before = model.weight.numpy().copy()
    engine.save(str(tmp_path / "ckpt"))
    model.weight.set_value(np.zeros_like(w_before))
    engine.load(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(model.weight.numpy(), w_before)
