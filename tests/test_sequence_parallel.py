"""Sequence-parallel (ring / ulysses) attention vs dense reference.

The reference snapshot has no sequence parallelism (SURVEY §5.7); these are
capability-exceeding tests: numeric parity of the sharded schedules against
single-device dense attention on the virtual 8-device CPU mesh, forward and
gradient, plus an end-to-end GPT step with an sp axis.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.ops.attention import attention_reference
from paddle_tpu.ops.ring_attention import (
    ring_attention, ulysses_attention, sequence_parallel_attention,
)


def _qkv(b=2, s=32, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("schedule", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sp_attention_matches_dense(schedule, causal):
    q, k, v = _qkv()
    mesh = dist.build_mesh({"dp": 2, "sp": 4})
    dist.set_mesh(mesh)
    try:
        fn = jax.jit(lambda a, b_, c: sequence_parallel_attention(
            a, b_, c, is_causal=causal, schedule=schedule))
        got = np.asarray(fn(q, k, v))
    finally:
        dist.set_mesh(None)
    want = np.asarray(attention_reference(q, k, v, is_causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("schedule", ["ring", "ulysses"])
def test_sp_attention_grad_matches_dense(schedule):
    q, k, v = _qkv(s=16)
    mesh = dist.build_mesh({"dp": 2, "sp": 4})
    dist.set_mesh(mesh)

    def loss_sp(q, k, v):
        o = sequence_parallel_attention(q, k, v, is_causal=True,
                                        schedule=schedule)
        return jnp.sum(o * o)

    def loss_dense(q, k, v):
        o = attention_reference(q, k, v, is_causal=True)
        return jnp.sum(o * o)

    try:
        g_sp = jax.jit(jax.grad(loss_sp, argnums=(0, 1, 2)))(q, k, v)
    finally:
        dist.set_mesh(None)
    g_dn = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_sp, g_dn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_ring_no_mesh_falls_back_dense():
    q, k, v = _qkv(s=8)
    got = np.asarray(ring_attention(q, k, v, is_causal=True))
    want = np.asarray(attention_reference(q, k, v, is_causal=True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ulysses_indivisible_heads_uses_ring():
    # h=3 not divisible by sp=4 -> silently uses ring schedule
    q, k, v = _qkv(s=16, h=3, d=4)
    mesh = dist.build_mesh({"dp": 2, "sp": 4})
    dist.set_mesh(mesh)
    try:
        got = np.asarray(jax.jit(
            lambda a, b_, c: ulysses_attention(a, b_, c, is_causal=True)
        )(q, k, v))
    finally:
        dist.set_mesh(None)
    want = np.asarray(attention_reference(q, k, v, is_causal=True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_gpt_step_with_sp_axis():
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainingCriterion

    mesh = dist.build_mesh({"dp": 2, "sp": 2, "mp": 2})
    dist.set_mesh(mesh)
    try:
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=32,
                        intermediate_size=64, sequence_parallel="ring")
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        step = TrainStep(model, opt, lambda ids, lbl: crit(model(ids), lbl),
                         mesh=mesh, data_axes=("dp",))
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 128, (4, 16)).astype("int32"))
        l0 = float(step(ids, ids))
        l1 = float(step(ids, ids))
        assert np.isfinite(l0) and np.isfinite(l1)
        assert l1 < l0  # optimizer actually descends
    finally:
        dist.set_mesh(None)


def test_pipeline_scan_interleaved_matches_sequential():
    """Interleaved virtual-stage schedule computes the same function as
    applying all L=S*V stages in order (reference
    PipelineParallelWithInterleave semantics)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu.distributed as dist

    mesh = dist.build_mesh({"pp": 4}, devices=jax.devices()[:4])
    dist.set_mesh(mesh)
    try:
        S, V, M, D = 4, 2, 3, 8
        L = S * V
        rng = np.random.RandomState(0)
        # logical stage l: x -> tanh(x @ W[l])
        W = jnp.asarray(rng.randn(L, D, D).astype(np.float32) * 0.3)
        xs = jnp.asarray(rng.randn(M, 2, D).astype(np.float32))

        def stage_fn(w, x):
            return jnp.tanh(x @ w)

        # deal stages round-robin: logical l -> device l % S, chunk l // S;
        # stacked_params must be ordered so shard s gets chunks [v*S+s]
        order = [v * S + d for d in range(S) for v in range(V)]
        stacked = W[jnp.asarray(order)]

        out = dist.pipeline_scan_interleaved(stage_fn, stacked, xs,
                                             axis="pp", num_virtual=V)
        want = xs
        for l in range(L):
            want = jnp.tanh(want @ W[l])
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
    finally:
        dist.set_mesh(None)
