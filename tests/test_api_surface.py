"""API-surface parity tests: every symbol in the reference's public
__all__ lists must exist here (SURVEY §2.3 rows; the judge's line-by-line
check automated), plus behavior spot-checks for this batch's additions.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


REF_TOP_LEVEL = [
    # the previously-missing 55 (full-list parity is asserted in the
    # surface test below via a frozen snapshot of the reference __all__)
    "CPUPlace", "CUDAPinnedPlace", "CUDAPlace", "DataParallel", "LazyGuard",
    "NPUPlace", "ParamAttr", "add_n", "batch", "bool", "broadcast_shape",
    "check_shape", "create_parameter", "crop", "deg2rad", "diagflat",
    "disable_signal_handler", "dtype", "floor_mod", "flops", "frexp", "gcd",
    "get_cuda_rng_state", "get_rng_state", "iinfo", "is_complex",
    "is_floating_point", "is_integer", "is_tensor", "lcm", "logit",
    "nanmedian", "nanquantile", "rad2deg", "randint_like", "rank", "renorm",
    "reverse", "scatter_", "set_cuda_rng_state", "set_printoptions",
    "set_rng_state", "sgn", "shape", "shard_index", "slice", "squeeze_",
    "stanh", "strided_slice", "take", "tanh_", "tensordot", "tolist",
    "unsqueeze_", "vsplit",
]

REF_NN = ["BeamSearchDecoder", "HSigmoidLoss", "LayerDict", "MultiMarginLoss",
          "RNNTLoss", "Softmax2D", "dynamic_decode"]

REF_F = ["adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool3d",
         "avg_pool3d", "bilinear", "channel_shuffle", "class_center_sample",
         "conv1d_transpose", "conv3d_transpose", "diag_embed", "dice_loss",
         "elu_", "fold", "gather_tree", "hsigmoid_loss", "log_sigmoid",
         "margin_cross_entropy", "max_pool3d", "max_unpool1d", "max_unpool2d",
         "max_unpool3d", "maxout", "multi_label_soft_margin_loss",
         "multi_margin_loss", "npair_loss", "pairwise_distance",
         "pixel_unshuffle", "relu_", "rnnt_loss", "rrelu", "soft_margin_loss",
         "softmax_", "sparse_attention", "tanh_", "thresholded_relu",
         "triplet_margin_with_distance_loss", "zeropad2d"]


def test_top_level_symbols_exist():
    missing = [n for n in REF_TOP_LEVEL if not hasattr(paddle, n)]
    assert not missing, missing


def test_nn_and_functional_symbols_exist():
    missing = [n for n in REF_NN if not hasattr(nn, n)]
    missing += [f"F.{n}" for n in REF_F if not hasattr(F, n)]
    assert not missing, missing


REF_ALL_FILES = [
    # (reference path under python/paddle, our module) — parity asserted
    # against the reference's literal __all__ lists
    ("io", "paddle_tpu.io"), ("optimizer", "paddle_tpu.optimizer"),
    ("metric", "paddle_tpu.metric"), ("amp", "paddle_tpu.amp"),
    ("profiler", "paddle_tpu.profiler"), ("vision", "paddle_tpu.vision"),
    ("text", "paddle_tpu.text"), ("distribution", "paddle_tpu.distribution"),
    ("sparse", "paddle_tpu.sparse"), ("autograd", "paddle_tpu.autograd"),
    ("jit", "paddle_tpu.jit"), ("inference", "paddle_tpu.inference"),
    ("device", "paddle_tpu.device"), ("incubate", "paddle_tpu.incubate"),
    ("vision/models", "paddle_tpu.vision.models"),
    ("vision/transforms", "paddle_tpu.vision.transforms"),
    ("vision/ops", "paddle_tpu.vision.ops"),
    ("optimizer/lr", "paddle_tpu.optimizer.lr"),
    ("incubate/nn", "paddle_tpu.incubate.nn"),
    ("static", "paddle_tpu.static"),
    ("distributed", "paddle_tpu.distributed"),
    ("linalg", "paddle_tpu.linalg"), ("fft", "paddle_tpu.fft"),
    ("signal", "paddle_tpu.signal"),
]


@pytest.mark.parametrize("refpath,modname", REF_ALL_FILES)
def test_subpackage_surface_parity(refpath, modname):
    """Every name in the reference subpackage's __all__ exists here."""
    import importlib
    import os
    import re
    f = f"/root/reference/python/paddle/{refpath}/__init__.py"
    if not os.path.exists(f):
        f = f"/root/reference/python/paddle/{refpath}.py"
    if not os.path.exists(f):
        pytest.skip("reference tree not present")
    m = re.search(r"__all__\s*=\s*\[(.*?)\]", open(f).read(), re.S)
    if not m:
        pytest.skip("no literal __all__")
    ref = set(re.findall(r"'([^']+)'", m.group(1)))
    mod = importlib.import_module(modname)
    missing = sorted(n for n in ref if not hasattr(mod, n))
    assert not missing, f"{modname} missing: {missing}"


class TestRound2Additions:
    def test_deform_conv_matches_plain_conv_at_zero_offset(self):
        from paddle_tpu.vision import ops as vops
        np.random.seed(0)
        x = paddle.to_tensor(np.random.randn(1, 3, 8, 8).astype("float32"))
        w = paddle.to_tensor(np.random.randn(4, 3, 3, 3).astype("float32"))
        off = paddle.to_tensor(np.zeros((1, 18, 6, 6), np.float32))
        np.testing.assert_allclose(
            vops.deform_conv2d(x, off, w).numpy(),
            F.conv2d(x, w).numpy(), rtol=1e-4, atol=1e-4)

    def test_segment_and_graph_send_recv(self):
        import paddle_tpu.incubate as inc
        d = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]],
                                      np.float32))
        ids = paddle.to_tensor(np.array([0, 0, 1]))
        np.testing.assert_array_equal(inc.segment_sum(d, ids).numpy(),
                                      [[4, 6], [5, 6]])
        np.testing.assert_array_equal(inc.segment_mean(d, ids).numpy(),
                                      [[2, 3], [5, 6]])
        x = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(4, 2))
        out = inc.graph_send_recv(
            x, paddle.to_tensor(np.array([0, 1, 2, 3])),
            paddle.to_tensor(np.array([1, 1, 2, 0])), "sum")
        np.testing.assert_array_equal(out.numpy(),
                                      [[6, 7], [2, 4], [4, 5], [0, 0]])

    def test_lookahead_trains(self):
        import paddle_tpu.incubate as inc
        paddle.seed(0)
        lin = nn.Linear(4, 1)
        la = inc.LookAhead(paddle.optimizer.SGD(
            learning_rate=0.1, parameters=lin.parameters()), alpha=0.5, k=2)
        X = paddle.randn([16, 4])
        Y = paddle.to_tensor((X.numpy() @ np.ones((4, 1))).astype("float32"))
        l0 = None
        for _ in range(15):
            loss = nn.MSELoss()(lin(X), Y)
            loss.backward()
            la.step()
            la.clear_grad()
            l0 = l0 if l0 is not None else float(loss)
        assert float(loss) < l0

    def test_sparse_coalesce_and_unaries(self):
        from paddle_tpu import sparse
        c = sparse.sparse_coo_tensor(
            np.array([[0, 0, 1], [1, 1, 0]]),
            np.array([1., 2., 3.], dtype=np.float32), shape=(2, 2))
        cc = sparse.coalesce(c)
        np.testing.assert_array_equal(cc.values().numpy(), [3., 3.])
        x = paddle.to_tensor(np.array([[0., 2.], [3., 0.]], np.float32))
        s = x.to_sparse_coo(2)
        np.testing.assert_allclose(sparse.expm1(s).values().numpy(),
                                   np.expm1([2., 3.]), rtol=1e-6)

    def test_transforms_functional_rotate_and_tensor(self):
        from paddle_tpu.vision import transforms as T
        img = (np.random.rand(8, 8, 3) * 255).astype(np.uint8)
        np.testing.assert_array_equal(T.rotate(img, 90),
                                      np.rot90(img, 1, (0, 1)))
        t = T.to_tensor(img)
        assert t.shape == [3, 8, 8] and float(t.max()) <= 1.0

    def test_independent_distribution(self):
        from paddle_tpu.distribution import Independent, Normal
        d = Independent(Normal(loc=np.zeros(3, np.float32),
                               scale=np.ones(3, np.float32)), 1)
        lp = float(d.log_prob(paddle.to_tensor(np.zeros(3, np.float32))))
        want = 3 * (-0.5 * np.log(2 * np.pi))
        np.testing.assert_allclose(lp, want, rtol=1e-5)

    def test_jit_enable_to_static_switch(self):
        import paddle_tpu.jit as jit

        @paddle.jit.to_static
        def f(x):
            return x * 2.0

        jit.enable_to_static(False)
        try:
            out = f(paddle.to_tensor(np.ones(2, np.float32)))
        finally:
            jit.enable_to_static(True)
        np.testing.assert_allclose(out.numpy(), 2.0)


def test_namespaces_importable_as_modules():
    import importlib
    for mod in ["paddle_tpu.linalg", "paddle_tpu.fft", "paddle_tpu.signal"]:
        importlib.import_module(mod)


class TestNewOps:
    def test_take_modes(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        idx = paddle.to_tensor(np.array([0, 7, -1]))
        np.testing.assert_array_equal(
            paddle.take(x, idx, mode="wrap").numpy(), [0.0, 1.0, 5.0])
        np.testing.assert_array_equal(
            paddle.take(x, idx, mode="clip").numpy(), [0.0, 5.0, 5.0])

    def test_tensordot_and_frexp(self):
        a = np.random.randn(3, 4).astype("float32")
        got = paddle.tensordot(paddle.to_tensor(a), paddle.to_tensor(a)).numpy()
        np.testing.assert_allclose(got, np.tensordot(a, a), rtol=1e-5)
        m, e = paddle.frexp(paddle.to_tensor(np.array([8.0], np.float32)))
        assert float(m) == 0.5 and int(e.numpy()[0]) == 4

    def test_shard_index(self):
        ids = paddle.to_tensor(np.array([1, 6, 11, 15]))
        out = paddle.shard_index(ids, 16, 2, 0)
        np.testing.assert_array_equal(out.numpy(), [1, 6, -1, -1])
        out = paddle.shard_index(ids, 16, 2, 1)
        np.testing.assert_array_equal(out.numpy(), [-1, -1, 3, 7])

    def test_renorm_clamps_norms(self):
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32") * 10)
        y = paddle.renorm(x, 2.0, 0, 1.0)
        norms = np.linalg.norm(y.numpy(), axis=1)
        assert (norms <= 1.0 + 1e-4).all()

    def test_inplace_tops(self):
        x = paddle.to_tensor(np.zeros((2, 1, 3), np.float32))
        paddle.squeeze_(x, 1)
        assert x.shape == [2, 3]
        paddle.unsqueeze_(x, 0)
        assert x.shape == [1, 2, 3]

    def test_slice_and_crop(self):
        x = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
        s = paddle.slice(x, [1, 2], [0, 1], [2, 3])
        assert s.shape == [2, 2, 2]
        c = paddle.crop(x, shape=[1, 2, 2], offsets=[1, 0, 1])
        assert c.shape == [1, 2, 2]

    def test_rng_state_roundtrip(self):
        st = paddle.get_rng_state()
        a = paddle.randn([4]).numpy()
        paddle.set_rng_state(st)
        b = paddle.randn([4]).numpy()
        np.testing.assert_array_equal(a, b)


class TestNewFunctional:
    def test_unpool_roundtrip(self):
        x = paddle.to_tensor(
            (np.abs(np.random.randn(2, 3, 8, 8)) + 0.1).astype("float32"))
        pooled, idx = F.max_pool2d(x, 2, return_mask=True)
        un = F.max_unpool2d(pooled, idx, 2)
        re, _ = F.max_pool2d(un, 2, return_mask=True)
        np.testing.assert_allclose(re.numpy(), pooled.numpy())

    def test_fold_inverts_unfold(self):
        x = paddle.to_tensor(np.random.randn(2, 3, 8, 8).astype("float32"))
        u = F.unfold(x, 2, strides=2)
        f = F.fold(u, (8, 8), 2, strides=2)
        np.testing.assert_allclose(f.numpy(), x.numpy(), rtol=1e-6)

    def test_rnnt_loss_matches_brute_dp(self):
        np.random.seed(0)
        B, T, U, V = 2, 4, 3, 5
        logits = np.random.randn(B, T, U + 1, V).astype("float32")
        labels = np.random.randint(1, V, (B, U)).astype("int32")
        loss = F.rnnt_loss(
            paddle.to_tensor(logits), paddle.to_tensor(labels),
            paddle.to_tensor(np.array([T, T], np.int32)),
            paddle.to_tensor(np.array([U, U], np.int32)), reduction="none")

        def brute(b):
            from scipy.special import log_softmax
            lp = log_softmax(logits, axis=-1)
            alpha = np.full((T, U + 1), -1e30)
            alpha[0, 0] = 0
            for u in range(1, U + 1):
                alpha[0, u] = alpha[0, u - 1] + lp[b, 0, u - 1, labels[b, u - 1]]
            for t in range(1, T):
                for u in range(U + 1):
                    a = alpha[t - 1, u] + lp[b, t - 1, u, 0]
                    if u > 0:
                        a = np.logaddexp(
                            a, alpha[t, u - 1] + lp[b, t, u - 1, labels[b, u - 1]])
                    alpha[t, u] = a
            return -(alpha[T - 1, U] + lp[b, T - 1, U, 0])

        np.testing.assert_allclose(np.asarray(loss._data),
                                   [brute(0), brute(1)], rtol=1e-4)

    def test_conv_transpose_1d_3d_shapes(self):
        x1 = paddle.to_tensor(np.random.randn(2, 3, 9).astype("float32"))
        w1 = paddle.to_tensor(np.random.randn(3, 4, 3).astype("float32"))
        assert F.conv1d_transpose(x1, w1, stride=2).shape == [2, 4, 19]
        x3 = paddle.to_tensor(np.random.randn(2, 3, 4, 8, 8).astype("float32"))
        w3 = paddle.to_tensor(np.random.randn(3, 4, 2, 2, 2).astype("float32"))
        assert F.conv3d_transpose(x3, w3, stride=2).shape == [2, 4, 8, 16, 16]

    def test_hsigmoid_grad_flows(self):
        m = nn.HSigmoidLoss(8, 10)
        x = paddle.randn([4, 8])
        loss = m(x, paddle.to_tensor(np.array([1, 2, 3, 9]))).mean()
        loss.backward()
        assert np.isfinite(m.weight.grad.numpy()).all()


class TestBeamSearch:
    def test_greedy_equivalence_with_beam1(self):
        """beam_size=1 must equal greedy argmax rollout."""
        paddle.seed(7)
        V, H, B = 5, 6, 2
        emb = nn.Embedding(V, H)
        cell = nn.GRUCell(H, H)
        proj = nn.Linear(H, V)
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=V - 1,
                                   beam_size=1, embedding_fn=emb,
                                   output_fn=proj)
        h0 = paddle.zeros([B, H])
        out, lp = nn.dynamic_decode(dec, inits=h0, max_step_num=4)
        # greedy rollout by hand
        ids = paddle.to_tensor(np.zeros((B,), np.int64))
        h = h0
        greedy = []
        for _ in range(4):
            o, h = cell(emb(ids), h)
            ids = paddle.argmax(proj(o), axis=-1)
            greedy.append(ids.numpy().copy())
            ids = paddle.to_tensor(ids.numpy().astype(np.int64))
        want = np.stack(greedy, axis=-1)  # [B, T]
        got = out.numpy()[:, 0, :]
        # compare until first end token per row
        for b in range(B):
            t_end = np.argmax(want[b] == V - 1) if (want[b] == V - 1).any() \
                else want.shape[1]
            np.testing.assert_array_equal(got[b][:t_end], want[b][:t_end])


class TestSignal:
    def test_stft_istft_roundtrip(self):
        import paddle_tpu.signal as signal
        x = np.sin(np.linspace(0, 60 * np.pi, 2048)).astype("float32")
        w = np.hanning(512).astype("float32")
        sp = signal.stft(paddle.to_tensor(x[None]), 512, 128,
                         window=paddle.to_tensor(w))
        assert sp.shape == [1, 257, 17]
        rec = signal.istft(sp, 512, 128, window=paddle.to_tensor(w),
                           length=2048)
        err = np.abs(rec.numpy()[0] - x)[256:-256].max()
        assert err < 1e-3


class TestStaticSurface:
    def test_ema_apply_restore(self):
        import paddle_tpu.static as st
        paddle.seed(0)
        lin = nn.Linear(2, 2)
        ema = st.ExponentialMovingAverage(decay=0.5)
        ema.register(lin.parameters())
        w0 = lin.weight.numpy().copy()
        lin.weight.set_value(w0 + 1.0)
        ema.update()
        with ema.apply():
            inside = lin.weight.numpy().copy()
        outside = lin.weight.numpy()
        assert not np.allclose(inside, outside)
        np.testing.assert_allclose(outside, w0 + 1.0)

    def test_accuracy_and_places(self):
        import paddle_tpu.static as st
        logits = paddle.to_tensor(np.array([[0.9, 0.1], [0.2, 0.8]],
                                           np.float32))
        lbl = paddle.to_tensor(np.array([[0], [0]]))
        acc = float(st.accuracy(logits, lbl))
        assert abs(acc - 0.5) < 1e-6
        assert len(st.cpu_places(2)) == 2
        with st.device_guard("cpu"):
            pass

    def test_lu_unpack_reconstructs(self):
        import paddle_tpu.linalg as la
        A = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
        lu, piv = la.lu(A)
        P, L, U = la.lu_unpack(lu, piv)
        np.testing.assert_allclose(P.numpy() @ L.numpy() @ U.numpy(),
                                   A.numpy(), atol=1e-5)
