"""Op-correctness suite through the OpTest harness (SURVEY §4: dual-executor
output checks + numeric-vs-analytic gradient checks, the reference's main
correctness net). Covers a representative op from each kernel family —
elementwise, reduction, matmul, activation, shape, softmax/norm, indexing."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import OpTest


def _f32(*shape, seed=0, scale=1.0, positive=False):
    rng = np.random.RandomState(seed)
    a = rng.randn(*shape).astype(np.float32) * scale
    return np.abs(a) + 0.5 if positive else a


class ExpCase(OpTest):
    def config(self):
        self.op = paddle.exp
        self.inputs = {"x": _f32(3, 4)}
        self.ref = np.exp


class LogCase(OpTest):
    def config(self):
        self.op = paddle.log
        self.inputs = {"x": _f32(3, 4, positive=True)}
        self.ref = np.log


class TanhCase(OpTest):
    def config(self):
        self.op = paddle.tanh
        self.inputs = {"x": _f32(2, 5)}
        self.ref = np.tanh


class AddCase(OpTest):
    def config(self):
        self.op = paddle.add
        self.inputs = {"x": _f32(3, 4), "y": _f32(1, 4, seed=1)}  # broadcast
        self.ref = np.add


class MultiplyCase(OpTest):
    def config(self):
        self.op = paddle.multiply
        self.inputs = {"x": _f32(3, 4), "y": _f32(3, 4, seed=2)}
        self.ref = np.multiply


class MatmulCase(OpTest):
    def config(self):
        self.op = paddle.matmul
        self.inputs = {"x": _f32(4, 6), "y": _f32(6, 3, seed=3)}
        self.ref = np.matmul
        self.rtol = 1e-4
        self.atol = 1e-5


class MatmulTransYCase(OpTest):
    def config(self):
        self.op = paddle.matmul
        self.attrs = {"transpose_y": True}
        self.inputs = {"x": _f32(4, 6), "y": _f32(3, 6, seed=4)}
        self.ref = lambda x, y, transpose_y: x @ y.T
        self.rtol = 1e-4
        self.atol = 1e-5


class MeanAxisCase(OpTest):
    def config(self):
        self.op = paddle.mean
        self.attrs = {"axis": 1}
        self.inputs = {"x": _f32(3, 5)}
        self.ref = lambda x, axis: x.mean(axis)


class SumKeepdimCase(OpTest):
    def config(self):
        self.op = paddle.sum
        self.attrs = {"axis": 0, "keepdim": True}
        self.inputs = {"x": _f32(4, 3)}
        self.ref = lambda x, axis, keepdim: x.sum(axis, keepdims=True)


class SoftmaxCase(OpTest):
    def config(self):
        self.op = F.softmax
        self.attrs = {"axis": -1}
        self.inputs = {"x": _f32(3, 7)}

        def ref(x, axis):
            e = np.exp(x - x.max(axis, keepdims=True))
            return e / e.sum(axis, keepdims=True)
        self.ref = ref


class SigmoidCase(OpTest):
    def config(self):
        self.op = F.sigmoid
        self.inputs = {"x": _f32(4, 4)}
        self.ref = lambda x: 1 / (1 + np.exp(-x))


class GeluCase(OpTest):
    def config(self):
        self.op = F.gelu
        self.inputs = {"x": _f32(3, 4)}

        def ref(x):
            import math
            return x * 0.5 * (1.0 + np.vectorize(math.erf)(x / math.sqrt(2)))
        self.ref = ref
        self.rtol = 1e-4
        self.atol = 1e-5


class TransposeCase(OpTest):
    def config(self):
        self.op = paddle.transpose
        self.attrs = {"perm": [1, 0, 2]}
        self.inputs = {"x": _f32(2, 3, 4)}
        self.ref = lambda x, perm: x.transpose(perm)


class ReshapeCase(OpTest):
    def config(self):
        self.op = paddle.reshape
        self.attrs = {"shape": [6, 2]}
        self.inputs = {"x": _f32(3, 4)}
        self.ref = lambda x, shape: x.reshape(shape)


class ConcatCase(OpTest):
    def config(self):
        self.op = lambda x, y, axis: paddle.concat([x, y], axis=axis)
        self.attrs = {"axis": 1}
        self.inputs = {"x": _f32(2, 3), "y": _f32(2, 4, seed=5)}
        self.ref = lambda x, y, axis: np.concatenate([x, y], axis)


class PowCase(OpTest):
    def config(self):
        self.op = paddle.pow
        self.attrs = {"y": 3.0}
        self.inputs = {"x": _f32(3, 3, positive=True)}
        self.ref = lambda x, y: np.power(x, y)
        self.grad_rtol = 3e-2


class MaximumCase(OpTest):
    def config(self):
        self.op = paddle.maximum
        self.inputs = {"x": _f32(4, 4), "y": _f32(4, 4, seed=6)}
        self.ref = np.maximum


class WhereGradFreeCase(OpTest):
    def config(self):
        c = _f32(3, 3) > 0
        self.op = lambda x, y: paddle.where(paddle.to_tensor(c), x, y)
        self.inputs = {"x": _f32(3, 3), "y": _f32(3, 3, seed=7)}
        self.ref = lambda x, y: np.where(c, x, y)


_OUTPUT_ONLY = (WhereGradFreeCase,)
_ALL = [ExpCase, LogCase, TanhCase, AddCase, MultiplyCase, MatmulCase,
        MatmulTransYCase, MeanAxisCase, SumKeepdimCase, SoftmaxCase,
        SigmoidCase, GeluCase, TransposeCase, ReshapeCase, ConcatCase,
        PowCase, MaximumCase, WhereGradFreeCase]


@pytest.mark.parametrize("case", _ALL, ids=lambda c: c.__name__)
def test_output(case):
    case().check_output()


@pytest.mark.parametrize("case", [c for c in _ALL if c not in _OUTPUT_ONLY],
                         ids=lambda c: c.__name__)
def test_grad(case):
    t = case()
    t.check_grad(list(t.inputs.keys()))
