"""Op-correctness suite through the OpTest harness (SURVEY §4: dual-executor
output checks + numeric-vs-analytic gradient checks, the reference's main
correctness net). Covers a representative op from each kernel family —
elementwise, reduction, matmul, activation, shape, softmax/norm, indexing."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import OpTest


def _f32(*shape, seed=0, scale=1.0, positive=False):
    rng = np.random.RandomState(seed)
    a = rng.randn(*shape).astype(np.float32) * scale
    return np.abs(a) + 0.5 if positive else a


class ExpCase(OpTest):
    def config(self):
        self.op = paddle.exp
        self.inputs = {"x": _f32(3, 4)}
        self.ref = np.exp


class LogCase(OpTest):
    def config(self):
        self.op = paddle.log
        self.inputs = {"x": _f32(3, 4, positive=True)}
        self.ref = np.log


class TanhCase(OpTest):
    def config(self):
        self.op = paddle.tanh
        self.inputs = {"x": _f32(2, 5)}
        self.ref = np.tanh


class AddCase(OpTest):
    def config(self):
        self.op = paddle.add
        self.inputs = {"x": _f32(3, 4), "y": _f32(1, 4, seed=1)}  # broadcast
        self.ref = np.add


class MultiplyCase(OpTest):
    def config(self):
        self.op = paddle.multiply
        self.inputs = {"x": _f32(3, 4), "y": _f32(3, 4, seed=2)}
        self.ref = np.multiply


class MatmulCase(OpTest):
    def config(self):
        self.op = paddle.matmul
        self.inputs = {"x": _f32(4, 6), "y": _f32(6, 3, seed=3)}
        self.ref = np.matmul
        self.rtol = 1e-4
        self.atol = 1e-5


class MatmulTransYCase(OpTest):
    def config(self):
        self.op = paddle.matmul
        self.attrs = {"transpose_y": True}
        self.inputs = {"x": _f32(4, 6), "y": _f32(3, 6, seed=4)}
        self.ref = lambda x, y, transpose_y: x @ y.T
        self.rtol = 1e-4
        self.atol = 1e-5


class MeanAxisCase(OpTest):
    def config(self):
        self.op = paddle.mean
        self.attrs = {"axis": 1}
        self.inputs = {"x": _f32(3, 5)}
        self.ref = lambda x, axis: x.mean(axis)


class SumKeepdimCase(OpTest):
    def config(self):
        self.op = paddle.sum
        self.attrs = {"axis": 0, "keepdim": True}
        self.inputs = {"x": _f32(4, 3)}
        self.ref = lambda x, axis, keepdim: x.sum(axis, keepdims=True)


class SoftmaxCase(OpTest):
    def config(self):
        self.op = F.softmax
        self.attrs = {"axis": -1}
        self.inputs = {"x": _f32(3, 7)}

        def ref(x, axis):
            e = np.exp(x - x.max(axis, keepdims=True))
            return e / e.sum(axis, keepdims=True)
        self.ref = ref


class SigmoidCase(OpTest):
    def config(self):
        self.op = F.sigmoid
        self.inputs = {"x": _f32(4, 4)}
        self.ref = lambda x: 1 / (1 + np.exp(-x))


class GeluCase(OpTest):
    def config(self):
        self.op = F.gelu
        self.inputs = {"x": _f32(3, 4)}

        def ref(x):
            import math
            return x * 0.5 * (1.0 + np.vectorize(math.erf)(x / math.sqrt(2)))
        self.ref = ref
        self.rtol = 1e-4
        self.atol = 1e-5


class TransposeCase(OpTest):
    def config(self):
        self.op = paddle.transpose
        self.attrs = {"perm": [1, 0, 2]}
        self.inputs = {"x": _f32(2, 3, 4)}
        self.ref = lambda x, perm: x.transpose(perm)


class ReshapeCase(OpTest):
    def config(self):
        self.op = paddle.reshape
        self.attrs = {"shape": [6, 2]}
        self.inputs = {"x": _f32(3, 4)}
        self.ref = lambda x, shape: x.reshape(shape)


class ConcatCase(OpTest):
    def config(self):
        self.op = lambda x, y, axis: paddle.concat([x, y], axis=axis)
        self.attrs = {"axis": 1}
        self.inputs = {"x": _f32(2, 3), "y": _f32(2, 4, seed=5)}
        self.ref = lambda x, y, axis: np.concatenate([x, y], axis)


class PowCase(OpTest):
    def config(self):
        self.op = paddle.pow
        self.attrs = {"y": 3.0}
        self.inputs = {"x": _f32(3, 3, positive=True)}
        self.ref = lambda x, y: np.power(x, y)
        self.grad_rtol = 3e-2


class MaximumCase(OpTest):
    def config(self):
        self.op = paddle.maximum
        self.inputs = {"x": _f32(4, 4), "y": _f32(4, 4, seed=6)}
        self.ref = np.maximum


class WhereGradFreeCase(OpTest):
    def config(self):
        c = _f32(3, 3) > 0
        self.op = lambda x, y: paddle.where(paddle.to_tensor(c), x, y)
        self.inputs = {"x": _f32(3, 3), "y": _f32(3, 3, seed=7)}
        self.ref = lambda x, y: np.where(c, x, y)


_OUTPUT_ONLY = (WhereGradFreeCase,)
_ALL = [ExpCase, LogCase, TanhCase, AddCase, MultiplyCase, MatmulCase,
        MatmulTransYCase, MeanAxisCase, SumKeepdimCase, SoftmaxCase,
        SigmoidCase, GeluCase, TransposeCase, ReshapeCase, ConcatCase,
        PowCase, MaximumCase, WhereGradFreeCase]


@pytest.mark.parametrize("case", _ALL, ids=lambda c: c.__name__)
def test_output(case):
    case().check_output()


@pytest.mark.parametrize("case", [c for c in _ALL if c not in _OUTPUT_ONLY],
                         ids=lambda c: c.__name__)
def test_grad(case):
    t = case()
    t.check_grad(list(t.inputs.keys()))


# ---- second wave: indexing / normalization / comparison / trig families
class SqrtCase(OpTest):
    def config(self):
        self.op = paddle.sqrt
        self.inputs = {"x": _f32(3, 4, positive=True)}
        self.ref = np.sqrt


class RsqrtCase(OpTest):
    def config(self):
        self.op = paddle.rsqrt
        self.inputs = {"x": _f32(3, 4, positive=True)}
        self.ref = lambda x: 1.0 / np.sqrt(x)


class SinCosCase(OpTest):
    def config(self):
        self.op = paddle.sin
        self.inputs = {"x": _f32(4, 4)}
        self.ref = np.sin


class AtanCase(OpTest):
    def config(self):
        self.op = paddle.atan
        self.inputs = {"x": _f32(3, 3)}
        self.ref = np.arctan


class SubtractCase(OpTest):
    def config(self):
        self.op = paddle.subtract
        self.inputs = {"x": _f32(2, 5), "y": _f32(2, 5, seed=8)}
        self.ref = np.subtract


class DivideCase(OpTest):
    def config(self):
        self.op = paddle.divide
        self.inputs = {"x": _f32(3, 3), "y": _f32(3, 3, seed=9, positive=True)}
        self.ref = np.divide
        self.grad_rtol = 3e-2


class MinimumCase(OpTest):
    def config(self):
        self.op = paddle.minimum
        self.inputs = {"x": _f32(4, 4), "y": _f32(4, 4, seed=10)}
        self.ref = np.minimum


class AbsCase(OpTest):
    def config(self):
        self.op = paddle.abs
        self.inputs = {"x": _f32(3, 4) + 0.3}  # keep away from 0 kink
        self.ref = np.abs


class ClipCase(OpTest):
    def config(self):
        self.op = paddle.clip
        self.attrs = {"min": -0.5, "max": 0.5}
        self.inputs = {"x": _f32(4, 4)}
        self.ref = lambda x, min, max: np.clip(x, min, max)  # noqa: A002


class SquareCase(OpTest):
    def config(self):
        self.op = paddle.square
        self.inputs = {"x": _f32(3, 3)}
        self.ref = np.square


class MaxReduceCase(OpTest):
    def config(self):
        self.op = paddle.max
        self.attrs = {"axis": 1}
        self.inputs = {"x": _f32(3, 5)}
        self.ref = lambda x, axis: x.max(axis)


class ProdCase(OpTest):
    def config(self):
        self.op = paddle.prod
        self.attrs = {"axis": 1}
        self.inputs = {"x": _f32(3, 4, positive=True)}
        self.ref = lambda x, axis: x.prod(axis)
        self.grad_rtol = 3e-2


class LogSumExpCase(OpTest):
    def config(self):
        self.op = paddle.logsumexp
        self.attrs = {"axis": 1}
        self.inputs = {"x": _f32(3, 6)}

        def ref(x, axis):
            m = x.max(axis, keepdims=True)
            return (np.log(np.exp(x - m).sum(axis)) + m.squeeze(axis))
        self.ref = ref


class StackCase(OpTest):
    def config(self):
        self.op = lambda x, y, axis: paddle.stack([x, y], axis=axis)
        self.attrs = {"axis": 1}
        self.inputs = {"x": _f32(2, 3), "y": _f32(2, 3, seed=11)}
        self.ref = lambda x, y, axis: np.stack([x, y], axis)


class SplitFirstCase(OpTest):
    def config(self):
        self.op = lambda x: paddle.split(x, 2, axis=1)[0]
        self.inputs = {"x": _f32(2, 6)}
        self.ref = lambda x: np.split(x, 2, axis=1)[0]


class GatherCase(OpTest):
    idx = np.array([2, 0, 1], np.int64)

    def config(self):
        self.op = lambda x: paddle.gather(x, paddle.to_tensor(self.idx), axis=0)
        self.inputs = {"x": _f32(4, 3)}
        self.ref = lambda x: x[self.idx]


class TileCase(OpTest):
    def config(self):
        self.op = paddle.tile
        self.attrs = {"repeat_times": [2, 3]}
        self.inputs = {"x": _f32(2, 2)}
        self.ref = lambda x, repeat_times: np.tile(x, repeat_times)


class PadCase(OpTest):
    def config(self):
        self.op = paddle.pad
        self.attrs = {"pad": [1, 1, 2, 2]}
        self.inputs = {"x": _f32(2, 3)}
        self.ref = lambda x, pad: np.pad(x, [(1, 1), (2, 2)])


class CumsumCase(OpTest):
    def config(self):
        self.op = paddle.cumsum
        self.attrs = {"axis": 1}
        self.inputs = {"x": _f32(3, 4)}
        self.ref = lambda x, axis: np.cumsum(x, axis)


class LogSoftmaxCase(OpTest):
    def config(self):
        self.op = F.log_softmax
        self.attrs = {"axis": -1}
        self.inputs = {"x": _f32(3, 5)}

        def ref(x, axis):
            m = x.max(axis, keepdims=True)
            e = np.exp(x - m)
            return x - m - np.log(e.sum(axis, keepdims=True))
        self.ref = ref


class LeakyReluCase(OpTest):
    def config(self):
        self.op = F.leaky_relu
        self.inputs = {"x": _f32(4, 4) + 0.3}
        self.ref = lambda x: np.where(x >= 0, x, 0.01 * x)


class MishCase(OpTest):
    def config(self):
        self.op = F.mish
        self.inputs = {"x": _f32(3, 4)}
        self.ref = lambda x: x * np.tanh(np.log1p(np.exp(x)))
        self.rtol = 1e-4
        self.atol = 1e-5


_WAVE2 = [SqrtCase, RsqrtCase, SinCosCase, AtanCase, SubtractCase, DivideCase,
          MinimumCase, AbsCase, ClipCase, SquareCase, MaxReduceCase, ProdCase,
          LogSumExpCase, StackCase, SplitFirstCase, GatherCase, TileCase,
          PadCase, CumsumCase, LogSoftmaxCase, LeakyReluCase, MishCase]


@pytest.mark.parametrize("case", _WAVE2, ids=lambda c: c.__name__)
def test_output_wave2(case):
    case().check_output()


@pytest.mark.parametrize("case", _WAVE2, ids=lambda c: c.__name__)
def test_grad_wave2(case):
    t = case()
    t.check_grad(list(t.inputs.keys()))


# ---------------------------------------------------------------- wave 3:
# surface-completion ops (take/tensordot/renorm/fold/logit/... — the batch
# added for reference __all__ parity), same dual-executor + numeric-grad
# contract.

class TakeCase(OpTest):
    def config(self):
        self.op = paddle.take
        self.inputs = {"x": _f32(3, 4)}
        self.attrs = {"index": paddle.to_tensor(np.array([0, 5, 11]))}
        self.ref = lambda x, index: x.reshape(-1)[[0, 5, 11]]


class TensordotCase(OpTest):
    def config(self):
        a = _f32(3, 4)
        b = _f32(4, 5)
        self.op = paddle.tensordot
        self.inputs = {"x": a, "y": b}
        self.attrs = {"axes": 1}
        self.ref = lambda x, y, axes: np.tensordot(x, y, axes=1)


class LogitCase(OpTest):
    def config(self):
        self.op = paddle.logit
        self.inputs = {"x": np.random.uniform(0.1, 0.9, (3, 4)).astype("float32")}
        self.ref = lambda x: np.log(x) - np.log1p(-x)
        self.rtol = 1e-4


class Deg2RadCase(OpTest):
    def config(self):
        self.op = paddle.deg2rad
        self.inputs = {"x": _f32(3, 4) * 90}
        self.ref = np.deg2rad


class StanhCase(OpTest):
    def config(self):
        self.op = paddle.stanh
        self.inputs = {"x": _f32(3, 4)}
        self.ref = lambda x: 1.7159 * np.tanh(0.67 * x)
        self.rtol = 1e-4


class DiagflatCase(OpTest):
    def config(self):
        self.op = paddle.diagflat
        self.inputs = {"x": _f32(4)}
        self.ref = np.diagflat


class RenormCase(OpTest):
    def config(self):
        self.op = paddle.renorm
        self.inputs = {"x": _f32(4, 6) * 3}
        self.attrs = {"p": 2.0, "axis": 0, "max_norm": 1.0}

        def ref(x, p, axis, max_norm):
            norms = np.linalg.norm(x, axis=1, keepdims=True)
            scale = np.where(norms > max_norm, max_norm / np.maximum(norms, 1e-12), 1.0)
            return x * scale
        self.ref = ref
        self.grad_rtol = 5e-2
        self.grad_atol = 5e-3


class LogSigmoidCase(OpTest):
    def config(self):
        self.op = F.log_sigmoid
        self.inputs = {"x": _f32(3, 4)}
        self.ref = lambda x: -np.log1p(np.exp(-x))
        self.rtol = 1e-4


class SoftMarginCase(OpTest):
    def config(self):
        self.op = F.soft_margin_loss
        self.inputs = {"input": _f32(3, 4)}
        self.attrs = {"label": paddle.to_tensor(
            np.sign(_f32(3, 4)) + (np.sign(_f32(3, 4)) == 0)),
            "reduction": "mean"}

        def ref(input, label, reduction):
            lbl = np.asarray(label._data)
            return np.mean(np.log1p(np.exp(-lbl * input)))
        self.ref = ref
        self.rtol = 1e-4
        self.grad_rtol = 5e-2


class FoldCase(OpTest):
    def config(self):
        self.op = F.fold
        self.inputs = {"x": _f32(2, 12, 4)}
        self.attrs = {"output_sizes": (4, 4), "kernel_sizes": 2, "strides": 2}

        def ref(x, output_sizes, kernel_sizes, strides):
            n, ckk, L = x.shape
            c = ckk // 4
            cols = x.reshape(n, c, 2, 2, 2, 2)
            out = np.zeros((n, c, 4, 4), x.dtype)
            for i in range(2):
                for j in range(2):
                    out[:, :, i::2, j::2] += cols[:, :, i, j]
            return out
        self.ref = ref


class MaxoutCase(OpTest):
    def config(self):
        self.op = F.maxout
        x = _f32(2, 6, 3, 3)
        x[:, 1::2] += 10.0  # keep the per-pair max away from ties (finite
        self.inputs = {"x": x}  # differences at a kink disagree)
        self.attrs = {"groups": 2}

        def ref(x, groups):
            # reference semantics: consecutive channels per output channel
            n, c, h, w = x.shape
            return x.reshape(n, c // groups, groups, h, w).max(axis=2)
        self.ref = ref


class PixelUnshuffleCase(OpTest):
    def config(self):
        self.op = F.pixel_unshuffle
        self.inputs = {"x": _f32(2, 2, 4, 4)}
        self.attrs = {"downscale_factor": 2}

        def ref(x, downscale_factor):
            r = downscale_factor
            n, c, h, w = x.shape
            y = x.reshape(n, c, h // r, r, w // r, r)
            return y.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r, h // r, w // r)
        self.ref = ref


_WAVE3 = [TakeCase, TensordotCase, LogitCase, Deg2RadCase, StanhCase,
          DiagflatCase, RenormCase, LogSigmoidCase, SoftMarginCase, FoldCase,
          MaxoutCase, PixelUnshuffleCase]


@pytest.mark.parametrize("case", _WAVE3, ids=lambda c: c.__name__)
def test_output_wave3(case):
    case().check_output()


@pytest.mark.parametrize("case", _WAVE3, ids=lambda c: c.__name__)
def test_grad_wave3(case):
    t = case()
    t.check_grad(list(t.inputs.keys()))
