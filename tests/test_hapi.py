"""hapi Model fit/evaluate/predict + callbacks + summary."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import callbacks as cbks
from paddle_tpu.metric import Accuracy


class ToyDS(paddle.io.Dataset):
    """Linearly separable 2-class blobs."""

    def __init__(self, n=64, seed=0):
        rng = np.random.default_rng(seed)
        self.x = rng.standard_normal((n, 4)).astype(np.float32)
        self.y = (self.x.sum(-1) > 0).astype(np.int64)
        self.x[self.y == 1] += 1.0

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _model():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.Adam(
        learning_rate=1e-2, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(), metrics=Accuracy())
    return m


def test_fit_evaluate_predict(tmp_path):
    m = _model()
    train, val = ToyDS(64, 0), ToyDS(32, 1)
    m.fit(train, val, batch_size=16, epochs=4, verbose=0)
    res = m.evaluate(val, batch_size=16, verbose=0)
    assert res["acc"] > 0.8
    assert "loss" in res
    outs = m.predict(val, batch_size=16, stack_outputs=True)
    assert outs[0].shape == (32, 2)


def test_save_load_roundtrip(tmp_path):
    m = _model()
    m.fit(ToyDS(32), batch_size=16, epochs=1, verbose=0)
    path = os.path.join(tmp_path, "ck", "model")
    m.save(path)
    assert os.path.exists(path + ".pdparams")
    assert os.path.exists(path + ".pdopt")
    m2 = _model()
    m2.load(path)
    x = paddle.to_tensor(ToyDS(8).x)
    np.testing.assert_allclose(np.asarray(m.network(x)._data),
                               np.asarray(m2.network(x)._data), atol=1e-6)


def test_early_stopping_stops():
    m = _model()
    stopper = cbks.EarlyStopping(monitor="loss", patience=1, verbose=0,
                                 mode="min")
    # loss on random labels won't improve forever; force quick stop via
    # zero lr so loss is flat
    m._optimizer.set_lr(0.0)
    m.fit(ToyDS(32), batch_size=16, epochs=10, verbose=0, callbacks=[stopper])
    assert m.stop_training


def test_model_checkpoint_callback(tmp_path):
    m = _model()
    ck = cbks.ModelCheckpoint(save_freq=1, save_dir=str(tmp_path))
    m.fit(ToyDS(32), batch_size=16, epochs=2, verbose=0, callbacks=[ck])
    assert os.path.exists(os.path.join(tmp_path, "0.pdparams"))
    assert os.path.exists(os.path.join(tmp_path, "final.pdparams"))


def test_lr_scheduler_callback_steps():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 2))
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=1,
                                          gamma=0.5)
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=net.parameters())
    m = paddle.Model(net)
    m.prepare(optimizer=opt, loss=nn.CrossEntropyLoss())
    m.fit(ToyDS(32), batch_size=16, epochs=1, verbose=0)
    # 2 batches -> scheduler stepped twice -> lr = 0.1 * 0.5^2
    assert opt.get_lr() == pytest.approx(0.025)


def test_summary_counts_params(capsys):
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
    info = paddle.summary(net, (1, 4))
    # 4*16+16 + 16*2+2 = 114
    assert info["total_params"] == 114
    out = capsys.readouterr().out
    assert "Total params" in out


def test_early_stopping_sees_eval_metrics(tmp_path):
    """on_epoch_end must receive eval_* keys (regression: ordering bug)."""
    m = _model()
    seen = {}

    class Spy(cbks.Callback):
        def on_epoch_end(self, epoch, logs=None):
            seen.update(logs or {})

    m.fit(ToyDS(32, 0), ToyDS(16, 1), batch_size=16, epochs=1, verbose=0,
          callbacks=[Spy()])
    assert any(k.startswith("eval_") for k in seen), seen


def test_early_stopping_saves_best(tmp_path):
    m = _model()
    stop = cbks.EarlyStopping(monitor="loss", patience=2, verbose=0,
                              save_dir=str(tmp_path))
    m.fit(ToyDS(32), batch_size=16, epochs=2, verbose=0, callbacks=[stop])
    assert os.path.exists(os.path.join(tmp_path, "best_model.pdparams"))


def test_reduce_lr_plateau_min_delta():
    """tiny (sub-min_delta) improvements must count as plateau."""
    m = _model()
    m._optimizer.set_lr(0.1)
    cb = cbks.ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                                verbose=0, min_delta=1e-2)
    cb.set_model(m)
    cb.on_epoch_end(0, {"loss": 1.0})
    cb.on_epoch_end(1, {"loss": 1.0 - 1e-6})  # below min_delta: wait=1
    cb.on_epoch_end(2, {"loss": 1.0 - 2e-6})  # still plateau -> reduce
    assert m._optimizer.get_lr() < 0.1


def test_reduce_lr_on_plateau():
    m = _model()
    m._optimizer.set_lr(0.1)
    cb = cbks.ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                                verbose=0)
    cb.set_model(m)
    cb.on_epoch_end(0, {"loss": 1.0})
    cb.on_epoch_end(1, {"loss": 1.0})  # wait=1 -> reduce
    cb.on_epoch_end(2, {"loss": 1.0})
    assert m._optimizer.get_lr() < 0.1


def test_model_fit_fused_step_matches_eager():
    """prepare(use_fused_step=...) trains equivalently to the eager loop
    (the fused path compiles fwd+bwd+update into one XLA program)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import TensorDataset

    def run(fused):
        paddle.seed(0)
        np.random.seed(0)
        X = np.random.randn(64, 4).astype("float32")
        Y = (X @ np.array([[1.], [2.], [-1.], [0.5]], np.float32))
        net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
        m = Model(net)
        m.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=0.05, parameters=net.parameters()),
            loss=nn.MSELoss(), use_fused_step=fused)
        ds = TensorDataset([paddle.to_tensor(X), paddle.to_tensor(Y)])
        m.fit(ds, batch_size=16, epochs=3, verbose=0)
        return {k: v.numpy().copy() for k, v in net.state_dict().items()}

    w_eager = run(False)
    w_fused = run(True)
    for k in w_eager:
        np.testing.assert_allclose(w_fused[k], w_eager[k], rtol=2e-4,
                                   atol=1e-5, err_msg=k)
