"""Native C++ data-pipeline tests (SURVEY §2.1 data pipeline parity)."""
import pickle
import threading

import numpy as np
import pytest

from paddle_tpu.io import native


pytestmark = pytest.mark.skipif(not native.native_available(),
                                reason=f"native lib unavailable: {native.native_error()}")


def test_record_file_roundtrip(tmp_path):
    path = str(tmp_path / "data.ptr")
    samples = [{"x": np.arange(i + 1, dtype=np.float32), "y": i}
               for i in range(10)]
    native.write_sample_records(path, samples)
    ds = native.RecordDataset(path)
    assert len(ds) == 10
    got = ds[3]
    np.testing.assert_allclose(got["x"], np.arange(4, dtype=np.float32))
    assert got["y"] == 3


def test_native_reader_streams_all(tmp_path):
    path = str(tmp_path / "data.ptr")
    native.write_sample_records(path, [{"i": i} for i in range(100)])
    reader = native.NativeRecordReader(path, queue_capacity=8, n_threads=4)
    seen = sorted(s["i"] for s in reader)
    assert seen == list(range(100))


def test_native_reader_sharding(tmp_path):
    path = str(tmp_path / "data.ptr")
    native.write_sample_records(path, [{"i": i} for i in range(10)])
    all_seen = []
    for rank in range(3):
        r = native.NativeRecordReader(path, rank=rank, world_size=3)
        all_seen += [s["i"] for s in r]
    assert sorted(all_seen) == list(range(10))


def test_native_reader_epochs(tmp_path):
    path = str(tmp_path / "data.ptr")
    native.write_sample_records(path, [{"i": i} for i in range(5)])
    r = native.NativeRecordReader(path, epochs=3)
    seen = [s["i"] for s in r]
    assert len(seen) == 15 and sorted(set(seen)) == list(range(5))


def test_blocking_queue_bounded_and_ordered():
    q = native.BlockingQueue(capacity=4)
    payloads = [pickle.dumps(i) for i in range(50)]
    popped = []

    def producer():
        for p in payloads:
            q.push(p)

    t = threading.Thread(target=producer)
    t.start()
    for _ in range(50):
        popped.append(pickle.loads(q.pop()))
    t.join()
    assert popped == list(range(50))  # single producer: FIFO order
    assert q.size() == 0


def test_blocking_queue_close_unblocks_pop():
    q = native.BlockingQueue(capacity=2)
    out = {}

    def consumer():
        out["v"] = q.pop()

    t = threading.Thread(target=consumer)
    t.start()
    q.close()
    t.join(timeout=10)
    assert not t.is_alive()
    assert out["v"] is None


class _SquareDataset:
    """Top-level (picklable) map-style dataset for worker processes."""

    def __len__(self):
        return 20

    def __getitem__(self, i):
        import numpy as np
        return np.full((3,), float(i), np.float32), i


def test_dataloader_process_workers():
    import numpy as np
    from paddle_tpu.io import DataLoader
    loader = DataLoader(_SquareDataset(), batch_size=4, num_workers=2)
    seen = []
    for xb, yb in loader:
        assert list(xb.shape) == [4, 3]
        seen.extend(yb.numpy().tolist())
    assert sorted(seen) == list(range(20))


def test_dataloader_process_workers_custom_collate():
    import numpy as np
    from paddle_tpu.io import DataLoader

    def collate(samples):
        xs = np.stack([s[0] for s in samples]).sum()
        return float(xs)

    loader = DataLoader(_SquareDataset(), batch_size=5, num_workers=2,
                        collate_fn=collate)
    out = list(loader)
    assert len(out) == 4 and abs(sum(out) - 3 * sum(range(20))) < 1e-5


_WORKER_IDS = []


def _record_wid(wid):
    # runs inside the worker process; assert the contract there
    assert 0 <= wid < 2, wid


def test_dataloader_worker_init_fn_ids():
    from paddle_tpu.io import DataLoader
    loader = DataLoader(_SquareDataset(), batch_size=4, num_workers=2,
                        worker_init_fn=_record_wid)
    n = sum(1 for _ in loader)
    assert n == 5


def test_dataloader_persistent_workers_reused():
    from paddle_tpu.io import DataLoader
    loader = DataLoader(_SquareDataset(), batch_size=4, num_workers=2,
                        persistent_workers=True)
    n1 = sum(1 for _ in loader)
    pool1 = loader._pool
    n2 = sum(1 for _ in loader)
    assert n1 == n2 == 5
    assert loader._pool is pool1 and pool1 is not None  # reused across epochs
