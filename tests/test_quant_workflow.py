"""Quantization workflow (VERDICT r1 missing #6): PTQ calibration over a
DataLoader → int8-annotated export, and a QAT → export round-trip.

Reference: python/paddle/quantization/ptq.py (observer insertion +
calibration), imperative qat.py (fake-quant training), slim deploy
(quantized save)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import quantization as Q


def _model():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def _loader(n=8):
    data = [paddle.to_tensor(
        np.random.RandomState(i).randn(4, 8).astype("float32"))
        for i in range(n)]
    return [(d,) for d in data]


class TestPTQWorkflow:
    def test_calibrate_populates_scales(self):
        m = _model()
        ptq = Q.PTQ()
        m = ptq.quantize(m)
        Q.calibrate(m, _loader(), num_batches=4)
        quanted = dict(Q._iter_quanted(m))
        assert quanted, "no layers instrumented"
        for name, q in quanted.items():
            s = q.act_quanter.scales()
            assert s is not None and float(s) > 0, name

    def test_int8_export_roundtrip(self):
        m = _model()
        x = paddle.to_tensor(np.random.RandomState(9).randn(4, 8)
                             .astype("float32"))
        ref = m(x).numpy()
        ptq = Q.PTQ()
        m = ptq.quantize(m)
        Q.calibrate(m, _loader(), num_batches=4)
        path = os.path.join(tempfile.mkdtemp(), "qmodel")
        Q.save_quantized(m, path,
                         input_spec=[paddle.jit.InputSpec([4, 8], "float32")])
        # int8 payload exists and dequantizes close to the fp weights
        payload = Q.load_quantized_weights(path)
        assert payload, "empty int8 payload"
        deq = Q.dequantize_weights(payload)
        for name, rec in payload.items():
            assert rec["codes"].dtype == np.int8
            w = deq[name]
            assert np.isfinite(w).all()
        # converted artifact still runs and is int8-close to the fp model
        from paddle_tpu import inference
        cfg = inference.Config(path, "")
        pred = inference.create_predictor(cfg)
        h = pred.get_input_handle(pred.get_input_names()[0])
        h.copy_from_cpu(np.asarray(x.numpy()))
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        # int8 weight quantization error bound, not exactness
        assert np.abs(out - ref).max() < 0.15 * max(1.0, np.abs(ref).max())

    def test_qat_train_then_export(self):
        m = _model()
        qat = Q.QAT(Q.QuantConfig())
        m = qat.quantize(m)
        m.train()
        opt = paddle.optimizer.Adam(parameters=m.parameters(),
                                    learning_rate=1e-3)
        X = np.random.RandomState(0).randn(16, 8).astype("float32")
        Y = np.random.RandomState(1).randn(16, 4).astype("float32")
        losses = []
        for _ in range(10):
            loss = nn.MSELoss()(m(paddle.to_tensor(X)), paddle.to_tensor(Y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses  # trains through fake-quant STE
        path = os.path.join(tempfile.mkdtemp(), "qat")
        Q.save_quantized(m, path,
                         input_spec=[paddle.jit.InputSpec([2, 8], "float32")])
        assert os.path.exists(path + ".pdquant.npz")
        assert os.path.exists(path + ".pdmodel")
