"""Compat-surface tests: asp, onnx, device.cuda, fluid shim, utils."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.incubate import asp


def test_asp_prune_2_4_and_decorate():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 4))
    masks = asp.prune_model(model)
    assert masks, "expected masks for Linear weights"
    w = model[0].weight.numpy()
    # every group of 4 along the input dim has >= 2 zeros
    groups = np.abs(w).T.reshape(8, -1, 4)
    assert ((groups != 0).sum(-1) <= 2).all()
    assert abs(asp.calculate_density(model[0].weight) - 0.5) < 0.01

    opt = asp.decorate(paddle.optimizer.SGD(
        learning_rate=0.1, parameters=model.parameters()))
    x = paddle.to_tensor(np.random.randn(4, 16).astype("float32"))
    loss = model(x).sum()
    loss.backward()
    opt.step()
    w2 = model[0].weight.numpy()
    assert ((np.abs(w2).T.reshape(8, -1, 4) != 0).sum(-1) <= 2).all()


def test_onnx_export_artifact(tmp_path):
    model = nn.Linear(4, 2)
    prefix = str(tmp_path / "m")
    paddle.onnx.export(model, prefix,
                       input_spec=[paddle.jit.InputSpec([1, 4], "float32")])
    from paddle_tpu import inference
    p = inference.create_predictor(inference.Config(prefix))
    (out,) = p.run([np.ones((1, 4), np.float32)])
    assert out.shape == (1, 2)
    with pytest.raises(NotImplementedError):
        paddle.onnx.export(model, str(tmp_path / "m.onnx"),
                           input_spec=[paddle.jit.InputSpec([1, 4], "float32")])


def test_device_cuda_stats():
    from paddle_tpu.device import cuda
    assert cuda.device_count() >= 1
    _ = paddle.to_tensor(np.ones((64, 64), np.float32)) * 2
    assert cuda.memory_allocated() >= 0
    assert cuda.max_memory_allocated() >= cuda.memory_allocated() * 0  # ints
    props = cuda.get_device_properties()
    assert props.name
    cuda.Stream().synchronize()
    assert cuda.Event().query()


def test_fluid_shim_static_flow():
    import paddle_tpu.fluid as fluid
    paddle.enable_static()
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [3, 5], "float32")
            y = fluid.layers.fc(x, 2)
            out = fluid.layers.reduce_sum(y, dim=1)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        res = exe.run(main, feed={"x": np.ones((3, 5), np.float32)},
                      fetch_list=[out])
        assert res[0].shape == (3,)
    finally:
        paddle.disable_static()


def test_utils():
    from paddle_tpu import utils
    n1, n2 = utils.unique_name.generate("fc"), utils.unique_name.generate("fc")
    assert n1 != n2
    utils.run_check()

    @utils.deprecated(update_to="paddle.new_api", since="2.0")
    def old():
        return 42
    with pytest.warns(DeprecationWarning):
        assert old() == 42
