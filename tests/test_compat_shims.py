"""Compat-surface tests: asp, onnx, device.cuda, fluid shim, utils."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.incubate import asp


def test_asp_prune_2_4_and_decorate():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 4))
    masks = asp.prune_model(model)
    assert masks, "expected masks for Linear weights"
    w = model[0].weight.numpy()
    # every group of 4 along the input dim has >= 2 zeros
    groups = np.abs(w).T.reshape(8, -1, 4)
    assert ((groups != 0).sum(-1) <= 2).all()
    assert abs(asp.calculate_density(model[0].weight) - 0.5) < 0.01

    opt = asp.decorate(paddle.optimizer.SGD(
        learning_rate=0.1, parameters=model.parameters()))
    x = paddle.to_tensor(np.random.randn(4, 16).astype("float32"))
    loss = model(x).sum()
    loss.backward()
    opt.step()
    w2 = model[0].weight.numpy()
    assert ((np.abs(w2).T.reshape(8, -1, 4) != 0).sum(-1) <= 2).all()


def test_onnx_export_artifact(tmp_path):
    model = nn.Linear(4, 2)
    prefix = str(tmp_path / "m")
    paddle.onnx.export(model, prefix,
                       input_spec=[paddle.jit.InputSpec([1, 4], "float32")])
    from paddle_tpu import inference
    p = inference.create_predictor(inference.Config(prefix))
    (out,) = p.run([np.ones((1, 4), np.float32)])
    assert out.shape == (1, 2)
    # a literal .onnx target now produces a REAL ONNX file for feed-forward
    # nets (built-in opset-13 converter, tests/test_onnx_export.py)
    paddle.onnx.export(model, str(tmp_path / "m.onnx"),
                       input_spec=[paddle.jit.InputSpec([1, 4], "float32")])
    assert (tmp_path / "m.onnx").exists()


def test_device_cuda_stats():
    from paddle_tpu.device import cuda
    assert cuda.device_count() >= 1
    _ = paddle.to_tensor(np.ones((64, 64), np.float32)) * 2
    assert cuda.memory_allocated() >= 0
    assert cuda.max_memory_allocated() >= cuda.memory_allocated() * 0  # ints
    props = cuda.get_device_properties()
    assert props.name
    cuda.Stream().synchronize()
    assert cuda.Event().query()


def test_fluid_shim_static_flow():
    import paddle_tpu.fluid as fluid
    paddle.enable_static()
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [3, 5], "float32")
            y = fluid.layers.fc(x, 2)
            out = fluid.layers.reduce_sum(y, dim=1)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        res = exe.run(main, feed={"x": np.ones((3, 5), np.float32)},
                      fetch_list=[out])
        assert res[0].shape == (3,)
    finally:
        paddle.disable_static()


def test_utils():
    from paddle_tpu import utils
    n1, n2 = utils.unique_name.generate("fc"), utils.unique_name.generate("fc")
    assert n1 != n2
    utils.run_check()

    @utils.deprecated(update_to="paddle.new_api", since="2.0")
    def old():
        return 42
    with pytest.warns(DeprecationWarning):
        assert old() == 42


class TestVerbatimFluidScripts:
    """Reference-era fluid user code runs UNCHANGED except the import line
    (VERDICT r2 #9; reference: python/paddle/fluid/layers/nn.py surface).
    Both scripts are the canonical fluid-1.x tutorial shapes."""

    def test_fluid_regression_script(self):
        import numpy as np
        import paddle_tpu.fluid as fluid

        train_prog = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(train_prog, startup):
            x = fluid.layers.data(name="x", shape=[13], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            hidden = fluid.layers.fc(input=x, size=32, act="relu")
            pred = fluid.layers.fc(input=hidden, size=1)
            cost = fluid.layers.square_error_cost(input=pred, label=y)
            avg_cost = fluid.layers.mean(cost)
            sgd = fluid.optimizer.SGD(learning_rate=0.05)
            sgd.minimize(avg_cost)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        xv = rng.randn(64, 13).astype("float32")
        w = rng.randn(13, 1).astype("float32")
        yv = xv @ w * 0.1
        losses = []
        for _ in range(30):
            (lv,) = exe.run(train_prog, feed={"x": xv, "y": yv},
                            fetch_list=[avg_cost])
            losses.append(float(lv))
        assert losses[-1] < 0.2 * losses[0], (losses[0], losses[-1])

    def test_fluid_classification_script(self):
        import numpy as np
        import paddle_tpu.fluid as fluid

        train_prog = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(train_prog, startup):
            img = fluid.layers.data(name="img", shape=[16], dtype="float32")
            label = fluid.layers.data(name="label", shape=[1], dtype="int64")
            probs = fluid.layers.fc(input=img, size=4, act="softmax")
            loss = fluid.layers.cross_entropy(input=probs, label=label)
            avg_loss = fluid.layers.mean(loss)
            acc = fluid.layers.accuracy(input=probs, label=label)
            opt = fluid.optimizer.Adam(
                learning_rate=0.05,
                regularization=fluid.regularizer.L2Decay(1e-4))
            opt.minimize(avg_loss)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(1)
        xv = rng.randn(64, 16).astype("float32")
        yv = (xv[:, :4].argmax(axis=1)).astype("int64").reshape(-1, 1)
        accs = []
        for _ in range(40):
            lv, av = exe.run(train_prog, feed={"img": xv, "label": yv},
                             fetch_list=[avg_loss, acc])
            accs.append(float(av))
        assert accs[-1] > 0.9, accs[-5:]


def test_fluid_optimizer_roster():
    """The fluid/optimizer.py class roster (reference fluid/optimizer.py:
    92-2762) beyond the original four: every alias constructs over the
    modern rule and trains a step eagerly with fluid-era kwargs."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import fluid

    for name in ["AdamW", "Adamax", "Adadelta", "RMSProp", "Lamb",
                 "LarsMomentum", "SGDOptimizer", "MomentumOptimizer",
                 "AdamOptimizer", "AdagradOptimizer", "AdamWOptimizer",
                 "RMSPropOptimizer", "LambOptimizer"]:
        paddle.seed(0)
        m = nn.Linear(4, 2)
        opt = getattr(fluid.optimizer, name)(
            learning_rate=0.01, parameter_list=m.parameters())
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(3, 4).astype("float32"))
        loss = (m(x) ** 2).mean()
        loss.backward()
        opt.step()
        assert np.isfinite(float(loss)), name
