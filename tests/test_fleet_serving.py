"""Fault-tolerant fleet serving (ISSUE 14): prefix-aware routing with
retry/failover, goodput-driven autoscaling, and the fleet fault
taxonomy.

Covers rendezvous routing math (stable keys, successor absorption on
ejection), the retriable rejection taxonomy (overloaded/draining/
queue_full retry ELSEWHERE; kv_oom/shape rejects terminal — surfaced in
the JSONL row), router retry + capped-backoff budgets (deterministic
schedule via the injected sleep), the seeded replica-kill failover
(eject -> redispatch -> bit-identical vs a fault-free oracle), scrape-
timeout ejection thresholds, autoscaler replace/scale-up/graceful-
scale-down, and registry membership mirroring into a FleetAggregator.
Every failover claim is pinned by an injected fault — chaos-first.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (AutoscaleController, FleetRouter,
                                  ReplicaRegistry, ServingConfig,
                                  ServingEngine)
from paddle_tpu.jit.api import compile_cache_misses
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.resilience import (Injector, ReplicaDown, ReplicaKill,
                                   ScrapeTimeout)

CAP, NEW = 12, 5


@pytest.fixture(scope="module")
def served_model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m, cfg


def _engine(m, **kw):
    base = dict(max_batch=2, prompt_cap=CAP, max_new_tokens=NEW,
                decode_chunk=2, paged=True, kv_block=4,
                prefix_cache=True)
    base.update(kw)
    return ServingEngine(m, ServingConfig(**base))


def _prompts(cfg, n, seed=1, lo=5, hi=None):
    rng = np.random.RandomState(seed)
    hi = hi or CAP
    return [rng.randint(1, cfg.vocab_size,
                        (int(rng.randint(lo, hi + 1)),)).astype(np.int64)
            for _ in range(n)]


# ------------------------------------------------------- routing math

class TestRendezvousRouting:
    def _registry(self, names):
        reg = ReplicaRegistry()
        for n in names:
            reg.add(n, engine=None)
        return reg

    def test_key_is_first_block_tuple(self, served_model):
        m, cfg = served_model
        reg = ReplicaRegistry({"a": _engine(m)})
        router = FleetRouter(reg)
        p = np.arange(1, 11, dtype=np.int64)
        q = np.concatenate([p[:4], np.asarray([90, 91], np.int64)])
        assert router.routing_key(p) == router.routing_key(q)   # kv_block=4
        assert router.routing_key(p) != router.routing_key(p[1:])
        # shorter than one block: the whole prompt is the key
        assert router.routing_key(p[:2]) == router.routing_key(p[:2])

    def test_stable_assignment_and_successor_absorption(self):
        """Removing one replica moves ONLY its keys; every key owned by
        a survivor keeps its owner — the property that keeps survivor
        prefix caches hot through membership churn."""
        reg = self._registry(["r0", "r1", "r2", "r3"])
        router = FleetRouter(reg, key_tokens=4)
        keys = [b"%d" % i for i in range(64)]
        before = {k: router.rank(k)[0] for k in keys}
        assert len(set(before.values())) > 1      # keys actually spread
        reg.eject("r1", "test")
        after = {k: router.rank(k)[0] for k in keys}
        moved = [k for k in keys if before[k] != after[k]]
        assert moved                              # r1 owned something
        for k in keys:
            if before[k] != "r1":
                assert after[k] == before[k]      # survivors untouched
            else:
                # an ejected owner's key lands on ITS successor
                assert after[k] != "r1"

    def test_random_policy_is_seeded(self):
        reg = self._registry(["r0", "r1", "r2"])
        a = FleetRouter(reg, policy="random", key_tokens=4, seed=3)
        b = FleetRouter(reg, policy="random", key_tokens=4, seed=3)
        assert [a.rank(b"k") for _ in range(4)] == \
            [b.rank(b"k") for _ in range(4)]
        with pytest.raises(ValueError, match="policy"):
            FleetRouter(reg, policy="lru")


# ------------------------------------------- retriable rejection taxonomy

class TestRetriableTagging:
    def test_replica_local_rejections_retriable(self, served_model):
        m, _ = served_model
        eng = _engine(m, queue_capacity=1, queue_high_watermark=1)
        eng.begin_drain()
        r = eng.submit(np.asarray([1, 2, 3], np.int64))
        assert (r.status, r.reason, r.retriable) == \
            ("rejected", "draining", True)
        eng.resume_admission()
        eng.submit(np.asarray([1, 2, 3], np.int64))       # fills queue
        r = eng.submit(np.asarray([1, 2, 3], np.int64))
        assert (r.reason, r.retriable) == ("overloaded", True)

    def test_terminal_rejections_not_retriable(self, served_model):
        m, _ = served_model
        eng = _engine(m)
        r = eng.submit(np.ones((CAP + 1,), np.int64))
        assert (r.reason, r.retriable) == ("prompt_shape", False)
        small = _engine(m, kv_blocks=2)         # one usable block
        r = small.submit(np.ones((CAP,), np.int64))
        assert (r.reason, r.retriable) == ("kv_oom", False)

    def test_retriable_lands_in_jsonl_row(self, served_model, tmp_path):
        from paddle_tpu.inference.serving import ServingMetrics
        m, _ = served_model
        path = tmp_path / "reqs.jsonl"
        eng = ServingEngine(m, ServingConfig(
            max_batch=2, prompt_cap=CAP, max_new_tokens=NEW, paged=True,
            kv_block=4, prefix_cache=True),
            metrics=ServingMetrics(jsonl_path=str(path)))
        eng.begin_drain()
        eng.submit(np.asarray([1, 2], np.int64))
        import json
        row = json.loads(path.read_text().strip().splitlines()[-1])
        assert row["request"]["reason"] == "draining"
        assert row["request"]["retriable"] is True


# ------------------------------------------------- router retry/failover

class TestRouterRetry:
    def test_shed_retries_on_next_candidate(self, served_model):
        """An overloaded replica's shed is retried elsewhere in the SAME
        ring pass — no backoff needed when a sibling has room."""
        m, cfg = served_model
        full = _engine(m, queue_capacity=1, queue_high_watermark=1)
        full.submit(np.asarray([1, 2, 3], np.int64))      # wedge it
        reg = ReplicaRegistry({"full": full, "ok": _engine(m)})
        router = FleetRouter(reg, key_tokens=4, retry_budget_s=1.0)
        # force the wedged replica first in rendezvous order
        router.rank = lambda key: ["full", "ok"]
        freq = router.submit(_prompts(cfg, 1, seed=2)[0])
        assert freq.status == "pending" and freq.replica == "ok"
        assert [a["replica"] for a in freq.attempts] == ["full", "ok"]
        assert freq.attempts[0]["reason"] == "overloaded"
        assert router.counters["retries"] == 1

    def test_terminal_rejection_never_ringed(self, served_model):
        m, cfg = served_model
        reg = ReplicaRegistry({"a": _engine(m), "b": _engine(m)})
        router = FleetRouter(reg, retry_budget_s=1.0)
        freq = router.submit(np.ones((CAP + 1,), np.int64))
        assert freq.status == "rejected"
        assert freq.reason == "prompt_shape"
        assert len(freq.attempts) == 1            # ONE replica asked

    def test_all_shed_backs_off_until_budget(self, served_model):
        """Every replica draining -> full-ring shed passes back off on
        the chaos.retry schedule until the deadline budget expires; the
        injected sleep pins the exact delays (deterministic, capped)."""
        m, cfg = served_model
        engines = {n: _engine(m) for n in ("a", "b")}
        for e in engines.values():
            e.begin_drain()
        reg = ReplicaRegistry(engines)
        delays = []
        t = [0.0]

        def clock():
            return t[0]

        def sleep(d):
            delays.append(d)
            t[0] += d

        router = FleetRouter(reg, retry_budget_s=0.1, base_delay=0.01,
                             max_delay=0.04, clock=clock, sleep=sleep)
        freq = router.submit(_prompts(cfg, 1)[0])
        assert freq.status == "rejected"
        assert freq.reason == "fleet_shed:draining"
        # capped exponential; a 4th 0.04 backoff would cross the 0.1s
        # deadline, so retry() re-raises without sleeping it
        assert delays == [0.01, 0.02, 0.04]
        assert router.counters["backoffs"] == len(delays)

    def test_kill_mid_traffic_redispatch_bit_identical(self, served_model):
        """THE failover contract: a seeded kill mid-traffic ejects the
        replica, its in-flight requests re-submit elsewhere, every
        completed output is bit-identical to the fault-free oracle, and
        the fault demonstrably FIRED."""
        m, cfg = served_model
        prompts = _prompts(cfg, 10, seed=4)
        oracle_eng = _engine(m)
        oracle = {}
        for p in prompts:
            r = oracle_eng.submit(p)
            oracle_eng.drain()
            oracle[p.tobytes()] = r.tokens

        chaos = Injector(5, faults=[ReplicaKill("r0", step=2)])
        reg = ReplicaRegistry({"r0": _engine(m), "r1": _engine(m)},
                              chaos=chaos)
        router = FleetRouter(reg, chaos=chaos, retry_budget_s=5.0)
        freqs = [router.submit(p) for p in prompts]
        router.drain()
        assert chaos.fired("replica_kill") == 1
        assert "r0" in reg.ejected
        assert reg.ejected["r0"].state == "ejected"
        assert router.counters["replicas_lost"] == 1
        assert router.counters["redispatched"] >= 1
        assert all(f.status == "done" for f in freqs)
        for f in freqs:
            np.testing.assert_array_equal(f.tokens,
                                          oracle[f.prompt.tobytes()])
        redone = [f for f in freqs if f.redispatches]
        assert redone and all(f.replica != "r0" for f in redone)

    def test_fleet_prefix_stats_and_metrics_text(self, served_model):
        from paddle_tpu.obs import lint_exposition
        m, cfg = served_model
        reg = ReplicaRegistry({"a": _engine(m), "b": _engine(m)})
        router = FleetRouter(reg)
        p = _prompts(cfg, 1, seed=6, lo=CAP, hi=CAP)[0]
        for _ in range(3):
            router.submit(p)
            router.drain()
        stats = router.fleet_prefix_stats()
        assert stats["prefix_hit"] >= 2           # same key -> same replica
        assert stats["hit_rate"] > 0.5
        text = router.metrics_text()
        lint_exposition(text)
        assert "paddle_tpu_router_completed_total 3" in text


# ------------------------------------------------- registry health/eject

class TestRegistryProbe:
    def test_scrape_timeout_ejects_at_threshold(self, served_model):
        m, _ = served_model
        chaos = Injector(0, faults=[ScrapeTimeout("r0", times=2)])
        reg = ReplicaRegistry({"r0": _engine(m), "r1": _engine(m)},
                              chaos=chaos, fail_threshold=2)
        assert "r0" not in reg.probe()            # 1st timeout: tolerated
        assert "r0" in reg
        assert reg.handle("r0").consecutive_failures == 1
        reg.probe()                               # 2nd: threshold -> eject
        assert "r0" not in reg and "r0" in reg.ejected
        assert "timeout" in reg.ejected["r0"].ejected_reason.lower()
        assert chaos.fired("scrape_timeout") == 2

    def test_transient_timeout_recovers(self, served_model):
        m, _ = served_model
        chaos = Injector(0, faults=[ScrapeTimeout("r0", times=1)])
        reg = ReplicaRegistry({"r0": _engine(m)}, chaos=chaos,
                              fail_threshold=2)
        reg.probe()
        assert reg.handle("r0").consecutive_failures == 1
        payloads = reg.probe()                    # scrape recovers
        assert payloads["r0"]["status"] == "ok"
        assert reg.handle("r0").consecutive_failures == 0

    def test_probe_payload_carries_goodput_inputs(self, served_model):
        m, cfg = served_model
        reg = ReplicaRegistry({"r0": _engine(m)})
        h = reg.probe()["r0"]
        for key in ("requests_total", "completed_total",
                    "overloaded_total", "queue_depth", "inflight"):
            assert key in h

    def test_aggregator_tracks_membership(self, served_model):
        """Registry add/eject mirrors into the obs FleetAggregator so
        the merged telemetry surface follows the fleet, not a config."""
        from paddle_tpu.obs import FleetAggregator
        m, _ = served_model
        agg = FleetAggregator(cache_ttl=0.0)
        try:
            reg = ReplicaRegistry(aggregator=agg)
            reg.add("r0", _engine(m), url="http://127.0.0.1:1/")
            reg.add("r1", _engine(m), url="http://127.0.0.1:2/")
            reg.add("local", _engine(m))          # no url: not scraped
            assert sorted(agg.replicas) == ["r0", "r1"]
            reg.eject("r0", "died")
            assert agg.replicas == ["r1"]
            reg.remove("r1")
            assert agg.replicas == []
        finally:
            agg.close()


# ---------------------------------------------------------- autoscaler

class TestAutoscaler:
    def test_replace_below_min(self, served_model):
        m, _ = served_model
        reg = ReplicaRegistry({"r0": _engine(m), "r1": _engine(m)})
        spawned = []

        def spawn(name):
            spawned.append(name)
            return _engine(m)

        auto = AutoscaleController(reg, spawn, min_replicas=2,
                                   max_replicas=3)
        reg.eject("r1", "test")
        rec = auto.tick()
        assert rec["action"] == "replace"
        assert spawned == ["auto0"]
        assert len(reg.names()) == 2

    def test_scale_up_on_overload_signal(self, served_model):
        """The r12 `overloaded_total` counter delta IS the scale-up
        signal: shed traffic -> next tick spawns."""
        m, cfg = served_model
        eng = _engine(m, queue_capacity=2, queue_high_watermark=1)
        reg = ReplicaRegistry({"r0": eng})
        auto = AutoscaleController(reg, lambda n: _engine(m),
                                   min_replicas=1, max_replicas=2,
                                   scale_up_queue_depth=1e9)
        auto.tick()                               # baseline snapshot
        eng.submit(_prompts(cfg, 1)[0])
        shed = eng.submit(_prompts(cfg, 1, seed=8)[0])
        assert shed.reason == "overloaded"
        rec = auto.tick()
        assert rec["action"] == "scale_up"
        assert rec["overloaded_delta"] == 1
        assert len(reg.names()) == 2
        # and never past max_replicas
        eng.submit(_prompts(cfg, 1, seed=9)[0])
        eng.submit(_prompts(cfg, 1, seed=10)[0])
        assert auto.tick()["action"] is None

    def test_graceful_scale_down_never_hard_kills(self, served_model):
        """Scale-down = begin_drain -> reroute -> remove once EMPTY: the
        drained replica leaves the candidate set immediately but leaves
        the registry only with queue AND slots empty."""
        m, cfg = served_model
        reg = ReplicaRegistry({"r0": _engine(m), "r1": _engine(m)})
        router = FleetRouter(reg)
        auto = AutoscaleController(reg, lambda n: _engine(m),
                                   min_replicas=1, max_replicas=2,
                                   idle_ticks_before_scale_down=2)
        freqs = [router.submit(p) for p in _prompts(cfg, 4, seed=11)]
        router.drain(tick=auto.tick)
        assert all(f.status == "done" for f in freqs)
        for _ in range(6):
            auto.tick()
            router.step()
        acts = [d["action"] for d in auto.decisions]
        assert "scale_down_begin" in acts and "scale_down_done" in acts
        assert len(reg.names(("serving",))) == 1
        victim = next(d["replica"] for d in auto.decisions
                      if d["action"] == "scale_down_begin")
        assert victim not in reg                  # removed, and it was
        # drained through the graceful path (begin_drain flag was set,
        # engine finished everything before removal)
        assert router.inflight == 0

    def test_drained_replica_rejections_route_elsewhere(self, served_model):
        """A draining replica refuses with retriable 'draining'; the
        router lands the request on a serving sibling."""
        m, cfg = served_model
        a, b = _engine(m), _engine(m)
        reg = ReplicaRegistry({"a": a, "b": b})
        router = FleetRouter(reg, retry_budget_s=2.0)
        reg.handle("a").state = "draining"
        a.begin_drain()
        for p in _prompts(cfg, 4, seed=12):
            freq = router.submit(p)
            assert freq.replica == "b"
        done = router.drain()
        assert all(f.status == "done" for f in done)


# --------------------------------------------------- fleet zero-recompile

def test_fleet_steady_loop_zero_recompiles(served_model):
    """Three replicas + a mid-run spawned replacement share one model's
    executables: after one replica's warmup, fleet traffic (incl. the
    replacement) adds zero jit cache misses."""
    m, cfg = served_model
    engines = {f"r{i}": _engine(m) for i in range(3)}
    reg = ReplicaRegistry(engines)
    prompts = _prompts(cfg, 6, seed=13)
    router = FleetRouter(reg, retry_budget_s=2.0)
    for p in prompts[:2]:                         # warmup traffic
        router.submit(p)
    router.drain()
    miss0 = compile_cache_misses()
    reg.add("late", _engine(m))                   # the replacement shape
    for p in prompts[2:]:
        router.submit(p)
    router.drain()
    assert compile_cache_misses() - miss0 == 0


class TestReviewRegressions:
    def test_transient_scrape_miss_no_phantom_scale_up(self, served_model):
        """Found in review: a transiently-unscraped member must not
        bounce the fleet counter baseline — its recovery would read as
        a phantom overloaded delta and spawn a replica for nothing."""
        m, cfg = served_model
        eng = _engine(m, queue_capacity=2, queue_high_watermark=1)
        inj = Injector(0)
        reg = ReplicaRegistry({"r0": eng, "r1": _engine(m)}, chaos=inj,
                              fail_threshold=5)
        auto = AutoscaleController(reg, lambda n: _engine(m),
                                   min_replicas=2, max_replicas=4,
                                   scale_up_queue_depth=1e9)
        eng.submit(_prompts(cfg, 1)[0])
        shed = eng.submit(_prompts(cfg, 1, seed=21)[0])
        assert shed.reason == "overloaded"      # history BEFORE tick 1
        eng.drain()
        assert auto.tick()["action"] is None    # baseline (first sight)
        inj.add(ScrapeTimeout("r0", times=1))
        rec2 = auto.tick()                      # r0 missing this tick
        assert rec2["action"] is None and rec2["overloaded_delta"] == 0
        rec3 = auto.tick()                      # r0 recovers: no bounce
        assert rec3["overloaded_delta"] == 0
        assert rec3["action"] is None
        assert len(reg.names()) == 2            # nothing spawned

    def test_backoff_step_results_not_dropped(self, served_model):
        """Found in review: a request finishing inside the router's
        backoff 'sleep' (which steps the fleet) must still come back
        from step()/drain() — terminal FleetRequests are buffered, not
        discarded."""
        m, cfg = served_model
        eng = _engine(m, max_batch=1, queue_capacity=1,
                      queue_high_watermark=1)
        reg = ReplicaRegistry({"only": eng})
        router = FleetRouter(reg, retry_budget_s=10.0)
        a = router.submit(_prompts(cfg, 1, seed=22)[0])
        # B sheds until A (queued ahead) completes INSIDE the backoff
        # steps; A's terminal FleetRequest lands in the pending buffer
        b = router.submit(_prompts(cfg, 1, seed=23)[0])
        assert b.status == "pending"
        done = router.drain()
        assert {f.id for f in done} == {a.id, b.id}
        assert a.status == "done" and b.status == "done"

    def test_deadline_is_end_to_end_across_failover(self, served_model):
        """Found in review: deadline_s must measure from submit() — a
        redispatch spends the SAME budget, never a fresh one; an
        expired budget is a terminal timeout surfaced by step()."""
        m, cfg = served_model
        t = [0.0]
        reg = ReplicaRegistry({"a": _engine(m)})
        router = FleetRouter(reg, clock=lambda: t[0],
                             sleep=lambda d: None, retry_budget_s=0.2)
        freq = router.submit(_prompts(cfg, 1, seed=30)[0],
                             deadline_s=0.5)
        assert freq.status == "pending"
        assert freq.request.deadline_s == 0.5     # full budget at t=0
        t[0] = 0.6                                # budget burned in queue
        router._replica_lost("a", "test")         # replica dies
        assert freq.status == "timeout"           # redispatch found the
        assert freq.reason == "queue_deadline"    # budget already spent
        got = router.step()                       # ...and it surfaces
        assert got == [freq]
        assert router.counters["timeout"] == 1

    def test_terminal_redispatch_surfaces_via_step(self, served_model):
        """Found in review: a redispatch that goes terminal (every
        survivor shedding past the retry budget) must come back from
        step()/drain(), not vanish."""
        m, cfg = served_model
        a, b = _engine(m), _engine(m, queue_capacity=1,
                                   queue_high_watermark=1)
        b.submit(np.asarray([1, 2, 3], np.int64))   # wedge the survivor
        reg = ReplicaRegistry({"a": a, "b": b})
        t = [0.0]

        def sleep(d):
            t[0] += d                               # no fleet stepping:
            #                                         b stays wedged

        router = FleetRouter(reg, clock=lambda: t[0], sleep=sleep,
                             retry_budget_s=0.05)
        router.rank = lambda key: [n for n in ("a", "b") if n in reg]
        freq = router.submit(_prompts(cfg, 1, seed=31)[0])
        assert freq.status == "pending" and freq.replica == "a"
        router._replica_lost("a", "test")
        assert freq.status == "rejected"
        assert freq.reason.startswith("fleet_shed")
        assert router.step() == [freq]
