"""Sharding lint (ISSUE 15): the SPMD communication plan proven
statically, before the job runs.

Covers: the HLO collective inventory (schema-compatible with the runtime
trace ledger, static bytes math, replica-group parsing in both the iota
and explicit forms), the CommPlan default-deny check + CommPlanError,
partitioner-inserted-resharding detection on a PLANTED wrong pspec
(named down to the layer), the large-replicated-parameter pass with its
suggested pspec, the static-vs-runtime bytes cross-check against the
checked-in mini-step fixture, the sharding-aware recompile signature
(ISSUE 15 satellite), the TrainStep(lint=) wiring under a mesh, and the
DEFAULT_ALLOWLIST drift guard."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.analysis import (
    Allowlist, CommPlan, CommPlanError, DEFAULT_ALLOWLIST, Findings,
    GraphLint, abstract_signature, audit_hlo, collective_inventory,
    collective_kind, compiled_hlo_text, diff_ledgers, diff_signatures,
    rows_by_kind)

SDS = jax.ShapeDtypeStruct

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device host mesh")


def _mesh(axes={"dp": 8}):
    return dist.build_mesh(axes)


# ------------------------------------------------------- HLO inventory

_HLO_SNIPPET = """\
HloModule jit_f

ENTRY %main.1 (param.1: f32[8,16], param.2: bf16[4,32]) -> f32[8,16] {
  %param.1 = f32[8,16]{1,0} parameter(0), sharding={replicated}, metadata={op_name="x"}
  %param.2 = bf16[4,32]{1,0} parameter(1), sharding={devices=[8,1]<=[8]}, metadata={op_name="w"}
  ROOT %all-reduce.3 = f32[8,16]{1,0} all-reduce(f32[8,16]{1,0} %param.1), channel_id=1, replica_groups={{0,1},{2,3}}, use_global_device_ids=true, metadata={op_name="jit(f)/jit(main)/add" source_file="/a/b/layer.py" source_line=42}
}
"""


def test_inventory_parses_shapes_groups_and_where():
    rows = collective_inventory(_HLO_SNIPPET, "snippet")
    assert len(rows) == 1
    r = rows[0]
    assert r["name"] == "all-reduce.3" and r["kind"] == "all-reduce"
    # static bytes = operand + output buffer bytes (bytes_accessed twin)
    assert r["bytes"] == 8 * 16 * 4 * 2
    assert r["group_size"] == 2 and r["shapes"] == [[8, 16]]
    assert r["where"] == "layer.py:42 (add)"
    # the runtime-ledger schema rides along, timing columns empty
    for k in ("calls", "dur_us", "busy_us", "overlapped_us",
              "exposed_us", "exposed_frac", "bytes", "bus_gbps"):
        assert k in r
    assert r["dur_us"] is None and r["bus_gbps"] is None


def test_inventory_from_real_compiled_hlo_iota_groups():
    mesh = _mesh()
    jfn = jax.jit(lambda x: jnp.sum(x, axis=0),
                  in_shardings=(NamedSharding(mesh, P("dp", None)),),
                  out_shardings=NamedSharding(mesh, P()))
    text = compiled_hlo_text(jfn, SDS((8, 1024), jnp.float32))
    rows = collective_inventory(text, "psum")
    kinds = rows_by_kind(rows)
    assert set(kinds) == {"all-reduce"}
    # one f32[1024] all-reduce: 4 KiB in + 4 KiB out
    assert kinds["all-reduce"]["bytes"] == 2 * 1024 * 4
    assert rows[0]["group_size"] == 8


def test_entry_param_sharding_and_global_shape():
    from paddle_tpu.analysis.sharding import parse_hlo
    _, entries, _ = parse_hlo(_HLO_SNIPPET)
    assert entries[0].replicated and not entries[0].sharded
    assert entries[1].sharded
    assert entries[1].arg_name == "w"
    # devices=[8,1]: dim 0 sharded 8 ways -> global [32, 32]
    assert entries[1].global_shape == (32, 32)


def test_static_table_renders_with_shared_formatter():
    audit = audit_hlo(_HLO_SNIPPET, executable="snippet")
    table = audit.table()
    assert "all-reduce.3" in table and "per kind" in table
    # the None timing columns render as '-' through the ONE formatter
    assert " - " in table or "-  " in table


# ------------------------------------------------------------ CommPlan

def test_comm_plan_default_deny_and_counts():
    rows = [{"name": "all-reduce.1", "calls": 3, "bytes": 300},
            {"name": "all-gather.2", "calls": 1, "bytes": 100}]
    fs = CommPlan({"all-reduce": "+"}).check(rows, executable="e")
    assert [f.code for f in fs] == ["comm_extra"]
    assert "all-gather" in fs[0].message
    fs = CommPlan({"all-reduce": 3, "all-gather": (1, 2)}).check(rows)
    assert not fs
    fs = CommPlan({"all-reduce": 2, "all-gather": "+"}).check(rows)
    assert [f.code for f in fs] == ["comm_count"]
    fs = CommPlan({"all-reduce": "+", "all-gather": "+",
                   "reduce-scatter": "+"}).check(rows)
    assert [f.code for f in fs] == ["comm_missing"]
    # allow_other flips the default-deny
    assert not CommPlan({"all-reduce": "+"},
                        allow_other=True).check(rows)


def test_comm_plan_verify_raises_structured_error():
    rows = [{"name": "all-gather", "calls": 1, "bytes": 64}]
    with pytest.raises(CommPlanError) as ei:
        CommPlan({"all-reduce": "+"}).verify(rows, executable="step")
    # structured: the findings ride on the error, per the lint schema
    codes = sorted(f.code for f in ei.value.findings)
    assert codes == ["comm_extra", "comm_missing"]
    from paddle_tpu.analysis import GraphLintError
    assert isinstance(ei.value, GraphLintError)


def test_collective_kind_normalization():
    assert collective_kind("all-reduce.37") == "all-reduce"
    assert collective_kind("all-gather-start.2") == "all-gather"
    assert collective_kind("reduce-scatter") == "reduce-scatter"
    assert collective_kind("fusion.3") is None
    bad = pytest.raises(ValueError, CommPlan, {"all-broadcast": "+"})
    assert "unknown collective kind" in str(bad.value)


# ------------------------------------------- resharding / replication

def _tiny_gpt_step(mesh, plant=False):
    from paddle_tpu import optimizer as opt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128,
                    intermediate_size=128, param_dtype="bfloat16")
    model = GPTForCausalLM(cfg)
    model.train()
    if plant:
        model.gpt.h[0].mlp.up.weight.pspec = P("dp", None)
    o = opt.AdamW(parameters=model.parameters(), learning_rate=1e-4)
    return TrainStep(model, o, lambda ids, lab: model.loss(ids, lab),
                     mesh=mesh)


def test_planted_resharding_detected_and_named():
    """The acceptance pin: a wrong pspec on ONE layer's weight makes the
    partitioner gather that weight back to replicated every step — the
    pass detects it and names the layer, and the dp CommPlan
    (all-reduce only) independently fails on the same hazard."""
    mesh = _mesh()
    dist.set_mesh(mesh)
    try:
        ts = _tiny_gpt_step(mesh, plant=True)
        audit = ts.sharding_audit(SDS((8, 16), "int64"),
                                  SDS((8, 16), "int64"),
                                  lint=GraphLint())
        hits = [f for f in audit.findings if f.code == "param_gather"]
        assert hits, "planted resharding not detected"
        assert any("gpt.h.0.mlp.up.weight" in f.where for f in hits)
        assert not any(f.allowed for f in hits)
        # the plan check sees the same hazard as forbidden traffic
        with pytest.raises(CommPlanError):
            CommPlan({"all-reduce": "+"}).verify(audit.rows,
                                                 executable="ts")
    finally:
        dist.set_mesh(None)


def test_dp_train_step_lint_clean_and_plan_holds():
    """Shipped dp config: TrainStep(lint=) under a {"dp": 8} mesh runs
    the FULL suite (abstract passes + sharded audit + CommPlan) and
    comes out clean — data parallelism is all-reduce-only traffic."""
    mesh = _mesh()
    dist.set_mesh(mesh)
    try:
        ts = _tiny_gpt_step(mesh)
        lint = GraphLint(comm_plan=CommPlan({"all-reduce": "+"}),
                         upcast_bytes=256, const_bytes=2048,
                         donate_bytes=2048)
        fs = ts.lint(SDS((8, 16), "int64"), SDS((8, 16), "int64"),
                     lint=lint)
        active = fs.active("warn")
        assert not active, [str(f) for f in active]
        assert ts.comm_audit is not None
        kinds = ts.comm_audit.by_kind()
        assert set(kinds) == {"all-reduce"}
        # the audit saw real traffic and sized it
        assert kinds["all-reduce"]["bytes"] > 0
    finally:
        dist.set_mesh(None)


def test_tp_train_step_wte_gather_is_allowlisted():
    """Shipped hybrid tp config: the vocab-parallel table gather is a
    REAL param-gather finding — reported, but allowlisted with its
    documented reason (scoped to wte); nothing else fires."""
    mesh = _mesh({"dp": 2, "mp": 4})
    dist.set_mesh(mesh)
    try:
        ts = _tiny_gpt_step(mesh)
        audit = ts.sharding_audit(
            SDS((8, 16), "int64"), SDS((8, 16), "int64"),
            lint=GraphLint(), plan=CommPlan({"all-reduce": "+",
                                             "all-gather": "+"}))
        active = audit.findings.active("warn")
        assert not active, [str(f) for f in active]
        gathers = [f for f in audit.findings
                   if f.code == "param_gather"]
        assert gathers and all(f.allowed for f in gathers)
        assert all("wte" in f.where for f in gathers)
        assert {"all-reduce", "all-gather"} <= set(audit.by_kind())
    finally:
        dist.set_mesh(None)


def test_replicated_param_flagged_with_suggested_pspec():
    mesh = _mesh()
    lint = GraphLint(replicated_bytes=1 << 10)

    def f(w_big, w_sharded, x):
        return (x @ w_sharded) @ w_big

    audit = lint.check_sharded(
        f, SDS((64, 64), jnp.float32), SDS((64, 64), jnp.float32),
        SDS((8, 64), jnp.float32),
        in_shardings=(NamedSharding(mesh, P()),
                      NamedSharding(mesh, P(None, "dp")),
                      NamedSharding(mesh, P("dp", None))),
        name="repl", mesh_axes=dict(mesh.shape))
    hits = [f_ for f_ in audit.findings if f_.code == "replicated_param"]
    assert hits, [str(f_) for f_ in audit.findings]
    assert "w_big" in hits[0].where
    assert hits[0].data["suggested_pspec"] == "P('dp', None)"


def test_replicated_pass_quiet_on_pure_dp():
    """Pure data parallelism replicates every parameter BY DESIGN — no
    float WEIGHT is sharded (only the batch is), so the pass must stay
    silent even for big replicated weights. param_names scopes which
    args are parameters; the dp-sharded float batch is not evidence."""
    mesh = _mesh()
    lint = GraphLint(replicated_bytes=1 << 10)

    def f(w, x):
        return jnp.sum((x @ w) ** 2)

    audit = lint.check_sharded(
        f, SDS((64, 64), jnp.float32), SDS((8, 64), jnp.float32),
        in_shardings=(NamedSharding(mesh, P()),
                      NamedSharding(mesh, P("dp", None))),
        name="dp_only", param_names={"w": "w"},
        mesh_axes=dict(mesh.shape))
    assert not [f_ for f_ in audit.findings
                if f_.code == "replicated_param"]


# --------------------------------------- static-vs-runtime cross-check

def test_static_bytes_match_fixture_ledger_within_1pct():
    """The acceptance pin: the static inventory of the mini-step twin
    matches the checked-in runtime trace ledger's bytes per collective
    kind within 1%."""
    import tools.graph_lint as gl
    findings = gl.audit_comm_xcheck(rtol=0.01)
    assert not findings, [str(f) for f in findings]


def test_diff_ledgers_steps_normalization_and_mismatch():
    static = [{"name": "all-reduce.1", "calls": 1, "bytes": 1000}]
    runtime = [{"name": "all-reduce.9", "calls": 4, "bytes": 4000}]
    d = diff_ledgers(static, runtime, steps=4)
    assert d[0]["ok"] and d[0]["rel_err"] == 0.0
    assert d[0]["runtime_calls"] == 1.0
    d = diff_ledgers(static, runtime, steps=2)   # 2000 B/step vs 1000
    assert not d[0]["ok"] and d[0]["rel_err"] == pytest.approx(0.5)
    # a kind present on one side only is a (non-ok) row, not a crash
    d = diff_ledgers(static, [{"name": "all-gather", "calls": 1,
                               "bytes": 8}])
    assert {r["kind"] for r in d} == {"all-reduce", "all-gather"}
    assert not any(r["ok"] for r in d)


def test_collective_ledger_check_static_roundtrip():
    from paddle_tpu.obs.collectives import CollectiveLedger
    import os
    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "mini_step.trace.json.gz")
    ledger = CollectiveLedger.from_trace(fixture, steps=2)
    static = [{"name": "all-reduce", "calls": 1, "bytes": 1048576}]
    diff = ledger.check_static(static, rtol=0.01)
    assert len(diff) == 1 and diff[0]["ok"]


# --------------------------------- recompile signatures grow sharding

def test_signature_sharding_delta_names_resharded_leaf():
    """ISSUE 15 satellite: two calls differing ONLY by NamedSharding
    recompile — the differ must say so and name the leaf (it used to
    report "no difference")."""
    mesh = _mesh()
    a = abstract_signature(
        SDS((8, 64), jnp.float32,
            sharding=NamedSharding(mesh, P("dp", None))))
    b = abstract_signature(
        SDS((8, 64), jnp.float32, sharding=NamedSharding(mesh, P())))
    fs = diff_signatures(a, b, names=("activations",))
    assert [f.code for f in fs] == ["sharding"]
    assert fs[0].severity == "error"
    assert "activations" in fs[0].message or fs[0].where == "activations"
    assert "dp" in str(fs[0].data["old"])


def test_signature_sharding_ignores_host_and_uncommitted():
    """Host numpy arrays and default-device jax arrays normalize to the
    same (empty) sharding key — the serving preflight must not start
    rejecting plain host batches."""
    host = abstract_signature(np.zeros((4, 8), np.float32))
    dev = abstract_signature(jnp.zeros((4, 8), jnp.float32))
    assert not diff_signatures(host, dev)
    mesh = _mesh()
    named = abstract_signature(
        SDS((4, 8), jnp.float32, sharding=NamedSharding(mesh, P("dp"))))
    assert diff_signatures(host, named)[0].code == "sharding"


def test_signature_mesh_shape_is_part_of_the_key():
    m8 = _mesh({"dp": 8})
    m24 = _mesh({"dp": 2, "mp": 4})
    a = abstract_signature(
        SDS((8, 8), jnp.float32, sharding=NamedSharding(m8, P("dp"))))
    b = abstract_signature(
        SDS((8, 8), jnp.float32, sharding=NamedSharding(m24, P("dp"))))
    assert diff_signatures(a, b)[0].code == "sharding"


# --------------------------------------------- allowlist drift guard

def test_default_allowlist_entries_stay_live():
    """ISSUE 15 satellite: re-run the dtype-promotion pass over the
    standard targets and prove (a) every finding is covered by
    DEFAULT_ALLOWLIST (a new upcast cannot hide behind the allowlist's
    existence) and (b) every allowlist entry that these targets CAN
    exercise still matches at least one finding — an entry matching
    nothing is rot: the code it documented moved, and the allowlist
    keeps suppressing whatever inherits its `where` substring."""
    from paddle_tpu.inference import ServingConfig, ServingEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=64, param_dtype="bfloat16")
    model = GPTForCausalLM(cfg)
    model.eval()
    lint = GraphLint(passes=("dtype_promotion",), upcast_bytes=1)
    eng = ServingEngine(model, ServingConfig(
        max_batch=2, prompt_cap=8, max_new_tokens=4, decode_chunk=2,
        lint=lint))
    eng.submit(np.arange(1, 6))
    eng.drain()
    findings = Findings().extend(eng.lint_findings or Findings())

    from paddle_tpu import optimizer as opt
    from paddle_tpu.jit.train_step import TrainStep
    model.train()
    o = opt.AdamW(parameters=model.parameters(), learning_rate=1e-4)
    ts = TrainStep(model, o, lambda ids, lab: model.loss(ids, lab))
    findings.extend(ts.lint(SDS((2, 8), "int64"), SDS((2, 8), "int64"),
                            lint=lint))

    dtype_findings = [f for f in findings
                     if f.pass_name == "dtype_promotion"]
    assert dtype_findings, "the pass saw no graphs — nothing was audited"
    # (a) nothing active: every upcast these targets lower is documented
    stray = [str(f) for f in dtype_findings if not f.allowed]
    assert not stray, f"undocumented upcasts appeared: {stray}"
    # (b) entry liveness. Entries whose `where` these two targets cannot
    # exercise are exempt: sampling variants and generate_static (the
    # engine routes through prefill/decode_ kinds here), the numerics
    # sentinel (numerics= off), the standalone norm module and the CE/
    # softmax sites (first-match-wins: the layer_norm/loss/attention
    # entries shadow them in these graphs), and train_step.py (its
    # grad-norm reductions only lower with numerics= enabled). Every
    # OTHER dtype entry must have matched at least once.
    exempt_wheres = {"sample_logits", "generate_static", "sentinel.py",
                     "norm.py", "cross_entropy", "softmax",
                     "train_step.py"}
    matched = set()
    for f in dtype_findings:
        e = DEFAULT_ALLOWLIST.match(f)
        if e is not None:
            matched.add(e["where"])
    for e in DEFAULT_ALLOWLIST.entries:
        if e["pass"] != "dtype_promotion" \
                or e["where"] in exempt_wheres:
            continue
        assert e["where"] in matched, \
            f"allowlist entry {e['where']!r} matched nothing — " \
            f"rotting entry (or the documented site moved)"
