"""Fault-tolerant training & serving (ISSUE 7) — every recovery claim
proven by an injected fault, not by inspection.

The contract under test:

  1. ATOMIC COMMIT — no kill point inside CheckpointManager.save() can
     corrupt latest(): a kill mid-leaf / mid-manifest / pre-commit leaves
     the previous checkpoint authoritative.
  2. VERIFIED RESTORE — bitrot in one leaf raises CheckpointCorruptError
     naming exactly that leaf; restore_latest() falls back to the newest
     intact checkpoint.
  3. BIT-EXACT RESUME — kill-at-step-k + restore reproduces the
     uninterrupted loss/param trajectory bit-identically (params, opt
     state, RNG stream, dataloader order, GradScaler, monitor counters
     all round-trip) — the r9/r10 decode-parity oracle style.
  4. PREEMPTION — SIGTERM finishes the in-flight step, takes one
     emergency checkpoint and exits with RESUME_EXIT_CODE;
     fleet.elastic.run_with_restarts restarts-and-resumes.
  5. Zero steady-state recompiles + the r11 graph-lint invariants hold
     with checkpointing and the signal handler enabled.
"""
import json
import os
import signal
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, resilience
from paddle_tpu.io import DataLoader, SeededBatchSampler
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.jit.api import compile_cache_misses
from paddle_tpu.jit.train_step import TrainStep
from paddle_tpu.profiler.monitor import StepMonitor
from paddle_tpu.resilience import (
    AsyncHandle, CheckpointCorruptError, CheckpointManager, Injector,
    KillAfterStep, KillAtSite, Preempted, PreemptionHandler,
    RESUME_EXIT_CODE, RaiseInStep, SimulatedKill, TrainState,
    TransientIOError, TransientIOErrors, TruncateDuringSave, corrupt_leaf,
    retry)


def _state(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "params": {"fc1": {"w": rng.randn(8, 16).astype(np.float32),
                           "b": rng.randn(16).astype(np.float32)},
                   "fc2": {"w": rng.randn(16, 4).astype(np.float32)}},
        "opt": {"m": rng.randn(8, 16).astype(np.float32),
                "ids": np.arange(12, dtype=np.int64)},
        "step": 7, "lr": 1e-3, "note": "hello"}


def _assert_state_equal(a, b):
    assert sorted(a) == sorted(b)
    for k, v in a.items():
        if isinstance(v, dict):
            _assert_state_equal(v, b[k])
        elif isinstance(v, np.ndarray):
            assert v.dtype == b[k].dtype and v.shape == b[k].shape
            assert v.tobytes() == b[k].tobytes(), k
        else:
            assert v == b[k], k


# ===================================================== atomic commit

class TestAtomicCommit:
    def test_round_trip_nested_dtypes_scalars(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        path = mgr.save(7, _state())
        assert os.path.basename(path) == "step_00000007"
        step, back = mgr.restore_latest()
        assert step == 7
        _assert_state_equal(_state(), back)

    def test_bfloat16_leaves_round_trip(self, tmp_path):
        import jax.numpy as jnp
        mgr = CheckpointManager(str(tmp_path))
        arr = jnp.asarray(np.random.RandomState(0).randn(4, 4),
                          dtype=jnp.bfloat16)
        mgr.save(1, {"w": arr})
        _, back = mgr.restore_latest()
        assert str(back["w"].dtype) == "bfloat16"
        assert np.asarray(arr).tobytes() == back["w"].tobytes()

    def test_latest_ignores_uncommitted_and_tmp_dirs(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(3, _state())
        # a torn save: files present but no COMMIT marker
        fake = tmp_path / "step_00000009"
        fake.mkdir()
        (fake / "MANIFEST.json").write_text("{}")
        (tmp_path / "tmp.deadbeef").mkdir()
        assert mgr.all_steps() == [3]
        assert mgr.latest().endswith("step_00000003")
        step, _ = mgr.restore_latest()
        assert step == 3

    @pytest.mark.parametrize("fault", [
        TruncateDuringSave(nth_leaf=0),             # kill mid data blob
        TruncateDuringSave(nth_leaf=3),
        KillAtSite("ckpt.manifest"),                # after blob, no COMMIT
        KillAtSite("ckpt.pre_commit"),              # sealed but unrenamed
        KillAtSite("ckpt.io", nth=0),               # first write syscall
        KillAtSite("ckpt.io", nth=2),
    ], ids=["leaf0", "leaf3", "manifest", "pre_commit", "io0", "io2"])
    def test_kill_at_every_save_stage_keeps_previous_latest(
            self, tmp_path, fault):
        """The tentpole claim: a kill at ANY byte of save() leaves the
        previous checkpoint authoritative and fully intact."""
        inj = Injector(0, [fault])
        mgr = CheckpointManager(str(tmp_path), chaos=inj,
                                retry_deadline=0.05, _retry_sleep=lambda s: None)
        mgr.chaos = None
        good = _state(1)
        mgr.save(5, good)
        mgr.chaos = inj
        with pytest.raises((SimulatedKill, TransientIOError)):
            mgr.save(6, _state(2))
        assert inj.fired() >= 1, "fault never triggered"
        assert mgr.all_steps() == [5]
        step, back = mgr.restore_latest()      # checksum-verified
        assert step == 5
        _assert_state_equal(good, back)
        # and the next save works (tmp orphans swept, no state leaked)
        mgr.chaos = None
        mgr.save(6, _state(2))
        assert mgr.all_steps() == [5, 6]
        assert not [n for n in os.listdir(tmp_path) if n.startswith("tmp.")]

    def test_zero_dim_array_leaf_round_trips_shape(self, tmp_path):
        """A 0-d array leaf must restore as 0-d (ascontiguousarray
        silently promotes to (1,) — a resumed pytree with changed avals
        forces a recompile and breaks shape fidelity while checksums
        still pass)."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"scalar": np.asarray(3.5, np.float32),
                     "vec": np.arange(3, dtype=np.int32)})
        _, back = mgr.restore_latest()
        assert back["scalar"].shape == ()
        assert float(back["scalar"]) == 3.5
        assert back["vec"].shape == (3,)

    def test_resave_same_step_replaces(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(2, _state(1))
        mgr.save(2, _state(9))
        _, back = mgr.restore(2)
        _assert_state_equal(_state(9), back)

    def test_kill_during_resave_publish_keeps_step_committed(
            self, tmp_path):
        """Overwriting an existing step must never pass through a state
        with ZERO committed checkpoints (the dist_save fallback re-saves
        step 0 every period — a naive rmtree-then-rename would lose ALL
        progress to a kill between them). The kill lands between the
        publish rename and the final swap: the step stays restorable
        (the sealed publish dir IS committed) and a fresh manager heals
        the swap."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(0, _state(1))
        mgr.chaos = Injector(0, [KillAtSite("ckpt.publish")])
        with pytest.raises(SimulatedKill):
            mgr.save(0, _state(2))
        # torn state: old step_ dir + sealed publish dir — the step is
        # still committed, and restore prefers the newer (sealed) bytes
        assert mgr.all_steps() == [0]
        step, back = mgr.restore_latest()
        assert step == 0
        _assert_state_equal(_state(2), back)
        # a fresh manager (the restarted process) finishes the swap
        mgr2 = CheckpointManager(str(tmp_path))
        assert mgr2.all_steps() == [0]
        _, back = mgr2.restore_latest()
        _assert_state_equal(_state(2), back)
        names = os.listdir(tmp_path)
        assert "step_00000000" in names
        assert not [n for n in names
                    if n.startswith(("tmp.", "publish."))]


# =================================================== verified restore

class TestVerifiedRestore:
    def test_corrupt_leaf_named_exactly(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(4, _state())
        corrupt_leaf(mgr.latest(), "params/fc1/w", seed=0)
        with pytest.raises(CheckpointCorruptError) as ei:
            mgr.restore(4)
        assert ei.value.leaf == "params/fc1/w"
        assert ei.value.step == 4
        assert "params/fc1/w" in str(ei.value)

    def test_neighbor_leaves_in_blob_stay_intact(self, tmp_path):
        """Single-blob layout: flipping one leaf's region must not
        spill into its neighbors' checksums."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(4, _state())
        corrupt_leaf(mgr.latest(), "params/fc1/b", seed=0)
        _, back = mgr.restore(4, verify=False)
        want = _state()
        assert back["params"]["fc1"]["w"].tobytes() == \
            want["params"]["fc1"]["w"].tobytes()
        assert back["params"]["fc2"]["w"].tobytes() == \
            want["params"]["fc2"]["w"].tobytes()
        assert back["params"]["fc1"]["b"].tobytes() != \
            want["params"]["fc1"]["b"].tobytes()

    def test_manifest_tamper_detected_via_commit_crc(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(4, _state())
        mpath = os.path.join(mgr.latest(), "MANIFEST.json")
        m = json.load(open(mpath))
        m["step"] = 99
        open(mpath, "w").write(json.dumps(m, sort_keys=True,
                                          separators=(",", ":")))
        with pytest.raises(CheckpointCorruptError) as ei:
            mgr.restore(4)
        assert ei.value.leaf is None          # the manifest itself

    def test_restore_latest_falls_back_to_intact(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, _state(1))
        mgr.save(2, _state(2))
        corrupt_leaf(mgr._step_dir(2), "opt/m", seed=1)
        step, back = mgr.restore_latest()          # fallback=True default
        assert step == 1
        _assert_state_equal(_state(1), back)
        with pytest.raises(CheckpointCorruptError):
            mgr.restore_latest(fallback=False)

    def test_missing_data_file_is_corruption_not_transient(self, tmp_path):
        calls = []
        mgr = CheckpointManager(str(tmp_path),
                                _retry_sleep=lambda s: calls.append(s))
        mgr.save(1, _state())
        os.unlink(os.path.join(mgr.latest(), "leaves.bin"))
        with pytest.raises(CheckpointCorruptError):
            mgr.restore(1)
        assert not calls, "ENOENT must fail fast, not burn the deadline"


# ================================================ retry + transient IO

class TestRetry:
    def test_transient_io_absorbed_with_exact_schedule(self, tmp_path):
        delays = []
        inj = Injector(0, [TransientIOErrors(times=3)])
        mgr = CheckpointManager(str(tmp_path), chaos=inj,
                                retry_base_delay=0.01,
                                _retry_sleep=lambda s: delays.append(s))
        mgr.save(1, _state())
        assert inj.fired("transient_io") == 3, "fault never fired"
        # deterministic exponential backoff: 10ms, 20ms, 40ms
        assert delays == [0.01, 0.02, 0.04]
        _, back = mgr.restore_latest()
        _assert_state_equal(_state(), back)

    def test_deadline_exhaustion_reraises(self):
        clock = [0.0]

        def tick(d):
            clock[0] += d

        def always_fails():
            raise TransientIOError("flaky")

        with pytest.raises(TransientIOError):
            retry(always_fails, deadline=0.5, base_delay=0.1, factor=2.0,
                  sleep=tick, clock=lambda: clock[0])
        assert clock[0] <= 0.5

    def test_simulated_kill_is_never_retried(self):
        attempts = []

        def dies():
            attempts.append(1)
            raise SimulatedKill("test.site")

        with pytest.raises(SimulatedKill):
            retry(dies, deadline=10.0, sleep=lambda s: None)
        assert len(attempts) == 1


# ======================================================== async save

class TestAsyncSave:
    def test_async_handle_and_snapshot_isolation(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        st = _state()
        h = mgr.save(3, st, async_save=True)
        assert isinstance(h, AsyncHandle)
        # mutate the caller's arrays AFTER save() returned: the snapshot
        # must already be isolated (donation-safety contract)
        st["params"]["fc1"]["w"][:] = -1.0
        path = h.wait()
        assert h.done()
        _, back = mgr.restore_latest()
        _assert_state_equal(_state(), back)
        assert path == mgr.latest()

    def test_writer_failure_surfaces_on_wait(self, tmp_path):
        inj = Injector(0, [KillAtSite("ckpt.pre_commit")])
        mgr = CheckpointManager(str(tmp_path), chaos=inj)
        h = mgr.save(1, _state(), async_save=True)
        with pytest.raises(SimulatedKill):
            h.wait()
        assert mgr.all_steps() == []

    def test_saves_serialize_through_wait(self, tmp_path):
        order = []
        mgr = CheckpointManager(str(tmp_path))
        gate = threading.Event()
        orig = mgr._write_commit

        def slow_commit(*a, **kw):
            order.append("start")
            gate.wait(2.0)
            out = orig(*a, **kw)
            order.append("done")
            return out

        mgr._write_commit = slow_commit
        mgr.save(1, _state(), async_save=True)
        t = threading.Thread(target=lambda: gate.set())
        t.start()
        mgr.save(2, _state())           # must wait for the async one
        t.join()
        assert order == ["start", "done", "start", "done"]
        assert mgr.all_steps() == [1, 2]

    def test_concurrent_saves_from_threads_lose_no_checkpoint(
            self, tmp_path):
        """The fallback manager behind dist_save is SHARED across
        callers: racing async saves from several threads must all
        commit (the bug: both racers passed wait(), the loser's
        AsyncHandle was overwritten and its writer orphaned — killed at
        interpreter exit mid-commit, silently losing the checkpoint)."""
        mgr = CheckpointManager(str(tmp_path))
        n = 4
        gate = threading.Barrier(n)
        errs = []

        def racer(step):
            gate.wait(5.0)
            try:
                mgr.save(step, _state(step), async_save=True)
            except BaseException as e:      # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=racer, args=(i,)) for i in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        mgr.wait()
        assert not errs
        assert mgr.all_steps() == list(range(n)), \
            "a racing save's writer was orphaned and its commit lost"
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("ckpt-save-")]

    def test_discard_inflight_drops_uncommitted_save(self, tmp_path):
        """Chaos fidelity: a SimulatedKill models a SIGKILL at that
        instant — an async save still in flight AT the kill must not
        commit post-mortem (it would let the simulated run resume from a
        checkpoint a real kill never produced), while a save whose
        commit completed BEFORE the kill is legitimately durable."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, _state(1))                 # durable before the kill
        gate = threading.Event()               # never set: mid-commit
        orig = mgr._write_commit
        mgr._write_commit = lambda *a, **kw: (gate.wait(1.0),
                                              orig(*a, **kw))[1]
        h = mgr.save(2, _state(2), async_save=True)
        assert not h.done()                    # still in flight
        mgr.discard_inflight()                 # the kill instant
        assert mgr.all_steps() == [1]
        assert not [n for n in os.listdir(tmp_path)
                    if n.startswith("tmp.")]
        # a save already committed at the kill instant is kept
        mgr._write_commit = orig
        h = mgr.save(3, _state(3), async_save=True)
        h.wait()
        mgr.discard_inflight()
        assert mgr.all_steps() == [1, 3]

    def test_discard_inflight_never_leaves_zero_checkpoints(self, tmp_path):
        """keep_last=1 + discard racing the commit: whichever side wins,
        at least one committed checkpoint must survive (the old
        wait-then-delete discard let the landing commit GC step 1 and
        then deleted step 2 — zero checkpoints, a state no real SIGKILL
        can produce)."""
        mgr = CheckpointManager(str(tmp_path), keep_last=1)
        mgr.save(1, _state(1))
        gate = threading.Event()
        orig = mgr._write_commit
        mgr._write_commit = lambda *a, **kw: (gate.wait(1.0),
                                              orig(*a, **kw))[1]
        mgr.save(2, _state(2), async_save=True)
        mgr.discard_inflight()                 # cancel beats the publish
        assert mgr.all_steps() == [1]          # step 1 never GC'd
        mgr._write_commit = orig
        h = mgr.save(3, _state(3), async_save=True)
        h.wait()                               # published before the kill
        mgr.discard_inflight()
        assert mgr.all_steps() == [3]          # kept, never deleted


# ========================================================= retention

class TestRetention:
    def test_keep_last_and_keep_every(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last=2, keep_every=5)
        for s in range(1, 12):
            mgr.save(s, {"x": np.float32(s)})
        # newest 2 + multiples of 5 survive
        assert mgr.all_steps() == [5, 10, 11]

    def test_no_retention_keeps_everything(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        for s in range(3):
            mgr.save(s, {"x": np.float32(s)})
        assert mgr.all_steps() == [0, 1, 2]

    def test_keep_every_only_applies_and_newest_survives(self, tmp_path):
        """keep_every without keep_last must still GC (a falsy keep_last
        used to disable configured retention entirely) — and the newest
        step always survives, or a resume right after GC would have
        nothing newer than the last archive step."""
        mgr = CheckpointManager(str(tmp_path), keep_every=5)
        for s in range(1, 13):
            mgr.save(s, {"x": np.float32(s)})
        assert mgr.all_steps() == [5, 10, 12]

    def test_keep_last_zero_keeps_archive_plus_newest(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last=0, keep_every=4)
        for s in range(1, 11):
            mgr.save(s, {"x": np.float32(s)})
        assert mgr.all_steps() == [4, 8, 10]


# ====================================== plain-file atomic save (satellite)

class TestAtomicPlainSave:
    def test_paddle_save_survives_mid_pickle_failure(self, tmp_path):
        """framework.io.save writes through atomic_writer: a failure at
        any byte leaves the previous file contents, never a truncation."""
        target = str(tmp_path / "model.pdparams")
        good = {"w": paddle.to_tensor(np.arange(4, dtype=np.float32))}
        paddle.save(good, target)

        class Poison:
            def __reduce__(self):
                raise RuntimeError("mid-pickle failure")

        with pytest.raises(RuntimeError):
            paddle.save({"w": good["w"], "boom": Poison()}, target)
        back = paddle.load(target)          # previous bytes, fully intact
        np.testing.assert_array_equal(np.asarray(back["w"]._data),
                                      np.arange(4, dtype=np.float32))
        assert [n for n in os.listdir(tmp_path)
                if n != "model.pdparams"] == [], "tmp file leaked"

    def test_atomic_writer_discards_on_simulated_kill(self, tmp_path):
        from paddle_tpu.resilience.checkpoint import atomic_writer
        target = str(tmp_path / "f.bin")
        open(target, "wb").write(b"previous")
        with pytest.raises(SimulatedKill):
            with atomic_writer(target) as f:
                f.write(b"half-writ")
                raise SimulatedKill("mid-write")
        assert open(target, "rb").read() == b"previous"
        assert os.listdir(tmp_path) == ["f.bin"]

    def test_atomic_writer_sweeps_real_kill_orphans(self, tmp_path):
        """A REAL SIGKILL mid-save never unwinds __exit__, leaving a
        full-size tmp orphan — the next save of the same target must
        sweep it (preemption-heavy fleets would otherwise leak one
        multi-GB hidden file per interrupted save, forever)."""
        from paddle_tpu.resilience.checkpoint import atomic_writer
        target = str(tmp_path / "f.bin")
        orphan = tmp_path / ".f.bin.tmp.deadbeef"
        orphan.write_bytes(b"x" * 64)         # the killed save's leavings
        (tmp_path / ".other.tmp.1").write_bytes(b"y")  # different target
        with atomic_writer(target) as f:
            f.write(b"new")
        assert not orphan.exists()
        assert open(target, "rb").read() == b"new"
        assert (tmp_path / ".other.tmp.1").exists()   # not ours: kept

    def test_atomic_writer_writes_through_symlink(self, tmp_path):
        """A symlinked target (ckpt/latest.pdparams -> volume) must be
        written THROUGH, like plain open(path,'wb') did — os.replace
        over the link itself would destroy the link and land the bytes
        on the wrong filesystem."""
        from paddle_tpu.resilience.checkpoint import atomic_writer
        real_dir = tmp_path / "volume"
        real_dir.mkdir()
        real = real_dir / "ckpt.bin"
        real.write_bytes(b"old")
        link = tmp_path / "latest.bin"
        os.symlink(str(real), str(link))
        with atomic_writer(str(link)) as f:
            f.write(b"new")
        assert os.path.islink(str(link)), "symlink clobbered"
        assert real.read_bytes() == b"new"

    def test_atomic_writer_preserves_target_mode(self, tmp_path):
        """os.replace would swap a group-writable shared checkpoint for
        a umask-default tmp file — the previous mode carries over."""
        from paddle_tpu.resilience.checkpoint import atomic_writer
        target = tmp_path / "shared.bin"
        target.write_bytes(b"old")
        os.chmod(str(target), 0o664)
        with atomic_writer(str(target)) as f:
            f.write(b"new")
        assert (os.stat(str(target)).st_mode & 0o777) == 0o664
        assert target.read_bytes() == b"new"

    def test_fsync_is_opt_in(self, tmp_path, monkeypatch):
        """Plain-file atomicity needs tmp+os.replace, NOT fsync — the
        process-durability default must not stall every paddle.save on
        an fsync (power-loss durability is the opt-in tier, same model
        as CheckpointManager's durability=)."""
        from paddle_tpu.resilience.checkpoint import atomic_writer
        calls = []
        real = os.fsync
        monkeypatch.setattr(os, "fsync", lambda fd: calls.append(fd))
        paddle.save({"w": paddle.to_tensor(np.zeros(2, np.float32))},
                    str(tmp_path / "m.pdparams"))
        assert calls == []                   # default: no fsync stall
        with atomic_writer(str(tmp_path / "p.bin"), fsync=True) as f:
            f.write(b"x")
        assert len(calls) == 1               # power tier opts in
        monkeypatch.setattr(os, "fsync", real)


# ============================================ resumable dataloader cursor

class _ArangeDS(Dataset):
    def __init__(self, n=24):
        self.n = n

    def __getitem__(self, i):
        return np.int64(i)

    def __len__(self):
        return self.n


class TestDataloaderCursor:
    def _stream(self, loader, n):
        out = []
        for _ in range(10):
            for b in loader:
                out.append(np.asarray(b).ravel().tolist())
                if len(out) >= n:
                    return out
        return out

    def test_seeded_resume_replays_remaining_stream(self):
        full = self._stream(DataLoader(_ArangeDS(), batch_size=4,
                                       shuffle=True, seed=11), 12)
        ref = DataLoader(_ArangeDS(), batch_size=4, shuffle=True, seed=11)
        first = self._stream(ref, 5)
        cursor = ref.state_dict()
        assert first == full[:5]
        resumed = DataLoader(_ArangeDS(), batch_size=4, shuffle=True,
                             seed=11)
        resumed.set_state_dict(cursor)
        rest = self._stream(resumed, 7)
        assert rest == full[5:12], "resumed stream diverged"

    def test_cursor_spans_epoch_boundary(self):
        full = self._stream(DataLoader(_ArangeDS(8), batch_size=4,
                                       shuffle=True, seed=3), 6)
        ref = DataLoader(_ArangeDS(8), batch_size=4, shuffle=True, seed=3)
        self._stream(ref, 4)              # 2 epochs of 2 batches
        resumed = DataLoader(_ArangeDS(8), batch_size=4, shuffle=True,
                             seed=3)
        resumed.set_state_dict(ref.state_dict())
        assert self._stream(resumed, 2) == full[4:6]

    def test_negative_seed_rejected_at_construction(self):
        """-1 is the cursor's no-seed sentinel: a loader with seed=-1
        would record a cursor indistinguishable from an unreplayable
        one, so it is rejected up front."""
        with pytest.raises(ValueError, match="seed"):
            DataLoader(_ArangeDS(), batch_size=4, shuffle=True, seed=-1)

    def test_seed_mismatch_rejected(self):
        a = DataLoader(_ArangeDS(), batch_size=4, shuffle=True, seed=1)
        b = DataLoader(_ArangeDS(), batch_size=4, shuffle=True, seed=2)
        with pytest.raises(ValueError, match="seed"):
            b.set_state_dict(a.state_dict())

    def test_rejected_cursor_leaves_loader_untouched(self):
        """A REJECTED restore must not arm the cursor (the bug:
        _skip/_pending_resume were assigned before validation, so a
        caller that caught the error and trained fresh silently lost
        the first batch_idx batches of its first epoch)."""
        a = DataLoader(_ArangeDS(), batch_size=4, shuffle=True, seed=1)
        self._stream(a, 2)                     # batch_idx = 2
        b = DataLoader(_ArangeDS(16), batch_size=4, shuffle=True, seed=2)
        with pytest.raises(ValueError, match="seed"):
            b.set_state_dict(a.state_dict())
        assert b._skip == 0 and b._pending_resume is False \
            and b._epoch == 0
        assert len(list(b)) == 4, "fresh epoch lost batches"

    def test_seedless_resume_of_seeded_cursor_rejected(self):
        """Forgetting seed= on the resume loader is a mismatch too: a
        plain shuffle=True loader draws from the global numpy RNG and
        cannot replay the recorded order (the bug: the guard
        short-circuited on seed-is-None and let the silently-different
        batch stream through)."""
        a = DataLoader(_ArangeDS(), batch_size=4, shuffle=True, seed=1)
        b = DataLoader(_ArangeDS(), batch_size=4, shuffle=True)
        with pytest.raises(ValueError, match="seed"):
            b.set_state_dict(a.state_dict())

    def test_unreplayable_shuffled_cursor_rejected(self):
        """A cursor recorded from shuffle=True WITHOUT seed= is
        unreplayable (the permutation came from the global numpy RNG and
        is gone) — restoring it must raise instead of silently
        fast-forwarding into a fresh, unrelated draw."""
        a = DataLoader(_ArangeDS(), batch_size=4, shuffle=True)
        cur = a.state_dict()
        assert cur["seed"] == -1 and cur["shuffle"] is True
        b = DataLoader(_ArangeDS(), batch_size=4, shuffle=True)
        with pytest.raises(ValueError, match="cannot be replayed"):
            b.set_state_dict(cur)
        # a sequential (shuffle=False) seedless cursor IS deterministic
        c = DataLoader(_ArangeDS(), batch_size=4)
        d = DataLoader(_ArangeDS(), batch_size=4)
        d.set_state_dict(c.state_dict())

    def test_user_seeded_sampler_cursor_round_trips(self):
        """A user-provided SEEDED sampler is a deterministic order
        source: its cursor must save AND restore (the bug: the loader
        only looked at its own seed=, recorded seed=-1 + shuffle=True,
        and restore refused its own cursor — breaking resume for the
        DistributedBatchSampler idiom)."""
        def mk():
            smp = SeededBatchSampler(_ArangeDS(), batch_size=4,
                                     shuffle=True, seed=7)
            return DataLoader(_ArangeDS(), batch_sampler=smp)
        full = self._stream(mk(), 6)
        ref = mk()
        first = self._stream(ref, 2)
        cur = ref.state_dict()
        assert cur["seed"] == 7                  # sampler seed recorded
        resumed = mk()
        resumed.set_state_dict(cur)              # must NOT raise
        assert first + self._stream(resumed, 4) == full

    def test_user_sampler_resume_replays_recorded_epoch(self):
        """Restoring a cursor from epoch>0 into a FRESH user sampler
        (epoch 0, the restarted process) must fast-forward through the
        RECORDED epoch's permutation — the resume iteration drives
        set_epoch once; afterwards the sampler is the user's again."""
        def mk():
            smp = SeededBatchSampler(_ArangeDS(), batch_size=4,
                                     shuffle=True, seed=9)
            return DataLoader(_ArangeDS(), batch_sampler=smp)
        # oracle: epochs 0+1 fully, then 2 batches into epoch 2
        oracle = mk()
        oracle.batch_sampler.set_epoch(2)
        epoch2 = self._stream(oracle, 6)
        ref = mk()
        ref._epoch = 2                           # mid-epoch-2 snapshot
        ref.batch_sampler.set_epoch(2)
        self._stream(ref, 2)
        cur = ref.state_dict()
        assert cur["epoch"] == 2 and cur["batch_idx"] == 2
        resumed = mk()                           # fresh process: epoch 0
        resumed.set_state_dict(cur)
        assert self._stream(resumed, 4) == epoch2[2:6]

    def test_shuffle_flag_mismatch_rejected(self):
        """Matching seeds don't help if one side shuffles and the other
        is sequential — the epoch orders still differ (the shuffle flag
        was recorded but never compared when seeds matched)."""
        a = DataLoader(_ArangeDS(), batch_size=4, shuffle=True, seed=5)
        b = DataLoader(_ArangeDS(), batch_size=4, shuffle=False, seed=5)
        with pytest.raises(ValueError, match="shuffle"):
            b.set_state_dict(a.state_dict())

    def test_user_sampler_epoch_not_clobbered(self):
        """A user-provided batch_sampler manages set_epoch itself (the
        DistributedBatchSampler idiom) — the loader's internal resume
        cursor must not overwrite it on every __iter__ (the bug: an
        early-broken epoch froze _epoch and every later epoch silently
        replayed the epoch-0 permutation)."""
        smp = SeededBatchSampler(_ArangeDS(), batch_size=4, shuffle=True,
                                 seed=3)
        dl = DataLoader(_ArangeDS(), batch_sampler=smp)
        smp.set_epoch(5)
        next(iter(dl))                       # early break mid-epoch
        assert smp.epoch == 5                # user's epoch survives
        # the loader's OWN sampler still follows the resume cursor
        own = DataLoader(_ArangeDS(), batch_size=4, shuffle=True, seed=3)
        own.set_state_dict({"epoch": 2, "batch_idx": 0, "seed": 3,
                            "shuffle": True})
        next(iter(own))
        assert own.batch_sampler.epoch == 2

    def test_seeded_sampler_epochs_differ_but_replay(self):
        s = SeededBatchSampler(_ArangeDS(12), batch_size=4, shuffle=True,
                               seed=5)
        e0 = list(s)
        s.set_epoch(1)
        e1 = list(s)
        assert e0 != e1
        s.set_epoch(0)
        assert list(s) == e0

    def test_batch_geometry_mismatch_rejected(self):
        """batch_idx counts BATCHES — fast-forwarding k batches of a
        different size lands on a different sample offset (seed checks
        all pass), so a changed batch_size/drop_last must be rejected,
        not silently resumed onto a shifted stream."""
        a = DataLoader(_ArangeDS(), batch_size=4, shuffle=True, seed=1)
        cur = a.state_dict()
        assert cur["batch_size"] == 4 and cur["drop_last"] is False
        b8 = DataLoader(_ArangeDS(), batch_size=8, shuffle=True, seed=1)
        with pytest.raises(ValueError, match="batch_size"):
            b8.set_state_dict(cur)
        bdl = DataLoader(_ArangeDS(), batch_size=4, shuffle=True, seed=1,
                         drop_last=True)
        with pytest.raises(ValueError, match="drop_last"):
            bdl.set_state_dict(cur)
        # a legacy cursor without the geometry keys still restores
        DataLoader(_ArangeDS(), batch_size=8, shuffle=True,
                   seed=1).set_state_dict(
            {k: v for k, v in cur.items()
             if k not in ("batch_size", "drop_last")})

    def test_distributed_sampler_cursor_resumes(self):
        """DistributedBatchSampler has no seed, but its shuffle order is
        RandomState(epoch) — a pure function of the epoch. The cursor
        must treat it as replayable (the bug: seed=-1 + shuffle=True was
        rejected as unreplayable) and the resumed stream must match."""
        from paddle_tpu.io.sampler import DistributedBatchSampler

        def mk():
            smp = DistributedBatchSampler(_ArangeDS(), 4, num_replicas=1,
                                          rank=0, shuffle=True)
            return DataLoader(_ArangeDS(), batch_sampler=smp)
        full = self._stream(mk(), 12)
        ref = mk()
        self._stream(ref, 5)
        cur = ref.state_dict()
        assert cur["seed"] == -1 and cur["epoch_ordered"] is True
        resumed = mk()                            # fresh process
        resumed.set_state_dict(cur)
        assert self._stream(resumed, 7) == full[5:12]
        # a cursor from a GLOBAL-RNG shuffle still cannot land on it the
        # other way round: epoch_ordered must hold on BOTH sides
        plain = DataLoader(_ArangeDS(), batch_size=4, shuffle=True)
        with pytest.raises(ValueError, match="seed"):
            plain.set_state_dict(cur)


# ============================================== bit-exact resume oracle

class _DropNet(nn.Layer):
    """Dropout exercises the RNG leg of the resume contract."""

    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.act = nn.ReLU()
        self.drop = nn.Dropout(0.25)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(self.drop(self.act(self.fc1(x))))


class _XYDS(Dataset):
    def __init__(self, seed, n=48):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 8).astype(np.float32)
        self.y = rng.randn(n, 4).astype(np.float32)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _world(seed=0, scaler=False, monitor=False):
    paddle.seed(seed)
    net = _DropNet()
    net.train()
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    sc = paddle.amp.GradScaler(init_loss_scaling=256.0) if scaler else None
    mon = StepMonitor(track_memory=False, log_recompiles=False) \
        if monitor else None
    step = TrainStep(net, opt, lambda x, y: nn.MSELoss()(net(x), y),
                     scaler=sc, monitor=mon)
    loader = DataLoader(_XYDS(seed + 1), batch_size=8, shuffle=True,
                        seed=seed + 2)
    return step, loader, mon


def _drive(step, loader, until, losses, manager=None, state=None,
           save_every=2):
    i = step._step_i
    while i < until:
        for batch in loader:
            loss = step(*batch)
            i = step._step_i
            losses.setdefault(i, []).append(
                np.float32(np.asarray(loss._data)).tobytes())
            if manager is not None and i % save_every == 0:
                manager.save(i, state.state_dict(), async_save=True)
            if i >= until:
                break
    if manager is not None:
        manager.wait()


class TestBitExactResume:
    N = 8

    def test_kill_at_step_k_resume_matches_oracle_bitwise(self, tmp_path):
        """The acceptance oracle: uninterrupted run vs (kill at k,
        restart process-equivalent, restore, run to completion) — loss
        trajectory and final params bit-identical. Dropout + seeded
        shuffle + Adam + GradScaler are all in the loop, so the RNG
        stream, dataloader cursor, opt state and scaler all must
        round-trip for this to hold."""
        step, loader, _ = _world(seed=5, scaler=True)
        oracle = {}
        _drive(step, loader, self.N, oracle)
        oracle_params = {n: np.asarray(p._data).tobytes()
                         for n, p in zip(step._param_names, step._params)}

        mgr = CheckpointManager(str(tmp_path), keep_last=3)
        step, loader, _ = _world(seed=5, scaler=True)
        ts = TrainState(train_step=step, loader=loader)
        step.chaos = Injector(0, [KillAfterStep(5)])
        chaos = {}
        with pytest.raises(SimulatedKill):
            _drive(step, loader, self.N, chaos, manager=mgr, state=ts)
        assert max(chaos) == 4      # the kill step's loss dies in flight

        # fresh process-equivalent: rebuild from CONFIG (same seeds — the
        # loader's cursor check enforces that), restore STATE from disk.
        # paddle.seed differs first (999) to prove params/RNG really come
        # from the checkpoint, not from construction.
        paddle.seed(999)
        step, loader, _ = _world(seed=5, scaler=True)
        ts = TrainState(train_step=step, loader=loader)
        resumed_at, sd = mgr.restore_latest()
        ts.load_state_dict(sd)
        # the step-4 async save raced the kill: the contract promises a
        # committed checkpoint survives — whichever one it is, the resume
        # must be bit-exact from there
        assert resumed_at in (2, 4)
        _drive(step, loader, self.N, chaos, manager=mgr, state=ts)

        for s in range(1, self.N + 1):
            want = oracle[s][0]
            for got in chaos.get(s, []):
                assert got == want, f"step {s} loss diverged"
        missing = [s for s in oracle if s not in chaos and s != 5]
        assert not missing
        got_params = {n: np.asarray(p._data).tobytes()
                      for n, p in zip(step._param_names, step._params)}
        assert got_params == oracle_params, "final params diverged"

    def test_scaler_and_monitor_round_trip(self, tmp_path):
        step, loader, mon = _world(seed=3, scaler=True, monitor=True)
        _drive(step, loader, 4, {})
        mon.record_compile("k", None, None)     # make counters non-zero
        ts = TrainState(train_step=step, loader=loader, monitor=mon)
        sd_before = step._scaler.state_dict()
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(4, ts.state_dict())

        step2, loader2, mon2 = _world(seed=3, scaler=True, monitor=True)
        ts2 = TrainState(train_step=step2, loader=loader2, monitor=mon2)
        n, sd = mgr.restore_latest()
        ts2.load_state_dict(sd)
        assert step2._step_i == 4
        assert step2._scaler.state_dict() == sd_before
        assert mon2.state_dict() == mon.state_dict()
        # optimizer master step + device opt state adopted
        assert step2.optimizer._step_count == step.optimizer._step_count
        for st, st2 in zip(step._opt_state, step2._opt_state):
            for k in st:
                assert np.asarray(st[k]).tobytes() == \
                    np.asarray(st2[k]).tobytes()

    def test_rng_stream_continues_exactly(self, tmp_path):
        paddle.seed(42)
        paddle.rand([4])                        # advance the stream
        snap = resilience.state.rng_state_dict()
        a = np.asarray(paddle.rand([8])._data)
        b = np.asarray(paddle.rand([8])._data)
        resilience.state.rng_load_state_dict(snap)
        a2 = np.asarray(paddle.rand([8])._data)
        b2 = np.asarray(paddle.rand([8])._data)
        assert a.tobytes() == a2.tobytes()
        assert b.tobytes() == b2.tobytes()


# ================================================ preemption handling

class TestPreemption:
    def test_poll_is_noop_without_signal(self):
        h = PreemptionHandler()
        h.poll(state=None)                      # no flag -> no raise

    def test_request_takes_emergency_checkpoint_and_exits(self, tmp_path):
        step, loader, _ = _world(seed=2)
        mgr = CheckpointManager(str(tmp_path))
        ts = TrainState(train_step=step, loader=loader)
        h = PreemptionHandler(manager=mgr, state=ts)
        step.preemption = h
        batch = next(iter(loader))
        loss0 = step(*batch)                    # clean step
        assert np.isfinite(np.asarray(loss0._data))
        h.request(signal.SIGTERM)
        with pytest.raises(Preempted) as ei:
            step(*batch)                        # in-flight step FINISHES
        assert ei.value.code == RESUME_EXIT_CODE
        assert ei.value.step == 2               # the completed step
        # emergency checkpoint committed and restorable
        n, sd = mgr.restore_latest()
        assert n == 2 and sd["step"] == 2
        m = json.load(open(os.path.join(mgr.latest(), "MANIFEST.json")))
        assert m["meta"]["reason"] == "preemption"
        assert m["meta"]["signum"] == signal.SIGTERM

    def test_manager_without_state_exits_as_crash(self, tmp_path):
        """The resume-me exit code is a PROMISE that durable progress
        exists. A manager-configured handler with nothing to save must
        exit as a crash (budget charged) — not loop the supervisor on
        free restarts of a job that loses all work every cycle."""
        mgr = CheckpointManager(str(tmp_path))
        h = PreemptionHandler(manager=mgr)
        h.request(signal.SIGTERM)
        with pytest.raises(Preempted) as ei:
            h.poll()
        assert ei.value.code == 1
        assert ei.value.code != RESUME_EXIT_CODE
        assert mgr.all_steps() == []            # nothing was written

    def test_real_sigterm_delivery(self, tmp_path):
        step, loader, _ = _world(seed=4)
        mgr = CheckpointManager(str(tmp_path))
        ts = TrainState(train_step=step, loader=loader)
        h = PreemptionHandler(manager=mgr, state=ts)
        batch = next(iter(loader))
        with h:                                 # installs SIGTERM/SIGINT
            step.preemption = h
            step(*batch)
            os.kill(os.getpid(), signal.SIGTERM)
            with pytest.raises(Preempted):
                step(*batch)
        assert mgr.latest_step() == 2
        # handlers restored on exit
        assert signal.getsignal(signal.SIGTERM) != h._handle

    def test_emergency_checkpoint_resumes_bit_exactly(self, tmp_path):
        """SIGTERM mid-run -> emergency ckpt -> restart resumes the exact
        trajectory (the ISSUE's SIGTERM acceptance row)."""
        N = 6
        step, loader, _ = _world(seed=8)
        oracle = {}
        _drive(step, loader, N, oracle)

        step, loader, _ = _world(seed=8)
        ts = TrainState(train_step=step, loader=loader)
        mgr = CheckpointManager(str(tmp_path))
        h = PreemptionHandler(manager=mgr, state=ts)
        step.preemption = h
        got = {}
        i = 0
        with pytest.raises(Preempted):
            while True:
                for batch in loader:
                    loss = step(*batch)
                    i = step._step_i
                    got.setdefault(i, []).append(
                        np.float32(np.asarray(loss._data)).tobytes())
                    if i == 3:
                        h.request(signal.SIGTERM)   # next boundary exits

        paddle.seed(1234)               # state must come from the ckpt
        step, loader, _ = _world(seed=8)
        ts = TrainState(train_step=step, loader=loader)
        n, sd = mgr.restore_latest()
        # request landed after step 3's boundary poll, so the handler
        # finishes the next in-flight step (4) and checkpoints THERE
        assert n == 4
        ts.load_state_dict(sd)
        _drive(step, loader, N, got)
        for s in range(1, N + 1):
            for v in got.get(s, []):
                assert v == oracle[s][0], f"step {s} diverged post-SIGTERM"

    def test_second_sigint_raises_keyboard_interrupt(self):
        h = PreemptionHandler(signals=(signal.SIGINT,))
        h._handle(signal.SIGINT, None)
        with pytest.raises(KeyboardInterrupt):
            h._handle(signal.SIGINT, None)

    def test_failed_emergency_save_keeps_request_armed(self, tmp_path):
        """An emergency save that fails (transient fault exhausting the
        retry deadline) must leave the preemption flag SET — clearing it
        up front would swallow the SIGTERM, keep training past the
        grace window, and lose everything to the follow-up SIGKILL."""
        mgr = CheckpointManager(str(tmp_path))
        boom = [True]

        def failing_save(*a, **kw):
            if boom[0]:
                raise OSError("disk transient")
            return orig(*a, **kw)

        orig, mgr.save = mgr.save, failing_save
        state = type("S", (), {"state_dict":
                               lambda self: {"step": 1,
                                             "x": np.float32(1)}})()
        h = PreemptionHandler(manager=mgr, state=state)
        h.request(signal.SIGTERM)
        with pytest.raises(OSError):
            h.poll()
        assert h.requested                    # still armed: will retry
        boom[0] = False
        with pytest.raises(Preempted) as ei:  # next boundary succeeds
            h.poll()
        assert ei.value.code == RESUME_EXIT_CODE
        assert not h.requested

    def test_poll_consumes_request_no_restart_loop(self, tmp_path):
        """poll() must CONSUME the preemption request: a handler shared
        across in-process run_with_restarts cycles (created once outside
        the job callable) otherwise re-fires at the restarted run's
        first step boundary and loops checkpoint/restart forever."""
        mgr = CheckpointManager(str(tmp_path))
        state = type("S", (), {"state_dict":
                               lambda self: {"step": 1,
                                             "x": np.float32(1)}})()
        h = PreemptionHandler(manager=mgr, state=state)
        h.request(signal.SIGTERM)
        with pytest.raises(Preempted) as ei:
            h.poll()
        assert ei.value.signum == signal.SIGTERM
        assert not h.requested                # consumed by the raise
        h.poll()                              # restarted run: no re-fire

    def test_sigterm_then_one_sigint_still_drains(self):
        """Only the SECOND ctrl-C means NOW: a spot-VM SIGTERM followed
        by ONE operator SIGINT must keep draining toward the emergency
        checkpoint (the bug: a shared signal counter escalated the first
        SIGINT to KeyboardInterrupt, skipping the checkpoint)."""
        h = PreemptionHandler()
        h._handle(signal.SIGTERM, None)
        h._handle(signal.SIGINT, None)       # still draining
        assert h._requested.is_set()
        with pytest.raises(KeyboardInterrupt):
            h._handle(signal.SIGINT, None)   # the second one means NOW

    def test_chaos_raise_in_step_is_catchable(self):
        """RaiseInStep (ordinary exception) CAN be absorbed by recovery
        code; SimulatedKill cannot — the taxonomy the harness enforces."""
        step, loader, _ = _world(seed=6)
        step.chaos = Injector(0, [RaiseInStep(1, exc=RuntimeError)])
        batch = next(iter(loader))
        try:
            step(*batch)
        except Exception as e:
            assert "injected fault" in str(e)
        else:
            pytest.fail("fault did not fire")


# ================================== fit() preemption via hapi callback

class TestFitPreemption:
    def test_sigterm_mid_fit_emergency_checkpoint(self, tmp_path):
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.callbacks import PreemptionCallback
        from paddle_tpu.static import InputSpec

        paddle.seed(0)
        net = _DropNet()
        model = Model(net, inputs=[InputSpec([None, 8], "float32", "x")],
                      labels=[InputSpec([None, 4], "float32", "y")])
        model.prepare(paddle.optimizer.Adam(learning_rate=1e-2,
                                            parameters=net.parameters()),
                      nn.MSELoss(), use_fused_step=True)
        mgr = CheckpointManager(str(tmp_path))
        h = PreemptionHandler(manager=mgr)

        class TripWire(paddle.hapi.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                if step == 2:
                    h.request(signal.SIGTERM)

        ds = _XYDS(1, n=64)
        with pytest.raises(Preempted) as ei:
            model.fit(ds, batch_size=8, epochs=2, verbose=0,
                      callbacks=[TripWire(),
                                 PreemptionCallback(h, install=False)])
        assert ei.value.code == RESUME_EXIT_CODE
        # the emergency snapshot captured the fused TrainStep's state
        n, sd = mgr.restore_latest()
        assert "params" in sd and sd["step"] == n >= 3

    def test_eager_fit_emergency_checkpoint_has_state(self, tmp_path):
        """Eager (non-fused) fit path: the resume-me exit must be backed
        by a real snapshot — network params, optimizer state and the RNG
        key — not an empty promise (the bug: the eager path exited with
        RESUME_EXIT_CODE having checkpointed nothing)."""
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.callbacks import PreemptionCallback
        from paddle_tpu.static import InputSpec

        paddle.seed(0)
        net = _DropNet()
        model = Model(net, inputs=[InputSpec([None, 8], "float32", "x")],
                      labels=[InputSpec([None, 4], "float32", "y")])
        model.prepare(paddle.optimizer.Adam(learning_rate=1e-2,
                                            parameters=net.parameters()),
                      nn.MSELoss(), use_fused_step=False)
        mgr = CheckpointManager(str(tmp_path))
        h = PreemptionHandler(manager=mgr)

        class TripWire(paddle.hapi.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                if step == 2:
                    h.request(signal.SIGTERM)

        with pytest.raises(Preempted) as ei:
            model.fit(_XYDS(1, n=64), batch_size=8, epochs=1, verbose=0,
                      callbacks=[TripWire(),
                                 PreemptionCallback(h, install=False)])
        assert ei.value.code == RESUME_EXIT_CODE
        n, sd = mgr.restore_latest()
        # 3 batches (0,1,2) completed -> monotonic global step 3 (NOT
        # the epoch-local batch index, which resets every epoch and
        # would let an older epoch's checkpoint shadow a newer one)
        assert sd["step"] == n == 3
        # the snapshot holds the live network weights + opt + RNG
        assert "rng" in sd and "optimizer" in sd
        for k, v in net.state_dict().items():
            np.testing.assert_array_equal(np.asarray(sd["model"][k]),
                                          np.asarray(v._data))


# ================================= restart supervision (fleet.elastic)

class TestRunWithRestarts:
    def test_resume_exits_restart_without_crash_budget(self):
        from paddle_tpu.distributed.fleet.elastic import run_with_restarts
        codes = iter([RESUME_EXIT_CODE, RESUME_EXIT_CODE, 0])
        seen = []

        def job():
            c = next(codes)
            if c == RESUME_EXIT_CODE:
                raise Preempted(c, step=len(seen))
            return c

        report = run_with_restarts(
            job, max_crash_restarts=0, sleep=lambda s: seen.append(s))
        assert report.final_code == 0
        assert report.resumes == 2 and report.crashes == 0
        assert report.exit_codes == [RESUME_EXIT_CODE, RESUME_EXIT_CODE, 0]
        assert seen == []                     # resumes never back off

    def test_crash_budget_and_backoff_schedule(self):
        from paddle_tpu.distributed.fleet.elastic import run_with_restarts
        delays = []

        def always_crashes():
            raise RuntimeError("boom")

        report = run_with_restarts(always_crashes, max_crash_restarts=3,
                                   backoff_s=1.0, max_backoff_s=3.0,
                                   sleep=lambda s: delays.append(s))
        assert report.final_code == 1
        assert report.crashes == 4            # initial + 3 restarts
        assert delays == [1.0, 2.0, 3.0]      # capped exponential

    def test_full_loop_preempt_resume_complete(self, tmp_path):
        """The production shape in miniature: a 'job' that trains with a
        PreemptionHandler, gets preempted twice, and completes — driven
        end-to-end by run_with_restarts."""
        from paddle_tpu.distributed.fleet.elastic import run_with_restarts
        N = 6
        mgr = CheckpointManager(str(tmp_path))
        preempt_at = iter([2, 4, None])
        losses = {}

        def job():
            step, loader, _ = _world(seed=9)
            ts = TrainState(train_step=step, loader=loader)
            if mgr.latest_step() is not None:
                _, sd = mgr.restore_latest()
                ts.load_state_dict(sd)
            h = PreemptionHandler(manager=mgr, state=ts)
            step.preemption = h
            trip = next(preempt_at)
            i = step._step_i
            while i < N:
                for batch in loader:
                    loss = step(*batch)
                    i = step._step_i
                    losses.setdefault(i, []).append(
                        np.float32(np.asarray(loss._data)).tobytes())
                    if trip is not None and i == trip:
                        h.request(signal.SIGTERM)
                    if i >= N:
                        break
            return 0

        report = run_with_restarts(job, max_crash_restarts=0,
                                   max_resumes=5)
        assert report.final_code == 0 and report.resumes == 2

        step, loader, _ = _world(seed=9)
        oracle = {}
        _drive(step, loader, N, oracle)
        for s, vals in losses.items():
            for v in vals:
                assert v == oracle[s][0], f"step {s} diverged"


# ============================ zero recompiles + lint with resilience on

class TestSteadyStateInvariants:
    def test_zero_steady_recompiles_with_ckpt_and_handler(self, tmp_path):
        step, loader, _ = _world(seed=12, monitor=True)
        mgr = CheckpointManager(str(tmp_path), keep_last=2)
        ts = TrainState(train_step=step, loader=loader)
        h = PreemptionHandler(manager=mgr, state=ts)
        step.preemption = h
        _drive(step, loader, 2, {})             # warmup: the one compile
        misses0 = compile_cache_misses()
        _drive(step, loader, 10, {}, manager=mgr, state=ts, save_every=2)
        assert compile_cache_misses() == misses0, \
            "checkpointing/preemption wiring caused steady-state recompiles"

    def test_train_step_lint_clean_with_resilience_wired(self):
        from paddle_tpu.analysis import GraphLint
        step, loader, _ = _world(seed=13)
        step.preemption = PreemptionHandler()
        step.chaos = Injector(0)
        x, y = next(iter(loader))
        fs = step.lint(x, y, lint=GraphLint(upcast_bytes=256,
                                            const_bytes=2048,
                                            donate_bytes=2048))
        active = fs.active("warn")
        assert not active, \
            f"resilience wiring dirtied the step graph: " \
            f"{[str(f) for f in active]}"


# ==================================== serving drain + load shedding

BATCH, CAP, NEW = 4, 16, 8


@pytest.fixture(scope="module")
def served_model():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m, cfg


def _engine(m, **kw):
    from paddle_tpu.inference import ServingConfig, ServingEngine
    base = dict(max_batch=BATCH, prompt_cap=CAP, max_new_tokens=NEW,
                decode_chunk=3)
    base.update(kw)
    return ServingEngine(m, ServingConfig(**base))


def _prompts(cfg, lens, seed=1):
    rng = np.random.RandomState(seed)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    return ids


class TestServingDrain:
    def test_drain_refuses_then_finishes_inflight(self, served_model):
        m, cfg = served_model
        eng = _engine(m)
        ids = _prompts(cfg, [5, 7, 4, 6])
        live = [eng.submit(ids[i, :l]) for i, l in
                enumerate([5, 7, 4, 6])]
        eng.begin_drain()
        refused = eng.submit(ids[0, :5])
        assert refused.status == "rejected" and refused.reason == "draining"
        done = eng.drain()
        assert {r.id for r in done} == {r.id for r in live}
        assert all(r.status == "done" for r in done)
        eng.resume_admission()
        ok = eng.submit(ids[0, :5])
        assert ok.status in ("queued", "admitted")

    def test_high_watermark_sheds_with_overloaded(self, served_model):
        m, cfg = served_model
        eng = _engine(m, queue_capacity=16, queue_high_watermark=3)
        ids = _prompts(cfg, [5] * 8)
        out = [eng.submit(ids[i, :5]) for i in range(8)]
        shed = [r for r in out if r.status == "rejected"]
        assert shed and all(r.reason == "overloaded" for r in shed)
        assert eng.metrics.counters["overloaded"] == len(shed)
        assert eng.metrics.counters["rejected"] == len(shed)
        eng.drain()

    def test_watermark_validation(self, served_model):
        from paddle_tpu.inference import ServingConfig
        with pytest.raises(ValueError, match="queue_high_watermark"):
            ServingConfig(max_batch=2, prompt_cap=8, max_new_tokens=4,
                          queue_capacity=4, queue_high_watermark=9)

    def test_seal_drain_flushes_metrics(self, served_model, tmp_path):
        m, cfg = served_model
        jl = str(tmp_path / "metrics.jsonl")
        eng = _engine(m)
        eng.metrics.jsonl_path = jl
        ids = _prompts(cfg, [5, 6])
        eng.submit(ids[0, :5])
        eng.submit(ids[1, :6])
        done = eng.drain(seal=True)
        assert len(done) == 2
        assert eng.draining
        assert eng.metrics.gauges["queue_depth"] == 0
        assert eng.metrics.gauges["inflight"] == 0
        assert eng.metrics.gauges["kv_occupancy"] is None
        rows = [json.loads(l) for l in open(jl) if l.strip()]
        assert any("drain" in r for r in rows)
        drain_row = [r for r in rows if "drain" in r][-1]
        assert drain_row["drain"]["completed_total"] == 2


# =========================================== dist_save / dist_load names

class TestDistSaveLoad:
    def test_round_trip(self, tmp_path):
        from paddle_tpu.distributed import dist_save, dist_load
        sd = {"w": paddle.to_tensor(
            np.random.RandomState(0).randn(4, 4).astype(np.float32))}
        dist_save(sd, str(tmp_path / "ckpt"))
        back = dist_load(str(tmp_path / "ckpt"))
        np.testing.assert_array_equal(np.asarray(back["w"]._data),
                                      np.asarray(sd["w"]._data))

    def test_scalar_and_string_leaves_round_trip(self, tmp_path):
        """Real state dicts carry config scalars next to the arrays
        (activation names, layer counts, LR floats). The manifest
        fallback must round-trip them as-is — the bug: dist_load pushed
        EVERY leaf through jnp.asarray, which crashes on str and turns
        python ints/floats into 0-d Tensors. (Forces the fallback: the
        orbax path has its own leaf-type rules and is not under test.)"""
        import paddle_tpu.distributed.checkpoint as dck
        from paddle_tpu.distributed import dist_save, dist_load
        sd = {"w": paddle.to_tensor(np.ones((2, 3), np.float32)),
              "meta": {"act": "linear", "layers": 3, "lr": 0.5}}
        with pytest.MonkeyPatch.context() as mp:
            mp.setattr(dck, "ocp", None)
            dist_save(sd, str(tmp_path / "ckpt"))
            back = dist_load(str(tmp_path / "ckpt"))
        assert back["meta"]["act"] == "linear"
        assert isinstance(back["meta"]["act"], str)
        assert back["meta"]["layers"] == 3
        assert isinstance(back["meta"]["layers"], int)
        assert back["meta"]["lr"] == 0.5
        assert isinstance(back["meta"]["lr"], float)

    def test_fallback_shares_manager_and_settles_async(self, tmp_path):
        """dist_save must reuse ONE manager per target path: a fresh
        manager per call bypasses the save-serialization guard, so a
        second save's tmp-dir GC could delete the first's still-in-
        flight write. dist_load waits out an in-flight async save."""
        import paddle_tpu.distributed.checkpoint as dck
        from paddle_tpu.distributed import dist_save, dist_load
        p = str(tmp_path / "ckpt")
        sd = {"w": paddle.to_tensor(np.full((8, 8), 3.0, np.float32))}
        with pytest.MonkeyPatch.context() as mp:
            mp.setattr(dck, "ocp", None)
            assert dck._fallback_manager(p) is dck._fallback_manager(p)
            dist_save(sd, p, async_save=True)   # in flight...
            back = dist_load(p)                 # ...must settle first
        np.testing.assert_array_equal(np.asarray(back["w"]._data),
                                      np.asarray(sd["w"]._data))
        assert not [n for n in os.listdir(p) if n.startswith("tmp.")]


# ============================================= chaos sweep (slow tier)

@pytest.mark.slow
def test_chaos_sweep_multi_seed():
    """The heavy sweep: several seeded kill/resume scenarios through the
    real chaos_train driver (GPT model), plus the overhead report."""
    import tools.chaos_train as ct
    rc = ct.main(["--sweep", "3", "--steps", "8", "--quick"])
    assert rc == 0
