"""paddle.text dataset parsers over local corpus files (VERDICT r1
missing #8: text breadth — the stubs became real parsers; download is the
only part that stays unavailable in a zero-egress environment).

Each test writes a tiny synthetic corpus in the canonical on-disk format
and checks parsing, vocab rules, and sample shapes against the reference
semantics (text/datasets/*.py)."""
import os

import numpy as np
import pytest

from paddle_tpu import text


def test_uci_housing_normalization(tmp_path):
    rng = np.random.RandomState(0)
    raw = rng.rand(20, 14) * 10
    f = tmp_path / "housing.data"
    f.write_text("\n".join(" ".join(f"{v:.4f}" for v in row) for row in raw))
    tr = text.UCIHousing(data_file=str(f), mode="train")
    te = text.UCIHousing(data_file=str(f), mode="test")
    assert len(tr) == 16 and len(te) == 4        # 80/20 split
    x, y = tr[0]
    assert x.shape == (13,) and y.shape == (1,)
    # reference normalization: (x - avg) / (max - min) per feature
    data = np.loadtxt(str(f))
    want = (data[0, 0] - data[:, 0].mean()) / (data[:, 0].max() - data[:, 0].min())
    np.testing.assert_allclose(x[0], want, rtol=1e-4)


def test_imikolov_ngram_and_seq(tmp_path):
    f = tmp_path / "ptb.txt"
    f.write_text("a b c\na b\nc a b\n")
    ds = text.Imikolov(data_file=str(f), data_type="NGRAM", window_size=2,
                       min_word_freq=0)
    # every line becomes <s> ... <e> bigrams
    assert len(ds) > 0
    g = ds[0]
    assert len(g) == 2
    seq = text.Imikolov(data_file=str(f), data_type="SEQ", window_size=-1,
                        min_word_freq=0)
    src, trg = seq[0]
    assert len(src) == len(trg)                  # <s>+sent / sent+<e>
    # vocab: freq > min sorted by (-freq, word); <unk> last
    assert seq.word_idx["<unk>"] == len(seq.word_idx) - 1


def test_imdb_tsv_and_vocab_cutoff(tmp_path):
    f = tmp_path / "imdb.tsv"
    rows = ["1\tgood great good movie", "0\tbad awful bad film",
            "1\tgood film", "0\tbad movie"]
    f.write_text("\n".join(rows))
    ds = text.Imdb(data_file=str(f), mode="train", cutoff=2)
    assert len(ds) == 4
    doc, label = ds[0]
    assert label == 1 and doc.dtype == np.int64
    # words with freq >= 2 kept: good(3) bad(3) film(2) movie(2)
    assert set(ds.word_idx) == {"good", "bad", "film", "movie", "<unk>"}
    # rarer word maps to <unk>
    unk = ds.word_idx["<unk>"]
    d1, _ = ds[1]
    assert unk in d1.tolist()                    # "awful"


def test_wmt_parallel_pairs(tmp_path):
    f = tmp_path / "pairs.tsv"
    f.write_text("hello world\tbonjour monde\nbye\tau revoir\n")
    ds = text.WMT14(data_file=str(f), mode="train", dict_size=50)
    assert len(ds) == 2
    src, trg, nxt = ds[0]
    assert src[0] == ds.src_ids["<s>"] and src[-1] == ds.src_ids["<e>"]
    assert trg[0] == ds.trg_ids["<s>"]
    assert nxt[-1] == ds.trg_ids["<e>"]
    assert len(trg) == len(nxt)


def test_movielens_ml1m_format(tmp_path):
    (tmp_path / "movies.dat").write_text(
        "1::Toy Story (1995)::Animation|Comedy\n2::Heat (1995)::Action\n")
    (tmp_path / "users.dat").write_text(
        "1::M::25::4::12345\n2::F::35::7::54321\n")
    (tmp_path / "ratings.dat").write_text(
        "1::1::5::978300760\n2::2::3::978300761\n1::2::4::978300762\n")
    tr = text.Movielens(data_file=str(tmp_path), mode="train", test_ratio=0.0)
    assert len(tr) == 3
    uid, g, a, j, mid, title, cats, rating = tr[0]
    assert int(uid) == 1 and float(rating) == 5.0
    assert "Animation" in tr.categories_dict


def test_conll05_columns(tmp_path):
    f = tmp_path / "srl.txt"
    f.write_text("The\t-\tB-A0\ncat\t-\tE-A0\nsat\tsit\tB-V\n\n"
                 "Dogs\t-\tB-A0\nbark\tbark\tB-V\n")
    ds = text.Conll05st(data_file=str(f))
    assert len(ds) == 2
    w, p, l = ds[0]
    assert w.shape == (3,) and p.shape == (3,) and l.shape == (3,)
    assert len(ds.word_dict) == 5


def test_missing_file_raises():
    with pytest.raises(FileNotFoundError, match="data_file"):
        text.UCIHousing(data_file="/nonexistent/x.data")
