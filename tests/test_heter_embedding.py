"""MeshShardedEmbedding — the HeterPS capability (VERDICT r1 missing #1).

Reference: framework/fleet/heter_ps/ keeps hot embedding rows device-resident
with host spill; these tests assert the TPU redesign's contract: exact
parity with an uncached row-sparse adagrad trajectory, exact spill/readmit
round-trips, mesh sharding of the cache rows, prefetch overlap, and
save/load persistence.
"""
import os
import tempfile

import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.heter import MeshShardedEmbedding
from paddle_tpu.distributed.ps import SparseTable


def _ref_table(dim, lr, seed):
    """Host reference: same init stream, merged row-sparse adagrad."""
    return SparseTable(dim=dim, optimizer="adagrad", lr=lr, seed=seed)


def _train(emb, steps=5, dim=8, seed=0, vocab=50):
    rng = np.random.RandomState(seed)
    ref = _ref_table(dim, emb.lr, seed=0)
    # identical init streams: SparseTable and MeshShardedEmbedding both draw
    # uniform(-scale, scale) rows from RandomState(seed) on first touch and
    # ids arrive in the same order, so row inits match exactly
    for _ in range(steps):
        ids = rng.randint(0, vocab, (6, 2)).astype(np.int64)
        out = emb(paddle.to_tensor(ids))
        ref_rows = ref.pull(ids.reshape(-1)).reshape(6, 2, dim)
        np.testing.assert_allclose(out.numpy(), ref_rows, rtol=1e-5,
                                   atol=1e-6, err_msg="pull mismatch")
        loss = (out * out).sum()
        loss.backward()
        ref.push(ids.reshape(-1), 2 * ref_rows.reshape(-1, dim))
    return ref


class TestMeshShardedEmbedding:
    def test_parity_with_host_table_infinite_cache(self):
        emb = MeshShardedEmbedding(dim=8, capacity=128, lr=0.05, seed=0)
        _train(emb, steps=5)

    def test_parity_with_tiny_cache_spill(self):
        """capacity 16 « 50 touched ids: steps evict/readmit; the
        trajectory must be identical to the infinite cache (rows carry
        their accumulators through spill)."""
        emb = MeshShardedEmbedding(dim=8, capacity=16, lr=0.05, seed=0)
        _train(emb, steps=5)
        assert emb.resident_rows() <= 16
        assert emb.state_size() > 16  # spill tier holds the cold tail
        # a batch whose working set exceeds capacity fails loudly
        with pytest.raises(ValueError, match="working set"):
            emb(paddle.to_tensor(np.arange(100, 120).reshape(1, 20)))

    def test_mesh_sharded_cache_rows(self):
        mesh = dist.build_mesh({"mp": 8})
        with dist.mesh_scope(mesh):
            emb = MeshShardedEmbedding(dim=8, capacity=64, axis="mp")
            ids = paddle.to_tensor(np.arange(16).reshape(4, 4))
            out = emb(ids)
            assert out.shape == [4, 4, 8]
            shard = emb._table.addressable_shards[0].data
            assert shard.shape[0] * 8 <= emb._table.shape[0] + 8

    def test_prefetch_overlap(self):
        emb = MeshShardedEmbedding(dim=4, capacity=32, seed=0)
        ids = np.array([[1, 2], [3, 4]], np.int64)
        t = emb.prefetch(ids)
        t.join()
        assert emb._staged is not None
        out = emb(paddle.to_tensor(ids))           # consumes staged admission
        assert emb._staged is None
        np.testing.assert_allclose(out.numpy(), emb.rows_for(
            [1, 2, 3, 4]).reshape(2, 2, 4))

    def test_save_load_roundtrip(self):
        emb = MeshShardedEmbedding(dim=4, capacity=16, seed=0)
        ids = np.arange(10, dtype=np.int64).reshape(2, 5)
        out = emb(paddle.to_tensor(ids))
        (out * out).sum().backward()               # perturb rows
        want = emb.rows_for(list(range(10)))
        path = os.path.join(tempfile.mkdtemp(), "emb.npz")
        emb.save(path)
        emb2 = MeshShardedEmbedding(dim=4, capacity=16, seed=0)
        emb2.load(path)
        got = emb2.rows_for(list(range(10)))
        np.testing.assert_allclose(got, want, rtol=1e-6)
        assert emb2.state_size() == emb.state_size()
