"""MoE expert parallelism + incubate fused layers / optimizers.

Reference surfaces: incubate/distributed/models/moe/moe_layer.py:260 (gates
naive/gshard/switch), incubate/nn/layer/fused_transformer.py, lbfgs.py.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.incubate.distributed.models.moe import (
    MoELayer, NaiveGate, GShardGate, SwitchGate)
from paddle_tpu.incubate.nn import (
    FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer,
    FusedMultiTransformer)
from paddle_tpu.incubate.nn.functional import (
    fused_matmul_bias, fused_bias_dropout_residual_layer_norm)
from paddle_tpu.incubate.optimizer import LBFGS, DistributedFusedLamb


def _x(b=2, s=8, m=16, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randn(b, s, m).astype("float32"))


class TestMoE:
    @pytest.mark.parametrize("gate", ["naive", "gshard", "switch"])
    def test_forward_shape_and_aux(self, gate):
        layer = MoELayer(16, 32, num_experts=4, gate=gate)
        y = layer(_x())
        assert tuple(y.shape) == (2, 8, 16)
        assert layer.aux_loss is not None
        aux = float(layer.aux_loss)
        assert np.isfinite(aux)
        if gate == "naive":
            assert aux == 0.0
        else:
            assert aux > 0.0

    def test_gate_objects(self):
        for g in (NaiveGate(), GShardGate(), SwitchGate()):
            layer = MoELayer(8, 16, num_experts=2, gate=g)
            assert layer.gate_type == g.gate_type
            assert layer.top_k == g.top_k

    def test_backward_flows_to_experts_and_gate(self):
        layer = MoELayer(16, 32, num_experts=4, gate="gshard",
                         capacity_factor=4.0)
        y = layer(_x())
        loss = paddle.mean(y * y) + 0.01 * layer.aux_loss
        loss.backward()
        assert layer.w1.grad is not None
        assert float(paddle.abs(layer.gate_weight.grad).sum()) > 0.0

    def test_switch_router_learns_from_task_loss(self):
        # top-1 combine weight must carry the raw router prob, so the task
        # loss (no aux term) reaches gate_weight
        layer = MoELayer(16, 32, num_experts=4, gate="switch",
                         capacity_factor=4.0)
        y = layer(_x())
        paddle.mean(y * y).backward()
        assert float(paddle.abs(layer.gate_weight.grad).sum()) > 0.0

    def test_external_gate_logits_change_routing(self):
        paddle.seed(0)
        layer = MoELayer(8, 16, num_experts=4, gate="switch",
                         capacity_factor=4.0)
        x = _x(m=8)
        base = np.asarray(layer(x)._data)
        # force all tokens to expert 2
        gl = np.full((2, 8, 4), -1e9, np.float32)
        gl[:, :, 2] = 0.0
        forced = np.asarray(layer(x, gate_logits=paddle.to_tensor(gl))._data)
        assert not np.allclose(base, forced)

    def test_ep_mesh_parity_with_single_device(self):
        paddle.seed(0)
        layer = MoELayer(16, 32, num_experts=4, gate="gshard",
                         capacity_factor=4.0)
        x = _x()
        want = np.asarray(layer(x)._data)

        from paddle_tpu.jit.api import _trace_guard, _swap_params
        from paddle_tpu.core import autograd as ag
        params = [p for _, p in layer.named_parameters()]

        def fn(arrs, xv):
            with _trace_guard(), _swap_params(params, list(arrs)), ag.no_grad():
                return layer(paddle.Tensor(xv))._data

        mesh = dist.build_mesh({"dp": 2, "ep": 4})
        dist.set_mesh(mesh)
        try:
            with mesh:
                got = np.asarray(jax.jit(fn)(
                    tuple(p._data for p in params), x._data))
        finally:
            dist.set_mesh(None)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_moe_model_trains_under_trainstep(self):
        from paddle_tpu.jit.train_step import TrainStep

        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.inp = nn.Linear(8, 16)
                self.moe = MoELayer(16, 32, num_experts=4, gate="switch",
                                    capacity_factor=4.0)
                self.out = nn.Linear(16, 1)

            def forward(self, x):
                return self.out(self.moe(self.inp(x)))

        net = Net()
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())

        def loss_fn(x, y):
            pred = net(x)
            return nn.MSELoss()(pred, y) + 0.01 * net.moe.aux_loss

        step = TrainStep(net, opt, loss_fn)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(4, 8, 1).astype("float32"))
        losses = [float(step(x, y)) for _ in range(8)]
        assert losses[-1] < losses[0]


class TestFusedLayers:
    def test_fused_mha_shape_and_eval_determinism(self):
        m = FusedMultiHeadAttention(16, 4, dropout_rate=0.1,
                                    attn_dropout_rate=0.1)
        m.eval()
        x = _x()
        a = np.asarray(m(x)._data)
        b = np.asarray(m(x)._data)
        assert a.shape == (2, 8, 16)
        np.testing.assert_array_equal(a, b)

    def test_fused_ffn_matches_manual(self):
        m = FusedFeedForward(16, 32, dropout_rate=0.0)
        m.eval()
        x = _x()
        got = np.asarray(m(x)._data)
        xv = x._data
        h = jax.nn.relu(xv @ m.linear1_weight._data + m.linear1_bias._data)
        y = h @ m.linear2_weight._data + m.linear2_bias._data
        y = xv + y
        mu = jnp.mean(y, -1, keepdims=True)
        var = jnp.var(y, -1, keepdims=True)
        want = (y - mu) * jax.lax.rsqrt(var + 1e-5) * m.ln2_scale._data \
            + m.ln2_bias._data
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5, atol=1e-5)

    def test_encoder_layer_and_stack_train(self):
        enc = FusedTransformerEncoderLayer(16, 4, 32, dropout_rate=0.0)
        y = enc(_x())
        loss = paddle.mean(y * y)
        loss.backward()
        assert enc.fused_attn.qkv_weight.grad is not None

        stack = FusedMultiTransformer(16, 4, 32, num_layers=2)
        stack.eval()
        assert tuple(stack(_x()).shape) == (2, 8, 16)

    def test_multi_transformer_cached_decode_matches_full(self):
        paddle.seed(0)
        stack = FusedMultiTransformer(16, 4, 32, num_layers=2)
        stack.eval()
        x = _x(s=6)
        full = np.asarray(stack(x)._data)
        # decode chunk-by-chunk with caches; last chunk must match the
        # full forward's tail (non-causal attention over the accumulated seq
        # differs from full bidirectional attention, so compare via a causal
        # equivalence: feed the whole prefix as the first chunk)
        caches = [(paddle.to_tensor(np.zeros((2, 0, 4, 4), np.float32)),
                   paddle.to_tensor(np.zeros((2, 0, 4, 4), np.float32)))
                  for _ in range(2)]
        out, caches = stack(x, caches=caches)
        np.testing.assert_allclose(np.asarray(out._data), full,
                                   rtol=1e-5, atol=1e-5)
        assert caches[0][0].shape[1] == 6  # cache accumulated

    def test_lbfgs_rejects_bad_line_search(self):
        p = paddle.Parameter(jnp.zeros((2,), jnp.float32))
        with pytest.raises(ValueError):
            LBFGS(parameters=[p], line_search_fn="wolfe")

    def test_fused_matmul_bias(self):
        rng = np.random.RandomState(0)
        a = paddle.to_tensor(rng.randn(4, 8).astype("float32"))
        w = paddle.to_tensor(rng.randn(8, 3).astype("float32"))
        b = paddle.to_tensor(rng.randn(3).astype("float32"))
        got = np.asarray(fused_matmul_bias(a, w, b)._data)
        want = np.asarray(a._data @ w._data + b._data)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_fused_bias_dropout_residual_ln(self):
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(2, 4, 8).astype("float32"))
        r = paddle.to_tensor(rng.randn(2, 4, 8).astype("float32"))
        out = fused_bias_dropout_residual_layer_norm(
            x, r, dropout_rate=0.0, training=False)
        y = x._data + r._data
        mu = jnp.mean(y, -1, keepdims=True)
        var = jnp.var(y, -1, keepdims=True)
        want = (y - mu) * jax.lax.rsqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(out._data), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestIncubateOptimizers:
    def test_lbfgs_minimizes_quadratic(self):
        paddle.seed(0)
        w = paddle.to_tensor(np.array([3.0, -2.0], np.float32))
        w.stop_gradient = False
        p = paddle.Parameter(w._data)
        target = jnp.asarray([1.0, 1.0], jnp.float32)
        opt = LBFGS(learning_rate=1.0, max_iter=10, parameters=[p],
                    line_search_fn="strong_wolfe")

        def closure():
            opt.clear_grad()
            diff = p - paddle.Tensor(target)
            loss = paddle.sum(diff * diff)
            loss.backward()
            return loss

        loss = opt.step(closure)
        assert float(loss) < 1e-6
        np.testing.assert_allclose(np.asarray(p._data), [1.0, 1.0], atol=1e-3)

    def test_distributed_fused_lamb_trains(self):
        paddle.seed(0)
        model = nn.Linear(4, 2)
        opt = DistributedFusedLamb(learning_rate=0.1,
                                   parameters=model.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4).astype("float32"))
        losses = []
        for _ in range(5):
            loss = paddle.mean(model(x) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]


def test_moe_capacity_drop_rates():
    """The README's capacity/overhead decomposition rests on these routing
    facts — keep them repo-verifiable: at balanced (random-init) routing,
    tight capacity cf=1.0 drops <2% of (token,slot) assignments and the
    GShard-default cf=1.25 drops none; under a deliberate 2-expert logit
    bias the tight config pays real drops (what cf>1 headroom buys)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.incubate.distributed.models.moe.moe_layer import (
        _topk_routing, _capacity)

    N, E, k = 8192, 8, 2
    rng = np.random.RandomState(0)

    def drop(logits, cf):
        probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
        cap = _capacity(N, E, k, cf)
        _, _, _, keeps, _ = _topk_routing(probs, k, cap)
        return 1.0 - float(jnp.mean(keeps.astype(jnp.float32)))

    balanced = rng.randn(N, E).astype(np.float32)
    assert drop(balanced, 1.0) < 0.02
    assert drop(balanced, 1.25) == 0.0
    biased = balanced + np.array([0.3, 0.3, 0, 0, 0, 0, 0, 0], np.float32)
    assert drop(biased, 1.0) > drop(biased, 1.25) > 0.0


def test_multi_transformer_int8_static_cache():
    """5-tuple int8 CacheKV (codes+scales, the reference fused_multi_
    transformer cache-quant analog) tracks the bf16 static cache closely:
    same decode trajectory with int8 quantization noise only."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer

    paddle.seed(0)
    d, nh, nl, B, L = 32, 2, 2, 2, 12
    hd = d // nh
    m = FusedMultiTransformer(d, nh, dim_feedforward=64, num_layers=nl,
                              dropout_rate=0.0)
    m.eval()
    rng = np.random.RandomState(0)
    steps = [paddle.to_tensor(rng.randn(B, 1, d).astype("float32"))
             for _ in range(4)]

    s_caches = [(paddle.zeros([B, L, nh, hd]),
                 paddle.zeros([B, L, nh, hd]),
                 paddle.to_tensor(np.int32(0))) for _ in range(nl)]
    q_caches = [(paddle.zeros([B, L, nh, hd], dtype="int8"),
                 paddle.zeros([B, L, nh]),
                 paddle.zeros([B, L, nh, hd], dtype="int8"),
                 paddle.zeros([B, L, nh]),
                 paddle.to_tensor(np.int32(0))) for _ in range(nl)]
    for i, x in enumerate(steps):
        o_s, s_caches = m(x, caches=s_caches)
        o_q, q_caches = m(x, caches=q_caches)
        ref = o_s.numpy()
        tol = 0.05 * np.abs(ref).max() + 1e-3
        np.testing.assert_allclose(o_q.numpy(), ref, atol=tol,
                                   err_msg=f"step {i}")
    assert int(q_caches[0][4].numpy()) == len(steps)
    assert q_caches[0][0].numpy().dtype == np.int8


def test_multi_transformer_static_cache_matches_growing():
    """FusedMultiTransformer 3-tuple static cache == 2-tuple growing cache
    over an incremental decode (the fused_multi_transformer CacheKV
    workspace semantics)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer

    paddle.seed(0)
    d, nh, nl, B, L = 32, 2, 2, 2, 12
    m = FusedMultiTransformer(d, nh, dim_feedforward=64, num_layers=nl,
                              dropout_rate=0.0)
    m.eval()
    rng = np.random.RandomState(0)
    steps = [paddle.to_tensor(rng.randn(B, 1, d).astype("float32"))
             for _ in range(4)]

    # growing cache
    g_caches = [(paddle.zeros([B, 0, nh, d // nh]),
                 paddle.zeros([B, 0, nh, d // nh])) for _ in range(nl)]
    g_outs = []
    for x in steps:
        o, g_caches = m(x, caches=g_caches)
        g_outs.append(o.numpy())

    # static buffers
    s_caches = [(paddle.zeros([B, L, nh, d // nh]),
                 paddle.zeros([B, L, nh, d // nh]),
                 paddle.to_tensor(np.int32(0))) for _ in range(nl)]
    for i, x in enumerate(steps):
        o, s_caches = m(x, caches=s_caches)
        np.testing.assert_allclose(o.numpy(), g_outs[i], rtol=1e-5,
                                   atol=1e-5, err_msg=f"step {i}")
    assert int(s_caches[0][2].numpy()) == len(steps)

    # multi-token PREFILL through the static path is causal per row —
    # must equal token-by-token growing decode (the growing path applies
    # no intra-step mask for s>1, so it is NOT the comparison point)
    x4 = paddle.to_tensor(rng.randn(B, 4, d).astype("float32"))
    p_caches = [(paddle.zeros([B, L, nh, d // nh]),
                 paddle.zeros([B, L, nh, d // nh]),
                 paddle.to_tensor(np.int32(0))) for _ in range(nl)]
    o4, p_caches = m(x4, caches=p_caches)
    gg = [(paddle.zeros([B, 0, nh, d // nh]),
           paddle.zeros([B, 0, nh, d // nh])) for _ in range(nl)]
    per_tok = []
    for t in range(4):
        o1, gg = m(x4[:, t:t + 1], caches=gg)
        per_tok.append(o1.numpy())
    np.testing.assert_allclose(o4.numpy(), np.concatenate(per_tok, axis=1),
                               rtol=1e-5, atol=1e-5)
    assert int(p_caches[0][2].numpy()) == 4

    # eager overflow raises instead of silently clamping
    tiny = [(paddle.zeros([B, 2, nh, d // nh]),
             paddle.zeros([B, 2, nh, d // nh]),
             paddle.to_tensor(np.int32(0))) for _ in range(nl)]
    import pytest as _pytest
    with _pytest.raises(ValueError, match="overflow"):
        m(x4, caches=tiny)


def test_moe_gather_dispatch_matches_einsum(monkeypatch):
    """The r4 index-gather dispatch must compute EXACTLY the one-hot
    einsum dispatch (same GShard assignment, same drops), fwd and grads."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.incubate.distributed.models.moe.moe_layer import (
        _moe_forward)

    rng = np.random.RandomState(0)
    B, S, M, H, E = 2, 16, 8, 16, 4
    x = jnp.asarray(rng.randn(B, S, M).astype(np.float32)) * 0.5
    gw = jnp.asarray(rng.randn(M, E).astype(np.float32))
    w1 = jnp.asarray(rng.randn(E, M, H).astype(np.float32)) * 0.1
    b1 = jnp.asarray(rng.randn(E, H).astype(np.float32)) * 0.1
    w2 = jnp.asarray(rng.randn(E, H, M).astype(np.float32)) * 0.1
    b2 = jnp.asarray(rng.randn(E, M).astype(np.float32)) * 0.1

    def run(mode, top_k, gate):
        monkeypatch.setenv("PADDLE_TPU_MOE_GATHER", mode)

        def f(x_, w1_, w2_):
            y, aux = _moe_forward(x_, gw, w1_, b1, w2_, b2, top_k=top_k,
                                  capacity_factor=1.25, gate_type=gate,
                                  activation=jax.nn.gelu)
            return jnp.sum(y ** 2) + aux, (y, aux)

        (loss, (y, aux)), grads = jax.value_and_grad(
            f, argnums=(0, 1, 2), has_aux=True)(x, w1, w2)
        return y, aux, grads

    for top_k, gate in [(2, "gshard"), (1, "switch"), (2, "naive")]:
        y_g, aux_g, g_g = run("1", top_k, gate)
        y_e, aux_e, g_e = run("0", top_k, gate)
        np.testing.assert_allclose(np.asarray(y_g), np.asarray(y_e),
                                   rtol=1e-5, atol=1e-5,
                                   err_msg=f"{gate} top{top_k} fwd")
        np.testing.assert_allclose(float(aux_g), float(aux_e), rtol=1e-6)
        for a, b_ in zip(g_g, g_e):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=f"{gate} top{top_k} grad")
