"""Numerics observability (paddle_tpu.debugging): in-graph sentinels,
anomaly detection, dump/replay, GradScaler-under-jit, facades, tier guard.

Reference surfaces: FLAGS_check_nan_inf / eager nan_inf_utils.cc scans,
paddle.amp.debugging.{check_numerics, check_layer_numerics,
TensorCheckerConfig}, update_loss_scaling_op — all reimplemented to work
INSIDE a compiled TrainStep (SURVEY §5.2)."""
import importlib.util
import json
import os
import shutil

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import debugging
from paddle_tpu.amp import GradScaler
from paddle_tpu.jit.train_step import TrainStep

TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.act = nn.ReLU()
        self.fc2 = nn.Linear(16, 1)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


def _replay_factory():
    """Model+loss factory for tools/replay_dump.py (imported by name)."""
    paddle.seed(0)
    net = Net()
    return net, (lambda x, y: nn.MSELoss()(net(x), y))


def _batch(rng=None, n=4):
    rng = rng or np.random.RandomState(0)
    return (paddle.to_tensor(rng.randn(n, 8).astype("float32")),
            paddle.to_tensor(rng.randn(n, 1).astype("float32")))


# ---------------------------------------------------------------- sentinel

class TestSentinel:
    def test_array_stats_matches_numpy(self):
        a = np.array([[1.0, -3.0, np.nan], [np.inf, 0.5, -np.inf]],
                     np.float32)
        row = np.asarray(debugging.array_stats(jnp.asarray(a)))
        finite = a[np.isfinite(a)]
        assert row[0] == finite.size
        assert row[1] == 1 and row[2] == 2
        np.testing.assert_allclose(row[3], np.abs(finite).max(), rtol=1e-6)
        np.testing.assert_allclose(row[4], finite.mean(), rtol=1e-6)
        np.testing.assert_allclose(row[5], np.sqrt((finite ** 2).sum()),
                                   rtol=1e-6)

    def test_merge_rows_equals_stats_of_concat(self):
        rng = np.random.RandomState(1)
        a = rng.randn(13).astype(np.float32)
        b = rng.randn(7).astype(np.float32) * 10
        merged = np.asarray(debugging.merge_stat_rows(
            [debugging.array_stats(jnp.asarray(a)),
             debugging.array_stats(jnp.asarray(b))]))
        whole = np.asarray(debugging.array_stats(
            jnp.asarray(np.concatenate([a, b]))))
        np.testing.assert_allclose(merged, whole, rtol=1e-5, atol=1e-6)

    def test_eager_collection_parity_with_numpy(self):
        paddle.seed(0)
        net = Net()
        h = debugging.check_layer_numerics(net)
        x, _ = _batch()
        with debugging.collect_stats() as col:
            y = net(x)
        tree = col.tree()
        h.remove()
        # fc1 row must equal numpy stats of x @ W1 + b1
        z = np.asarray(x._data) @ np.asarray(net.fc1.weight._data) \
            + np.asarray(net.fc1.bias._data)
        r = tree.row("Net/fc1")
        assert r["finite"] == z.size and r["nan"] == 0 and r["inf"] == 0
        np.testing.assert_allclose(r["absmax"], np.abs(z).max(), rtol=1e-5)
        np.testing.assert_allclose(r["mean"], z.mean(), rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(r["l2"], np.sqrt((z ** 2).sum()),
                                   rtol=1e-5)
        # root row == stats of the model output
        np.testing.assert_allclose(
            tree.row("Net")["l2"],
            np.sqrt((np.asarray(y._data) ** 2).sum()), rtol=1e-5)
        # removal: no rows recorded afterwards
        with debugging.collect_stats() as col2:
            net(x)
        assert col2.tree() is None

    def test_instrumentation_idempotent(self):
        net = Net()
        h1 = debugging.check_layer_numerics(net)
        h2 = debugging.check_layer_numerics(net)   # second install: no-op
        assert h2.paths == []
        x, _ = _batch()
        with debugging.collect_stats() as col:
            net(x)
        assert len(col.paths) == len(set(col.paths))  # no duplicate rows
        h1.remove()


# ---------------------------------------------------------------- TrainStep

class TestTrainStepNumerics:
    def test_stats_tree_parity_and_lazy_fetch(self):
        paddle.seed(0)
        net = Net()
        w1 = np.asarray(net.fc1.weight._data).copy()
        b1 = np.asarray(net.fc1.bias._data).copy()
        opt = paddle.optimizer.SGD(learning_rate=0.0,
                                   parameters=net.parameters())
        cfg = debugging.NumericsConfig(every_n_steps=0)   # manual fetch only
        step = TrainStep(net, opt, lambda x, y: nn.MSELoss()(net(x), y),
                         numerics=cfg)
        x, y = _batch()
        step(x, y)
        # not fetching: the aux stays a device array and no detector ran —
        # the "zero per-step host syncs" contract
        assert isinstance(step._last_aux["stats"], jax.Array)
        assert cfg.detector.events == []
        tree = step.numerics_stats()
        # lr=0: the traced forward used exactly the initial params
        z = np.asarray(x._data) @ w1 + b1
        r = tree.row("Net/fc1")
        np.testing.assert_allclose(r["absmax"], np.abs(z).max(), rtol=1e-5)
        np.testing.assert_allclose(r["l2"], np.sqrt((z ** 2).sum()),
                                   rtol=1e-5)
        # grad rows exist and the global grad norm is finite
        assert any(p.startswith("grad:") for p in tree.paths)
        assert np.isfinite(float(np.asarray(step._last_aux["grad_norm"])))

    def test_injected_nan_names_layer_dumps_and_replays(self, tmp_path):
        dump_dir = str(tmp_path / "dumps")
        paddle.seed(0)
        net = Net()
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        cfg = debugging.NumericsConfig(every_n_steps=1, dump_dir=dump_dir)
        step = TrainStep(net, opt, lambda x, y: nn.MSELoss()(net(x), y),
                         numerics=cfg)
        x, y = _batch()
        step(x, y)
        assert cfg.detector.events == []
        # poison fc1: the sentinel must name THAT layer first
        w = np.asarray(net.fc1.weight._data).copy()
        w[0, 0] = np.nan
        net.fc1.weight._data = jnp.asarray(w)
        net.fc1.weight._node = None
        step(x, y)
        kinds = [(e.kind, e.path) for e in cfg.detector.events]
        assert kinds[0] == ("nan", "Net/fc1")
        assert ("nan", "grad:Net/fc1") in kinds
        # skip_nonfinite_updates held: params did NOT ingest the NaN'd grads
        w_after = np.asarray(net.fc1.weight._data)
        assert np.isnan(w_after[0, 0])          # the injected one persists
        assert np.isfinite(w_after[1:]).all()   # but the update was skipped
        # dump written with pre-step state; replay reproduces the same rows
        dumps = os.listdir(dump_dir)
        assert len(dumps) == 1 and dumps[0].startswith("step2_nan")
        d = debugging.load_dump(os.path.join(dump_dir, dumps[0]))
        assert np.isnan(d.params["fc1.weight"][0, 0])
        net2, loss2 = _replay_factory()
        res = debugging.replay(d, net2, loss2)
        assert res.matches is True
        bad = [p for p, _ in res.stats.nonfinite_rows()]
        assert "Net/fc1" in bad and "grad:Net/fc1" in bad
        assert not np.isfinite(res.loss)

    def test_replay_cli(self, tmp_path):
        dump_dir = str(tmp_path / "dumps")
        paddle.seed(0)
        net = Net()
        opt = paddle.optimizer.SGD(learning_rate=1e-2,
                                   parameters=net.parameters())
        cfg = debugging.NumericsConfig(every_n_steps=1, dump_dir=dump_dir)
        step = TrainStep(net, opt, lambda x, y: nn.MSELoss()(net(x), y),
                         numerics=cfg)
        w = np.asarray(net.fc2.weight._data).copy()
        w[0, 0] = np.inf
        net.fc2.weight._data = jnp.asarray(w)
        net.fc2.weight._node = None
        step(*_batch())
        dump_path = os.path.join(dump_dir, os.listdir(dump_dir)[0])
        spec = importlib.util.spec_from_file_location(
            "replay_dump", os.path.join(TOOLS, "replay_dump.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main([dump_path, "--model",
                       "test_numerics_debug:_replay_factory", "--json"])
        assert rc == 0

    def test_run_steps_carries_stats(self):
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        step = TrainStep(net, opt, lambda x, y: nn.MSELoss()(net(x), y),
                         numerics=debugging.NumericsConfig())
        rng = np.random.RandomState(0)
        xs = paddle.to_tensor(rng.randn(3, 8, 4).astype("float32"))
        ys = paddle.to_tensor(rng.randn(3, 8, 2).astype("float32"))
        losses = step.run_steps(3, xs, ys)
        assert losses.shape == [3]
        tree = step.numerics_stats()
        assert tree is not None and "Linear" in tree.paths
        assert tree.row("Linear")["nan"] == 0

    def test_grad_accum_merges_micro_stats(self):
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.0,
                                   parameters=net.parameters())
        step = TrainStep(net, opt, lambda x, y: nn.MSELoss()(net(x), y),
                         numerics=debugging.NumericsConfig(),
                         grad_accum_steps=2)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(8, 4).astype("float32"))
        y = paddle.to_tensor(rng.randn(8, 2).astype("float32"))
        step(x, y)
        tree = step.numerics_stats()
        r = tree.row("Linear")
        # both microbatches' outputs counted: 8*2 elements total
        assert r["finite"] == 16
        z = np.asarray(x._data) @ np.asarray(net.weight._data) \
            + np.asarray(net.bias._data)
        np.testing.assert_allclose(r["l2"], np.sqrt((z ** 2).sum()),
                                   rtol=1e-5)

    def test_no_host_transfers_in_compiled_step(self):
        """The 'zero per-step host syncs' contract, verified on the lowered
        HLO: enabling numerics adds the stats array to the step's RESULTS
        (fetched lazily by the host) but no outfeed/host custom-calls into
        the program body."""
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        step = TrainStep(net, opt, lambda x, y: nn.MSELoss()(net(x), y),
                         numerics=debugging.NumericsConfig())
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(4, 4).astype(np.float32))
        y = jnp.asarray(rng.randn(4, 2).astype(np.float32))
        flat, treedef = jax.tree.flatten((x, y))
        pure = step._build_pure(treedef)
        if step._opt_state is None:
            step._opt_state = step._init_opt_state()
        key = jax.random.PRNGKey(0)
        hlo = jax.jit(pure).lower(
            tuple(p._data for p in step._params), tuple(step._opt_state),
            None, jnp.int32(1), jnp.float32(0.1), key, x, y).as_text()
        for marker in ("outfeed", "infeed", "send", "recv",
                       "host_callback", "io_callback"):
            assert marker not in hlo.lower(), f"host transfer: {marker}"

    def test_raise_on_nonfinite(self):
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=1e-2,
                                   parameters=net.parameters())
        cfg = debugging.NumericsConfig(every_n_steps=1,
                                       raise_on_nonfinite=True)
        step = TrainStep(net, opt, lambda x, y: nn.MSELoss()(net(x), y),
                         numerics=cfg)
        rng = np.random.RandomState(0)
        x = rng.randn(4, 4).astype("float32")
        x[0, 0] = np.nan
        with pytest.raises(FloatingPointError, match="Linear"):
            step(paddle.to_tensor(x),
                 paddle.to_tensor(rng.randn(4, 2).astype("float32")))


# ---------------------------------------------------------------- detector

class TestAnomalyDetector:
    def test_grad_explosion_zscore(self):
        det = debugging.AnomalyDetector(grad_z=4.0, min_history=5)
        for i in range(8):
            assert det.observe(i, grad_norm=1.0 + 0.01 * i) == []
        evs = det.observe(9, grad_norm=100.0)
        assert [e.kind for e in evs] == ["grad_explosion"]
        assert evs[0].details["zscore"] > 4.0

    def test_loss_spike_and_nonfinite_loss(self):
        det = debugging.AnomalyDetector(loss_z=4.0, min_history=4)
        for i in range(6):
            assert det.observe(i, loss=2.0 - 0.1 * i) == []
        assert [e.kind for e in det.observe(7, loss=50.0)] == ["loss_spike"]
        det2 = debugging.AnomalyDetector()
        evs = det2.observe(0, loss=float("nan"))
        assert evs and evs[0].kind == "loss_spike"

    def test_dead_layer_fires_once(self):
        det = debugging.AnomalyDetector(dead_absmax=1e-8)
        dead = debugging.StatsTree(
            ["M/a", "grad:M/a"],
            np.array([[10, 0, 0, 0.0, 0.0, 0.0],
                      [10, 0, 0, 0.0, 0.0, 0.0]], np.float32))
        evs = det.observe(1, tree=dead)
        # grad rows are exempt from dead-layer (zero grads are normal)
        assert [(e.kind, e.path) for e in evs] == [("dead_layer", "M/a")]
        assert det.observe(2, tree=dead) == []     # fires once
        alive = debugging.StatsTree(
            ["M/a", "grad:M/a"],
            np.array([[10, 0, 0, 1.0, 0.1, 1.0],
                      [10, 0, 0, 1.0, 0.1, 1.0]], np.float32))
        assert det.observe(3, tree=alive) == []
        assert [e.kind for e in det.observe(4, tree=dead)] == ["dead_layer"]

    def test_monitor_records_numerics(self, tmp_path):
        from paddle_tpu.profiler import StepMonitor
        jsonl = str(tmp_path / "m.jsonl")
        mon = StepMonitor(jsonl_path=jsonl)
        ev = debugging.NumericsEvent("nan", 7, path="M/a", message="boom")
        mon.record_numerics(step=7, loss=1.5, grad_norm=2.5, events=[ev])
        assert len(mon.numerics_events) == 1
        rows = [json.loads(l) for l in open(jsonl)]
        assert rows[0]["numerics"]["loss"] == 1.5
        assert rows[0]["numerics"]["events"][0]["kind"] == "nan"
        txt = mon.metrics_text()
        assert "numerics_events_total 1" in txt
        assert "paddle_tpu_grad_norm 2.5" in txt


# ---------------------------------------------------------------- GradScaler

class TestGradScalerJit:
    def _data(self):
        rng = np.random.RandomState(0)
        xs = [rng.randn(8, 4).astype("float32") for _ in range(6)]
        ys = [rng.randn(8, 2).astype("float32") for _ in range(6)]
        xs[2][0, 0] = np.inf    # force one overflow step
        return xs, ys

    def _scaler(self):
        return GradScaler(init_loss_scaling=2.0 ** 8, incr_every_n_steps=3,
                          decr_every_n_nan_or_inf=1)

    def test_trajectory_parity_eager_vs_jit(self):
        xs, ys = self._data()

        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        sc = self._scaler()
        eager_scales = []
        for x, y in zip(xs, ys):
            loss = nn.MSELoss()(net(paddle.to_tensor(x)), paddle.to_tensor(y))
            sc.scale(loss).backward()
            sc.step(opt)
            sc.update()
            opt.clear_grad()
            eager_scales.append(sc.get_loss_scaling())

        paddle.seed(0)
        net2 = nn.Linear(4, 2)
        opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=net2.parameters())
        sc2 = self._scaler()
        step = TrainStep(net2, opt2,
                         lambda x, y: nn.MSELoss()(net2(x), y), scaler=sc2)
        jit_scales = []
        for x, y in zip(xs, ys):
            step(paddle.to_tensor(x), paddle.to_tensor(y))
            jit_scales.append(sc2.get_loss_scaling())

        # the decrease at the overflow step and the increase after
        # incr_every_n good steps land identically
        assert jit_scales == eager_scales
        assert 128.0 in jit_scales and 256.0 in jit_scales
        np.testing.assert_allclose(np.asarray(net.weight._data),
                                   np.asarray(net2.weight._data),
                                   rtol=1e-5, atol=1e-6)

    def test_eager_unscale_is_one_fused_reduction(self):
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        sc = GradScaler(init_loss_scaling=4.0)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(4, 4).astype("float32"))
        loss = nn.MSELoss()(net(x), paddle.to_tensor(
            np.zeros((4, 2), np.float32)))
        sc.scale(loss).backward()
        sc.unscale_(opt)
        # the sentinel is a DEVICE scalar until someone reads it
        assert isinstance(sc._found_inf_arr, jax.Array)
        assert sc._found_inf is False
        sc.update()
        assert sc.get_loss_scaling() == 4.0   # good step, no change yet


# ---------------------------------------------------------------- facades

class TestAmpDebuggingFacade:
    def test_check_numerics_counts(self):
        from paddle_tpu.amp import debugging as amp_dbg
        t = paddle.to_tensor(np.array([1.0, np.nan, np.inf, np.nan],
                                      np.float32))
        with pytest.raises(FloatingPointError, match="2 NaN and 1 Inf"):
            amp_dbg.check_numerics(t, "relu", "out")
        clean = paddle.to_tensor(np.ones((3,), np.float32))
        assert amp_dbg.check_numerics(clean) is clean
        ints = paddle.to_tensor(np.arange(3, dtype=np.int32))
        assert amp_dbg.check_numerics(ints) is ints

    def test_tensor_checker_config_maps_to_numerics(self):
        from paddle_tpu.amp import debugging as amp_dbg
        cfg = amp_dbg.TensorCheckerConfig(
            enable=True, debug_mode=amp_dbg.DebugMode.CHECK_NAN_INF_AND_ABORT,
            output_dir="/tmp/x")
        ncfg = cfg.to_numerics_config()
        assert isinstance(ncfg, debugging.NumericsConfig)
        assert ncfg.raise_on_nonfinite and ncfg.dump_dir == "/tmp/x"
        assert amp_dbg.TensorCheckerConfig(enable=False) \
            .to_numerics_config() is None

    def test_enable_tensor_checker_flags(self):
        from paddle_tpu.amp import debugging as amp_dbg
        from paddle_tpu.core import flags
        cfg = amp_dbg.TensorCheckerConfig(enable=True)
        amp_dbg.enable_tensor_checker(cfg)
        try:
            assert flags.get_flags("FLAGS_check_nan_inf")[
                "FLAGS_check_nan_inf"] is True
            assert amp_dbg.get_tensor_checker_config() is cfg
        finally:
            amp_dbg.disable_tensor_checker()
        assert amp_dbg.get_tensor_checker_config() is None

    def test_check_layer_numerics_alias(self):
        from paddle_tpu.amp import debugging as amp_dbg
        net = Net()
        h = amp_dbg.check_layer_numerics(net)
        assert "Net/fc1" in h.paths
        h.remove()


# ---------------------------------------------------------------- callback

class TestNumericsCallback:
    def test_eager_regime_detects_poisoned_params(self):
        from paddle_tpu.hapi.callbacks import NumericsCallback
        paddle.seed(0)
        net = nn.Linear(4, 2)
        model = paddle.Model(net)
        model.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=0.1, parameters=net.parameters()),
            loss=nn.MSELoss(), use_fused_step=False)
        cb = NumericsCallback(every_n_steps=1)
        cb.set_model(model)
        cb.on_train_batch_end(0, {"loss": 1.0})
        assert cb.events == []
        w = np.asarray(net.weight._data).copy()
        w[0, 0] = np.nan
        net.weight._data = jnp.asarray(w)
        net.weight._node = None
        cb.on_train_batch_end(1, {"loss": 1.0})
        assert any(e.kind == "nan" and "Linear" in (e.path or "")
                   for e in cb.events)

    def test_fused_regime_attaches_to_trainstep(self):
        from paddle_tpu.hapi.callbacks import NumericsCallback
        paddle.seed(0)
        net = nn.Linear(4, 2)
        model = paddle.Model(net)
        model.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=0.1, parameters=net.parameters()),
            loss=nn.MSELoss())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 4).astype("float32"))
        y = paddle.to_tensor(rng.randn(4, 2).astype("float32"))
        cb = NumericsCallback(every_n_steps=1)
        cb.set_model(model)
        model.train_batch([x], y)            # builds the fused TrainStep
        cb.on_train_batch_end(0, {"loss": 1.0})
        ts = model._fused_step
        assert ts is not None and ts._numerics is cb.numerics
        model.train_batch([x], y)            # recompiles with stats outputs
        assert ts.numerics_stats() is not None


# ---------------------------------------------------------------- dump bits

class TestDumpFormat:
    def test_tree_spec_roundtrip(self):
        from paddle_tpu.debugging import tree_spec, tree_build
        obj = ({"b": 1, "a": (2, [3, None])}, 4)
        leaves, _ = jax.tree.flatten(obj)
        rebuilt = tree_build(tree_spec(obj), list(leaves))
        assert rebuilt == ({"a": (2, [3, None]), "b": 1}, 4)


# ---------------------------------------------------------------- tier guard

class TestCheckTiers:
    def _mod(self):
        spec = importlib.util.spec_from_file_location(
            "check_tiers", os.path.join(TOOLS, "check_tiers.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_flags_unmarked_slow_and_budget(self, tmp_path):
        ct = self._mod()
        f = tmp_path / "dur.jsonl"
        rows = [
            {"nodeid": "t.py::fast", "duration": 1.0, "markers": []},
            {"nodeid": "t.py::big_unmarked", "duration": 120.0,
             "markers": ["heavy"]},
            {"nodeid": "t.py::big_marked", "duration": 500.0,
             "markers": ["slow"]},
        ]
        f.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        res = ct.check(ct.load_records([str(f)]), budget=780.0,
                       slow_threshold=60.0)
        assert not res["ok"]
        assert [r["nodeid"] for r in res["unmarked_slow"]] == \
            ["t.py::big_unmarked"]
        # slow-marked tests are excluded from the tier-1 sum
        assert res["tier1_total_s"] == 121.0
        assert ct.main([str(f)]) == 1

    def test_budget_overflow_and_merge(self, tmp_path):
        ct = self._mod()
        f1, f2 = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        f1.write_text(json.dumps(
            {"nodeid": "t.py::x", "duration": 10.0, "markers": []}) + "\n")
        f2.write_text(json.dumps(
            {"nodeid": "t.py::x", "duration": 30.0, "markers": []}) + "\n")
        recs = ct.load_records([str(f1), str(f2)])
        assert recs[0]["duration"] == 30.0       # max across runs
        res = ct.check(recs, budget=20.0, slow_threshold=60.0)
        assert res["over_budget"] and not res["ok"]
        ok = ct.check(recs, budget=40.0, slow_threshold=60.0)
        assert ok["ok"]

    @pytest.mark.slow
    def test_conftest_records_durations(self, tmp_path):
        """The recording hook end-to-end: run one trivial test under the
        env var (tests/conftest.py loaded via PYTEST_PLUGINS) and feed the
        ledger to the checker."""
        import subprocess
        import sys
        dur = tmp_path / "d.jsonl"
        test = tmp_path / "test_tiny.py"
        test.write_text("def test_ok():\n    assert True\n")
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = dict(os.environ, PADDLE_TPU_TIER_DURATIONS=str(dur),
                   JAX_PLATFORMS="cpu", PYTEST_PLUGINS="conftest",
                   PYTHONPATH=repo_root + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        r = subprocess.run(
            [sys.executable, "-m", "pytest", str(test), "-q", "-p",
             "no:cacheprovider"],
            cwd=os.path.dirname(__file__), env=env, capture_output=True,
            text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        rows = [json.loads(l) for l in open(dur)]
        assert rows and rows[0]["nodeid"].endswith("test_ok")
        ct = self._mod()
        assert ct.main([str(dur)]) == 0
