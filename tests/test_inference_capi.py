"""C inference ABI (VERDICT r1 missing #5): a plain-C program linked
against libptinfer.so loads a jit.save StableHLO artifact and runs it —
the reference's capi_exp capability (pd_inference_api.h) for non-Python
serving stacks."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io.native import build_infer_capi

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def exported_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("capi")
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    path = str(d / "m")
    paddle.jit.save(model, path,
                    input_spec=[paddle.jit.InputSpec([2, 4], "float32")])
    assert os.path.exists(path + ".pdmodel")
    # expected output for ones input, via the python predictor
    from paddle_tpu import inference
    cfg = inference.Config(path, "")
    pred = inference.create_predictor(cfg)
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(np.ones((2, 4), np.float32))
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    return path, out


def test_c_program_runs_exported_model(capi_exe, exported_model):
    path, want = exported_model
    r = subprocess.run([capi_exe, path], capture_output=True, text=True,
                       timeout=300, env=_c_env(), cwd=REPO)
    assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
    first = float(r.stdout.split("first=")[1])
    np.testing.assert_allclose(first, float(want.reshape(-1)[0]), rtol=1e-5)


@pytest.fixture(scope="module")
def capi_exe(tmp_path_factory):
    lib = build_infer_capi()
    if lib is None:
        pytest.skip("no native toolchain / libpython")
    exe = str(tmp_path_factory.mktemp("capi_bin") / "test_capi")
    src = os.path.join(REPO, "native", "tests", "test_capi.c")
    inc = os.path.join(REPO, "native", "include")
    r = subprocess.run(["gcc", "-O2", src, f"-I{inc}", lib, "-o", exe],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return exe


def _c_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON_")):
            env.pop(k)
    return env


def test_c_error_paths(capi_exe, exported_model):
    """VERDICT r2 #10: missing artifact, unknown handle names, undersized
    output buffer, NULL destroys — every failure must be soft (NULL/0
    return), leave the interpreter unpoisoned, and the predictor must still
    work afterwards."""
    path, want = exported_model
    r = subprocess.run([capi_exe, path, "errors"], capture_output=True,
                       text=True, timeout=300, env=_c_env(), cwd=REPO)
    assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
    first = float(r.stdout.split("first=")[1])
    np.testing.assert_allclose(first, float(want.reshape(-1)[0]), rtol=1e-5)


@pytest.fixture(scope="module")
def exported_multiio(tmp_path_factory):
    d = tmp_path_factory.mktemp("capi_mio")
    paddle.seed(1)

    class TwoIO(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(4, 3)
            self.l2 = nn.Linear(5, 2)

        def forward(self, a, b):
            return self.l1(a), self.l2(b)

    m = TwoIO()
    path = str(d / "mio")
    paddle.jit.save(m, path, input_spec=[
        paddle.jit.InputSpec([2, 4], "float32", name="a"),
        paddle.jit.InputSpec([2, 5], "float32", name="b")])
    from paddle_tpu import inference
    pred = inference.create_predictor(inference.Config(path, ""))
    names = pred.get_input_names()
    pred.get_input_handle(names[0]).copy_from_cpu(
        np.full((2, 4), 1.0, np.float32))
    pred.get_input_handle(names[1]).copy_from_cpu(
        np.full((2, 5), 2.0, np.float32))
    pred.run()
    sums = [float(pred.get_output_handle(n).copy_to_cpu().sum())
            for n in pred.get_output_names()]
    return path, sums


def test_c_multi_input_output(capi_exe, exported_multiio):
    """Two named inputs, two outputs through the C surface; sums match the
    python predictor (reference: capi_exp multi-io contract)."""
    path, want = exported_multiio
    r = subprocess.run([capi_exe, path, "multiio"], capture_output=True,
                       text=True, timeout=300, env=_c_env(), cwd=REPO)
    assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
    got0 = float(r.stdout.split("sum0=")[1].split()[0])
    got1 = float(r.stdout.split("sum1=")[1].split()[0])
    np.testing.assert_allclose([got0, got1], want, rtol=1e-4)


def test_c_runs_int8_payload_artifact(capi_exe, tmp_path):
    """Weight-only-int8 export (quantization.save_quantized): the C ABI
    serves the artifact, and the int8 payload rides alongside (codes
    verified int8 on disk)."""
    import paddle_tpu.quantization as Q
    paddle.seed(2)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    ptq = Q.PTQ()
    m = ptq.quantize(m)
    rng = np.random.RandomState(3)
    for _ in range(4):   # calibration passes
        m(paddle.to_tensor(rng.randn(4, 8).astype("float32")))
    path = str(tmp_path / "qm")
    Q.save_quantized(m, path, input_spec=[
        paddle.jit.InputSpec([2, 8], "float32")])
    payload = np.load(path + ".pdquant.npz")
    code_keys = [k for k in payload.files if k.endswith("/codes")]
    assert code_keys and all(payload[k].dtype == np.int8 for k in code_keys)

    from paddle_tpu import inference
    pred = inference.create_predictor(inference.Config(path, ""))
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(np.ones((2, 8), np.float32))
    pred.run()
    want = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()

    r = subprocess.run([capi_exe, path], capture_output=True, text=True,
                       timeout=300, env=_c_env(), cwd=REPO)
    assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
    first = float(r.stdout.split("first=")[1])
    np.testing.assert_allclose(first, float(want.reshape(-1)[0]), rtol=1e-5)
