"""C inference ABI (VERDICT r1 missing #5): a plain-C program linked
against libptinfer.so loads a jit.save StableHLO artifact and runs it —
the reference's capi_exp capability (pd_inference_api.h) for non-Python
serving stacks."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io.native import build_infer_capi

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def exported_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("capi")
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    path = str(d / "m")
    paddle.jit.save(model, path,
                    input_spec=[paddle.jit.InputSpec([2, 4], "float32")])
    assert os.path.exists(path + ".pdmodel")
    # expected output for ones input, via the python predictor
    from paddle_tpu import inference
    cfg = inference.Config(path, "")
    pred = inference.create_predictor(cfg)
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(np.ones((2, 4), np.float32))
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    return path, out


def test_c_program_runs_exported_model(exported_model, tmp_path):
    lib = build_infer_capi()
    if lib is None:
        pytest.skip("no native toolchain / libpython")
    path, want = exported_model
    exe = str(tmp_path / "test_capi")
    src = os.path.join(REPO, "native", "tests", "test_capi.c")
    inc = os.path.join(REPO, "native", "include")
    r = subprocess.run(
        ["gcc", "-O2", src, f"-I{inc}", lib, "-o", exe],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON_")):
            env.pop(k)   # embedded interpreter must not claim the real chip
    r = subprocess.run([exe, path], capture_output=True, text=True,
                       timeout=300, env=env, cwd=REPO)
    assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
    first = float(r.stdout.split("first=")[1])
    np.testing.assert_allclose(first, float(want.reshape(-1)[0]), rtol=1e-5)
