"""Inference predictor + profiler tests (SURVEY §2.4 / §5.1 parity)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static, inference, profiler
import paddle_tpu.nn as nn


def _export_model(tmp_path):
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [-1, 8], "float32")
            y = static.nn.fc(x, 4)
        exe = static.Executor()
        prefix = str(tmp_path / "model")
        static.save_inference_model(prefix, [x], [y], exe, program=main)
        w, b = main.all_parameters()[:2]
        return prefix, np.asarray(w._data), np.asarray(b._data)
    finally:
        paddle.disable_static()


def test_predictor_named_handles(tmp_path):
    prefix, w, b = _export_model(tmp_path)
    config = inference.Config(prefix)
    config.disable_gpu()
    predictor = inference.create_predictor(config)
    assert predictor.get_input_names() == ["x"]
    xv = np.random.randn(3, 8).astype(np.float32)
    h = predictor.get_input_handle("x")
    h.copy_from_cpu(xv)
    assert predictor.run()
    out = predictor.get_output_handle(predictor.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, xv @ w + b, rtol=1e-4, atol=1e-5)


def test_predictor_symbolic_batch(tmp_path):
    prefix, w, b = _export_model(tmp_path)
    predictor = inference.create_predictor(inference.Config(prefix))
    for bs in (1, 7):
        xv = np.random.randn(bs, 8).astype(np.float32)
        (out,) = predictor.run([xv])
        np.testing.assert_allclose(out, xv @ w + b, rtol=1e-4, atol=1e-5)


def test_predictor_clone_independent_buffers(tmp_path):
    prefix, w, b = _export_model(tmp_path)
    p1 = inference.create_predictor(inference.Config(prefix))
    p2 = p1.clone()
    x1 = np.ones((2, 8), np.float32)
    x2 = np.zeros((2, 8), np.float32)
    (o1,) = p1.run([x1])
    (o2,) = p2.run([x2])
    np.testing.assert_allclose(o1, x1 @ w + b, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(o2, np.tile(b, (2, 1)), rtol=1e-4, atol=1e-5)


def test_jit_save_aot_artifact(tmp_path):
    model = nn.Sequential(nn.Linear(6, 3), nn.Tanh())
    prefix = str(tmp_path / "jit_model")
    paddle.jit.save(model, prefix,
                    input_spec=[paddle.jit.InputSpec([2, 6], "float32")])
    predictor = inference.create_predictor(inference.Config(prefix))
    xv = np.random.randn(2, 6).astype(np.float32)
    (out,) = predictor.run([xv])
    model.eval()
    want = model(paddle.to_tensor(xv)).numpy()
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_scheduler_state_machine():
    sched = profiler.make_scheduler(closed=1, ready=1, record=2, repeat=1,
                                    skip_first=1)
    states = [sched(i) for i in range(6)]
    S = profiler.ProfilerState
    assert states == [S.CLOSED, S.CLOSED, S.READY, S.RECORD,
                      S.RECORD_AND_RETURN, S.CLOSED]


def test_profiler_records_and_exports(tmp_path):
    done = {}

    def ready(prof):
        done["summary"] = prof.summary()
        profiler.export_chrome_tracing(str(tmp_path))(prof)

    p = profiler.Profiler(scheduler=profiler.make_scheduler(
        closed=0, ready=0, record=2, repeat=1), on_trace_ready=ready,
        timer_only=True)
    p.start()
    for _ in range(2):
        with profiler.RecordEvent("my_step"):
            _ = paddle.to_tensor(np.ones(4)) * 2
        p.step()
    p.stop()
    assert "my_step" in done["summary"]
    assert p._last_export is not None
    import json
    with open(p._last_export) as f:
        trace = json.load(f)
    assert any(e["name"] == "my_step" for e in trace["traceEvents"])
