"""Reference .pdparams/.pdopt wire-format interop (VERDICT r3 missing #3).

Fixtures are constructed by replicating the REFERENCE pickle layout
byte-for-byte from its save code path (python/paddle/framework/io.py:637 →
_build_saved_state_dict io.py:59; fluid/io.py:1845 big-param splitting) —
raw numpy values, "StructuredToParameterName@@" name table, protocol-2
"key@@.N" slices — then loaded through paddle_tpu.load into real models.
The reverse direction asserts our save() output parses as exactly that
layout with plain pickle + numpy (what reference paddle.load would see).
"""
import math
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _reference_layout_pdparams(state, protocol=4, split_threshold=None):
    """Byte-layout twin of reference save(): raw ndarrays + name table
    (+ optional big-param split as written by fluid/io.py:1845)."""
    save_dict = {k: np.asarray(v, np.float32) for k, v in state.items()}
    save_dict["StructuredToParameterName@@"] = {k: k for k in state}
    if split_threshold is not None:
        unpack = {}
        out = dict(save_dict)
        for k, v in save_dict.items():
            if not isinstance(v, np.ndarray) or v.size <= split_threshold:
                continue
            unpack[k] = {"OriginShape": v.shape, "slices": []}
            flat = v.flatten()
            out.pop(k)
            for i in range(int(math.ceil(v.size / split_threshold))):
                part = f"{k}@@.{i}"
                unpack[k]["slices"].append(part)
                out[part] = flat[i * split_threshold:(i + 1) * split_threshold]
        if unpack:
            out["UnpackBigParamInfor@@"] = unpack
        save_dict = out
    return pickle.dumps(save_dict, protocol=protocol)


def _mlp():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))


class TestLoadReferenceLayout:
    def test_load_reference_pdparams_into_model(self, tmp_path):
        src = _mlp()
        state = {k: v.numpy() for k, v in src.state_dict().items()}
        p = tmp_path / "ref.pdparams"
        p.write_bytes(_reference_layout_pdparams(state))

        loaded = paddle.load(str(p))
        assert "StructuredToParameterName@@" in loaded  # reference keeps it
        dst = _mlp()
        for param in dst.parameters():      # scramble
            param.set_value(np.zeros(param.shape, np.float32))
        missing, unexpected = dst.set_state_dict(loaded)
        assert missing == []
        assert unexpected == ["StructuredToParameterName@@"]
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(3, 4).astype(np.float32))
        np.testing.assert_allclose(dst(x).numpy(), src(x).numpy(),
                                   rtol=1e-6)

    def test_load_protocol2_split_big_param(self, tmp_path):
        # a param over the (scaled-down) slice threshold arrives as
        # key@@.0/key@@.1 + UnpackBigParamInfor@@ and must reassemble
        src = _mlp()
        state = {k: v.numpy() for k, v in src.state_dict().items()}
        p = tmp_path / "ref2.pdparams"
        p.write_bytes(_reference_layout_pdparams(state, protocol=2,
                                                 split_threshold=10))
        raw = pickle.loads(p.read_bytes())
        assert "UnpackBigParamInfor@@" in raw          # fixture really split
        assert any(k.endswith("@@.1") for k in raw)

        loaded = paddle.load(str(p))
        dst = _mlp()
        for param in dst.parameters():
            param.set_value(np.zeros(param.shape, np.float32))
        dst.set_state_dict(loaded)
        x = paddle.to_tensor(np.random.RandomState(1)
                             .randn(3, 4).astype(np.float32))
        np.testing.assert_allclose(dst(x).numpy(), src(x).numpy(),
                                   rtol=1e-6)

    def test_load_return_numpy(self, tmp_path):
        state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
        p = tmp_path / "w.pdparams"
        p.write_bytes(_reference_layout_pdparams(state))
        out = paddle.load(str(p), return_numpy=True)
        np.testing.assert_array_equal(out["w"], state["w"])


class TestSaveReferenceLayout:
    def test_save_emits_reference_layout(self, tmp_path):
        """Our .pdparams must parse with NOTHING but pickle+numpy into the
        reference structure: raw ndarrays + the name table, no wrappers."""
        m = _mlp()
        p = tmp_path / "ours.pdparams"
        paddle.save(m.state_dict(), str(p))
        raw = pickle.loads(p.read_bytes())
        assert isinstance(raw, dict)
        table = raw.pop("StructuredToParameterName@@")
        assert set(table) == set(raw)
        for k, v in raw.items():
            assert type(v) is np.ndarray, (k, type(v))
        np.testing.assert_allclose(raw["0.weight"],
                                   m.state_dict()["0.weight"].numpy())

    def test_save_protocol2_splits_like_reference(self, tmp_path):
        # >2^30-1 bytes is not testable in RAM; exercise the code path by
        # checking small arrays do NOT split and the layout stays loadable
        m = _mlp()
        p = tmp_path / "p2.pdparams"
        paddle.save(m.state_dict(), str(p), protocol=2)
        raw = pickle.loads(p.read_bytes())
        assert "UnpackBigParamInfor@@" not in raw
        loaded = paddle.load(str(p))
        dst = _mlp()
        dst.set_state_dict(loaded)

    def test_optimizer_pdopt_roundtrip(self, tmp_path):
        m = _mlp()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
        loss = m(x).sum()
        loss.backward()
        opt.step()
        p = tmp_path / "opt.pdopt"
        paddle.save(opt.state_dict(), str(p))
        raw = pickle.loads(p.read_bytes())
        assert isinstance(raw, dict)
        loaded = paddle.load(str(p))
        opt.set_state_dict(loaded)

    def test_legacy_sentinel_files_still_load(self, tmp_path):
        # pre-r4 paddle_tpu wire format (sentinel-wrapped tensors)
        legacy = {"w": {"__paddle_tpu_tensor__": True,
                        "data": np.ones((2, 2), np.float32),
                        "stop_gradient": False, "param": True}}
        p = tmp_path / "legacy.pdparams"
        p.write_bytes(pickle.dumps(legacy))
        out = paddle.load(str(p))
        np.testing.assert_array_equal(out["w"].numpy(),
                                      np.ones((2, 2), np.float32))
