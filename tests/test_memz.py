"""HBM ledger (ISSUE 18) — owner-attributed memory accounting invariants.

The contract under test:

  1. LEDGER — registration/push/pull semantics, the bounded delta ring,
     overlay owners excluded from the conservation sum, host owners never
     summed against HBM, a broken reader degrading to a stale value.
  2. CONSERVATION — census() reconciles attributed + unattributed ≡ the
     allocator view, pinned on a LIVE paged engine under churn
     (admissions, frees, prefix COW) with /memz scraped concurrently at
     ZERO post-warmup jit cache misses.
  3. HEADROOM — one {"headroom_low"} row per episode, armed as a
     flight-recorder trigger; the *_clear twin is inert on the bus.
  4. FORENSICS — post_mortem() writes the census + growth-curve artifact
     (largest owner in the head row), round-trips through
     load_postmortem/render_report, and fires from the real seams: a
     chaos-injected AllocFailure in the serving step and a TrainStep
     launch failure. kv_oom rejects name the top owners; admission
     stalls emit paired mem_pressure episode rows.
  5. WIRING — TrainStep registers params/opt-state after compile,
     CheckpointManager tracks the in-flight snapshot (host tier),
     StepMonitor samples the ledger EVERY record (the r7 rationing fix),
     FleetAggregator merges /memz with dead/ledger-less members degraded
     around, never fatal.
"""
import json
import os
import threading
import urllib.error
from urllib.request import urlopen

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ServingConfig, ServingEngine
from paddle_tpu.jit.api import compile_cache_misses
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.obs import (FleetAggregator, MemoryLedger, MetricsRegistry,
                            TelemetryServer, lint_exposition, looks_like_oom)
from paddle_tpu.obs.memz import load_postmortem, render_report
from paddle_tpu.resilience import AllocFailure, Injector


# ------------------------------------------------------------------ ledger

class TestLedgerCore:
    def test_push_pull_and_detail(self):
        led = MemoryLedger(allocated_fn=lambda: 1000)
        led.set("a", 600, kind="params")
        state = {"bytes": 300, "used": 5}
        led.register("b", lambda: state, kind="kv")
        assert led.attributed_bytes() == 900
        c = led.census()
        assert c["attributed_bytes"] == 900
        assert c["allocated_bytes"] == 1000
        assert c["unattributed_bytes"] == 100
        b = next(d for d in c["owners"] if d["owner"] == "b")
        assert b["detail"] == {"used": 5}
        # owners sort largest-first
        assert [d["owner"] for d in c["owners"]] == ["a", "b"]

    def test_duplicate_register_raises_replace_rebinds(self):
        led = MemoryLedger()
        led.register("a", lambda: 1)
        with pytest.raises(ValueError):
            led.register("a", lambda: 2)
        led.register("a", lambda: 2, replace=True)
        assert led.sample().census(reconcile=False)["owners"][0]["bytes"] == 2

    def test_overlay_and_host_excluded_from_conservation_sum(self):
        led = MemoryLedger(allocated_fn=lambda: 500)
        led.set("pool", 400, kind="kv")
        led.set("cache", 250, kind="kv", overlay=True)   # inside pool
        led.set("spill", 9000, kind="spill", device=False)
        assert led.attributed_bytes() == 400
        c = led.census()
        assert c["unattributed_bytes"] == 100            # not -8150
        assert {d["owner"] for d in c["owners"]} == {"pool", "cache"}
        assert [d["owner"] for d in c["host_owners"]] == ["spill"]
        assert next(d for d in c["owners"]
                    if d["owner"] == "cache").get("overlay") is True

    def test_delta_ring_bounded_and_high_watermarks(self):
        led = MemoryLedger(delta_ring=4)
        for i in range(10):
            led.set("a", (i % 3) * 100)
        assert len(led.deltas()) == 4
        assert led.deltas(2) == led.deltas()[-2:]
        c = led.census(reconcile=False)
        assert c["owners"][0]["high_watermark_bytes"] == 200
        # no-change sets append nothing
        n = len(led.deltas())
        led.set("a", led.census(reconcile=False)["owners"][0]["bytes"])
        assert len(led.deltas()) == n

    def test_broken_reader_degrades_to_stale_value(self):
        led = MemoryLedger()
        state = {"v": 100, "boom": False}

        def reader():
            if state["boom"]:
                raise RuntimeError("reader died")
            return state["v"]
        led.register("a", reader)
        state["boom"] = True
        c = led.census(reconcile=False)      # samples; must not raise
        assert c["owners"][0]["bytes"] == 100

    def test_quick_stats_and_top_owners(self):
        led = MemoryLedger()
        led.set("big", 500).set("small", 10).set("zero", 0)
        led.set("host", 999, device=False)
        assert led.top_owners(2) == [{"owner": "big", "bytes": 500},
                                     {"owner": "small", "bytes": 10}]
        led.set("big", 50)
        q = led.quick_stats()
        assert q == {"bytes_in_use": 60, "peak_bytes_in_use": 510,
                     "source": "memz_ledger"}

    def test_looks_like_oom(self):
        assert looks_like_oom(MemoryError())
        assert looks_like_oom(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 1073741824"))
        assert looks_like_oom(ValueError("failed to allocate 8 bytes"))
        assert not looks_like_oom(KeyError("kv_pool"))


# ------------------------------------------------- headroom + exposition

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "mini_step.trace.json.gz")


class TestHeadroomAndMetrics:
    def test_one_row_per_episode_and_flightrec_trigger(self, tmp_path):
        from paddle_tpu.obs import FixtureBackend, FlightRecorder
        alloc = {"v": 100}
        led = MemoryLedger(capacity_bytes=1000,
                           allocated_fn=lambda: alloc["v"],
                           headroom_low_frac=0.2)
        led.set("pool", 100, kind="kv")
        rec = FlightRecorder(str(tmp_path / "cap"),
                             backend=FixtureBackend(FIXTURE),
                             cooldown_s=0.0)
        led.on_row = rec.tap
        assert led.check_headroom() is None          # plenty of headroom
        alloc["v"] = 950                             # headroom 50 < 200
        row = led.check_headroom()
        assert "headroom_low" in row
        assert row["headroom_low"]["top_owners"][0]["owner"] == "pool"
        assert rec.triggers_total == 1               # capture armed
        assert led.check_headroom() is None          # same episode: 1 row
        assert led.headroom_low_total == 1
        alloc["v"] = 100
        clear = led.check_headroom()
        assert "headroom_low_clear" in clear
        assert rec.triggers_total == 1               # *_clear is inert

    def test_metrics_text_lints_through_registry(self):
        led = MemoryLedger(capacity_bytes=1 << 20,
                           allocated_fn=lambda: 4096)
        led.set("pool", 4000, kind="kv")
        led.set("cache", 100, kind="kv", overlay=True)
        led.set("spill", 77, kind="spill", device=False)
        reg = MetricsRegistry()
        reg.register("memz", lambda: led.metrics_text())
        page = reg.render()
        lint_exposition(page)
        assert 'paddle_tpu_hbm_bytes{owner="pool"} 4000' in page
        assert 'paddle_tpu_host_bytes{owner="spill"} 77' in page
        assert "paddle_tpu_hbm_attributed_bytes 4000" in page
        assert "paddle_tpu_hbm_unattributed_bytes 96" in page
        assert f"paddle_tpu_hbm_headroom_bytes {(1 << 20) - 4096}" in page

    def test_headroom_gauge_absent_without_capacity(self):
        led = MemoryLedger(allocated_fn=lambda: 100)
        led.set("a", 100)
        assert "hbm_headroom_bytes" not in led.metrics_text()


# ------------------------------------------------------------- forensics

class TestPostMortem:
    def _ledger(self, tmp_path):
        led = MemoryLedger(capacity_bytes=1000, allocated_fn=lambda: 900,
                           postmortem_dir=str(tmp_path))
        led.set("kv_pool", 700, kind="kv")
        led.set("model_params", 150, kind="params")
        led.set("spill", 42, kind="spill", device=False)
        return led

    def test_artifact_round_trip_and_rendering(self, tmp_path):
        led = self._ledger(tmp_path)
        err = RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        path = led.post_mortem(error=err, context={"step": 7})
        assert path and os.path.exists(path)
        assert led.postmortems_total == 1
        pm = load_postmortem(path)
        assert pm["oom"]["largest_owner"] == "kv_pool"
        assert pm["oom"]["is_alloc_failure"] is True
        assert pm["oom"]["context"] == {"step": 7}
        assert pm["census"]["unattributed_bytes"] == 50
        assert pm["deltas"]                      # the growth curve rows
        text = render_report(path)
        assert "largest owner: kv_pool" in text
        assert "step=7" in text and "unattributed" in text
        assert "spill" in text                   # host tier rendered

    def test_dump_failure_never_masks_the_oom(self, tmp_path):
        blocker = tmp_path / "not_a_dir"
        blocker.write_text("file where the artifact dir should go")
        led = self._ledger(tmp_path)
        assert led.post_mortem(error=MemoryError(),
                               dir=str(blocker)) is None

    def test_load_rejects_non_artifact(self, tmp_path):
        p = tmp_path / "x.jsonl"
        p.write_text('{"other": 1}\n')
        with pytest.raises(ValueError):
            load_postmortem(str(p))


# ----------------------------------------------------------- live engine

@pytest.fixture(scope="module")
def live():
    """One warmed paged engine + attached ledger, shared by the live
    tests (executable builds dominate this file's wall time)."""
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_position_embeddings=32,
                    intermediate_size=64)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, prompt_cap=8, max_new_tokens=4, decode_chunk=2,
        paged=True, kv_block=4, kv_blocks=16, prefix_cache=True))
    ledger = engine.attach_memory_ledger(
        MemoryLedger(capacity_bytes=1 << 30))
    rng = np.random.RandomState(0)
    prefix = rng.randint(1, 64, (4,)).astype(np.int64)
    prompts = []
    for i in range(6):
        if i % 2:
            sfx = rng.randint(1, 64, (int(rng.randint(1, 4)),))
            prompts.append(np.concatenate([prefix, sfx]).astype(np.int64))
        else:
            prompts.append(rng.randint(1, 64, (int(rng.randint(3, 8)),))
                           .astype(np.int64))
    for p in prompts:          # build every executable the churn touches
        engine.submit(p)
    engine.drain()
    for p in prompts[:2]:      # the zero-prefill COW admission path
        engine.submit(p)
    engine.drain()
    # the CPU live-array fallback counts EVERY live array in the
    # process — other test files' jit constants and cached models are
    # "foreign" bytes this engine's owners rightly never claim. Baseline
    # the residual post-warmup; conservation under churn is then pinned
    # as "the residual does not DRIFT" (in a fresh process, e.g. the
    # tier-1 memz_smoke leg, the baseline itself is ~0)
    c0 = ledger.census()
    return {"model": model, "cfg": cfg, "engine": engine,
            "ledger": ledger, "prompts": prompts,
            "unattr0": c0["unattributed_bytes"] or 0}


class TestLiveEngine:
    def test_conservation_under_churn_with_concurrent_memz(self, live):
        engine, ledger = live["engine"], live["ledger"]
        prompts = live["prompts"]
        miss0 = compile_cache_misses()
        srv = engine.serve_telemetry()
        errors, scrapes = [], [0]
        stop = threading.Event()

        def scrape():
            while not stop.is_set():
                try:
                    m = json.loads(urlopen(srv.url("/memz?deltas=8"),
                                           timeout=5).read())
                    assert any(o["owner"] == "kv_pool"
                               for o in m["owners"])
                    assert m["allocated_bytes"] is not None
                    scrapes[0] += 1
                except Exception as e:       # noqa: BLE001 — the gate
                    errors.append(f"{type(e).__name__}: {e}")
                    return
                stop.wait(0.02)
        t = threading.Thread(target=scrape, daemon=True)
        t.start()
        try:
            for b in range(3):
                for p in prompts[2 * b:2 * b + 2]:
                    engine.submit(p)
                engine.drain()
                c = ledger.census()
                alloc, unattr = (c["allocated_bytes"],
                                 c["unattributed_bytes"])
                assert alloc is not None
                drift = abs(unattr - live["unattr0"])
                assert drift <= 0.15 * c["attributed_bytes"], c
        finally:
            stop.set()
            t.join(timeout=5)
            srv.close()
        assert not errors, errors
        assert scrapes[0] >= 1
        assert compile_cache_misses() - miss0 == 0   # scrape never syncs
        # statusz carries the compact memory block
        s = engine.statusz()
        assert s["memory"]["owners"]["model_params"] > 0
        assert "kv_pool" in s["memory"]["owners"]

    def test_memz_route_rejects_bad_deltas(self, live):
        with pytest.raises(ValueError):
            live["ledger"].memz({"deltas": "abc"})

    def test_kv_oom_reject_names_top_owners(self, live):
        eng = ServingEngine(live["model"], ServingConfig(
            max_batch=2, prompt_cap=12, max_new_tokens=8, decode_chunk=4,
            paged=True, kv_block=4, kv_blocks=5))
        eng.attach_memory_ledger()
        # 12 + 8 - 1 = 19 rows > the whole pool (4 usable blocks = 16)
        f = eng.preflight(np.arange(1, 13, dtype=np.int64), 8)
        oom = [x for x in f if x.code == "kv_oom"]
        assert len(oom) == 1
        assert "top HBM owners" in oom[0].message
        owners = [t["owner"] for t in oom[0].data["top_owners"]]
        assert "model_params" in owners and "kv_pool" in owners

    def test_mem_pressure_rows_paired_per_episode(self, live):
        eng = ServingEngine(live["model"], ServingConfig(
            max_batch=2, prompt_cap=12, max_new_tokens=4, decode_chunk=2,
            paged=True, kv_block=4, kv_blocks=6))
        eng.attach_memory_ledger()
        rows = []
        eng.metrics.on_record = rows.append
        rng = np.random.RandomState(1)
        for _ in range(4):
            eng.submit(rng.randint(1, 64, (10,)).astype(np.int64))
        eng.drain()
        enter = [r for r in rows if "mem_pressure" in r]
        clear = [r for r in rows if "mem_pressure_clear" in r]
        assert len(enter) >= 1 and len(enter) == len(clear)
        body = enter[0]["mem_pressure"]
        assert body["need_rows"] > 0 and "top_owners" in body
        assert (eng.metrics.counters["mem_pressure_episodes"]
                == len(enter))
        assert all("waited_s" in c["mem_pressure_clear"] for c in clear)

    def test_injected_alloc_failure_dumps_post_mortem(self, live, tmp_path):
        engine, ledger = live["engine"], live["ledger"]
        old_dir = ledger.postmortem_dir
        ledger.postmortem_dir = str(tmp_path)
        engine.chaos = Injector(faults=[AllocFailure()])
        try:
            engine.submit(live["prompts"][0])
            with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
                while engine.busy:
                    engine.step()
            assert engine.chaos.fired("alloc_failure") == 1
        finally:
            engine.chaos = None
            ledger.postmortem_dir = old_dir
        arts = sorted(p for p in os.listdir(tmp_path)
                      if p.endswith(".jsonl"))
        assert len(arts) == 1
        pm = load_postmortem(str(tmp_path / arts[0]))
        assert pm["oom"]["context"]["site"] == "serving.step"
        assert pm["oom"]["largest_owner"] in ("model_params", "kv_pool")
        # the engine stays servable after the unwind
        r = engine.submit(live["prompts"][1])
        engine.drain()
        assert r.status == "done"


# ------------------------------------------------------- train/ckpt/monitor

class TestTrainingSeams:
    def test_train_step_registers_params_and_opt_state(self):
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models import GPTPretrainingCriterion
        from paddle_tpu.profiler.monitor import StepMonitor
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=2, max_position_embeddings=16,
                        intermediate_size=64)
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        led = MemoryLedger(allocated_fn=lambda: None)
        mon = StepMonitor()
        step = TrainStep(model, opt,
                         lambda ids, lbl: crit(model(ids), lbl),
                         monitor=mon, memz=led)
        ids = paddle.to_tensor(np.random.RandomState(0)
                               .randint(0, 64, (2, 8)).astype("int32"))
        step(ids, ids)
        c = led.census(reconcile=False)
        by = {d["owner"]: d["bytes"] for d in c["owners"]}
        assert by["train_params"] > 0
        # AdamW carries two moments: opt state outweighs the params
        assert by["train_opt_state"] > by["train_params"]
        assert mon.memz is led               # monitor rides the ledger

    def test_launch_oom_dumps_train_post_mortem(self, tmp_path):
        from paddle_tpu.jit import TrainStep
        led = MemoryLedger(postmortem_dir=str(tmp_path))
        led.set("train_opt_state", 500, kind="opt_state")
        model = paddle.nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(learning_rate=1e-2,
                                   parameters=model.parameters())
        ts = TrainStep(model, opt, lambda x: x, memz=led)

        def boom(*_a):
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        with pytest.raises(RuntimeError):
            ts._launch(boom)
        arts = [p for p in os.listdir(tmp_path) if p.endswith(".jsonl")]
        assert len(arts) == 1
        pm = load_postmortem(str(tmp_path / arts[0]))
        assert pm["oom"]["context"]["site"] == "train_step.launch"
        assert pm["oom"]["largest_owner"] == "train_opt_state"
        # a NON-OOM failure must not dump an artifact
        def bug(*_a):
            raise ValueError("shape mismatch")
        with pytest.raises(ValueError):
            ts._launch(bug)
        assert len([p for p in os.listdir(tmp_path)
                    if p.endswith(".jsonl")]) == 1

    def test_checkpoint_inflight_snapshot_tracked(self, tmp_path):
        from paddle_tpu.resilience import CheckpointManager
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        led = MemoryLedger()
        mgr.memz = led
        w = np.zeros((64, 64), dtype=np.float32)
        h = mgr.save(1, {"w": w}, async_save=True)
        h.wait()
        c = led.census(reconcile=False)
        owner = next(d for d in c["host_owners"]
                     if d["owner"] == "ckpt_inflight")
        assert owner["bytes"] == 0                    # released on commit
        assert owner["high_watermark_bytes"] == w.nbytes

    def test_monitor_samples_ledger_every_record(self):
        from paddle_tpu.profiler.monitor import StepMonitor
        led = MemoryLedger()
        led.set("train_params", 1234, kind="params")
        mon = StepMonitor()
        mon.memz = led
        for _ in range(5):       # r7 rationing would skip records 2..5
            mon.begin_step()
            mon.end_step(items=1)
        assert all(r.get("hbm_bytes_in_use") == 1234
                   for r in mon.records)


# ------------------------------------------------------------------ fleet

class TestFleetMemz:
    def test_merge_labels_sums_and_degrades(self):
        la = MemoryLedger(capacity_bytes=1100, allocated_fn=lambda: 1000,
                          headroom_low_frac=0.10)
        la.set("kv_pool", 600, kind="kv").set("model_params", 300,
                                              kind="params")
        lb = MemoryLedger(allocated_fn=lambda: None)   # no allocator view
        lb.set("kv_pool", 50, kind="kv")
        srvs = [TelemetryServer(MetricsRegistry(),
                                routes={"/memz": la.memz}).start(),
                TelemetryServer(MetricsRegistry(),
                                routes={"/memz": lb.memz}).start(),
                TelemetryServer(MetricsRegistry()).start()]   # no ledger
        dead = TelemetryServer(MetricsRegistry()).start()
        dead.close()
        try:
            fleet = FleetAggregator(
                {"a": srvs[0], "b": srvs[1], "bare": srvs[2],
                 "dead": dead}, timeout=1.0, cache_ttl=0.0)
            fm = fleet.fleet_memz()
            s = fm["summary"]
            assert s["replicas"] == 4
            assert s["with_ledger"] == 2          # bare 404s, dead is gone
            assert s["attributed_bytes"] == 950
            # b has no allocator view: those sums degrade to None,
            # never invent bytes
            assert s["allocated_bytes"] is None
            assert s["unattributed_bytes"] is None
            # a: headroom 100 < 10% of 1100 -> flagged by replica name
            assert s["headroom_low"] == ["a"]
            top = fm["owners"][0]
            assert (top["owner"], top["replica"],
                    top["bytes"]) == ("kv_pool", "a", 600)
            assert set(fm["per_replica"]) == {"a", "b"}
        finally:
            for srv in srvs:
                srv.close()

    def test_fleet_memz_route_served(self):
        led = MemoryLedger(allocated_fn=lambda: 100)
        led.set("kv_pool", 80, kind="kv")
        srv = TelemetryServer(MetricsRegistry(),
                              routes={"/memz": led.memz}).start()
        fsrv = None
        try:
            fleet = FleetAggregator({"r0": srv}, timeout=1.0)
            fsrv = fleet.serve()
            fm = json.loads(urlopen(fsrv.url("/fleet/memz"),
                                    timeout=5).read())
            assert fm["summary"]["attributed_bytes"] == 80
            assert fm["owners"][0]["replica"] == "r0"
        finally:
            if fsrv is not None:
                fsrv.close()
            srv.close()
