"""sequence_* op family (VERDICT r1 missing #4: op-corpus tail).

Reference: static/nn/sequence_lod.py over LoD tensors; TPU-native contract
is padded-dense [B, T, ...] + lengths [B] (static/sequence.py docstring).
Each test checks against a per-row numpy simulation of the LoD semantics."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static.nn as snn


def _t(a):
    return paddle.to_tensor(np.asarray(a))


B, T, H = 3, 5, 4
RNG = np.random.RandomState(0)
X = RNG.randn(B, T, H).astype(np.float32)
LEN = np.array([5, 3, 1], np.int64)


def _rows():
    return [X[b, :LEN[b]] for b in range(B)]


class TestSequencePool:
    @pytest.mark.parametrize("pt,ref", [
        ("sum", lambda r: r.sum(0)),
        ("average", lambda r: r.mean(0)),
        ("sqrt", lambda r: r.sum(0) / np.sqrt(len(r))),
        ("max", lambda r: r.max(0)),
        ("first", lambda r: r[0]),
        ("last", lambda r: r[-1]),
    ])
    def test_pool(self, pt, ref):
        out = snn.sequence_pool(_t(X), pt, lengths=_t(LEN))
        want = np.stack([ref(r) for r in _rows()])
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-5, atol=1e-6)

    def test_first_last_steps(self):
        np.testing.assert_allclose(
            snn.sequence_first_step(_t(X), _t(LEN)).numpy(),
            np.stack([r[0] for r in _rows()]), rtol=1e-6)
        np.testing.assert_allclose(
            snn.sequence_last_step(_t(X), _t(LEN)).numpy(),
            np.stack([r[-1] for r in _rows()]), rtol=1e-6)


def test_sequence_softmax():
    ids = RNG.randn(B, T).astype(np.float32)
    out = snn.sequence_softmax(_t(ids), lengths=_t(LEN)).numpy()
    for b in range(B):
        v = ids[b, :LEN[b]]
        e = np.exp(v - v.max())
        np.testing.assert_allclose(out[b, :LEN[b]], e / e.sum(), rtol=1e-5,
                                   atol=1e-6)
        assert np.all(out[b, LEN[b]:] == 0)


def test_sequence_reverse():
    out = snn.sequence_reverse(_t(X), lengths=_t(LEN)).numpy()
    for b in range(B):
        np.testing.assert_allclose(out[b, :LEN[b]], X[b, :LEN[b]][::-1])


def test_sequence_concat():
    X2 = RNG.randn(B, 4, H).astype(np.float32)
    L2 = np.array([2, 4, 3], np.int64)
    out, new_len = snn.sequence_concat([_t(X), _t(X2)], [_t(LEN), _t(L2)])
    np.testing.assert_array_equal(new_len.numpy(), LEN + L2)
    for b in range(B):
        want = np.concatenate([X[b, :LEN[b]], X2[b, :L2[b]]], 0)
        np.testing.assert_allclose(out.numpy()[b, :LEN[b] + L2[b]], want,
                                   rtol=1e-6)


def test_sequence_slice():
    off = np.array([1, 0, 0], np.int64)
    ln = np.array([2, 2, 1], np.int64)
    out, olen = snn.sequence_slice(_t(X), _t(off), _t(ln), lengths=_t(LEN))
    np.testing.assert_array_equal(olen.numpy(), ln)
    for b in range(B):
        np.testing.assert_allclose(out.numpy()[b, :ln[b]],
                                   X[b, off[b]:off[b] + ln[b]], rtol=1e-6)


def test_sequence_pad_and_unpad():
    pv = np.float32(9.5)
    out, ln = snn.sequence_pad(_t(X), _t(pv), _t(LEN), maxlen=6)
    o = out.numpy()
    assert o.shape == (B, 6, H)
    for b in range(B):
        np.testing.assert_allclose(o[b, :LEN[b]], X[b, :LEN[b]])
        assert np.all(o[b, LEN[b]:] == pv)
    flat = snn.sequence_unpad(_t(X), _t(LEN))
    want = np.concatenate(_rows(), 0)
    np.testing.assert_allclose(flat.numpy(), want)


def test_sequence_reshape():
    out, nl = snn.sequence_reshape(_t(X), new_dim=2, lengths=_t(LEN))
    assert out.shape == [B, T * H // 2, 2]
    np.testing.assert_array_equal(nl.numpy(), LEN * H // 2)
    np.testing.assert_allclose(out.numpy()[0].reshape(-1),
                               X[0].reshape(-1), rtol=1e-6)


def test_sequence_expand_as():
    xs = RNG.randn(B, H).astype(np.float32)
    out = snn.sequence_expand_as(_t(xs), _t(X), _t(LEN)).numpy()
    for b in range(B):
        np.testing.assert_allclose(out[b, :LEN[b]],
                                   np.tile(xs[b], (LEN[b], 1)), rtol=1e-6)
        assert np.all(out[b, LEN[b]:] == 0)


def test_sequence_scatter():
    base = np.zeros((B, T), np.float32)
    idx = np.array([[0, 2, 4, 0, 0], [1, 1, 0, 0, 0], [3, 0, 0, 0, 0]],
                   np.int64)
    upd = np.ones((B, T), np.float32)
    ln = np.array([3, 2, 1], np.int64)
    out = snn.sequence_scatter(_t(base), _t(idx), _t(upd), lengths=_t(ln))
    want = np.zeros((B, T), np.float32)
    for b in range(B):
        for i in range(ln[b]):
            want[b, idx[b, i]] += 1.0
    np.testing.assert_allclose(out.numpy(), want)


def test_sequence_enumerate():
    ids = np.array([[1, 2, 3, 4, 5], [6, 7, 8, 0, 0], [9, 0, 0, 0, 0]],
                   np.int64)
    out = snn.sequence_enumerate(_t(ids), win_size=2, lengths=_t(LEN)).numpy()
    assert out.shape == (B, T, 2)
    np.testing.assert_array_equal(out[0, 0], [1, 2])
    # window elements past the row boundary take pad_value (reference:
    # sequence_enumerate_op fills beyond-boundary positions with pad)
    np.testing.assert_array_equal(out[0, 4], [5, 0])
    assert np.all(out[2, 1:] == 0)                      # past length -> pad


def test_sequence_conv_matches_manual():
    paddle.seed(0)
    out = snn.sequence_conv(_t(X), num_filters=6, filter_size=3,
                            lengths=_t(LEN))
    assert out.shape == [B, T, 6]
    o = out.numpy()
    assert np.all(o[2, 1:] == 0)        # masked past row length
    assert np.isfinite(o).all()
    # functional form with explicit weight: exact numpy check
    W = RNG.randn(3 * H, 6).astype(np.float32)
    out2 = snn.sequence_conv(_t(X), 6, filter_size=3, lengths=_t(LEN),
                             weight=_t(W)).numpy()
    b = 0
    for t in range(LEN[b]):
        ctx = []
        for k in (-1, 0, 1):
            tt = t + k
            ctx.append(X[b, tt] if 0 <= tt < LEN[b] else np.zeros(H, np.float32))
        want = np.concatenate(ctx) @ W
        np.testing.assert_allclose(out2[b, t], want, rtol=1e-4, atol=1e-5)


def test_sequence_grad_flows():
    x = _t(X)
    x.stop_gradient = False
    out = snn.sequence_pool(x, "average", lengths=_t(LEN))
    out.sum().backward()
    g = x.grad.numpy()
    assert np.all(g[0, :5] != 0)
    assert np.all(g[2, 1:] == 0)        # padding gets no gradient


class TestStringOps:
    """StringTensor family (reference: phi/kernels/strings — lower/upper
    kernels with ASCII+UTF-8 paths, CPU-resident there too)."""

    def test_lower_upper_utf8(self):
        from paddle_tpu.text import strings as S
        st = S.StringTensor([["Hello", "WÖRLD"], ["ÉcOlE", "abc"]])
        lo = S.lower(st)
        up = S.upper(st)
        assert lo.numpy()[0, 1] == "wörld"
        assert up.numpy()[1, 0] == "ÉCOLE"
        assert lo.shape == [2, 2]

    def test_ascii_path_and_length(self):
        from paddle_tpu.text import strings as S
        st = S.StringTensor(["AbC", "deF!"])
        assert list(S.lower(st, use_utf8_encoding=False).numpy()) == \
            ["abc", "def!"]
        ln = S.length(st)
        np.testing.assert_array_equal(ln.numpy(), [3, 4])
        assert str(ln.dtype) == "int64"

    def test_strip_join_hash(self):
        from paddle_tpu.text import strings as S
        st = S.StringTensor([" a ", "b  "])
        assert list(S.strip(st).numpy()) == ["a", "b"]
        j = S.join(S.StringTensor([["x", "y"], ["z", "w"]]), sep="-")
        assert list(j.numpy()) == ["x-y", "z-w"]
        h = S.to_hash(st, num_buckets=1000)
        assert h.numpy().shape == (2,)
        assert (h.numpy() >= 0).all() and (h.numpy() < 1000).all()
        # hash is stable across calls
        np.testing.assert_array_equal(h.numpy(),
                                      S.to_hash(st, 1000).numpy())


def test_sequence_review_edges():
    """Edges from review: lengths=None default, pad maxlen validation,
    reshape per-row divisibility, expand static width."""
    x = _t(X)
    # lengths=None == full rows
    np.testing.assert_allclose(
        snn.sequence_pool(x, "sum").numpy(), X.sum(1), rtol=1e-5)
    with pytest.raises(ValueError, match="maxlen"):
        snn.sequence_pad(x, _t(np.float32(0)), _t(LEN), maxlen=3)
    with pytest.raises(ValueError, match="divide"):
        snn.sequence_reshape(_t(X), new_dim=3, lengths=_t(LEN))
    with pytest.raises(ValueError, match="max_repeat"):
        import jax
        jax.jit(lambda a, ln: snn.sequence_expand(
            paddle.Tensor(a), paddle.Tensor(ln))._data)(
            X[:, 0], LEN)
    out = snn.sequence_expand(_t(X[:, 0]), _t(np.array([3, 1, 2])))
    assert out.shape == [B, 3, H]
    assert np.all(out.numpy()[1, 1:] == 0)


def test_string_join_no_truncation():
    """review: np.apply_along_axis froze width at the first row."""
    from paddle_tpu.text import strings as S
    j = S.join(S.StringTensor([["abc", "defgh"], ["x", "ylongerstring"]]),
               sep="-")
    assert list(j.numpy()) == ["abc-defgh", "x-ylongerstring"]


def test_wmt_literal_special_tokens():
    """review: corpora containing literal <unk> must not alias ids."""
    import tempfile, os
    from paddle_tpu import text
    d = tempfile.mkdtemp()
    f = os.path.join(d, "p.tsv")
    open(f, "w").write("the <unk> cat\tle <unk> chat\nthe dog\tle chien\n")
    ds = text.WMT16(data_file=f)
    ids = list(ds.src_ids.values())
    assert len(ids) == len(set(ids)), ds.src_ids
