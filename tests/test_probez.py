"""Active probing (ISSUE 19): golden-canary sentinels, deep invariant
pollers, probe/SLO isolation, and fleet /probez.

Pins the tentpole guarantees: goldens minted once per config
fingerprint via the reference generate_static_ragged oracle; probes
ride the REAL submit()/step path with zero steady-state jit misses;
chaos-injected KV corruption is detected within ONE probe cycle and
produces exactly one structured probe_fail row (flight-recorder pinned
capture attached) plus router ejection with bit-identical redispatch;
probe traffic leaves the user-facing SLO/latency/goodput accounting
BYTE-identical (structural exclusion, not subtraction); the deep
invariant auditor passes on a healthy engine and fires transition-based
findings on seeded violations; and the r16 straggler-granularity
follow-up (StepMonitor JSONL buffering flushes on every straggler
transition).
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ServingConfig, ServingEngine
from paddle_tpu.inference.fleet import FleetRouter, ReplicaRegistry
from paddle_tpu.jit.api import compile_cache_misses
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.obs import (FixtureBackend, FleetAggregator,
                            FlightRecorder, GoldenStore, InvariantAuditor,
                            Prober, SLOMonitor, config_fingerprint)
from paddle_tpu.obs.collectives import load_shard_walls
from paddle_tpu.profiler.monitor import StepMonitor
from paddle_tpu.resilience import CorruptKVBlock, Injector

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "mini_step.trace.json.gz")

CAP, NEW = 8, 6


@pytest.fixture(scope="module")
def served_model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m, cfg


def _engine(m, **kw):
    base = dict(max_batch=2, prompt_cap=CAP, max_new_tokens=NEW,
                decode_chunk=2, paged=True, kv_block=4,
                prefix_cache=True)
    base.update(kw)
    return ServingEngine(m, ServingConfig(**base))


# ------------------------------------------------------------ fingerprint

def test_config_fingerprint_deterministic_and_drift():
    a = config_fingerprint({"h": 32, "v": 96}, {"max_batch": 2},
                           env={"PADDLE_TPU_X": "1"})
    b = config_fingerprint({"v": 96, "h": 32}, {"max_batch": 2},
                           env={"PADDLE_TPU_X": "1"})
    assert a["sha"] == b["sha"]                  # key order is identity-free
    assert json.dumps(a["components"], sort_keys=True) == \
        json.dumps(b["components"], sort_keys=True)
    # any deciding component moves the sha: config, envelope, env
    assert config_fingerprint({"h": 33, "v": 96}, {"max_batch": 2},
                              env={"PADDLE_TPU_X": "1"})["sha"] != a["sha"]
    assert config_fingerprint({"h": 32, "v": 96}, {"max_batch": 4},
                              env={"PADDLE_TPU_X": "1"})["sha"] != a["sha"]
    assert config_fingerprint({"h": 32, "v": 96}, {"max_batch": 2},
                              env={"PADDLE_TPU_X": "2"})["sha"] != a["sha"]
    # callables hash by qualname, never repr (repr embeds the address —
    # identical replicas would fingerprint apart)
    c1 = config_fingerprint({"fn": test_config_fingerprint_deterministic_and_drift})
    c2 = config_fingerprint({"fn": test_config_fingerprint_deterministic_and_drift})
    assert c1["sha"] == c2["sha"]


def test_engine_statusz_carries_fingerprint(served_model):
    m, _ = served_model
    eng = _engine(m)
    fp = eng.statusz()["fingerprint"]
    assert fp["sha"] == eng.fingerprint()["sha"]
    assert set(fp["components"]) == {"model", "serving", "versions", "env"}
    # same model+config => same sha; a different envelope drifts
    assert _engine(m).fingerprint()["sha"] == fp["sha"]
    assert _engine(m, max_batch=4).fingerprint()["sha"] != fp["sha"]


# ----------------------------------------------------------------- prober

def test_prober_passes_with_zero_steady_state_misses(served_model):
    m, cfg = served_model
    eng = _engine(m)
    store = GoldenStore()
    pr = Prober(eng, store=store, replica="r0").warm()
    assert set(pr.variants) == {"decode", "prefix_miss", "prefix_hit"}
    assert store.minted_total == 3               # one golden per variant
    miss0 = compile_cache_misses()
    for _ in range(3):
        out = pr.probe_once()
        assert not out["failing"]
    assert compile_cache_misses() - miss0 == 0   # steady state: no churn
    pz = pr.probez()
    assert pz["state"] == "passing" and pz["failures_total"] == 0
    for st in pz["variants"].values():
        assert st["fail_total"] == 0 and st["pass_total"] >= 3
    # a second replica with the SAME fingerprint shares the goldens:
    # nothing new minted
    Prober(_engine(m), store=store, replica="r1").warm()
    assert store.minted_total == 3
    text = pr.metrics_text()
    assert 'paddle_tpu_probe_pass_total{variant="prefix_hit"}' in text
    assert "paddle_tpu_probe_failing 0" in text


def _user_slice(met):
    """The user-facing accounting the ISSUE pins: every request-scoped
    counter (goodput inputs, token volumes, cache/spec efficiency) and
    the rendered latency histograms. Excludes `batches` and the
    occupancy gauges — those describe MACHINE state, which probe rows
    genuinely occupy."""
    from paddle_tpu.profiler._metrics import histogram_lines
    counters = {k: v for k, v in met.counters.items() if k != "batches"}
    hists = "\n".join(
        "\n".join(histogram_lines("u", name, met.hists[name], help_))
        for name, help_ in met.HISTS)
    return counters, hists


def test_probe_requests_never_touch_user_accounting(served_model):
    """Satellite: probe/SLO isolation is STRUCTURAL. A probe storm —
    passing, then failing, then recovering — leaves the user-facing
    counters, TTFT/e2e/goodput histograms, and the SLO monitor
    byte-identical to their pre-storm state."""
    m, cfg = served_model
    eng = _engine(m)
    pr = Prober(eng, replica="r0").warm()
    slo = SLOMonitor("ttft_p99=10s,goodput=0.0", eng.metrics)
    rows = []
    eng.metrics.on_record = rows.append

    # some real user traffic first, so the histograms are non-trivial
    rng = np.random.RandomState(3)
    for ln in (CAP, 5, 3):
        eng.submit(rng.randint(1, cfg.vocab_size, (ln,)).astype(np.int64))
    eng.drain()
    slo.poll()
    before = _user_slice(eng.metrics)
    before_alerts = slo.alerts_total

    # the storm: clean cycles, a corruption-induced failure, recovery
    for _ in range(2):
        pr.probe_once()
    blks = pr.probe_blocks("prefix_hit")
    eng.chaos = Injector(0).add(
        CorruptKVBlock(engine=eng, block=blks[0]))
    pr.probe_once()
    assert pr.failing
    eng.chaos = None
    eng._prefix.clear()                          # drop the corrupted block
    pr.probe_once()
    assert not pr.failing                        # recovered
    slo.poll()

    assert _user_slice(eng.metrics) == before    # bitwise unaffected
    assert slo.alerts_total == before_alerts and not slo.breaching
    assert not any("slo_alert" in r for r in rows)
    # ...while the probe-side families saw everything
    assert eng.metrics.probe_counters["requests"] > 0
    assert [r for r in rows if "probe_fail" in r]
    assert [r for r in rows if "probe_clear" in r]


def test_rejected_probe_is_noise_not_user_rejection(served_model):
    """Satellite: rejection reasons gain the probe dimension — a probe
    shed during drain is prober noise, never user-facing rejected_total
    (the r12 autoscaler overload signal stays clean)."""
    m, _ = served_model
    eng = _engine(m)
    pr = Prober(eng, replica="r0").warm()
    eng.begin_drain()
    pr.probe_once()
    assert not pr.failing                        # refusal != wrongness
    assert eng.metrics.counters["rejected"] == 0
    assert eng.metrics.probe_counters["rejected"] == len(pr.variants)
    assert eng.metrics.probe_reject_reasons == {
        "draining": len(pr.variants)}
    text = eng.metrics.probe_metrics_text()
    assert 'rejected_reason_total{reason="draining"}' in text
    st = pr.probez()["variants"]["decode"]
    assert st["noise_total"] == 1 and st["last_status"] == "noise"
    eng.resume_admission()
    pr.probe_once()
    assert pr.probez()["state"] == "passing"


def test_corruption_detected_one_cycle_one_row_pinned_capture(
        served_model, tmp_path):
    """Acceptance: one flipped KV-block region -> the next probe cycle
    fails the hit-path variant, emits exactly ONE structured probe_fail
    row naming variant + first diverging position, and pins a flight-
    recorder capture."""
    m, _ = served_model
    eng = _engine(m)
    rec = FlightRecorder(str(tmp_path / "cap"),
                         backend=FixtureBackend(FIXTURE),
                         trigger_steps=1, cooldown_s=0.0)
    rec.attach(monitor=eng.monitor, metrics=eng.metrics)
    rows = []
    prev = eng.metrics.on_record
    eng.metrics.on_record = lambda r: (prev(r), rows.append(r))
    pr = Prober(eng, replica="r0").warm()
    blks = pr.probe_blocks("prefix_hit")
    assert blks                                  # trie seeded by warm()
    fault = CorruptKVBlock(engine=eng, block=blks[0])
    eng.chaos = Injector(0).add(fault)

    pr.probe_once()                              # detection cycle
    assert fault.fired and fault.corrupted_block == blks[0]
    assert pr.failing
    fails = [r for r in rows if "probe_fail" in r]
    assert len(fails) == 1
    body = fails[0]["probe_fail"]
    assert body["variant"] == "prefix_hit"
    assert body["first_divergence"] is not None
    assert body["fingerprint"] == eng.fingerprint()["sha"]
    assert "memz_census" not in pr.probez().get("last_fail", {})
    # sustained failure stays ONE row (transition machine, not a spam)
    pr.probe_once()
    assert len([r for r in rows if "probe_fail" in r]) == 1
    # the trigger pinned a capture
    caps = [c for c in rec.captures if c.get("pinned")]
    assert caps
    assert [t["kind"] for t in caps[0]["triggers"]] == ["probe_fail"]
    # only the hit-path variant fails: decode + miss bypass the cache
    vs = pr.probez()["variants"]
    assert vs["prefix_hit"]["failing"]
    assert not vs["decode"]["failing"]
    assert not vs["prefix_miss"]["failing"]
    rec.detach()


def test_router_ejects_failing_replica_and_redispatches(served_model):
    """Acceptance: a correctness-failing replica leaves routing like a
    dead one — drained + ejected, in-flight work redispatched elsewhere
    bit-identically — while the fleet keeps serving."""
    m, cfg = served_model
    store = GoldenStore()
    reg = ReplicaRegistry()
    probers = {}
    for i in range(3):
        name = f"r{i}"
        eng = _engine(m)
        reg.add(name, eng)
        pr = Prober(eng, store=store, replica=name).warm()
        reg._handles[name].prober = pr
        probers[name] = pr
    router = FleetRouter(reg)

    lens = [CAP, 5, 3]
    rng = np.random.RandomState(7)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    ref = m.generate_static_ragged(
        paddle.to_tensor(ids), lens, max_new_tokens=NEW).numpy()[:, CAP:]

    # corrupt the cached block of the replica that serves prompt 0: the
    # probe catches it, then freshly-dispatched work lands on the (not
    # yet ejected) victim and the router's next step must eject it and
    # redispatch — the chains still match the oracle bit-for-bit
    victim = router.rank(router.routing_key(ids[0, :lens[0]]))[0]
    pv = probers[victim]
    blks = pv.probe_blocks("prefix_hit")
    pv.engine.chaos = Injector(0).add(
        CorruptKVBlock(engine=pv.engine, block=blks[0]))
    pv.probe_once()                              # sentinel fires
    assert pv.failing
    freqs = [router.submit(ids[i, :lens[i]]) for i in range(len(lens))]
    done = []
    for _ in range(200):
        done += router.step()
        if len(done) == len(lens):
            break
    assert router.counters["probe_ejected"] == 1
    assert victim in reg.ejected
    assert reg.ejected[victim].ejected_reason.startswith("probe_fail:")
    assert victim not in reg.names(("serving",))
    assert len(reg.names(("serving",))) == 2     # fleet keeps serving
    assert [f.status for f in freqs] == ["done"] * len(lens)
    for i, f in enumerate(freqs):
        np.testing.assert_array_equal(f.request.tokens, ref[i])


def test_router_settles_requests_finished_by_a_local_step_loop(
        served_model):
    """A Prober cycle steps its engine to complete the probe — and can
    finish a router-dispatched request along the way. That step()'s
    `finished` list goes to the prober, so the router must settle the
    FleetRequest by the shared Request's terminal status (the
    _step_once sweep); without it the request pends forever."""
    m, cfg = served_model
    reg = ReplicaRegistry()
    eng = _engine(m)
    reg.add("r0", eng)
    pr = Prober(eng, replica="r0").warm()
    reg._handles["r0"].prober = pr
    router = FleetRouter(reg)

    prompt = np.arange(1, 6, dtype=np.int64)
    freq = router.submit(prompt)
    assert freq.status == "pending"
    # probe cycles ride the engine NOW: their internal step loops run
    # the user request to completion and swallow the finished lists
    for _ in range(5):
        pr.probe_once()
        if freq.request.status == "done":
            break
    assert freq.request.status == "done"      # engine-side: terminal
    assert freq.status == "pending"           # router hasn't looked yet
    done = router.step()
    assert freq in done and freq.status == "done"
    padded = np.pad(prompt, (0, CAP - prompt.size)).reshape(1, -1)
    ref = m.generate_static_ragged(
        paddle.to_tensor(padded), [prompt.size],
        max_new_tokens=NEW).numpy()[:, CAP:]
    np.testing.assert_array_equal(freq.request.tokens, ref[0])


# ------------------------------------------------------ invariant auditor

def test_invariant_auditor_clean_engine_all_green(served_model):
    m, cfg = served_model
    eng = _engine(m)
    pr = Prober(eng, replica="r0").warm()
    aud = InvariantAuditor(eng, lock=pr.lock)
    s = aud.audit()
    assert s["ok"] == {c: True for c in InvariantAuditor.CHECKS}
    assert not s["violating"] and s["violations_total"] == 0
    text = aud.metrics_text()
    assert 'paddle_tpu_invariant_ok{check="pool_conservation"} 1' in text
    assert 'paddle_tpu_invariant_ok{check="trie_pool"} 1' in text


def test_invariant_auditor_transition_rows_on_seeded_violations(
        served_model):
    m, _ = served_model
    eng = _engine(m)
    Prober(eng, replica="r0").warm()             # seeds trie + traffic
    rows = []
    eng.metrics.on_record = rows.append
    aud = InvariantAuditor(eng)
    aud.audit()
    assert not aud.violating

    # seed a conservation break: leak one block off the free list
    leaked = eng._pool._free.pop()
    aud.audit()
    assert aud.violating
    v = [r for r in rows if "invariant_violation" in r]
    assert len(v) == 1
    assert v[0]["invariant_violation"]["check"] == "pool_conservation"
    aud.audit()                                  # sustained: still ONE row
    assert len([r for r in rows if "invariant_violation" in r]) == 1
    eng._pool._free.append(leaked)               # repair
    aud.audit()
    assert not aud.violating
    clears = [r for r in rows if "invariant_clear" in r]
    assert len(clears) == 1
    assert clears[0]["invariant_clear"]["check"] == "pool_conservation"

    # a refcount break is the owner_refcounts check's job
    blocks = [b for b, r in eng._pool._refs.items() if r > 0]
    eng._pool._refs[blocks[0]] += 1
    aud.audit()
    assert aud.violating
    kinds = {r["invariant_violation"]["check"]
             for r in rows if "invariant_violation" in r}
    assert "owner_refcounts" in kinds
    eng._pool._refs[blocks[0]] -= 1
    aud.audit()
    assert not aud.violating


# ----------------------------------------------------------- fleet merge

def test_fleet_probez_merges_and_flags_config_drift():
    agg = FleetAggregator()
    findings = []
    agg.on_finding = findings.append
    probez = {
        "r0": {"state": "passing", "variants": {"decode": {}},
               "fingerprint": "aaaa"},
        "r1": {"state": "failing", "variants": {"decode": {
            "failing": True}}, "fingerprint": "bbbb"},
        "r2": {"error": "not found"},            # no prober attached
    }
    statusz = {
        "r0": {"fingerprint": {"sha": "aaaa"}},
        "r1": {"fingerprint": {"sha": "bbbb"}},  # the drifted member
        "r2": {"fingerprint": {"sha": "aaaa"}},
    }
    agg._scrape_route = lambda route, decode, ok_codes=(): \
        dict(probez) if route == "/probez" else dict(statusz)
    out = agg.fleet_probez()
    assert out["summary"]["failing"] == ["r1"]
    assert out["summary"]["with_prober"] == 2
    assert out["summary"]["config_drift"]
    assert out["summary"]["fingerprints"]["r1"] == "bbbb"
    assert len(findings) == 1 and "config_drift" in findings[0]
    assert findings[0]["config_drift"]["fingerprints"]["r2"] == "aaaa"
    agg.fleet_probez()                           # sustained drift: one row
    assert len(findings) == 1
    statusz["r1"]["fingerprint"]["sha"] = "aaaa"  # drift repaired
    out = agg.fleet_probez()
    assert not out["summary"]["config_drift"]
    agg.fleet_probez()                           # re-entry fires again
    statusz["r1"]["fingerprint"]["sha"] = "cccc"
    agg.fleet_probez()
    assert len(findings) == 2
    agg.close()


def test_served_probez_route_and_fleet_scrape(served_model):
    m, _ = served_model
    eng = _engine(m)
    pr = Prober(eng, replica="r0").warm()
    srv = eng.serve_telemetry(prober=pr)
    try:
        agg = FleetAggregator({"r0": srv.url("/")}, cache_ttl=0.0)
        out = agg.fleet_probez()
        assert out["summary"]["with_prober"] == 1
        assert out["summary"]["failing"] == []
        sha = eng.fingerprint()["sha"]
        assert out["summary"]["fingerprints"] == {"r0": sha}
        assert out["per_replica"]["r0"]["state"] == "passing"
        assert "invariants" in out["per_replica"]["r0"]
        page = agg.merged_metrics()
        assert "paddle_tpu_probe_cycles_total" in page
        assert "paddle_tpu_invariant_audits_total" in page
        agg.close()
    finally:
        srv.close()


# ------------------------------------------------- straggler granularity

def test_stepmonitor_flushes_jsonl_on_straggler_transition(tmp_path):
    """Satellite (the r16 NOTE): with a buffered JSONL cadence, a
    straggler/straggler_clear transition forces the flush — a live
    load_shard_walls reader sees skew events at transition granularity,
    never `flush_every` rows late."""
    path = str(tmp_path / "shard_0.jsonl")
    mon = StepMonitor(jsonl_path=path, track_memory=False,
                      jsonl_flush_every=64, straggler_threshold=1.5)
    for step in range(1, 4):
        mon._emit({"step": step, "wall_s": 0.1})
    # buffered: nothing durable yet (3 rows < 64)
    assert not os.path.exists(path) or os.path.getsize(path) == 0
    mon.record_shard_steps({"0": 0.1, "1": 0.9}, step=4)
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert any("straggler" in r for r in lines)  # durable NOW
    assert len(lines) == 4                       # the buffer came along
    mon.record_shard_steps({"0": 0.1, "1": 0.1}, step=5)
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert any("straggler_clear" in r for r in lines)
    mon.close()
    walls = load_shard_walls({"0": path})
    assert set(walls) == {1, 2, 3}               # step rows stitch; the
    #                                              event rows are skipped


def test_stepmonitor_default_flush_unchanged(tmp_path):
    """flush_every=1 (the default) keeps the historical open-per-row
    behavior: every row durable immediately, no handle held."""
    path = str(tmp_path / "m.jsonl")
    mon = StepMonitor(jsonl_path=path, track_memory=False)
    mon._emit({"step": 1, "wall_s": 0.1})
    with open(path) as f:
        assert len(f.readlines()) == 1
    assert mon._jsonl_f is None
    mon.close()
