"""Distributed layer tests on the 8-virtual-device CPU mesh.

Mirrors the reference's three distributed-test mechanisms (SURVEY §4) in
single-process form: collective API checks (analog of unittests/collective/
runner scripts), hybrid-parallel model parity (analog of
hybrid_parallel_mp_model.py), and sharding-stage parity (analog of
dygraph_group_sharded_stage2/3.py) — all vs single-device ground truth
instead of N spawned processes.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DistributedStrategy
import paddle_tpu.nn as nn


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    dist.set_mesh(None)
    fleet._fleet_state.update(initialized=False, strategy=None, hcg=None)


def _world():
    dist.init_parallel_env()
    return dist.get_group()


class TestCollectives:
    def test_all_reduce_sum(self):
        g = _world()
        n = g.nranks
        x = paddle.to_tensor(np.arange(n * 3, dtype=np.float32).reshape(n, 3))
        expect = x.numpy().sum(0)
        dist.all_reduce(x)
        for r in range(n):
            np.testing.assert_allclose(x.numpy()[r], expect, rtol=1e-6)

    def test_all_reduce_max_avg(self):
        g = _world()
        n = g.nranks
        base = np.random.randn(n, 4).astype(np.float32)
        x = paddle.to_tensor(base.copy())
        dist.all_reduce(x, op=dist.ReduceOp.MAX)
        np.testing.assert_allclose(x.numpy()[0], base.max(0), rtol=1e-6)
        y = paddle.to_tensor(base.copy())
        dist.all_reduce(y, op=dist.ReduceOp.AVG)
        np.testing.assert_allclose(y.numpy()[-1], base.mean(0), rtol=1e-6)

    def test_all_gather(self):
        g = _world()
        n = g.nranks
        base = np.random.randn(n, 2).astype(np.float32)
        out = []
        dist.all_gather(out, paddle.to_tensor(base.copy()))
        assert len(out) == n
        for r in range(n):
            np.testing.assert_allclose(out[r].numpy(), base[r], rtol=1e-6)

    def test_broadcast(self):
        g = _world()
        n = g.nranks
        base = np.random.randn(n, 5).astype(np.float32)
        x = paddle.to_tensor(base.copy())
        dist.broadcast(x, src=2)
        for r in range(n):
            np.testing.assert_allclose(x.numpy()[r], base[2], rtol=1e-6)

    def test_reduce(self):
        g = _world()
        n = g.nranks
        base = np.random.randn(n, 3).astype(np.float32)
        x = paddle.to_tensor(base.copy())
        dist.reduce(x, dst=1)
        np.testing.assert_allclose(x.numpy()[1], base.sum(0), rtol=1e-5)
        np.testing.assert_allclose(x.numpy()[0], base[0], rtol=1e-6)

    def test_reduce_scatter(self):
        g = _world()
        n = g.nranks
        base = np.random.randn(n, n * 2).astype(np.float32)
        x = paddle.to_tensor(base.copy())
        dist.reduce_scatter(x)
        s = base.sum(0)  # [n*2]
        for r in range(n):
            np.testing.assert_allclose(x.numpy()[r], s[r * 2:(r + 1) * 2], rtol=1e-5)

    def test_alltoall(self):
        g = _world()
        n = g.nranks
        base = np.arange(n * n, dtype=np.float32).reshape(n, n)
        out = dist.alltoall(paddle.to_tensor(base.copy()))
        np.testing.assert_allclose(out.numpy(), base.T, rtol=1e-6)

    def test_scatter(self):
        g = _world()
        n = g.nranks
        base = np.arange(n * n * 2, dtype=np.float32).reshape(n, n * 2)
        x = paddle.to_tensor(base.copy())
        dist.scatter(x, src=1)
        for r in range(n):
            np.testing.assert_allclose(x.numpy()[r], base[1, r * 2:(r + 1) * 2])

    def test_send_recv(self):
        _world()
        a = paddle.to_tensor(np.float32([1, 2, 3]))
        out = paddle.to_tensor(np.zeros(3, np.float32))
        dist.send(a, dst=2)
        dist.recv(out, src=0, rank=2)
        np.testing.assert_allclose(out.numpy(), [1, 2, 3])

    def test_recompute_nontensor_args(self):
        """Non-Tensor positional args must not shift Tensor slots."""
        x = paddle.to_tensor(np.float32([10.0, 20.0]), stop_gradient=False)
        y = dist.recompute(lambda s, t: t * s, 3.0, x)
        np.testing.assert_allclose(y.numpy(), [30.0, 60.0])
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])

    def test_in_trace_collectives(self):
        """The production path: collectives inside shard_map-traced code."""
        from jax import shard_map
        g = _world()
        mesh = g.mesh

        def f(x):
            t = dist.all_reduce(paddle.Tensor(x), group=g)
            return t._data

        base = np.random.randn(g.nranks, 3).astype(np.float32)
        out = jax.jit(shard_map(f, mesh=mesh, in_specs=P(g.axis),
                                out_specs=P(g.axis)))(base)
        for r in range(g.nranks):
            np.testing.assert_allclose(np.asarray(out)[r], base.sum(0), rtol=1e-5)


class TestTopologyFleet:
    def test_fleet_init_hybrid(self):
        st = DistributedStrategy()
        st.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        fleet.init(is_collective=True, strategy=st)
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 4
        assert hcg.get_parallel_mode() == "model"
        assert hcg.get_model_parallel_group().nranks == 4
        m = dist.get_mesh()
        assert m.shape["dp"] == 2 and m.shape["mp"] == 4

    def test_mesh_axis_helpers(self):
        m = dist.build_mesh({"dp": 2, "mp": 4})
        with dist.mesh_scope(m):
            assert dist.mesh_axis_size("mp") == 4
            assert dist.mesh_axis_size("pp") == 1


class _TPMLP(nn.Layer):
    """Megatron-style block: column-parallel then row-parallel."""

    def __init__(self, d, h):
        super().__init__()
        self.fc1 = dist.ColumnParallelLinear(d, h, gather_output=False)
        self.fc2 = dist.RowParallelLinear(h, d, input_is_parallel=True)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


class TestTensorParallel:
    def test_mp_layers_math_single_device(self):
        """Without a mesh, TP layers are plain dense layers (the correctness
        reference, like OpTest comparing against numpy)."""
        paddle.seed(7)
        m = _TPMLP(8, 16)
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        y = m(x)
        w1, b1 = m.fc1.weight.numpy(), m.fc1.bias.numpy()
        w2, b2 = m.fc2.weight.numpy(), m.fc2.bias.numpy()
        ref = np.maximum(x.numpy() @ w1 + b1, 0) @ w2 + b2
        np.testing.assert_allclose(y.numpy(), ref, rtol=1e-5)

    def test_tp_training_matches_single_device(self):
        """hybrid dp2×mp4 TrainStep == single-device training (analog of
        hybrid_parallel_mp_model.py comparing distributed vs single loss)."""
        import paddle_tpu.optimizer as opt
        from paddle_tpu.jit.train_step import TrainStep

        def build():
            paddle.seed(3)
            return _TPMLP(8, 16)

        x = np.random.randn(8, 8).astype(np.float32)
        y = np.random.randn(8, 8).astype(np.float32)

        def loss_fn(pred, target):
            return ((pred - target) ** 2).mean()

        # single device ground truth
        m1 = build()
        o1 = opt.SGD(learning_rate=0.1, parameters=m1.parameters())
        s1 = TrainStep(m1, o1, lambda a, b: loss_fn(m1(a), b))
        losses1 = [float(s1(paddle.to_tensor(x), paddle.to_tensor(y))) for _ in range(3)]

        # hybrid mesh
        st = DistributedStrategy()
        st.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        fleet.init(strategy=st)
        m2 = build()
        o2 = opt.SGD(learning_rate=0.1, parameters=m2.parameters())
        s2 = TrainStep(m2, o2, lambda a, b: loss_fn(m2(a), b),
                       mesh=dist.get_mesh(), data_axes=("dp",))
        losses2 = [float(s2(paddle.to_tensor(x), paddle.to_tensor(y))) for _ in range(3)]
        np.testing.assert_allclose(losses1, losses2, rtol=2e-4)
        # weights actually sharded over mp
        shard = m2.fc1.weight._data.sharding
        assert shard.spec == P(None, "mp")

    def test_vocab_parallel_embedding_and_ce(self):
        paddle.seed(0)
        emb = dist.VocabParallelEmbedding(32, 8)
        ids = paddle.to_tensor(np.array([[1, 5], [7, 31]], dtype=np.int32))
        out = emb(ids)
        np.testing.assert_allclose(out.numpy(), emb.weight.numpy()[ids.numpy()], rtol=1e-6)

        ce = dist.ParallelCrossEntropy()
        logits = paddle.to_tensor(np.random.randn(4, 10).astype(np.float32))
        labels = paddle.to_tensor(np.array([1, 2, 3, 4], dtype=np.int64))
        loss = ce(logits, labels)
        lg = logits.numpy()
        ref = (np.log(np.exp(lg).sum(-1)) - lg[np.arange(4), labels.numpy()])
        np.testing.assert_allclose(loss.numpy().squeeze(-1), ref, rtol=1e-5)


class TestSharding:
    def test_group_sharded_stage3_parity(self):
        import paddle_tpu.optimizer as opt
        from paddle_tpu.jit.train_step import TrainStep

        def build():
            paddle.seed(11)
            return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 16))

        x = np.random.randn(8, 16).astype(np.float32)
        y = np.random.randn(8, 16).astype(np.float32)

        def mk_loss(m):
            return lambda a, b: ((m(a) - b) ** 2).mean()

        m1 = build()
        o1 = opt.AdamW(learning_rate=0.01, parameters=m1.parameters())
        s1 = TrainStep(m1, o1, mk_loss(m1))
        ref = [float(s1(paddle.to_tensor(x), paddle.to_tensor(y))) for _ in range(3)]

        st = DistributedStrategy()
        st.hybrid_configs = {"dp_degree": 2, "sharding_degree": 4}
        fleet.init(strategy=st)
        m2 = build()
        o2 = opt.AdamW(learning_rate=0.01, parameters=m2.parameters())
        m2, o2, _ = dist.group_sharded_parallel(m2, o2, level="p_g_os")
        s2 = TrainStep(m2, o2, mk_loss(m2), mesh=dist.get_mesh(), data_axes=("dp",))
        got = [float(s2(paddle.to_tensor(x), paddle.to_tensor(y))) for _ in range(3)]
        np.testing.assert_allclose(ref, got, rtol=2e-4)
        # params sharded over sdp (ZeRO-3)
        assert any("sdp" in str(p._data.sharding.spec) for p in m2.parameters())

    def test_stage1_opt_state_sharded(self):
        import paddle_tpu.optimizer as opt
        from paddle_tpu.jit.train_step import TrainStep
        st = DistributedStrategy()
        st.hybrid_configs = {"dp_degree": 2, "sharding_degree": 4}
        fleet.init(strategy=st)
        paddle.seed(1)
        m = nn.Linear(16, 32)
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        dist.shard_optimizer_state(o, stage=1)
        s = TrainStep(m, o, lambda a, b: ((m(a) - b) ** 2).mean(),
                      mesh=dist.get_mesh(), data_axes=("dp",))
        x = paddle.to_tensor(np.random.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(8, 32).astype(np.float32))
        s(x, y)
        spec = s._opt_state[0]["moment1"].sharding.spec
        assert "sdp" in str(spec)


class TestRecompute:
    def test_recompute_grads_match(self):
        paddle.seed(5)
        m = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 8))
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))

        y1 = m(x)
        y1.sum().backward()
        g_ref = [p.grad.numpy().copy() for p in m.parameters()]
        for p in m.parameters():
            p.clear_grad()

        y2 = dist.recompute(m, x)
        y2.sum().backward()
        g_rc = [p.grad.numpy() for p in m.parameters()]
        np.testing.assert_allclose(y1.numpy(), y2.numpy(), rtol=1e-6)
        for a, b in zip(g_ref, g_rc):
            # 2e-5: recompute replays the forward through a separate XLA
            # program; jax 0.4.37's CPU fusion choices land 1.2e-5 apart
            np.testing.assert_allclose(a, b, rtol=2e-5)


class TestPipeline:
    def test_pipeline_scan_matches_sequential(self):
        mesh = dist.build_mesh({"pp": 8})
        with dist.mesh_scope(mesh):
            S, M, D = 8, 4, 16
            rng = np.random.RandomState(0)
            ws = jnp.asarray(rng.randn(S, D, D).astype(np.float32) * 0.1)
            xs = jnp.asarray(rng.randn(M, 2, D).astype(np.float32))

            def stage_fn(w, x):
                return jnp.tanh(x @ w)

            out = dist.pipeline_scan(stage_fn, ws, xs, axis="pp", num_stages=S)
            ref = xs
            for s in range(S):
                ref = jnp.tanh(ref @ ws[s])
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)

    def test_pipeline_parallel_train_batch(self):
        st = DistributedStrategy()
        st.pipeline = True
        st.pipeline_configs = {"accumulate_steps": 2}
        st.hybrid_configs = {"dp_degree": 2, "pp_degree": 4}
        fleet.init(strategy=st)
        paddle.seed(2)
        import paddle_tpu.optimizer as opt
        model = dist.PipelineLayer(
            layers=[dist.LayerDesc(nn.Linear, 8, 8) for _ in range(4)],
            loss_fn=nn.MSELoss())
        pp = fleet.distributed_model(model)
        o = opt.SGD(learning_rate=0.05, parameters=model.parameters())
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        l0 = float(pp.train_batch((x, y), o))
        l1 = float(pp.train_batch((x, y), o))
        assert np.isfinite(l0) and np.isfinite(l1)
        assert l1 < l0


class TestStackedPipelineGPT:
    """The flagship pp path (VERDICT r1 #3): stacked-stage GPT through the
    compiled pipeline_spmd schedule on a dp×pp×mp mesh — loss/grad parity vs
    the layered single-device model, fleet routing, and the pp memory
    contract (per-device stacked-param shards are 1/(pp·mp) of the total:
    the reference 1F1B's reason to exist, meta_parallel/pipeline_parallel.py
    :117)."""

    def _cfg(self):
        from paddle_tpu.models import GPTConfig
        return GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                         num_heads=4, max_position_embeddings=16,
                         intermediate_size=64)

    def test_loss_and_grad_parity_vs_layered(self):
        from paddle_tpu.models import GPTForCausalLM, GPTStackedForCausalLM
        paddle.seed(3)
        m = GPTForCausalLM(self._cfg())
        sm = GPTStackedForCausalLM.from_layered(m)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 64, (4, 8)).astype("int32"))
        ref = float(m.loss(ids, ids))
        assert abs(float(sm.loss(ids, ids)) - ref) < 1e-5

        mesh = dist.build_mesh({"dp": 2, "pp": 2, "mp": 2})
        with dist.mesh_scope(mesh):
            pl = sm.loss(ids, ids, num_microbatches=2)
            assert abs(float(pl) - ref) < 1e-4
            pl.backward()
            g_pp = sm.qkv_w.grad.numpy().copy()
        for p in sm.parameters():
            p.clear_grad()
        l = sm.loss(ids, ids)
        l.backward()
        np.testing.assert_allclose(g_pp, sm.qkv_w.grad.numpy(),
                                   rtol=1e-4, atol=1e-6)

    def test_fleet_routes_compiled_pipeline_and_trains(self):
        from paddle_tpu.models import GPTStackedForCausalLM
        from paddle_tpu.distributed.pipeline import CompiledPipelineParallel
        import paddle_tpu.optimizer as opt
        st = DistributedStrategy()
        st.pipeline = True
        st.pipeline_configs = {"accumulate_steps": 2}
        st.hybrid_configs = {"dp_degree": 2, "pp_degree": 2, "mp_degree": 2}
        fleet.init(strategy=st)
        paddle.seed(4)
        sm = GPTStackedForCausalLM(self._cfg())
        pp = fleet.distributed_model(sm)
        assert isinstance(pp, CompiledPipelineParallel), \
            "stacked model must take the compiled pipeline, not eager GPipe"
        o = opt.AdamW(learning_rate=1e-3, parameters=sm.parameters())
        ids = paddle.to_tensor(
            np.random.RandomState(1).randint(0, 64, (4, 8)).astype("int32"))
        l0 = float(pp.train_batch((ids, ids), o))
        losses = [float(pp.train_batch((ids, ids), o)) for _ in range(4)]
        assert np.isfinite(l0) and all(np.isfinite(x) for x in losses)
        assert losses[-1] < l0, (l0, losses)
        # pp memory contract: each device holds 1/(pp·mp) of the stacked
        # block weights (pspec P("pp", None, "mp")) — the point of pp
        qkv = sm.qkv_w._data
        shard = qkv.addressable_shards[0].data
        assert shard.size * 4 == qkv.size, (shard.shape, qkv.shape)

    def test_pipeline_activation_memory_bounded(self):
        """Scan-carry activations hold ONE microbatch per stage slot (the
        1F1B live-set shape), so the pipeline buffer does not scale with M:
        jaxpr-level check on the carry shapes."""
        from paddle_tpu.models import GPTStackedForCausalLM
        paddle.seed(5)
        sm = GPTStackedForCausalLM(self._cfg())
        mesh = dist.build_mesh({"dp": 4, "pp": 2})
        import jax as _jax
        from paddle_tpu.jit.api import _swap_params, _trace_guard
        from paddle_tpu.core import autograd as _ag

        params = [p for _, p in sm.named_parameters()]

        def loss_of(arrs, ids):
            with _trace_guard(), _swap_params(params, list(arrs)), _ag.no_grad():
                return sm.loss(paddle.Tensor(ids), paddle.Tensor(ids),
                               num_microbatches=4)._data

        with dist.mesh_scope(mesh):
            ids = jnp.zeros((8, 8), jnp.int32)
            jaxpr = _jax.make_jaxpr(loss_of)(
                [p._data for p in params], ids)
        # the pipeline scan's activation buffer is [pp, mb, s, H]; with
        # B=8, M=4 → mb=2: buffer 2*... not 8*... (M-independent)
        txt = str(jaxpr)
        assert "2,2,8,32" in txt.replace(" ", ""), \
            "expected [pp=2, mb=2, s=8, H=32] pipeline buffer in jaxpr"


# jaxlib<0.5's SPMD partitioner rejects the PartitionId that axis_index
# lowers to inside a PARTIAL-manual shard_map body (manual pp, auto dp/mp)
# — the formulation pipeline_scan_interleaved needs; data-derived stage ids
# make that jaxlib hard-abort instead. Runtime-gate the two tests that
# compile it.
_partial_manual_shard_map_ok = pytest.mark.skipif(
    tuple(int(x) for x in __import__("jax").__version__.split(".")[:2])
    < (0, 5),
    reason="partial-manual shard_map axis_index unsupported on jaxlib<0.5")


class TestInterleavedPipelineGPT:
    """Interleaved virtual-stage pipeline wired into the flagship path
    (VERDICT r2 #3; reference PipelineParallelWithInterleave,
    pipeline_parallel.py:461-761): loss parity vs the plain schedule AND
    the layered model on a hybrid dp×pp×mp mesh, fleet strategy routing,
    and the bubble-accounting claim."""

    def _cfg(self):
        from paddle_tpu.models import GPTConfig
        return GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                         num_heads=4, max_position_embeddings=16,
                         intermediate_size=64)

    @_partial_manual_shard_map_ok
    def test_interleaved_loss_and_grad_parity(self):
        from paddle_tpu.models import GPTForCausalLM, GPTStackedForCausalLM
        paddle.seed(7)
        m = GPTForCausalLM(self._cfg())
        sm = GPTStackedForCausalLM.from_layered(m)
        ids = paddle.to_tensor(
            np.random.RandomState(2).randint(0, 64, (4, 8)).astype("int32"))
        ref = float(m.loss(ids, ids))

        mesh = dist.build_mesh({"dp": 2, "pp": 2, "mp": 2})
        with dist.mesh_scope(mesh):
            plain = sm.loss(ids, ids, num_microbatches=2)
            inter = sm.loss(ids, ids, num_microbatches=2, num_virtual=2)
            assert abs(float(inter) - ref) < 1e-4, (float(inter), ref)
            assert abs(float(inter) - float(plain)) < 1e-5
            inter.backward()
            g_i = sm.qkv_w.grad.numpy().copy()
        for p in sm.parameters():
            p.clear_grad()
        l = sm.loss(ids, ids)
        l.backward()
        np.testing.assert_allclose(g_i, sm.qkv_w.grad.numpy(),
                                   rtol=1e-4, atol=1e-6)

    @_partial_manual_shard_map_ok
    def test_fleet_interleave_flag_routes_and_trains(self):
        from paddle_tpu.models import GPTStackedForCausalLM
        from paddle_tpu.distributed.pipeline import CompiledPipelineParallel
        import paddle_tpu.optimizer as opt
        st = DistributedStrategy()
        st.pipeline = True
        st.pipeline_configs = {"accumulate_steps": 2, "interleave": 2}
        st.hybrid_configs = {"dp_degree": 2, "pp_degree": 2, "mp_degree": 2}
        fleet.init(strategy=st)
        paddle.seed(8)
        sm = GPTStackedForCausalLM(self._cfg())
        pp = fleet.distributed_model(sm)
        assert isinstance(pp, CompiledPipelineParallel)
        assert pp.num_virtual == 2
        o = opt.AdamW(learning_rate=1e-3, parameters=sm.parameters())
        ids = paddle.to_tensor(
            np.random.RandomState(3).randint(0, 64, (4, 8)).astype("int32"))
        l0 = float(pp.train_batch((ids, ids), o))
        losses = [float(pp.train_batch((ids, ids), o)) for _ in range(4)]
        assert np.isfinite(l0) and all(np.isfinite(x) for x in losses)
        assert losses[-1] < l0, (l0, losses)

    def test_interleaved_bubble_accounting(self):
        """The schedule's cost model: T ticks of ONE chunk each. For V>1
        the total chunk-time cost M·V+S-1 (M=k·S) is strictly below the
        plain schedule's V·(M+S-1) — the interleave bubble reduction; at
        V=1 the two coincide."""
        from paddle_tpu.distributed.pipeline import interleaved_ticks
        for S in (2, 4):
            for M in (S, 2 * S, 4 * S):
                assert interleaved_ticks(M, S, 1) == M + S - 1
                for V in (2, 4):
                    ticks = interleaved_ticks(M, S, V)
                    assert ticks == M * V + S - 1
                    assert ticks < V * (M + S - 1)
                    # bubble fraction shrinks ~1/V (fill cost S-1 chunks
                    # instead of V*(S-1))
                    bubble_i = (ticks - M * V) / ticks
                    bubble_p = (S - 1) / (M + S - 1)
                    assert bubble_i < bubble_p
