"""OpTest — the framework's op-correctness harness.

Reference (SURVEY §4): unittests/op_test.py:327 — a test declares inputs/
attrs, `check_output` runs the op through BOTH executors (static + dygraph)
comparing against a numpy reference, and `check_grad` compares analytic
gradients against numeric finite differences (delta=0.005). This harness
keeps that exact contract for the TPU build:

- check_output: eager path AND recorded-static path (the two executors
  here) vs the numpy reference
- check_grad: tape-analytic grads vs central finite differences

Usage:
    class TestExp(OpTest):
        def config(self):
            self.op = paddle.exp
            self.inputs = {"x": np.random.rand(3, 4).astype("float32")}
            self.ref = np.exp
    ...
    t = TestExp(); t.check_output(); t.check_grad(["x"])
"""
from __future__ import annotations

from typing import Callable, Dict, List, Sequence

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import static


class OpTest:
    op: Callable = None
    inputs: Dict[str, np.ndarray] = None
    attrs: Dict = None
    ref: Callable = None
    rtol: float = 1e-5
    atol: float = 1e-6
    grad_rtol: float = 1e-2
    grad_atol: float = 1e-3
    numeric_delta: float = 5e-3   # reference: numeric_grad_delta=0.005
    check_static: bool = True     # dual-executor check (skip for ops whose
                                  # python fallback needs concrete values)

    def __init__(self):
        self.attrs = self.attrs or {}
        self.config()

    def config(self):
        raise NotImplementedError

    # -- helpers -----------------------------------------------------------
    def _run_eager(self, inputs):
        tensors = {k: paddle.to_tensor(v) for k, v in inputs.items()}
        out = self.op(*tensors.values(), **(self.attrs or {}))
        return out

    def _run_static(self, inputs):
        """The 'other executor': record the op into a Program and replay it
        through the static Executor (the dual-executor check of the
        reference's check_output_with_place)."""
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                feeds = {k: static.data(k, list(v.shape), str(v.dtype))
                         for k, v in inputs.items()}
                out = self.op(*feeds.values(), **(self.attrs or {}))
            exe = static.Executor()
            outs = out if isinstance(out, (tuple, list)) else [out]
            res = exe.run(main, feed=dict(inputs), fetch_list=list(outs))
            return res if len(res) > 1 else res[0]
        finally:
            paddle.disable_static()

    def _run_jit(self, inputs):
        """The THIRD executor: the op traced inside an outer jax.jit (the
        framework-wide trace-safety check VERDICT r2 #5 asked for — host
        fallbacks that materialize values explode here, not in a user's
        to_static model)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core.tensor import Tensor

        keys = list(inputs)

        def f(*arrs):
            ts = [Tensor(a) for a in arrs]
            out = self.op(*ts, **(self.attrs or {}))
            outs = out if isinstance(out, (tuple, list)) else [out]
            return tuple(o._data if isinstance(o, Tensor) else jnp.asarray(o)
                         for o in outs)

        res = jax.jit(f)(*[jnp.asarray(inputs[k]) for k in keys])
        return list(res)

    def check_jit(self):
        """Outputs under an outer jax.jit match the reference."""
        want = self.ref(*self.inputs.values(), **(self.attrs or {}))
        wants = list(want) if isinstance(want, (tuple, list)) else [want]
        got = self._run_jit(self.inputs)
        for w, g in zip(wants, got):
            np.testing.assert_allclose(np.asarray(g), w, rtol=self.rtol,
                                       atol=self.atol, err_msg="under-jit")

    # -- checks ------------------------------------------------------------
    def check_output(self):
        want = self.ref(*self.inputs.values(), **(self.attrs or {}))
        multi = isinstance(want, (tuple, list))

        got_eager = self._run_eager(self.inputs)
        if not self.check_static:
            outs = got_eager if multi else [got_eager]
            wants = want if multi else [want]
            for w, ge in zip(wants, outs):
                np.testing.assert_allclose(ge.numpy(), w, rtol=self.rtol,
                                           atol=self.atol, err_msg="eager")
            return
        got_static = self._run_static(self.inputs)
        if multi:
            for w, ge, gs in zip(want, got_eager, [got_static] if not
                                 isinstance(got_static, list) else got_static):
                np.testing.assert_allclose(ge.numpy(), w, rtol=self.rtol,
                                           atol=self.atol, err_msg="eager")
                np.testing.assert_allclose(gs, w, rtol=self.rtol,
                                           atol=self.atol, err_msg="static")
        else:
            np.testing.assert_allclose(got_eager.numpy(), want, rtol=self.rtol,
                                       atol=self.atol, err_msg="eager")
            np.testing.assert_allclose(np.asarray(got_static), want,
                                       rtol=self.rtol, atol=self.atol,
                                       err_msg="static")

    def check_grad(self, inputs_to_check: Sequence[str], output_grad=None):
        """Analytic (tape) vs central finite-difference gradients of
        sum(op(inputs) * output_grad)."""
        og = output_grad

        def scalar_loss(arrays: Dict[str, np.ndarray]) -> float:
            tensors = {k: paddle.to_tensor(v.astype(np.float64).astype(v.dtype))
                       for k, v in arrays.items()}
            out = self.op(*tensors.values(), **(self.attrs or {}))
            out = out[0] if isinstance(out, (tuple, list)) else out
            w = 1.0 if og is None else og
            return float((out * w).sum().numpy())

        # analytic
        tensors = {k: paddle.to_tensor(v) for k, v in self.inputs.items()}
        for k in inputs_to_check:
            tensors[k].stop_gradient = False
        out = self.op(*tensors.values(), **(self.attrs or {}))
        out = out[0] if isinstance(out, (tuple, list)) else out
        w = 1.0 if og is None else paddle.to_tensor(og)
        (out * w).sum().backward()

        for k in inputs_to_check:
            analytic = tensors[k].grad.numpy().astype(np.float64)
            numeric = np.zeros_like(analytic, dtype=np.float64)
            base = {kk: vv.copy() for kk, vv in self.inputs.items()}
            flat = base[k].reshape(-1)
            num_flat = numeric.reshape(-1)
            d = self.numeric_delta
            for i in range(flat.size):
                orig = flat[i]
                flat[i] = orig + d
                hi = scalar_loss(base)
                flat[i] = orig - d
                lo = scalar_loss(base)
                flat[i] = orig
                num_flat[i] = (hi - lo) / (2 * d)
            np.testing.assert_allclose(
                analytic, numeric, rtol=self.grad_rtol, atol=self.grad_atol,
                err_msg=f"grad mismatch for input {k!r}")
