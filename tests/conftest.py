"""Test harness configuration.

Mirrors the reference's test strategy (SURVEY §4): numeric checks against a
CPU reference + a virtual multi-device mesh for distributed logic (the analog
of TestDistBase's single-host multi-process clusters, test_dist_base.py:899) —
here an 8-device XLA host platform, so sharding/collective tests run without
TPU hardware.

MUST run before jax backend initialization: forces CPU with 8 virtual
devices and 'highest' matmul precision so numpy comparisons are exact-ish
(the production default keeps the TPU-native bf16-pass matmuls).
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)
    import paddle_tpu
    paddle_tpu.seed(0)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-minute tests (subprocess clusters, detector "
        "training) — `-m 'not slow'` gives the quick pass")
    config.addinivalue_line(
        "markers", "heavy: compile-heavy batches (numeric-grad sweep, "
        "under-jit sweep, model trainings); the SMOKE tier is "
        "`-m 'not slow and not heavy'` and finishes <5 min on one core "
        "(reference testslist.csv RUN_TYPE labels)")
