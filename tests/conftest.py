"""Test harness configuration.

Mirrors the reference's test strategy (SURVEY §4): numeric checks against a
CPU reference + a virtual multi-device mesh for distributed logic (the analog
of TestDistBase's single-host multi-process clusters, test_dist_base.py:899) —
here an 8-device XLA host platform, so sharding/collective tests run without
TPU hardware.

MUST run before jax backend initialization: forces CPU with 8 virtual
devices and 'highest' matmul precision so numpy comparisons are exact-ish
(the production default keeps the TPU-native bf16-pass matmuls).
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)
    import paddle_tpu
    paddle_tpu.seed(0)


# ---------------------------------------------------------------------------
# Tier table (reference: testslist.csv RUN_TYPE labels). The SMOKE tier
# (`-m 'not slow and not heavy'`) keeps at least one representative per
# subsystem and finishes <5 min on one core; everything matching a pattern
# below joins the heavy tier (compile-heavy trainings/parities), on top of
# tests explicitly marked slow/heavy in their files.
_HEAVY_PATTERNS = (
    # vision: model-zoo forwards + trainings (transforms/box-op math stays)
    "test_vision.py::test_model_forward_shape",
    "test_vision.py::test_more_zoo_constructs",
    "test_vision.py::test_swin_",
    "test_vision.py::test_vgg_forward",
    "test_vision.py::test_train_step_resnet18",
    "test_vision.py::TestDetectionOpsTail::test_generate_proposals",
    # GPT model family: parities/moe/int8 (core fwd+bwd+train stays)
    "test_models_gpt.py::test_generate_kv_cache",
    "test_models_gpt.py::test_recompute_parity",
    "test_models_gpt.py::test_hybrid_tp_parity",
    "test_models_gpt.py::test_gpt_moe_",
    "test_models_gpt.py::test_adam_int8_moments_train",
    "test_models_gpt.py::test_int8_moments_on_sharded_mesh",
    "test_models_gpt.py::test_adam_selective_q8",
    # distributed: multi-device trainings/parities (collectives/topology/
    # mesh math/bubble accounting stay)
    "test_distributed.py::TestTensorParallel::test_tp_training_matches",
    "test_distributed.py::TestSharding::test_group_sharded_stage3",
    "test_distributed.py::TestRecompute",
    "test_distributed.py::TestPipeline::test_pipeline_parallel_train_batch",
    "test_distributed.py::TestStackedPipelineGPT",
    "test_distributed.py::TestInterleavedPipelineGPT::test_interleaved_loss",
    "test_distributed.py::TestInterleavedPipelineGPT::test_fleet_interleave",
    # launch CLI: subprocess spawns (store + one basic launch stay)
    "test_launch_elastic.py::test_launch_restarts",
    "test_launch_elastic.py::test_launch_fails_without",
    "test_launch_elastic.py::test_launch_jax_distributed",
    "test_launch_elastic.py::test_launch_multihost",
    "test_launch_elastic.py::test_launch_rpc_mode",
    # hapi/moe/sp/nn trainings
    "test_hapi.py::test_fit_evaluate_predict",
    "test_hapi.py::test_model_fit_fused_step",
    "test_hapi.py::test_early_stopping_saves_best",
    "test_moe_incubate.py::TestMoE::test_switch_router_learns",
    "test_moe_incubate.py::TestMoE::test_moe_model_trains",
    "test_moe_incubate.py::TestMoE::test_ep_mesh_parity",
    "test_moe_incubate.py::TestFusedLayers::test_encoder_layer_and_stack",
    "test_moe_incubate.py::TestFusedLayers::test_multi_transformer_cached",
    "test_moe_incubate.py::TestIncubateOptimizers::test_distributed_fused",
    "test_sequence_parallel.py::test_sp_attention_matches_dense",
    "test_sequence_parallel.py::test_gpt_step_with_sp_axis",
    "test_nn_extras.py::test_conv2d_transpose_matches_numpy_scatter",
    "test_nn_extras.py::test_pool3d_and_adaptive",
    "test_dgc.py::TestDGC::test_training_converges",
    # r3 re-tier (measured 844s on a shared 1-core container): the
    # slowest trainings/subprocess/worker tests whose subsystems keep a
    # faster representative in the smoke tier
    "test_ps_rpc.py::TestPsRuntime::test_launch_ps_mode_end_to_end",
    "test_models_bert_vit.py::TestBert::test_cls_learns_toy_task",
    "test_models_bert_vit.py::test_ernie_classification_and_mlm",
    "test_models_bert_vit.py::TestViT::test_learns_toy_task",
    "test_models_bert_vit.py::test_bert_fused_mlm_loss_matches_unfused",
    "test_native_pipeline.py::test_dataloader_process_workers",
    "test_native_pipeline.py::test_dataloader_worker_init_fn_ids",
    "test_native_pipeline.py::test_dataloader_persistent_workers_reused",
    "test_native_pipeline.py::test_dataloader_process_workers_custom_collate",
    "test_inference_capi.py::test_c_multi_input_output",
    "test_inference_capi.py::test_c_error_paths",
    "test_inference_capi.py::test_c_runs_int8_payload_artifact",
    "test_launch_elastic.py::test_launch_two_procs_single_node",
    # r7 audit: the onnx numpy-evaluator parities went from protoc-skip to
    # RUNNING on this image (runtime-descriptor fallback) — the python-loop
    # conv/attention evaluators are the slow part (25s + 9s + 8s); the
    # format/wire tests stay in smoke
    "test_onnx_export.py::TestOnnxTransformerExport::test_bert_base_encoder",
    "test_onnx_export.py::TestOnnxTransformerExport::test_gpt_decoder_block",
    "test_onnx_export.py::TestOnnxExport::test_convnet_roundtrip",
    # r9: tests/test_serving.py measured 7.3s total non-slow on this
    # container (module-scoped model shares the serving executables) — no
    # heavy entries needed; its open-loop load-generation test is marked
    # slow in-file per the tier contract.
)


# nodeid -> marker names, filled at collection; consumed by the duration
# recorder below (report objects don't carry the item)
_ITEM_MARKERS = {}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if any(p in item.nodeid for p in _HEAVY_PATTERNS):
            item.add_marker(pytest.mark.heavy)
        _ITEM_MARKERS[item.nodeid] = sorted(
            {m.name for m in item.iter_markers()})


def pytest_runtest_logreport(report):
    """Wall-time ledger for tools/check_tiers.py: with
    PADDLE_TPU_TIER_DURATIONS=<path> set, append one JSONL row per test
    call (nodeid, duration, markers, outcome). tools/run_tier1.sh sets the
    env around the canonical tier-1 command and runs the checker on the
    result — the guard that keeps tier-1 under its 870s cap."""
    path = os.environ.get("PADDLE_TPU_TIER_DURATIONS")
    if not path or report.when != "call":
        return
    import json
    row = {"nodeid": report.nodeid,
           "duration": round(report.duration, 3),
           "markers": _ITEM_MARKERS.get(report.nodeid, []),
           "outcome": report.outcome}
    with open(path, "a") as f:
        f.write(json.dumps(row) + "\n")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-minute tests (subprocess clusters, detector "
        "training) — `-m 'not slow'` gives the quick pass")
    config.addinivalue_line(
        "markers", "heavy: compile-heavy batches (numeric-grad sweep, "
        "under-jit sweep, model trainings); the SMOKE tier is "
        "`-m 'not slow and not heavy'` — ~5 min on an unshared core, "
        "~10 min on a time-shared container core "
        "(reference testslist.csv RUN_TYPE labels)")
