"""PS production depth (VERDICT r1 missing #2): CTR accessor lifecycle,
disk-spill tier for tables beyond RAM, and kill-and-restore durability.

Reference: fluid/distributed/ps/table/ctr_accessor.cc (show/click decay +
shrink), ssd_sparse_table.cc (rocksdb cold tier), memory_sparse_table.cc
Save/Load (shard files)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (
    CtrAccessor, CtrSparseTable, DiskSpillSparseTable, SparseTable)


class TestCtrAccessor:
    def test_show_click_decay_and_shrink(self):
        t = CtrSparseTable(dim=4, accessor=CtrAccessor(
            nonclk_coeff=0.1, click_coeff=1.0, show_click_decay_rate=0.5,
            delete_threshold=0.05))
        ids = np.arange(10)
        t.pull(ids)
        # feature 0..4 get clicks, 5..9 only shows
        t.push_show_click(ids, shows=np.ones(10),
                          clicks=(ids < 5).astype(np.float32))
        assert len(t) == 10
        assert t.shrink() == 0                   # fresh counters keep all
        # decay several passes: non-clicked features (score 0.1·show) fall
        # below 0.05 while clicked ones (score ≈ click) survive
        for _ in range(4):
            t.decay()
        dropped = t.shrink()
        assert dropped == 5, dropped             # the never-clicked tail
        assert len(t) == 5
        # clicked features keep their rows intact through compaction
        rows = t.pull(np.arange(5))
        assert rows.shape == (5, 4)

    def test_ctr_save_load_keeps_counters(self):
        t = CtrSparseTable(dim=4)
        t.pull(np.arange(6))
        t.push_show_click(np.arange(6), np.full(6, 3.0), np.full(6, 1.0))
        path = os.path.join(tempfile.mkdtemp(), "ctr")
        t.save(path)
        t2 = CtrSparseTable(dim=4)
        t2.load(path)
        assert len(t2) == 6
        s2 = t2._show[t2._slots(np.arange(6), create=False)]
        np.testing.assert_allclose(s2, 3.0)


class TestDiskSpill:
    def test_beyond_ram_exact_trajectory(self):
        """A table capped at 16 RAM rows must follow the identical adagrad
        trajectory as an unbounded table across 200 touched ids."""
        rng = np.random.RandomState(0)
        ram = DiskSpillSparseTable(dim=4, max_ram_rows=16, lr=0.1, seed=0)
        ref = SparseTable(dim=4, lr=0.1, seed=0)
        for step in range(6):
            ids = rng.randint(0, 200, 32)
            # identical first-touch order
            a = ram.pull(ids)
            b = ref.pull(ids)
            np.testing.assert_allclose(a, b, rtol=1e-6, err_msg=f"step {step}")
            g = rng.randn(32, 4).astype(np.float32)
            ram.push(ids, g)
            ref.push(ids, g)
        assert len(ram) == len(ref)
        assert len(ram._slot_of) <= 16 + 4       # RAM tier stays bounded
        assert len(ram._disk_index) > 0          # tail actually on disk

    def test_save_load_both_tiers(self):
        t = DiskSpillSparseTable(dim=4, max_ram_rows=8, lr=0.1, seed=0)
        ids = np.arange(40)
        t.pull(ids)
        t.push(ids, np.ones((40, 4), np.float32))
        want = t.pull(ids)
        path = os.path.join(tempfile.mkdtemp(), "spill")
        t.save(path)
        t2 = DiskSpillSparseTable(dim=4, max_ram_rows=8, seed=0)
        t2.load(path)
        got = t2.pull(ids)
        np.testing.assert_allclose(got, want, rtol=1e-6)


class TestKillAndRestore:
    def test_training_loss_continuous_across_restore(self):
        """Kill-and-restore keeps the loss trajectory identical: train 3
        steps, checkpoint, 'crash' (drop the object), restore, continue —
        the continued losses equal an uninterrupted run's."""
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.ps import DistributedEmbedding

        def run(restore_at=None, ckpt=None):
            paddle.seed(0)
            emb = DistributedEmbedding(dim=8, num_shards=2, lr=0.05, seed=0)
            tower = nn.Linear(8, 1)
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=tower.parameters())
            rng = np.random.RandomState(0)
            # warm ALL ids up front so post-restore steps never create fresh
            # rows (row init draws from each table's rng, whose state is not
            # part of the checkpoint — same contract as the reference's
            # table Save/Load, which persists rows, not RNG)
            emb(paddle.to_tensor(np.arange(50).reshape(-1, 1)))
            losses = []
            for step in range(6):
                if restore_at is not None and step == restore_at:
                    # crash: rebuild everything from the checkpoint
                    emb = DistributedEmbedding(dim=8, num_shards=2, lr=0.05,
                                               seed=0)
                    emb.load(ckpt + "/emb")
                    tower = nn.Linear(8, 1)
                    tower.set_state_dict(paddle.load(ckpt + "/tower.pd"))
                    opt = paddle.optimizer.SGD(learning_rate=0.1,
                                               parameters=tower.parameters())
                ids = rng.randint(0, 50, (16, 1))
                y = (ids % 2).astype(np.float32)
                feats = emb(paddle.to_tensor(ids))[:, 0]
                loss = nn.MSELoss()(tower(feats), paddle.to_tensor(y))
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
                if ckpt is not None and restore_at is None and step == 2:
                    emb.save(ckpt + "/emb")
                    paddle.save(tower.state_dict(), ckpt + "/tower.pd")
            return losses

        ckpt = tempfile.mkdtemp()
        base = run(ckpt=ckpt)                    # uninterrupted + checkpoint
        resumed = run(restore_at=3, ckpt=ckpt)   # crash after step 2
        np.testing.assert_allclose(resumed[3:], base[3:], rtol=1e-6,
                                   err_msg=(base, resumed))
