"""Sparse / quantization / text / audio / flags coverage (SURVEY §2.3)."""
import os
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import sparse, quantization as Q, text, audio


# ----------------------------------------------------------------- sparse
def test_coo_roundtrip_and_values_grad():
    dense = np.array([[1., 0., 2.], [0., 3., 0.]], np.float32)
    x = paddle.to_tensor(dense)
    x.stop_gradient = False
    coo = x.to_sparse_coo()
    assert coo.is_sparse_coo() and coo.nnz() == 3
    np.testing.assert_allclose(coo.to_dense().numpy(), dense)
    # grad flows dense -> sparse -> dense
    y = sparse.relu(coo).to_dense().sum()
    y.backward()
    assert x.grad is not None
    np.testing.assert_allclose(x.grad.numpy(), (dense > 0).astype(np.float32))


def test_csr_roundtrip():
    dense = np.array([[1., 0., 2.], [0., 3., 0.]], np.float32)
    csr = paddle.to_tensor(dense).to_sparse_csr()
    assert csr.is_sparse_csr()
    np.testing.assert_allclose(np.asarray(csr.crows_), [0, 2, 3])
    np.testing.assert_allclose(csr.to_dense().numpy(), dense)
    coo = csr.to_sparse_coo()
    np.testing.assert_allclose(coo.to_dense().numpy(), dense)


def test_sparse_matmul_and_masked_matmul():
    rng = np.random.RandomState(0)
    dense = (rng.rand(4, 6) > 0.5).astype(np.float32) * rng.randn(4, 6).astype(np.float32)
    y = rng.randn(6, 3).astype(np.float32)
    coo = paddle.to_tensor(dense).to_sparse_coo()
    out = sparse.matmul(coo, paddle.to_tensor(y))
    np.testing.assert_allclose(out.numpy(), dense @ y, rtol=1e-5, atol=1e-6)

    a = rng.randn(4, 5).astype(np.float32)
    b = rng.randn(5, 6).astype(np.float32)
    mm = sparse.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b), coo)
    full = a @ b
    mask = (dense != 0)
    np.testing.assert_allclose(mm.to_dense().numpy(), full * mask, rtol=1e-4,
                               atol=1e-5)


def test_sparse_add_same_pattern():
    d = np.array([[1., 0.], [0., 2.]], np.float32)
    a = paddle.to_tensor(d).to_sparse_coo()
    b = paddle.to_tensor(d * 3).to_sparse_coo()
    np.testing.assert_allclose(sparse.add(a, b).to_dense().numpy(), d * 4)


def test_sparse_softmax_rows():
    d = np.array([[1., 0., 2.], [0., 5., 0.]], np.float32)
    coo = paddle.to_tensor(d).to_sparse_coo()
    sm = sparse.nn.Softmax()(coo)
    out = sm.to_dense().numpy()
    # row 0: softmax over [1,2]; row 1: single entry -> 1.0
    e = np.exp([1., 2.])
    np.testing.assert_allclose(out[0, [0, 2]], e / e.sum(), rtol=1e-5)
    np.testing.assert_allclose(out[1, 1], 1.0, rtol=1e-6)


# ----------------------------------------------------------- quantization
def test_fake_quant_ste_grad():
    x = paddle.to_tensor(np.linspace(-1, 1, 11).astype(np.float32))
    x.stop_gradient = False
    y = Q.fake_quant(x, paddle.to_tensor(1.0), bit_length=8)
    err = np.abs(y.numpy() - x.numpy()).max()
    assert err < 1 / 127 + 1e-6  # quantized to ~1/127 grid
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones(11), rtol=1e-6)  # STE


def test_qat_quantize_and_convert():
    model = nn.Sequential(nn.Linear(8, 4), nn.ReLU(), nn.Linear(4, 2))
    q = Q.QAT(Q.QuantConfig())
    qmodel = q.quantize(model)
    x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
    out = qmodel(x)
    assert list(out.shape) == [2, 2]
    # training still works through fake-quant
    loss = out.sum()
    loss.backward()
    deployed = q.convert(qmodel)
    out2 = deployed(x)
    assert list(out2.shape) == [2, 2]


def test_ptq_observe():
    model = nn.Sequential(nn.Linear(4, 4))
    p = Q.PTQ()
    qm = p.quantize(model)
    for _ in range(3):
        qm(paddle.to_tensor(np.random.randn(2, 4).astype(np.float32)))
    p.convert(qm)


# ------------------------------------------------------------------- text
def test_viterbi_decode_matches_bruteforce():
    rng = np.random.RandomState(0)
    B, T, N = 2, 4, 5
    emis = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    scores, paths = text.viterbi_decode(
        paddle.to_tensor(emis), paddle.to_tensor(trans),
        include_bos_eos_tag=False)
    # brute force
    import itertools
    for b in range(B):
        best, best_path = -1e9, None
        for path in itertools.product(range(N), repeat=T):
            s = emis[b, 0, path[0]]
            for t in range(1, T):
                s += trans[path[t - 1], path[t]] + emis[b, t, path[t]]
            if s > best:
                best, best_path = s, path
        np.testing.assert_allclose(scores.numpy()[b], best, rtol=1e-5)
        assert tuple(paths.numpy()[b]) == best_path


# ------------------------------------------------------------------ audio
def test_mel_spectrogram_shapes_and_energy():
    sr = 16000
    t = np.linspace(0, 1, sr, endpoint=False)
    wav = np.sin(2 * np.pi * 440 * t).astype(np.float32)[None, :]
    mel = audio.features.MelSpectrogram(sr=sr, n_fft=512, n_mels=40)
    out = mel(paddle.to_tensor(wav))
    assert out.shape[0] == 1 and out.shape[1] == 40
    m = out.numpy()[0]
    # energy concentrates near 440 Hz's mel bin
    peak_bin = m.sum(axis=1).argmax()
    freqs = audio.mel_frequencies(42, 50.0, sr / 2)
    assert 300 < freqs[peak_bin + 1] < 700


def test_mfcc_runs():
    wav = np.random.randn(2, 8000).astype(np.float32)
    mfcc = audio.features.MFCC(sr=8000, n_mfcc=13, n_fft=256)
    out = mfcc(paddle.to_tensor(wav))
    assert out.shape[0] == 2 and out.shape[1] == 13


def test_fbank_matrix_rows_normalized():
    fb = audio.compute_fbank_matrix(16000, 512, n_mels=26)
    assert fb.shape == (26, 257)
    assert (fb >= 0).all() and fb.sum(axis=1).min() > 0


# ------------------------------------------------------------------ flags
def test_flags_nan_inf_check():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError):
            _ = paddle.log(x * 0 - 1)  # log(-1) = nan
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
    assert paddle.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is False


class TestAudioBackendAndDatasets:
    def _write_wavs(self, tmp, names, sr=16000, n=1600):
        import paddle_tpu.audio as audio
        paths = []
        rng = np.random.RandomState(0)
        for name in names:
            p = os.path.join(tmp, name)
            audio.save(p, rng.uniform(-0.5, 0.5, n).astype("float32"), sr)
            paths.append(p)
        return paths

    def test_wav_save_load_info_roundtrip(self, tmp_path):
        import paddle_tpu.audio as audio
        sr, n = 8000, 800
        x = np.sin(np.linspace(0, 40 * np.pi, n)).astype("float32") * 0.7
        p = str(tmp_path / "tone.wav")
        audio.save(p, x, sr)
        meta = audio.info(p)
        assert (meta.sample_rate, meta.num_samples, meta.num_channels) == \
            (sr, n, 1)
        y, sr2 = audio.load(p)
        assert sr2 == sr and y.shape == (1, n)
        np.testing.assert_allclose(y[0], x, atol=1e-3)

    def test_esc50_fold_split_and_labels(self, tmp_path):
        from paddle_tpu.audio.datasets import ESC50
        names = ["1-100-A-0.wav", "1-101-A-7.wav", "2-200-B-3.wav",
                 "3-300-C-49.wav"]
        self._write_wavs(str(tmp_path), names)
        train = ESC50(mode="train", split=1, data_dir=str(tmp_path))
        dev = ESC50(mode="dev", split=1, data_dir=str(tmp_path))
        assert len(train) == 2 and len(dev) == 2
        wav, label = dev[0]
        assert label in (0, 7) and wav.ndim == 1

    def test_tess_emotion_labels_and_features(self, tmp_path):
        from paddle_tpu.audio.datasets import TESS
        names = ["OAF_back_angry.wav", "OAF_back_happy.wav",
                 "YAF_dog_sad.wav", "YAF_dog_neutral.wav", "OAF_bite_fear.wav"]
        self._write_wavs(str(tmp_path), names)
        ds = TESS(mode="train", n_folds=5, split=5, data_dir=str(tmp_path),
                  feat_type="melspectrogram", n_fft=256, n_mels=8)
        feats, label = ds[0]
        assert feats.shape[0] == 8 and 0 <= label < len(TESS.EMOTIONS)

    def test_missing_dir_clear_error(self):
        from paddle_tpu.audio.datasets import ESC50
        with pytest.raises(RuntimeError, match="data_dir"):
            ESC50(data_dir="/nonexistent/path")
