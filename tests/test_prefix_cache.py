"""Prefix cache subsystem (ISSUE 10): radix-trie matching, refcounted
copy-on-write block sharing, and the int8 paged KV mode.

Covers the refcounted BlockPool (shared alloc, retain/release, free only
at refcount zero, conservation), the PrefixCache trie (insert/match
alignment, LRU eviction, byte budget, reclaim under pool pressure), the
int8 paged ops (gather reference == the static factored-scale math, the
Pallas kernel's interpret path), and the serving engine: zero-prefill
admission on a repeated prefix (TTFT = one decode step, prefill never
called), suffix-only prefill on a partial hit, COW never mutating a
shared block (checksummed), greedy bit-parity with the cache on vs off
and int8-paged vs the static int8 path, pinned shared-occupancy metrics
math, and zero post-warmup recompiles with cache + int8 enabled.
"""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.inference import (BlockPool, PrefixCache, ServingConfig,
                                  ServingEngine, shared_prefix_traffic)
from paddle_tpu.jit.api import compile_cache_misses
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.ops.attention import (attention_q8_cache,
                                      paged_attention_reference,
                                      paged_attention_reference_q8,
                                      paged_cache_write_q8,
                                      paged_prefill_write,
                                      paged_prefix_attention_reference,
                                      quantize_kv)
from paddle_tpu.ops.pallas.paged_attention import paged_attention_q8_kernel


# ------------------------------------------------- refcounted allocator

def _pool(blocks=10, bs=4, **kw):
    return BlockPool(num_blocks=blocks, block_size=bs, num_layers=2,
                     num_heads=2, head_dim=4, **kw)


class TestRefcountedPool:
    def test_shared_alloc_free_at_zero(self):
        p = _pool()
        a = p.alloc(1, 8)                       # 2 private blocks
        assert [p.refcount(b) for b in a] == [1, 1]
        b = p.alloc(2, 12, shared=list(a))      # maps both + 1 fresh
        assert len(b) == 3 and list(b[:2]) == list(a)
        assert [p.refcount(x) for x in a] == [2, 2]
        # owner 1 frees: shared blocks stay resident (owner 2 holds them)
        assert p.free(1) == 0
        assert [p.refcount(x) for x in a] == [1, 1]
        assert p.free(2) == 3                   # last refs -> free list
        assert p.free_blocks == p.capacity_blocks

    def test_retain_release_conservation(self):
        """Every alloc path balanced by release: pool drains to full
        capacity whatever the interleaving."""
        p = _pool()
        a = p.alloc(1, 8)
        p.retain(a)                             # the cache's reference
        p.free(1)
        assert p.free_blocks == p.capacity_blocks - 2   # cache holds 2
        c = p.alloc(3, 8, shared=list(a))       # served FROM the cache
        assert list(c) == list(a)
        p.free(3)
        assert p.release(a) == 2
        assert p.free_blocks == p.capacity_blocks
        assert p._refs == {}

    def test_guard_rails(self):
        p = _pool()
        with pytest.raises(ValueError, match="never shared"):
            p.alloc(1, 4, shared=[0])
        with pytest.raises(ValueError, match="not live"):
            p.alloc(1, 4, shared=[3])           # nobody allocated 3
        a = p.alloc(1, 4)
        with pytest.raises(ValueError, match="longer than"):
            p.alloc(2, 2, shared=[int(a[0]), int(a[0])])
        p.free(1)
        with pytest.raises(ValueError, match="underflow"):
            p.release(a)

    def test_int8_pools_and_bytes(self):
        p = _pool(cache_dtype="int8")
        pools = p.make_pools()
        kc, ks, vc, vs = pools[0]
        assert kc.shape == (10, 4, 2, 4) and kc.dtype == jnp.int8
        assert ks.shape == (10, 4, 2) and ks.dtype == jnp.float32
        # 2 layers * (K+V) * (4*2*4 int8 codes + 4*2 f32 scales)
        assert p.bytes_per_block == 2 * 2 * (4 * 2 * 4 + 4 * 2 * 4)
        fp = _pool()
        assert fp.bytes_per_block == 2 * 2 * (4 * 2 * 4 * 4)
        with pytest.raises(ValueError, match="cache_dtype"):
            _pool(cache_dtype="fp8")


# ------------------------------------------------------ the radix trie

class TestPrefixTrie:
    def test_match_is_block_aligned(self):
        p = _pool(blocks=16)
        c = PrefixCache(p)
        toks = np.arange(10, dtype=np.int64) + 1
        blocks = p.alloc(1, 10)                 # 3 blocks, last partial
        assert c.insert(toks, blocks) == 2      # only FULL blocks cached
        assert c.cached_blocks == 2
        got, n = c.match(toks)
        assert n == 8 and got == [int(blocks[0]), int(blocks[1])]
        # divergence inside block 2 -> only block 1 matches
        div = toks.copy()
        div[5] = 99
        got, n = c.match(div)
        assert n == 4 and got == [int(blocks[0])]
        # shorter than one block -> no match
        assert c.match(toks[:3]) == ([], 0)

    def test_insert_dedups_and_shares_nodes(self):
        p = _pool(blocks=16)
        c = PrefixCache(p)
        a = np.arange(8, dtype=np.int64) + 1
        blk_a = p.alloc(1, 8)
        c.insert(a, blk_a)
        # a second chain with the same first block: node dedup'd, the
        # duplicate block is NOT retained (its owner's free releases it)
        b = np.concatenate([a[:4], np.int64([50, 51, 52, 53])])
        blk_b = p.alloc(2, 8)
        assert c.insert(b, blk_b) == 1          # only the divergent block
        assert c.cached_blocks == 3
        assert p.refcount(blk_b[0]) == 1        # not retained by cache
        got, n = c.match(b)
        assert n == 8 and got[0] == int(blk_a[0])

    def test_lru_eviction_refcount_guarded(self):
        p = _pool(blocks=16)
        c = PrefixCache(p)
        a = np.arange(8, dtype=np.int64) + 1
        blk = p.alloc(1, 8)
        c.insert(a, blk)
        p.free(1)                               # cache-only refs now
        b = np.int64([9, 9, 9, 9])
        blk_b = p.alloc(2, 4)
        c.insert(b, blk_b)
        c.match(a)                              # stamp a as recently used
        # owner 2 still live: b's block is NOT evictable; a's chain is,
        # but LRU order inside it is leaf-first (cascade)
        assert c.evict(4) == 2
        assert c.cached_blocks == 1             # b survived via refcount
        assert c.match(a) == ([], 0)
        p.free(2)
        assert c.evict(4) == 1
        assert p.free_blocks == p.capacity_blocks

    def test_byte_budget_evicts_on_insert(self):
        p = _pool(blocks=16)
        c = PrefixCache(p, byte_budget=2 * p.bytes_per_block)
        a = np.arange(8, dtype=np.int64) + 1
        blk = p.alloc(1, 8)
        c.insert(a, blk)
        p.free(1)                               # a's pair is reclaimable
        b = np.int64([7, 7, 7, 7, 8, 8, 8, 8])
        blk_b = p.alloc(2, 8)
        c.insert(b, blk_b)                      # 4 cached > budget of 2:
        # insert evicts a's LRU (reclaimable) pair; b's blocks are
        # refcount-guarded by their live owner
        assert c.cached_blocks == 2
        assert c.match(b)[1] == 8 and c.match(a)[1] == 0
        assert c.cached_bytes <= c.byte_budget
        with pytest.raises(ValueError, match="zero blocks"):
            PrefixCache(p, byte_budget=1)

    def test_reclaim_under_pool_pressure(self):
        p = _pool(blocks=6, bs=4)               # 5 usable blocks
        c = PrefixCache(p)
        a = np.arange(8, dtype=np.int64) + 1
        blk = p.alloc(1, 8)
        c.insert(a, blk)
        p.free(1)                               # 2 blocks cache-resident
        assert p.free_blocks == 3
        assert c.reclaim(5)                     # evicts the cached pair
        assert p.free_blocks == 5
        assert not c.reclaim(6)                 # beyond capacity: honest

    def test_clear_releases(self):
        p = _pool(blocks=16)
        c = PrefixCache(p)
        blk = p.alloc(1, 8)
        c.insert(np.arange(8, dtype=np.int64) + 1, blk)
        p.free(1)
        assert c.clear() == 2
        assert p.free_blocks == p.capacity_blocks and c.cached_blocks == 0


# ----------------------------------------------------- int8 paged ops

def _q8_pool(lens, bs=4, nh=4, hd=8, mb=4, seed=0):
    rng = np.random.RandomState(seed)
    B = len(lens)
    nb = 2 + sum(-(-ln // bs) for ln in lens)
    kc = jnp.zeros((nb, bs, nh, hd), jnp.int8)
    ks = jnp.zeros((nb, bs, nh), jnp.float32)
    vc = jnp.zeros_like(kc)
    vs = jnp.zeros_like(ks)
    tables = np.zeros((B, mb), np.int32)
    nxt = 1
    K = rng.randn(B, mb * bs, nh, hd).astype(np.float32) * 0.3
    V = rng.randn(B, mb * bs, nh, hd).astype(np.float32) * 0.3
    for b, ln in enumerate(lens):
        nblk = -(-ln // bs)
        tables[b, :nblk] = range(nxt, nxt + nblk)
        nxt += nblk
    t = jnp.asarray(tables)
    for b, ln in enumerate(lens):
        for pos in range(ln):
            args = (t[b:b + 1], jnp.asarray([pos], jnp.int32))
            kc, ks = paged_cache_write_q8(
                kc, ks, jnp.asarray(K[b:b + 1, pos:pos + 1]), *args)
            vc, vs = paged_cache_write_q8(
                vc, vs, jnp.asarray(V[b:b + 1, pos:pos + 1]), *args)
    return kc, ks, vc, vs, t, K, V


@pytest.mark.parametrize("lens", [(5, 8, 1), (4, 12, 7)])
def test_paged_q8_reference_matches_static_math(lens):
    """Gathered int8 paged attention == the static factored-scale math
    (attention_q8_cache) on the same rows — the paged pool's per-block
    scales reproduce the static path's per-(pos, head) quantization
    exactly, ragged lengths incl. an exact block boundary."""
    kc, ks, vc, vs, t, K, V = _q8_pool(lens)
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(len(lens), 1, 4, 8).astype(np.float32) * 0.3)
    la = jnp.asarray(lens, jnp.int32)
    got = paged_attention_reference_q8(q, kc, ks, vc, vs, t, la)
    kcod, kscl = quantize_kv(jnp.asarray(K))
    vcod, vscl = quantize_kv(jnp.asarray(V))
    col = jnp.arange(K.shape[1])[None, None, None, :]
    mask = col < la[:, None, None, None]
    want = attention_q8_cache(q, kcod, kscl, vcod, vscl, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_paged_q8_kernel_interpret_matches_reference():
    lens = (5, 8, 1)
    kc, ks, vc, vs, t, _, _ = _q8_pool(lens, seed=2)
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(len(lens), 1, 4, 8).astype(np.float32) * 0.3)
    la = jnp.asarray(lens, jnp.int32)
    got = paged_attention_q8_kernel(q, kc, ks, vc, vs, t, la,
                                    interpret=True)
    want = paged_attention_reference_q8(q, kc, ks, vc, vs, t, la)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_prefill_write_pad_past_table_goes_to_trash():
    """Suffix-prefill padding positions past the TABLE WIDTH must land in
    the trash block — clipping them into the last table entry would let a
    garbage pad column share a destination row with a real suffix column
    (scatter order would then decide who wins)."""
    bs, nh, hd = 4, 2, 4
    pool = jnp.zeros((4, bs, nh, hd), jnp.float32)
    tables = jnp.asarray(np.array([[1, 2]], np.int32))   # width 2, no
    #                                           trailing trash entry
    rng = np.random.RandomState(0)
    new = rng.randn(1, 8, nh, hd).astype(np.float32)     # 4 real + 4 pad
    out = paged_prefill_write(pool, jnp.asarray(new), tables,
                              start=jnp.asarray([4], jnp.int32))
    # real suffix (positions 4..7) lands in block 2 intact
    np.testing.assert_array_equal(np.asarray(out)[2], new[0, :4])
    # pad positions 8..11 went to trash (block 0), not over the suffix
    assert np.abs(np.asarray(out)[0]).sum() > 0
    assert np.abs(np.asarray(out)[3]).sum() == 0


def test_prefix_attention_matches_single_token_reference():
    """Suffix-prefill attention at query row i == single-token paged
    decode attention with lens = start + i + 1 (same pool, same global
    position) — the executable a partial hit runs equals the one the
    plain decode path would have produced token by token."""
    bs, nh, hd, mb = 4, 4, 8, 4
    rng = np.random.RandomState(5)
    nb = 6
    kp = jnp.zeros((nb, bs, nh, hd), jnp.float32)
    vp = jnp.zeros_like(kp)
    tables = jnp.asarray(np.array([[1, 2, 3, 4]], np.int32))
    K = rng.randn(1, 8, nh, hd).astype(np.float32) * 0.3
    V = rng.randn(1, 8, nh, hd).astype(np.float32) * 0.3
    kp = paged_prefill_write(kp, jnp.asarray(K), tables)
    vp = paged_prefill_write(vp, jnp.asarray(V), tables)
    q = jnp.asarray(rng.randn(1, 4, nh, hd).astype(np.float32) * 0.3)
    start = jnp.asarray([4], jnp.int32)
    got = paged_prefix_attention_reference(q, kp, vp, tables, start)
    for i in range(4):
        want = paged_attention_reference(q[:, i:i + 1], kp, vp, tables,
                                         jnp.asarray([4 + i + 1],
                                                     jnp.int32))
        np.testing.assert_allclose(np.asarray(got[:, i]),
                                   np.asarray(want[:, 0]),
                                   rtol=1e-6, atol=1e-6)


# ------------------------------------------------------- engine oracle

CAP, NEW = 8, 6


@pytest.fixture(scope="module")
def served_model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m, cfg


def _prompts(cfg, lens, seed=1):
    rng = np.random.RandomState(seed)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    return ids


def _engine(m, **kw):
    base = dict(max_batch=2, prompt_cap=CAP, max_new_tokens=NEW,
                decode_chunk=2, paged=True, kv_block=4, prefix_cache=True)
    base.update(kw)
    return ServingEngine(m, ServingConfig(**base))


def test_config_paged_cache_dtype_validation():
    """int8 + paged is now a served combination; other narrow dtypes
    keep the structured config-validation finding."""
    from paddle_tpu.analysis.findings import ConfigValidationError
    cfg = ServingConfig(paged=True, cache_dtype="int8")
    assert cfg.cache_dtype == "int8"
    with pytest.raises(ConfigValidationError) as ei:
        ServingConfig(paged=True, cache_dtype="float16")
    assert ei.value.finding.code == "paged_cache_dtype"
    with pytest.raises(ValueError, match="requires paged"):
        ServingConfig(prefix_cache=True)


def test_zero_prefill_admission_repeated_prefix(served_model):
    """Acceptance: a repeated block-aligned prompt admits with ZERO
    prefill tokens — prefill_paged is never called for it, TTFT is one
    decode step (no prefill wall: t_prefill_done == t_admit), prompt
    tokens minus the re-decoded last one count as saved — and greedy
    output is bit-identical to the uncached chain."""
    m, cfg = served_model
    ids = _prompts(cfg, [CAP])
    ref = m.generate_static_ragged(paddle.to_tensor(ids), [CAP],
                                   max_new_tokens=NEW).numpy()[:, CAP:]
    eng = _engine(m)
    eng.submit(ids[0])
    first = eng.drain()
    np.testing.assert_array_equal(first[0].tokens, ref[0])

    calls = {"n": 0}
    real = m.prefill_paged

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    m.prefill_paged = counting
    try:
        req = eng.submit(ids[0])
        done = eng.drain()
    finally:
        m.prefill_paged = real
    assert calls["n"] == 0                      # zero prefill tokens
    assert req.trace.t_prefill_done == req.trace.t_admit
    assert req.trace.t_first_token is not None
    np.testing.assert_array_equal(done[0].tokens, ref[0])
    s = eng.summary()
    assert s["prefill_tokens_saved_total"] == CAP - 1
    assert s["prefix_hit_total"] == 1 and s["prefix_miss_total"] == 1


def test_cow_never_mutates_shared_blocks(served_model):
    """COW invariant: checksums of the SHARED pool regions are identical
    before and after a request that diverges mid-prefix (and after a
    full-hit COW re-decode) — shared blocks are mapped, copied, never
    written."""
    m, cfg = served_model
    ids = _prompts(cfg, [CAP])
    eng = _engine(m, max_batch=1, kv_blocks=33)
    eng.submit(ids[0])
    eng.drain()
    cached, t = eng._prefix.match(ids[0])
    assert t == CAP
    before = [tuple(np.asarray(p)[cached].tobytes() for p in layer)
              for layer in eng._pools]

    # divergent request: shares the first block, new content after
    div = ids[0].copy()
    div[4:] = _prompts(cfg, [CAP], seed=7)[0, 4:]
    eng.submit(div)
    eng.drain()
    # full-hit repeat: exercises the COW copy of the last shared block
    eng.submit(ids[0])
    eng.drain()
    after = [tuple(np.asarray(p)[cached].tobytes() for p in layer)
             for layer in eng._pools]
    assert before == after

    # and the divergent chain was still exact (suffix prefill attended
    # across the shared prefix correctly)
    refd = m.generate_static_ragged(paddle.to_tensor(div[None]), [CAP],
                                    max_new_tokens=NEW).numpy()[0, CAP:]
    eng2 = _engine(m, prefix_cache=False)
    eng2.submit(div)
    np.testing.assert_array_equal(eng2.drain()[0].tokens, refd)


def test_refcount_conservation_through_engine(served_model):
    """Every alloc path the engine takes (miss, suffix hit, COW hit,
    eviction) balances: after drain + cache clear the pool is whole."""
    m, cfg = served_model
    eng = _engine(m)
    lens = [CAP, 5, 3, CAP, 7]
    ids = _prompts(cfg, lens)
    for i in range(len(lens)):
        eng.submit(ids[i, :lens[i]])
    eng.submit(ids[0, :CAP])                    # repeat: COW path
    done = eng.drain()
    assert all(r.status == "done" for r in done)
    assert eng._pool.free_blocks == \
        eng._pool.capacity_blocks - eng._prefix.cached_blocks
    eng._prefix.clear()
    assert eng._pool.free_blocks == eng._pool.capacity_blocks
    assert eng._pool._refs == {}


def test_engine_cache_on_off_parity_and_zero_recompiles(served_model):
    """Acceptance: greedy output bit-identical with the prefix cache on
    vs off across shared-prefix traffic, and ZERO post-warmup jit cache
    misses with the cache enabled (full-prefill, suffix-prefill, COW and
    decode executables all live in the warmup set)."""
    m, cfg = served_model
    traffic = shared_prefix_traffic(12, n_prefixes=2, prefix_len=4,
                                    prompt_cap=CAP,
                                    vocab_size=cfg.vocab_size,
                                    rate=1e9, seed=3)
    eng = _engine(m, kv_blocks=65)
    # warmup: one miss (full prefill + decode), one aligned repeat (COW),
    # one partial hit (suffix prefill)
    warm = _prompts(cfg, [CAP], seed=11)[0]
    eng.submit(warm)
    eng.drain()
    eng.submit(warm)
    eng.drain()
    div = warm.copy()
    div[4:] = _prompts(cfg, [CAP], seed=12)[0, 4:]
    eng.submit(div)
    eng.drain()
    miss0 = compile_cache_misses()
    got = {}
    for item in traffic:
        eng.submit(item["prompt"])
    for r in eng.drain():
        got[r.prompt.tobytes()] = r.tokens
    assert compile_cache_misses() - miss0 == 0
    assert eng.monitor.recompiles == 0
    s = eng.summary()
    assert s["prefix_hit_total"] >= 1           # the traffic repeats

    off = _engine(m, prefix_cache=False)
    for item in traffic:
        off.submit(item["prompt"])
    for r in off.drain():
        np.testing.assert_array_equal(got[r.prompt.tobytes()], r.tokens)


def test_engine_int8_paged_parity(served_model):
    """int8-paged greedy chains track the static int8 path bit-for-bit
    on the f32 CPU reference (the established tolerance is exactness in
    a shared numerics class), with the prefix cache enabled on top."""
    m, cfg = served_model
    lens = [CAP, 5, 3]
    ids = _prompts(cfg, lens)
    ref8 = m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                    max_new_tokens=NEW,
                                    cache_dtype="int8").numpy()[:, CAP:]
    eng = _engine(m, cache_dtype="int8")
    for i in range(len(lens)):
        eng.submit(ids[i, :lens[i]])
    eng.submit(ids[0, :CAP])                    # int8 COW repeat
    done = eng.drain()
    assert len(done) == len(lens) + 1
    for r in done:
        row = next(i for i in range(len(lens))
                   if np.array_equal(ids[i, :lens[i]], r.prompt))
        np.testing.assert_array_equal(r.tokens, ref8[row])
    # int8 pools really are the compact form
    assert eng._pools[0][0].dtype == jnp.int8
    assert len(eng._pools[0]) == 4


def test_shared_occupancy_metrics_pinned(served_model):
    """Physical kv_occupancy counts a shared block ONCE; kv_shared_tokens
    is the logical volume served out of shared blocks — math pinned on a
    concurrent aligned-hit pair."""
    m, cfg = served_model
    ids = _prompts(cfg, [CAP])
    eng = _engine(m)
    eng.submit(ids[0])
    eng.drain()                                 # prefix now cached
    cap_tokens = eng._pool.capacity_tokens
    cached = eng._prefix.cached_blocks          # CAP/4 = 2 blocks
    assert cached == CAP // 4
    # two concurrent requests: A re-admits the cached prompt (COW: 1
    # shared block + 1 private copy, lens starts at CAP-1), B is fresh
    eng.submit(ids[0])
    fresh = _prompts(cfg, [5], seed=21)[0, :5]
    eng.submit(fresh)
    eng.step()                                  # admit both + 1 chunk
    # snapshot at decode entry: A lens=7 over [shared b, cow b] -> 4+3
    # physical but 4 of its 7 logical rows are shared; B lens=5 -> 4+1
    phys = 4 + 3 + 5
    assert eng._kv_snapshot[0] == phys
    assert eng._kv_snapshot[2] == 4
    assert eng.metrics.gauges["kv_occupancy"] == phys / cap_tokens
    assert eng.metrics.gauges["kv_shared_tokens"] == 4
    eng.drain()


def test_engine_pool_pressure_reclaims_cache(served_model):
    """A pool too small to hold live traffic + the cache reclaims cached
    blocks at admission instead of stalling — cached-but-idle prefixes
    are soft capacity."""
    m, cfg = served_model
    # 6 usable blocks: a CAP request pins ceil(13/4)=4 blocks and caches
    # 2 on finish — the second distinct CAP request fits, but the first's
    # REPEAT (1 shared + 3 fresh) only fits after evicting cached blocks
    eng = _engine(m, kv_blocks=7, max_batch=1)
    a = _prompts(cfg, [CAP], seed=31)[0]
    b = _prompts(cfg, [CAP], seed=32)[0]
    ref = {}
    for p in (a, b):
        ref[p.tobytes()] = m.generate_static_ragged(
            paddle.to_tensor(p[None]), [CAP],
            max_new_tokens=NEW).numpy()[0, CAP:]
    for p in (a, b, a, b):
        eng.submit(p)
    done = eng.drain()
    assert [r.status for r in done] == ["done"] * 4
    for r in done:
        np.testing.assert_array_equal(r.tokens, ref[r.prompt.tobytes()])
    assert eng._prefix.evicted_total >= 1
    assert eng.summary()["prefix_hit_total"] >= 1


def test_instant_finish_request_still_populates_cache(served_model):
    """A budget-1 request finishes AT admission — the cache insert must
    land while the request still holds its blocks (retain-after-free
    would raise), and the cached prefix must serve a later repeat."""
    m, cfg = served_model
    eng = _engine(m)
    ids = _prompts(cfg, [CAP])
    r1 = eng.submit(ids[0], max_new_tokens=1)
    eng.drain()
    assert r1.status == "done" and r1.n_out == 1
    assert eng._prefix.cached_blocks == CAP // 4
    ref = m.generate_static_ragged(paddle.to_tensor(ids), [CAP],
                                   max_new_tokens=NEW).numpy()[:, CAP:]
    r2 = eng.submit(ids[0])                     # full hit off the cache
    eng.drain()
    np.testing.assert_array_equal(r2.tokens, ref[0])
    assert eng.summary()["prefill_tokens_saved_total"] == CAP - 1


def test_zero_prefill_insert_never_caches_unwritten_block(served_model):
    """kv_block=1 regression: a zero-prefill hit defers writing position
    plen-1 to its first decode chunk, so the insert at admission must
    not cache that block — a same-step longer prompt would otherwise
    match into all-zero KV and decode garbage."""
    m, cfg = served_model
    eng = _engine(m, kv_block=1)
    p = _prompts(cfg, [3], seed=41)[0, :3]
    q = np.concatenate([p, _prompts(cfg, [1], seed=42)[0, :1]])
    eng.submit(p[:2])                           # caches blocks for p[:2]
    eng.drain()
    eng.submit(p)                               # t=2=plen-1: zero-prefill
    eng.submit(q)                               # same step: extends p
    done = eng.drain()
    ref = {}
    for pr in (p, q):
        ln = len(pr)
        ref[pr.tobytes()] = m.generate_static_ragged(
            paddle.to_tensor(np.pad(pr, (0, CAP - ln))[None]), [ln],
            max_new_tokens=NEW).numpy()[0, CAP:]
    for r in done:
        np.testing.assert_array_equal(r.tokens, ref[r.prompt.tobytes()])


def test_warmup_prefix_cache_covers_every_executable(served_model):
    """engine.warmup_prefix_cache (the shared serve_bench/bench/lint
    choreography) leaves the engine at zero steady-state misses across
    miss + COW + suffix traffic, with its own cached prefixes dropped."""
    m, cfg = served_model
    eng = _engine(m, kv_blocks=65)
    eng.warmup_prefix_cache(cfg.vocab_size)
    assert eng._prefix.cached_blocks == 0       # measured start is cold
    miss0 = compile_cache_misses()
    w = _prompts(cfg, [CAP], seed=51)[0]
    for prompt in (w, w):                       # miss then COW hit
        eng.submit(prompt)
        eng.drain()
    d = w.copy()
    d[4:] = _prompts(cfg, [CAP], seed=52)[0, 4:]
    eng.submit(d)                               # suffix prefill
    eng.drain()
    assert compile_cache_misses() - miss0 == 0
    with pytest.raises(ValueError, match="prefix_cache=True"):
        ServingEngine(m, ServingConfig(max_batch=1, prompt_cap=CAP,
                                       max_new_tokens=2, paged=True,
                                       kv_block=4)) \
            .warmup_prefix_cache(cfg.vocab_size)


def test_whole_pool_request_never_starves_on_own_prefix(served_model):
    """Starvation edge: a request needing the ENTIRE pool whose cached
    prefix is protected during its own admission would wait forever with
    nothing in flight to free blocks — the engine must drop the hit and
    full-prefill instead (progress beats reuse when they conflict)."""
    m, cfg = served_model
    # 4 usable blocks == exactly one CAP request (ceil(13/4)); its cached
    # prefix (2 blocks) + a COW repeat (1 shared + 3 fresh) cannot coexist
    eng = _engine(m, kv_blocks=5, max_batch=1)
    ids = _prompts(cfg, [CAP])
    ref = m.generate_static_ragged(paddle.to_tensor(ids), [CAP],
                                   max_new_tokens=NEW).numpy()[:, CAP:]
    eng.submit(ids[0])
    eng.drain()
    eng.submit(ids[0])                          # would COW-deadlock
    done = eng.drain(max_batches=20)
    assert [r.status for r in done] == ["done"]
    np.testing.assert_array_equal(done[0].tokens, ref[0])


def test_shared_prefix_traffic_profile():
    tr = shared_prefix_traffic(32, n_prefixes=3, prefix_len=6,
                               prompt_cap=16, vocab_size=64, rate=100.0,
                               seed=0)
    assert len(tr) == 32
    prefixes = {t["prompt"][:6].tobytes() for t in tr}
    assert len(prefixes) == 3
    lens = [t["prompt"].shape[0] for t in tr]
    assert min(lens) >= 7 and max(lens) <= 16
    assert all(0 <= t["prefix_id"] < 3 for t in tr)
    with pytest.raises(ValueError, match="prefix_len"):
        shared_prefix_traffic(2, n_prefixes=1, prefix_len=16,
                              prompt_cap=16, vocab_size=64)


def test_engine_exception_recovers_with_cache(served_model):
    """The mid-flight failure path also resets the prefix cache (the
    pool reset reissued every block) — the engine stays usable and the
    cache repopulates."""
    m, cfg = served_model
    eng = _engine(m)
    ids = _prompts(cfg, [CAP])
    eng.submit(ids[0])
    eng.drain()
    assert eng._prefix.cached_blocks == 2
    eng.submit(ids[0])
    real = m.decode_paged

    def boom(*a, **kw):
        raise RuntimeError("injected device failure")

    m.decode_paged = boom
    try:
        with pytest.raises(RuntimeError, match="injected"):
            eng.step()
    finally:
        m.decode_paged = real
    assert eng._prefix.cached_blocks == 0
    assert eng._pool.free_blocks == eng._pool.capacity_blocks
    eng.submit(ids[0])
    done = eng.drain()
    assert [r.status for r in done] == ["done"]
    assert eng._prefix.cached_blocks == 2


# ---------------------------------------------- host spill tier (ISSUE 14)

def _spill_engine(m, budget_blocks=2, **kw):
    """Engine with a device prefix budget of `budget_blocks` blocks and
    an ample host spill tier — eviction spills instead of dying."""
    from paddle_tpu.inference import BlockPool
    bpb = BlockPool.for_model(m, num_blocks=2, block_size=4).bytes_per_block
    base = dict(prefix_cache_bytes=budget_blocks * bpb,
                spill_host_bytes=1 << 22)
    base.update(kw)
    return _engine(m, **base)


class TestSpillTier:
    def test_pool_block_round_trip_bit_identical(self, served_model):
        """read_block -> write_block moves bytes, never recomputes:
        the round-tripped block equals the source bitwise (f32 AND
        int8 pools), and the write is one donated in-place scatter."""
        m, cfg = served_model
        for cache_dtype in (None, "int8"):
            eng = _engine(m, cache_dtype=cache_dtype)
            ids = _prompts(cfg, [CAP])
            eng.submit(ids[0])
            eng.drain()
            blk = int(eng._prefix.match(ids[0])[0][0])
            src = [tuple(np.asarray(p)[blk].copy() for p in layer)
                   for layer in eng._pools]
            payload = eng._pool.read_block(eng._pools, blk)
            # scatter into a different free block and compare planes
            dst = eng._pool.take(1)[0]
            eng._pools = eng._pool.write_block(eng._pools, dst, payload)
            for li, layer in enumerate(eng._pools):
                for pi, p in enumerate(layer):
                    np.testing.assert_array_equal(
                        np.asarray(p)[dst], src[li][pi])
            eng._pool.release([dst])

    def test_evict_spill_rehydrate_bit_identical_decode(self, served_model):
        """evict-under-budget -> spill -> later hit rehydrates with ONE
        host->device copy per block, decode bit-identical to a
        never-evicted engine AND to the cache-off reference."""
        m, cfg = served_model
        ids = _prompts(cfg, [CAP, CAP, CAP], seed=3)
        eng = _spill_engine(m, budget_blocks=2, kv_blocks=40)
        never = _engine(m, kv_blocks=40)         # ample budget, no spill
        first = {}
        for i in range(3):
            r = eng.submit(ids[i]); eng.drain()
            first[i] = r.tokens
            never.submit(ids[i]); never.drain()
        t = eng._spill
        assert t.spilled_total >= 1              # the 2-block budget
        assert eng._prefix.spilled_blocks == t.spilled_blocks
        # resubmit the LRU-spilled prompt: its blocks rehydrate
        r0 = t.rehydrated_total
        ra = eng.submit(ids[0]); eng.drain()
        rb = never.submit(ids[0]); never.drain()
        assert t.rehydrated_total > r0
        assert t.h2d_copies == t.rehydrated_total   # one copy per block
        np.testing.assert_array_equal(ra.tokens, first[0])
        np.testing.assert_array_equal(ra.tokens, rb.tokens)

    def test_cow_after_rehydrate_checksum_invariance(self, served_model):
        """A full-hit repeat on a REHYDRATED prefix still goes through
        COW: the rehydrated shared blocks' checksums never change."""
        m, cfg = served_model
        ids = _prompts(cfg, [CAP], seed=5)
        eng = _spill_engine(m, budget_blocks=2, kv_blocks=40, max_batch=1)
        eng.submit(ids[0]); eng.drain()
        eng._prefix.evict(eng._prefix.cached_blocks)     # all -> host
        assert eng._prefix.cached_blocks == 0
        eng.submit(ids[0])                               # rehydrates +
        eng.drain()                                      # COW full hit
        assert eng._spill.rehydrated_total >= 2
        cached, t = eng._prefix.match(ids[0])
        assert t == CAP
        before = [tuple(np.asarray(p)[cached].tobytes() for p in layer)
                  for layer in eng._pools]
        eng.submit(ids[0]); eng.drain()                  # another COW hit
        after = [tuple(np.asarray(p)[cached].tobytes() for p in layer)
                 for layer in eng._pools]
        assert before == after

    def test_refcount_conservation_mixed_spill_traffic(self, served_model):
        """Pool conservation through mixed spill/rehydrate/upgrade
        traffic: after drain + clear, every block is back on the free
        list and the refcount table is empty (spilled entries hold NO
        pool reference)."""
        m, cfg = served_model
        eng = _spill_engine(m, budget_blocks=2, kv_blocks=40)
        lens = [CAP, 5, CAP, 3, CAP, 7, CAP]
        ids = _prompts(cfg, lens, seed=9)
        for i, ln in enumerate(lens):
            eng.submit(ids[i, :ln])
            eng.drain()
        eng.submit(ids[0, :CAP]); eng.drain()     # rehydrate + COW
        t = eng._spill
        assert t.spilled_total >= 1
        # device refs == device-cached blocks; spilled hold none
        assert eng._pool.free_blocks == \
            eng._pool.capacity_blocks - eng._prefix.cached_blocks
        eng._prefix.clear()
        assert eng._pool.free_blocks == eng._pool.capacity_blocks
        assert eng._pool._refs == {}
        assert eng._prefix.spilled_blocks == 0
        assert t.spilled_blocks == 0

    def test_tier_budget_drops_lru_spilled(self, served_model):
        """The host tier has its own budget: spilling past it drops the
        LRU spilled leaves for good (dropped_total) and host residency
        never exceeds capacity_blocks."""
        m, cfg = served_model
        from paddle_tpu.inference import BlockPool
        bpb = BlockPool.for_model(m, num_blocks=2,
                                  block_size=4).bytes_per_block
        eng = _engine(m, kv_blocks=40, prefix_cache_bytes=2 * bpb,
                      spill_host_bytes=2 * bpb)    # tier holds 2 blocks
        lens = [CAP, CAP, CAP, CAP]
        ids = _prompts(cfg, lens, seed=11)
        for i, ln in enumerate(lens):
            eng.submit(ids[i, :ln])
            eng.drain()
        t = eng._spill
        assert t.dropped_total >= 1
        assert t.spilled_blocks <= t.capacity_blocks
        assert eng._prefix.spilled_blocks == t.spilled_blocks

    def test_spill_zero_recompiles_after_warmup(self, served_model):
        """warmup_prefix_cache's spill leg lowers the d2h gather and h2d
        scatter too: steady spill/rehydrate traffic adds zero jit cache
        misses and zero logged recompiles."""
        m, cfg = served_model
        eng = _spill_engine(m, budget_blocks=2, kv_blocks=40)
        eng.warmup_prefix_cache(cfg.vocab_size)
        miss0 = compile_cache_misses()
        lens = [CAP, CAP, CAP, 5, CAP]
        ids = _prompts(cfg, lens, seed=13)
        for i, ln in enumerate(lens):
            eng.submit(ids[i, :ln])
            eng.drain()
        eng.submit(ids[0, :CAP]); eng.drain()
        assert eng._spill.rehydrated_total >= 1
        assert compile_cache_misses() - miss0 == 0
        assert eng.monitor.recompiles == 0

    def test_statusz_and_metrics_surface(self, served_model):
        """The tier is scrapeable: statusz carries the spill block and
        metrics_registry renders a lint-clean spill producer."""
        from paddle_tpu.obs import lint_exposition
        m, cfg = served_model
        eng = _spill_engine(m, budget_blocks=2, kv_blocks=40)
        ids = _prompts(cfg, [CAP, CAP, CAP], seed=15)
        for i in range(3):
            eng.submit(ids[i]); eng.drain()
        s = eng.statusz()
        assert s["spill"]["spilled_total"] >= 1
        assert s["prefix_cache"]["spilled_blocks"] == \
            eng._prefix.spilled_blocks
        reg = eng.metrics_registry()
        assert "spill" in reg.producers
        page = reg.render()
        lint_exposition(page)
        assert "paddle_tpu_serving_spill_spilled_total" in page

    def test_rehydrate_survives_tier_trim_under_pool_pressure(self):
        """Found in review: _rehydrate's eviction can spill ANOTHER
        block, whose tier trim scans LRU childless spilled leaves — the
        node being rehydrated is one (stale stamp) and must be
        protected, or its payload is dropped mid-flight and the write
        crashes. Unit-level: tier budget 1 block, pool exhausted."""
        from paddle_tpu.inference import HostSpillTier
        p = _pool(blocks=6, bs=4)
        tier = HostSpillTier(bytes_per_block=p.bytes_per_block,
                             byte_budget=p.bytes_per_block)
        c = PrefixCache(p)
        writes = []
        c.attach_spill(tier,
                       reader=lambda b: (f"payload{b}",),
                       writer=lambda b, pl: writes.append((b, pl)))
        ta = np.arange(4, dtype=np.int64) + 1
        tb = np.arange(4, dtype=np.int64) + 50
        A = p.alloc(1, 4)
        c.insert(ta, A)
        p.free(1)
        B = p.alloc(2, 4)
        c.insert(tb, B)
        p.free(2)
        c.evict(1)                    # spills LRU = A (tier now full)
        assert c.spilled_blocks == 1 and tier.spilled_blocks == 1
        p.alloc(9, p.free_blocks * 4)   # exhaust the free list
        blocks, t = c.match(ta)       # rehydrate A: must evict+trim B,
        assert t == 4                 # NOT drop A's own payload
        assert writes and writes[-1][1] == (f"payload{int(A[0])}",)
        assert tier.rehydrated_total == 1
        assert tier.dropped_total == 1          # B: spilled then dropped
        assert tier.spilled_blocks == 0
        assert c.match(tb) == ([], 0)           # B is gone for good
        # conservation: drop everything, pool whole again
        p.free(9)
        c.clear()
        assert p.free_blocks == p.capacity_blocks and p._refs == {}
