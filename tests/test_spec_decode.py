"""ISSUE 11: ragged multi-token paged attention + speculative decoding.

Covers (1) interpret-mode parity of the [B, k] Pallas kernels (f32 + q8)
against the gather reference across ragged (k, start, lens) mixes incl.
the k=1 degenerate and exact block-boundary rows; (2) verify_paged's
longest-accepted-prefix rule against a numpy oracle, EOS chain forcing
included; (3) the spec engine's bit-identical-greedy contract vs
generate_static_ragged across mixed accept/reject traffic with zero
post-warmup jit cache misses; (4) chunked prefill: parity + one
executable for every prompt length; (5) trie prompt-lookup drafting and
the spec acceptance metrics.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.inference import (ServingConfig, ServingEngine,
                                  model_draft_fn, repeated_traffic,
                                  shared_prefix_traffic)
from paddle_tpu.inference.kv_cache import BlockPool
from paddle_tpu.inference.prefix_cache import PrefixCache
from paddle_tpu.jit.api import compile_cache_misses
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.ops.attention import (paged_prefill_write,
                                      paged_prefill_write_q8,
                                      paged_prefix_attention_reference,
                                      paged_prefix_attention_reference_q8,
                                      paged_attention_reference)
from paddle_tpu.ops.pallas.paged_attention import (
    paged_prefix_attention_kernel, paged_prefix_attention_q8_kernel)


# ------------------------------------------------ multi-token kernel parity

def _fp_pool(n_rows=3, bs=4, nh=4, hd=8, mb=4, seed=0):
    """Pool with n_rows block-table rows fully written (mb blocks each)."""
    rng = np.random.RandomState(seed)
    nb = 1 + n_rows * mb
    kp = jnp.zeros((nb, bs, nh, hd), jnp.float32)
    vp = jnp.zeros_like(kp)
    tables = np.arange(1, nb, dtype=np.int32).reshape(n_rows, mb)
    t = jnp.asarray(tables)
    K = rng.randn(n_rows, mb * bs, nh, hd).astype(np.float32) * 0.3
    V = rng.randn(n_rows, mb * bs, nh, hd).astype(np.float32) * 0.3
    for b in range(n_rows):
        kp = paged_prefill_write(kp, jnp.asarray(K[b:b + 1]), t[b:b + 1])
        vp = paged_prefill_write(vp, jnp.asarray(V[b:b + 1]), t[b:b + 1])
    return kp, vp, t


@pytest.mark.parametrize("s,start", [
    (1, (8, 3, 0)),          # k=1 degenerate (the decode case)
    (4, (4, 0, 1)),          # window starting AT a block boundary
    (4, (3, 5, 0)),          # window CROSSING a block boundary
    (5, (11, 2, 7)),         # odd window, mixed offsets
    (8, (8, 0, 0)),          # window = two whole blocks
])
def test_multi_token_kernel_interpret_parity(s, start):
    """Pallas [B, k] kernel (interpret mode) == gather reference across
    ragged (k, start) mixes — block-boundary rows included."""
    kp, vp, t = _fp_pool()
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(3, s, 4, 8).astype(np.float32) * 0.3)
    st = jnp.asarray(start, jnp.int32)
    got = paged_prefix_attention_kernel(q, kp, vp, t, st, interpret=True)
    want = paged_prefix_attention_reference(q, kp, vp, t, st)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_multi_token_kernel_k1_matches_decode_reference():
    """The k=1 window with start = lens-1 IS single-token decode: the
    multi-token kernel subsumes the decode case (same attended set as
    paged_attention_reference at lens attendable rows)."""
    kp, vp, t = _fp_pool(seed=3)
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(3, 1, 4, 8).astype(np.float32) * 0.3)
    lens = jnp.asarray([9, 4, 1], jnp.int32)   # incl. a block boundary
    got = paged_prefix_attention_kernel(q, kp, vp, t, lens - 1,
                                        interpret=True)
    want = paged_attention_reference(q, kp, vp, t, lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("s,start", [(1, (8, 3)), (4, (4, 0)),
                                     (6, (10, 2))])
def test_multi_token_q8_kernel_interpret_parity(s, start):
    rng = np.random.RandomState(1)
    bs, nh, hd, mb = 4, 4, 8, 4
    nb = 1 + 2 * mb
    kc = jnp.zeros((nb, bs, nh, hd), jnp.int8)
    ks = jnp.zeros((nb, bs, nh), jnp.float32)
    vc = jnp.zeros_like(kc)
    vs = jnp.zeros_like(ks)
    t = jnp.asarray(np.arange(1, nb, dtype=np.int32).reshape(2, mb))
    K = rng.randn(2, mb * bs, nh, hd).astype(np.float32) * 0.3
    V = rng.randn(2, mb * bs, nh, hd).astype(np.float32) * 0.3
    for b in range(2):
        kc, ks = paged_prefill_write_q8(kc, ks, jnp.asarray(K[b:b + 1]),
                                        t[b:b + 1])
        vc, vs = paged_prefill_write_q8(vc, vs, jnp.asarray(V[b:b + 1]),
                                        t[b:b + 1])
    q = jnp.asarray(rng.randn(2, s, nh, hd).astype(np.float32) * 0.3)
    st = jnp.asarray(start, jnp.int32)
    got = paged_prefix_attention_q8_kernel(q, kc, ks, vc, vs, t, st,
                                           interpret=True)
    want = paged_prefix_attention_reference_q8(q, kc, ks, vc, vs, t, st)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


# --------------------------------------------------- trie prompt lookup

class TestLookupContinuation:
    def _pool(self):
        return BlockPool(num_blocks=32, block_size=4, num_layers=1,
                         num_heads=1, head_dim=2)

    def test_continuation_after_full_blocks(self):
        p = self._pool()
        c = PrefixCache(p)
        toks = np.arange(12, dtype=np.int64) + 1
        c.insert(toks, p.alloc(1, 12))
        # aligned context: the next cached block's key is the draft
        assert c.lookup_continuation(toks[:4], 4) == [5, 6, 7, 8]
        assert c.lookup_continuation(toks[:8], 8) == [9, 10, 11, 12]
        # n caps the draft; walking past the cached path truncates
        assert c.lookup_continuation(toks[:4], 2) == [5, 6]
        assert c.lookup_continuation(toks[:4], 99) == list(range(5, 13))

    def test_partial_tail_matches_inside_a_block(self):
        p = self._pool()
        c = PrefixCache(p)
        toks = np.arange(8, dtype=np.int64) + 1
        c.insert(toks, p.alloc(1, 8))
        # context ends mid-block: the block key's remainder is the draft
        assert c.lookup_continuation(toks[:5], 4) == [6, 7, 8]
        assert c.lookup_continuation(toks[:7], 4) == [8]

    def test_divergence_returns_empty(self):
        p = self._pool()
        c = PrefixCache(p)
        toks = np.arange(8, dtype=np.int64) + 1
        c.insert(toks, p.alloc(1, 8))
        wrong = toks.copy()
        wrong[6] = 77                        # tail diverges from the key
        assert c.lookup_continuation(wrong[:7], 4) == []
        wrong2 = toks.copy()
        wrong2[1] = 77                       # full block diverges
        assert c.lookup_continuation(wrong2[:6], 4) == []
        assert c.lookup_continuation(toks, 4) == []   # path exhausted

    def test_lookup_does_not_stamp_lru(self):
        p = self._pool()
        c = PrefixCache(p)
        a = np.arange(8, dtype=np.int64) + 1
        b = np.arange(8, dtype=np.int64) + 50
        c.insert(a, p.alloc(1, 8))
        c.insert(b, p.alloc(2, 8))
        p.free(1)
        p.free(2)
        c.match(a)                           # a is the recent one
        c.lookup_continuation(b[:4], 4)      # a peek must NOT refresh b
        c.evict(2)
        # b's leaf+root went, a survived
        assert c.lookup_continuation(a[:4], 4) == [5, 6, 7, 8]
        assert c.lookup_continuation(b[:4], 4) == []


# ------------------------------------------------ verify acceptance oracle

@pytest.fixture(scope="module")
def served_model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=96,
                    intermediate_size=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m, cfg


CAP, NEW = 8, 6


def _setup_chain(m, seed=1, budget=8):
    pool = BlockPool.for_model(m, num_blocks=16, block_size=4)
    pools = pool.make_pools()
    prompt = np.random.RandomState(seed).randint(
        1, 96, (1, CAP)).astype(np.int64)
    pool.alloc(0, CAP + budget)
    tbl = pool.table_row(0, 4)[None]
    pools, first = m.prefill_paged(prompt, [CAP], pools, tbl)
    return pools, tbl, int(first.numpy()[0])


def test_verify_accept_math_against_plain_chain(served_model):
    """Longest-accepted-prefix rule vs the step-by-step decode chain:
    full accept, full reject, and a mid-window mismatch all emit exactly
    the plain chain's tokens and advance by n_acc + 1."""
    m, cfg = served_model
    pools, tbl, t0 = _setup_chain(m)
    lens = np.asarray([CAP], np.int32)
    pend = np.asarray([t0], np.int32)
    toks, pools, _, _ = m.decode_paged(pools, tbl, lens, pend,
                                       np.zeros((1,), bool), 6)
    ref = np.asarray(toks.numpy())[0]

    cases = [
        (ref[:3].astype(np.int32), 3),                     # full accept
        (np.asarray([95, 94, 93], np.int32), 0),           # full reject
        (np.asarray([ref[0], 93, ref[2]], np.int32), 1),   # mid mismatch
    ]
    for draft, want_acc in cases:
        pools2, tbl2, t0b = _setup_chain(m)
        assert t0b == t0
        e, n_acc, pools2, _ = m.verify_paged(
            pools2, tbl2, lens, pend, draft[None], np.zeros((1,), bool))
        n = int(np.asarray(n_acc)[0])
        e = np.asarray(e.numpy())[0]
        assert n == want_acc
        np.testing.assert_array_equal(e[:n + 1], ref[:n + 1])


def test_verify_chain_continues_bitwise_after_rejects(served_model):
    """Rejected-position KV writes are garbage BELOW the next window's
    start: a plain decode resumed after a partial-accept window matches
    the uninterrupted chain bitwise (the overwrite-before-attendable
    invariant)."""
    m, cfg = served_model
    pools, tbl, t0 = _setup_chain(m)
    lens = np.asarray([CAP], np.int32)
    pend = np.asarray([t0], np.int32)
    toks, pools, _, _ = m.decode_paged(pools, tbl, lens, pend,
                                       np.zeros((1,), bool), 6)
    ref = np.asarray(toks.numpy())[0]

    pools2, tbl2, _ = _setup_chain(m)
    draft = np.asarray([[ref[0], 93, 92]], np.int32)    # accept 1 of 3
    e, n_acc, pools2, _ = m.verify_paged(
        pools2, tbl2, lens, pend, draft, np.zeros((1,), bool))
    n = int(np.asarray(n_acc)[0])
    assert n == 1
    e = np.asarray(e.numpy())
    toks2, pools2, _, _ = m.decode_paged(
        pools2, tbl2, lens + n + 1, e[:, n].astype(np.int32),
        np.zeros((1,), bool), 4)
    np.testing.assert_array_equal(np.asarray(toks2.numpy())[0],
                                  ref[n + 1:n + 5])


def test_verify_eos_chain_forcing(served_model):
    """EOS semantics match decode_paged's sequential rule: once the
    chain emits EOS at a window position, every later emitted position
    is EOS regardless of argmax, and done_out reflects only EMITTED
    positions."""
    m, cfg = served_model
    pools, tbl, t0 = _setup_chain(m)
    lens = np.asarray([CAP], np.int32)
    pend = np.asarray([t0], np.int32)
    toks, pools, _, _ = m.decode_paged(pools, tbl, lens, pend,
                                       np.zeros((1,), bool), 6)
    ref = np.asarray(toks.numpy())[0]
    eos = int(ref[1])          # make the chain's 2nd token "EOS"

    # plain chain with that eos: decode_paged forces post-EOS tokens
    pools2, tbl2, _ = _setup_chain(m)
    toksf, pools2, _, donef = m.decode_paged(
        pools2, tbl2, lens, pend, np.zeros((1,), bool), 4,
        eos_token_id=eos)
    want = np.asarray(toksf.numpy())[0]
    assert np.all(want[1:] == eos)

    # spec window drafting the same chain: emitted tokens match, done set
    pools3, tbl3, _ = _setup_chain(m)
    draft = want[:3].astype(np.int32)[None]
    e, n_acc, pools3, done3 = m.verify_paged(
        pools3, tbl3, lens, pend, draft, np.zeros((1,), bool),
        eos_token_id=eos)
    n = int(np.asarray(n_acc)[0])
    e = np.asarray(e.numpy())[0]
    np.testing.assert_array_equal(e[:n + 1], want[:n + 1])
    assert bool(np.asarray(done3)[0])       # EOS was emitted

    # a row done on ENTRY emits eos everywhere and stays done
    pools4, tbl4, _ = _setup_chain(m)
    e4, _, pools4, done4 = m.verify_paged(
        pools4, tbl4, lens, pend, draft, np.ones((1,), bool),
        eos_token_id=eos)
    assert np.all(np.asarray(e4.numpy()) == eos)
    assert bool(np.asarray(done4)[0])


# ----------------------------------------------------- spec engine oracle

def _ref_chains(m, ids, lens, **kw):
    return m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                    max_new_tokens=NEW,
                                    **kw).numpy()[:, ids.shape[1]:]


def _check_parity(done, ids, lens, ref):
    assert all(r.status == "done" for r in done)
    for r in done:
        row = next(i for i in range(len(lens))
                   if np.array_equal(ids[i, :lens[i]], r.prompt))
        np.testing.assert_array_equal(r.tokens, ref[row])


def test_spec_engine_bit_identical_and_zero_misses(served_model):
    """The headline oracle: speculative greedy output == non-speculative
    generate_static_ragged per row across MIXED accept/reject traffic
    (repeats draft + accept fully; fresh prompts reject or have no
    draft), with zero post-warmup jit cache misses."""
    m, cfg = served_model
    eng = ServingEngine(m, ServingConfig(
        max_batch=2, prompt_cap=CAP, max_new_tokens=NEW, decode_chunk=2,
        paged=True, kv_block=4, kv_blocks=96, prefix_cache=True,
        spec_decode=True, spec_k=3))
    eng.warmup_prefix_cache(cfg.vocab_size, clear=False)

    # traffic: 2 prompts repeated (full acceptance after first pass) + 3
    # fresh ragged prompts (no draft / rejecting drafts)
    rep = repeated_traffic(6, n_prompts=2, prompt_len=CAP,
                           vocab_size=cfg.vocab_size, rate=1e9, seed=5)
    lens = [CAP, CAP, 7, 3, 5]
    rng = np.random.RandomState(9)
    ids = rng.randint(1, cfg.vocab_size,
                      (len(lens), CAP)).astype(np.int64)
    ids[0] = rep[0]["prompt"] if rep[0]["prompt_id"] == 0 else \
        next(t["prompt"] for t in rep if t["prompt_id"] == 0)
    ids[1] = next(t["prompt"] for t in rep if t["prompt_id"] == 1)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    ref = _ref_chains(m, ids, lens)

    miss0 = compile_cache_misses()
    submitted = []
    for t in rep:
        submitted.append(t["prompt"])
    for i in range(2, len(lens)):
        submitted.append(ids[i, :lens[i]])
    for p in submitted:
        eng.submit(p)
    done = eng.drain()
    assert compile_cache_misses() - miss0 == 0, \
        f"steady spec traffic recompiled: {eng.monitor.recompiles}"
    _check_parity(done, ids, lens, ref)
    s = eng.metrics.counters
    assert s["spec_windows"] > 0 and s["spec_drafts_trie"] > 0
    assert 0 < s["spec_accepted"] <= s["spec_proposed"]
    # repeats accept fully: at least one window emitted spec_k + 1
    assert eng.metrics.hists["spec_accept_len"]._max == 4


def test_spec_engine_parity_with_eos(served_model):
    """Mixed traffic with an EOS token id: spec chains stay bit-identical
    incl. post-EOS forcing and early finish."""
    m, cfg = served_model
    eos = 11
    eng = ServingEngine(m, ServingConfig(
        max_batch=2, prompt_cap=CAP, max_new_tokens=NEW, decode_chunk=2,
        paged=True, kv_block=4, kv_blocks=96, prefix_cache=True,
        spec_decode=True, spec_k=3, eos_token_id=eos))
    eng.warmup_prefix_cache(cfg.vocab_size, clear=False)
    lens = [CAP, CAP, 6, 2]
    rng = np.random.RandomState(3)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    ref = _ref_chains(m, ids, lens, eos_token_id=eos)
    for rep in range(2):        # second pass drafts the first's chains
        for i in range(len(lens)):
            eng.submit(ids[i, :lens[i]])
        done = eng.drain()
        # engine rows truncate at EOS (n_out); compare the truncated form
        assert all(r.status == "done" for r in done)
        for r in done:
            row = next(i for i in range(len(lens))
                       if np.array_equal(ids[i, :lens[i]], r.prompt))
            want = ref[row]
            np.testing.assert_array_equal(r.tokens[:r.n_out],
                                          want[:r.n_out])
            # beyond n_out the reference chain is EOS-forced padding
            assert np.all(want[r.n_out:] == eos) or \
                r.n_out == want.shape[0]


def test_spec_engine_model_draft_and_source_split(served_model):
    """A draft-model hook (the target itself = oracle drafter) serves
    rows the trie cannot; the metrics split trie vs model windows."""
    m, cfg = served_model
    # budget 1 + spec_k + 1: every request is exactly one full verify
    # window after the prefill token, so no window is budget-truncated
    # and the oracle drafter's acceptance accounting is exact
    new = 5
    eng = ServingEngine(m, ServingConfig(
        max_batch=2, prompt_cap=CAP, max_new_tokens=new, decode_chunk=2,
        paged=True, kv_block=4, spec_decode=True, spec_k=3,
        spec_draft=model_draft_fn(m, window=16)))
    lens = [CAP, 5, 3]
    rng = np.random.RandomState(2)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    ref = m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                   max_new_tokens=new).numpy()[:, CAP:]
    eng.submit(ids[0, :lens[0]])
    eng.drain()                 # warm: prefill + verify + draft executable
    miss0 = compile_cache_misses()
    for i in range(len(lens)):
        eng.submit(ids[i, :lens[i]])
    done = eng.drain()
    assert compile_cache_misses() - miss0 == 0
    _check_parity(done, ids, lens, ref)
    s = eng.metrics.counters
    assert s["spec_drafts_model"] > 0 and s["spec_drafts_trie"] == 0
    # the oracle drafter's proposals all accept (no truncated windows)
    assert s["spec_accepted"] == s["spec_proposed"]
    # emitted-per-window accounting ties out against real output: every
    # window emitted accepted-drafts + bonus, summed = histogram sum
    assert eng.metrics.hists["spec_accept_len"].sum == \
        s["spec_accepted"] + s["spec_windows"]


def test_spec_request_jsonl_row_carries_acceptance(served_model, tmp_path):
    m, cfg = served_model
    import json
    path = str(tmp_path / "req.jsonl")
    from paddle_tpu.inference import ServingMetrics
    eng = ServingEngine(m, ServingConfig(
        max_batch=2, prompt_cap=CAP, max_new_tokens=NEW, decode_chunk=2,
        paged=True, kv_block=4, kv_blocks=96, prefix_cache=True,
        spec_decode=True, spec_k=3),
        metrics=ServingMetrics(jsonl_path=path))
    prompt = np.random.RandomState(4).randint(
        1, cfg.vocab_size, (CAP,)).astype(np.int64)
    for _ in range(2):          # second run drafts the first's chain
        eng.submit(prompt)
        eng.drain()
    rows = [json.loads(l) for l in open(path)]
    spec_rows = [r for r in rows
                 if "request" in r and "spec" in r["request"]]
    assert spec_rows, "no request row carried spec acceptance"
    sp = spec_rows[-1]["request"]["spec"]
    assert sp["proposed"] > 0 and 0 <= sp["accepted"] <= sp["proposed"]
    assert sp["accept_rate"] == round(sp["accepted"] / sp["proposed"], 4)


def test_spec_config_validation():
    with pytest.raises(ValueError, match="requires paged"):
        ServingConfig(spec_decode=True)
    with pytest.raises(ValueError, match="prefix_cache"):
        ServingConfig(paged=True, spec_decode=True, spec_draft="trie")
    with pytest.raises(ValueError, match="greedy"):
        ServingConfig(paged=True, prefix_cache=True, spec_decode=True,
                      temperature=0.7)
    with pytest.raises(ValueError, match="spec_k"):
        ServingConfig(paged=True, prefix_cache=True, spec_decode=True,
                      spec_k=0)
    with pytest.raises(ValueError, match="spec_k"):
        # cap keeps the accept-length histogram's exact integer buckets
        ServingConfig(paged=True, prefix_cache=True, spec_decode=True,
                      spec_k=32)
    with pytest.raises(ValueError, match="callable"):
        ServingConfig(paged=True, spec_decode=True, spec_draft="ngram")
    with pytest.raises(ValueError, match="prefill_chunk"):
        ServingConfig(paged=True, prompt_cap=8, prefill_chunk=9)
    with pytest.raises(ValueError, match="requires paged"):
        ServingConfig(prefill_chunk=4)
    # a callable drafter needs no prefix cache
    ServingConfig(paged=True, spec_decode=True, spec_draft=lambda c, k: [])


def test_spec_int8_paged_parity(served_model):
    """Speculative decode over int8 paged pools: bit-identical to the
    static int8 chain (the q8 multi-token kernel path)."""
    m, cfg = served_model
    eng = ServingEngine(m, ServingConfig(
        max_batch=2, prompt_cap=CAP, max_new_tokens=NEW, decode_chunk=2,
        paged=True, kv_block=4, kv_blocks=96, prefix_cache=True,
        cache_dtype="int8", spec_decode=True, spec_k=3))
    eng.warmup_prefix_cache(cfg.vocab_size, clear=False)
    lens = [CAP, 5]
    rng = np.random.RandomState(6)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    ref = _ref_chains(m, ids, lens, cache_dtype="int8")
    for _ in range(2):
        for i in range(len(lens)):
            eng.submit(ids[i, :lens[i]])
        done = eng.drain()
        _check_parity(done, ids, lens, ref)
    assert eng.metrics.counters["spec_windows"] > 0


# --------------------------------------------------------- chunked prefill

@pytest.mark.parametrize("pc", [1, 3, 4, 8])
def test_chunked_prefill_parity_and_one_executable(served_model, pc):
    """prefill_chunk=N: greedy output bit-identical to one-shot prefill
    for every prompt length, with ZERO new executables across lengths
    (offsets are data through the single [1, N] start-form program).
    N=1 pins the start-before-width dispatch in the attention branch —
    a [1, 1] window with a start offset is a suffix-prefill chunk, not
    a decode step (it would otherwise write the wrong pool position)."""
    m, cfg = served_model
    lens = [CAP, 7, 3, 1, 5, CAP]
    rng = np.random.RandomState(1)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    ref = _ref_chains(m, ids, lens)
    eng = ServingEngine(m, ServingConfig(
        max_batch=2, prompt_cap=CAP, max_new_tokens=NEW, decode_chunk=2,
        paged=True, kv_block=4, prefill_chunk=pc))
    eng.submit(ids[0, :lens[0]])
    eng.drain()                                  # warm
    miss0 = compile_cache_misses()
    for i in range(len(lens)):
        eng.submit(ids[i, :lens[i]])
    done = eng.drain()
    assert compile_cache_misses() - miss0 == 0
    _check_parity(done, ids, lens, ref)


def test_chunked_prefill_interleaves_decode(served_model):
    """A long prompt admitted mid-flight must NOT stall the live decode
    batch for its whole prefill: with prefill_chunk set, decode chunks
    keep landing between prefill windows (the monopolization fix), and
    both requests' outputs stay bit-identical to the reference."""
    m, cfg = served_model
    eng = ServingEngine(m, ServingConfig(
        max_batch=2, prompt_cap=CAP, max_new_tokens=NEW, decode_chunk=1,
        paged=True, kv_block=4, prefill_chunk=2))
    rng = np.random.RandomState(8)
    a = rng.randint(1, cfg.vocab_size, (3,)).astype(np.int64)
    b = rng.randint(1, cfg.vocab_size, (CAP,)).astype(np.int64)
    eng.submit(a)
    eng.step()                 # admit a; prefill window 1 of 2
    eng.step()                 # a's final window: first token sampled
    slot_a = next(i for i, r in enumerate(eng._slots) if r is not None)
    assert eng._prefill_pos[slot_a] < 0     # a is now a decode row
    eng.submit(b)              # cap-length prompt joins mid-flight
    produced_before = eng._slots[slot_a]._produced
    done = eng.step()          # b: window 1 of 4; a: decode chunk runs
    slot_b = next(i for i, r in enumerate(eng._slots)
                  if r is not None and i != slot_a)
    assert eng._prefill_pos[slot_b] >= 0    # b still mid-prefill...
    assert eng._slots[slot_a] is None or \
        eng._slots[slot_a]._produced > produced_before \
        or any(r.prompt.shape[0] == 3 for r in done)
    # ...while a made decode progress in the same step
    done += eng.drain()
    ids = np.stack([np.pad(a, (0, CAP - 3)), b])
    ref = _ref_chains(m, ids, [3, CAP])
    _check_parity(done, ids, [3, CAP], ref)


def test_chunked_prefill_composes_with_prefix_cache_and_spec(served_model):
    """All three together: chunked prefill + prefix cache + speculative
    decode — parity holds and the steady loop stays compile-free."""
    m, cfg = served_model
    eng = ServingEngine(m, ServingConfig(
        max_batch=2, prompt_cap=CAP, max_new_tokens=NEW, decode_chunk=2,
        paged=True, kv_block=4, kv_blocks=96, prefix_cache=True,
        spec_decode=True, spec_k=3, prefill_chunk=4))
    eng.warmup_prefix_cache(cfg.vocab_size, clear=False)
    lens = [CAP, CAP, 5]
    rng = np.random.RandomState(12)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    ref = _ref_chains(m, ids, lens)
    miss_after_warm = None
    for rep in range(2):
        for i in range(len(lens)):
            eng.submit(ids[i, :lens[i]])
        done = eng.drain()
        _check_parity(done, ids, lens, ref)
        if rep == 0:
            miss_after_warm = compile_cache_misses()
    assert compile_cache_misses() == miss_after_warm
    assert eng.metrics.counters["spec_windows"] > 0


# ------------------------------------------------------ traffic generator

def test_repeated_traffic_profile():
    tr = repeated_traffic(40, n_prompts=3, prompt_len=6, vocab_size=50,
                          rate=100.0, seed=0)
    assert len(tr) == 40
    ids = {t["prompt_id"] for t in tr}
    assert ids <= {0, 1, 2} and len(ids) > 1
    by_id = {}
    for t in tr:
        key = t["prompt_id"]
        if key in by_id:
            np.testing.assert_array_equal(by_id[key], t["prompt"])
        by_id[key] = t["prompt"]
    ats = [t["at"] for t in tr]
    assert ats == sorted(ats) and ats[0] == 0.0
    with pytest.raises(ValueError):
        repeated_traffic(1, n_prompts=0, prompt_len=4, vocab_size=10)


def test_spec_throughput_exceeds_plain_on_repeat_traffic(served_model):
    """The perf claim at toy scale: on repeated-prompt traffic the spec
    engine makes strictly fewer device calls per emitted token than the
    plain paged engine (wall-clock is too noisy for CI; call count is
    the deterministic proxy — each call is one launch+sync)."""
    m, cfg = served_model
    traffic = repeated_traffic(8, n_prompts=2, prompt_len=CAP,
                               vocab_size=cfg.vocab_size, rate=1e9,
                               seed=7)

    def run(spec):
        eng = ServingEngine(m, ServingConfig(
            max_batch=2, prompt_cap=CAP, max_new_tokens=NEW,
            decode_chunk=1, paged=True, kv_block=4, kv_blocks=96,
            prefix_cache=True, spec_decode=spec, spec_k=3))
        eng.warmup_prefix_cache(cfg.vocab_size)
        eng.metrics = type(eng.metrics)()
        calls0 = eng._calls
        for t in traffic:
            eng.submit(t["prompt"])
        eng.drain()
        toks = eng.metrics.counters["tokens_out"]
        return (eng._calls - calls0) / max(toks, 1), toks

    plain_cpt, toks_p = run(False)
    spec_cpt, toks_s = run(True)
    assert toks_p == toks_s
    assert spec_cpt < plain_cpt, \
        f"spec {spec_cpt:.3f} calls/token !< plain {plain_cpt:.3f}"
