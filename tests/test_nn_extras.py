"""Tail nn symbols (SURVEY §2.3: the 137-layer surface) + transposed-conv
numeric regression (the IOHW spec bug made in!=out channel counts crash and
silently channel-transposed square cases)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_conv2d_transpose_matches_numpy_scatter():
    rng = np.random.RandomState(0)
    x = rng.randn(1, 3, 5, 5).astype(np.float32)
    w = rng.randn(3, 4, 3, 3).astype(np.float32)   # [in, out, kh, kw]
    stride, pad = 2, 1
    IH = IW = 5
    OH = OW = (IH - 1) * stride + 3 - 2 * pad
    out = np.zeros((1, 4, OH + 2 * pad, OW + 2 * pad), np.float32)
    for i in range(IH):
        for j in range(IW):
            for o in range(4):
                out[0, o, i * stride:i * stride + 3,
                    j * stride:j * stride + 3] += (
                    x[0, :, i, j][:, None, None] * w[:, o]).sum(0)
    want = out[:, :, pad:pad + OH, pad:pad + OW]
    got = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                             stride=stride, padding=pad).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_conv_transpose_1d_3d_shapes_and_grad():
    x1 = paddle.to_tensor(np.random.randn(2, 4, 10).astype("float32"))
    ct1 = nn.Conv1DTranspose(4, 6, 3, stride=2)
    y = ct1(x1)
    assert list(y.shape) == [2, 6, 21]
    xd = paddle.to_tensor(np.random.randn(1, 2, 4, 4, 4).astype("float32"))
    ct3 = nn.Conv3DTranspose(2, 3, 3, stride=2)
    y3 = ct3(xd)
    assert list(y3.shape) == [1, 3, 9, 9, 9]
    y3.sum().backward()
    assert ct3.weight.grad is not None


def test_pool3d_and_adaptive():
    x = paddle.to_tensor(np.random.randn(2, 3, 8, 8, 8).astype("float32"))
    assert list(nn.MaxPool3D(2)(x).shape) == [2, 3, 4, 4, 4]
    assert list(nn.AvgPool3D(2)(x).shape) == [2, 3, 4, 4, 4]
    assert list(nn.AdaptiveAvgPool3D(3)(x).shape) == [2, 3, 3, 3, 3]
    assert list(nn.AdaptiveMaxPool3D((2, 3, 4))(x).shape) == [2, 3, 2, 3, 4]
    x1 = paddle.to_tensor(np.random.randn(2, 4, 10).astype("float32"))
    got = nn.AdaptiveMaxPool1D(5)(x1).numpy()
    want = np.asarray(x1._data).reshape(2, 4, 5, 2).max(-1)
    np.testing.assert_allclose(got, want)


def test_shuffles_fold_unflatten():
    x = paddle.to_tensor(np.random.randn(1, 4, 4, 4).astype("float32"))
    assert list(nn.ZeroPad2D(1)(x).shape) == [1, 4, 6, 6]
    assert list(nn.PixelUnshuffle(2)(x).shape) == [1, 16, 2, 2]
    np.testing.assert_allclose(nn.ChannelShuffle(2)(x).numpy().sum(),
                               x.numpy().sum(), rtol=1e-5)
    xi = paddle.to_tensor(np.random.randn(1, 2, 4, 4).astype("float32"))
    cols = F.unfold(xi, [2, 2], strides=2)
    rec = nn.Fold((4, 4), (2, 2), strides=2)(cols)
    np.testing.assert_allclose(rec.numpy(), xi.numpy(), rtol=1e-5)
    uf = nn.Unflatten(1, [2, 2])
    assert list(uf(paddle.to_tensor(np.zeros((3, 4), np.float32))).shape) == [3, 2, 2]


def test_losses_and_misc():
    rng = np.random.RandomState(0)
    a = paddle.to_tensor(rng.randn(4, 3).astype("float32"))
    b = paddle.to_tensor(rng.randn(4, 3).astype("float32"))
    assert float(nn.HuberLoss()(a, b)) > 0
    sign = paddle.to_tensor(np.sign(rng.randn(4, 3)).astype("float32"))
    assert float(nn.SoftMarginLoss()(a, sign)) > 0
    lbl = paddle.to_tensor((rng.rand(4, 3) > 0.5).astype("float32"))
    assert float(nn.MultiLabelSoftMarginLoss()(a, lbl)) > 0
    pos = paddle.to_tensor(np.abs(rng.randn(4, 3)).astype("float32"))
    assert np.isfinite(float(nn.PoissonNLLLoss()(a, pos)))
    var = paddle.to_tensor(np.ones((4, 3), np.float32))
    assert np.isfinite(float(nn.GaussianNLLLoss()(a, b, var)))
    assert list(nn.PairwiseDistance()(a, b).shape) == [4]
    assert float(nn.TripletMarginWithDistanceLoss()(a, b, a)) >= 0
    # distance(a,a)=~0 so loss ~= margin
    m = float(nn.TripletMarginWithDistanceLoss(margin=0.7)(a, b, b))
    d = float(nn.PairwiseDistance()(a, b).mean())
    assert m >= 0


def test_activations_and_rnn_extras():
    act = nn.RReLU()
    act.train()
    o = act(paddle.to_tensor(np.array([-1.0, 2.0], np.float32)))
    assert float(o.numpy()[1]) == 2.0
    assert -1 / 3 - 1e-6 <= float(o.numpy()[0]) <= -1 / 8 + 1e-6
    act.eval()
    o2 = act(paddle.to_tensor(np.array([-1.0], np.float32)))
    np.testing.assert_allclose(o2.numpy(), [-(1/8 + 1/3) / 2], rtol=1e-5)
    assert float(nn.LogSigmoid()(paddle.to_tensor(
        np.zeros(1, np.float32))).numpy()) == pytest.approx(np.log(0.5), rel=1e-5)
    bi = nn.BiRNN(nn.GRUCell(4, 8), nn.GRUCell(4, 8))
    out, _ = bi(paddle.to_tensor(np.random.randn(2, 5, 4).astype("float32")))
    assert list(out.shape) == [2, 5, 16]


def test_spectral_norm_unit_sigma():
    w = paddle.to_tensor(np.random.RandomState(0).randn(6, 4).astype("float32"))
    sn = nn.SpectralNorm([6, 4], power_iters=20)
    s = np.linalg.svd(sn(w).numpy(), compute_uv=False)[0]
    assert abs(s - 1.0) < 1e-3


def test_max_unpool2d_scatter():
    pooled = np.array([[[[5., 7.], [13., 15.]]]], np.float32)
    idx = np.array([[[[5, 7], [13, 15]]]], np.int64)
    up = nn.MaxUnPool2D(2)(paddle.to_tensor(pooled), paddle.to_tensor(idx))
    assert list(up.shape) == [1, 1, 4, 4]
    flat = up.numpy().reshape(-1)
    assert flat[5] == 5.0 and flat[15] == 15.0 and flat.sum() == pooled.sum()
