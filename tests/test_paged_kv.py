"""Paged KV cache + ragged paged decode attention (ISSUE 5).

Covers the block-pool allocator (alloc/free/reuse, fragmentation, OOM →
reject with reason), paged-vs-reference attention parity across ragged
lengths (including a row at an exact block boundary), the Pallas kernel in
interpret mode, model-level bit-parity of paged prefill/decode with
generate_static_ragged, buffer donation (decode_static satellite + the
paged pools), the true-token occupancy gauges, and the engine's
slot-level continuous batching: a short request finishes early, frees its
blocks immediately, and a queued request is spliced into the vacated slot
mid-flight with ZERO recompiles.
"""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.inference import (BlockPool, ServingConfig, ServingEngine,
                                  synthetic_traffic)
from paddle_tpu.jit.api import compile_cache_misses
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.ops.attention import (attention_reference,
                                      paged_attention_reference,
                                      paged_cache_write,
                                      paged_prefill_write)
from paddle_tpu.ops.pallas.paged_attention import paged_attention_kernel


# ------------------------------------------------------ block allocator

class TestBlockPool:
    def _pool(self, blocks=8, bs=4):
        return BlockPool(num_blocks=blocks, block_size=bs, num_layers=1,
                         num_heads=2, head_dim=4)

    def test_alloc_free_reuse(self):
        p = self._pool()
        a = p.alloc(1, 10)                      # 3 blocks of 4
        assert a is not None and len(a) == 3
        assert 0 not in a                       # trash block never issued
        assert p.free_blocks == 4 and p.used_blocks == 3
        b = p.alloc(2, 4)
        assert len(b) == 1 and set(b).isdisjoint(a)
        assert p.free(1) == 3
        c = p.alloc(3, 12)                      # reuses 1's freed blocks
        assert set(c) & set(int(x) for x in a)
        assert p.free_blocks == 3

    def test_fragmented_free_list_still_serves(self):
        """Blocks are unit-granular: interleaved frees can never strand
        capacity — any request whose block count fits the free COUNT is
        servable regardless of which blocks were freed."""
        p = self._pool(blocks=9, bs=4)
        owners = [p.alloc(i, 8) for i in range(4)]      # 8 blocks out
        assert all(o is not None for o in owners)
        p.free(0), p.free(2)                            # non-contiguous
        got = p.alloc(9, 16)                            # 4 blocks
        assert got is not None and len(got) == 4
        assert p.free_blocks == 0

    def test_oom_returns_none_and_fits_ever(self):
        p = self._pool(blocks=4, bs=4)          # 3 usable blocks
        assert p.fits_ever(12) and not p.fits_ever(13)
        assert p.alloc(1, 12) is not None
        assert p.alloc(2, 1) is None            # full now: caller waits
        p.free(1)
        assert p.alloc(2, 1) is not None        # ...and is served after

    def test_double_alloc_raises(self):
        p = self._pool()
        p.alloc(1, 4)
        with pytest.raises(ValueError, match="already holds"):
            p.alloc(1, 4)
        assert p.free(99) == 0                  # unknown owner: no-op

    def test_table_row_padding_and_occupancy(self):
        p = self._pool(blocks=8, bs=4)
        p.alloc(7, 6)
        row = p.table_row(7, 5)
        assert row.dtype == np.int32 and row.shape == (5,)
        assert (row[2:] == 0).all() and (row[:2] > 0).all()
        assert p.capacity_tokens == 28
        assert p.occupancy(6) == 6 / 28
        assert p.slots_occupancy() == 2 / 7
        with pytest.raises(ValueError, match="table width"):
            p.table_row(7, 1)

    def test_validation(self):
        with pytest.raises(ValueError, match="num_blocks"):
            BlockPool(num_blocks=1, block_size=4, num_layers=1,
                      num_heads=1, head_dim=4)
        p = self._pool()
        pools = p.make_pools()
        assert len(pools) == 1
        assert pools[0][0].shape == (8, 4, 2, 4)


# ------------------------------------------- paged attention op parity

def _build_pool(lens, bs=4, nh=4, hd=8, mb=4, seed=0):
    """Pool + tables holding per-row contiguous K/V; returns the ground
    truth contiguous arrays too."""
    rng = np.random.RandomState(seed)
    B = len(lens)
    nb = 1 + sum(-(-ln // bs) for ln in lens) + 1
    pool_shape = (nb, bs, nh, hd)
    kp = jnp.zeros(pool_shape, jnp.float32)
    vp = jnp.zeros(pool_shape, jnp.float32)
    alloc = BlockPool(num_blocks=nb, block_size=bs, num_layers=1,
                      num_heads=nh, head_dim=hd)
    tables = np.zeros((B, mb), np.int32)
    L = mb * bs
    K = rng.randn(B, L, nh, hd).astype(np.float32) * 0.3
    V = rng.randn(B, L, nh, hd).astype(np.float32) * 0.3
    for b, ln in enumerate(lens):
        if ln:
            alloc.alloc(b, ln)
            tables[b] = alloc.table_row(b, mb)
        for p in range(ln):
            kp = paged_cache_write(kp, jnp.asarray(K[b:b + 1, p:p + 1]),
                                   jnp.asarray(tables[b:b + 1]),
                                   jnp.asarray([p], jnp.int32))
            vp = paged_cache_write(vp, jnp.asarray(V[b:b + 1, p:p + 1]),
                                   jnp.asarray(tables[b:b + 1]),
                                   jnp.asarray([p], jnp.int32))
    return kp, vp, jnp.asarray(tables), K, V


@pytest.mark.parametrize("lens", [(5, 8, 1), (4, 12, 7)])
def test_paged_reference_matches_masked_attention(lens):
    """Gather-reference == dense masked attention on the same K/V — ragged
    lengths including a row at EXACTLY a block boundary (8 and 12 with
    bs=4)."""
    bs, nh, hd, mb = 4, 4, 8, 4
    kp, vp, tables, K, V = _build_pool(lens, bs, nh, hd, mb)
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(len(lens), 1, nh, hd).astype(np.float32) * 0.3)
    la = jnp.asarray(lens, jnp.int32)
    got = paged_attention_reference(q, kp, vp, tables, la)
    col = jnp.arange(mb * bs)[None, None, None, :]
    mask = col < la[:, None, None, None]
    want = attention_reference(q, jnp.asarray(K), jnp.asarray(V), mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_paged_kernel_interpret_matches_reference():
    """The Pallas kernel (interpret mode on CPU; compiled mode is
    tools/validate_paged_tpu.py) against the gather reference — live rows
    only (the kernel zeros dummy lens=0 rows by design)."""
    lens = (5, 8, 1)
    bs, nh, hd, mb = 4, 4, 8, 4
    kp, vp, tables, _, _ = _build_pool(lens, bs, nh, hd, mb, seed=2)
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(len(lens), 1, nh, hd).astype(np.float32) * 0.3)
    la = jnp.asarray(lens, jnp.int32)
    got = paged_attention_kernel(q, kp, vp, tables, la, interpret=True)
    want = paged_attention_reference(q, kp, vp, tables, la)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_prefill_write_matches_per_token_writes():
    """Bulk prompt write lands every VALID position exactly where the
    decode-time single-token write would."""
    bs, nh, hd, mb = 4, 2, 4, 4
    lens = (6, 3)
    kp, vp, tables, K, _ = _build_pool(lens, bs, nh, hd, mb, seed=5)
    bulk = jnp.zeros_like(kp)
    bulk = paged_prefill_write(bulk, jnp.asarray(K[:, :8]), tables)
    tb = np.asarray(tables)
    for b, ln in enumerate(lens):
        for p in range(ln):
            blk, off = tb[b, p // bs], p % bs
            np.testing.assert_array_equal(np.asarray(bulk)[blk, off],
                                          np.asarray(kp)[blk, off])
    # padding past a row's reservation landed in the TRASH block (row 1's
    # positions 4..7 hit table entries of 0), never in another row's
    # blocks — the loop above already proves every valid cell of every
    # row survived the other rows' bulk writes
    assert np.abs(np.asarray(bulk)[0]).sum() > 0      # trash got garbage
    assert np.abs(np.asarray(kp)[0]).sum() == 0       # per-token never


# ------------------------------------------------- model-level parity

CAP, NEW = 8, 6


@pytest.fixture(scope="module")
def served_model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m, cfg


def _prompts(cfg, lens, seed=1):
    rng = np.random.RandomState(seed)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    return ids


def test_paged_decode_bit_identical_to_static_ragged(served_model):
    """Acceptance: chunked paged greedy decode replays the EXACT token
    chain of generate_static_ragged — ragged lengths incl. a full-cap row
    and one at a block boundary — and a second mixed batch reuses every
    executable (zero new jit cache misses)."""
    m, cfg = served_model
    lens = [CAP, 4, 1]                  # 4 == kv_block: boundary row
    ids = _prompts(cfg, lens)
    ref = m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                   max_new_tokens=NEW).numpy()[:, CAP:]
    pool = BlockPool.for_model(m, num_blocks=32, block_size=4)
    pools = pool.make_pools()
    mb = pool.blocks_needed(CAP + NEW - 1)
    tables = np.zeros((len(lens), mb), np.int32)
    for b, ln in enumerate(lens):
        pool.alloc(b, ln + NEW - 1)
        tables[b] = pool.table_row(b, mb)
    pools, first = m.prefill_paged(ids, np.int32(lens), pools, tables)
    first = first.numpy()
    np.testing.assert_array_equal(first, ref[:, 0])
    pend = first.astype(np.int32)
    done = np.zeros((len(lens),), bool)
    lens_h = np.asarray(lens, np.int32)
    got = [first[:, None]]
    for c in (2, 3):                    # chunked: [1, 2, 3] totals NEW
        toks, pools, _, done_d = m.decode_paged(pools, tables, lens_h,
                                                pend, done, c)
        arr = np.asarray(toks.numpy())
        got.append(arr)
        pend = arr[:, -1].astype(np.int32)
        lens_h = lens_h + c
    np.testing.assert_array_equal(np.concatenate(got, axis=1), ref)
    # steady state: fresh lens/tables, SAME shapes -> zero compiles
    miss0 = compile_cache_misses()
    pools, f2 = m.prefill_paged(ids, np.int32([3, 2, 5]), pools, tables)
    m.decode_paged(pools, tables, np.int32([3, 2, 5]),
                   f2.numpy().astype(np.int32), done, 2)
    assert compile_cache_misses() - miss0 == 0


def test_paged_pools_are_donated(served_model):
    """prefill_paged/decode_paged donate the pool buffers: XLA updates KV
    in place, and the caller's input arrays are consumed."""
    m, cfg = served_model
    pool = BlockPool.for_model(m, num_blocks=16, block_size=4)
    pools = pool.make_pools()
    mb = pool.blocks_needed(CAP + NEW - 1)
    pool.alloc(0, CAP + NEW - 1)
    tables = pool.table_row(0, mb)[None]
    ids = _prompts(cfg, [5])
    buf0 = pools[0][0]
    pools2, first = m.prefill_paged(ids, np.int32([5]), pools, tables)
    assert buf0.is_deleted()
    buf1 = pools2[0][0]
    _, pools3, _, _ = m.decode_paged(pools2, tables, np.int32([5]),
                                     first.numpy().astype(np.int32),
                                     np.zeros((1,), bool), 2)
    assert buf1.is_deleted()
    assert not pools3[0][0].is_deleted()

    # the pool must carry the model dtype — stale pools are rejected
    bad = [(p[0].astype(jnp.bfloat16), p[1].astype(jnp.bfloat16))
           for p in pools3]
    with pytest.raises(ValueError, match="paged KV pools"):
        m.prefill_paged(ids, np.int32([5]), bad, tables)


def test_decode_static_donates_cache_buffers(served_model):
    """Satellite: donate_cache=True updates the static KV tuples in place
    (input buffers consumed, tokens bit-identical); the default keeps the
    prefill fan-out contract (buffers intact, decodes repeatable)."""
    m, cfg = served_model
    lens = [CAP, 5]
    ids = _prompts(cfg, lens)
    t = paddle.to_tensor(ids)
    ref = m.generate_static_ragged(t, lens, max_new_tokens=NEW).numpy()[:, CAP:]

    st = m.prefill_static(t, max_len=CAP + NEW, prompt_lens=np.int32(lens))
    buf0 = st["caches"][0][0]
    t1, st = m.decode_static(st, 1, return_state=True, donate_cache=True)
    assert buf0.is_deleted()            # donated: consumed, not copied
    t2, st = m.decode_static(st, NEW - 1, return_state=True,
                             donate_cache=True)
    got = np.concatenate([t1.numpy(), t2.numpy()], axis=1)
    np.testing.assert_array_equal(got, ref)

    # default: NOT donated — one prefill fans out to many continuations
    st = m.prefill_static(t, max_len=CAP + NEW, prompt_lens=np.int32(lens))
    buf0 = st["caches"][0][0]
    a, _ = m.decode_static(st, 3, return_state=True)
    b, _ = m.decode_static(st, 3, return_state=True)
    assert not buf0.is_deleted()
    np.testing.assert_array_equal(a.numpy(), b.numpy())
    with pytest.raises(ValueError, match="donate_cache"):
        m.decode_static(st, 1, donate_cache=True)   # needs return_state


# ------------------------------------------------------ the paged engine

def _engine(m, **kw):
    base = dict(max_batch=2, prompt_cap=CAP, max_new_tokens=NEW,
                decode_chunk=2, paged=True, kv_block=4)
    base.update(kw)
    return ServingEngine(m, ServingConfig(**base))


def _row_of(ids, lens, r):
    return next(i for i in range(len(lens))
                if np.array_equal(ids[i, :lens[i]], r.prompt))


def test_engine_paged_parity_and_splice_zero_recompiles(served_model):
    """Acceptance: a short request finishes early, frees its blocks, and a
    QUEUED request is spliced into the vacated slot mid-flight — while the
    longer co-batched row keeps decoding. Every output bit-identical to
    generate_static_ragged; zero jit cache misses after warmup."""
    m, cfg = served_model
    lens = [CAP, 5, 3, 7, 2]
    ids = _prompts(cfg, lens)
    ref = m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                   max_new_tokens=NEW).numpy()[:, CAP:]
    eng = _engine(m)
    eng.submit(ids[0, :lens[0]])
    eng.drain()                         # warmup: prefill + decode compile
    miss0 = compile_cache_misses()
    # 5 requests through 2 slots; request 1 gets a 2-token budget so its
    # slot frees mid-flight and the queue splices into it
    for i in range(len(lens)):
        eng.submit(ids[i, :lens[i]],
                   max_new_tokens=NEW if i != 1 else 2)
    done = eng.drain()
    assert [r.status for r in done] == ["done"] * len(lens)
    for r in done:
        want = ref[_row_of(ids, lens, r)][:r.max_new_tokens]
        np.testing.assert_array_equal(r.tokens, want)
    assert compile_cache_misses() - miss0 == 0
    assert eng.monitor.recompiles == 0
    # the splice actually happened: more admissions than batch capacity
    # finished without ever draining to an empty batch between them
    assert eng.summary()["completed_total"] == len(lens) + 1


def test_engine_paged_eos_early_exit(served_model):
    m, cfg = served_model
    lens = [CAP, 5, 3]
    ids = _prompts(cfg, lens)
    ref = m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                   max_new_tokens=NEW).numpy()
    eos = int(ref[0, CAP])              # row 0 emits EOS as token 1
    refe = m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                    max_new_tokens=NEW,
                                    eos_token_id=eos).numpy()[:, CAP:]
    eng = _engine(m, eos_token_id=eos)
    for i in range(len(lens)):
        eng.submit(ids[i, :lens[i]])
    done = eng.drain()
    by_row = {_row_of(ids, lens, r): r for r in done}
    assert by_row[0].n_out == 1 and by_row[0].tokens[0] == eos
    for i, r in by_row.items():
        np.testing.assert_array_equal(r.tokens[:r.n_out],
                                      refe[i][:r.n_out])
    s = eng.summary()
    assert s["tokens_out_total"] == sum(r.n_out for r in done)


def test_engine_oversubscribed_pool_waits_not_rejects(served_model):
    """A pool smaller than the batch worst case: admission WAITS for freed
    blocks instead of rejecting — anything that fits the pool is served
    (the bucket-mismatch rejection path is gone)."""
    m, cfg = served_model
    lens = [CAP, 5, 7, 3]
    ids = _prompts(cfg, lens)
    ref = m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                   max_new_tokens=NEW).numpy()[:, CAP:]
    # 9 blocks usable = 36 rows; one request needs up to 13 rows (4
    # blocks) — only ~2 fit at once
    eng = _engine(m, kv_blocks=10)
    for i in range(len(lens)):
        eng.submit(ids[i, :lens[i]])
    done = eng.drain()
    assert [r.status for r in done] == ["done"] * len(lens)
    for r in done:
        np.testing.assert_array_equal(r.tokens, ref[_row_of(ids, lens, r)])


def test_engine_kv_oom_reject_reason(served_model):
    m, cfg = served_model
    eng = _engine(m, kv_blocks=3)       # 2 usable blocks = 8 rows
    r = eng.submit(_prompts(cfg, [CAP])[0, :CAP])   # needs 13 rows: never
    assert r.status == "rejected" and r.reason == "kv_oom"
    assert eng.summary()["rejected_total"] == 1
    # a request that fits is still served
    ok = eng.submit(_prompts(cfg, [2])[0, :2], max_new_tokens=3)
    assert ok.status == "queued"
    done = eng.drain()
    assert [x.status for x in done] == ["done"]


def test_occupancy_gauges_pinned_math(served_model):
    """kv_occupancy = live tokens / pooled capacity; kv_slots_occupancy =
    allocation-granular rows / capacity — pinned on both engines."""
    m, cfg = served_model
    # padded engine: 1 request (len 4) in a 2-slot batch, full budget
    eng = ServingEngine(m, ServingConfig(max_batch=2, prompt_cap=CAP,
                                         max_new_tokens=NEW,
                                         decode_chunk=3))
    eng.submit(_prompts(cfg, [4])[0, :4])
    eng.drain()
    s = eng.summary()
    L = eng.config.max_len
    # device-side decode runs the full chunk schedule (fixed shapes), so
    # written rows = prompt + schedule_sum - 1 even when the row's budget
    # truncates the returned tokens
    written = 4 + sum(eng.config.chunk_schedule) - 1
    assert s["kv_occupancy"] == written / (2 * L)
    assert s["kv_slots_occupancy"] == L / (2 * L)
    # paged engine: 1 request (len 5, budget 2) -> snapshot at the decode
    # chunk entry holds 5 live rows over (kv_blocks-1)*kv_block capacity,
    # with ceil((5+2-1)/4)=2 blocks reserved
    eng = _engine(m)
    cap_tokens = (eng.config.kv_blocks - 1) * 4
    eng.submit(_prompts(cfg, [5])[0, :5], max_new_tokens=2)
    eng.drain()
    s = eng.summary()
    assert s["kv_occupancy"] == 5 / cap_tokens
    assert s["kv_slots_occupancy"] == 2 * 4 / cap_tokens


def test_engine_paged_exception_recovers(served_model):
    """A batch dying mid-flight records the in-flight requests as errors
    AND rebuilds the (possibly consumed, donated) pools — the engine stays
    usable, matching the padded engine's contract."""
    m, cfg = served_model
    eng = _engine(m)
    ids = _prompts(cfg, [5])
    eng.submit(ids[0, :5])
    real = m.decode_paged

    def boom(*a, **kw):
        raise RuntimeError("injected device failure")

    m.decode_paged = boom
    try:
        with pytest.raises(RuntimeError, match="injected"):
            eng.step()
    finally:
        m.decode_paged = real
    s = eng.summary()
    assert s["errors_total"] == 1 and s["inflight"] == 0
    assert eng._pool.free_blocks == eng._pool.capacity_blocks
    eng.submit(ids[0, :5])
    assert [r.status for r in eng.drain()] == ["done"]


def test_longtail_traffic_profile():
    tr = synthetic_traffic(64, prompt_cap=16, vocab_size=64, rate=100.0,
                           seed=0, length_dist="longtail")
    lens = np.asarray([t["prompt"].shape[0] for t in tr])
    assert lens.min() >= 1 and lens.max() <= 16
    # heavy tail: mostly short, some at the cap
    assert np.median(lens) <= 4 and (lens >= 16).any()
    with pytest.raises(ValueError, match="length_dist"):
        synthetic_traffic(2, prompt_cap=4, vocab_size=8,
                          length_dist="zipf")


@pytest.mark.slow
def test_engine_paged_under_load_open_loop(served_model):
    """Open-loop long-tail replay through the paged engine: everything
    completes, outputs stay bit-identical per row, zero steady-state
    recompiles (the serve_bench --paged path minus the CLI)."""
    m, cfg = served_model
    eng = _engine(m)
    traffic = synthetic_traffic(24, prompt_cap=CAP,
                                vocab_size=cfg.vocab_size, rate=500.0,
                                seed=7, length_dist="longtail")
    eng.submit(traffic[0]["prompt"])
    eng.drain()                         # warmup
    miss0 = compile_cache_misses()
    t0 = eng.clock()
    finished = []
    for item in traffic:
        eng.submit(item["prompt"], enqueue_at=t0 + item["at"])
        while eng.queue_depth >= 2:
            finished.extend(eng.step())
    finished.extend(eng.drain())
    assert sum(1 for r in finished if r.status == "done") == 24
    assert compile_cache_misses() - miss0 == 0
    for r in finished:
        ln = r.prompt_len
        ref = m.generate_static_ragged(
            paddle.to_tensor(np.pad(r.prompt, (0, CAP - ln))[None]),
            [ln], max_new_tokens=NEW).numpy()[0, CAP:]
        np.testing.assert_array_equal(r.tokens, ref)
