"""Launcher / TCPStore / elastic tests.

Mirrors the reference's distributed-test mechanism (SURVEY §4): single-host
multi-process subprocess clusters (test_dist_base.py:899) — here driven
through the actual `paddle_tpu.distributed.launch` CLI on CPU workers."""
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.store import TCPStore, MasterDaemon
from paddle_tpu.distributed.fleet.elastic import (
    ElasticManager, ElasticStatus, rank_table)


# --------------------------------------------------------------- TCPStore
def test_store_set_get_add():
    s = TCPStore(is_master=True)
    s.set("k", "v1")
    assert s.get("k") == "v1"
    assert s.add("ctr", 2) == 2
    assert s.add("ctr", 3) == 5
    assert s.get("missing") is None
    s.close()


def test_store_wait_blocks_until_set():
    master = TCPStore(is_master=True)
    client = TCPStore("127.0.0.1", master.port)
    result = {}

    def waiter():
        result["v"] = client.wait("late_key", timeout=10)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.3)
    assert "v" not in result
    master.set("late_key", "arrived")
    t.join(timeout=10)
    assert result["v"] == "arrived"
    client.close()
    master.close()


def test_native_daemon_preferred_and_correct():
    """The C++ poll-loop daemon (native/src/store.cc) serves the same
    protocol; WAIT long-poll, timeout, DEL, KEYS, many-client barrier."""
    from paddle_tpu.io.native import native_available
    if not native_available():
        pytest.skip("no native toolchain")
    d = MasterDaemon()
    assert d.is_native
    c = TCPStore("127.0.0.1", d.port, world_size=3)
    c.set("a/x", "1")
    c.set("a/y", "with spaces ok")
    assert c.get("a/y") == "with spaces ok"
    assert sorted(c.keys("a/")) == ["a/x", "a/y"]
    assert c.add("n", 5) == 5 and c.add("n") == 6
    c.delete("a/x")
    assert c.get("a/x") is None
    with pytest.raises(TimeoutError):
        c.wait("never", timeout=0.3)
    # long-poll served on later SET from another client
    c2 = TCPStore("127.0.0.1", d.port)
    got = {}
    t = threading.Thread(target=lambda: got.setdefault(
        "v", c.wait("late", timeout=10)))
    t.start()
    time.sleep(0.2)
    c2.set("late", "done")
    t.join(10)
    assert got["v"] == "done"
    c.close(), c2.close()
    d.stop()


def test_python_fallback_daemon_still_works():
    d = MasterDaemon(use_native=False)
    assert not d.is_native
    c = TCPStore("127.0.0.1", d.port)
    c.set("k", "v")
    assert c.get("k") == "v"
    c.close()
    d.stop()


def test_store_barrier_two_clients():
    master = TCPStore(is_master=True, world_size=2)
    c2 = TCPStore("127.0.0.1", master.port, world_size=2)
    order = []

    def side(store, tag):
        store.barrier("b1", 2, timeout=10)
        order.append(tag)

    t1 = threading.Thread(target=side, args=(master, "a"))
    t2 = threading.Thread(target=side, args=(c2, "b"))
    t1.start()
    time.sleep(0.2)
    t2.start()
    t1.join(10), t2.join(10)
    assert sorted(order) == ["a", "b"]
    c2.close()
    master.close()


# --------------------------------------------------------------- elastic
def test_elastic_detects_membership_change():
    store = TCPStore(is_master=True)
    m1 = ElasticManager(store, "job", "n0", np_min=1, np_max=3,
                        ttl=5.0, beat_interval=0.1)
    m1.start()
    assert m1.watch() == ElasticStatus.COMPLETED
    # node joins → scale event under ELASTIC level
    store.set("job/hb/n1", str(time.time()))
    assert m1.watch() == ElasticStatus.RESTART
    m1.mark_epoch()
    assert m1.watch() == ElasticStatus.COMPLETED
    assert rank_table(m1) == {"n0": 0, "n1": 1}
    # node dies (stale beat) → RESTART
    store.set("job/hb/n1", str(time.time() - 100))
    assert m1.watch() == ElasticStatus.RESTART
    m1.stop()
    store.close()


def test_elastic_below_quorum_holds():
    store = TCPStore(is_master=True)
    m = ElasticManager(store, "j2", "a", np_min=2, np_max=4,
                       ttl=5.0, beat_interval=0.1)
    m.start()
    assert m.watch() == ElasticStatus.HOLD  # only 1 of min 2 nodes
    m.stop()
    store.close()


# --------------------------------------------------------------- launch CLI
WORKER = textwrap.dedent("""
    import os, sys
    rank = os.environ["PADDLE_TPU_PROCESS_ID"]
    world = os.environ["PADDLE_TPU_NUM_PROCESSES"]
    out_dir = sys.argv[1]
    with open(os.path.join(out_dir, f"rank_{rank}.txt"), "w") as f:
        f.write(f"{rank}/{world}")
    if len(sys.argv) > 2 and sys.argv[2] == "fail" and rank == "1" \
            and not os.path.exists(os.path.join(out_dir, "restarted")):
        open(os.path.join(out_dir, "restarted"), "w").write("1")
        sys.exit(7)
""")


def _run_launch(tmp_path, extra_args, script_args):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           *extra_args, str(script), str(tmp_path), *script_args]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=120, cwd="/root/repo")


def test_launch_two_procs_single_node(tmp_path):
    r = _run_launch(tmp_path, ["--nproc_per_node", "2"], [])
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "rank_0.txt").read_text() == "0/2"
    assert (tmp_path / "rank_1.txt").read_text() == "1/2"


def test_launch_restarts_on_failure(tmp_path):
    r = _run_launch(tmp_path, ["--nproc_per_node", "2", "--elastic_level", "1",
                               "--max_restarts", "2"], ["fail"])
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "restarted").exists()
    assert "restart 1/2" in r.stderr


def test_launch_fails_without_elastic(tmp_path):
    r = _run_launch(tmp_path, ["--nproc_per_node", "2"], ["fail"])
    assert r.returncode == 7


JAX_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.distributed as dist
    env = dist.init_parallel_env()
    n = len(jax.devices())
    pc = jax.process_count()
    out_dir = sys.argv[1]
    with open(os.path.join(out_dir, f"world_{jax.process_index()}.txt"), "w") as f:
        f.write(f"{pc}:{n}")
""")


def test_launch_jax_distributed_two_procs(tmp_path):
    """The launcher's coordinator env contract actually stitches two
    processes into one jax.distributed world (the analog of the reference's
    2-proc NCCL tests, SURVEY §4 mechanism 2)."""
    script = tmp_path / "jaxworker.py"
    script.write_text(JAX_WORKER)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--devices_per_proc", "1",
           str(script), str(tmp_path)]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=180, cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-2000:]
    w0 = (tmp_path / "world_0.txt").read_text()
    w1 = (tmp_path / "world_1.txt").read_text()
    assert w0 == "2:2" and w1 == "2:2", (w0, w1)


TRAIN_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainingCriterion

    dist.init_parallel_env()
    assert jax.process_count() == 2, jax.process_count()
    mesh = dist.build_mesh({"dp": 2, "mp": 4})   # dp across hosts, mp local
    dist.set_mesh(mesh)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                    max_position_embeddings=32, intermediate_size=128)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(model, opt, lambda ids, lbl: crit(model(ids), lbl),
                     mesh=mesh, data_axes=("dp",))
    rng = np.random.RandomState(jax.process_index())  # per-host local shard
    losses = []
    for _ in range(2):
        ids = paddle.to_tensor(rng.randint(0, 128, (2, 16)).astype("int32"))
        losses.append(float(step(ids, ids)))
    out_dir = sys.argv[1]
    with open(os.path.join(out_dir, f"loss_{jax.process_index()}.txt"), "w") as f:
        f.write(",".join(f"{l:.6f}" for l in losses))
""")


@pytest.mark.slow
def test_launch_multihost_dp_tp_training(tmp_path):
    """Full DP(cross-process) x TP(local) training through the launcher:
    two processes with 4 virtual devices each form one 8-device mesh; the
    SPMD step yields the identical global loss on both hosts."""
    script = tmp_path / "train.py"
    script.write_text(TRAIN_WORKER)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--devices_per_proc", "4",
           str(script), str(tmp_path)]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=300, cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-3000:]
    l0 = (tmp_path / "loss_0.txt").read_text()
    l1 = (tmp_path / "loss_1.txt").read_text()
    assert l0 == l1, (l0, l1)   # SPMD: same global loss on every host
    vals = [float(x) for x in l0.split(",")]
    assert all(np.isfinite(v) for v in vals)


# ----------------------------------------------------- elastic scale in/out
ELASTIC_WORKER = textwrap.dedent("""
    import os, sys, time
    rank = os.environ["PADDLE_TPU_PROCESS_ID"]
    world = os.environ["PADDLE_TPU_NUM_PROCESSES"]
    out_dir = sys.argv[1]
    secs = float(sys.argv[2])
    with open(os.path.join(out_dir, f"gen_{rank}_{world}.txt"), "a") as f:
        f.write(f"{rank}/{world}\\n")
    time.sleep(secs)
""")


def _spawn_node(tmp_path, master, nnodes, secs, ttl="1.0", log=None):
    script = tmp_path / "ew.py"
    if not script.exists():
        script.write_text(ELASTIC_WORKER)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nnodes", nnodes, "--master", master, "--elastic_level", "2",
           "--elastic_ttl", ttl, "--poll_interval", "0.2",
           "--hold_patience", "3",
           str(script), str(tmp_path), str(secs)]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            cwd="/root/repo")


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_launch_elastic_scale_up(tmp_path):
    """Node joins a running 1:2 job: the incumbent rebuilds the rank table
    (nnodes 1 -> 2) and restarts its trainers (reference: manager.py:126
    join -> RESTART)."""
    master = f"127.0.0.1:{_free_port()}"
    a = _spawn_node(tmp_path, master, "1:2", secs=120)
    # wait for generation 1 (world=1) to start
    t0 = time.time()
    while not (tmp_path / "gen_0_1.txt").exists():
        assert time.time() - t0 < 90, "gen1 never started"
        assert a.poll() is None, a.communicate()[1]
        time.sleep(0.2)
    b = _spawn_node(tmp_path, master, "1:2", secs=2)
    t0 = time.time()
    # incumbent must restart into a 2-node world: rank 0 of world 2
    while not (tmp_path / "gen_0_2.txt").exists():
        assert time.time() - t0 < 120, (a.poll(), b.poll())
        time.sleep(0.2)
    assert (tmp_path / "gen_1_2.txt").exists() or \
        _wait_file(tmp_path / "gen_1_2.txt", 60)
    a.kill(); b.kill()
    a.communicate(); b.communicate()


@pytest.mark.slow
def test_launch_elastic_scale_down(tmp_path):
    """Node dies mid-job: the survivor notices the lost heartbeat, shrinks
    the world (nnodes 2 -> 1), and restarts trainers (reference:
    manager.py leave -> RESTART; FAULT_TOLERANCE would HOLD)."""
    master = f"127.0.0.1:{_free_port()}"
    a = _spawn_node(tmp_path, master, "1:2", secs=180)
    assert _wait_file(tmp_path / "gen_0_1.txt", 90)
    b = _spawn_node(tmp_path, master, "1:2", secs=180)
    assert _wait_file(tmp_path / "gen_0_2.txt", 120)  # two-node generation up
    gen1 = tmp_path / "gen_0_1.txt"
    base = gen1.read_text()                        # BEFORE the kill (race)
    b.kill()                                       # hard kill: no dereg
    b.communicate()
    # survivor must rebuild to world=1 after TTL expiry
    t0 = time.time()
    while gen1.read_text() == base:
        assert time.time() - t0 < 120, "no scale-down restart"
        assert a.poll() is None, a.communicate()[1][-2000:]
        time.sleep(0.3)
    a.kill()
    a.communicate()


def _wait_file(path, timeout):
    t0 = time.time()
    while not path.exists():
        if time.time() - t0 > timeout:
            return False
        time.sleep(0.2)
    return True


# ----------------------------------------------------------- rpc controller
RPC_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, "/root/repo")
    import paddle_tpu.distributed.rpc as rpc
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    assert os.environ["PADDLE_MASTER"]
    ep_port = int(os.environ["PADDLE_WORKER_ENDPOINT"].rsplit(":", 1)[1])
    # define BEFORE init_rpc: calls resolve functions by __main__ reference
    # on the receiving side, and a peer may dispatch the moment init_rpc
    # registers us — defining add afterwards is a race under load (seen
    # once with a heavily loaded host CPU)
    def add(a, b):
        return a + b
    me = rpc.init_rpc(f"worker{rank}")
    assert me.port == ep_port, (me.port, ep_port)  # endpoint contract honored
    if rank == 0:
        got = rpc.rpc_sync("worker1", add, args=(20, 22))
        assert got == 42, got
        with open(os.path.join(sys.argv[1], "rpc_ok.txt"), "w") as f:
            f.write(str(got))
    else:
        import time
        time.sleep(2.0)   # stay up to serve rank 0's call
    rpc.shutdown()
""")


def test_launch_rpc_mode(tmp_path):
    """--run_mode rpc wires PADDLE_MASTER / PADDLE_WORKER_ENDPOINT /
    TRAINER_ID so paddle.distributed.rpc workers rendezvous and call each
    other (reference: launch/controllers/rpc.py RpcController)."""
    script = tmp_path / "rpc_worker.py"
    script.write_text(RPC_WORKER)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--run_mode", "rpc", "--nproc_per_node", "2",
           "--start_port", "6390", str(script), str(tmp_path)]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=120, cwd="/root/repo")
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "rpc_ok.txt").read_text() == "42"


ALLREDUCE_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()
    mesh = dist.build_mesh({"dp": 2})     # one device per process
    dist.set_mesh(mesh)
    grp = dist.new_group(axis="dp")
    # each process contributes its LOCAL shard (rank+1); the psum riding
    # the dp axis crosses the OS-process boundary via jax.distributed
    local = np.full((1, 4), float(rank + 1), np.float32)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), local)
    f = jax.jit(jax.shard_map(
        lambda x: dist.all_reduce(x, group=grp),
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))
    out = f(garr)
    shard = np.asarray(out.addressable_shards[0].data)
    out_dir = sys.argv[1]
    with open(os.path.join(out_dir, f"ar_{rank}.txt"), "w") as fh:
        fh.write(",".join(str(float(v)) for v in shard.ravel()))
""")


@pytest.mark.slow
def test_launch_allreduce_across_processes(tmp_path):
    """A REAL cross-process collective (VERDICT r3 missing #1): two OS
    processes stitched by jax.distributed.initialize on CPU run
    dist.all_reduce and both observe the global sum — the analog of the
    reference's 2-proc collective tests (unittests/collective/)."""
    script = tmp_path / "ar.py"
    script.write_text(ALLREDUCE_WORKER)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--devices_per_proc", "1",
           str(script), str(tmp_path)]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=300, cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-3000:]
    a0 = (tmp_path / "ar_0.txt").read_text()
    a1 = (tmp_path / "ar_1.txt").read_text()
    assert a0 == a1 == "3.0,3.0,3.0,3.0", (a0, a1)


PP_TRAIN_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import GPTConfig, GPTStackedForCausalLM

    dist.init_parallel_env()
    assert jax.process_count() == 2, jax.process_count()
    # pp OUTERMOST: stage 0 lives on process 0's devices, stage 1 on
    # process 1's — the 1F1B ppermute boundary transfers cross the REAL
    # OS-process boundary via jax.distributed
    mesh = dist.build_mesh({"pp": 2, "dp": 2})
    dist.set_mesh(mesh)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                    num_heads=4, max_position_embeddings=32,
                    intermediate_size=128)
    model = GPTStackedForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(model, opt,
                     lambda ids, lbl: model.loss(ids, lbl,
                                                 num_microbatches=2),
                     mesh=mesh, data_axes=("dp",))
    # dp shards are replicated over pp, so every process addresses every
    # dp shard: both hosts feed the SAME global batch (seed 0)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(2):
        ids = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype("int32"))
        losses.append(float(step(ids, ids)))
    out_dir = sys.argv[1]
    with open(os.path.join(out_dir,
                           f"pploss_{jax.process_index()}.txt"), "w") as f:
        f.write(",".join(f"{l:.6f}" for l in losses))
""")


ZERO_TRAIN_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    dist.init_parallel_env()
    assert jax.process_count() == 2, jax.process_count()
    # sdp OUTERMOST: each optimizer-state shard lives on ONE process —
    # the ZeRO-1 partition itself crosses the OS-process boundary
    mesh = dist.build_mesh({"sdp": 2, "dp": 2})
    dist.set_mesh(mesh)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=32,
                    intermediate_size=128)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    dist.shard_optimizer_state(opt, stage=1, axis="sdp")
    step = TrainStep(model, opt, lambda ids, lbl: crit(model(ids), lbl),
                     mesh=mesh, data_axes=("dp",))
    rng = np.random.RandomState(0)      # same GLOBAL batch on both hosts
    losses = []
    for _ in range(2):
        ids = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype("int32"))
        losses.append(float(step(ids, ids)))
    # the state must actually BE sharded over the process-crossing axis —
    # otherwise this test would pass even if ZeRO silently no-ops
    spec = step._opt_state[0]["moment1"].sharding.spec
    assert "sdp" in str(spec), spec
    out_dir = sys.argv[1]
    with open(os.path.join(out_dir,
                           f"zloss_{jax.process_index()}.txt"), "w") as f:
        f.write(",".join(f"{l:.6f}" for l in losses))
""")


EP_TRAIN_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    dist.init_parallel_env()
    assert jax.process_count() == 2, jax.process_count()
    # ep OUTERMOST: each expert lives on ONE process — the MoE
    # all_to_all dispatch itself crosses the OS-process boundary
    mesh = dist.build_mesh({"ep": 2, "dp": 2})
    dist.set_mesh(mesh)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=32,
                    intermediate_size=128, moe_num_experts=2,
                    moe_every_n_layers=2, moe_gate="gshard")
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(model, opt,
                     lambda a, b: model.loss(a, b, chunk_size=64),
                     mesh=mesh, data_axes=("dp",))
    rng = np.random.RandomState(0)      # same GLOBAL batch on both hosts
    losses = []
    for _ in range(2):
        ids = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype("int32"))
        losses.append(float(step(ids, ids)))
    # expert weights must actually shard over the process-crossing axis
    moe = [b for b in model.gpt.h if b.is_moe][0].mlp
    assert "ep" in str(moe.w1._data.sharding.spec), \\
        moe.w1._data.sharding.spec
    out_dir = sys.argv[1]
    with open(os.path.join(out_dir,
                           f"eloss_{jax.process_index()}.txt"), "w") as f:
        f.write(",".join(f"{l:.6f}" for l in losses))
""")


SP_TRAIN_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    dist.init_parallel_env()
    assert jax.process_count() == 2, jax.process_count()
    # sp OUTERMOST: each sequence half lives on ONE process — the ring
    # attention's K/V ppermute rotation crosses the OS-process boundary
    mesh = dist.build_mesh({"sp": 2, "dp": 2})
    dist.set_mesh(mesh)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=32,
                    intermediate_size=128, sequence_parallel="ring")
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(model, opt, lambda ids, lbl: crit(model(ids), lbl),
                     mesh=mesh, data_axes=("dp",))
    rng = np.random.RandomState(0)      # same GLOBAL batch on both hosts
    losses = []
    for _ in range(2):
        ids = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype("int32"))
        losses.append(float(step(ids, ids)))
    out_dir = sys.argv[1]
    with open(os.path.join(out_dir,
                           f"sloss_{jax.process_index()}.txt"), "w") as f:
        f.write(",".join(f"{l:.6f}" for l in losses))
""")


@pytest.mark.slow
def test_launch_ring_attention_across_processes_matches_single_process(
        tmp_path):
    """Ring-attention sp where the SEQUENCE halves live on different OS
    processes: {sp:2, dp:2} mesh with sp across the boundary — the ring's
    K/V ppermute hops ride jax.distributed. With this, every parallelism
    axis (dp, mp, pp, sdp, ep, sp) has real cross-process parity
    coverage. Loss matches a single-process no-sp replay."""
    script = tmp_path / "strain.py"
    script.write_text(SP_TRAIN_WORKER)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--devices_per_proc", "2",
           str(script), str(tmp_path)]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=300, cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-3000:]
    l0 = (tmp_path / "sloss_0.txt").read_text()
    l1 = (tmp_path / "sloss_1.txt").read_text()
    assert l0 == l1, (l0, l1)
    multi = [float(x) for x in l0.split(",")]

    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=32,
                    intermediate_size=128, sequence_parallel=None)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(model, opt, lambda ids, lbl: crit(model(ids), lbl))
    rng = np.random.RandomState(0)
    single = []
    for _ in range(2):
        ids = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype("int32"))
        single.append(float(step(ids, ids)))
    np.testing.assert_allclose(multi, single, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_launch_moe_experts_across_processes_matches_single_process(tmp_path):
    """EP where the EXPERTS live on different OS processes (r5: the last
    parallelism axis never exercised across a process boundary): 2 procs x
    2 devices, {ep:2, dp:2} mesh with ep across the boundary — the MoE
    dispatch/combine collectives ride jax.distributed. Loss matches a
    single-process no-mesh replay on the same global batch."""
    script = tmp_path / "etrain.py"
    script.write_text(EP_TRAIN_WORKER)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--devices_per_proc", "2",
           str(script), str(tmp_path)]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=300, cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-3000:]
    l0 = (tmp_path / "eloss_0.txt").read_text()
    l1 = (tmp_path / "eloss_1.txt").read_text()
    assert l0 == l1, (l0, l1)
    multi = [float(x) for x in l0.split(",")]

    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=32,
                    intermediate_size=128, moe_num_experts=2,
                    moe_every_n_layers=2, moe_gate="gshard")
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(model, opt,
                     lambda a, b: model.loss(a, b, chunk_size=64))
    rng = np.random.RandomState(0)
    single = []
    for _ in range(2):
        ids = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype("int32"))
        single.append(float(step(ids, ids)))
    np.testing.assert_allclose(multi, single, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_launch_zero_shard_across_processes_matches_single_process(tmp_path):
    """ZeRO-1 where the optimizer-state SHARDS live on different OS
    processes (r5: exercises the make_array_from_callback assembly for
    process-crossing state sharding): 2 procs x 2 devices, {sdp:2, dp:2}
    mesh with sdp across the boundary; loss matches single-process."""
    script = tmp_path / "ztrain.py"
    script.write_text(ZERO_TRAIN_WORKER)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--devices_per_proc", "2",
           str(script), str(tmp_path)]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=300, cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-3000:]
    l0 = (tmp_path / "zloss_0.txt").read_text()
    l1 = (tmp_path / "zloss_1.txt").read_text()
    assert l0 == l1, (l0, l1)
    multi = [float(x) for x in l0.split(",")]

    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=32,
                    intermediate_size=128)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(model, opt, lambda ids, lbl: crit(model(ids), lbl))
    rng = np.random.RandomState(0)
    single = []
    for _ in range(2):
        ids = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype("int32"))
        single.append(float(step(ids, ids)))
    np.testing.assert_allclose(multi, single, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_launch_pp_across_processes_matches_single_process(tmp_path):
    """dp x pp training where the PIPELINE axis crosses the OS-process
    boundary (VERDICT r4 #8 — the last parallelism axis never exercised
    across processes): 2 processes x 2 devices form a {pp:2, dp:2} mesh
    with stage boundaries between processes; the global loss matches a
    single-process replay of the same batch. Reference anchor:
    unittests/test_dist_base.py:899 multi-process parity strategy."""
    script = tmp_path / "pptrain.py"
    script.write_text(PP_TRAIN_WORKER)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--devices_per_proc", "2",
           str(script), str(tmp_path)]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=300, cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-3000:]
    l0 = (tmp_path / "pploss_0.txt").read_text()
    l1 = (tmp_path / "pploss_1.txt").read_text()
    assert l0 == l1, (l0, l1)           # SPMD: same global loss everywhere
    multi = [float(x) for x in l0.split(",")]

    # single-process replay (no mesh), same model/seed/batch
    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import GPTConfig, GPTStackedForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                    num_heads=4, max_position_embeddings=32,
                    intermediate_size=128)
    model = GPTStackedForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(model, opt,
                     lambda ids, lbl: model.loss(ids, lbl,
                                                 num_microbatches=2))
    rng = np.random.RandomState(0)
    single = []
    for _ in range(2):
        ids = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype("int32"))
        single.append(float(step(ids, ids)))
    np.testing.assert_allclose(multi, single, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_launch_multihost_matches_single_process(tmp_path):
    """2-process DP training loss == single-process replay on the same
    global batch (the reference's test_dist_base.py:899 strategy, here
    ACROSS REAL OS PROCESS BOUNDARIES rather than a virtual mesh)."""
    script = tmp_path / "train.py"
    script.write_text(TRAIN_WORKER)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--devices_per_proc", "4",
           str(script), str(tmp_path)]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=300, cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-3000:]
    multi = [float(x) for x in (tmp_path / "loss_0.txt").read_text().split(",")]

    # single-process replay: same seed model, global batch = concat of the
    # two hosts' per-rank shards (rank r draws from RandomState(r))
    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                    max_position_embeddings=32, intermediate_size=128)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(model, opt, lambda ids, lbl: crit(model(ids), lbl))
    rngs = [np.random.RandomState(0), np.random.RandomState(1)]
    single = []
    for _ in range(2):
        ids = np.concatenate([r.randint(0, 128, (2, 16)).astype("int32")
                              for r in rngs])
        t = paddle.to_tensor(ids)
        single.append(float(step(t, t)))
    np.testing.assert_allclose(multi, single, rtol=2e-5, atol=2e-5)
