"""Distributed checkpoint tests: sharded save, resharding restore, async
(SURVEY §5.4 — dist_save/dist_load + converter re-partitioning parity)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import mesh as dmesh


def _mesh(shape, names):
    return Mesh(np.array(jax.devices()).reshape(shape), names)


def test_save_load_plain(tmp_path):
    state = {"w": paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4)),
             "step": 7}
    ckpt.save_state_dict(state, str(tmp_path / "ck"))
    out = ckpt.load_state_dict(str(tmp_path / "ck"))
    np.testing.assert_allclose(out["w"].numpy(), state["w"].numpy())
    assert out["step"] == 7


def test_reshard_on_restore(tmp_path):
    m1 = _mesh((2, 4), ("x", "y"))
    arr = jax.device_put(jnp.arange(64.).reshape(8, 8),
                         NamedSharding(m1, P("x", "y")))
    ckpt.save_state_dict({"w": paddle.Tensor(arr)}, str(tmp_path / "ck"))

    # restore onto a DIFFERENT mesh topology + layout
    m2 = _mesh((4, 2), ("x", "y"))
    tgt = paddle.Tensor(jnp.zeros((8, 8)))
    tgt.pspec = P("y", "x")
    out = ckpt.load_state_dict(str(tmp_path / "ck"), {"w": tgt}, mesh=m2)
    w = out["w"]
    np.testing.assert_allclose(np.asarray(w._data), np.arange(64.).reshape(8, 8))
    # sharded as requested on the new mesh: each shard is 8/2 x 8/4
    shard = next(iter(w._data.addressable_shards))
    assert shard.data.shape == (4, 2)


def test_async_save(tmp_path):
    state = {"w": paddle.to_tensor(np.random.randn(16, 16).astype(np.float32))}
    h = ckpt.save_state_dict(state, str(tmp_path / "ck"), async_save=True)
    h.wait()
    out = ckpt.load_state_dict(str(tmp_path / "ck"))
    np.testing.assert_allclose(out["w"].numpy(), state["w"].numpy())


def test_model_roundtrip_with_optimizer(tmp_path):
    model = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    opt = paddle.optimizer.Adam(parameters=model.parameters())
    x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
    loss = model(x).sum()
    loss.backward()
    opt.step()
    opt.clear_grad()

    want = {k: v.numpy().copy() for k, v in model.state_dict().items()}
    ckpt.save_model(model, str(tmp_path / "ck"), optimizer=opt)

    for p in model.parameters():
        p.set_value(np.zeros_like(p.numpy()))
    ckpt.load_model(model, str(tmp_path / "ck"), optimizer=opt)
    for k, v in model.state_dict().items():
        np.testing.assert_allclose(v.numpy(), want[k], err_msg=k)
