"""Multi-chip sharded paged serving (ISSUE 16): tensor-parallel decode
over the mesh's `mp` (head) axis with a PROVEN communication plan.

Covers the per-shard invariant suite: greedy output bit-identical across
shard counts 1 vs 2 vs 4 on a CPU host-platform mesh for plain,
prefix-cached, chunked-prefill, and spec-decode traffic; int8 scale
pools sharded WITH their codes (co-sharding, so dequant never crosses
shards); COW copies staying shard-local (zero collectives in the COW
executable); zero post-warmup jit misses at a fixed shard count; the
spill codec's shard-consistency pin (read_block gathers to ONE
full-width host payload whatever the shard count, write_block reshards
it back); and the config/engine validation for the `shards` knob.

The collective-inventory side of the plan (decode = mp-group all-reduce
only, no partitioner-inserted KV gather, pools donated) is gated
statically by `tools/graph_lint.py gpt-paged-sharded` — these tests pin
the numerics the lint cannot see.
"""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
from paddle_tpu.inference import ServingConfig, ServingEngine
from paddle_tpu.jit.api import compile_cache_misses
from paddle_tpu.models import GPTConfig, GPTForCausalLM

CAP, NEW = 8, 6
SHARDS = (1, 2, 4)


@pytest.fixture(scope="module")
def served_model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m, cfg


def _engine(m, shards, **kw):
    base = dict(max_batch=2, prompt_cap=CAP, max_new_tokens=NEW,
                decode_chunk=2, paged=True, kv_block=4, shards=shards)
    base.update(kw)
    return ServingEngine(m, ServingConfig(**base))


def _prompts(cfg, lens, seed=1):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, cfg.vocab_size, (n,)).astype(np.int64)
            for n in lens]


def _serve(eng, prompts):
    """{prompt bytes: token list} so cross-engine comparison is
    order-independent."""
    for p in prompts:
        eng.submit(p)
    return {tuple(r.prompt.tolist()): list(r.tokens)
            for r in eng.drain()}


# -------------------------------------------- shard-count bit-identity

def test_plain_traffic_bit_identical_across_shards(served_model):
    """The headline oracle: the SAME greedy tokens at 1, 2 and 4 shards
    for mixed ragged prompts — head-sharding is a layout choice, never a
    numerics choice — and the 1-shard engine already matches the static
    generator, so all shard counts transitively match it too."""
    m, cfg = served_model
    lens = [CAP, 7, 3, 5]
    prompts = _prompts(cfg, lens, seed=3)
    ref = m.generate_static_ragged(
        paddle.to_tensor(np.stack([np.pad(p, (0, CAP - len(p)))
                                   for p in prompts])),
        lens, max_new_tokens=NEW).numpy()
    got = {}
    for s in SHARDS:
        got[s] = _serve(_engine(m, s), prompts)
    assert got[1] == got[2] == got[4]
    for i, p in enumerate(prompts):
        np.testing.assert_array_equal(
            got[1][tuple(p.tolist())], ref[i, CAP:])


def test_prefix_cached_traffic_bit_identical_across_shards(served_model):
    """Prefix-cache hit paths (zero-prefill admission, suffix prefill,
    COW) produce shard-count-invariant tokens: both the cold pass and
    the warm (cached) pass agree across 1/2/4 shards."""
    m, cfg = served_model
    prompts = _prompts(cfg, [CAP, CAP, 5], seed=7)
    cold, warm = {}, {}
    for s in SHARDS:
        eng = _engine(m, s, prefix_cache=True, kv_blocks=48)
        cold[s] = _serve(eng, prompts)
        warm[s] = _serve(eng, prompts)       # full hits + suffix hits
    assert cold[1] == cold[2] == cold[4]
    assert warm[1] == warm[2] == warm[4]
    assert cold[1] == warm[1]                # cache itself is invisible


def test_chunked_prefill_bit_identical_across_shards(served_model):
    """prefill_chunk caps per-step prefill work; the chunk boundary must
    not interact with the head sharding (each chunk writes only its own
    shard's H-slice of the pool)."""
    m, cfg = served_model
    prompts = _prompts(cfg, [CAP, 7, CAP], seed=11)
    got = {s: _serve(_engine(m, s, prefill_chunk=3), prompts)
           for s in SHARDS}
    assert got[1] == got[2] == got[4]


def test_spec_decode_bit_identical_across_shards(served_model):
    """Speculative verify windows accept/reject IDENTICALLY at every
    shard count — argmax over replicated logits, so draft acceptance is
    shard-invariant (a vocab-sharded argmax would tie-break per shard
    and silently fork the sequence)."""
    m, cfg = served_model
    prompts = _prompts(cfg, [CAP, CAP], seed=13)
    repeats = prompts + prompts              # second pass drafts + accepts
    got = {}
    for s in SHARDS:
        eng = _engine(m, s, prefix_cache=True, kv_blocks=64,
                      spec_decode=True, spec_k=3)
        first = _serve(eng, prompts)
        second = _serve(eng, prompts)        # trie drafting kicks in
        assert first == second
        got[s] = (first, second)
        assert eng.metrics.counters["spec_windows"] > 0
    assert got[1] == got[2] == got[4]


# ------------------------------------------------- pool sharding layout

def _pool_specs(eng):
    return [[(p.ndim, getattr(p.sharding, "spec", None)) for p in layer]
            for layer in eng._pools]


def test_pools_head_sharded_and_int8_scales_co_sharded(served_model):
    """Device pools carry the declared head sharding: 4D planes
    [num_blocks, bs, H, D] shard H over mp; the int8 scale pools
    [num_blocks, bs, H] shard their H WITH the codes, so a shard
    dequantizes its own heads without ever reading a remote scale."""
    from jax.sharding import PartitionSpec as P
    m, cfg = served_model
    for cache_dtype in (None, "int8"):
        eng = _engine(m, 2, cache_dtype=cache_dtype)
        for layer in _pool_specs(eng):
            for ndim, spec in layer:
                want = P(None, None, "mp", None) if ndim == 4 \
                    else P(None, None, "mp")
                assert spec == want, (ndim, spec)
        if cache_dtype == "int8":
            dts = {str(np.asarray(p).dtype)[:4] for layer in eng._pools
                   for p in layer}
            assert "int8" in dts           # codes really are int8 planes


def test_unsharded_engine_pools_uncommitted(served_model):
    """shards=1 (and the default) never builds a mesh: pools stay plain
    single-device arrays, so the single-chip path is byte-for-byte the
    pre-ISSUE-16 engine."""
    m, cfg = served_model
    eng = _engine(m, 1)
    assert eng._mesh is None
    for layer in _pool_specs(eng):
        for ndim, spec in layer:
            assert spec is None


# ------------------------------------------------- COW shard locality

def test_cow_copy_is_shard_local(served_model):
    """The COW block copy at mp>1 compiles to ZERO collectives: each
    shard copies its own H-slice (gather source and scatter target carry
    the same head sharding), so sharing a prefix never costs a hop."""
    from paddle_tpu.analysis import lint_capture
    m, cfg = served_model
    eng = _engine(m, 2, prefix_cache=True, kv_blocks=48)
    prompts = _prompts(cfg, [CAP], seed=17)
    _serve(eng, prompts)
    with lint_capture() as calls:
        _serve(eng, prompts)                 # full hit -> COW copy
    cow = [c for c in calls
           if isinstance(c[0], tuple) and c[0][0] == "paged_cow"]
    assert cow, "full-hit repeat did not take the COW path"
    kind, fn, (args, kwargs) = cow[0]
    with eng._mesh_scope():
        txt = fn.lower(*args, **kwargs).compile().as_text()
    for coll in ("all-reduce", "all-gather", "all-to-all",
                 "collective-permute", "reduce-scatter"):
        assert coll not in txt, f"COW copy lowered a {coll}"


# -------------------------------------------- steady-state compile cache

def test_zero_post_warmup_misses_sharded(served_model):
    """At a fixed shard count the executable set is closed: after one
    pass of mixed traffic, further traffic (same length profile) causes
    ZERO jit cache misses — resharding never sneaks in a recompile."""
    m, cfg = served_model
    eng = _engine(m, 2, prefix_cache=True, kv_blocks=48)
    _serve(eng, _prompts(cfg, [CAP, 7, 3], seed=19))
    before = compile_cache_misses()
    _serve(eng, _prompts(cfg, [CAP, 7, 3], seed=23))
    assert compile_cache_misses() == before


# -------------------------------------------- spill codec shard pin

def test_spill_payload_shard_consistent_round_trip(served_model):
    """The spill codec's SHARD CONSISTENCY contract: read_block gathers
    ONE full-width host payload whatever the shard count (same shapes,
    same dtypes — the mp axis never leaks into the host format), the
    round trip is BITWISE within an engine (gather → reshard-scatter →
    gather returns the same bytes, and the rehydrated pool keeps its
    head sharding), and a payload read from the 2-shard pool writes
    cleanly into the 1-shard pool and back — one codec, any shard
    count. Across shard counts the VALUES only match to float tolerance:
    the row-parallel all-reduce reorders the partial-sum reduction, so
    layer>0 KV differs in the last ulps (greedy tokens stay
    bit-identical — that oracle is the parity tests above)."""
    m, cfg = served_model
    prompts = _prompts(cfg, [CAP], seed=29)
    for cache_dtype in (None, "int8"):
        engs, payloads = {}, {}
        for s in (1, 2):
            eng = _engine(m, s, prefix_cache=True, kv_blocks=48,
                          cache_dtype=cache_dtype)
            _serve(eng, prompts)
            blk = int(eng._prefix.match(prompts[0])[0][0])
            engs[s] = eng
            payloads[s] = eng._pool.read_block(eng._pools, blk)

        # round trip within the SHARDED engine: bitwise, sharding kept
        eng = engs[2]
        blk = int(eng._prefix.match(prompts[0])[0][0])
        src = [tuple(np.asarray(p)[blk].copy() for p in layer)
               for layer in eng._pools]
        dst = eng._pool.take(1)[0]
        eng._pools = eng._pool.write_block(eng._pools, dst, payloads[2])
        for li, layer in enumerate(eng._pools):
            for pi, p in enumerate(layer):
                np.testing.assert_array_equal(
                    np.asarray(p)[dst], src[li][pi])
                assert getattr(p.sharding, "spec", None) is not None
        eng._pool.release([dst])

        # one host format: same geometry, values within float tolerance
        assert len(payloads[1]) == len(payloads[2])
        for a, b in zip(payloads[1], payloads[2]):
            a, b = np.asarray(a), np.asarray(b)
            assert a.shape == b.shape and a.dtype == b.dtype
            if a.dtype == np.int8:
                assert np.mean(a != b) < 0.01   # quantized: rare ulp flips
            else:
                np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

        # cross-shard-count rehydrate: a block spilled at 2 shards
        # restores BITWISE into the 1-shard pool
        one = engs[1]
        dst = one._pool.take(1)[0]
        one._pools = one._pool.write_block(one._pools, dst, payloads[2])
        back = one._pool.read_block(one._pools, dst)
        for a, b in zip(payloads[2], back):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        one._pool.release([dst])


# ------------------------------------------------------- validation

def test_shards_config_validation(served_model):
    from paddle_tpu.analysis.findings import ConfigValidationError
    m, cfg = served_model
    with pytest.raises(ValueError, match="shards must be >= 1"):
        ServingConfig(paged=True, shards=0)
    with pytest.raises(ConfigValidationError) as ei:
        ServingConfig(shards=2)
    assert ei.value.finding.code == "sharded_requires_paged"
    # head divisibility is an ENGINE check (needs the model)
    with pytest.raises(ValueError, match="num_heads"):
        _engine(m, 3)
    # more shards than local devices names the XLA escape hatch
    with pytest.raises(ValueError, match="device"):
        _engine(m, 2 * len(jax.devices()))
