"""Fused LM-head + cross-entropy kernel parity (interpret mode on CPU).

Covers the c_softmax_with_cross_entropy_op.cu capability class, extended:
the head matmul itself is inside the loss (logits never materialized in the
forward). Checks forward loss parity vs the dense XLA formula, dx/dW grad
parity (the backward is closed-form from the saved lse, not autodiff), the
ragged final vocab tile, both weight layouts, and the array-level
fused_linear_cross_entropy_array dispatch equivalence.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.linear_ce import linear_cross_entropy


def _dense_ce(x, w, labels, w_layout="vh"):
    logits = (x @ w.T if w_layout == "vh" else x @ w).astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return lse - gold


def _rand(t, h, v, seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(t, h).astype(np.float32)) * 0.5
    w = jnp.asarray(rng.randn(v, h).astype(np.float32)) * 0.2
    labels = jnp.asarray(rng.randint(0, v, t).astype(np.int32))
    return x, w, labels


@pytest.mark.parametrize("v", [1024, 1000])   # aligned + ragged tail tile
def test_linear_ce_forward_matches_dense(v):
    x, w, labels = _rand(64, 128, v)
    got = linear_cross_entropy(x, w, labels, block_t=32, block_v=256,
                               interpret=True)
    want = _dense_ce(x, w, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_linear_ce_hv_layout():
    x, w, labels = _rand(32, 128, 640, seed=2)
    got = linear_cross_entropy(x, w.T, labels, w_layout="hv", block_t=16,
                               block_v=256, interpret=True)
    want = _dense_ce(x, w, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("w_layout", ["vh", "hv"])
def test_linear_ce_grads_match_dense(w_layout):
    x, w, labels = _rand(48, 128, 520, seed=1)
    wa = w if w_layout == "vh" else w.T
    # non-uniform per-token upstream grads exercise the g-scaling path
    coef = jnp.asarray(np.random.RandomState(7).rand(48).astype(np.float32))

    def f_kernel(xx, ww):
        return jnp.sum(coef * linear_cross_entropy(
            xx, ww, labels, w_layout=w_layout, block_t=16, block_v=128,
            bwd_chunks=3, interpret=True))

    def f_ref(xx, ww):
        return jnp.sum(coef * _dense_ce(xx, ww, labels, w_layout=w_layout))

    gx_k, gw_k = jax.grad(f_kernel, argnums=(0, 1))(x, wa)
    gx_r, gw_r = jax.grad(f_ref, argnums=(0, 1))(x, wa)
    np.testing.assert_allclose(np.asarray(gx_k), np.asarray(gx_r),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gw_k), np.asarray(gw_r),
                               rtol=2e-4, atol=2e-4)


def test_array_level_dispatch_parity():
    # forced Pallas path == legacy chunked-XLA path at [B, S, H] rank
    import os
    from paddle_tpu.incubate.nn.functional import (
        fused_linear_cross_entropy_array)
    x, w, labels = _rand(64, 128, 1000, seed=3)
    x3, l3 = x.reshape(2, 32, 128), labels.reshape(2, 32)
    legacy = fused_linear_cross_entropy_array(x3, w, l3, chunk_size=16)
    os.environ["PADDLE_TPU_LINEAR_CE"] = "1"
    try:
        # interpret-mode via the public wrapper is not plumbed through the
        # array API; on CPU the gate needs the env force AND interpret —
        # call the kernel path directly at the same shapes instead
        got = linear_cross_entropy(x, w, labels, interpret=True)
    finally:
        del os.environ["PADDLE_TPU_LINEAR_CE"]
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(legacy).reshape(-1),
                               rtol=1e-5, atol=1e-5)
