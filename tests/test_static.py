"""Static graph tests — build/run parity with eager, training, export.

Mirrors the reference's static-mode coverage (SURVEY §4: OpTest runs every op
through BOTH the static executor and dygraph and compares; here we compare
recorded-program replay against the eager path and numpy)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_data_and_simple_ops():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 3], "float32")
        y = paddle.exp(x) + 1.0
        z = paddle.sum(y, axis=1)
    exe = static.Executor()
    xv = np.random.randn(4, 3).astype(np.float32)
    (zv,) = exe.run(main, feed={"x": xv}, fetch_list=[z])
    np.testing.assert_allclose(zv, (np.exp(xv) + 1.0).sum(1), rtol=1e-5)


def test_fc_matches_eager():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 8], "float32")
        y = static.nn.fc(x, 4)
    # the fc layer's parameters were created eagerly and recorded by ref
    w, b = main.all_parameters()[:2]
    exe = static.Executor()
    xv = np.random.randn(2, 8).astype(np.float32)
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    expect = xv @ np.asarray(w._data) + np.asarray(b._data)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_program_guard_isolation():
    p1, p2 = static.Program(), static.Program()
    with static.program_guard(p1):
        a = static.data("a", [2], "float32")
        _ = a * 2.0
    with static.program_guard(p2):
        b = static.data("b", [2], "float32")
        _ = b + 1.0
    assert len(p1._nodes) == 1 and len(p2._nodes) == 1
    assert static.default_main_program() is not p1


def test_append_backward_grads():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 3], "float32")
        lin = paddle.nn.Linear(3, 1)
        loss = paddle.mean(lin(x))
        pairs = static.append_backward(loss)
    exe = static.Executor()
    xv = np.random.randn(4, 3).astype(np.float32)
    main._optimizer = paddle.optimizer.SGD(learning_rate=0.0,
                                           parameters=main.all_parameters())
    fetches = exe.run(main, feed={"x": xv},
                      fetch_list=[loss] + [g for _, g in pairs])
    w_grad = fetches[1]
    # d(mean(xW+b))/dW = mean over batch of x / 1
    np.testing.assert_allclose(w_grad.squeeze(), xv.mean(0) / 1.0, rtol=1e-4, atol=1e-5)


def test_sgd_minimize_trains():
    np.random.seed(0)
    xv = np.random.randn(64, 4).astype(np.float32)
    true_w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    yv = xv @ true_w

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [64, 4], "float32")
        y = static.data("y", [64, 1], "float32")
        pred = static.nn.fc(x, 1)
        loss = paddle.mean((pred - y) ** 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    exe = static.Executor()
    losses = []
    for _ in range(60):
        (lv,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.01, losses[::10]


def test_static_dropout_fresh_mask_per_run():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [1000], "float32")
        y = paddle.nn.functional.dropout(x, p=0.5, training=True)
    exe = static.Executor()
    xv = np.ones(1000, np.float32)
    (a,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    (b,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    assert not np.array_equal(a, b), "dropout mask must differ across runs"
    assert 0.3 < (a == 0).mean() < 0.7


def test_gradients_wrt_input():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3], "float32")
        y = paddle.sum(x * x)
        (gx,) = static.gradients(y, x)
    exe = static.Executor()
    xv = np.array([1.0, -2.0, 3.0], np.float32)
    (g,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * xv, rtol=1e-5)


def test_executor_recompiles_on_new_shape():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [-1, 3], "float32")
        y = paddle.sum(paddle.tanh(x), axis=1)
    exe = static.Executor()
    for bs in (2, 5):
        xv = np.random.randn(bs, 3).astype(np.float32)
        (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
        np.testing.assert_allclose(out, np.tanh(xv).sum(1), rtol=1e-5)


def test_save_load_inference_model(tmp_path):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 8], "float32")
        y = static.nn.fc(x, 4)
    exe = static.Executor()
    xv = np.random.randn(2, 8).astype(np.float32)
    (want,) = exe.run(main, feed={"x": xv}, fetch_list=[y])

    prefix = str(tmp_path / "model")
    static.save_inference_model(prefix, [x], [y], exe, program=main)
    prog, feed_names, fetch_names = static.load_inference_model(prefix)
    assert feed_names == ["x"]
    (got,) = prog.run(xv)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_static_save_load_params(tmp_path):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 4], "float32")
        y = static.nn.fc(x, 2)
    w = main.all_parameters()[0]
    before = np.asarray(w._data).copy()
    prefix = str(tmp_path / "ckpt")
    static.save(main, prefix)
    w._data = w._data * 0
    static.load(main, prefix)
    np.testing.assert_allclose(np.asarray(w._data), before)


def test_scope_lookup():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 4], "float32")
        y = static.nn.fc(x, 2)
    exe = static.Executor()
    exe.run(main, feed={"x": np.zeros((2, 4), np.float32)}, fetch_list=[y])
    w = main.all_parameters()[0]
    assert static.global_scope().find_var(w.name) is not None


def test_clone_for_test_strips_dropout():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [100], "float32")
        y = paddle.nn.functional.dropout(x, p=0.5, training=True) * 2.0
    infer = main.clone(for_test=True)
    exe = static.Executor()
    xv = np.ones(100, np.float32)
    (out,) = exe.run(infer, feed={"x": xv}, fetch_list=[infer._vars[y.vid]])
    np.testing.assert_allclose(out, 2.0 * xv)  # dropout removed, pure scale


def test_minimize_outside_program_guard():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [8, 2], "float32")
        y = static.data("y", [8, 1], "float32")
        loss = paddle.mean((static.nn.fc(x, 1) - y) ** 2)
    # minimize called AFTER the guard must attach to loss's own program
    paddle.optimizer.SGD(learning_rate=0.02).minimize(loss)
    assert main._optimizer is not None
    exe = static.Executor()
    xv = np.random.randn(8, 2).astype(np.float32)
    yv = np.random.randn(8, 1).astype(np.float32)
    l0 = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])[0]
    for _ in range(30):
        l1 = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])[0]
    assert float(l1) < float(l0)


def test_gradients_wrt_intermediate():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3], "float32")
        h = paddle.tanh(x)
        y = paddle.sum(h * h)
        (gh,) = static.gradients(y, h)
    exe = static.Executor()
    xv = np.array([0.1, -0.5, 2.0], np.float32)
    (g,) = exe.run(main, feed={"x": xv}, fetch_list=[gh])
    np.testing.assert_allclose(g, 2 * np.tanh(xv), rtol=1e-5)


def test_dropout_batch_independent_with_dynamic_batch():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [-1, 64], "float32")
        y = paddle.nn.functional.dropout(x, p=0.5, training=True)
    exe = static.Executor()
    xv = np.ones((32, 64), np.float32)
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    masks = (out == 0)
    # rows must not all share one mask (build-time shape was batch=1)
    assert not all(np.array_equal(masks[0], masks[i]) for i in range(1, 32))


def test_while_loop_static():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [1], "float32")
        i = paddle.full([1], 0.0)
        s = paddle.full([1], 0.0)

        def cond_fn(i, s):
            return i < 5.0

        def body_fn(i, s):
            return i + 1.0, s + x * i

        i_out, s_out = static.nn.while_loop(cond_fn, body_fn, [i, s])
    exe = static.Executor()
    (sv,) = exe.run(main, feed={"x": np.array([2.0], np.float32)},
                    fetch_list=[s_out])
    # sum over i=0..4 of 2*i = 2*(0+1+2+3+4) = 20
    np.testing.assert_allclose(sv, [20.0])


def test_while_loop_eager():
    paddle.disable_static()
    try:
        i = paddle.full([1], 0.0)
        s = paddle.full([1], 1.0)
        i_out, s_out = static.nn.while_loop(
            lambda i, s: i < 3.0, lambda i, s: (i + 1.0, s * 2.0), [i, s])
        np.testing.assert_allclose(s_out.numpy(), [8.0])
    finally:
        paddle.enable_static()


def test_switch_case_static():
    main = static.Program()
    with static.program_guard(main):
        idx = static.data("idx", [1], "int32")
        x = static.data("x", [3], "float32")
        out = static.nn.switch_case(idx, [
            lambda: x * 1.0, lambda: x * 10.0],
            default=lambda: x * 0.0)
    exe = static.Executor()
    xv = np.array([1., 2., 3.], np.float32)
    for i, mult in ((0, 1.0), (1, 10.0), (7, 0.0)):
        (ov,) = exe.run(main, feed={"idx": np.array([i], np.int32), "x": xv},
                        fetch_list=[out])
        np.testing.assert_allclose(ov, xv * mult)


def test_case_static():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [1], "float32")
        out = static.nn.case(
            [(x > 2.0, lambda: x * 100.0), (x > 0.0, lambda: x * 10.0)],
            default=lambda: x)
    exe = static.Executor()
    for v, want in ((3.0, 300.0), (1.0, 10.0), (-1.0, -1.0)):
        (ov,) = exe.run(main, feed={"x": np.array([v], np.float32)},
                        fetch_list=[out])
        np.testing.assert_allclose(ov, [want])


def test_static_amp_autocast_records_policy():
    """paddle.amp.auto_cast around graph building makes whitelisted ops run
    in bf16 at replay (the reference's AMP meta-optimizer pass, recorded as
    per-node policy here)."""
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 8], "float32")
        w = static.data("w", [8, 4], "float32")
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16", level="O1"):
            y = paddle.matmul(x, w)     # whitelisted -> bf16 at replay
        z = paddle.sum(y)
    exe = static.Executor()
    xv = np.random.randn(4, 8).astype(np.float32)
    wv = np.random.randn(8, 4).astype(np.float32)
    yv, zv = exe.run(main, feed={"x": xv, "w": wv}, fetch_list=[y, z])
    assert yv.dtype.name == "bfloat16", yv.dtype
    np.testing.assert_allclose(zv.astype(np.float32), (xv @ wv).sum(),
                               rtol=2e-2)
