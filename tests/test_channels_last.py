"""Channels-last vision fast path: NCHW<->NHWC parity (convs, BN folding,
fused conv-bn-act epilogues, resnet blocks) + layout smoke steps.

The contract under test: with FLAGS_conv_channels_last set, every conv
computes with NHWC/HWIO dimension numbers (transposing at op or trunk
boundaries) and fp32 results stay allclose (rtol 1e-4) with the NCHW path.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.nn import layout


@pytest.fixture
def channels_last_flag():
    """Restore the flag after each test, whatever happens inside."""
    def set_flag(v):
        paddle.set_flags({"FLAGS_conv_channels_last": v})
    yield set_flag
    paddle.set_flags({"FLAGS_conv_channels_last": False})


def _rand(*shape, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randn(*shape).astype("float32"))


def _param(*shape, seed=1):
    t = _rand(*shape, seed=seed)
    t.stop_gradient = False
    return t


def _conv_parity(fn, x, w, channels_last_flag, rtol=1e-4, atol=1e-5,
                 **kw):
    """fn(x, w, **kw) must agree (value + input/weight grads) across the
    flag, with gradients flowing through the in-graph kernel transpose."""
    channels_last_flag(False)
    y0 = fn(x, w, **kw)
    (y0 * y0).mean().backward()
    g0w, g0x = w.grad.numpy(), x.grad.numpy()
    w.clear_grad(), x.clear_grad()
    channels_last_flag(True)
    y1 = fn(x, w, **kw)
    (y1 * y1).mean().backward()
    g1w, g1x = w.grad.numpy(), x.grad.numpy()
    w.clear_grad(), x.clear_grad()
    np.testing.assert_allclose(y0.numpy(), y1.numpy(), rtol=rtol, atol=atol)
    np.testing.assert_allclose(g0w, g1w, rtol=1e-3, atol=atol)
    np.testing.assert_allclose(g0x, g1x, rtol=1e-3, atol=atol)


class TestConvParity:
    def test_conv1d(self, channels_last_flag):
        _conv_parity(F.conv1d, _param(2, 4, 16, seed=0), _param(6, 4, 3),
                     channels_last_flag, stride=2, padding=1)

    def test_conv2d(self, channels_last_flag):
        _conv_parity(F.conv2d, _param(2, 4, 12, 12, seed=0),
                     _param(6, 4, 3, 3), channels_last_flag,
                     stride=2, padding=1)

    def test_conv2d_bias(self, channels_last_flag):
        b = _param(6, seed=3)
        _conv_parity(lambda x, w, **kw: F.conv2d(x, w, b, **kw),
                     _param(2, 4, 8, 8, seed=0), _param(6, 4, 3, 3),
                     channels_last_flag, padding="SAME")

    def test_conv2d_grouped(self, channels_last_flag):
        _conv_parity(F.conv2d, _param(2, 8, 10, 10, seed=0),
                     _param(8, 2, 3, 3), channels_last_flag,
                     groups=4, padding=1)

    def test_conv2d_dilated(self, channels_last_flag):
        _conv_parity(F.conv2d, _param(2, 4, 14, 14, seed=0),
                     _param(5, 4, 3, 3), channels_last_flag,
                     dilation=2, padding=2)

    def test_conv3d(self, channels_last_flag):
        _conv_parity(F.conv3d, _param(2, 3, 6, 8, 8, seed=0),
                     _param(4, 3, 3, 3, 3), channels_last_flag, padding=1)

    def test_conv2d_transpose_unaffected(self, channels_last_flag):
        # conv_transpose keeps its NCHW lowering; the flag must be a no-op
        x, w = _param(2, 4, 8, 8, seed=0), _param(4, 5, 3, 3)
        _conv_parity(F.conv2d_transpose, x, w, channels_last_flag,
                     stride=2, padding=1, output_padding=1)

    def test_nhwc_data_format_matches_nchw(self, channels_last_flag):
        """Explicit NHWC data_format (now lowered via HWIO kernels) matches
        the NCHW reference, flag on or off."""
        x = _rand(2, 4, 9, 9, seed=0)
        w = _rand(6, 4, 3, 3, seed=1)
        ref = F.conv2d(x, w, padding=1).numpy()
        x_cl = paddle.to_tensor(np.transpose(x.numpy(), (0, 2, 3, 1)))
        for flag in (False, True):
            channels_last_flag(flag)
            out = F.conv2d(x_cl, w, padding=1, data_format="NHWC").numpy()
            np.testing.assert_allclose(
                np.transpose(out, (0, 3, 1, 2)), ref, rtol=1e-4, atol=1e-5)


class TestFusedConvBnAct:
    def _ref(self, x, w, mean, var, g, b, training, act="relu",
             residual=None, **kw):
        out = F.batch_norm(F.conv2d(x, w, **kw), mean, var, g, b,
                           training=training)
        if residual is not None:
            out = out + residual
        return F.relu(out) if act == "relu" else out

    @pytest.mark.parametrize("training", [False, True])
    def test_matches_sequential(self, channels_last_flag, training):
        """BN folding (eval) and one-op batch-stat path (train) must match
        conv -> batch_norm -> relu exactly, including the running-stat
        update side effect."""
        x = _rand(2, 4, 10, 10, seed=0)
        w = _rand(6, 4, 3, 3, seed=1)
        g, b = _rand(6, seed=2), _rand(6, seed=3)
        mean_r = paddle.to_tensor(np.random.RandomState(4).randn(6).astype("float32"))
        var_r = paddle.to_tensor(np.abs(np.random.RandomState(5).randn(6)).astype("float32") + 0.5)
        mean_f, var_f = paddle.to_tensor(mean_r.numpy()), paddle.to_tensor(var_r.numpy())
        ref = self._ref(x, w, mean_r, var_r, g, b, training, padding=1)
        out = F.fused_conv_bn_act(x, w, None, mean_f, var_f, g, b,
                                  padding=1, training=training, act="relu")
        np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)
        # running stats advanced identically (train) / untouched (eval)
        np.testing.assert_allclose(mean_f.numpy(), mean_r.numpy(),
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(var_f.numpy(), var_r.numpy(),
                                   rtol=1e-5, atol=1e-7)

    @pytest.mark.parametrize("training", [False, True])
    def test_channels_last_parity(self, channels_last_flag, training):
        x = _param(2, 4, 10, 10, seed=0)
        w = _param(6, 4, 3, 3, seed=1)
        g, b = _rand(6, seed=2), _rand(6, seed=3)
        res = _rand(2, 6, 10, 10, seed=6)
        outs, grads = [], []
        for flag in (False, True):
            channels_last_flag(flag)
            mean = paddle.to_tensor(np.zeros(6, np.float32))
            var = paddle.to_tensor(np.ones(6, np.float32))
            out = F.fused_conv_bn_act(x, w, None, mean, var, g, b,
                                      padding=1, training=training,
                                      act="relu", residual=res)
            out.mean().backward()
            outs.append(out.numpy())
            grads.append(w.grad.numpy())
            w.clear_grad(), x.clear_grad()
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(grads[0], grads[1], rtol=1e-3, atol=1e-5)

    def test_conv_bias_folds(self, channels_last_flag):
        """A conv bias must fold into the BN shift in eval mode."""
        x = _rand(2, 4, 8, 8, seed=0)
        w = _rand(6, 4, 3, 3, seed=1)
        cb = _rand(6, seed=7)
        mean = _rand(6, seed=4)
        var = paddle.to_tensor(np.abs(np.random.RandomState(5).randn(6)).astype("float32") + 0.5)
        ref = F.relu(F.batch_norm(F.conv2d(x, w, cb, padding=1), mean, var))
        out = F.fused_conv_bn_act(x, w, cb, mean, var, padding=1, act="relu")
        np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestResnetBlockParity:
    def test_basic_block_fwd_bwd(self, channels_last_flag):
        from paddle_tpu.vision.models.resnet import BasicBlock
        for training in (False, True):
            results = []
            for flag in (False, True):
                channels_last_flag(flag)
                paddle.seed(0)
                blk = BasicBlock(8, 8)
                blk.train() if training else blk.eval()
                x = _param(2, 8, 12, 12, seed=0)
                xin = layout.to_nhwc(x) if flag else x
                y = layout.to_nchw(blk(xin))
                (y * y).mean().backward()
                results.append((y.numpy(), blk.conv1.weight.grad.numpy(),
                                x.grad.numpy(), blk.bn1._mean.numpy()))
                x.clear_grad()
            (y0, gw0, gx0, m0), (y1, gw1, gx1, m1) = results
            np.testing.assert_allclose(y0, y1, rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(gw0, gw1, rtol=1e-3, atol=1e-5)
            np.testing.assert_allclose(gx0, gx1, rtol=1e-3, atol=1e-5)
            np.testing.assert_allclose(m0, m1, rtol=1e-5, atol=1e-7)

    def test_tag_propagates_through_trunk_layers(self, channels_last_flag):
        """Conv2D/BatchNorm2D/pools propagate the internal NHWC tag: one
        entry transpose, one exit transpose, NHWC physical shapes inside."""
        import paddle_tpu.nn as nn
        channels_last_flag(True)
        x = layout.to_nhwc(_rand(2, 3, 16, 16, seed=0))
        assert layout.is_nhwc(x) and tuple(x.shape) == (2, 16, 16, 3)
        conv = nn.Conv2D(3, 8, 3, padding=1)
        bn = nn.BatchNorm2D(8)
        mp = nn.MaxPool2D(2, stride=2)
        ap = nn.AdaptiveAvgPool2D((1, 1))
        h = ap(mp(bn(conv(x))))
        assert layout.is_nhwc(h) and tuple(h.shape) == (2, 1, 1, 8)
        out = layout.to_nchw(h)
        assert not layout.is_nhwc(out) and tuple(out.shape) == (2, 8, 1, 1)
        # and the values equal the plain NCHW composition
        channels_last_flag(False)
        ref = ap(mp(bn(conv(_rand(2, 3, 16, 16, seed=0)))))
        np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestLayoutSmoke:
    """Tier-1 layout-regression canaries: one real training step under both
    layouts on CPU (satellite of the channels-last PR). The swin step uses
    the same tiny stand-in config bench.py runs off-TPU — identical code
    paths (patch-embed conv, shifted-window attention, fused patch merge)
    at CPU-smoke cost; resnet50 is the real bench model at a small input."""

    def _one_step(self, model, x, lab):
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters())
        loss = paddle.nn.CrossEntropyLoss()(model(x), lab)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return float(loss)

    def test_resnet50_step_both_layouts(self, channels_last_flag):
        from paddle_tpu.vision.models import resnet50
        lab = paddle.to_tensor(np.array([1, 3]))
        paddle.seed(0)
        m = resnet50(num_classes=8)
        x = _rand(2, 3, 32, 32, seed=0)
        # layout-regression canary: EVAL forward parity on the SAME weights
        # is the tight check (train-mode batch-stat BN over 2-sample 1x1
        # maps at this smoke size chaotically amplifies fp reassociation,
        # so train losses are not comparable across layouts)
        m.eval()
        outs = {}
        for flag in (False, True):
            channels_last_flag(flag)
            with paddle.no_grad():
                outs[flag] = m(x).numpy()
        np.testing.assert_allclose(outs[False], outs[True],
                                   rtol=1e-4, atol=1e-5)
        m.train()
        for flag in (False, True):
            channels_last_flag(flag)
            assert np.isfinite(self._one_step(m, x, lab))

    def test_swin_step_both_layouts(self, channels_last_flag):
        from paddle_tpu.vision.models import SwinTransformer
        lab = paddle.to_tensor(np.array([1, 3]))
        paddle.seed(0)
        m = SwinTransformer(image_size=32, patch_size=2, embed_dim=16,
                            depths=(2, 2), num_heads=(2, 4),
                            window_size=4, num_classes=8)
        x = _rand(2, 3, 32, 32, seed=1)
        m.eval()
        outs = {}
        for flag in (False, True):
            channels_last_flag(flag)
            with paddle.no_grad():
                outs[flag] = m(x).numpy()
        np.testing.assert_allclose(outs[False], outs[True],
                                   rtol=1e-4, atol=1e-5)
        m.train()
        for flag in (False, True):
            channels_last_flag(flag)
            assert np.isfinite(self._one_step(m, x, lab))
