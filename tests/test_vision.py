"""Vision kit tests: model zoo forward shapes, transforms, ops, datasets."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models, transforms, ops
from paddle_tpu.vision.datasets import FakeData


SMALL = [  # builder, input size, n_classes
    (lambda: models.resnet18(num_classes=10), 64),
    (lambda: models.resnet50(num_classes=10), 64),
    (lambda: models.mobilenet_v2(num_classes=10), 64),
    (lambda: models.mobilenet_v3_small(num_classes=10), 64),
    (lambda: models.shufflenet_v2_x0_25(num_classes=10), 64),
    (lambda: models.squeezenet1_1(num_classes=10), 64),
]


@pytest.mark.parametrize("builder,size", SMALL)
def test_model_forward_shape(builder, size):
    paddle.seed(0)
    m = builder()
    m.eval()
    x = paddle.randn([2, 3, size, size])
    y = m(x)
    assert tuple(y.shape) == (2, 10)
    assert np.isfinite(np.asarray(y._data)).all()


def test_lenet_mnist_shape():
    m = models.LeNet()
    m.eval()
    y = m(paddle.randn([2, 1, 28, 28]))
    assert tuple(y.shape) == (2, 10)


def test_more_zoo_constructs():
    # constructors only (forward is expensive on CPU for the big ones)
    models.vgg11(num_classes=7)
    models.densenet121(num_classes=7)
    models.googlenet(num_classes=7)
    models.inception_v3(num_classes=7)
    models.resnext50_32x4d(num_classes=7)
    models.wide_resnet50_2(num_classes=7)
    models.alexnet(num_classes=7)
    models.mobilenet_v1(num_classes=7)


def test_swin_forward_and_grad():
    """Tiny Swin: exercises window partition, shifted-window mask, patch
    merging, and the relative-bias gradient path."""
    paddle.seed(0)
    m = models.SwinTransformer(image_size=32, patch_size=2, embed_dim=16,
                               depths=(2, 2), num_heads=(2, 4),
                               window_size=4, num_classes=5)
    m.train()
    x = paddle.randn([2, 3, 32, 32])
    y = m(x)
    assert tuple(y.shape) == (2, 5)
    label = paddle.to_tensor(np.array([1, 3]))
    loss = paddle.nn.CrossEntropyLoss()(y, label)
    loss.backward()
    blk = m.stages[0][1]            # odd block: shifted windows
    assert blk.shift > 0 and blk._mask is not None
    table = blk.attn.rel_bias_table
    assert table.grad is not None
    assert np.isfinite(np.asarray(table.grad._data)).all()
    assert np.isfinite(float(loss))


def test_swin_presets_construct():
    models.swin_t(num_classes=3)


def test_vgg_forward():
    m = models.vgg11(num_classes=5)
    m.eval()
    y = m(paddle.randn([1, 3, 224, 224]))
    assert tuple(y.shape) == (1, 5)


def test_train_step_resnet18():
    paddle.seed(0)
    m = models.resnet18(num_classes=4)
    m.train()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    x = paddle.randn([2, 3, 32, 32])
    label = paddle.to_tensor(np.array([1, 3]))
    loss = paddle.nn.CrossEntropyLoss()(m(x), label)
    loss.backward()
    opt.step()
    assert np.isfinite(float(loss))


class TestTransforms:
    def test_compose_pipeline(self):
        t = transforms.Compose([
            transforms.Resize(40),
            transforms.CenterCrop(32),
            transforms.RandomHorizontalFlip(1.0),
            transforms.ToTensor(),
            transforms.Normalize([0.5, 0.5, 0.5], [0.5, 0.5, 0.5]),
        ])
        img = (np.random.rand(50, 60, 3) * 255).astype(np.uint8)
        out = t(img)
        assert out.shape == (3, 32, 32)
        assert out.dtype == np.float32

    def test_resize_aspect(self):
        img = np.zeros((100, 200, 3), np.uint8)
        out = transforms.Resize(50)(img)
        assert out.shape == (50, 100, 3)

    def test_resize_bilinear_values(self):
        img = np.array([[0.0, 10.0], [20.0, 30.0]], np.float32)[:, :, None]
        out = transforms.Resize((4, 4))(img)
        assert out.shape == (4, 4, 1)
        assert out.min() >= 0 and out.max() <= 30

    def test_random_resized_crop(self):
        img = (np.random.rand(64, 64, 3) * 255).astype(np.uint8)
        out = transforms.RandomResizedCrop(32)(img)
        assert out.shape == (32, 32, 3)

    def test_color_and_erase(self):
        img = (np.random.rand(32, 32, 3) * 255).astype(np.uint8)
        out = transforms.ColorJitter(0.4, 0.4, 0.4, 0.2)(img)
        assert out.shape == (32, 32, 3)
        out = transforms.RandomErasing(prob=1.0)(img)
        assert out.shape == (32, 32, 3)
        out = transforms.Grayscale(3)(img)
        assert out.shape == (32, 32, 3)
        out = transforms.Pad(2)(img)
        assert out.shape == (36, 36, 3)
        out = transforms.RandomRotation(30)(img)
        assert out.shape == (32, 32, 3)

    def test_hue_rotates_colors(self):
        img = np.zeros((4, 4, 3), np.float32)
        img[..., 0] = 200.0  # pure red
        t = transforms.HueTransform(0.5)
        t_val = t._apply_image(img)
        # some rotation must move energy out of the red channel
        moved = any(np.abs(t._apply_image(img)[..., 1:]).sum() > 1
                    for _ in range(8))
        assert moved

    def test_rotation_expand(self):
        img = (np.random.rand(20, 40, 3) * 255).astype(np.uint8)
        out = transforms.RandomRotation((90, 90), expand=True)(img)
        assert out.shape[0] >= 39 and out.shape[1] >= 19


class TestOps:
    def test_box_iou_identity(self):
        boxes = paddle.to_tensor(np.array([[0, 0, 10, 10], [5, 5, 15, 15]],
                                          np.float32))
        iou = ops.box_iou(boxes, boxes)
        np.testing.assert_allclose(np.diag(np.asarray(iou._data)), 1.0, atol=1e-6)

    def test_nms_suppresses(self):
        boxes = paddle.to_tensor(np.array(
            [[0, 0, 10, 10], [1, 1, 11, 11], [100, 100, 110, 110]], np.float32))
        scores = paddle.to_tensor(np.array([0.9, 0.8, 0.7], np.float32))
        keep = ops.nms(boxes, 0.5, scores=scores)
        kept = np.asarray(keep._data)
        assert 0 in kept and 2 in kept and 1 not in kept

    def test_roi_align_shape(self):
        x = paddle.randn([2, 4, 16, 16])
        boxes = paddle.to_tensor(np.array([[0, 0, 8, 8], [4, 4, 12, 12],
                                           [0, 0, 16, 16]], np.float32))
        bn = paddle.to_tensor(np.array([2, 1], np.int32))
        out = ops.roi_align(x, boxes, bn, 4)
        assert tuple(out.shape) == (3, 4, 4, 4)

    def test_box_coder_roundtrip(self):
        prior = np.array([[0, 0, 10, 10], [10, 10, 30, 30]], np.float32)
        target = np.array([[1, 1, 9, 9], [12, 8, 28, 32]], np.float32)
        enc = ops.box_coder(paddle.to_tensor(prior), None,
                            paddle.to_tensor(target))
        dec = ops.box_coder(paddle.to_tensor(prior), None, enc,
                            code_type="decode_center_size")
        np.testing.assert_allclose(np.asarray(dec._data), target, atol=1e-4)

    def test_roi_pool_takes_max(self):
        x = paddle.zeros([1, 1, 8, 8])
        xd = np.zeros((1, 1, 8, 8), np.float32)
        xd[0, 0, 2, 2] = 100.0
        x = paddle.to_tensor(xd)
        boxes = paddle.to_tensor(np.array([[0, 0, 8, 8]], np.float32))
        bn = paddle.to_tensor(np.array([1], np.int32))
        out = ops.roi_pool(x, boxes, bn, 2)
        assert np.asarray(out._data).max() == 100.0

    def test_roi_align_sampling_ratio(self):
        x = paddle.randn([1, 2, 8, 8])
        boxes = paddle.to_tensor(np.array([[0, 0, 8, 8]], np.float32))
        bn = paddle.to_tensor(np.array([1], np.int32))
        o1 = ops.roi_align(x, boxes, bn, 4, sampling_ratio=1)
        o4 = ops.roi_align(x, boxes, bn, 4, sampling_ratio=4)
        assert o1.shape == o4.shape == [1, 2, 4, 4]
        assert not np.allclose(np.asarray(o1._data), np.asarray(o4._data))

    def test_distribute_fpn_restore_index(self):
        rois = np.array([[0, 0, 300, 300], [0, 0, 10, 10], [0, 0, 60, 60]],
                        np.float32)
        outs, restore, nums = ops.distribute_fpn_proposals(
            paddle.to_tensor(rois), 2, 5, 4, 224)
        concat = np.concatenate([np.asarray(o._data).reshape(-1, 4)
                                 for o in outs if o._data.size])
        r = np.asarray(restore._data)
        # restore[orig] = concat position: gathering concat rows by inverse
        # permutation restores original order
        np.testing.assert_allclose(concat[r], rois)

    def test_yolo_box_shapes(self):
        x = paddle.randn([2, 3 * 7, 4, 4])  # 3 anchors, 2 classes: 3*(5+2)=21
        img_size = paddle.to_tensor(np.array([[416, 416], [416, 416]], np.int32))
        boxes, scores = ops.yolo_box(x, img_size, [10, 13, 16, 30, 33, 23], 2,
                                     0.01, 32)
        assert tuple(boxes.shape) == (2, 48, 4)
        assert tuple(scores.shape) == (2, 48, 2)


def test_fake_data_dataloader():
    ds = FakeData(size=8, image_shape=(3, 8, 8), num_classes=3)
    loader = paddle.io.DataLoader(ds, batch_size=4)
    batches = list(loader)
    assert len(batches) == 2
    imgs, labels = batches[0]
    assert tuple(imgs.shape) == (4, 3, 8, 8)


@pytest.mark.slow
def test_yolo_detector_trains_and_decodes():
    """PP-YOLOE-class detector: dense static-shape loss decreases on a
    synthetic single-box task; decode returns NMS'd detections."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import yolo_lite, yolo_loss

    paddle.seed(0)
    np.random.seed(0)
    model = yolo_lite(num_classes=3, width=8)
    cfg = model.config

    B, H = 2, 64
    imgs = np.random.randn(B, 3, H, H).astype("float32") * 0.1
    # one gt box per image
    gt_boxes = np.array([[[8., 8., 40., 40.]], [[16., 16., 56., 48.]]],
                        np.float32)
    gt_labels = np.array([[1], [2]], np.int64)
    gt_mask = np.ones((B, 1), np.float32)

    opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                parameters=model.parameters())
    losses = []
    for _ in range(8):
        outs = model(paddle.to_tensor(imgs))
        loss = yolo_loss(outs, paddle.to_tensor(gt_boxes),
                         paddle.to_tensor(gt_labels),
                         paddle.to_tensor(gt_mask), cfg)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses

    model.eval()
    dets = model.decode(paddle.to_tensor(imgs), score_thresh=0.0, max_dets=5)
    assert len(dets) == B
    boxes, scores, classes = dets[0]
    assert boxes.shape[1] == 4 and len(scores) == len(classes) <= 5


@pytest.mark.slow
def test_ppyoloe_dfl_varifocal_trains_and_decodes():
    """PP-YOLOE ET-head pieces (BASELINE toolkit entrypoint): DFL integral
    regression + varifocal classification — train a few steps on one
    synthetic box, loss decreases, decode returns finite boxes."""
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import ppyoloe_s
    from paddle_tpu.vision.models.yolo import (YOLOConfig, YOLODetector,
                                               yolo_loss, _dfl_expectation)
    import jax.numpy as jnp

    paddle.seed(0)
    model = YOLODetector(YOLOConfig(num_classes=3, width=8, reg_max=8,
                                    use_varifocal=True))
    imgs = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 3, 64, 64).astype("float32"))
    outs = model(imgs)
    # head emits 4*(reg_max+1) bin logits per cell
    assert outs[0][1].shape[1] == 4 * 9
    # expectation decode is bounded by reg_max
    d = _dfl_expectation(outs[0][1]._data, 8)
    assert float(jnp.max(d)) <= 8.0 and float(jnp.min(d)) >= 0.0

    gt_boxes = paddle.to_tensor(np.array(
        [[[8.0, 8.0, 40.0, 40.0]], [[16.0, 16.0, 56.0, 48.0]]], np.float32))
    gt_labels = paddle.to_tensor(np.array([[1], [2]], np.int64))
    gt_mask = paddle.to_tensor(np.ones((2, 1), np.float32))
    opt = paddle.optimizer.Adam(parameters=model.parameters(),
                                learning_rate=5e-3)
    losses = []
    for _ in range(6):
        loss = yolo_loss(model(imgs), gt_boxes, gt_labels, gt_mask,
                         model.config)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    model.eval()
    dets = model.decode(imgs, score_thresh=0.0, max_dets=5)
    assert len(dets) == 2
    bb, ss, cc = dets[0]
    assert np.isfinite(bb).all() if len(bb) else True
    # preset entrypoints exist
    assert ppyoloe_s(num_classes=3).config.reg_max == 16


class TestDetectionOpsTail:
    """VERDICT r2 #6: prior_box, generate_proposals, and the task-aligned
    assigner (reference: vision/ops.py:424 prior_box;
    operators/detection/generate_proposals_v2_op.cc; ppdet
    TaskAlignedAssigner)."""

    def test_prior_box_shapes_and_values(self):
        from paddle_tpu.vision import ops as vops
        x = paddle.to_tensor(np.zeros((1, 8, 4, 4), np.float32))
        img = paddle.to_tensor(np.zeros((1, 3, 32, 32), np.float32))
        box, var = vops.prior_box(x, img, min_sizes=[8.0], max_sizes=[16.0],
                                  aspect_ratios=[2.0], flip=True, clip=True)
        # priors: ar {1, 2, 1/2} + sqrt(min*max) = 4
        assert box.shape == [4, 4, 4, 4] or tuple(box.shape) == (4, 4, 4, 4)
        assert tuple(var.shape) == tuple(box.shape)
        b = box.numpy()
        # cell (0,0): center at offset 0.5 * step 8 = (4, 4); min box 8x8
        # normalized by 32
        np.testing.assert_allclose(b[0, 0, 0], [0.0, 0.0, 0.25, 0.25],
                                   atol=1e-6)
        # sqrt(8*16) box is last (min_max_aspect_ratios_order=False)
        sq = np.sqrt(8.0 * 16.0) / 2 / 32
        np.testing.assert_allclose(
            b[0, 0, 3], np.clip([0.125 - sq, 0.125 - sq,
                                 0.125 + sq, 0.125 + sq], 0, 1), atol=1e-5)
        v = var.numpy()
        np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2])
        # caffe order flag: sqrt box second
        box2, _ = vops.prior_box(x, img, min_sizes=[8.0], max_sizes=[16.0],
                                 aspect_ratios=[2.0], flip=True, clip=True,
                                 min_max_aspect_ratios_order=True)
        np.testing.assert_allclose(box2.numpy()[0, 0, 1], b[0, 0, 3],
                                   atol=1e-6)

    def test_generate_proposals_static_and_correct(self):
        from paddle_tpu.vision import ops as vops
        rng = np.random.RandomState(0)
        H = W = 4
        A = 2
        # anchors tiled over the grid
        ys, xs = np.meshgrid(np.arange(H) * 8.0, np.arange(W) * 8.0,
                             indexing="ij")
        anchors = np.zeros((H, W, A, 4), np.float32)
        for a, sz in enumerate((8.0, 16.0)):
            anchors[..., a, 0] = xs
            anchors[..., a, 1] = ys
            anchors[..., a, 2] = xs + sz
            anchors[..., a, 3] = ys + sz
        variances = np.ones_like(anchors)
        scores = rng.rand(1, A, H, W).astype(np.float32)
        deltas = (rng.randn(1, 4 * A, H, W) * 0.1).astype(np.float32)
        img_size = np.array([[32.0, 32.0]], np.float32)
        rois, probs, num = vops.generate_proposals(
            paddle.to_tensor(scores), paddle.to_tensor(deltas),
            paddle.to_tensor(img_size), paddle.to_tensor(anchors),
            paddle.to_tensor(variances), pre_nms_top_n=12,
            post_nms_top_n=5, nms_thresh=0.7, min_size=1.0, return_rois_num=True)
        assert tuple(rois.shape) == (5, 4)
        assert tuple(probs.shape) == (5, 1)
        n = int(num.numpy()[0])
        assert 1 <= n <= 5
        r = rois.numpy()[:n]
        p = probs.numpy()[:n, 0]
        # sorted desc, clipped to the image, non-degenerate
        assert (np.diff(p) <= 1e-6).all()
        assert (r >= 0).all() and (r[:, [0, 2]] <= 32).all() \
            and (r[:, [1, 3]] <= 32).all()
        assert ((r[:, 2] - r[:, 0]) >= 0).all()
        # kept boxes must be mutually below the NMS threshold
        from paddle_tpu.vision.ops import box_iou
        iou = box_iou(paddle.to_tensor(r), paddle.to_tensor(r)).numpy()
        off = iou - np.eye(n)
        assert (off <= 0.7 + 1e-5).all(), off

    def test_tal_assigner_prefers_aligned_anchor(self):
        """An anchor with BOTH high cls score and high IoU must win the
        assignment over a high-IoU/low-score one (the task-aligned metric;
        center-window assignment cannot express this)."""
        import jax.numpy as jnp
        from paddle_tpu.vision.models.yolo import tal_assign
        B, M, A = 1, 1, 4
        iou = jnp.asarray([[[0.9, 0.8, 0.2, 0.0]]])
        s = jnp.asarray([[[0.01, 0.9, 0.9, 0.9]]])
        align = s * iou ** 2         # anchor 1 has the best product
        inside = jnp.asarray([[[True, True, True, False]]])
        assigned, pos = tal_assign(align, inside, topk=2)
        assert bool(pos[0, 1])
        # top-2 candidates are anchors 0 and 1; anchor 3 (outside) never
        assert not bool(pos[0, 3])

    @pytest.mark.slow
    def test_ppyoloe_tal_trains(self):
        """The production preset (assigner='tal') trains to decreasing
        loss on synthetic data and decodes finite boxes."""
        import paddle_tpu as paddle
        from paddle_tpu.vision.models.yolo import (YOLOConfig, YOLODetector,
                                                   yolo_loss)
        paddle.seed(1)
        model = YOLODetector(YOLOConfig(num_classes=3, width=8, reg_max=8,
                                        use_varifocal=True, assigner="tal"))
        assert model.config.assigner == "tal"
        imgs = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 3, 64, 64).astype("float32"))
        gt_boxes = paddle.to_tensor(np.array(
            [[[8.0, 8.0, 40.0, 40.0]], [[16.0, 16.0, 56.0, 48.0]]],
            np.float32))
        gt_labels = paddle.to_tensor(np.array([[1], [2]], np.int64))
        gt_mask = paddle.to_tensor(np.ones((2, 1), np.float32))
        opt = paddle.optimizer.Adam(parameters=model.parameters(),
                                    learning_rate=5e-3)
        losses = []
        for _ in range(8):
            loss = yolo_loss(model(imgs), gt_boxes, gt_labels, gt_mask,
                             model.config)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses
        model.eval()
        dets = model.decode(imgs, score_thresh=0.0, max_dets=5)
        assert len(dets) == 2
