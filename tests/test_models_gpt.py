"""GPT flagship model tests (analog of the reference's dygraph_to_static
model tests running real models, SURVEY §4 API/layer level)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                               GPTPretrainingCriterion, gpt_config)


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    dist.set_mesh(None)
    fleet._fleet_state.update(initialized=False, strategy=None, hcg=None)


def _tiny(**kw):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                max_position_embeddings=64, intermediate_size=128)
    base.update(kw)
    return GPTConfig(**base)


def test_forward_backward_and_train():
    paddle.seed(0)
    cfg = _tiny()
    m = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype("int64"))
    logits = m(ids)
    assert logits.shape == [2, 16, 128]
    loss = crit(logits, ids)
    loss.backward()
    assert np.isfinite(m.gpt.wte.weight.grad.numpy()).all()

    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, opt, lambda a, b: crit(m(a), b))
    l0 = float(step(ids, ids))
    for _ in range(5):
        l = float(step(ids, ids))
    assert l < l0


def test_fused_lm_head_ce_matches_unfused():
    """model.loss (chunked fused linear+CE, no logits materialization) must
    equal forward()+criterion in value AND parameter gradients."""
    paddle.seed(0)
    cfg = _tiny()
    m = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype("int64"))

    ref = crit(m(ids), ids)
    ref.backward()
    ref_grad = m.gpt.wte.weight.grad.numpy().copy()
    ref_val = float(ref)
    m.clear_gradients()

    fused = m.loss(ids, ids, chunk_size=8)
    fused.backward()
    np.testing.assert_allclose(float(fused), ref_val, rtol=1e-5)
    np.testing.assert_allclose(m.gpt.wte.weight.grad.numpy(), ref_grad,
                               rtol=2e-4, atol=2e-5)

    # masked variant + non-divisible chunk size falls back to a divisor
    mask = paddle.to_tensor(np.random.randint(0, 2, (2, 16)).astype("float32"))
    lm = m.loss(ids, ids, loss_mask=mask, chunk_size=7)
    assert np.isfinite(float(lm))


def test_adam_bf16_moments_train_and_dtype():
    import jax.numpy as jnp
    paddle.seed(0)
    cfg = _tiny()
    m = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters(),
                                 moment_dtype="bfloat16")
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype("int64"))
    step = paddle.jit.TrainStep(m, opt, lambda a, b: m.loss(a, b, chunk_size=8))
    l0 = float(step(ids, ids))
    for _ in range(5):
        l = float(step(ids, ids))
    assert l < l0
    assert step._opt_state[0]["moment1"].dtype == jnp.bfloat16


def test_generate_kv_cache_matches_full_forward():
    """Incremental decode with cache == argmax over full forward logits."""
    paddle.seed(1)
    m = GPTForCausalLM(_tiny())
    m.eval()
    ids = paddle.to_tensor(np.random.randint(0, 128, (1, 8)).astype("int64"))
    out = m.generate(ids, max_new_tokens=4)
    assert out.shape == [1, 12]
    # greedy reference: step the full forward
    cur = ids.numpy()
    for _ in range(4):
        logits = m(paddle.to_tensor(cur)).numpy()
        nxt = logits[:, -1].argmax(-1)[:, None]
        cur = np.concatenate([cur, nxt], axis=1)
    np.testing.assert_array_equal(out.numpy(), cur)


def test_recompute_parity():
    paddle.seed(2)
    ids = np.random.randint(0, 128, (2, 16)).astype("int64")

    def run(use_recompute):
        paddle.seed(3)
        m = GPTForCausalLM(_tiny(use_recompute=use_recompute))
        crit = GPTPretrainingCriterion()
        loss = crit(m(paddle.to_tensor(ids)), paddle.to_tensor(ids))
        loss.backward()
        return float(loss), m.gpt.h[0].attn.qkv.weight.grad.numpy()

    l1, g1 = run(False)
    l2, g2 = run(True)
    assert abs(l1 - l2) < 1e-5
    np.testing.assert_allclose(g1, g2, rtol=1e-4, atol=1e-6)


def test_hybrid_tp_parity_with_single_device():
    ids = np.random.randint(0, 128, (4, 16)).astype("int32")

    def run(mesh):
        paddle.seed(7)
        m = GPTForCausalLM(_tiny())
        crit = GPTPretrainingCriterion()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = paddle.jit.TrainStep(m, opt, lambda a, b: crit(m(a), b),
                                    mesh=mesh, data_axes=("dp",))
        return [float(step(paddle.to_tensor(ids), paddle.to_tensor(ids)))
                for _ in range(3)]

    ref = run(None)
    st = fleet.DistributedStrategy()
    st.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    fleet.init(strategy=st)
    got = run(dist.get_mesh())
    np.testing.assert_allclose(ref, got, rtol=3e-4)


def test_gpt_moe_blocks_train_and_aux_loss_flows():
    """GShard-pattern GPT-MoE: every 2nd block routed; router aux loss is
    part of loss() and gradients reach expert AND router weights."""
    paddle.seed(0)
    cfg = _tiny(moe_num_experts=4, moe_every_n_layers=2, moe_gate="gshard")
    m = GPTForCausalLM(cfg)
    moe_blocks = [b for b in m.gpt.h if b.is_moe]
    dense_blocks = [b for b in m.gpt.h if not b.is_moe]
    assert len(moe_blocks) == 1 and len(dense_blocks) == 1

    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype("int64"))
    loss = m.loss(ids, ids, chunk_size=8)
    assert m.gpt.last_aux_loss is not None
    # the criterion path carries the aux loss explicitly
    crit_loss = GPTPretrainingCriterion(cfg)(
        m(ids), ids, aux_loss=cfg.moe_aux_weight * m.gpt.last_aux_loss)
    np.testing.assert_allclose(float(crit_loss), float(loss), rtol=1e-4)
    loss.backward()
    mlp = moe_blocks[0].mlp
    assert np.isfinite(mlp.w1.grad.numpy()).all()
    assert np.isfinite(mlp.gate_weight.grad.numpy()).all()
    m.clear_gradients()

    # trains through the fused step too
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, opt, lambda a, b: m.loss(a, b, chunk_size=8))
    l0 = float(step(ids, ids))
    for _ in range(5):
        l = float(step(ids, ids))
    assert l < l0


def test_gpt_moe_capacity_factor_plumbs():
    """moe_capacity_factor reaches MoELayer and changes the expert-slot
    capacity; cf=1.0 (tight slots) still trains with finite grads."""
    from paddle_tpu.incubate.distributed.models.moe.moe_layer import \
        _capacity
    paddle.seed(0)
    cfg = _tiny(moe_num_experts=4, moe_every_n_layers=2,
                moe_capacity_factor=1.0)
    m = GPTForCausalLM(cfg)
    mlp = [b for b in m.gpt.h if b.is_moe][0].mlp
    assert mlp.capacity_factor == 1.0
    n_tok = 2 * 16
    assert _capacity(n_tok, 4, 2, 1.0) < _capacity(n_tok, 4, 2, 1.25)
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype("int64"))
    loss = m.loss(ids, ids, chunk_size=8)
    loss.backward()
    assert np.isfinite(mlp.w1.grad.numpy()).all()


def test_gpt_moe_dryrun_on_ep_mesh():
    """Expert weights shard over the ep axis; the fused hybrid step
    compiles and runs on a dp x ep virtual mesh."""
    paddle.seed(0)
    mesh = dist.build_mesh({"dp": 2, "ep": 4})
    dist.set_mesh(mesh)
    cfg = _tiny(moe_num_experts=4, moe_every_n_layers=2)
    m = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, opt,
                                lambda a, b: m.loss(a, b, chunk_size=8),
                                mesh=mesh, data_axes=("dp",))
    ids = paddle.to_tensor(np.random.randint(0, 128, (4, 16)).astype("int64"))
    loss = step(ids, ids)
    assert np.isfinite(float(loss))


def test_gpt_moe_with_recompute_aux_flows():
    """Remat + MoE: aux loss is an explicit remat output (a tracer read off
    the layer after jax.checkpoint would leak)."""
    paddle.seed(0)
    cfg = _tiny(moe_num_experts=4, use_recompute=True)
    m = GPTForCausalLM(cfg)
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype("int64"))
    loss = m.loss(ids, ids, chunk_size=8)
    loss.backward()
    moe = [b for b in m.gpt.h if b.is_moe][0]
    assert np.isfinite(moe.mlp.gate_weight.grad.numpy()).all()


def test_adam_int8_moments_train():
    """Blockwise 8-bit Adam state: ~2 bytes/param total moments; must
    still converge through the fused step."""
    import jax.numpy as jnp
    paddle.seed(0)
    m = GPTForCausalLM(_tiny())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters(),
                                 moment_dtype="int8")
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype("int64"))
    step = paddle.jit.TrainStep(m, opt, lambda a, b: m.loss(a, b, chunk_size=8))
    l0 = float(step(ids, ids))
    for _ in range(6):
        l = float(step(ids, ids))
    assert l < l0
    assert step._opt_state[0]["moment1_q"].dtype == jnp.int8


def test_int8_moments_on_sharded_mesh():
    """int8 q/scale state arrays are not param-shaped: spec placement must
    replicate them instead of applying the param PartitionSpec."""
    paddle.seed(0)
    mesh = dist.build_mesh({"dp": 2, "mp": 4})
    dist.set_mesh(mesh)
    m = GPTForCausalLM(_tiny())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters(),
                                 moment_dtype="int8")
    step = paddle.jit.TrainStep(m, opt, lambda a, b: m.loss(a, b, chunk_size=8),
                                mesh=mesh, data_axes=("dp",))
    ids = paddle.to_tensor(np.random.randint(0, 128, (4, 16)).astype("int64"))
    assert np.isfinite(float(step(ids, ids)))


def test_adam_selective_q8_embedding_moments():
    """q8_param_fun: int8 moments for SELECTED params (embedding tables),
    bf16/f32 for the rest — what fits the S=8192 long-context config on one
    chip (bench.py r2 ladder). Mixed state kinds must train together."""
    import jax.numpy as jnp
    paddle.seed(0)
    m = GPTForCausalLM(_tiny())
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-3, parameters=m.parameters(),
        moment_dtype="bfloat16",
        q8_param_fun=lambda n: "wte" in n or "wpe" in n)
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)).astype("int64"))
    step = paddle.jit.TrainStep(m, opt, lambda a, b: m.loss(a, b, chunk_size=8))
    l0 = float(step(ids, ids))
    for _ in range(6):
        l = float(step(ids, ids))
    assert l < l0
    kinds = {}
    for name, st in zip(step._param_names, step._opt_state):
        kinds[name] = "q8" if "moment1_q" in st else str(st["moment1"].dtype)
    embs = [k for k in kinds if "wte" in k or "wpe" in k]
    others = [k for k in kinds if k not in embs]
    assert embs and all(kinds[k] == "q8" for k in embs), kinds
    assert others and all(kinds[k] == "bfloat16" for k in others), kinds


def test_generate_static_matches_growing_cache():
    """generate_static (fixed buffers + one compiled scan) must produce
    exactly the growing-cache generate() sequence for greedy decoding."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_config

    paddle.seed(0)
    cfg = gpt_config("gpt3-125m", hidden_size=128, num_layers=2, num_heads=2,
                     vocab_size=256, max_position_embeddings=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 256, (2, 8)).astype("int64"))
    a = m.generate(ids, max_new_tokens=6).numpy()
    b = m.generate_static(ids, max_new_tokens=6).numpy()
    assert (a == b).all(), (a, b)
    # second call reuses the compiled runner (no retrace)
    c = m.generate_static(ids, max_new_tokens=6).numpy()
    assert (a == c).all()
    assert len(m._gen_static_cache) == 1


def test_sampling_top_k_top_p():
    """top-k restricts sampled ids to the k best; top-p to the nucleus;
    both paths (eager generate and compiled generate_static) honor them."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_config
    from paddle_tpu.models.gpt import sample_logits

    # unit level: a peaked distribution
    logits = jnp.asarray(np.array([[10.0, 9.0, 1.0, 0.0, -5.0]], np.float32))
    key = jax.random.PRNGKey(0)
    for i in range(5):
        tok = int(sample_logits(logits, jax.random.fold_in(key, i),
                                temperature=1.0, top_k=2)[0])
        assert tok in (0, 1), tok
    # top_p tiny -> only the argmax survives
    for i in range(3):
        tok = int(sample_logits(logits, jax.random.fold_in(key, i),
                                temperature=5.0, top_p=1e-6)[0])
        assert tok == 0, tok
    # greedy path unaffected by the knobs
    assert int(sample_logits(logits, key, temperature=0.0, top_k=1)[0]) == 0

    # model level: both generates run with the knobs and stay in-vocab
    paddle.seed(0)
    cfg = gpt_config("gpt3-125m", hidden_size=64, num_layers=1, num_heads=2,
                     vocab_size=32, max_position_embeddings=32)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(np.arange(4, dtype="int64").reshape(1, 4))
    a = m.generate(ids, max_new_tokens=4, temperature=0.9, top_k=5, seed=3)
    b = m.generate_static(ids, max_new_tokens=4, temperature=0.9, top_k=5,
                          top_p=0.9, seed=3)
    for o in (a, b):
        arr = o.numpy()
        assert arr.shape == (1, 8) and (arr >= 0).all() and (arr < 32).all()


def test_generate_eos_early_stop():
    """eos_token_id: eager generate stops early; static generate masks
    finished rows to EOS inside the compiled scan."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_config

    paddle.seed(0)
    cfg = gpt_config("gpt3-125m", hidden_size=64, num_layers=1, num_heads=2,
                     vocab_size=32, max_position_embeddings=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(np.arange(8, dtype="int64").reshape(2, 4))
    # greedy reference without eos
    ref = m.generate(ids, max_new_tokens=8).numpy()
    # pick the token the model emits FIRST for row 0 as the eos id
    eos = int(ref[0, 4])
    a = m.generate(ids, max_new_tokens=8, eos_token_id=eos).numpy()
    b = m.generate_static(ids, max_new_tokens=8, eos_token_id=eos).numpy()
    # row 0 hits eos immediately: everything after is eos in both paths
    assert (a[0, 4:] == eos).all()
    assert (b[0, 4:] == eos).all()
    # rows that never emit eos match the unconstrained reference prefix
    if not (ref[1] == eos).any():
        n = a.shape[1]
        assert (a[1, :n] == ref[1, :n]).all()

    # single-row batch where the row hits eos immediately: the eager path
    # must actually BREAK (strictly shorter than the unconstrained run)
    one = paddle.to_tensor(ids.numpy()[:1])
    short = m.generate(one, max_new_tokens=8, eos_token_id=eos).numpy()
    assert short.shape[1] < ref.shape[1], short.shape
    assert short[0, -1] == eos


def test_generate_static_ragged_one_program():
    """Ragged serving (VERDICT r3 #7a): one compiled program serves any
    prompt length <= cap — per-row greedy parity with generate_static on
    the unpadded prompts, and a second lengths-pattern must NOT add a new
    executable to the cache."""
    import numpy as np
    paddle.seed(3)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
                    max_position_embeddings=64, intermediate_size=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(0)
    P_cap, new = 10, 6
    lens = [4, 10, 7]
    prompts = np.zeros((3, P_cap), np.int64)
    rows = []
    for i, ln in enumerate(lens):
        row = rng.randint(1, 96, (ln,))
        prompts[i, :ln] = row
        rows.append(row)

    out = m.generate_static_ragged(
        paddle.to_tensor(prompts), lens, max_new_tokens=new).numpy()
    assert out.shape == (3, P_cap + new)

    for i, ln in enumerate(lens):
        single = m.generate_static(
            paddle.to_tensor(rows[i][None]), max_new_tokens=new).numpy()[0]
        np.testing.assert_array_equal(out[i, P_cap:], single[ln:],
                                      err_msg=f"row {i} len {ln}")

    n_exec = len(m._gen_static_cache)
    lens2 = [9, 2, 5]
    prompts2 = np.zeros((3, P_cap), np.int64)
    for i, ln in enumerate(lens2):
        prompts2[i, :ln] = rng.randint(1, 96, (ln,))
    _ = m.generate_static_ragged(paddle.to_tensor(prompts2), lens2,
                                 max_new_tokens=new)
    assert len(m._gen_static_cache) == n_exec  # SAME executable reused


def test_generate_static_ragged_eos_and_sampling():
    import numpy as np
    paddle.seed(4)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1, num_heads=4,
                    max_position_embeddings=48, intermediate_size=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    prompts = np.zeros((2, 6), np.int64)
    prompts[0, :3] = [5, 6, 7]
    prompts[1, :6] = [8, 9, 10, 11, 12, 13]
    out = m.generate_static_ragged(
        paddle.to_tensor(prompts), [3, 6], max_new_tokens=5,
        temperature=0.8, top_k=8, seed=11).numpy()
    assert out.shape == (2, 11)
    assert np.all((out[:, 6:] >= 0) & (out[:, 6:] < 64))


def test_generate_static_int8_weights(monkeypatch):
    """Weight-only int8 decode (VERDICT r3 #7b): quantized payload
    generates near-greedy-parity output on a toy model and never NaNs."""
    import numpy as np
    monkeypatch.setenv("PADDLE_TPU_Q8_DECODE_MIN", "4096")  # toy-size gate
    paddle.seed(5)
    cfg = GPTConfig(vocab_size=96, hidden_size=128, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=256)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(1, 96, (2, 8)).astype(np.int64))
    full = m.generate_static(ids, max_new_tokens=8).numpy()
    q8 = m.generate_static(ids, max_new_tokens=8, weight_dtype="int8").numpy()
    assert q8.shape == full.shape
    # per-channel int8 weights keep greedy decode mostly on-trajectory for
    # a toy model; exact parity is not the contract (weights ARE perturbed)
    agree = (q8[:, 8:] == full[:, 8:]).mean()
    assert agree >= 0.5, f"int8 decode diverged: agreement {agree}"
    # quantized payload is cached: second call must reuse it
    assert m._q8_decode_cache is m._decode_quantized_params()
    # a >=1M-param weight must actually be int8 in the payload
    assert any(q.dtype == np.int8 for q, _ in m._q8_decode_cache.values())


def test_generate_static_int8_kv_cache():
    """cache_dtype="int8" (VERDICT r4 #5 follow-on): the KV cache is stored
    as int8 codes + per-(pos,head) scales — attention reads half the HBM
    bytes per decode step. Greedy output must stay near-parity with the
    bf16 cache on a toy model (the cache IS perturbed by quantization, so
    exact parity is not the contract), and the factored-scale attention
    math must match explicit dequantization."""
    import numpy as np
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=96, hidden_size=128, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=256)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(1, 96, (2, 8)).astype(np.int64))
    full = m.generate_static(ids, max_new_tokens=8).numpy()
    c8 = m.generate_static(ids, max_new_tokens=8,
                           cache_dtype="int8").numpy()
    assert c8.shape == full.shape
    assert (c8[:, :8] == full[:, :8]).all()          # prompt passthrough
    agree = (c8[:, 8:] == full[:, 8:]).mean()
    assert agree >= 0.5, f"int8-cache decode diverged: agreement {agree}"
    # ragged variant composes with the int8 cache (one program, any len):
    # full-length rows must stay on the non-ragged greedy trajectory
    lens = [3, 8]
    r_full = m.generate_static_ragged(ids, lens, max_new_tokens=6).numpy()
    r_c8 = m.generate_static_ragged(ids, lens, max_new_tokens=6,
                                    cache_dtype="int8").numpy()
    assert r_c8.shape == r_full.shape
    assert (r_c8[1] == r_full[1]).mean() >= 0.75
    import pytest
    with pytest.raises(ValueError):
        m.generate_static(ids, max_new_tokens=2, cache_dtype="float64")


def test_generate_static_int8_weights_and_kv_compose(monkeypatch):
    """weight_dtype="int8" + cache_dtype="int8" together — the exact config
    of the bench ladder's decode-int8-b8 row: int8 GEMM weight streaming
    AND factored-scale int8 cache attention in one compiled program."""
    import numpy as np
    monkeypatch.setenv("PADDLE_TPU_Q8_DECODE_MIN", "4096")  # toy-size gate
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=96, hidden_size=128, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=256)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(2).randint(1, 96, (2, 8)).astype(np.int64))
    full = m.generate_static(ids, max_new_tokens=8).numpy()
    both = m.generate_static(ids, max_new_tokens=8, weight_dtype="int8",
                             cache_dtype="int8").numpy()
    assert both.shape == full.shape
    assert (both[:, :8] == full[:, :8]).all()
    agree = (both[:, 8:] == full[:, 8:]).mean()
    assert agree >= 0.5, f"w8+c8 decode diverged: agreement {agree}"
    assert not np.isnan(both.astype(np.float64)).any()


def test_prefill_decode_static_prefix_reuse():
    """prefill_static/decode_static (r5 prefix-reuse serving): one prompt
    forward fans out to many continuations — greedy decode equals
    generate_static's tail, repeated decodes from one state are identical
    (the state is immutable), different sampling seeds diverge, int8
    weights+cache compose, and capacity overflow raises."""
    import numpy as np
    import pytest
    paddle.seed(3)
    cfg = GPTConfig(vocab_size=96, hidden_size=128, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=256)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(4).randint(1, 96, (2, 8)).astype(np.int64))
    full = m.generate_static(ids, max_new_tokens=8).numpy()
    st = m.prefill_static(ids, max_len=16)
    d1 = m.decode_static(st, max_new_tokens=8).numpy()
    assert (d1 == full[:, 8:]).all()
    d2 = m.decode_static(st, max_new_tokens=8).numpy()
    assert (d1 == d2).all()
    s1 = m.decode_static(st, max_new_tokens=8, temperature=0.9,
                         seed=1).numpy()
    s2 = m.decode_static(st, max_new_tokens=8, temperature=0.9,
                         seed=2).numpy()
    assert not (s1 == s2).all()
    # eos handling inside the reused-state decode
    eos = int(d1[0, 0])
    de = m.decode_static(st, max_new_tokens=8, eos_token_id=eos).numpy()
    assert (de[0] == eos).all()          # row 0 hits eos immediately
    with pytest.raises(ValueError):
        m.decode_static(st, max_new_tokens=64)     # 8 + 64 > max_len 16
    with pytest.raises(ValueError):
        m.prefill_static(ids, max_len=8)           # no decode room
    # int8 cache composes with the prefix-reuse path
    st8 = m.prefill_static(ids, max_len=16, cache_dtype="int8")
    d8 = m.decode_static(st8, max_new_tokens=8).numpy()
    assert d8.shape == d1.shape
    assert (d8 == full[:, 8:]).mean() >= 0.5
    # RAGGED prompts compose: per-row greedy tail equals
    # generate_static_ragged on the same padded prompts/lens
    lens = [3, 8]
    r_full = m.generate_static_ragged(ids, lens, max_new_tokens=6).numpy()
    str_ = m.prefill_static(ids, max_len=16, prompt_lens=lens)
    dr = m.decode_static(str_, max_new_tokens=6).numpy()
    assert (dr == r_full[:, 8:]).all()
    with pytest.raises(ValueError):
        m.prefill_static(ids, max_len=16, prompt_lens=[0, 8])  # len 0


def test_decode_static_capacity_and_stale_weight_guard():
    """r6 (ADVICE r5): the last sampled token is never written to the KV
    cache, so p_len + max_new_tokens - 1 == max_len is admissible; and
    decode against parameters mutated since prefill is rejected (decode
    replays the prefill-time snapshot)."""
    import numpy as np
    import pytest
    paddle.seed(5)
    cfg = GPTConfig(vocab_size=96, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=128)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(6).randint(1, 96, (2, 8)).astype(np.int64))
    st = m.prefill_static(ids, max_len=16)
    out = m.decode_static(st, max_new_tokens=9)    # 8 + 9 - 1 == 16 == L
    assert tuple(out.shape) == (2, 9)
    with pytest.raises(ValueError):
        m.decode_static(st, max_new_tokens=10)     # 8 + 10 - 1 > 16
    # stale-weight replay guard: a same-dtype weight swap must be caught
    st2 = m.prefill_static(ids, max_len=16)
    p = next(iter(m.parameters()))
    p.set_value(p.numpy())                         # same values, new array
    with pytest.raises(ValueError, match="parameters changed"):
        m.decode_static(st2, max_new_tokens=4)


def test_attention_q8_cache_matches_dequant():
    """attention_q8_cache's factored scales (q·cᵀ·s_k; (p·s_v)·c_v) must be
    numerically equivalent to attending over explicitly dequantized K/V."""
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu.ops.attention import (attention_q8_cache, quantize_kv,
                                          dequantize_kv,
                                          attention_reference,
                                          static_cache_mask)
    rng = np.random.RandomState(3)
    B, L, H, D = 2, 16, 4, 32
    k = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, L, H, D).astype(np.float32))
    q = jnp.asarray(rng.randn(B, 1, H, D).astype(np.float32))
    kc, ks = quantize_kv(k)
    vc, vs = quantize_kv(v)
    # roundtrip error bound: symmetric int8 over head_dim rows
    kd = dequantize_kv(kc, ks, jnp.float32)
    rel = float(jnp.max(jnp.abs(kd - k)) / jnp.max(jnp.abs(k)))
    assert rel < 0.01, rel
    pos = jnp.int32(L - 1)
    mask = static_cache_mask(L, 1, pos)
    got = attention_q8_cache(q, kc, ks, vc, vs, mask)
    want = attention_reference(q, kd, dequantize_kv(vc, vs, jnp.float32),
                               mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-3)


def test_fused_small_param_update_parity(monkeypatch):
    """The fused multi-tensor optimizer apply (TrainStep) must produce
    numerically identical params/moments to the per-param loop — it is the
    same elementwise math on a concatenation."""
    import numpy as np
    from paddle_tpu.jit.train_step import TrainStep

    def build():
        paddle.seed(9)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=32,
                        intermediate_size=64)
        m = GPTForCausalLM(cfg)
        o = paddle.optimizer.AdamW(learning_rate=1e-3,
                                   parameters=m.parameters(),
                                   weight_decay=0.01)
        s = TrainStep(m, o, lambda a, b: m.loss(a, b, chunk_size=64))
        return m, s

    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 64, (4, 16)).astype("int32"))

    monkeypatch.setenv("PADDLE_TPU_FUSE_SMALL_UPDATES", "0")
    m0, s0 = build()
    l0 = [float(s0(ids, ids)) for _ in range(3)]

    monkeypatch.setenv("PADDLE_TPU_FUSE_SMALL_UPDATES", "262144")
    m1, s1 = build()
    l1 = [float(s1(ids, ids)) for _ in range(3)]

    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    for p0, p1 in zip(m0.parameters(), m1.parameters()):
        np.testing.assert_allclose(np.asarray(p0._data, np.float64),
                                   np.asarray(p1._data, np.float64),
                                   rtol=1e-6, atol=1e-7,
                                   err_msg=p0.name)


def test_fused_small_param_update_parity_momentum(monkeypatch):
    """Momentum joins the fused multi-tensor apply (the big customer is
    ResNet's 628 BN/bias updates): parity vs the per-param loop."""
    import numpy as np
    import paddle_tpu.nn as nn
    from paddle_tpu.jit.train_step import TrainStep

    def build():
        paddle.seed(2)
        m = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 8))
        o = paddle.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                      parameters=m.parameters(),
                                      weight_decay=0.001)
        ce = nn.MSELoss()
        s = TrainStep(m, o, lambda a, b: ce(m(a), b))
        return m, s

    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(8, 16).astype("float32"))
    y = paddle.to_tensor(np.random.RandomState(1)
                         .randn(8, 8).astype("float32"))
    monkeypatch.setenv("PADDLE_TPU_FUSE_SMALL_UPDATES", "0")
    m0, s0 = build()
    l0 = [float(s0(x, y)) for _ in range(3)]
    monkeypatch.setenv("PADDLE_TPU_FUSE_SMALL_UPDATES", "262144")
    m1, s1 = build()
    l1 = [float(s1(x, y)) for _ in range(3)]
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    for p0, p1 in zip(m0.parameters(), m1.parameters()):
        np.testing.assert_allclose(np.asarray(p0._data), np.asarray(p1._data),
                                   rtol=1e-6, atol=1e-7)


def test_generate_static_ragged_int8(monkeypatch):
    """Ragged serving composes with weight-only int8: one executable, any
    prompt length, quantized payload."""
    import numpy as np
    monkeypatch.setenv("PADDLE_TPU_Q8_DECODE_MIN", "4096")
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=96, hidden_size=128, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=256)
    m = GPTForCausalLM(cfg)
    m.eval()
    P_cap, new = 8, 6
    lens = [8, 3]
    prompts = np.zeros((2, P_cap), np.int64)
    rng = np.random.RandomState(0)
    for i, ln in enumerate(lens):
        prompts[i, :ln] = rng.randint(1, 96, (ln,))
    full = m.generate_static_ragged(paddle.to_tensor(prompts), lens,
                                    max_new_tokens=new).numpy()
    q8 = m.generate_static_ragged(paddle.to_tensor(prompts), lens,
                                  max_new_tokens=new,
                                  weight_dtype="int8").numpy()
    assert q8.shape == full.shape
    agree = (q8[:, P_cap:] == full[:, P_cap:]).mean()
    assert agree >= 0.5, f"int8 ragged diverged: {agree}"
    n_exec = len(m._gen_static_cache)
    lens2 = [5, 7]
    prompts2 = np.zeros((2, P_cap), np.int64)
    for i, ln in enumerate(lens2):
        prompts2[i, :ln] = rng.randint(1, 96, (ln,))
    _ = m.generate_static_ragged(paddle.to_tensor(prompts2), lens2,
                                 max_new_tokens=new, weight_dtype="int8")
    assert len(m._gen_static_cache) == n_exec   # same executable reused
